#!/usr/bin/env python
"""Headline benchmark suite: BASELINE.json configs on one real chip.

Four hardware measurements, each printed as a JSON metric line (the
headline — config 2, the reference's full MAX_TARGETS x
MAX_RULES_PER_TARGET capacity, bpf/ingress_node_firewall.h:13-14 — is
printed LAST so drivers that parse the final line keep recording the
same series as previous rounds):

  1. config 3: 100K-CIDR LPM (poptrie walk, XLA) — the
     scale tier of the reference's LPM trie map
     (bpf/ingress_node_firewall_kernel.c:218-219, map :43-57) — with
     the per-depth-class split and standalone FULL-DEPTH v6 lines
     (XLA walk vs the fused Pallas deep-walk kernel, pallas_walk.py).
  2. config 5a: 10M-packet frames-file replay through the daemon's
     pipelined ingest (read + vectorized parse + compressed-wire classify
     + verdict sidecar + stats/events), sustained packets/s — min AND
     median of 3 passes, with the raw-bytes link floor measured in the
     same record, a link-normalized dataplane-attributable line, the
     delta+varint codec's bytes/packet, and a double-buffered-vs-
     serialized H2D overlap A/B.
  3. config 5b: 1M-entry adversarial overlap table classified on chip,
     with the same per-class split + standalone deep-class lines.
  4. config 4: 8 interfaces x per-iface rulesets, mixed-ifindex batch.
  5. BASELINE configs 1 (single-CIDR/single-rule, CPU reference C++)
     and 2 (1K mixed-family CIDRs x 16 mixed-protocol rules).
  6. 1-key incremental device update latency: rules edit, CIDR add
     (overlay), structural DELETE, and the overlay-overflow merge spike.
  7. wire-path p50 verdict latency (pack -> H2D -> classify -> 2B/packet
     readback), batch sweep 32..4096 incl. pinned-device-input mode.
  8. build-path lines (ISSUE 6): 1M cold-build A/B — vectorized columnar
     compiler vs the retired per-key reference on the BENCH_r05
     adversarial substrate, bit-identity checked (also standalone as
     `bench.py --build-bench`, `make build-bench`, with a regression
     threshold) — and the 10M tier: columnar cold build, full reload,
     compressed-poptrie (ctrie) classify throughput, 1-key joined
     diff-scatter patch, 1-key structural overlay add (200K smoke
     off-TPU).
  9. config 2 headline: 1000 CIDRs x 100 rules, fused int8-MXU Pallas
     dense kernel.

After all tiers, every recorded metric line is RE-EMITTED in one final
block, then ONE compact single-line JSON with the complete metric set
(emit_compact_record) lands immediately before the headline so even a
tail capture of a few lines holds every ladder metric.

Timing methodology (the device is reached through a tunnel whose dispatch
layer memoizes repeated identical executions and whose block_until_ready
is unreliable): K classify iterations are CHAINED on-device inside one
jitted fori_loop — iteration i+1's ports AND ip words depend on iteration
i's verdicts, so no caching, reordering, or loop-invariant hoisting is
possible — and only a scalar checksum is read back.  Chaining the ip
words matters: with only the port chained the LPM stage is loop-invariant
and XLA hoists it out of the loop entirely (rounds 2-3 published
rule-scan-only trie numbers that were 30x+ optimistic because of this).
Throughput is the two-point slope (K=k2 minus K=k1)/(k2-k1) with k2 grown
until the signal clears the tunnel's per-call jitter, which cancels the
fixed RPC/dispatch overhead exactly.  The replay tier instead times
wall-clock over the daemon's real ingest loop with fresh file contents
per pass (min of 3).
"""
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from infw import oracle, testing  # noqa: E402
from infw.kernels import jaxpath, pallas_dense  # noqa: E402

TARGET = 10_000_000.0  # classifications/sec (BASELINE.json north star)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


#: every metric line emitted during the run, re-printed as one final
#: block so a driver that keeps only the output tail still records the
#: FULL metric set (round-3 lost the 100K-CIDR line to tail truncation)
_RECORDED = []


def emit(metric, value, unit, vs_baseline=None, record=True):
    line = json.dumps({
        "metric": metric,
        "value": round(value, 3 if value < 1e3 else 1),
        "unit": unit,
        "vs_baseline": round(vs_baseline if vs_baseline is not None
                             else value / TARGET, 3),
    })
    if record:
        _RECORDED.append(line)
    print(line, flush=True)


def re_emit_recorded():
    """Re-print every recorded metric line in one contiguous final block
    (the headline is emitted after this, keeping it the last line)."""
    log(f"re-emitting {len(_RECORDED)} recorded metric lines")
    for line in _RECORDED:
        print(line, flush=True)


def emit_compact_record(headline_metric=None, headline_value=None):
    """ONE compact single-line JSON holding every ladder metric — the
    truncation-proof record (round-5 verdict weak #6: the re-emit block
    outgrew the driver's tail budget and BENCH_r05.json lost the
    trie/replay/8-iface lines mid-block; a single line survives any tail
    capture that keeps its last few lines).  Printed immediately before
    the headline so both always land inside the tail window."""
    items = []
    for line in _RECORDED:
        d = json.loads(line)
        items.append({"metric": d["metric"], "value": d["value"],
                      "unit": d["unit"]})
    if headline_metric is not None:
        items.append({
            "metric": headline_metric,
            "value": round(headline_value,
                           3 if headline_value < 1e3 else 1),
            "unit": "packets/s",
        })
    print(json.dumps({"bench_record": items}, separators=(",", ":")),
          flush=True)


def fail(reason):
    log(f"FATAL: {reason}")
    emit("packet classifications/sec/chip @100K rules", 0.0, "packets/s", 0.0)
    return 1


def chained_throughput(classify_step, dt, db, n_packets, on_tpu, label):
    """Two-point slope of an on-device chained fori_loop (see module
    docstring).  classify_step(dt, batch) -> u32 results.

    The chain feeds the results back into BOTH dst_port and the ip words
    (low nibble, word 0 for v4 / word 3 for v6, preserving the v4
    zero-word invariant).  The ip feedback is what makes the number
    honest: with only the port chained, the LPM stage (trie walk / dense
    compare) is loop-invariant and XLA HOISTS IT OUT of the fori_loop —
    rounds 2-3 reported rule-scan-only throughput for the XLA trie tiers
    (30x+ optimistic; the Pallas headline was unaffected, a pallas_call
    is opaque to loop-invariant code motion)."""
    from infw.constants import KIND_IPV4

    word_sel = (
        jnp.arange(4, dtype=jnp.int32)[None, :]
        == jnp.where(db.kind == KIND_IPV4, 0, 3)[:, None]
    )

    @jax.jit
    def loop(k, dt, db):
        def step(i, carry):
            dport, ip, acc = carry
            res = classify_step(dt, db._replace(dst_port=dport, ip_words=ip))
            dport = (dport + (res & 1).astype(jnp.int32)) % 65536
            pert = (res & 0xF) ^ (i.astype(jnp.uint32) & 0xF)
            ip = jnp.where(word_sel, ip ^ pert[:, None], ip)
            return dport, ip, acc + jnp.sum(res.astype(jnp.uint32))

        return jax.lax.fori_loop(
            0, k, step, (db.dst_port, db.ip_words, jnp.uint32(0))
        )[2]

    t0 = time.perf_counter()
    int(loop(1, dt, db))
    log(f"{label}: loop compile {time.perf_counter()-t0:.1f}s")
    k1, k2 = (3, 23) if on_tpu else (1, 3)
    # Untimed warmup at k1: the first real execution pays any deferred
    # table upload through the tunnel (multi-GB for the 1M-entry tier);
    # timing it would corrupt the two-point slope.
    t0 = time.perf_counter()
    int(loop(k1, dt, db))
    log(f"{label}: warmup k={k1} {time.perf_counter()-t0:.1f}s")

    def best_of(k, attempts=3):
        best = float("inf")
        for _ in range(attempts):
            t0 = time.perf_counter()
            int(loop(k, dt, db))
            best = min(best, time.perf_counter() - t0)
        return best

    # SIGNAL RESOLUTION: the per-call RPC jitter through the tunnel is
    # tens of ms, so the k2-k1 time difference must be grown until it
    # dominates — a fixed k2=23 under-resolves fast kernels (a 0.3 ms/iter
    # walk gives a 6 ms signal against 40 ms noise; round-3's family-split
    # numbers wandered 3-8x between runs because of exactly this).  Grow
    # k2 until the measured difference clears _MIN_SIGNAL_S, then take
    # min-of-3 per point.
    _MIN_SIGNAL_S = 0.5 if on_tpu else 0.05
    best1 = best_of(k1)
    dt_s = -1.0
    while True:
        best2 = best_of(k2)
        signal = best2 - best1
        if signal >= _MIN_SIGNAL_S or k2 >= 6000:
            break
        grow = 4 if signal <= 0 else min(
            4, max(2, int(_MIN_SIGNAL_S / max(signal, 1e-3) + 1))
        )
        k2 *= grow
        log(f"{label}: growing k2 -> {k2} (signal {signal*1e3:.0f} ms "
            f"below {_MIN_SIGNAL_S*1e3:.0f} ms floor)")
    dt_s = (best2 - best1) / (k2 - k1)
    if dt_s <= 0:
        raise RuntimeError(
            f"{label}: non-monotonic timing k={k1}:{best1:.3f}s k={k2}:{best2:.3f}s"
        )
    thr = n_packets / dt_s
    log(f"{label}: {thr/1e6:.2f} M classifications/s "
        f"({dt_s*1e3:.3f} ms / {n_packets} packets, slope k={k1}->k={k2})")
    return thr


def chained_wire_throughput(dt, wire, n_packets, on_tpu, label):
    """Two-point chained slope over the WIRE-format classify (the
    daemon's production path) with device-resident input: iteration
    i+1's ip word AND port word depend on iteration i's verdicts, same
    honesty rules as chained_throughput."""
    ip_col = wire.shape[1] - 1  # narrow layouts end with the ip word(s)

    @jax.jit
    def loop(k, dt, w):
        def step(i, carry):
            w, acc = carry
            res, _stats = jaxpath.classify_wire(dt, w, use_trie=True)
            res = res.astype(jnp.uint32)
            w = w.at[:, 1].set(w[:, 1] ^ (res & 1).astype(w.dtype))
            pert = ((res & 0xF) ^ (i.astype(jnp.uint32) & 0xF)).astype(w.dtype)
            w = w.at[:, ip_col].set(w[:, ip_col] ^ pert)
            return w, acc + jnp.sum(res.astype(jnp.uint32))

        return jax.lax.fori_loop(0, k, step, (w, jnp.uint32(0)))[1]

    t0 = time.perf_counter()
    int(loop(1, dt, wire))
    log(f"{label}: loop compile {time.perf_counter()-t0:.1f}s")
    k1, k2 = (3, 23) if on_tpu else (1, 3)
    int(loop(k1, dt, wire))

    def best_of(k, attempts=3):
        best = float("inf")
        for _ in range(attempts):
            t0 = time.perf_counter()
            int(loop(k, dt, wire))
            best = min(best, time.perf_counter() - t0)
        return best

    _MIN_SIGNAL_S = 0.5 if on_tpu else 0.05
    best1 = best_of(k1)
    while True:
        best2 = best_of(k2)
        if best2 - best1 >= _MIN_SIGNAL_S or k2 >= 6000:
            break
        k2 *= 4
    dt_s = (best2 - best1) / (k2 - k1)
    if dt_s <= 0:
        raise RuntimeError(f"{label}: non-monotonic timing")
    thr = n_packets / dt_s
    log(f"{label}: {thr/1e6:.2f} M packets/s (device-resident wire)")
    return thr


def family_split_throughput(dt, batch, on_tpu, label, tables=None):
    """Aggregate throughput with the daemon's steering (infw/daemon.py
    ingest regroups chunks): the v4 sub-batch walks only the trie levels
    reachable under the 32-bit cap (3 gathers); v6 sub-batches further
    split by DEPTH CLASS (jaxpath.build_depth_lut — each root slot knows
    how many deep levels its subtree can need), with thresholds TUNED to
    the table's depth histogram (jaxpath.tune_depth_classes — the 1M
    adversarial histogram differs from the 100K one, round-5 ask #3).
    Combined = total packets over the summed per-group batch times.

    Returns (combined, per_group) where per_group rows are
    (name, depth_class_or_None, positions, throughput) — the caller
    emits the per-class ladder split and the standalone full-depth
    line from them."""
    from infw.constants import KIND_IPV6

    kinds = np.asarray(batch.kind)
    groups = [("v4", None, np.nonzero(kinds != KIND_IPV6)[0])]
    idx6 = np.nonzero(kinds == KIND_IPV6)[0]
    full_depth_names = set()
    if tables is not None and len(idx6):
        lut = jaxpath.build_depth_lut(tables)
        classes = jaxpath.tune_depth_classes(tables)
        hist = jaxpath.depth_class_histogram(tables)
        log(f"{label}: depth histogram (slots per deep-level requirement) "
            f"{list(hist)}; tuned classes {classes}")
        for d, g in jaxpath.depth_group_indices(
            np.asarray(tables.root_lut, np.int64), lut, classes,
            batch.ifindex, batch.ip_words, idx6,
        ):
            label_d = classes[-1] if d is None else d
            name = f"v6<=d{label_d}"
            if d is None:
                full_depth_names.add(name)
            groups.append((name, d, g))
    elif len(idx6):
        groups.append(("v6", None, idx6))

    total_t, total_n = 0.0, 0
    per_group = []
    for name, depth, idx in groups:
        if len(idx) == 0:
            continue
        sub = jaxpath.device_batch(batch.take(idx))
        dtab = dt
        if name == "v4":
            d = jaxpath.v4_trie_depth(len(dt.trie_levels))
            dtab = dt._replace(trie_levels=dt.trie_levels[:d])
        elif depth is not None:
            dtab = dt._replace(trie_levels=dt.trie_levels[: 1 + depth])

        def step(dtab, b):
            res, _xdp, _stats = jaxpath.classify(dtab, b, use_trie=True)
            return res

        thr = chained_throughput(
            step, dtab, sub, len(idx), on_tpu, f"{label}/{name}"
        )
        total_t += len(idx) / thr
        total_n += len(idx)
        per_group.append((name, depth, idx, thr))
    combined = total_n / total_t
    split = ", ".join(
        f"{name}: {len(idx)} pkts @ {thr/1e6:.2f} M/s"
        for name, _d, idx, thr in per_group
    )
    log(f"{label}: per-class split — {split}")
    log(f"{label}: combined steered-split {combined/1e6:.2f} M classifications/s")
    return combined, per_group


def spot_check(fn_results, tables, batch, n=2000, label=""):
    """Differential verdict check vs the oracle.  Above _SCALAR_LIMIT
    packets the LPM-by-hash oracle is the ground truth (O(mask lens) per
    packet vs the scalar oracle's O(entries)); the hash oracle itself is
    cross-validated against the scalar one on the first 2000 packets, so
    the scalar transliteration stays the root of trust."""
    _SCALAR_LIMIT = 4000
    n = min(n, len(batch))
    sub = batch.slice(0, n)
    t0 = time.perf_counter()
    if n <= _SCALAR_LIMIT and tables.num_entries <= 20_000:
        ref = oracle.classify(tables, sub).results
    else:
        h = oracle.HashLpmOracle(tables)
        ref = h.classify(sub).results
        # scalar cross-check budget ~2e7 entry-visits (~10s of Python);
        # the hash results for the prefix are already in ref
        n_cross = min(2000, max(50, int(2e7 / max(1, tables.num_entries))))
        scalar = oracle.classify(tables, batch.slice(0, n_cross)).results
        if not (ref[:n_cross] == scalar).all():
            raise RuntimeError(f"{label}: hash oracle disagrees with scalar oracle")
    got = fn_results(sub)
    if not (got == ref).all():
        raise RuntimeError(f"{label}: verdict mismatch vs oracle")
    log(f"{label}: verdict spot-check vs oracle OK "
        f"({n} packets, {time.perf_counter()-t0:.1f}s)")


# --- shared XLA-trie tier body (configs 3, 4, 5b) --------------------------


def trie_tier(rng, on_tpu, *, label, metric_of, table_kw, spot_n,
              batch_check=None, deep_lines=False):
    """One trie-path tier: build table -> upload -> compile wire path ->
    spot-check vs oracle -> family-split chained throughput -> emit.
    Shared by the 100K-CIDR, 1M-adversarial and 8-iface tiers so a
    methodology fix lands in all of them at once.

    ``deep_lines=True`` additionally emits the standalone FULL-DEPTH v6
    class as its own ladder lines — the XLA walk and the fused Pallas
    deep-walk kernel (kernels.pallas_walk) — since that class is the
    throughput floor every deep-heavy adversarial mix converges to
    (round-5 verdict asks #2/#3)."""
    t0 = time.perf_counter()
    tables = testing.random_tables_fast(rng, **table_kw)
    log(f"{label}: table build {time.perf_counter()-t0:.1f}s "
        f"entries={tables.num_entries} levels={tables.levels} "
        f"trie nodes={sum(l.shape[0] for l in tables.trie_levels)//256}")
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    if batch_check is not None:
        batch_check(batch)
    t0 = time.perf_counter()
    dt = jaxpath.device_tables(tables)
    wire_fn = jaxpath.jitted_classify_wire(True)
    np.asarray(wire_fn(dt, jnp.asarray(batch.slice(0, 2000).pack_wire()))[0])
    log(f"{label}: upload+compile+first {time.perf_counter()-t0:.1f}s")

    def results_of(sub):
        res16 = np.asarray(wire_fn(dt, jnp.asarray(sub.pack_wire()))[0])
        return jaxpath.host_finalize_wire(res16, sub.kind)[0]

    spot_check(results_of, tables, batch,
               n=spot_n if on_tpu else 2_000, label=label)

    thr, per_group = family_split_throughput(
        dt, batch, on_tpu, label, tables=tables
    )
    emit(metric_of(tables), thr, "packets/s")
    if deep_lines:
        try:
            deep_class_lines(tables, batch, per_group, on_tpu, label)
        except Exception as e:
            log(f"{label}: deep-class lines FAILED: {e}")
    return tables


def deep_class_lines(tables, batch, per_group, on_tpu, label):
    """Standalone ladder lines for the full-depth v6 class: the XLA walk
    rate (from the steered split, no re-measure) and the fused Pallas
    deep-walk kernel on the SAME packets, with the extraction memory
    math in the log (round-5 weak #4: memory headroom at 1M was
    undiscussed)."""
    from infw.kernels import pallas_walk

    tier = (f"{tables.num_entries // 1000}K"
            if tables.num_entries < 1_000_000
            else f"{tables.num_entries/1e6:.0f}M")
    deep = [(idx, thr) for name, d, idx, thr in per_group
            if d is None and name.startswith("v6")]
    if not deep:
        log(f"{label}: no full-depth v6 packets in the mix; skipping "
            "deep-class lines")
        return
    deep_idx, thr_xla = deep[0]
    emit(
        f"standalone full-depth v6 class @{tier} entries "
        f"({len(deep_idx)} pkts of the adversarial mix, XLA poptrie walk)",
        thr_xla, "packets/s",
    )

    classes = jaxpath.tune_depth_classes(tables)
    min_depth = classes[-2] if len(classes) >= 2 else None
    t0 = time.perf_counter()
    built = pallas_walk.build_walk_tables_meta(tables, min_depth=min_depth)
    if built is None:
        log(f"{label}: fused deep walk unavailable for this table "
            f"(VMEM gate {pallas_walk.DEFAULT_VMEM_BUDGET/1e6:.0f} MB or "
            "layout); the XLA walk line above stands alone")
        return
    wt, meta = built
    jdesc = (f"joined planes {wt.joined.shape[0]} rows x "
             f"{wt.joined.shape[1]} B VMEM-resident"
             if meta["tail"] == "fused" else
             f"positions tail ({wt.joined_u16.shape[0]} u16 rows in HBM, "
             "one XLA fat-row gather)")
    log(f"{label}: fused walk tables built {time.perf_counter()-t0:.1f}s "
        f"(extraction threshold >{min_depth} deep levels, "
        f"tail={meta['tail']}): "
        f"levels {[l.shape[0] for l in wt.levels]} rows, {jdesc}, "
        f"{len(meta['tidx_sorted'])} resident targets, "
        f"VMEM {meta['vmem_bytes']/1e6:.2f} MB of "
        f"{pallas_walk.DEFAULT_VMEM_BUDGET/1e6:.0f} MB budget")
    sub = jaxpath.device_batch(batch.take(deep_idx))
    interpret = not on_tpu

    def step(wtab, b):
        res, _xdp, _stats = pallas_walk.classify_walk(
            wtab, b, interpret=interpret
        )
        return res

    thr_fused = chained_throughput(
        step, wt, sub, len(deep_idx), on_tpu, f"{label}/v6-deep-fused"
    )
    emit(
        f"standalone full-depth v6 class @{tier} entries "
        f"(fused Pallas deep-walk kernel, VMEM-resident extracted tail, "
        f"{meta['tail']} rules tail; "
        f"XLA walk {thr_xla/1e6:.1f} M/s on the same packets)",
        thr_fused, "packets/s",
    )


# --- cold-build microbench (make build-bench) ------------------------------

#: BENCH_r05 cold table build @1M entries (the retired per-key
#: compiler on the recorded TPU host) — the ISSUE-6 10x target's anchor
BUILD_BASELINE_1M_S = 44.0


def bench_build(rng, n_entries=1_000_000, legacy=True):
    """Cold-build A/B at the 1M tier on the BENCH_r05 substrate (the
    adversarial overlap distribution whose per-key compile was the
    recorded 44s): the vectorized compiler (compile_tables_from_content,
    now routed through the columnar sorted-prefix batch build) against
    the retired per-key reference (from_content_legacy), SAME content
    dict, with a tensor bit-identity cross-check tying the speedup to a
    correctness proof.  Host-side only — no device, no tunnel jitter;
    the two compilers run INTERLEAVED (C L C L C L), ratio min-vs-min:
    this CI host's throughput swings ~2-3x with ambient load on a
    scale of minutes, so back-to-back blocks (3xC then 1xL) hand
    whichever path runs second a different machine — interleaving is
    what makes the same-host ratio a property of the code.  The
    clean-corpus pure-columns build (no dict input at all) is the 10M
    tier's line (bench_scale_10m).

    Returns {"columnar_s", "legacy_s"|None, "speedup"|None}."""
    from infw.compiler import (
        IncrementalTables,
        compile_tables_from_content,
    )

    tier = (f"{n_entries/1e6:.0f}M" if n_entries >= 1_000_000
            else f"{n_entries//1000}K")
    t0 = time.perf_counter()
    adv = testing.random_tables_fast(
        rng, n_entries=n_entries, width=4, group_size=16,
        ifindexes=(2, 3, 4),
    )
    content = dict(adv.content)  # the dict INPUT of both paths, untimed
    log(f"build@{tier}: adversarial corpus generated "
        f"{time.perf_counter()-t0:.1f}s ({len(content)} keys)")
    best = float("inf")
    t_leg = float("inf")
    ref = None
    rounds = 3 if legacy else 0
    for _ in range(3):
        t0 = time.perf_counter()
        tables = compile_tables_from_content(content, rule_width=4)
        best = min(best, time.perf_counter() - t0)
        if rounds:
            rounds -= 1
            t0 = time.perf_counter()
            ref = IncrementalTables.from_content_legacy(
                content, rule_width=4
            ).snapshot(consume=True)
            t_leg = min(t_leg, time.perf_counter() - t0)
    emit(
        f"cold table build @{tier} entries (vectorized columnar "
        f"compiler, adversarial overlap mix, min of 3 interleaved; "
        f"BENCH_r05 per-key baseline {BUILD_BASELINE_1M_S:.0f}s @1M)",
        best, "s",
        vs_baseline=(BUILD_BASELINE_1M_S * n_entries / 1e6) / best,
    )
    rec = {"columnar_s": best, "legacy_s": None, "speedup": None}
    if not legacy:
        return rec
    emit(
        f"cold table build @{tier} entries (retired per-key compiler, "
        "same host/content, min of 3 interleaved — the in-record "
        "denominator)",
        t_leg, "s",
        vs_baseline=(BUILD_BASELINE_1M_S * n_entries / 1e6) / t_leg,
    )
    # bit-identity: the speedup is only meaningful if both paths build
    # the SAME tables
    mismatch = []
    for name in ("key_words", "mask_words", "mask_len", "rules", "root_lut"):
        if not np.array_equal(getattr(tables, name), getattr(ref, name)):
            mismatch.append(name)
    if len(tables.trie_levels) != len(ref.trie_levels) or any(
        not np.array_equal(a, b)
        for a, b in zip(tables.trie_levels, ref.trie_levels)
    ):
        mismatch.append("trie_levels")
    if mismatch:
        raise RuntimeError(
            f"build@{tier}: columnar vs per-key output mismatch in "
            f"{mismatch} — the speedup line would be comparing different "
            "tables"
        )
    log(f"build@{tier}: columnar output bit-identical to the per-key "
        "reference")
    rec["legacy_s"] = t_leg
    rec["speedup"] = t_leg / best
    emit(
        f"cold-build speedup @{tier} entries (columnar vs per-key, same "
        "host, bit-identical output)",
        rec["speedup"], "x", vs_baseline=rec["speedup"] / 10.0,
    )
    return rec


def bench_scale_10m(rng, on_tpu):
    """The 10M-entry tier (ISSUE 6): columnar cold build -> compressed
    (ctrie) device layout -> chained classify throughput -> 1-key
    diff-scatter rules patch -> 1-key structural overlay add, all
    through the production TpuClassifier dispatch.  Off-TPU the tier
    runs a 200K smoke so the pipeline stays exercised on CPU hosts.

    The clean /24+/48 distribution (testing.clean_columns_fast) is the
    tier's corpus: at 10M entries even the adversarial generator's
    C-level dict build costs real minutes, and the build-path numbers
    here must measure the COMPILER, not the corpus generator (see
    benchruns/README.md for the measurement rules)."""
    from infw.backend.tpu import TpuClassifier
    from infw.compiler import (
        IncrementalTables, LpmKey, compile_tables_from_content,
    )

    n = 10_000_000 if on_tpu else 200_000
    tier = f"{n/1e6:.0f}M" if n >= 1_000_000 else f"{n//1000}K"
    t0 = time.perf_counter()
    cols = testing.clean_columns_fast(rng, n, width=4)
    log(f"scale@{tier}: corpus generated {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    it = IncrementalTables.from_columns(cols, rule_width=4)
    snap = it.snapshot()
    t_build = time.perf_counter() - t0
    emit(
        f"cold table build @{tier} entries (vectorized columnar "
        "compiler, clean /24+/48 mix)",
        t_build, "s",
        vs_baseline=(BUILD_BASELINE_1M_S * n / 1e6) / t_build,
    )
    clf = TpuClassifier(force_path="ctrie")
    t0 = time.perf_counter()
    clf.load_tables(snap)
    it.clear_dirty()
    t_load = time.perf_counter() - t0
    assert clf.active_path == "ctrie", clf.active_path
    cdev, d_max = clf._active[1]
    log(f"scale@{tier}: compressed layout resident — "
        f"{cdev.nodes.shape[0]} skip-node rows, d_max {d_max} "
        f"(vs {len(snap.trie_levels)} per-level walk levels), "
        f"load {t_load:.1f}s")
    emit(
        f"full reload @{tier} entries (columnar compile + compressed "
        f"poptrie transform + upload; build {t_build:.1f}s + load "
        f"{t_load:.1f}s)",
        t_build + t_load, "s",
    )

    # classify throughput through the compressed walk (device-resident
    # wire, chained two-point slope — the standard honesty rules)
    n_packets = 2**19 if on_tpu else 2**13
    batch = testing.random_batch_fast(rng, snap, n_packets=n_packets)
    t0 = time.perf_counter()
    oracle_h = oracle.HashLpmOracle(snap)
    log(f"scale@{tier}: hash oracle built {time.perf_counter()-t0:.1f}s")
    wire_np = batch.pack_wire()
    fn = jaxpath.jitted_classify_ctrie_wire_fused(d_max)
    res16 = jaxpath.split_wire_outputs(
        np.asarray(fn(cdev, jnp.asarray(batch.slice(0, 2000).pack_wire()))),
        2000,
    )[0]
    got = jaxpath.host_finalize_wire(res16, batch.slice(0, 2000).kind)[0]
    ref = oracle_h.classify(batch.slice(0, 2000))
    if not np.array_equal(got, ref.results):
        raise RuntimeError(f"scale@{tier}: ctrie verdicts diverge from "
                           "the oracle")
    log(f"scale@{tier}: verdict spot-check vs oracle OK (2000 packets)")
    wire = jnp.asarray(wire_np)
    ip_col = wire_np.shape[1] - 1

    @jax.jit
    def loop(k, cd, w):
        def step(i, carry):
            w, acc = carry
            res, _stats = jaxpath.classify_ctrie_wire(cd, w, d_max=d_max)
            res = res.astype(jnp.uint32)
            w = w.at[:, 1].set(w[:, 1] ^ (res & 1).astype(w.dtype))
            pert = ((res & 0xF) ^ (i.astype(jnp.uint32) & 0xF)).astype(w.dtype)
            w = w.at[:, ip_col].set(w[:, ip_col] ^ pert)
            return w, acc + jnp.sum(res.astype(jnp.uint32))

        return jax.lax.fori_loop(0, k, step, (w, jnp.uint32(0)))[1]

    t0 = time.perf_counter()
    int(loop(1, cdev, wire))
    log(f"scale@{tier}: loop compile {time.perf_counter()-t0:.1f}s")
    k1, k2 = (3, 23) if on_tpu else (1, 3)

    def best_of(k, attempts=3):
        best = float("inf")
        for _ in range(attempts):
            t0 = time.perf_counter()
            int(loop(k, cdev, wire))
            best = min(best, time.perf_counter() - t0)
        return best

    _MIN_SIGNAL_S = 0.5 if on_tpu else 0.05
    best1 = best_of(k1)
    while True:
        best2 = best_of(k2)
        if best2 - best1 >= _MIN_SIGNAL_S or k2 >= 6000:
            break
        k2 *= 4
    dt_s = (best2 - best1) / (k2 - k1)
    if dt_s <= 0:
        raise RuntimeError(f"scale@{tier}: non-monotonic timing")
    emit(
        f"packet classifications/sec/chip @{tier} entries "
        f"(path/level-compressed poptrie walk, d_max {d_max}, XLA)",
        n_packets / dt_s, "packets/s",
    )

    # 1-key RULES edit -> the per-tidx joined diff-scatter patch.  The
    # timed region is the steady-state edit pipeline (apply + snapshot +
    # device patch) — identical to the 100K/1M tiers so the lines stay
    # comparable; note the non-consume snapshot's defensive copies are
    # O(table size) and dominate at this tier (the device scatter is
    # kilobytes).  The first edit's one-time lazy ident-map
    # materialization (columns -> {LpmKey: rules} dicts) is timed
    # separately, outside the patch latency.
    t0 = time.perf_counter()
    _ = it._ident_to_t  # force materialization once, outside the timing
    log(f"scale@{tier}: lazy content materialization "
        f"{time.perf_counter()-t0:.1f}s (one-time, first edit only)")
    lats = []
    for i in range(5):
        ki = LpmKey(int(cols.prefix_len[i]), int(cols.ifindex[i]),
                    cols.ip[i].tobytes())
        rows = np.asarray(it.content[ki]).copy()
        rows[1, 6] = 1 if rows[1, 6] == 2 else 2
        t0 = time.perf_counter()
        it.apply({ki: rows})
        clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
        it.clear_dirty()
        lats.append(time.perf_counter() - t0)
        mode, n_rows = clf._last_load
        log(f"scale@{tier} edit {i}: {lats[-1]*1e3:.0f} ms mode={mode} "
            f"rows={n_rows}")
        assert mode == "patch", "ctrie 1-key rules edit must diff-scatter"
    emit(
        f"1-key rule update to device @{tier} entries, best of "
        f"{len(lats)} (compressed layout, per-tidx joined diff-scatter; "
        f"full reload {t_build + t_load:.1f}s)",
        min(lats) * 1e3, "ms",
        vs_baseline=(t_build + t_load) / min(lats),
    )

    # 1-key structural CIDR add via the overlay side-table: the merged
    # node array is untouched (the whole point — a structural re-place
    # at this tier costs a full build)
    snap2 = it.snapshot()
    it.clear_dirty()
    overlay = {}
    add_lats = []
    for i in range(5):
        new_key = LpmKey(88, 2, bytes([0x20, 1, 0xD, 0xB8, 0, i]) + bytes(10))
        rows = np.zeros((4, 7), np.int32)
        rows[1] = [1, 6, 443, 0, 0, 0, 1]
        t0 = time.perf_counter()
        overlay[new_key] = rows
        ov_tables = compile_tables_from_content(dict(overlay), rule_width=4)
        clf.load_tables(snap2, dirty_hint=it.peek_dirty(), overlay=ov_tables)
        it.clear_dirty()
        add_lats.append(time.perf_counter() - t0)
        mode, _ = clf._last_load
        log(f"scale@{tier} cidr-add {i}: {add_lats[-1]*1e3:.0f} ms "
            f"mode={mode}")
        assert mode == "patch", "ctrie CIDR add must not re-upload"
    emit(
        f"1-key CIDR add to device @{tier} entries, best of "
        f"{len(add_lats)} (structural overlay, compressed main table "
        f"untouched; full reload {t_build + t_load:.1f}s)",
        min(add_lats) * 1e3, "ms",
        vs_baseline=(t_build + t_load) / min(add_lats),
    )
    clf.close()


def build_bench_main() -> int:
    """``make build-bench``: the 1M cold-build microbenchmark with a
    regression threshold — exit 1 when the columnar compiler's measured
    speedup over the in-record per-key denominator falls below
    INFW_BUILD_SPEEDUP_MIN (the acceptance floor is host-normalized: a
    gVisor CI host pays page-fault costs the TPU host does not, so the
    gate compares against the SAME-host interleaved denominator, not
    the recorded 44s anchor.  Measured on the 2-core CI host: ~2.1x
    unloaded, up to ~5x under ambient memory pressure — the per-key
    path's random small accesses degrade much faster than the columnar
    streaming passes — so the floor is 1.3x: below the observed noise
    band (1.66x worst case), while a reversion to per-key work lands
    at ~1x)."""
    threshold = float(os.environ.get("INFW_BUILD_SPEEDUP_MIN", "1.3"))
    n = int(os.environ.get("INFW_BUILD_BENCH_ENTRIES", "1000000"))
    rng = np.random.default_rng(2024)
    rec = bench_build(rng, n_entries=n)
    emit_compact_record()
    if rec["speedup"] is None or rec["speedup"] < threshold:
        log(f"build-bench FAIL: speedup {rec['speedup']} below the "
            f"{threshold}x regression threshold")
        return 1
    log(f"build-bench OK: {rec['speedup']:.1f}x (threshold {threshold}x)")
    return 0


# --- config 3: 100K-CIDR trie --------------------------------------------


def bench_trie_100k(rng, on_tpu):
    return trie_tier(
        rng, on_tpu, label="trie100k", spot_n=100_000, deep_lines=True,
        table_kw=dict(n_entries=100_000 if on_tpu else 2_000, width=8,
                      ifindexes=(2, 3, 4)),
        metric_of=lambda t: (
            f"packet classifications/sec/chip @{t.num_entries // 1000}K CIDRs "
            "(poptrie LPM walk, XLA, family+depth-steered chunks)"
        ),
    )


# --- config 5a: 10M-packet replay through daemon ingest -------------------


def bench_replay_10m(rng, tables, on_tpu, n_passes=3):
    """Config 5a.  Round-3's record showed a 4.7x gap between a local run
    (1.17 M pkts/s) and the driver's (0.25 M) — the tier is H2D-bandwidth
    bound through the tunnel, so a single timed pass is hostage to link
    variance.  Now: min-of-N passes, per-pass DISTINCT file contents
    (ifindex rolls — the tunnel memoizes identical executions, so reused
    bytes would fake the later passes), and a logged phase breakdown
    (host read+parse+pack vs device round trips) plus the effective H2D
    bandwidth so the record shows WHERE a slow pass went."""
    from infw.backend.tpu import TpuClassifier
    from infw.daemon import write_frames_file_v2
    from infw.obs.events import EventRing
    from infw.obs.pcap import build_frames_bulk

    n_total = 10_000_000 if on_tpu else 100_000
    n_file = 1_000_000 if on_tpu else 50_000

    t0 = time.perf_counter()
    batch = testing.random_batch_fast(rng, tables, n_packets=n_file)
    fb = build_frames_bulk(batch.kind, batch.ip_words, batch.proto,
                           batch.dst_port, batch.icmp_type, batch.icmp_code,
                           l4_ok=batch.l4_ok)
    base_ifx = np.asarray(batch.ifindex, np.uint32)
    fb.ifindex = base_ifx
    log(f"replay: synthesized {n_file} frames in {time.perf_counter()-t0:.1f}s "
        f"({len(fb.buf)/1e6:.0f} MB)")

    clf = TpuClassifier()
    clf.load_tables(tables)

    state_dir = tempfile.mkdtemp(prefix="infw-bench-")
    try:
        from infw.daemon import Daemon, parse_frames_buf, read_frames_any

        d = Daemon.__new__(Daemon)  # ingest-only harness: no watch threads
        d.ingest_dir = os.path.join(state_dir, "ingest")
        d.out_dir = os.path.join(state_dir, "out")
        os.makedirs(d.ingest_dir); os.makedirs(d.out_dir)
        # ~1M-packet chunks: the tunnel's per-RPC cost (~0.1-0.8s however
        # small the payload) dominates below this; the real-PCIe deployment
        # would use smaller chunks for latency.
        d.ingest_chunk = 1 << 20
        d.pipeline_depth = 16
        d.max_tick_packets = 16 << 20
        d.debug_lookup = False
        # double-buffered ingestion (the production default): the next
        # chunk's compressed payload is encoded + its H2D copy started
        # while the current chunk's classify runs; the serialized A/B
        # control below measures the margin in the same record
        d.h2d_overlap = True
        d.h2d_stage_depth = 2
        # production-default ring sizing + a draining logger with the
        # binary spill sink, so the replay measures the REAL event
        # pipeline (round-4 weak #2: 20-57% of deny events were lost at
        # exactly this load with the 4096 ring and no drainer)
        d.ring = EventRing(capacity=1 << 21)
        from infw.obs.events import EventsLogger

        ev_lines = open(os.path.join(state_dir, "events.log"), "a")
        d.events_logger = EventsLogger(
            d.ring, lambda l: ev_lines.write(l + "\n"),
            spill_path=os.path.join(state_dir, "deny-events.bin"),
            poll_interval_s=0.02,
        )
        d.events_logger.start()

        class _Syncer:
            classifier = clf
        d.syncer = _Syncer()

        n_files = n_total // n_file

        # the table's live ifindex domain, derived (not assumed): rotation
        # permutes WITHIN it and leaves miss traffic (out-of-domain
        # ifindexes from the batch generator) untouched, so every pass
        # replays the same hit/miss workload mix as the nominal batch
        live = np.asarray(tables.mask_len[: tables.num_entries]) >= 0
        dom = np.unique(
            np.asarray(tables.key_words[: tables.num_entries, 0])[live]
        ).astype(np.uint32)
        pos = np.searchsorted(dom, base_ifx)
        pos_ok = (pos < len(dom)) & (dom[np.minimum(pos, len(dom) - 1)] == base_ifx)

        def write_pass_files(p):
            """Distinct content per (pass, file): roll + rotate the
            ifindex column (feeds wire word 2 -> every device execution
            is unique)."""
            t0 = time.perf_counter()
            for i in range(n_files):
                k = p * n_files + i
                rot = dom[(pos + k) % len(dom)]
                ifx = np.where(pos_ok, rot, base_ifx).astype(np.uint32)
                fb.ifindex = np.roll(ifx, 977 * k)
                write_frames_file_v2(
                    os.path.join(d.ingest_dir, f"f{i:03d}.frames"), fb
                )
            return time.perf_counter() - t0

        # warmup: compile both family-specialized wire paths
        fb.ifindex = base_ifx
        write_frames_file_v2(os.path.join(d.ingest_dir, "warm.frames"), fb)
        t0 = time.perf_counter()
        d.process_ingest_once()
        log(f"replay: warmup (compile) {time.perf_counter()-t0:.1f}s")

        # host-phase cost (read+parse+pack), measured once on one file:
        # the pipelined tick overlaps this with device work, so it is the
        # floor the daemon could hit if the link were free.
        path0 = os.path.join(d.ingest_dir, "probe.frames")
        write_frames_file_v2(path0, fb)
        t0 = time.perf_counter()
        pfb = read_frames_any(path0)
        pbatch = parse_frames_buf(pfb)
        _ = pbatch.pack_wire_subset(
            np.arange(len(pbatch), dtype=np.int64)
        )
        t_host_file = time.perf_counter() - t0
        os.remove(path0)
        log(f"replay: host phase (read+parse+pack) {t_host_file:.2f}s/file "
            f"-> {n_file/t_host_file/1e6:.2f} M pkts/s host-only floor")

        def _wire_totals():
            s = clf.wire_stats() if hasattr(clf, "wire_stats") else {}
            return (sum(v[0] for v in s.values()),
                    sum(v[1] for v in s.values()), s)

        pk0, by0, _ = _wire_totals()
        best_dt, pass_times = float("inf"), []
        for p in range(n_passes):
            t_write = write_pass_files(p)
            t0 = time.perf_counter()
            done = d.process_ingest_once()
            dt_s = time.perf_counter() - t0
            assert done == n_files, f"processed {done}/{n_files}"
            pass_times.append(dt_s)
            best_dt = min(best_dt, dt_s)
            log(f"replay pass {p}: {n_files} x {n_file} packets in {dt_s:.1f}s "
                f"(+{t_write:.1f}s file write) -> {n_total/dt_s/1e6:.2f} M "
                f"pkts/s; "
                f"device-attributable ~{max(dt_s - n_files*t_host_file, 0):.1f}s "
                f"if unpipelined host cost {n_files*t_host_file:.1f}s; "
                f"ring lost_samples={d.ring.lost_samples}")
        pk1, by1, fmt_split = _wire_totals()
        thr = n_total / best_dt
        med_dt = sorted(pass_times)[len(pass_times) // 2]
        log(f"replay: min-of-{n_passes} {thr/1e6:.2f} M packets/s, "
            f"median {n_total/med_dt/1e6:.2f} M "
            f"(passes: {', '.join(f'{t:.1f}s' for t in pass_times)})")
        emit(
            f"daemon ingest replay sustained @{n_total/1e6:.0f}M packets, "
            f"min of {n_passes} "
            f"({tables.num_entries // 1000}K-CIDR trie, incl. file read + "
            "parse + verdict sidecar + stats + deny events)",
            thr, "packets/s",
        )
        # median alongside min (round-6 ask: a single lucky pass through
        # the tunnel must not be the only recorded number)
        emit(
            f"daemon ingest replay sustained @{n_total/1e6:.0f}M packets, "
            f"median of {n_passes} (same passes as the min line)",
            n_total / med_dt, "packets/s",
        )
        # compressed-wire accounting: the delta codec's measured average
        # payload bytes per packet (the ≤6 B/packet target; v6 chunks
        # ride the 24B narrow wire and are reported as the blend in the
        # log so the link-floor line stays interpretable)
        if pk1 > pk0:
            blend = (by1 - by0) / (pk1 - pk0)
            log("replay wire formats (packets, bytes): " + ", ".join(
                f"{k}: {v[0]}, {v[1]}" for k, v in sorted(fmt_split.items()))
                + f"; all-format blend {blend:.2f} B/packet")
            dstats = fmt_split.get("delta")
            if dstats and dstats[0]:
                bpp = dstats[1] / dstats[0]
                emit(
                    "replay compressed wire bytes/packet (delta+varint "
                    "codec, v4 share; target <= 6)",
                    bpp, "bytes/packet", vs_baseline=round(bpp / 8.0, 3),
                )
            else:
                emit(
                    "replay compressed wire bytes/packet (delta codec "
                    "NOT engaged — all-format blend)",
                    blend, "bytes/packet", vs_baseline=round(blend / 8.0, 3),
                )

        # H2D-overlap A/B in the same record, two controls so the new
        # staging is not credited with the pre-existing classify
        # pipelining: (a) staged H2D off but the 16-deep classify window
        # kept — isolates the double-buffered prepare stage; (b) fully
        # serialized (no staging, pipeline depth 1) — the total overlap
        # win of the pipeline over chunk-at-a-time ingest.
        try:
            d.h2d_overlap = False
            t_write = write_pass_files(n_passes)
            t0 = time.perf_counter()
            done = d.process_ingest_once()
            dt_nostage = time.perf_counter() - t0
            assert done == n_files, f"processed {done}/{n_files}"
            d.pipeline_depth = 1
            t_write = write_pass_files(n_passes + 1)
            t0 = time.perf_counter()
            done = d.process_ingest_once()
            dt_serial = time.perf_counter() - t0
            assert done == n_files, f"processed {done}/{n_files}"
            # controls are ONE pass each, so compare them to the MEDIAN
            # staged pass, not the min — min-vs-single-pass would credit
            # tunnel weather (1-31 MB/s between passes) to the staging
            log(f"replay overlap A/B: staged+pipelined median {med_dt:.1f}s "
                f"(best {best_dt:.1f}s), "
                f"no-stage (pipeline 16) {dt_nostage:.1f}s, "
                f"fully serialized {dt_serial:.1f}s")
            emit(
                "replay H2D staging speedup (double-buffered prepare vs "
                "unstaged, classify pipeline kept; vs median staged pass)",
                dt_nostage / med_dt, "x",
                vs_baseline=round(dt_nostage / med_dt, 3),
            )
            emit(
                "replay pipeline overlap speedup (staged + 16-deep "
                "pipeline vs fully serialized chunks; vs median staged "
                "pass)",
                dt_serial / med_dt, "x",
                vs_baseline=round(dt_serial / med_dt, 3),
            )
        except Exception as e:
            log(f"replay no-overlap control FAILED: {e}")
        finally:
            d.h2d_overlap = True
            d.pipeline_depth = 16

        # raw-bytes link floor IN the record: ship the same number of
        # compressed bytes as one measured pass, chunked like the ingest
        # jobs, with no decode/classify behind them — the hard ceiling
        # the link imposes on ANY codec, so the record separates "the
        # wire is slow" from "the dataplane is slow".
        try:
            per_pass_bytes = int((by1 - by0) / max(n_passes, 1))
            if per_pass_bytes > 0:
                n_jobs = max(1, n_total // d.ingest_chunk)
                chunk_b = max(1, per_pass_bytes // n_jobs)
                rng_f = np.random.default_rng(424242)
                bufs = [
                    rng_f.integers(0, 256, chunk_b, dtype=np.uint8)
                    for _ in range(-(-per_pass_bytes // chunk_b))
                ]
                t0 = time.perf_counter()
                handles = [jax.device_put(b) for b in bufs]
                for h in handles:
                    h.block_until_ready()
                floor_s = time.perf_counter() - t0
                del handles
                thr_floor = n_total / floor_s
                log(f"replay raw-bytes link floor: {per_pass_bytes/1e6:.1f} MB "
                    f"in {floor_s:.2f}s = {per_pass_bytes/floor_s/1e6:.1f} MB/s "
                    f"-> {thr_floor/1e6:.2f} M pkts/s ceiling")
                emit(
                    "replay raw-bytes link floor (same compressed bytes, "
                    "no compute)",
                    thr_floor, "packets/s",
                )
                # link-normalized dataplane-attributable rate: the pass
                # time with the raw link cost subtracted — what the SAME
                # pipeline sustains once the link is not the wall (the
                # on-node PCIe deployment), bounded away from the
                # divide-by-zero when a pass ran entirely at the floor
                attr_dt = max(best_dt - floor_s, 0.02 * best_dt)
                emit(
                    "replay link-normalized dataplane-attributable rate "
                    "(best pass minus raw-bytes link floor)",
                    n_total / attr_dt, "packets/s",
                )
        except Exception as e:
            log(f"replay link-floor tier FAILED: {e}")

        # deny-event fidelity at the recorded sustained rate: drain what
        # is still queued, then report loss over everything seen
        deadline = time.time() + 30
        while len(d.ring) and time.time() < deadline:
            time.sleep(0.05)
        d.events_logger.stop()
        seen = d.ring.queued_total + d.ring.lost_samples
        loss_pct = 100.0 * d.ring.lost_samples / max(seen, 1)
        log(f"replay events: queued={d.ring.queued_total} "
            f"lost={d.ring.lost_samples} "
            f"spilled={d.events_logger.spilled_total} loss={loss_pct:.3f}%")
        emit(
            "replay deny-event loss at sustained rate "
            f"(ring {1 << 21} events, batch records + binary spill)",
            loss_pct, "percent", vs_baseline=0.0,
        )

        # Device-attributable replay rate (round-4 weak: the end-to-end
        # number is hostage to the tunnel's 8-17MB/s H2D): the SAME wire
        # chunks the daemon ships, classified from device-resident
        # buffers in a chained loop — the rate the dataplane would
        # sustain if the link were free (an on-node PCIe deployment).
        try:
            from infw.constants import KIND_IPV6 as _K6

            kinds = np.asarray(batch.kind)
            idx4 = np.nonzero(kinds != _K6)[0]
            sub = batch.take(idx4)
            wire, v4_only = sub.pack_wire_subset(
                np.arange(len(sub), dtype=np.int64)
            )
            dtab = jaxpath.device_tables(tables)
            if v4_only:
                depth = jaxpath.v4_trie_depth(len(dtab.trie_levels))
                dtab = dtab._replace(trie_levels=dtab.trie_levels[:depth])
            dw = jnp.asarray(wire)

            thr_dev = chained_wire_throughput(
                dtab, dw, len(sub), on_tpu, "replay-device")
            emit(
                "replay device-attributable classify rate "
                "(device-resident wire chunks, chained, v4 share)",
                thr_dev, "packets/s",
            )
        except Exception as e:
            log(f"replay device-attributable tier FAILED: {e}")
    finally:
        shutil.rmtree(state_dir, ignore_errors=True)


# --- config 5b: 1M-entry adversarial table --------------------------------


def bench_adversarial_1m(rng, on_tpu):
    trie_tier(
        rng, on_tpu, label="adv1m", spot_n=100_000, deep_lines=True,
        table_kw=dict(n_entries=1_000_000 if on_tpu else 10_000, width=4,
                      group_size=16),
        metric_of=lambda t: (
            f"packet classifications/sec/chip @{t.num_entries/1e6:.0f}M-entry "
            "adversarial overlap table (poptrie LPM walk, XLA, "
            "family+depth-steered chunks)"
        ),
    )


# --- multi-chip serving ladder ---------------------------------------------


def multichip_ladder(rng, on_tpu, counts=(1, 2, 4, 8), *,
                     dense_entries=None, trie_entries=None,
                     n_packets=None, spot=True):
    """Measured multi-chip scaling: packets/s at each device count for
    the two production mesh configurations (backend/mesh.py):

      - **dense**: tables replicated, the int8 MXU Pallas kernel (the
        single-chip headline kernel) running per shard under shard_map,
        batch sharded over "data";
      - **trie-sharded**: LPM entries partitioned into per-shard tries
        over "rules" (2 when the count allows), batch over "data",
        winner by pmax — the above-single-chip-capacity configuration.

    Timing is the same chained-fori-loop two-point slope as every other
    tier (no caching/hoisting possible); verdicts at the widest mesh are
    spot-checked against the oracle so the scaling numbers are tied to a
    bit-exactness proof.  Returns the record dict (None when fewer than
    two device counts fit), shared by bench_multichip below and
    __graft_entry__.dryrun_multichip — the MULTICHIP driver record."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from infw.parallel import mesh as meshmod

    devs = jax.devices()
    counts = [c for c in counts if c <= len(devs)]
    if len(counts) < 2:
        log(f"multichip: only {len(devs)} device(s) visible; ladder skipped")
        return None
    interpret = not on_tpu
    npk = n_packets or (2**19 if on_tpu else 2**13)

    nd = dense_entries or (1000 if on_tpu else 256)
    tables_d = testing.random_tables_fast(
        rng, n_entries=nd, width=16, ifindexes=(2, 3)
    )
    batch_d = testing.random_batch_fast(rng, tables_d, n_packets=npk)
    pt_host = pallas_dense.build_pallas_tables(tables_d)
    block_b = pallas_dense.choose_block_b(pt_host.mdt.shape[1])

    nt = trie_entries or (100_000 if on_tpu else 4_000)
    tables_t = testing.random_tables_fast(
        rng, n_entries=nt, width=8, group_size=6, ifindexes=(2, 3, 4)
    )
    batch_t = testing.random_batch_fast(rng, tables_t, n_packets=npk)

    rec = {
        "devices": counts, "packets": npk,
        "dense_entries": tables_d.num_entries,
        "trie_entries": tables_t.num_entries,
        "dense_pps": {}, "trie_sharded_pps": {},
    }
    for n in counts:
        mesh = meshmod.make_mesh(n, rules_shards=1)
        put = lambda a: jax.device_put(a, NamedSharding(mesh, P()))
        pt = jax.tree.map(put, pt_host)
        db = meshmod.shard_batch(batch_d, mesh)
        fn = meshmod.jitted_mesh_classify(
            mesh, "pallas-dense", pt, interpret=interpret, block_b=block_b
        )
        thr = chained_throughput(
            lambda t, b: fn(t, b)[0], pt, db, npk, on_tpu,
            f"mesh-dense@{n}dev",
        )
        rec["dense_pps"][n] = thr
        if spot and n == counts[-1]:
            spot_check(
                lambda sub: np.asarray(
                    fn(pt, meshmod.shard_batch(sub, mesh))[0]
                ),
                tables_d, batch_d, n=2000, label=f"mesh-dense@{n}dev",
            )

        rs = 2 if n % 2 == 0 else 1
        mesh_t = meshmod.make_mesh(n, rules_shards=rs)
        st = meshmod.shard_tables_trie(tables_t, mesh_t)
        db_t = meshmod.shard_batch(batch_t, mesh_t)
        fn_t = meshmod.make_sharded_trie_classifier(
            mesh_t, len(st.trie_levels)
        )
        thr_t = chained_throughput(
            lambda t, b: fn_t(t, b)[0], st, db_t, npk, on_tpu,
            f"mesh-trie@{n}dev(data{n // rs}x rules{rs})",
        )
        rec["trie_sharded_pps"][n] = thr_t
        if spot and n == counts[-1]:
            spot_check(
                lambda sub: np.asarray(
                    fn_t(st, meshmod.shard_batch(sub, mesh_t))[0]
                ),
                tables_t, batch_t, n=2000, label=f"mesh-trie@{n}dev",
            )

    base = counts[0]
    for kind in ("dense_pps", "trie_sharded_pps"):
        pps = rec[kind]
        rec[kind.replace("_pps", "_scaling_pct")] = {
            n: round(100.0 * pps[n] / (pps[base] * (n / base)), 1)
            for n in counts
        }
    return rec


def bench_multichip(rng, on_tpu):
    """Multichip bench tier: one ladder line per (config, device count),
    the per-chip rate printed beside the 1-device baseline so a scaling
    regression is visible at a glance, and one scaling-efficiency line
    (% of linear at the widest mesh) per configuration."""
    rec = multichip_ladder(rng, on_tpu)
    if rec is None:
        return
    sim = "" if on_tpu else " simulated"
    counts = rec["devices"]
    for kind, label in (
        ("dense_pps",
         f"int8 Pallas dense under shard_map @{rec['dense_entries']} "
         "entries, tables replicated"),
        ("trie_sharded_pps",
         f"rules-sharded per-shard tries @{rec['trie_entries'] // 1000}K "
         "entries, pmax winner combine"),
    ):
        pps = rec[kind]
        eff = rec[kind.replace("_pps", "_scaling_pct")]
        for n in counts:
            log(f"multichip {kind} @{n}: {pps[n]/1e6:.2f} M pkts/s "
                f"({pps[n]/n/1e6:.2f} M/chip vs {pps[counts[0]]/1e6:.2f} M "
                f"single-chip, {eff[n]:.0f}% of linear)")
            emit(
                f"multichip classify, {label}, {n}{sim} device(s) "
                f"(per-chip {pps[n]/n/1e6:.2f} M/s; 1-device baseline "
                f"{pps[counts[0]]/1e6:.2f} M/s)",
                pps[n], "packets/s",
            )
        emit(
            f"multichip scaling efficiency at {counts[-1]}{sim} devices, "
            f"{label} (% of linear from the 1-device baseline)",
            eff[counts[-1]], "percent",
            vs_baseline=eff[counts[-1]] / 100.0,
        )


# --- config 4: 8 interfaces x per-iface rule tables ------------------------


def bench_8iface(rng, on_tpu):
    """BASELINE config 4: one chip serving 8 interfaces, each with its own
    ruleset (the reference's per-iface LPM key space — ifindex is the top
    32 bits of the key, interfaces.go:85-116 expands bonds into member
    indices the same way).  The batch mixes all 8 ifindexes; the root LUT
    steers each packet into its interface's trie subtree."""
    def check(batch):
        ifx = np.asarray(batch.ifindex)
        n_if = len(np.unique(ifx[(ifx >= 2) & (ifx < 10)]))
        assert n_if == 8, f"batch covers {n_if}/8 interfaces"

    trie_tier(
        rng, on_tpu, label="8iface", spot_n=50_000, batch_check=check,
        table_kw=dict(n_entries=100_000 if on_tpu else 2_000, width=8,
                      ifindexes=tuple(range(2, 10))),
        metric_of=lambda t: (
            f"packet classifications/sec/chip, 8 ifaces x per-iface "
            f"rulesets @{t.num_entries // 1000}K entries "
            "(mixed-ifindex batch, poptrie)"
        ),
    )


# --- incremental rule-update latency --------------------------------------


def bench_incremental_update(rng, on_tpu, n_entries=None, width=8,
                             table_kw=None):
    """1-key RULE edit and 1-key CIDR ADD -> device latency: the
    Map.Update analogue (loader.go:200-218).  The rules edit takes the
    diff-scatter patch (ships only changed rows); the CIDR add takes the
    structural overlay (a tiny dense side-table upload — the main trie's
    poptrie form is untouched, round-4 missing #2)."""
    from infw.backend.tpu import TpuClassifier
    from infw.compiler import IncrementalTables, LpmKey, compile_tables_from_content

    if n_entries is None:
        n_entries = 100_000 if on_tpu else 2_000
    tkw = dict(n_entries=n_entries, width=width, ifindexes=(2, 3, 4))
    tkw.update(table_kw or {})
    tier = (f"{n_entries // 1000}K" if n_entries < 1_000_000
            else f"{n_entries/1e6:.0f}M")
    tables = testing.random_tables_fast(rng, **tkw)
    it = IncrementalTables.from_content(tables.content,
                                        rule_width=tkw["width"])
    clf = TpuClassifier(force_path="trie")
    t0 = time.perf_counter()
    clf.load_tables(it.snapshot())
    it.clear_dirty()  # device baseline established
    t_full = time.perf_counter() - t0
    log(f"update@{tier}: full load: {t_full:.2f}s")
    keys = list(it.content)
    lats = []
    for i in range(5):
        key = keys[1000 + i]
        rows = it.content[key].copy()
        rows[0, 6] = 1 if rows[0, 6] == 2 else 2
        t0 = time.perf_counter()
        it.apply({key: rows})
        clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
        it.clear_dirty()
        lats.append(time.perf_counter() - t0)
        mode, n_rows = clf._last_load
        log(f"update@{tier} {i}: {lats[-1]*1e3:.0f} ms mode={mode} rows={n_rows}")
        assert mode == "patch", "patch path must engage for 1-key edits"
    # best-of-N, like the replay tier: each sample rides 2-3 tunnel RPCs,
    # so the median measures link spikes (samples ranged 167ms-1.6s
    # across recorded runs), while the min is the dataplane's capability
    best = min(lats)
    log(f"update@{tier}: best {best*1e3:.0f} ms of "
        f"{sorted(int(l*1e3) for l in lats)}")
    emit(
        f"1-key rule update to device @{tier} entries, "
        f"best of {len(lats)} "
        f"(incremental diff-scatter patch; full reload {t_full:.1f}s)",
        best * 1e3, "ms", vs_baseline=t_full / best,
    )

    # structural CIDR ADD via the overlay (the syncer's routing: a new
    # identity never touches the main trie's device form)
    overlay = {}
    snap = it.snapshot()
    it.clear_dirty()
    add_lats = []
    for i in range(5):
        new_key = LpmKey(
            prefix_len=56,
            ingress_ifindex=2,
            ip_data=bytes([203, 0, 113 + i, 0]) + bytes(12),
        )
        rows = np.zeros((tkw["width"], 7), np.int32)
        rows[1] = [1, 6, 443, 0, 0, 0, 1]
        t0 = time.perf_counter()
        overlay[new_key] = rows
        ov_tables = compile_tables_from_content(
            dict(overlay), rule_width=tkw["width"])
        clf.load_tables(snap, dirty_hint=it.peek_dirty(), overlay=ov_tables)
        it.clear_dirty()
        add_lats.append(time.perf_counter() - t0)
        mode, n_rows = clf._last_load
        log(f"cidr-add@{tier} {i}: {add_lats[-1]*1e3:.0f} ms mode={mode}")
        assert mode == "patch", "CIDR add must not re-upload the main table"
    best_add = min(add_lats)
    log(f"cidr-add@{tier}: best {best_add*1e3:.0f} ms of "
        f"{sorted(int(l*1e3) for l in add_lats)}")
    emit(
        f"1-key CIDR add to device @{tier} entries, best of "
        f"{len(add_lats)} (structural overlay, main trie untouched; "
        f"full reload {t_full:.1f}s)",
        best_add * 1e3, "ms", vs_baseline=t_full / best_add,
    )

    # 1-key structural DELETE (round-5 weak #5: implemented but
    # unmeasured): tombstone + node-local repush in the compiler
    # (compiler.py purgeKeys analogue), then the diff-scatter device
    # patch — the Map.Delete analogue (loader.go:633-647).  Unlike the
    # CIDR add there is no overlay shortcut: the trie itself changes, so
    # this measures the real structural-edit device path.
    del_lats = []
    for i in range(5):
        key = keys[-(i + 1)]  # distinct from the rule-edit keys above
        t0 = time.perf_counter()
        it.apply({}, deletes=[key])
        clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
        it.clear_dirty()
        del_lats.append(time.perf_counter() - t0)
        mode, n_rows = clf._last_load
        log(f"delete@{tier} {i}: {del_lats[-1]*1e3:.0f} ms mode={mode} "
            f"rows={n_rows}")
    best_del = min(del_lats)
    log(f"delete@{tier}: best {best_del*1e3:.0f} ms of "
        f"{sorted(int(l*1e3) for l in del_lats)}")
    emit(
        f"1-key structural delete to device @{tier} entries, best of "
        f"{len(del_lats)} (tombstone + node-local repush + diff-scatter "
        f"patch; full reload {t_full:.1f}s)",
        best_del * 1e3, "ms", vs_baseline=t_full / best_del,
    )

    # Overlay-overflow merge spike (round-5 weak #5): the amortized slow
    # path a long-running daemon pays when the dense side-table outgrows
    # OVERLAY_CAP and its accumulated keys merge into the main trie
    # (syncer.py overflow branch) — measured as the structural apply of
    # every overlay key plus the device load, in one timed step.
    t0 = time.perf_counter()
    it.apply(dict(overlay))
    clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
    it.clear_dirty()
    t_merge = time.perf_counter() - t0
    mode, n_rows = clf._last_load
    log(f"overlay-merge@{tier}: {t_merge*1e3:.0f} ms mode={mode} "
        f"rows={n_rows} ({len(overlay)} overlay keys into main)")
    emit(
        f"overlay-overflow merge into main table @{tier} entries "
        f"({len(overlay)} accumulated structural adds, {mode} load; "
        f"full reload {t_full:.1f}s)",
        t_merge * 1e3, "ms", vs_baseline=t_full / t_merge,
    )
    clf.close()


# --- wire-path p50 latency -------------------------------------------------


def bench_wire_latency(tables, batch, on_tpu):
    """p50 of the production daemon path: pack_wire on host -> H2D ->
    fused classify -> 2B/packet readback.  Fresh dst_ports per iteration
    so the tunnel cannot memoize."""
    # Control: the tunnel's bare sync round-trip (noop kernel, 8B each
    # way).  Anything at or under this floor is the link, not the
    # dataplane — on-node PCIe deployment has a ~µs floor instead.
    noop = jax.jit(lambda x: x + 1)
    floors = []
    for i in range(8):
        x = np.array([i], np.uint32)
        t0 = time.perf_counter()
        np.asarray(noop(x))
        floors.append(time.perf_counter() - t0)
    floor = sorted(floors)[len(floors) // 2]
    log(f"tunnel sync floor (noop round-trip): {floor*1e3:.3f} ms")

    dt = jaxpath.device_tables(tables)
    fn = jaxpath.jitted_classify_wire(False)
    ladder = (32, 64, 128, 256, 1024, 4096)
    # Pre-warm EVERY ladder shape before any timed sample: round-5's
    # record read 11.768 ms "above link floor" @batch=32 (pinned device
    # input) while 64/128 read ~0 — the first ladder shape's jit
    # specialization (and the tunnel's per-executable first-dispatch
    # cost) landed inside the timed loop of whichever batch size ran
    # first.  After this loop the sweep must be compile-free, and the
    # recompile lint below asserts it (jaxcheck's _cache_size check, the
    # same invariant `make entry-check` enforces on the registered
    # entrypoints).
    for bs in ladder:
        w = jnp.asarray(batch.slice(0, bs).pack_wire())
        np.asarray(fn(dt, w)[0])
        dw = jax.device_put(np.asarray(w))
        np.asarray(fn(dt, dw)[0])
    cache0 = getattr(fn, "_cache_size", lambda: None)()
    best = None
    pinned_small = []
    for bs in ladder:
        sub = batch.slice(0, bs)
        wires = []
        for i in range(12):
            s = sub.slice(0, bs)
            s.dst_port = ((s.dst_port.astype(np.int64) + i) % 65536).astype(np.int32)
            wires.append(s.pack_wire())
        np.asarray(fn(dt, jnp.asarray(wires[0]))[0])  # warm (pre-compiled)
        lats = []
        for w in wires[2:]:
            t0 = time.perf_counter()
            res16, _stats = fn(dt, jnp.asarray(w))
            np.asarray(res16)
            lats.append(time.perf_counter() - t0)
        p50 = sorted(lats)[len(lats) // 2]
        # Pinned-input latency mode: the wire buffers are device-resident
        # BEFORE the clock starts (a latency-sensitive on-node deployment
        # keeps a pinned ring of input buffers), so the measured path is
        # classify + readback only.  The pinned set is perturbed
        # DIFFERENTLY from the unpinned wires above — re-executing those
        # byte-identical inputs would hit the tunnel's memoization and
        # time cached replays.
        pwires = []
        for i in range(12):
            s = sub.slice(0, bs)
            s.dst_port = ((s.dst_port.astype(np.int64) + 7000 + i) % 65536).astype(np.int32)
            pwires.append(s.pack_wire())
        dev_wires = [jax.device_put(w) for w in pwires]
        for dw in dev_wires:
            dw.block_until_ready()
        plats = []
        for dw in dev_wires[2:]:
            t0 = time.perf_counter()
            res16, _stats = fn(dt, dw)
            np.asarray(res16)
            plats.append(time.perf_counter() - t0)
        pin50 = sorted(plats)[len(plats) // 2]
        log(f"wire p50 @batch={bs}: {p50*1e3:.3f} ms "
            f"({p50/bs*1e9:.0f} ns/packet amortized); "
            f"pinned-input {pin50*1e3:.3f} ms "
            f"(above floor {max(pin50-floor,0.0)*1e3:.3f} ms)")
        if bs <= 128:
            pinned_small.append((bs, pin50))
        if best is None or p50 < best[1]:
            best = (bs, p50)
    if cache0 is not None:
        grew = fn._cache_size() - cache0
        assert grew == 0, (
            f"wire path recompiled during the latency sweep ({grew} new "
            "executables after the ladder pre-warm) — the serving shapes "
            "are not cached and every latency sample is suspect"
        )
        log("wire latency: recompile lint OK — all ladder shapes served "
            "from the pre-warmed jit cache")
    # BENCH_r05 anomaly sentinel (ISSUE-12 satellite): the round-5
    # record read 11.77 ms pinned-input p50 @batch=32 beside 0.25 ms
    # @batch=128 — diagnosed as a MEASUREMENT ARTIFACT, not a rung-32
    # dataplane bug: the ladder's first-measured shape paid its jit
    # specialization plus the tunnel's per-executable first-dispatch
    # cost inside the timed loop (batch 32 ran first), which the
    # full-ladder pre-warm above now moves off the clock and the
    # recompile assert pins.  A small-batch pinned p50 dwarfing the
    # large-batch one is therefore always suspect — flag a recurrence
    # loudly in the record instead of letting it read as a real floor
    # (tests/test_resident.py pins the compile-free pinned sweep).
    if len(pinned_small) >= 2:
        small = dict(pinned_small)
        if 32 in small and small[32] > 8 * max(small[max(small)], 1e-9):
            log(f"WARNING: pinned-input p50 @batch=32 "
                f"({small[32]*1e3:.3f} ms) is >8x the @batch="
                f"{max(small)} line — the BENCH_r05 anomaly shape; "
                "suspect a first-dispatch cost inside the timed loop, "
                "not the dataplane")
    emit(
        f"p50 verdict latency, wire path (batch={best[0]}, 1000-CIDR dense; "
        f"tunnel sync floor {floor*1e3:.1f} ms)",
        best[1] * 1e3, "ms", vs_baseline=0.0,
    )
    emit(
        "p50 verdict latency above link floor (dataplane-attributable)",
        max(best[1] - floor, 0.0) * 1e3, "ms", vs_baseline=0.0,
    )
    for bs, pin50 in pinned_small:
        emit(
            f"p50 verdict latency above link floor @batch={bs} "
            "(pinned device input)",
            max(pin50 - floor, 0.0) * 1e3, "ms", vs_baseline=0.0,
        )


# --- SLO serving tier: deadline-aware continuous microbatching -------------


def _slo_floor():
    """The tunnel's bare sync round-trip (noop kernel) — the link floor
    every reported SLO latency is measured above, same control as the
    wire-latency tier."""
    noop = jax.jit(lambda x: x + 1)
    floors = []
    for i in range(8):
        x = np.array([i], np.uint32)
        t0 = time.perf_counter()
        np.asarray(noop(x))
        floors.append(time.perf_counter() - t0)
    return sorted(floors)[len(floors) // 2]


def bench_slo(rng, on_tpu):
    """ISSUE-7 SLO tier: open-loop p50/p99/p999 verdict latency above
    link floor at three fixed offered loads, deadline-miss rate, the
    achieved batch-size distribution, and an A/B against the
    fixed-ingest_chunk dispatch the scheduler replaced — all in one
    record.

    Methodology (benchruns/README):
    - OPEN loop: arrivals follow a seeded Poisson schedule at the
      offered load regardless of how the pipeline keeps up; per-packet
      latency is completion minus SCHEDULED arrival, so backlog the
      scheduler causes is measured, not silently excluded (the
      closed-loop coordinated-omission failure).
    - loads are fractions (0.2 / 0.5 / 0.9) of the measured pipeline
      capacity at the max ladder batch, so the tier exercises the
      coalescing regime on every host class; the absolute pkts/s is
      emitted alongside.
    - the deadline budget is link floor + 20 ms (TPU tunnel) / 50 ms
      (CPU smoke): a dispatch cannot beat the floor, so the budget is
      what the SCHEDULER adds above it.

    Returns {sched_p99_ms, baseline_p99_ms, miss_rate} at the mid load
    for the slo-bench regression gate."""
    from infw.backend.tpu import TpuClassifier
    from infw.scheduler import (
        ContinuousScheduler,
        DeadlinePolicy,
        FixedChunkPolicy,
        ServiceModel,
        batch_ladder,
        prewarm_ladder,
    )
    from infw.daemon import DEFAULT_INGEST_CHUNK

    floor = _slo_floor()
    log(f"slo: link sync floor {floor*1e3:.3f} ms")
    deadline_s = floor + (0.020 if on_tpu else 0.050)
    max_batch = 4096 if on_tpu else 512

    t0 = time.perf_counter()
    tables = testing.random_tables_fast(
        rng, n_entries=100_000 if on_tpu else 2_000, width=8,
        ifindexes=(2, 3, 4),
    )
    clf = TpuClassifier(force_path="trie", wire_codec="wire8")
    clf.load_tables(tables)
    log(f"slo: table build+load {time.perf_counter()-t0:.1f}s "
        f"({tables.num_entries} entries, trie path, wire8)")

    # startup ladder pre-warm: every shape the scheduler can emit is
    # compiled (and first-dispatched) HERE; the recompile lint in
    # tests/test_scheduler.py asserts the serving path stays compile-free
    service = ServiceModel()
    t0 = time.perf_counter()
    n_warm = prewarm_ladder(clf, batch_ladder(max_batch),
                            include_depth_classes=False, service=service)
    log(f"slo: ladder prewarm {n_warm} dispatches in "
        f"{time.perf_counter()-t0:.1f}s; seeded service estimates "
        + ", ".join(f"{b}:{v*1e3:.1f}ms"
                    for b, v in sorted(service.snapshot().items())))

    # Measured pipeline capacity, calibrated by an intentionally
    # OVERLOADED probe serve on real mixed-family traffic: the achieved
    # (not offered) throughput of the full loop — subset pack, family
    # split, dispatch, drain-thread materialize — is the sustainable
    # rate; single-batch timings over-estimate it badly (they miss the
    # reduced pipeline overlap under trickled arrivals).  The offered
    # loads are fixed fractions of this, with the absolute pkts/s
    # emitted alongside so records stay comparable.
    pipe_depth = 4
    n_cal = 16 * max_batch
    calib = testing.random_batch_fast(rng, tables, n_packets=n_cal)
    policy0 = DeadlinePolicy(deadline_s, max_batch, service=service)
    # pass 1 (all-at-zero): warms every remaining dispatch shape and
    # bounds the saturated rate (includes any residual first-dispatch
    # cost, so it LOW-balls — pass 2 corrects)
    t0 = time.perf_counter()
    ContinuousScheduler(clf, policy0, pipeline_depth=pipe_depth).serve(
        calib, np.zeros(n_cal)
    )
    r0 = n_cal / max(time.perf_counter() - t0, 1e-6)
    # pass 2: Poisson arrivals at 3x the pass-1 rate = guaranteed
    # sustained overload; ACHIEVED throughput is the capacity
    offs_cal = testing.poisson_arrivals(
        np.random.default_rng(999), 3.0 * r0, n_cal
    )
    t0 = time.perf_counter()
    ContinuousScheduler(
        clf, DeadlinePolicy(deadline_s, max_batch, service=service),
        pipeline_depth=pipe_depth,
    ).serve(calib, offs_cal)
    cap_pps = n_cal / max(time.perf_counter() - t0, 1e-6)
    log(f"slo: calibrated capacity {cap_pps/1e3:.1f}K pkts/s "
        f"(saturated probe {r0/1e3:.1f}K, overloaded-Poisson achieved "
        f"{cap_pps/1e3:.1f}K over {n_cal} packets)")
    loads = [("low", 0.2), ("mid", 0.5), ("high", 0.9)]
    mid_out = {}

    def run_serve(policy, batch, offs, label):
        sched = ContinuousScheduler(clf, policy, pipeline_depth=pipe_depth)
        res = sched.serve(batch, offs)
        # bit-identity witness vs the CPU oracle through the scheduled
        # path (seeded subset; the full-batch check lives in the tests)
        wit = min(2000, len(batch))
        ref = oracle.classify(tables, batch.slice(0, wit)).results
        if not (res.results[:wit] == ref).all():
            raise RuntimeError(f"slo[{label}]: verdict mismatch vs oracle")
        return res

    for name, frac in loads:
        rate = max(frac * cap_pps, 500.0)
        n = int(min(max(rate * 2.0, 4_000), 200_000 if on_tpu else 40_000))
        batch = testing.random_batch_fast(rng, tables, n_packets=n)
        offs = testing.poisson_arrivals(
            np.random.default_rng(1000 + int(frac * 10)), rate, n
        )
        policy = DeadlinePolicy(deadline_s, max_batch, service=service)
        res = run_serve(policy, batch, offs, name)
        above = np.maximum(res.latency_s - floor, 0.0) * 1e3
        p50, p99, p999 = np.percentile(above, [50, 99, 99.9])
        st = res.stats.snapshot()
        miss_rate = st["misses"] / max(st["completed"], 1)
        bs = res.batch_sizes
        log(f"slo[{name}]: offered {rate:.0f} pkts/s n={n} "
            f"p50/p99/p999 above floor {p50:.2f}/{p99:.2f}/{p999:.2f} ms "
            f"miss {100*miss_rate:.2f}% "
            f"batches n={len(bs)} mean={bs.mean():.0f} "
            f"p50={np.percentile(bs, 50):.0f} max={bs.max()}; "
            f"hist {sorted(st['batch_hist'].items())}")
        emit(f"SLO offered load ({name}, {frac:.0%} of measured capacity)",
             rate, "packets/s", vs_baseline=0.0)
        for pname, val in (("p50", p50), ("p99", p99), ("p999", p999)):
            emit(
                f"SLO {pname} verdict latency above link floor @{name} "
                f"offered load (open-loop Poisson, deadline-aware "
                "microbatching)",
                val, "ms", vs_baseline=0.0,
            )
        emit(
            f"SLO deadline-miss rate @{name} offered load "
            f"(budget = link floor + {(deadline_s-floor)*1e3:.0f} ms)",
            100.0 * miss_rate, "percent", vs_baseline=0.0,
        )
        emit(
            f"SLO achieved batch size, mean @{name} offered load",
            float(bs.mean()), "packets", vs_baseline=0.0,
        )
        if name == "mid":
            mid_out.update(rate=rate, n=n, sched_p99_ms=float(p99),
                           miss_rate=float(miss_rate))

    # A/B at the mid load, same record: the fixed-ingest_chunk dispatch
    # the scheduler replaced (wait for a full chunk, flush at end of
    # stream).  The chunk is the daemon's historical default, capped at
    # half the run so the baseline dispatches at least twice instead of
    # degenerating to one end-of-stream flush.
    rate, n = mid_out["rate"], mid_out["n"]
    batch = testing.random_batch_fast(rng, tables, n_packets=n)
    offs = testing.poisson_arrivals(np.random.default_rng(1005), rate, n)
    base_chunk = min(DEFAULT_INGEST_CHUNK, max(n // 2, 32))
    res = run_serve(FixedChunkPolicy(base_chunk), batch, offs, "baseline")
    above = np.maximum(res.latency_s - floor, 0.0) * 1e3
    b50, b99 = np.percentile(above, [50, 99])
    log(f"slo[baseline]: fixed chunk={base_chunk} p50/p99 above floor "
        f"{b50:.2f}/{b99:.2f} ms vs scheduled p99 "
        f"{mid_out['sched_p99_ms']:.2f} ms")
    emit(
        "SLO p99 verdict latency above link floor @mid offered load, "
        "fixed-ingest_chunk baseline (the pre-scheduler dispatch, A/B "
        "same record)",
        b99, "ms", vs_baseline=0.0,
    )
    emit(
        "SLO scheduled-vs-fixed-chunk p99 improvement @mid offered load",
        b99 / max(mid_out["sched_p99_ms"], 1e-3), "x",
        vs_baseline=round(b99 / max(mid_out["sched_p99_ms"], 1e-3), 3),
    )
    mid_out["baseline_p99_ms"] = float(b99)

    # burst arrival shape at the mid load (the adversarial case for a
    # coalescing scheduler: a whole burst lands on one admission)
    try:
        offs_b = testing.burst_arrivals(
            np.random.default_rng(1006), rate, n,
            burst=min(256, max_batch),
        )
        res_b = run_serve(
            DeadlinePolicy(deadline_s, max_batch, service=service),
            batch, offs_b, "burst",
        )
        pb99 = float(np.percentile(
            np.maximum(res_b.latency_s - floor, 0.0) * 1e3, 99
        ))
        emit(
            "SLO p99 verdict latency above link floor @mid offered "
            "load, burst arrivals (256-packet bursts, same mean rate)",
            pb99, "ms", vs_baseline=0.0,
        )
    except Exception as e:
        log(f"slo burst line FAILED: {e}")

    # the batch=32 pinned-input regression (ISSUE-7 satellite): after
    # the ladder prewarm the small-batch wire shape must serve at the
    # batch=64/128 level — measured here the same way the wire-latency
    # tier measures it, against THIS classifier's serving path
    try:
        small = {}
        for bs_i in (32, 64, 128):
            sub = testing.random_batch_fast(rng, tables, n_packets=bs_i)
            lats = []
            for i in range(10):
                wire, v4o = sub.pack_wire_subset(
                    np.arange(bs_i, dtype=np.int64)
                )
                wire = wire.copy()
                wire[:, -1] ^= np.uint32(i + 1)  # defeat memoization
                t0 = time.perf_counter()
                clf.classify_prepared(
                    clf.prepare_packed(wire, v4o), apply_stats=False
                ).result()
                lats.append(time.perf_counter() - t0)
            small[bs_i] = sorted(lats)[len(lats) // 2]
            emit(
                f"SLO serving-path p50 latency above link floor "
                f"@batch={bs_i} (post-prewarm)",
                max(small[bs_i] - floor, 0.0) * 1e3, "ms",
                vs_baseline=0.0,
            )
        log("slo small-batch: " + ", ".join(
            f"{k}: {v*1e3:.2f}ms" for k, v in small.items()))
    except Exception as e:
        log(f"slo small-batch lines FAILED: {e}")
    return mid_out


def slo_bench_main() -> int:
    """``make slo-bench``: the SLO tier standalone at a smoke load
    (off-TPU CI) with a p99 regression gate — the scheduled path's
    p99-above-floor at the mid offered load must beat the
    fixed-ingest_chunk baseline by at least 1/INFW_SLO_P99_MAX_RATIO
    (default: scheduled <= 0.9x baseline).  Bit-identity vs the CPU
    oracle is asserted inside the tier; any mismatch raises."""
    ratio_max = float(os.environ.get("INFW_SLO_P99_MAX_RATIO", "0.9"))
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_slo(rng, on_tpu)
    emit_compact_record()
    sched, base = rec["sched_p99_ms"], rec["baseline_p99_ms"]
    if not sched <= ratio_max * base:
        log(f"slo-bench FAIL: scheduled p99 {sched:.2f} ms not <= "
            f"{ratio_max} x baseline {base:.2f} ms")
        return 1
    log(f"slo-bench OK: scheduled p99 {sched:.2f} ms vs baseline "
        f"{base:.2f} ms (gate {ratio_max}x)")
    return 0


# --- update-storm churn tier: batched multi-edit patch transactions --------


def bench_churn(rng, on_tpu):
    """ISSUE-9 churn tier: sustained rule edits concurrent with
    classification, on both trie-path layouts (the per-level poptrie
    walk and the compressed ctrie).

    Lines per layout, all in one record:
    - amortized per-edit device latency of a folded 64-edit transaction
      (ONE updater apply + ONE load_tables: one H2D staging pass, one
      fused scatter launch) vs the sequential one-edit-one-generation
      path — same-record A/B, INTERLEAVED rounds min-vs-min so ambient
      host load cannot skew the ratio (the build-bench discipline);
    - sustained edits/s actually flushed while serving a FIXED offered
      classify load (open loop, Poisson arrivals), p99 edit-visible
      latency (enqueue -> flush completion, the bounded-staleness
      metric), and classify-throughput retention vs an idle (no-churn)
      run of the same offered load.

    Returns {<layout>: {speedup, retention, p99_visible_ms, ...}} for
    the churn-bench regression gate."""
    from infw.backend.tpu import TpuClassifier
    from infw.compiler import IncrementalTables
    from infw.scheduler import (
        ContinuousScheduler,
        DeadlinePolicy,
        ServiceModel,
        batch_ladder,
        prewarm_ladder,
    )
    from infw.txn import EditOp, TxnApplier, TxnBatcher, TxnStats

    n_entries = 1_000_000 if on_tpu else 4_000
    width = 4
    batch_b = 64
    rounds = 2
    max_batch = 1024 if on_tpu else 256
    tier = (f"{n_entries/1e6:.0f}M" if n_entries >= 1_000_000
            else f"{n_entries // 1000}K")
    out = {}
    for layout in ("trie", "ctrie"):
        t0 = time.perf_counter()
        tables = testing.random_tables_fast(
            rng, n_entries=n_entries, width=width, ifindexes=(2, 3, 4),
        )
        it = IncrementalTables.from_content(tables.content,
                                            rule_width=width)
        clf = TpuClassifier(force_path=layout, wire_codec="wire8")
        clf.load_tables(it.snapshot())
        it.clear_dirty()
        if clf.active_path != layout:
            log(f"churn[{layout}]: layout declined (serving "
                f"{clf.active_path}); skipping")
            clf.close()
            continue
        log(f"churn[{layout}]: table build+load "
            f"{time.perf_counter()-t0:.1f}s ({n_entries} entries)")
        txn_stats = TxnStats()
        applier = TxnApplier(clf, it, stats=txn_stats)
        keys = list(it.content)
        edit_rng = np.random.default_rng(4242)

        def mk_edits(n, keys=keys, edit_rng=edit_rng):
            # rules-only edits on live keys: the churn hot path (adds/
            # deletes ride the same fold; their structural cost is
            # measured by the incremental-update tier)
            picks = edit_rng.choice(len(keys), size=n, replace=False)
            return [
                EditOp("rules_edit", keys[int(i)],
                       testing.random_rules(edit_rng, width))
                for i in picks
            ]

        # -- A/B: folded transaction vs sequential, interleaved ----------
        applier.apply(mk_edits(1))  # warm both paths' first-edit cost
        seq_best = txn_best = float("inf")
        for _r in range(rounds):
            edits = mk_edits(batch_b)
            t0 = time.perf_counter()
            for e in edits:
                rep = applier.apply([e], reason="manual")
                assert rep.mode == "patch", (
                    "sequential edit fell off the patch path"
                )
            seq_best = min(seq_best,
                           (time.perf_counter() - t0) / batch_b)
            edits = mk_edits(batch_b)
            t0 = time.perf_counter()
            rep = applier.apply(edits, reason="manual")
            assert rep.mode == "patch", (
                "folded transaction fell off the patch path"
            )
            txn_best = min(txn_best,
                           (time.perf_counter() - t0) / batch_b)
        speedup = seq_best / max(txn_best, 1e-9)
        log(f"churn[{layout}]: per-edit seq {seq_best*1e3:.2f} ms vs "
            f"txn@{batch_b} {txn_best*1e3:.2f} ms -> {speedup:.1f}x")
        emit(
            f"churn amortized per-edit device latency @{tier} "
            f"({layout}, folded txn batch={batch_b}: one fused patch "
            "generation)",
            txn_best * 1e3, "ms", vs_baseline=round(speedup, 2),
        )
        emit(
            f"churn per-edit device latency @{tier} ({layout}, "
            "sequential one-edit-one-generation baseline, A/B same "
            "record)",
            seq_best * 1e3, "ms", vs_baseline=0.0,
        )

        # -- sustained churn under a fixed offered classify load ---------
        service = ServiceModel()
        prewarm_ladder(clf, batch_ladder(max_batch),
                       include_depth_classes=False, service=service)
        n_pkts = 32_000 if on_tpu else 8_000
        probe = testing.random_batch_fast(rng, it.snapshot(), n_pkts)
        t0 = time.perf_counter()
        ContinuousScheduler(
            clf, DeadlinePolicy(0.5, max_batch, service=service),
            pipeline_depth=4,
        ).serve(probe, np.zeros(n_pkts))
        r0 = n_pkts / max(time.perf_counter() - t0, 1e-6)
        offered = max(0.3 * r0, 500.0)
        n = int(min(max(offered * 2.0, 4_000), 100_000))
        batch = testing.random_batch_fast(rng, it.snapshot(), n)
        offs = testing.poisson_arrivals(
            np.random.default_rng(77), offered, n
        )

        def run_serve(with_churn: bool):
            policy = DeadlinePolicy(0.5, max_batch, service=service)
            visible: list = []
            stop = threading.Event()
            churner = None
            batcher = None
            flushed = [0]
            if with_churn:
                batcher = TxnBatcher(
                    staleness_s=0.002, max_ops=batch_b
                )

                def flush(items, reason):
                    applier.apply(
                        [op for op, _ts in items], reason=reason,
                        enqueue_ts=[ts for _op, ts in items],
                    )
                    t_done = time.monotonic()
                    visible.extend(t_done - ts for _op, ts in items)
                    flushed[0] += len(items)

                edit_rate = 2000.0 if on_tpu else 400.0

                def churn_loop():
                    # open loop: edits queue on their absolute schedule
                    t_anchor = time.monotonic()
                    i = 0
                    while not stop.is_set():
                        target = t_anchor + i / edit_rate
                        dt = target - time.monotonic()
                        if dt > 0:
                            stop.wait(min(dt, 0.05))
                            continue
                        for e in mk_edits(8):
                            batcher.queue(e)
                        i += 8

                churner = threading.Thread(
                    target=churn_loop, daemon=True
                )
                churner.start()
                sched = ContinuousScheduler(
                    clf, policy, pipeline_depth=4,
                    txn_batcher=batcher, txn_flush=flush,
                )
            else:
                sched = ContinuousScheduler(clf, policy, pipeline_depth=4)
            t0 = time.perf_counter()
            res = sched.serve(batch, offs)
            elapsed = time.perf_counter() - t0
            # snapshot the IN-WINDOW accounting before draining
            # leftovers: the end-of-stream flush keeps the device state
            # and staleness histogram complete, but its edits landed
            # outside the timed window and must not inflate the
            # published edits/s or skew the p99 with teardown time
            n_flushed_in_window = flushed[0]
            visible_in_window = list(visible)
            if churner is not None:
                stop.set()
                churner.join()
                leftovers = batcher.drain()
                if leftovers:
                    flush(leftovers, "eof")
            return res, elapsed, visible_in_window, n_flushed_in_window

        _res_i, idle_s, _v, _f = run_serve(False)
        res_c, churn_s, visible, n_flushed = run_serve(True)
        idle_pps = n / idle_s
        churn_pps = n / churn_s
        retention = churn_pps / max(idle_pps, 1e-9)
        eps = n_flushed / max(churn_s, 1e-9)
        p99_vis = (
            float(np.percentile(np.asarray(visible) * 1e3, 99))
            if visible else 0.0
        )
        st = txn_stats.snapshot()
        log(f"churn[{layout}]: offered {offered:.0f} pps, idle "
            f"{idle_pps:.0f} pps vs churn {churn_pps:.0f} pps "
            f"(retention {100*retention:.1f}%), {eps:.0f} edits/s "
            f"flushed, p99 edit-visible {p99_vis:.1f} ms, txn stats "
            f"{st['txns']} txns / {st['ops']} ops / "
            f"{st['escalations']} escalations")
        emit(
            f"churn sustained edit rate @{tier} ({layout}, flushed "
            "while serving the fixed offered classify load)",
            eps, "edits/s", vs_baseline=0.0,
        )
        emit(
            f"churn p99 edit-visible latency @{tier} ({layout}, "
            "enqueue -> flush completion, 2 ms staleness budget)",
            p99_vis, "ms", vs_baseline=0.0,
        )
        emit(
            f"churn classify-throughput retention @{tier} ({layout}, "
            f"achieved at fixed offered load vs idle baseline, "
            f"offered {offered:.0f} pkts/s)",
            100.0 * retention, "percent",
            vs_baseline=round(retention, 3),
        )
        out[layout] = {
            "speedup": float(speedup),
            "seq_ms": float(seq_best * 1e3),
            "txn_ms": float(txn_best * 1e3),
            "retention": float(retention),
            "p99_visible_ms": p99_vis,
            "edits_per_s": float(eps),
        }
        clf.close()
    return out


def churn_bench_main() -> int:
    """``make churn-bench``: the churn tier standalone (CPU smoke off
    TPU) with the regression gates — the folded transaction's amortized
    per-edit cost must beat the sequential path by
    INFW_CHURN_SPEEDUP_MIN (default 5x, the ISSUE-9 acceptance) and
    classify-throughput retention under churn must stay above
    INFW_CHURN_RETENTION_MIN (default 0.9).  The statecheck multi-op
    equivalence (txn config: cold-rebuild bit-identity + per-op-ground-
    truth oracle parity) runs FIRST and gates record publication."""
    speedup_min = float(os.environ.get("INFW_CHURN_SPEEDUP_MIN", "5.0"))
    retention_min = float(os.environ.get("INFW_CHURN_RETENTION_MIN", "0.9"))
    from infw.analysis import statecheck

    for cfg in ("txn", "txn-ctrie"):
        rep = statecheck.run_config(cfg, seed=0, n_ops=6,
                                    shrink_on_failure=False)
        if not rep["ok"]:
            log(f"churn-bench FAIL: statecheck {cfg} not green before "
                f"record publication: {rep['failure']}")
            return 1
        log(f"churn-bench: statecheck {cfg} green "
            f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_churn(rng, on_tpu)
    emit_compact_record()
    if not rec:
        log("churn-bench FAIL: no layout produced a record")
        return 1
    rc = 0
    for layout, r in rec.items():
        if not r["speedup"] >= speedup_min:
            log(f"churn-bench FAIL[{layout}]: txn speedup "
                f"{r['speedup']:.2f}x < gate {speedup_min}x")
            rc = 1
        if not r["retention"] >= retention_min:
            log(f"churn-bench FAIL[{layout}]: classify retention "
                f"{r['retention']:.3f} < gate {retention_min}")
            rc = 1
    if rc == 0:
        log("churn-bench OK: " + ", ".join(
            f"{la}: {r['speedup']:.1f}x speedup, "
            f"{100*r['retention']:.1f}% retention"
            for la, r in rec.items()
        ))
    return rc


# --- multi-tenant paged arena tier (ISSUE-10) -------------------------------


def bench_tenant(rng, on_tpu):
    """Multi-tenant arena tier (``make tenant-bench``, folded into
    bench-checked):

    - **tenant hot-swap vs full re-upload** (the acceptance line): the
      page-table row flip activating a PRE-STAGED slab on a warm arena
      vs the single-tenant classifier's full table upload of the same
      ruleset, measured INTERLEAVED min-vs-min (benchruns rules: both
      sides see the same ambient load) at 1M entries on TPU (20K CPU
      smoke);
    - **mixed-tenant batch vs sequential per-tenant dispatch** at 64
      (and 512 on TPU) tenants: one tenant-column batch through the
      arena dispatch vs one dispatch per tenant on the same arena;
    - **arena HBM footprint vs N independent padded tables**;
    - every line gated on mixed-batch bit-identity vs the per-tenant
      CPU oracles through the production wire dispatch.

    Returns the record dict for the tenant-bench gate
    (INFW_SWAP_SPEEDUP_MIN)."""
    from infw import oracle as oracle_mod, packets as packets_mod
    from infw.backend.tpu import ArenaClassifier

    out = {}

    # -- swap A/B at scale --------------------------------------------------
    n_swap = 1_000_000 if on_tpu else 200_000
    big = testing.clean_tables_fast(rng, n_entries=n_swap, width=4)
    big2 = testing.clean_tables_fast(
        np.random.default_rng(4242), n_entries=n_swap, width=4
    )
    spec = jaxpath.arena_spec_for(
        "ctrie", (big, big2), pages=4, max_tenants=8
    )
    alloc = jaxpath.ArenaAllocator(spec)
    alloc.load_tenant(0, big)
    # pre-stage the standby slabs once; the measured swap is the
    # ACTIVATION (page-table row flip) — the serving-path cost
    pg_a = alloc.stage(big2)
    pg_b = alloc.page_of(0)

    def flip_once(i):
        t0 = time.perf_counter()
        alloc.activate(0, pg_a if i % 2 == 0 else pg_b)
        jax.block_until_ready(alloc.arena.page_table)
        return time.perf_counter() - t0

    def upload_once(i):
        t = big2 if i % 2 == 0 else big
        t0 = time.perf_counter()
        dev = jaxpath.device_ctrie(t, pad=True)
        jax.block_until_ready(dev[0].nodes)
        return time.perf_counter() - t0

    flip_s, upload_s = float("inf"), float("inf")
    flip_once(0)  # warm both executables off the clock
    upload_once(0)
    for i in range(1, 4):  # interleaved min-vs-min
        flip_s = min(flip_s, flip_once(i))
        upload_s = min(upload_s, upload_once(i))
    speedup = upload_s / max(flip_s, 1e-9)
    log(f"tenant swap @{n_swap} entries: flip {flip_s*1e6:.0f} us vs "
        f"full re-upload {upload_s*1e3:.1f} ms ({speedup:.0f}x)")
    emit(f"tenant hot-swap page-flip @{n_swap} entries", flip_s * 1e3, "ms",
         vs_baseline=0.0)
    emit(f"tenant full re-upload @{n_swap} entries", upload_s * 1e3, "ms",
         vs_baseline=0.0)
    emit("tenant swap speedup vs re-upload", speedup, "x", vs_baseline=0.0)
    out["swap_speedup"] = float(speedup)
    del alloc

    # -- mixed-tenant batch vs sequential per-tenant dispatch ---------------
    for n_tenants in (64, 512) if on_tpu else (64,):
        per_entries = 64
        tabs = [
            testing.random_tables_fast(
                np.random.default_rng(9000 + t), n_entries=per_entries,
                width=4, v6_fraction=0.3, ifindexes=(2, 3),
            )
            for t in range(n_tenants)
        ]
        spec = jaxpath.arena_spec_for(
            "ctrie", tabs, pages=n_tenants + 2,
            max_tenants=n_tenants + 1,
        )
        clf = ArenaClassifier(spec, fused_deep=False)
        for t, tab in enumerate(tabs):
            clf.load_tenant(t, tab)
        per_b = 4096 // n_tenants if on_tpu else 16
        parts, tags, refs = [], [], []
        for t, tab in enumerate(tabs):
            b = testing.random_batch_fast(
                np.random.default_rng(100 + t), tab, n_packets=per_b
            )
            parts.append(b)
            tags.append(np.full(per_b, t, np.int32))
            refs.append(oracle_mod.classify(tab, b))
        batch = packets_mod.concat(parts)
        tenant = np.concatenate(tags)
        wire = batch.pack_wire()
        B = len(batch)

        # oracle bit-identity gate BEFORE any timing line
        got = clf.classify_async_packed_tenant(
            wire, tenant, apply_stats=False
        ).result()
        want = np.concatenate([r.results for r in refs])
        if not np.array_equal(got.results, want):
            raise RuntimeError(
                f"tenant-bench oracle mismatch at {n_tenants} tenants: "
                f"{int((got.results != want).sum())}/{B} verdicts"
            )
        log(f"tenant mixed-batch oracle bit-identity OK "
            f"({n_tenants} tenants, {B} packets)")

        reps = 8 if on_tpu else 3

        def mixed_once():
            t0 = time.perf_counter()
            clf.classify_async_packed_tenant(
                wire, tenant, apply_stats=False
            ).result()
            return time.perf_counter() - t0

        sub_wires = []
        for t in range(n_tenants):
            idx = np.nonzero(tenant == t)[0]
            w, _v4 = batch.pack_wire_subset(idx.astype(np.int64))
            sub_wires.append((w, np.full(len(idx), t, np.int32)))

        def seq_once():
            t0 = time.perf_counter()
            pend = [
                clf.classify_async_packed_tenant(w, tg, apply_stats=False)
                for w, tg in sub_wires
            ]
            for p in pend:
                p.result()
            return time.perf_counter() - t0

        mixed_s, seq_s = float("inf"), float("inf")
        mixed_once()
        seq_once()  # warm
        for _ in range(reps):  # interleaved
            mixed_s = min(mixed_s, mixed_once())
            seq_s = min(seq_s, seq_once())
        log(f"mixed-tenant batch @{n_tenants} tenants: "
            f"{B/mixed_s/1e6:.2f} M pkts/s vs sequential "
            f"{B/seq_s/1e6:.2f} M pkts/s "
            f"({seq_s/mixed_s:.1f}x)")
        emit(f"mixed-tenant classify @{n_tenants} tenants", B / mixed_s,
             "packets/s", vs_baseline=0.0)
        emit(f"sequential per-tenant classify @{n_tenants} tenants",
             B / seq_s, "packets/s", vs_baseline=0.0)
        out[f"mixed_vs_seq_{n_tenants}"] = float(seq_s / mixed_s)

        # -- HBM footprint vs N independent padded tables -------------------
        pool_b = clf.allocator.pool_bytes()
        one = jaxpath.device_ctrie(tabs[0], pad=True)
        table_b = sum(int(np.asarray(a).nbytes) for a in one[0])
        ratio = (n_tenants * table_b) / max(pool_b, 1)
        log(f"arena footprint @{n_tenants} tenants: pool "
            f"{pool_b/1e6:.1f} MB vs {n_tenants} padded tables "
            f"{n_tenants*table_b/1e6:.1f} MB ({ratio:.2f}x)")
        emit(f"arena HBM pool @{n_tenants} tenants", pool_b / 1e6, "MB",
             vs_baseline=0.0)
        emit(f"{n_tenants} independent padded tables",
             n_tenants * table_b / 1e6, "MB", vs_baseline=0.0)
        out[f"footprint_ratio_{n_tenants}"] = float(ratio)
        clf.close()

    # -- CoW redundancy ladder (ISSUE-15) -----------------------------------
    # 1/10/100 distinct rulesets across N tenants: HBM bytes/tenant
    # under content-addressed sharing vs unshared (one slab per tenant),
    # create-from-shared-content latency vs a cold bake, and the CoW
    # clone-then-patch latency vs the PR-10 full-rebake baseline —
    # every A/B interleaved min-vs-min (benchruns rules).
    n_cow = 10_000 if on_tpu else 2_500
    ladder = (1, 10, 100)
    cow_tabs = [
        testing.random_tables_fast(
            np.random.default_rng(12000 + i), n_entries=48, width=4,
            v6_fraction=0.3, ifindexes=(2, 3),
        )
        for i in range(max(ladder))
    ]
    for distinct in ladder:
        # the pool is sized to the rung's CONTENT capacity, not the
        # tenant count — that is the lever: N tenants on D rulesets
        # cost D slabs (+ spare pages for CoW headroom) + one
        # page-table row each
        spec = jaxpath.arena_spec_for(
            "ctrie", cow_tabs[:distinct], pages=distinct + 4,
            max_tenants=n_cow + 4, headroom=2.0,
        )
        pt_bytes = spec.max_tenants * 4
        al = jaxpath.ArenaAllocator(spec)
        t0 = time.perf_counter()
        for t in range(n_cow):
            al.load_tenant(t, cow_tabs[t % distinct])
        create_s = time.perf_counter() - t0
        pool_b = al.pool_bytes()
        slab_b = (pool_b - pt_bytes) // spec.pages
        shared_pt = pool_b / n_cow
        unshared_pt = (n_cow * slab_b + pt_bytes) / n_cow
        ratio = unshared_pt / max(shared_pt, 1e-9)
        assert al.counters["slab_writes"] == distinct
        log(f"cow ladder {distinct:3d}/{n_cow} distinct: "
            f"{shared_pt:.0f} B/tenant shared vs {unshared_pt:.0f} B "
            f"unshared ({ratio:.1f}x), {n_cow} creates in "
            f"{create_s*1e3:.0f} ms")
        emit(f"cow HBM bytes/tenant @{distinct} distinct of {n_cow}",
             shared_pt, "B", vs_baseline=0.0)
        emit(f"cow bytes/tenant reduction @{distinct} distinct", ratio,
             "x", vs_baseline=0.0)
        out[f"cow_bytes_ratio_{distinct}"] = float(ratio)
        if distinct != max(ladder):
            del al
            continue

        # create-from-shared-content vs cold bake (interleaved): the
        # hash-hit create is a dict probe + page-table flip; the cold
        # bake pays canonical build + full-slab fused scatter.  Cold
        # tables are FRESH objects per rep (no memoized bake).
        reps = 4
        cold_tabs = [
            testing.random_tables_fast(
                np.random.default_rng(13000 + i), n_entries=48, width=4,
                v6_fraction=0.3, ifindexes=(2, 3),
            )
            for i in range(reps)
        ]
        shared_s, cold_s = float("inf"), float("inf")
        spare = n_cow
        for i in range(reps):
            t0 = time.perf_counter()
            assert al.load_tenant(spare, cow_tabs[0]) == "share"
            jax.block_until_ready(al.arena.page_table)
            shared_s = min(shared_s, time.perf_counter() - t0)
            al.destroy_tenant(spare)
            t0 = time.perf_counter()
            assert al.load_tenant(spare + 1, cold_tabs[i]) == "assign"
            jax.block_until_ready(al.arena.nodes)
            cold_s = min(cold_s, time.perf_counter() - t0)
            al.destroy_tenant(spare + 1)
        log(f"cow create-from-shared {shared_s*1e6:.0f} us vs cold bake "
            f"{cold_s*1e3:.2f} ms ({cold_s/max(shared_s,1e-9):.0f}x)")
        emit("cow create-from-shared-content", shared_s * 1e6, "us",
             vs_baseline=0.0)
        emit("cow cold slab bake", cold_s * 1e6, "us", vs_baseline=0.0)
        out["cow_create_speedup"] = float(cold_s / max(shared_s, 1e-9))
        del al

    # -- CoW clone-then-patch vs the PR-10 re-upload baseline ----------------
    # A PRODUCTION-SIZED slab (the swap-bench scale), small pool: the
    # clone copies the donor's canonical mirror and patches the dirty
    # rows — no table rebuild; the baseline recompiles + rebakes the
    # edited snapshot from scratch (what every edit of a shared ruleset
    # cost before content addressing).  Interleaved min-vs-min.
    from infw.compiler import IncrementalTables as _IT

    n_clone = 200_000 if on_tpu else 20_000
    base_big = testing.clean_tables_fast(
        np.random.default_rng(777), n_entries=n_clone, width=4
    )
    base_content = dict(base_big.content)
    spec = jaxpath.arena_spec_for(
        "ctrie", (base_big,), pages=6, max_tenants=8, headroom=1.5
    )
    al = jaxpath.ArenaAllocator(spec)
    al.load_tenant(0, base_big)
    al.load_tenant(1, base_big)  # the shared baseline (refcount 2)
    k_edit = sorted(
        base_content, key=lambda k: (k.ingress_ifindex, k.ip_data)
    )[0]
    reps = 3
    clone_s, rebake_s = float("inf"), float("inf")
    for i in range(reps):
        upd = _IT.from_content(dict(base_content), rule_width=4)
        snap0 = upd.snapshot()
        al.load_tenant(2, snap0)  # joins the shared baseline
        upd.start_dirty_tracking()
        r = np.asarray(base_content[k_edit]).copy()
        r[1] = [1, 6, 1000 + i, 0, 0, 0, 2]
        upd.apply({k_edit: r}, [])
        hint = upd.peek_dirty()
        snap1 = upd.snapshot()
        assert al.tenant_shares_page(2)
        t0 = time.perf_counter()
        path = al.load_tenant(2, snap1, hint=hint)
        jax.block_until_ready(al.arena.nodes)
        clone_s = min(clone_s, time.perf_counter() - t0)
        assert path == "cow", path
        al.destroy_tenant(2)
        # baseline: the same edited ruleset re-uploaded — canonical
        # bake (cpoptrie build) + full-slab write from a FRESH snapshot
        # object (no memoized layout), the PR-10 path for a full
        # tenant-ruleset replacement; the updater compile stays off the
        # clock on both sides
        upd2 = _IT.from_content(dict(base_content), rule_width=4)
        upd2.apply({k_edit: r}, [])
        snap2 = upd2.snapshot()
        t0 = time.perf_counter()
        al.load_tenant(3, snap2)
        jax.block_until_ready(al.arena.nodes)
        rebake_s = min(rebake_s, time.perf_counter() - t0)
        al.destroy_tenant(3)
    log(f"cow clone-then-patch @{n_clone} entries {clone_s*1e3:.1f} ms "
        f"vs PR-10 rebake {rebake_s*1e3:.1f} ms "
        f"({rebake_s/max(clone_s,1e-9):.1f}x)")
    emit(f"cow clone-then-patch @{n_clone} entries", clone_s * 1e3, "ms",
         vs_baseline=0.0)
    emit(f"cow edit full-rebake baseline @{n_clone} entries",
         rebake_s * 1e3, "ms", vs_baseline=0.0)
    out["cow_clone_speedup"] = float(rebake_s / max(clone_s, 1e-9))
    del al
    return out


def bench_splice(rng, on_tpu):
    """Structural-compression ladder (ISSUE-17, ``make splice-bench``,
    folded into bench-checked) — the similar-NOT-identical extension of
    the tenant tier's CoW ladder.  Content addressing (ISSUE-15) only
    pays off for bit-identical rulesets; this tier measures the
    subtree-plane splice layer on a drift chain of tenants where every
    tenant is a k-edit delta of its predecessor (no two identical):

    - **bytes/tenant rungs** at k ∈ {1, 16, 256} rules-edits between
      neighbours: resident HBM of the spliced pool (shared trunk pages
      + refcounted subtree planes + splice banks) vs one flat slab per
      tenant, k=16 over 2.5K CPU / 10K TPU tenants is the gate rung
      (INFW_SPLICE_BYTES_RATIO_MIN);
    - **walk-latency tax**: the same 64-tenant mixed batch through the
      spliced arena vs a flat (unspliced) arena holding identical
      tables, interleaved min-vs-min — the splice indirection must
      cost <2x (INFW_SPLICE_WALK_TAX_MAX);
    - **oracle gate**: sampled tenants' verdicts bit-identical to
      per-tenant CPU oracles through the spliced fused dispatch BEFORE
      any timing or footprint line;
    - **zero-recompile pin**: k more drift edits + a fresh tenant load
      + classify on the warm arena must compile nothing.

    The base table puts one deep entry (alternating /24 subnet and /32
    host — the two masks whose subtrees leaf-push to a single target
    row) in each of 192 distinct /16s, so every l0 slot owns exactly
    one plane-eligible subtree and a k-edit delta dirties exactly k
    subtrees.  Returns the record dict for the splice-bench gates."""
    from infw import oracle as oracle_mod, packets as packets_mod
    from infw.compiler import IncrementalTables as _IT, LpmKey

    out = {}
    width = 4
    n_keys = 192
    base_content = {}
    for i in range(n_keys):
        mask = 24 if i % 2 else 32
        data = bytes(
            [10 + (i >> 8), i & 0xFF, 1 + (i % 254), i % 251]
        ) + bytes(12)
        base_content[LpmKey(mask + 32, 2, data)] = testing.random_rules(
            rng, width
        )
    base = _IT.from_content(dict(base_content), rule_width=width).snapshot()
    keys = sorted(base_content, key=lambda k: k.ip_data)

    gate_tenants = int(os.environ.get(
        "INFW_SPLICE_TENANTS", "10240" if on_tpu else "2560"
    ))
    ladder = (
        (1, max(gate_tenants // 5, 8)),
        (16, gate_tenants),
        (256, max(gate_tenants // 10, 8)),
    )
    for k, n_t in ladder:
        erng = np.random.default_rng(31000 + k)
        upd = _IT.from_content(dict(base_content), rule_width=width)
        # plane pool sized to the rung's DISTINCT subtree versions:
        # the 192 base subtrees plus one new plane per edit (a k-edit
        # delta dirties min(k, 192) subtrees per tenant)
        planes = n_keys + n_t * min(k, n_keys) + 64
        spec = jaxpath.arena_spec_for(
            "ctrie", (base,), pages=8, max_tenants=n_t + 8,
            headroom=1.5, plane_slots=planes, plane_node_rows=8,
            plane_target_rows=8, plane_joined_rows=8, splice_slots=256,
        )
        al = jaxpath.ArenaAllocator(spec)
        al.load_tenant(0, base)
        sample_ids = sorted({0, 1, n_t // 3, n_t // 2, n_t - 1})
        snaps = {0: base}
        snaps64 = [base]
        cur = 0
        t0 = time.perf_counter()
        for t in range(1, n_t):
            edits = {}
            for j in range(k):
                edits[keys[(cur + j) % n_keys]] = testing.random_rules(
                    erng, width
                )
            cur = (cur + k) % n_keys
            upd.apply(edits, [])
            snap = upd.snapshot()
            al.load_tenant(t, snap)
            if t in sample_ids:
                snaps[t] = snap
            if len(snaps64) < 64:
                snaps64.append(snap)
        create_s = time.perf_counter() - t0

        # -- footprint: spliced pool vs one flat slab per tenant ------------
        ar = al.arena
        P = spec.pages
        nb = ar.nodes.nbytes // ar.nodes.shape[0]
        tb = ar.targets.nbytes // ar.targets.shape[0]
        jb = ar.joined.nbytes // ar.joined.shape[0]
        slab_b = (ar.l0.nbytes // P + ar.root_lut.nbytes // P
                  + spec.node_rows * nb + spec.target_rows * tb
                  + spec.joined_rows * jb)
        plane_b = (spec.plane_node_rows * nb
                   + spec.plane_target_rows * tb
                   + spec.plane_joined_rows * jb)
        cnt = al.counter_values()
        trunk_pages = cnt["tenant_distinct_slabs"]
        n_planes = al.distinct_planes()
        if trunk_pages > 4:
            raise RuntimeError(
                f"splice ladder k={k}: {trunk_pages} trunk pages live — "
                "the drift chain fell back to whole-slab tenants"
            )
        spliced_total = (trunk_pages * slab_b + n_planes * plane_b
                         + ar.splice.nbytes + ar.page_table.nbytes)
        spliced_pt = spliced_total / n_t
        flat_pt = slab_b + 4  # full slab + its page-table row
        ratio = flat_pt / max(spliced_pt, 1e-9)
        log(f"splice ladder k={k:3d} @{n_t} tenants: "
            f"{spliced_pt/1e3:.1f} KB/tenant spliced "
            f"({trunk_pages} trunk page(s), {n_planes} planes) vs "
            f"{flat_pt/1e3:.1f} KB flat ({ratio:.1f}x), "
            f"{n_t} creates in {create_s:.1f} s")
        emit(f"splice HBM bytes/tenant @k={k} of {n_t}", spliced_pt, "B",
             vs_baseline=0.0)
        emit(f"splice bytes/tenant reduction @k={k}", ratio, "x",
             vs_baseline=0.0)
        out[f"splice_bytes_ratio_k{k}"] = float(ratio)

        # -- oracle gate: sampled tenants bit-identical to CPU oracles ------
        fn = jaxpath.jitted_classify_arena_wire_fused(
            "ctrie", spec.pages, spec.d_max, spec=spec
        )
        for t in sample_ids:
            tab = snaps[t]
            b = testing.random_batch(
                np.random.default_rng(500 + t), tab, 64
            )
            fused = fn(
                al.arena, jax.device_put(b.pack_wire()),
                jax.device_put(np.full(len(b), t, np.int32)),
            )
            res16, _ = jaxpath.split_wire_outputs(np.asarray(fused), len(b))
            got, _ = jaxpath.host_finalize_wire(res16, np.asarray(b.kind))
            want = oracle_mod.classify(tab, b).results
            if not np.array_equal(got, want):
                raise RuntimeError(
                    f"splice ladder k={k} oracle mismatch tenant {t}: "
                    f"{int((got != want).sum())}/{len(b)} verdicts"
                )
        log(f"splice ladder k={k} oracle bit-identity OK "
            f"({len(sample_ids)} sampled tenants)")

        if k != 16:
            del al
            continue

        # -- walk-latency tax vs a flat arena (gate rung only) --------------
        n_lat = min(64, n_t)
        flat_spec = jaxpath.arena_spec_for(
            "ctrie", (base,), pages=n_lat + 2, max_tenants=n_lat + 2,
            headroom=1.5,
        )
        flat = jaxpath.ArenaAllocator(flat_spec)
        parts, tags, wants = [], [], []
        for t in range(n_lat):
            flat.load_tenant(t, snaps64[t])
            b = testing.random_batch(
                np.random.default_rng(900 + t), snaps64[t], 16
            )
            parts.append(b)
            tags.append(np.full(len(b), t, np.int32))
            wants.append(oracle_mod.classify(snaps64[t], b).results)
        batch = packets_mod.concat(parts)
        tenant = np.concatenate(tags)
        want = np.concatenate(wants)
        B = len(batch)
        wire = jax.device_put(batch.pack_wire())
        tenant_dev = jax.device_put(tenant)
        fn_flat = jaxpath.jitted_classify_arena_wire_fused(
            "ctrie", flat_spec.pages, flat_spec.d_max
        )
        kinds = np.asarray(batch.kind)
        for name, f, arena in (
            ("spliced", fn, al.arena), ("flat", fn_flat, flat.arena)
        ):
            res16, _ = jaxpath.split_wire_outputs(
                np.asarray(f(arena, wire, tenant_dev)), B
            )
            got, _ = jaxpath.host_finalize_wire(res16, kinds)
            if not np.array_equal(got, want):
                raise RuntimeError(
                    f"splice walk-tax oracle mismatch on the {name} side"
                )

        def spliced_once():
            t0 = time.perf_counter()
            jax.block_until_ready(fn(al.arena, wire, tenant_dev))
            return time.perf_counter() - t0

        def flat_once():
            t0 = time.perf_counter()
            jax.block_until_ready(fn_flat(flat.arena, wire, tenant_dev))
            return time.perf_counter() - t0

        sp_s, fl_s = float("inf"), float("inf")
        spliced_once()
        flat_once()  # warm both off the clock
        for _ in range(16 if on_tpu else 8):  # interleaved min-vs-min
            sp_s = min(sp_s, spliced_once())
            fl_s = min(fl_s, flat_once())
        tax = sp_s / max(fl_s, 1e-9)
        log(f"splice walk tax @{n_lat} tenants x {B} packets: "
            f"{sp_s*1e6:.0f} us spliced vs {fl_s*1e6:.0f} us flat "
            f"({tax:.2f}x)")
        emit(f"splice-indirect walk @{n_lat} tenants", sp_s * 1e6, "us",
             vs_baseline=0.0)
        emit(f"flat-slab walk @{n_lat} tenants", fl_s * 1e6, "us",
             vs_baseline=0.0)
        out["splice_walk_tax"] = float(tax)
        del flat

        # -- zero-recompile pin: warm drift + classify compiles nothing -----
        scatter0 = jaxpath._scatter_rows_jit()._cache_size()
        fn0 = fn._cache_size()
        edits = {}
        for j in range(k):
            edits[keys[(cur + j) % n_keys]] = testing.random_rules(
                erng, width
            )
        upd.apply(edits, [])
        assert al.load_tenant(n_t, upd.snapshot()) in (
            "share", "assign"
        )
        jax.block_until_ready(fn(al.arena, wire, tenant_dev))
        if fn._cache_size() != fn0:
            raise RuntimeError(
                "splice ladder: classify executable recompiled on the "
                "warm drift lifecycle"
            )
        grew = jaxpath._scatter_rows_jit()._cache_size() - scatter0
        if grew:
            raise RuntimeError(
                f"splice ladder: {grew} scatter executable(s) compiled "
                "on the warm drift lifecycle"
            )
        log("splice ladder zero-recompile pin OK (k-edit drift load + "
            "classify on the warm arena)")
        out["splice_zero_recompile"] = 1.0
        del al
    return out


def splice_bench_main() -> int:
    """``make splice-bench``: the structural-compression ladder
    standalone (CPU smoke off TPU) with the ISSUE-17 regression gates —
    the k=16 rung's bytes/tenant reduction must clear
    INFW_SPLICE_BYTES_RATIO_MIN (default 10x) and the splice-indirect
    walk must stay under INFW_SPLICE_WALK_TAX_MAX (default 2x) of the
    flat walk.  The arena-splice statecheck config runs FIRST and gates
    record publication, mirroring the tenant-bench discipline."""
    ratio_min = float(os.environ.get("INFW_SPLICE_BYTES_RATIO_MIN", "10.0"))
    tax_max = float(os.environ.get("INFW_SPLICE_WALK_TAX_MAX", "2.0"))
    from infw.analysis import statecheck

    for cfg in ("arena-splice",):
        rep = statecheck.run_config(cfg, seed=0, n_ops=6,
                                    shrink_on_failure=False)
        if not rep["ok"]:
            log(f"splice-bench FAIL: statecheck {cfg} not green before "
                f"record publication: {rep['failure']}")
            return 1
        log(f"splice-bench: statecheck {cfg} green "
            f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2025)
    rec = bench_splice(rng, on_tpu)
    emit_compact_record()
    rc = 0
    if not rec.get("splice_bytes_ratio_k16", 0.0) >= ratio_min:
        log(f"splice-bench FAIL: bytes/tenant reduction "
            f"{rec.get('splice_bytes_ratio_k16', 0):.1f}x @k=16 < "
            f"gate {ratio_min}x")
        rc = 1
    if not rec.get("splice_walk_tax", float("inf")) < tax_max:
        log(f"splice-bench FAIL: walk tax "
            f"{rec.get('splice_walk_tax', float('inf')):.2f}x >= "
            f"gate {tax_max}x")
        rc = 1
    if rc == 0:
        log("splice-bench OK: " + ", ".join(
            f"{k}={v:.2f}" for k, v in sorted(rec.items())
        ))
    return rc


def tenant_bench_main() -> int:
    """``make tenant-bench``: the multi-tenant arena tier standalone
    (CPU smoke off TPU) with the regression gates — the pre-staged
    hot-swap (page-table flip) must beat the full re-upload by
    INFW_SWAP_SPEEDUP_MIN (default 10x, the ISSUE-10 acceptance).  The
    statecheck arena equivalence configs run FIRST and gate record
    publication, mirroring the churn-bench discipline."""
    speedup_min = float(os.environ.get("INFW_SWAP_SPEEDUP_MIN", "10.0"))
    cow_ratio_min = float(os.environ.get("INFW_COW_BYTES_RATIO_MIN", "20.0"))
    from infw.analysis import statecheck

    for cfg in ("arena", "arena-ctrie", "arena-cow"):
        rep = statecheck.run_config(cfg, seed=0, n_ops=6,
                                    shrink_on_failure=False)
        if not rep["ok"]:
            log(f"tenant-bench FAIL: statecheck {cfg} not green before "
                f"record publication: {rep['failure']}")
            return 1
        log(f"tenant-bench: statecheck {cfg} green "
            f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_tenant(rng, on_tpu)
    emit_compact_record()
    rc = 0
    if not rec.get("swap_speedup", 0.0) >= speedup_min:
        log(f"tenant-bench FAIL: swap speedup "
            f"{rec.get('swap_speedup', 0):.1f}x < gate {speedup_min}x")
        rc = 1
    if not rec.get("cow_bytes_ratio_100", 0.0) >= cow_ratio_min:
        log(f"tenant-bench FAIL: CoW bytes/tenant reduction "
            f"{rec.get('cow_bytes_ratio_100', 0):.1f}x @100 distinct < "
            f"gate {cow_ratio_min}x")
        rc = 1
    if rc == 0:
        log("tenant-bench OK: " + ", ".join(
            f"{k}={v:.2f}" for k, v in sorted(rec.items())
        ))
    return rc


def bench_flow(rng, on_tpu):
    """Stateful flow tier (``make flow-bench``, folded into
    bench-checked):

    - **hit-rate ladder**: classify throughput at 0/50/90/99%
      established-flow traffic (testing.flow_trace_batch, chunk-aware)
      through the flow-enabled classifier vs the stateless baseline on
      the SAME tables — interleaved min-vs-min, each measured flow pass
      from a cold table (FlowTier.reset) so the pass itself carries the
      rung's real insert + hit mix; measured hit rate reported beside
      each nominal rung (the TCP SYN -> EST handshake gate costs one
      extra miss per TCP flow — what counts as a hit is a serve-eligible
      established entry, see benchruns/README.md);
    - **eviction-storm line**: the 90% trace against a flow table ~8x
      smaller than the flow population — constant LRU displacement;
    - **oracle gate**: every rung's verdicts checked bit-identical
      against the stateless path before any timing line;
    - **zero-recompile pin**: the probe/insert executable caches must
      not grow across the measured passes (warm lifecycle contract).

    Returns the record dict for the flow-bench gate
    (INFW_FLOW_SPEEDUP_MIN at the 90% point)."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig

    out = {}
    # a v6-heavy wide-rule table: the deep-walk regime the flow tier
    # targets (stateless cost ~ trie depth x rule width; the probe is
    # table-size-independent).  Shallow/cheap tables are the honest
    # floor — the 0% rung reports the tier's overhead there.
    n_entries = 200_000 if on_tpu else 50_000
    n = 262_144 if on_tpu else 65_536
    chunk = 4096
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, v6_fraction=0.8,
        ifindexes=(2, 3),
    )
    # sized to the worst-case flow population of the ladder (the 0%%
    # rung is all-fresh): capacity pressure is the STORM line's job
    fcfg = FlowConfig.make(entries=1 << 17 if on_tpu else 1 << 16)
    clf = TpuClassifier(flow_table=fcfg)
    base = TpuClassifier()
    clf.load_tables(tables)
    base.load_tables(tables)
    clf.warm_flow_ladder([chunk])

    def run_pass(c, batch, check_against=None):
        n_div = 0
        outs = []
        for lo in range(0, len(batch), chunk):
            outs.append(c.classify(batch.slice(lo, lo + chunk),
                                   apply_stats=False))
        if check_against is not None:
            for o, want in zip(outs, check_against):
                n_div += int(np.sum(o.results != want.results))
        return outs, n_div

    reps = 5 if on_tpu else 3
    for ef in (0.0, 0.5, 0.9, 0.99):
        batch, meta = testing.flow_trace_batch(
            np.random.default_rng(7700 + int(ef * 100)), tables, n, ef,
            chunk_packets=chunk,
        )
        # oracle bit-identity gate BEFORE any timing line: a full flow
        # pass (cold -> warm, hits engaged) vs the stateless path
        clf.flow.reset()
        want, _ = run_pass(base, batch)
        _, n_div = run_pass(clf, batch, check_against=want)
        if n_div:
            raise RuntimeError(
                f"flow-bench oracle mismatch at ef={ef}: {n_div}/{n} "
                "verdicts diverge from the stateless path"
            )
        # recompile pin: the measured passes below must be compile-free
        probe_fn = jaxpath.jitted_flow_probe(fcfg.entries, fcfg.ways)
        insert_fn = jaxpath.jitted_flow_insert(fcfg.entries, fcfg.ways)
        cache0 = probe_fn._cache_size() + insert_fn._cache_size()

        def flow_pass():
            clf.flow.reset()
            s0 = clf.flow.stats.values()
            t0 = time.perf_counter()
            run_pass(clf, batch)
            dt = time.perf_counter() - t0
            s1 = clf.flow.stats.values()
            return dt, (s1["hits"] - s0["hits"]) / n

        def base_pass():
            t0 = time.perf_counter()
            run_pass(base, batch)
            return time.perf_counter() - t0

        flow_s, base_s, hit_rate = float("inf"), float("inf"), 0.0
        flow_pass()  # warm off the clock
        base_pass()
        for _ in range(reps):  # interleaved min-vs-min
            dt, hr = flow_pass()
            if dt < flow_s:
                flow_s, hit_rate = dt, hr
            base_s = min(base_s, base_pass())
        grew = (probe_fn._cache_size() + insert_fn._cache_size()) - cache0
        if grew:
            raise RuntimeError(
                f"flow-bench recompile on the warm lifecycle at ef={ef}: "
                f"probe/insert cache grew by {grew}"
            )
        speedup = base_s / max(flow_s, 1e-9)
        pct = int(ef * 100)
        log(f"flow ladder ef={pct}%: {n/flow_s/1e6:.2f} M pkts/s flow "
            f"(measured hit rate {hit_rate:.3f}, {meta['n_flows']} flows) "
            f"vs {n/base_s/1e6:.2f} M pkts/s stateless "
            f"({speedup:.2f}x)")
        emit(f"flow-tier classify @{pct}% established", n / flow_s,
             "packets/s", vs_baseline=0.0)
        emit(f"stateless classify @{pct}% established baseline",
             n / base_s, "packets/s", vs_baseline=0.0)
        emit(f"flow-tier speedup @{pct}% established", speedup, "x",
             vs_baseline=0.0)
        out[f"speedup_{pct}"] = float(speedup)
        out[f"hit_rate_{pct}"] = float(hit_rate)
    clf.close()

    # -- eviction storm: flow table ~8x smaller than the population ---------
    batch, meta = testing.flow_trace_batch(
        np.random.default_rng(7790), tables, n, 0.9, chunk_packets=chunk
    )
    small = FlowConfig.make(entries=max(meta["n_flows"] // 8, 64))
    sclf = TpuClassifier(flow_table=small)
    sclf.load_tables(tables)
    sclf.warm_flow_ladder([chunk])
    want, _ = run_pass(base, batch)
    _, n_div = run_pass(sclf, batch, check_against=want)
    if n_div:
        raise RuntimeError(
            f"flow-bench oracle mismatch under eviction storm: {n_div}"
        )
    sclf.flow.reset()
    t0 = time.perf_counter()
    run_pass(sclf, batch)
    storm_s = time.perf_counter() - t0
    v = sclf.flow.stats.values()
    log(f"flow eviction storm ({small.capacity} slots, "
        f"{meta['n_flows']} flows): {n/storm_s/1e6:.2f} M pkts/s, "
        f"{v['evictions']} evictions, hit rate {v['hits']/(2*n):.3f}")
    emit("flow-tier classify under eviction storm", n / storm_s,
         "packets/s", vs_baseline=0.0)
    emit("flow eviction-storm displacements", float(v["evictions"]),
         "evictions", vs_baseline=0.0)
    out["storm_evictions"] = float(v["evictions"])
    sclf.close()
    base.close()
    return out


def flow_bench_main() -> int:
    """``make flow-bench``: the stateful flow tier standalone (CPU smoke
    off TPU) with the regression gate — flow-tier classify at the 90%
    established-flow point must beat the stateless baseline by
    INFW_FLOW_SPEEDUP_MIN (default 1.15x; the verdict-bit-identity
    oracle gate and the zero-recompile pin run inside the tier).  The
    statecheck flow equivalence configs run FIRST and gate record
    publication, mirroring the churn/tenant-bench discipline."""
    speedup_min = float(os.environ.get("INFW_FLOW_SPEEDUP_MIN", "1.15"))
    from infw.analysis import statecheck

    for cfg in ("flow", "flow-ctrie"):
        rep = statecheck.run_config(cfg, seed=0, n_ops=6,
                                    shrink_on_failure=False)
        if not rep["ok"]:
            log(f"flow-bench FAIL: statecheck {cfg} not green before "
                f"record publication: {rep['failure']}")
            return 1
        log(f"flow-bench: statecheck {cfg} green "
            f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_flow(rng, on_tpu)
    emit_compact_record()
    rc = 0
    if not rec.get("speedup_90", 0.0) >= speedup_min:
        log(f"flow-bench FAIL: 90%-point speedup "
            f"{rec.get('speedup_90', 0):.2f}x < gate {speedup_min}x")
        rc = 1
    if rc == 0:
        log("flow-bench OK: " + ", ".join(
            f"{k}={v:.3f}" for k, v in sorted(rec.items())
        ))
    return rc


# --- resident serving loop: donated buffers, one fused program -------------


def bench_resident(rng, on_tpu):
    """ISSUE-12 resident tier (``make resident-bench``, folded into
    bench-checked): per-admission p50 latency of the ONE-fused-program
    donated-buffer serving loop vs the probe-then-classify
    multi-dispatch plan it replaces (prepare_packed/classify_prepared
    with the flow tier), at the batch-32 anomaly rung and batch 128.

    Methodology (benchruns/README):
    - RING-RECORD discipline: chunks are pre-packed wire records (the
      producer's job — tools/loadgen.py --ring packs into the mapped
      slot), so the measured loop is dispatch + materialize only, the
      dataplane-attributable path;
    - interleaved min-vs-min: alternating passes over the SAME 90%%-
      established trace, each pass from a cold flow table (reset), so
      ambient load cannot skew the ratio and each pass carries the
      rung's real insert + hit mix;
    - dataplane-attributable: reported p50s subtract the in-record link
      floor (noop round-trip) — a dispatch cannot beat the link;
    - ORACLE GATE before any timing line: resident verdicts + stats
      bit-identical to the CPU oracle AND to the multi-dispatch path on
      the same chunks;
    - ZERO-ALLOC + ZERO-RECOMPILE gate: a warmed 1000-dispatch steady-
      state run must leave the resident pool's allocation counter and
      the fused executable cache exactly where the prewarm left them
      (ResidentPool.steady_allocs() == 0, _cache_size flat).

    Returns the record dict for the resident-bench gate
    (INFW_RESIDENT_SPEEDUP_MIN at batch 32)."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig
    from infw.scheduler import prewarm_ladder

    out = {}
    floor = _slo_floor()
    log(f"resident: link sync floor {floor*1e3:.3f} ms")
    n_entries = 100_000 if on_tpu else 20_000
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, v6_fraction=0.5,
        ifindexes=(2, 3),
    )
    # a production-scale connection table (the bench_flow on-TPU size):
    # the multi-dispatch plan's undonated probe/insert launches copy
    # O(table) column bytes per admission, the donated loop rewrites
    # them in place — the gap this tier exists to measure
    fcfg = FlowConfig.make(entries=1 << 17)
    res = TpuClassifier(force_path="trie", flow_table=fcfg, resident=True)
    multi = TpuClassifier(force_path="trie",
                          flow_table=FlowConfig.make(entries=1 << 17))
    res.load_tables(tables)
    multi.load_tables(tables)
    t0 = time.perf_counter()
    ladder = (32, 64, 128)
    prewarm_ladder(res, ladder)
    prewarm_ladder(multi, ladder)
    log(f"resident: ladder prewarm in {time.perf_counter()-t0:.1f}s; "
        f"pool after warm: {res.resident_counters()}")

    reps = 5 if on_tpu else 3
    for bs in (32, 128):
        batch, meta = testing.flow_trace_batch(
            np.random.default_rng(8800 + bs), tables, bs * 100, 0.9,
            chunk_packets=bs,
        )
        tflags = np.asarray(batch.tcp_flags, np.int32)
        chunks = []
        for lo in range(0, len(batch), bs):
            sub = np.arange(lo, lo + bs, dtype=np.int64)
            w, v4 = batch.pack_wire_subset(sub)
            chunks.append((w, v4, np.ascontiguousarray(tflags[sub])))

        # oracle + multi-dispatch bit-identity gate BEFORE any timing
        # line: one full cold->warm pass on each path, every chunk's
        # verdicts AND statistics compared
        ref = oracle.classify(tables, batch)
        res.flow.reset()
        multi.flow.reset()
        n_div = 0
        off = 0
        for w, v4, tf in chunks:
            o = res.classify_prepared(
                res.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
            ).result()
            om = multi.classify_prepared(
                multi.prepare_packed(w, v4, tcp_flags=tf),
                apply_stats=False,
            ).result()
            want = ref.results[off : off + len(w)]
            n_div += int((o.results != want).sum())
            n_div += int((o.results != om.results).sum())
            n_div += int((o.stats_delta != om.stats_delta).sum())
            off += len(w)
        if n_div:
            raise RuntimeError(
                f"resident-bench oracle mismatch @batch={bs}: {n_div} "
                "divergences vs CPU oracle / multi-dispatch path"
            )

        def run_pass(clf):
            clf.flow.reset()
            lats = []
            for w, v4, tf in chunks:
                t0 = time.perf_counter()
                clf.classify_prepared(
                    clf.prepare_packed(w, v4, tcp_flags=tf),
                    apply_stats=False,
                ).result()
                lats.append(time.perf_counter() - t0)
            return np.asarray(lats[5:])

        res.mark_resident_warm()
        best = {"multi": 1e9, "res": 1e9}
        for _ in range(reps):  # interleaved min-vs-min
            best["multi"] = min(best["multi"],
                                float(np.percentile(run_pass(multi), 50)))
            best["res"] = min(best["res"],
                              float(np.percentile(run_pass(res), 50)))
        above = {k: max(v - floor, 0.0) for k, v in best.items()}
        speedup = above["multi"] / max(above["res"], 1e-9)
        log(f"resident @batch={bs}: fused {best['res']*1e3:.3f} ms "
            f"({above['res']*1e3:.3f} above floor) vs multi-dispatch "
            f"{best['multi']*1e3:.3f} ms ({above['multi']*1e3:.3f}) "
            f"-> {speedup:.2f}x; measured hit rate ~0.9 nominal "
            f"({meta['n_flows']} flows)")
        emit(
            f"resident fused-serving p50 above link floor @batch={bs} "
            "(one device program per admission, donated buffers)",
            above["res"] * 1e3, "ms", vs_baseline=0.0,
        )
        emit(
            f"multi-dispatch flow-path p50 above link floor @batch={bs} "
            "(probe-then-classify plan, A/B same record)",
            above["multi"] * 1e3, "ms", vs_baseline=0.0,
        )
        emit(f"resident serving speedup @batch={bs}", speedup, "x",
             vs_baseline=0.0)
        out[f"speedup_{bs}"] = float(speedup)
        out[f"res_p50_ms_{bs}"] = float(above["res"] * 1e3)
        out[f"multi_p50_ms_{bs}"] = float(above["multi"] * 1e3)

    # -- zero-alloc / zero-recompile steady state ---------------------------
    # 1000 warmed dispatches at the batch-32 rung: the pool allocation
    # counter and the fused executable cache must not move (what
    # "zero-alloc steady state" MEANS — see benchruns/README)
    bs = 32
    batch, _meta = testing.flow_trace_batch(
        np.random.default_rng(8899), tables, bs * 50, 0.9,
        chunk_packets=bs,
    )
    tflags = np.asarray(batch.tcp_flags, np.int32)
    chunks = []
    for lo in range(0, len(batch), bs):
        sub = np.arange(lo, lo + bs, dtype=np.int64)
        w, v4 = batch.pack_wire_subset(sub)
        chunks.append((w, v4, np.ascontiguousarray(tflags[sub])))
    res.mark_resident_warm()
    fn = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False
    )
    fn4 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", True, None, 0, False
    )
    cache0 = fn._cache_size() + fn4._cache_size()
    n_disp = 0
    while n_disp < 1000:
        for w, v4, tf in chunks:
            res.classify_prepared(
                res.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
            ).result()
            n_disp += 1
            if n_disp >= 1000:
                break
    grew = (fn._cache_size() + fn4._cache_size()) - cache0
    allocs = res.resident.steady_allocs()
    if grew or allocs:
        raise RuntimeError(
            f"resident steady state not zero-cost: {grew} recompile(s), "
            f"{allocs} pool allocation(s) across {n_disp} warmed "
            "dispatches"
        )
    log(f"resident steady state: {n_disp} dispatches, 0 recompiles, "
        f"0 pool allocations (counters: {res.resident_counters()})")
    emit("resident steady-state pool allocations per 1000 dispatches",
         float(allocs), "allocations", vs_baseline=0.0)
    out["steady_allocs"] = float(allocs)
    out["steady_recompiles"] = float(grew)
    res.close()
    multi.close()
    return out


def resident_bench_main() -> int:
    """``make resident-bench``: the resident serving tier standalone
    (CPU smoke off TPU) with the regression gate — the fused
    donated-buffer loop must beat the multi-dispatch flow plan at
    batch 32 by INFW_RESIDENT_SPEEDUP_MIN (default 3x, the ISSUE-12
    acceptance), with the oracle/multi bit-identity and the
    zero-alloc/zero-recompile steady-state gates enforced inside the
    tier.  The statecheck resident config runs FIRST and gates record
    publication, mirroring the flow/churn/tenant-bench discipline."""
    speedup_min = float(os.environ.get("INFW_RESIDENT_SPEEDUP_MIN", "3"))
    from infw.analysis import statecheck

    rep = statecheck.run_config("resident", seed=0, n_ops=6,
                                shrink_on_failure=False)
    if not rep["ok"]:
        log(f"resident-bench FAIL: statecheck resident not green before "
            f"record publication: {rep['failure']}")
        return 1
    log(f"resident-bench: statecheck resident green "
        f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_resident(rng, on_tpu)
    emit_compact_record()
    if not rec.get("speedup_32", 0.0) >= speedup_min:
        log(f"resident-bench FAIL: batch-32 speedup "
            f"{rec.get('speedup_32', 0):.2f}x < gate {speedup_min}x")
        return 1
    log("resident-bench OK: " + ", ".join(
        f"{k}={v:.3f}" for k, v in sorted(rec.items())
    ))
    return 0


# --- pipelined admissions: superbatch + two-slot overlap (ISSUE-16) --------


def bench_pipeline(rng, on_tpu):
    """ISSUE-16 pipeline tier (``make pipeline-bench``, folded into
    bench-checked): packets/s of the pipelined resident serving loop —
    the K=4 device-side epoch program (jitted_resident_superbatch: one
    dispatch chews four stacked admissions with the flow/epoch/sketch
    state chained through the loop carry) plus the two-slot overlap
    (the next superbatch is dispatched before the previous one's rows
    are materialized) — against the single-dispatch resident loop it
    pipelines, at batch 32 and batch 128.

    Methodology (benchruns/README):
    - interleaved min-vs-min over the SAME 90%%-established trace, each
      pass from a cold flow table;
    - dataplane-attributable: each pass's wall subtracts the in-record
      link floor (noop round-trip) once per DEVICE DISPATCH before the
      packets/s division — the serial pass pays the floor n_chunks
      times, the superbatch pass n_chunks/K times, so the subtraction
      is conservative for the reported speedup;
    - ORACLE GATE before any timing line: superbatch verdicts + stats
      bit-identical to K sequential fused dispatches AND the CPU
      oracle, with the flow columns and the sketch tensors compared
      after the full pass (telemetry plane enabled on the gate pair);
    - ZERO-ALLOC + ZERO-RECOMPILE gate across BOTH pipeline slots: a
      warmed steady-state run cycling slot parity (3 singles + one K=4
      superbatch per cycle — an odd 7-admission stride, so superbatches
      start from both slots) must leave the pool allocation counter and
      both executable caches flat;
    - DEVICE-BUSY FRACTION (achieved overlap): the serial pass's total
      above-floor compute / the pipelined wall — >1 means the epoch
      loop retired the same admissions in less device time than the
      single-dispatch baseline spent on them;
    - MESH LEG (ungated reference): DeviceStripe packets/s at 1/2/4/8
      devices, admissions striped round-robin over per-device ingest
      rings, with the ring occupancy/backpressure gauges surfaced in
      the record so overlap regressions are diagnosable.

    Returns the record dict for the pipeline-bench gate
    (INFW_PIPELINE_OVERLAP_MIN on the batch-32/128 throughput ratios)."""
    import tempfile

    from infw.backend.mesh import DeviceStripe
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig
    from infw.kernels.sketch import SketchSpec
    from infw.ring import IngestRing
    from infw.scheduler import prewarm_ladder

    K = 4
    out = {}
    floor = _slo_floor()
    log(f"pipeline: link sync floor {floor*1e3:.3f} ms, superbatch K={K}")
    n_entries = 100_000 if on_tpu else 20_000
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, v6_fraction=0.5,
        ifindexes=(2, 3),
    )
    fcfg = FlowConfig.make(entries=1 << 14)

    def make_clf(spec=None, device=None):
        kw = {"telemetry": spec} if spec is not None else {}
        c = TpuClassifier(
            force_path="trie", flow_table=FlowConfig.make(entries=1 << 14),
            resident=True, device=device, **kw,
        )
        c.load_tables(tables)
        return c

    def make_chunks(bs, n_chunks, seed):
        batch, meta = testing.flow_trace_batch(
            np.random.default_rng(seed), tables, bs * n_chunks, 0.9,
            chunk_packets=bs,
        )
        wire = batch.pack_wire()
        tflags = np.asarray(batch.tcp_flags, np.int32)
        chunks = [
            (np.ascontiguousarray(wire[lo:lo + bs]),
             np.ascontiguousarray(tflags[lo:lo + bs]))
            for lo in range(0, len(batch), bs)
        ]
        return batch, meta, chunks

    def super_plan(clf, chunks, g):
        stack = np.stack([chunks[g + j][0] for j in range(K)])
        fstack = np.stack([chunks[g + j][1] for j in range(K)])
        plan = clf.prepare_packed_super(stack, False,
                                        tcp_flags_stack=fstack)
        if plan is None:
            raise RuntimeError("superbatch plan unexpectedly refused")
        return plan

    # -- superbatch bit-identity gate BEFORE any timing line ----------------
    # K sequential fused dispatches vs one K-stacked epoch program, the
    # telemetry plane riding both: per-row verdicts + stats vs each
    # other AND the CPU oracle, then the full flow columns and sketch
    # tensors compared after the pass
    bs_gate = 64
    batch, _m, chunks = make_chunks(bs_gate, 16, 9100)
    ref = oracle.classify(tables, batch)
    spec = SketchSpec.make()
    tel_seq = make_clf(spec)
    tel_sup = make_clf(spec)
    n_div = 0
    for g in range(0, len(chunks), K):
        rows = tel_sup.classify_prepared_super(
            super_plan(tel_sup, chunks, g), apply_stats=False
        )
        for j in range(K):
            w, tf = chunks[g + j]
            o_seq = tel_seq.classify_prepared(
                tel_seq.prepare_packed(w, False, tcp_flags=tf),
                apply_stats=False,
            ).result()
            o_sup = rows[j].result()
            want = ref.results[(g + j) * bs_gate:(g + j + 1) * bs_gate]
            n_div += int((o_sup.results != want).sum())
            n_div += int((o_sup.results != o_seq.results).sum())
            n_div += int((o_sup.stats_delta != o_seq.stats_delta).sum())
    if n_div:
        raise RuntimeError(
            f"pipeline-bench superbatch mismatch: {n_div} divergences "
            "vs K sequential fused dispatches / CPU oracle"
        )
    fc_sup = tel_sup.flow.flow_columns()
    fc_seq = tel_seq.flow.flow_columns()
    for name in fc_sup:
        if not np.array_equal(fc_sup[name], fc_seq[name]):
            raise RuntimeError(
                f"pipeline-bench flow-column mismatch: {name!r} diverged "
                "between superbatch and sequential dispatches"
            )
    cols_sup = tel_sup.telemetry.columns()
    cols_seq = tel_seq.telemetry.columns()
    for name in cols_sup:
        if not np.array_equal(cols_sup[name], cols_seq[name]):
            raise RuntimeError(
                f"pipeline-bench sketch mismatch: tensor {name!r} "
                "diverged between superbatch and sequential dispatches"
            )
    tel_seq.close()
    tel_sup.close()
    log(f"pipeline: superbatch bit-identity gate clean ({len(chunks)} "
        "chunks — verdicts, stats, flow columns, sketch tensors)")

    # -- pipelined vs single-dispatch A/B (interleaved min-vs-min) ----------
    ser = make_clf()
    pipe = make_clf()
    t0 = time.perf_counter()
    prewarm_ladder(ser, (32, 128))
    prewarm_ladder(pipe, (32, 128))
    log(f"pipeline: ladder prewarm in {time.perf_counter()-t0:.1f}s")

    def run_serial(clf, chunks):
        clf.flow.reset()
        t0 = time.perf_counter()
        for w, tf in chunks:
            clf.classify_prepared(
                clf.prepare_packed(w, False, tcp_flags=tf),
                apply_stats=False,
            ).result()
        return time.perf_counter() - t0

    def run_pipelined(clf, chunks):
        clf.flow.reset()
        t0 = time.perf_counter()
        pending = []
        for g in range(0, len(chunks), K):
            rows = clf.classify_prepared_super(
                super_plan(clf, chunks, g), apply_stats=False
            )
            # two-slot overlap: the PREVIOUS superbatch's rows
            # materialize only after this one is in flight
            for p in pending:
                p.result()
            pending = rows
        for p in pending:
            p.result()
        return time.perf_counter() - t0

    reps = 5 if on_tpu else 3
    ser_above_per_chunk_128 = 0.0
    for bs in (32, 128):
        n_chunks = 48
        batch, meta, chunks = make_chunks(bs, n_chunks, 8800 + bs)
        run_serial(ser, chunks)  # warm the timed shapes (untimed)
        run_pipelined(pipe, chunks)
        best = {"ser": 1e9, "pipe": 1e9}
        for _ in range(reps):  # interleaved min-vs-min
            best["ser"] = min(best["ser"], run_serial(ser, chunks))
            best["pipe"] = min(best["pipe"], run_pipelined(pipe, chunks))
        above_ser = max(best["ser"] - floor * n_chunks, 1e-9)
        above_pipe = max(best["pipe"] - floor * (n_chunks // K), 1e-9)
        pps_ser = len(batch) / above_ser
        pps_pipe = len(batch) / above_pipe
        speedup = pps_pipe / pps_ser
        busy = above_ser / best["pipe"]
        if bs == 128:
            ser_above_per_chunk_128 = above_ser / n_chunks
        log(f"pipeline @batch={bs}: pipelined {pps_pipe:,.0f} pkt/s vs "
            f"single-dispatch {pps_ser:,.0f} pkt/s -> {speedup:.2f}x; "
            f"device-busy fraction {busy:.2f} "
            f"({meta['n_flows']} flows)")
        emit(
            f"pipelined serving throughput above link floor @batch={bs} "
            f"(K={K} superbatch epoch loop, two-slot overlap)",
            pps_pipe, "packets/s", vs_baseline=0.0,
        )
        emit(
            f"single-dispatch serving throughput above link floor "
            f"@batch={bs} (A/B same record)",
            pps_ser, "packets/s", vs_baseline=0.0,
        )
        emit(f"pipeline overlap win @batch={bs}", speedup, "x",
             vs_baseline=0.0)
        emit(f"device-busy fraction @batch={bs} (baseline-relative)",
             busy, "fraction", vs_baseline=0.0)
        out[f"pipeline_speedup_{bs}"] = float(speedup)
        out[f"pps_pipelined_{bs}"] = float(pps_pipe)
        out[f"pps_single_{bs}"] = float(pps_ser)
        out[f"device_busy_{bs}"] = float(busy)

    # -- zero-alloc / zero-recompile steady state across BOTH slots ---------
    # cycles of 3 single dispatches + one K=4 superbatch: the 7-admission
    # stride is odd, so consecutive cycles land the superbatch (and the
    # singles) on alternating pipeline slots; pool allocations and both
    # executable caches must stay exactly flat
    bs = 32
    _b, _m, chunks = make_chunks(bs, 28, 8899)
    fn1 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False
    )
    fnK = jaxpath.jitted_resident_superbatch(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False
    )
    pipe.flow.reset()
    for g in range(0, len(chunks) - K + 1, 7):  # warm both shapes (untimed)
        for j in range(3):
            w, tf = chunks[g + j]
            pipe.classify_prepared(
                pipe.prepare_packed(w, False, tcp_flags=tf),
                apply_stats=False,
            ).result()
        for p in pipe.classify_prepared_super(
            super_plan(pipe, chunks, g + 3), apply_stats=False
        ):
            p.result()
    pipe.mark_resident_warm()
    cache0 = fn1._cache_size() + fnK._cache_size()
    n_disp = 0
    while n_disp < 400:
        for g in range(0, len(chunks) - K + 1, 7):
            for j in range(3):
                w, tf = chunks[g + j]
                pipe.classify_prepared(
                    pipe.prepare_packed(w, False, tcp_flags=tf),
                    apply_stats=False,
                ).result()
            for p in pipe.classify_prepared_super(
                super_plan(pipe, chunks, g + 3), apply_stats=False
            ):
                p.result()
            n_disp += 4
    grew = (fn1._cache_size() + fnK._cache_size()) - cache0
    allocs = pipe.resident.steady_allocs()
    if grew or allocs:
        raise RuntimeError(
            f"pipeline steady state not zero-cost: {grew} recompile(s), "
            f"{allocs} pool allocation(s) across {n_disp} warmed "
            "dispatches over both slots"
        )
    ctr = pipe.resident_counters()
    log(f"pipeline steady state: {n_disp} dispatches over both slots, "
        f"0 recompiles, 0 pool allocations "
        f"(slot0={ctr['resident_slot0_dispatches_total']} "
        f"slot1={ctr['resident_slot1_dispatches_total']} "
        f"super={ctr['resident_superbatch_dispatches_total']})")
    emit("pipeline steady-state pool allocations per 400 dispatches "
         "(both slots)", float(allocs), "allocations", vs_baseline=0.0)
    out["steady_allocs"] = float(allocs)
    out["steady_recompiles"] = float(grew)
    ser.close()
    pipe.close()

    # -- mesh leg: DeviceStripe packets/s at 1/2/4/8 devices ----------------
    # admissions striped round-robin over per-device ingest rings; the
    # ring occupancy/backpressure gauges ride the record (ungated
    # reference — CPU "devices" share cores, so smoke scaling is flat)
    ndev = len(jax.devices())
    bs, n_chunks = 128, 32
    batch, _m, chunks = make_chunks(bs, n_chunks, 9300)
    for width in (1, 2, 4, 8):
        if width > ndev:
            log(f"pipeline: stripe width {width} skipped "
                f"(only {ndev} devices)")
            continue
        with tempfile.TemporaryDirectory() as d:
            stripe = DeviceStripe(
                width=width, ring_dir=d, ring_slots=n_chunks + 8,
                ring_slot_packets=bs, force_path="trie",
                flow_table=FlowConfig.make(entries=1 << 14), resident=True,
            )
            stripe.load_tables(tables)
            prods = [
                IngestRing.attach(os.path.join(d, f"stripe{i}.ring"))
                for i in range(width)
            ]

            def fill():
                for i, (w, tf) in enumerate(chunks):
                    prods[i % width].push(w, v4_only=False, tcp_flags=tf)

            fill()  # warm (untimed)
            n = stripe.drain_rings_once()
            if n != len(batch):
                raise RuntimeError(
                    f"stripe width {width} drained {n} of {len(batch)}"
                )
            stripe.mark_resident_warm()
            best = 1e9
            for _ in range(reps):
                fill()
                t0 = time.perf_counter()
                stripe.drain_rings_once()
                best = min(best, time.perf_counter() - t0)
            pps = len(batch) / best
            busy = ser_above_per_chunk_128 * n_chunks / (best * width)
            cv = stripe.counter_values()
            blocked = sum(
                p.counter_values()["ring_blocked_us_total"] for p in prods
            )
            log(f"pipeline stripe width={width}: {pps:,.0f} pkt/s, "
                f"per-device busy fraction {busy:.2f}, ring depth hwm "
                f"{cv['ring_depth_hwm']}, producer blocked {blocked} us")
            emit(
                f"striped admission throughput @{width} device(s) "
                "(per-device ingest rings, round-robin)",
                pps, "packets/s", vs_baseline=0.0,
            )
            out[f"stripe_pps_{width}"] = float(pps)
            out[f"stripe_busy_{width}"] = float(busy)
            out["ring_depth_hwm"] = float(cv["ring_depth_hwm"])
            out["ring_blocked_us"] = float(blocked)
            for p in prods:
                p.close()
            stripe.close()
    return out


def pipeline_bench_main() -> int:
    """``make pipeline-bench``: the pipelined-admission tier standalone
    (CPU smoke off TPU) with the regression gate — the K=4 superbatch +
    two-slot overlap must beat the single-dispatch resident loop's
    packets/s at batch 32 AND batch 128 by INFW_PIPELINE_OVERLAP_MIN
    (default 1.3x, the ISSUE-16 acceptance), with the superbatch
    bit-identity and zero-alloc/zero-recompile both-slots gates
    enforced inside the tier.  The statecheck pipeline config runs
    FIRST and gates record publication (the resident-bench
    discipline)."""
    overlap_min = float(os.environ.get("INFW_PIPELINE_OVERLAP_MIN", "1.3"))
    from infw.analysis import statecheck

    rep = statecheck.run_config("pipeline", seed=0, n_ops=6,
                                shrink_on_failure=False)
    if not rep["ok"]:
        log(f"pipeline-bench FAIL: statecheck pipeline not green before "
            f"record publication: {rep['failure']}")
        return 1
    log(f"pipeline-bench: statecheck pipeline green "
        f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_pipeline(rng, on_tpu)
    emit_compact_record()
    worst = min(rec.get("pipeline_speedup_32", 0.0),
                rec.get("pipeline_speedup_128", 0.0))
    if not worst >= overlap_min:
        log(f"pipeline-bench FAIL: pipelined/single throughput ratio "
            f"{worst:.2f}x < gate {overlap_min}x")
        return 1
    log("pipeline-bench OK: " + ", ".join(
        f"{k}={v:.3f}" for k, v in sorted(rec.items())
    ))
    return 0


def bench_telemetry(rng, on_tpu):
    """ISSUE-13 telemetry tier (``make telemetry-bench``, folded into
    bench-checked): the device-resident telemetry plane measured three
    ways on seeded attack traces (testing.attack_trace_batch):

    - RETENTION (the churn-bench discipline): served classify
      throughput at a FIXED OFFERED LOAD — 70%% of the sketches-off
      capacity, calibrated in-record — with sketches on vs off on the
      resident serving loop, interleaved min-vs-min, gated at
      INFW_TELEMETRY_RETENTION_MIN (default 0.95).  Telemetry must fit
      inside the serving headroom at the operating point; a plane whose
      cost pushed the dataplane past capacity fails the gate.  The RAW
      full-speed dispatch A/B (resident fused, and the multi-dispatch
      path's extra follow-on launch) is reported beside it as ungated
      reference lines — on this 2-core CPU smoke the in-program
      scatters cost ~10-20%% of the fused step, a share that shrinks to
      noise on parallel device hardware but is priced honestly here;
    - ORACLE GATE before any timing line: verdicts with telemetry on
      bit-identical to the off path AND the CPU oracle, and the device
      sketch tensors bit-identical to the HostSketchModel on a tracked
      twin over the same chunks;
    - DETECTION LATENCY: drains run per chunk from the attack's first
      chunk; reported as chunks/packets until the drained summary
      surfaces the planted attacker (top-talker for synflood/portscan,
      deny-storm flag for denystorm);
    - LIVE DAEMON: an in-process --telemetry --trace daemon ingests the
      synflood trace; /metrics must serve the per-stage span histograms
      and the events log the per-tenant heavy-hitter summaries.

    Returns the record dict for the telemetry-bench gate."""
    import json as json_mod
    import urllib.request

    from infw.backend.tpu import TpuClassifier
    from infw.kernels.sketch import SketchSpec
    from infw.scheduler import prewarm_ladder

    out = {}
    n_entries = 100_000 if on_tpu else 20_000
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, v6_fraction=0.4,
        ifindexes=(2, 3),
    )
    spec = SketchSpec.make()  # the production default geometry
    bs = 256
    trace, meta = testing.attack_trace_batch(
        np.random.default_rng(1300), tables, bs * 80, mode="synflood",
        chunk_packets=bs,
    )
    tflags = np.asarray(trace.tcp_flags, np.int32)
    chunks = []
    for lo in range(0, len(trace), bs):
        sub = np.arange(lo, lo + bs, dtype=np.int64)
        w, v4 = trace.pack_wire_subset(sub)
        chunks.append((w, v4, np.ascontiguousarray(tflags[sub])))

    from infw.flow import FlowConfig

    fcfg = FlowConfig.make(entries=1 << 14)
    clf_on = TpuClassifier(force_path="trie", flow_table=fcfg,
                           resident=True, telemetry=spec)
    clf_off = TpuClassifier(force_path="trie",
                            flow_table=FlowConfig.make(entries=1 << 14),
                            resident=True)
    clf_con = TpuClassifier(force_path="trie", telemetry=spec)
    clf_coff = TpuClassifier(force_path="trie")
    for c in (clf_on, clf_off, clf_con, clf_coff):
        c.load_tables(tables)
        prewarm_ladder(c, (bs,))

    # -- oracle + model bit-identity gate BEFORE any timing line -----------
    ref = oracle.classify(tables, trace)
    clf_chk = TpuClassifier(force_path="trie", telemetry=spec,
                            telemetry_track_model=True)
    clf_chk.load_tables(tables)
    n_div = 0
    off = 0
    for w, v4, tf in chunks:
        o_on = clf_on.classify_prepared(
            clf_on.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
        ).result()
        o_off = clf_off.classify_prepared(
            clf_off.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
        ).result()
        clf_chk.classify_prepared(
            clf_chk.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
        ).result()
        want = ref.results[off : off + len(w)]
        n_div += int((o_on.results != want).sum())
        n_div += int((o_on.results != o_off.results).sum())
        off += len(w)
    cols = clf_chk.telemetry.columns()
    mcols = clf_chk.telemetry.model.columns()
    for name in cols:
        if not np.array_equal(cols[name], mcols[name]):
            raise RuntimeError(
                f"telemetry-bench sketch oracle mismatch: tensor "
                f"{name!r} diverged from the host model"
            )
    if n_div:
        raise RuntimeError(
            f"telemetry-bench verdict mismatch: {n_div} divergences "
            "(telemetry-on vs off vs CPU oracle)"
        )
    log(f"telemetry: oracle gate clean ({len(chunks)} chunks, sketch "
        "tensors bit-identical to the host model)")

    # -- retention A/B (interleaved min-vs-min) -----------------------------
    def run_pass(clf):
        if clf.flow is not None:
            clf.flow.reset()
        t0 = time.perf_counter()
        for w, v4, tf in chunks:
            clf.classify_prepared(
                clf.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
            ).result()
        return time.perf_counter() - t0

    clf_on.mark_resident_warm()
    clf_off.mark_resident_warm()
    reps = 5 if on_tpu else 3
    best = {"on": 1e9, "off": 1e9, "con": 1e9, "coff": 1e9}
    for _ in range(reps):
        best["off"] = min(best["off"], run_pass(clf_off))
        best["on"] = min(best["on"], run_pass(clf_on))
        best["coff"] = min(best["coff"], run_pass(clf_coff))
        best["con"] = min(best["con"], run_pass(clf_con))
    raw_ab = best["off"] / max(best["on"], 1e-12)
    raw_ab_classic = best["coff"] / max(best["con"], 1e-12)
    log(f"telemetry: RAW full-speed A/B — resident fused sketches-on "
        f"{best['on']*1e3:.1f} ms vs off {best['off']*1e3:.1f} ms over "
        f"{len(trace)} pkts ({raw_ab:.3f}); multi-dispatch follow-on "
        f"launch {raw_ab_classic:.3f} (both ungated reference lines)")
    emit("raw full-speed dispatch A/B with telemetry sketches on "
         "(resident fused serving loop, ungated reference)",
         raw_ab, "ratio", vs_baseline=0.0)
    emit("multi-dispatch telemetry A/B (one follow-on launch per "
         "admission, ungated reference)",
         raw_ab_classic, "ratio", vs_baseline=0.0)
    out["raw_ab"] = float(raw_ab)
    out["raw_ab_classic"] = float(raw_ab_classic)

    # the GATED line: served throughput at a fixed offered load (70% of
    # the sketches-off capacity) — telemetry must fit the headroom at
    # the operating point.  Open-loop pacing: each admission waits for
    # its ABSOLUTE scheduled time (never "dispatch then sleep"), so a
    # side that cannot keep up visibly overruns the schedule instead of
    # silently stretching the offered load.
    cap_off = len(trace) / best["off"]
    offered = 0.7 * cap_off
    sched = np.arange(len(chunks)) * (bs / offered)
    sched_end = len(trace) / offered

    def run_offered(clf):
        clf.flow.reset()
        t0 = time.perf_counter()
        for (w, v4, tf), s in zip(chunks, sched):
            now = time.perf_counter() - t0
            if now < s:
                time.sleep(s - now)
            clf.classify_prepared(
                clf.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
            ).result()
        return max(time.perf_counter() - t0, sched_end)

    best_o = {"on": 1e9, "off": 1e9}
    for _ in range(reps):
        best_o["off"] = min(best_o["off"], run_offered(clf_off))
        best_o["on"] = min(best_o["on"], run_offered(clf_on))
    ach_on = len(trace) / best_o["on"]
    ach_off = len(trace) / best_o["off"]
    retention = ach_on / max(ach_off, 1e-12)
    log(f"telemetry: served throughput at {offered/1e3:.1f} K pkt/s "
        f"offered (70% of sketches-off capacity {cap_off/1e3:.1f} K): "
        f"on {ach_on/1e3:.1f} K vs off {ach_off/1e3:.1f} K -> retention "
        f"{retention:.3f}")
    emit("classify throughput retention with telemetry sketches on "
         "(fixed offered load at 70% of sketches-off capacity, "
         "resident serving loop, synflood trace)",
         retention, "ratio", vs_baseline=0.0)
    out["retention"] = float(retention)

    # -- zero-recompile / zero-alloc steady state (telemetry ON) ------------
    # the resident-bench discipline, with the telemetry plane enabled:
    # a warmed run must leave the fused telemetry executable's cache and
    # the resident pool's allocation counter exactly where the prewarm
    # left them — telemetry must be compile-free and alloc-free on the
    # steady serving path (the decimated drain is the only exception,
    # and it reuses its buffers via the donated clear)
    clf_on.mark_resident_warm()
    fn_t = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False,
        sketch=spec,
    )
    fn_t4 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", True, None, 0, False,
        sketch=spec,
    )
    cache0 = fn_t._cache_size() + fn_t4._cache_size()
    n_disp = 0
    while n_disp < 300:
        for w, v4, tf in chunks:
            clf_on.classify_prepared(
                clf_on.prepare_packed(w, v4, tcp_flags=tf),
                apply_stats=False,
            ).result()
            n_disp += 1
            if n_disp >= 300:
                break
    grew = (fn_t._cache_size() + fn_t4._cache_size()) - cache0
    allocs = clf_on.resident.steady_allocs()
    if grew or allocs:
        raise RuntimeError(
            f"telemetry steady state not zero-cost: {grew} recompile(s), "
            f"{allocs} pool allocation(s) across {n_disp} warmed "
            "dispatches with sketches on"
        )
    log(f"telemetry steady state: {n_disp} fused dispatches with "
        "sketches on, 0 recompiles, 0 pool allocations")
    emit("telemetry-on steady-state recompiles + pool allocations per "
         "300 warmed dispatches", float(grew + allocs), "events",
         vs_baseline=0.0)
    out["steady"] = float(grew + allocs)

    # -- detection latency (per-chunk drains from the attack start) ---------
    atk_srcs = {
        ".".join(str(b) for b in int(s[0]).to_bytes(4, "big"))
        for s, _k in meta["attackers"]
    }
    for mode in ("synflood", "denystorm"):
        dtrace, dmeta = testing.attack_trace_batch(
            np.random.default_rng(1400), tables, bs * 40, mode=mode,
            chunk_packets=bs,
        )
        dflags = np.asarray(dtrace.tcp_flags, np.int32)
        det = TpuClassifier(force_path="trie", telemetry=spec)
        det.load_tables(tables)
        tier = det.telemetry
        tier.min_packets = 32
        # per-window flag thresholds sit below the trace's nominal
        # attack fraction (0.4): the flags fire on the attack windows
        # and stay off on the pre-onset ones
        tier.syn_flood_frac = 0.3
        tier.deny_storm_frac = 0.3
        start_chunk = dmeta["start"] // bs
        srcs = {
            ".".join(str(b) for b in int(s[0]).to_bytes(4, "big"))
            if k == 1 else "v6"
            for s, k in dmeta["attackers"]
        }
        detected_at = None
        for ci in range(0, len(dtrace) // bs):
            sub = np.arange(ci * bs, (ci + 1) * bs, dtype=np.int64)
            w, v4 = dtrace.pack_wire_subset(sub)
            det.classify_prepared(
                det.prepare_packed(
                    w, v4, tcp_flags=np.ascontiguousarray(dflags[sub])
                ),
                apply_stats=False,
            ).result()
            if ci < start_chunk:
                continue
            rec = tier.drain(force=True)[0]
            hit = any(h["src"] in srcs for h in rec.top)
            if mode == "synflood":
                hit = hit and any(t["syn_flood"] for t in rec.tenants)
            if mode == "denystorm":
                hit = hit and any(t["deny_storm"] for t in rec.tenants)
            if hit:
                detected_at = ci - start_chunk + 1
                break
        if detected_at is None:
            raise RuntimeError(
                f"telemetry-bench: {mode} attacker never surfaced in "
                "the drained summaries"
            )
        log(f"telemetry: {mode} detected after {detected_at} "
            f"post-onset admission(s) ({detected_at * bs} packets)")
        emit(f"attack detection latency ({mode}, drain-per-admission)",
             float(detected_at), "admissions", vs_baseline=0.0)
        out[f"detect_{mode}_admissions"] = float(detected_at)
        det.close()

    # -- live daemon leg: span histograms + heavy hitters from /metrics ----
    import tempfile

    from infw.daemon import Daemon, write_frames_file_v2
    from infw.interfaces import Interface, InterfaceRegistry
    from infw.obs.pcap import build_frames_bulk
    from infw.spec import (
        ACTION_DENY,
        IngressNodeFirewallNodeState,
        IngressNodeFirewallNodeStateSpec,
        IngressNodeFirewallProtoRule,
        IngressNodeFirewallProtocolRule,
        IngressNodeFirewallRules,
        IngressNodeProtocolConfig,
        ObjectMeta,
        PROTOCOL_TYPE_TCP,
    )

    with tempfile.TemporaryDirectory() as td:
        reg = InterfaceRegistry()
        reg.add(Interface(name="dummy0", index=10))
        d = Daemon(
            state_dir=os.path.join(td, "state"), node_name="bench",
            backend="tpu", registry=reg, metrics_port=0, health_port=0,
            file_poll_interval_s=0.02, telemetry=spec, telemetry_drain=512,
            trace=True, trace_slow_us=1.0,
        )
        d.start()
        ns = IngressNodeFirewallNodeState(
            metadata=ObjectMeta(name="bench",
                                namespace="ingress-node-firewall-system"),
            spec=IngressNodeFirewallNodeStateSpec(interface_ingress_rules={
                "dummy0": [IngressNodeFirewallRules(
                    source_cidrs=["0.0.0.0/0"],
                    rules=[IngressNodeFirewallProtocolRule(
                        order=1,
                        protocol_config=IngressNodeProtocolConfig(
                            protocol=PROTOCOL_TYPE_TCP,
                            tcp=IngressNodeFirewallProtoRule(ports=443),
                        ),
                        action=ACTION_DENY,
                    )],
                )],
            }),
        )
        p = os.path.join(d.nodestates_dir, "bench.json")
        with open(p + ".tmp", "w") as f:
            json_mod.dump(ns.to_dict(), f)
        os.replace(p + ".tmp", p)
        deadline = time.time() + 60
        while time.time() < deadline and d.syncer.classifier is None:
            time.sleep(0.05)
        if d.syncer.classifier is None:
            raise RuntimeError("telemetry-bench daemon never synced rules")
        fb = build_frames_bulk(
            trace.kind, np.asarray(trace.ip_words, np.uint32),
            trace.proto, trace.dst_port, trace.icmp_type, trace.icmp_code,
        )
        fb.ifindex = np.full(len(trace), 10, np.uint32)
        write_frames_file_v2(os.path.join(d.ingest_dir, "atk.frames"), fb)
        done = os.path.join(d.out_dir, "atk.frames.verdicts.json")
        deadline = time.time() + 120
        while time.time() < deadline and not os.path.exists(done):
            time.sleep(0.05)
        if not os.path.exists(done):
            raise RuntimeError("telemetry-bench daemon never drained "
                               "the attack trace")
        tier = d.syncer.classifier.telemetry
        tier.drain(force=True)
        d.events_logger.drain_once()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{d.actual_metrics_port}/metrics", timeout=5
        ).read().decode()
        with open(d.events_path) as f:
            ev = f.read()
        d.stop()
        if "ingressnodefirewall_node_span_us_bucket" not in body:
            raise RuntimeError("telemetry-bench: /metrics served no "
                               "span histograms from the live daemon")
        if "telemetry_updates_total" not in body:
            raise RuntimeError("telemetry-bench: /metrics served no "
                               "telemetry counters")
        top_lines = [ln for ln in ev.splitlines() if "top-talker" in ln]
        if not any(src in ln for ln in top_lines for src in atk_srcs):
            raise RuntimeError(
                "telemetry-bench: live daemon summaries never surfaced "
                f"the planted attacker(s) {sorted(atk_srcs)}; got "
                f"{top_lines[:4]}"
            )
        log(f"telemetry: live daemon served span histograms + "
            f"{len(top_lines)} heavy-hitter line(s); attacker surfaced")
        out["daemon_leg"] = 1.0
    emit("live-daemon telemetry leg (span histograms + heavy hitters)",
         1.0, "ok", vs_baseline=0.0)
    for c in (clf_on, clf_off, clf_con, clf_coff, clf_chk):
        c.close()
    return out


def telemetry_bench_main() -> int:
    """``make telemetry-bench``: the telemetry tier standalone (CPU
    smoke off TPU) with the regression gates — classify retention with
    sketches on must stay >= INFW_TELEMETRY_RETENTION_MIN (default
    0.95), every detection leg must surface its planted attacker, and
    the statecheck telemetry config runs FIRST and gates record
    publication (the flow/churn/tenant/resident-bench discipline)."""
    retention_min = float(
        os.environ.get("INFW_TELEMETRY_RETENTION_MIN", "0.95")
    )
    from infw.analysis import statecheck

    rep = statecheck.run_config("telemetry", seed=0, n_ops=8,
                                shrink_on_failure=False)
    if not rep["ok"]:
        log(f"telemetry-bench FAIL: statecheck telemetry not green "
            f"before record publication: {rep['failure']}")
        return 1
    log(f"telemetry-bench: statecheck telemetry green "
        f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_telemetry(rng, on_tpu)
    emit_compact_record()
    if not rec.get("retention", 0.0) >= retention_min:
        log(f"telemetry-bench FAIL: retention "
            f"{rec.get('retention', 0):.3f} < gate {retention_min}")
        return 1
    log("telemetry-bench OK: " + ", ".join(
        f"{k}={v:.3f}" for k, v in sorted(rec.items())
    ))
    return 0


def bench_mlscore(rng, on_tpu):
    """ISSUE-14 anomaly-scoring tier (``make mlscore-bench``, folded
    into bench-checked): the MXU inference plane measured four ways on
    seeded labeled attack traces (testing.attack_trace_batch):

    - ORACLE GATE before any timing line: shadow-mode verdicts with
      scoring on bit-identical to the off path AND the CPU oracle, and
      the device score tensors + per-lane scores bit-identical to the
      HostScoreModel across the dense, ctrie and resident serving
      paths;
    - DETECTION QUALITY: per-lane precision/recall of the device
      anomaly decisions against the generator's ground-truth attack
      mask (label discipline: features never read the labels —
      benchruns/README.md), gated at INFW_MLSCORE_PRECISION_MIN /
      INFW_MLSCORE_RECALL_MIN, plus detection latency (admissions from
      onset until a drained anomaly-verdict record surfaces the planted
      attacker);
    - RETENTION (the telemetry-bench discipline): served classify
      throughput at a FIXED OFFERED LOAD — 70%% of the scoring-off
      capacity, calibrated in-record — scoring on vs off on the
      resident serving loop, interleaved min-vs-min, gated at
      INFW_MLSCORE_RETENTION_MIN;
    - ZERO-COST STEADY STATE: a warmed run with scoring on must leave
      the fused executables' caches and the resident pool's allocation
      counter exactly where the prewarm left them;
    - ENFORCE LEG: with enforcement on, post-onset attacker lanes are
      denied (ruleId 0) while failsafe-port cells keep their rule
      verdicts bit-exactly (the failsaferules precedence contract).

    Returns the record dict for the mlscore-bench gate."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig
    from infw.kernels.mxu_score import (
        DENY as _DENY,
        HostScoreModel,
        ScoreSpec,
        default_model,
        failsafe_lane_mask_np,
        zero_tparams,
    )
    from infw.scheduler import prewarm_ladder

    out = {}
    n_entries = 100_000 if on_tpu else 20_000
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, v6_fraction=0.4,
        ifindexes=(2, 3),
    )
    spec = ScoreSpec.make()  # the production default geometry
    model = default_model(spec)
    bs = 256
    trace, meta = testing.attack_trace_batch(
        np.random.default_rng(1400), tables, bs * 60, mode="synflood",
        chunk_packets=bs,
    )
    tflags = np.asarray(trace.tcp_flags, np.int32)

    def chunked(tr, fl):
        cs = []
        for lo in range(0, len(tr), bs):
            sub = np.arange(lo, lo + bs, dtype=np.int64)
            w, v4 = tr.pack_wire_subset(sub)
            cs.append((w, v4, np.ascontiguousarray(fl[sub])))
        return cs

    chunks = chunked(trace, tflags)
    fcfg = FlowConfig.make(entries=1 << 14)
    clf_on = TpuClassifier(force_path="trie", flow_table=fcfg,
                           resident=True, mlscore=spec,
                           mlscore_model=model)
    clf_off = TpuClassifier(force_path="trie",
                            flow_table=FlowConfig.make(entries=1 << 14),
                            resident=True)
    for c in (clf_on, clf_off):
        c.load_tables(tables)
        prewarm_ladder(c, (bs,))

    # -- oracle + model bit-identity gate BEFORE any timing line ------------
    # the three-path sweep: dense (tiny table), ctrie, trie-resident —
    # shadow scores and state must match the HostScoreModel bit for bit
    # on every path, and verdicts must match the scoring-off path + the
    # CPU oracle
    small = testing.random_tables(np.random.default_rng(7), n_entries=48,
                                  width=8)
    strace, _smeta = testing.attack_trace_batch(
        np.random.default_rng(1401), small, bs * 6, mode="synflood",
        chunk_packets=bs,
    )
    sflags = np.asarray(strace.tcp_flags, np.int32)
    schunks = chunked(strace, sflags)
    sref = oracle.classify(small, strace)
    for label, kw in (
        ("dense", dict(force_path="dense")),
        ("ctrie", dict(force_path="ctrie")),
        ("resident", dict(force_path="trie",
                          flow_table=FlowConfig.make(entries=1 << 12),
                          resident=True)),
    ):
        chk = TpuClassifier(mlscore=spec, mlscore_model=model,
                            mlscore_track_model=True, **kw)
        chk.load_tables(small)
        chk.mlscore.set_keep_masks(len(schunks))
        twin = HostScoreModel(spec, model, zero_tparams(spec))
        n_div = 0
        off = 0
        twin_scores = []
        for w, v4, tf in schunks:
            o = chk.classify_prepared(
                chk.prepare_packed(w, v4, tcp_flags=tf),
                apply_stats=False,
            ).result()
            ts, _ta, _tr = twin.update(w, o.results, None, tf)
            twin_scores.append(ts)
            n_div += int(
                (o.results != sref.results[off : off + len(w)]).sum()
            )
            off += len(w)
        if n_div:
            raise RuntimeError(
                f"mlscore-bench verdict mismatch on the {label} path: "
                f"{n_div} divergences vs the CPU oracle (shadow mode "
                "must never touch verdicts)"
            )
        cols = chk.mlscore.columns()
        mcols = chk.mlscore.model.columns()
        for name in cols:
            if not np.array_equal(cols[name], mcols[name]):
                raise RuntimeError(
                    f"mlscore-bench score oracle mismatch ({label}): "
                    f"tensor {name!r} diverged from the host model"
                )
        got = [s for _e, _a, s in chk.mlscore.recent_masks()]
        want = [np.clip(s, -32768, 32767) for s in twin_scores]
        if not all(np.array_equal(g, w) for g, w in zip(got, want)):
            raise RuntimeError(
                f"mlscore-bench per-lane scores diverged from the "
                f"host model on the {label} path"
            )
        chk.close()
    log("mlscore: oracle gate clean (dense/ctrie/resident score + "
        "state + verdict bit-identity)")

    # -- detection quality on the labeled traces ----------------------------
    for mode in ("synflood", "portscan"):
        dtrace, dmeta = testing.attack_trace_batch(
            np.random.default_rng(1400), tables, bs * 60, mode=mode,
            chunk_packets=bs,
        )
        dflags = np.asarray(dtrace.tcp_flags, np.int32)
        truth = np.asarray(dmeta["attack_mask"], bool)
        det = TpuClassifier(force_path="trie",
                            flow_table=FlowConfig.make(entries=1 << 14),
                            resident=True, mlscore=spec,
                            mlscore_model=model)
        det.load_tables(tables)
        tier = det.mlscore
        tier.set_keep_masks(len(dtrace) // bs)
        srcs = {
            ".".join(str(b) for b in int(s[0]).to_bytes(4, "big"))
            if k == 1 else "v6"
            for s, k in dmeta["attackers"]
        }
        start_chunk = dmeta["start"] // bs
        detected_at = None
        pred = np.zeros(len(dtrace), bool)
        for ci, (w, v4, tf) in enumerate(chunked(dtrace, dflags)):
            det.classify_prepared(
                det.prepare_packed(w, v4, tcp_flags=tf),
                apply_stats=False,
            ).result()
            _e, anom, _s = tier.recent_masks()[-1]
            pred[ci * bs : ci * bs + len(w)] = anom
            if ci < start_chunk or detected_at is not None:
                continue
            rec = tier.drain(force=True)[0]
            if any(h["src"] in srcs for h in rec.top):
                detected_at = ci - start_chunk + 1
        tp = int((pred & truth).sum())
        fp = int((pred & ~truth).sum())
        fn = int((~pred & truth).sum())
        precision = tp / max(tp + fp, 1)
        recall = tp / max(tp + fn, 1)
        if detected_at is None:
            raise RuntimeError(
                f"mlscore-bench: {mode} attacker never surfaced in the "
                "drained anomaly-verdict records"
            )
        log(f"mlscore: {mode} precision {precision:.4f} recall "
            f"{recall:.4f} (TP={tp} FP={fp} FN={fn}); detected after "
            f"{detected_at} post-onset admission(s)")
        emit(f"anomaly detection precision ({mode}, device decisions "
             "vs labeled trace)", precision, "ratio", vs_baseline=0.0)
        emit(f"anomaly detection recall ({mode})", recall, "ratio",
             vs_baseline=0.0)
        emit(f"anomaly detection latency ({mode}, drain-per-admission)",
             float(detected_at), "admissions", vs_baseline=0.0)
        out[f"precision_{mode}"] = float(precision)
        out[f"recall_{mode}"] = float(recall)
        out[f"detect_{mode}_admissions"] = float(detected_at)
        det.close()

    # -- retention at a fixed offered load (interleaved min-vs-min) ---------
    def run_pass(clf):
        clf.flow.reset()
        if clf.mlscore is not None:
            clf.mlscore.reset_state()  # per-pass reset (benchruns rules)
        t0 = time.perf_counter()
        for w, v4, tf in chunks:
            clf.classify_prepared(
                clf.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
            ).result()
        return time.perf_counter() - t0

    clf_on.mark_resident_warm()
    clf_off.mark_resident_warm()
    reps = 5 if on_tpu else 3
    best = {"on": 1e9, "off": 1e9}
    for _ in range(reps):
        best["off"] = min(best["off"], run_pass(clf_off))
        best["on"] = min(best["on"], run_pass(clf_on))
    raw_ab = best["off"] / max(best["on"], 1e-12)
    log(f"mlscore: RAW full-speed A/B — scoring-on {best['on']*1e3:.1f} "
        f"ms vs off {best['off']*1e3:.1f} ms over {len(trace)} pkts "
        f"({raw_ab:.3f}, ungated reference)")
    emit("raw full-speed dispatch A/B with anomaly scoring on "
         "(resident fused serving loop, ungated reference)",
         raw_ab, "ratio", vs_baseline=0.0)
    out["raw_ab"] = float(raw_ab)

    cap_off = len(trace) / best["off"]
    offered = 0.7 * cap_off
    sched = np.arange(len(chunks)) * (bs / offered)
    sched_end = len(trace) / offered

    def run_offered(clf):
        clf.flow.reset()
        if clf.mlscore is not None:
            clf.mlscore.reset_state()
        t0 = time.perf_counter()
        for (w, v4, tf), s in zip(chunks, sched):
            now = time.perf_counter() - t0
            if now < s:
                time.sleep(s - now)
            clf.classify_prepared(
                clf.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
            ).result()
        return max(time.perf_counter() - t0, sched_end)

    best_o = {"on": 1e9, "off": 1e9}
    for _ in range(reps):
        best_o["off"] = min(best_o["off"], run_offered(clf_off))
        best_o["on"] = min(best_o["on"], run_offered(clf_on))
    ach_on = len(trace) / best_o["on"]
    ach_off = len(trace) / best_o["off"]
    retention = ach_on / max(ach_off, 1e-12)
    log(f"mlscore: served throughput at {offered/1e3:.1f} K pkt/s "
        f"offered (70% of scoring-off capacity {cap_off/1e3:.1f} K): "
        f"on {ach_on/1e3:.1f} K vs off {ach_off/1e3:.1f} K -> "
        f"retention {retention:.3f}")
    emit("classify throughput retention with anomaly scoring on "
         "(fixed offered load at 70% of scoring-off capacity, "
         "resident serving loop, synflood trace)",
         retention, "ratio", vs_baseline=0.0)
    out["retention"] = float(retention)

    # -- zero-recompile / zero-alloc steady state (scoring ON) --------------
    clf_on.mark_resident_warm()
    fn_t = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False,
        score=spec,
    )
    fn_t4 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", True, None, 0, False,
        score=spec,
    )
    from infw.kernels.mxu_score import jitted_score_update

    fn_c = jitted_score_update(spec)
    cache0 = fn_t._cache_size() + fn_t4._cache_size() + fn_c._cache_size()
    n_disp = 0
    while n_disp < 300:
        for w, v4, tf in chunks:
            clf_on.classify_prepared(
                clf_on.prepare_packed(w, v4, tcp_flags=tf),
                apply_stats=False,
            ).result()
            n_disp += 1
            if n_disp >= 300:
                break
    grew = (
        fn_t._cache_size() + fn_t4._cache_size() + fn_c._cache_size()
    ) - cache0
    allocs = clf_on.resident.steady_allocs()
    if grew or allocs:
        raise RuntimeError(
            f"mlscore steady state not zero-cost: {grew} recompile(s), "
            f"{allocs} pool allocation(s) across {n_disp} warmed "
            "dispatches with scoring on"
        )
    log(f"mlscore steady state: {n_disp} fused dispatches with scoring "
        "on, 0 recompiles, 0 pool allocations")
    emit("mlscore-on steady-state recompiles + pool allocations per "
         "300 warmed dispatches", float(grew + allocs), "events",
         vs_baseline=0.0)
    out["steady"] = float(grew + allocs)

    # -- enforce leg: mitigation sticks, failsafe precedence holds ----------
    enf = TpuClassifier(force_path="trie",
                        flow_table=FlowConfig.make(entries=1 << 14),
                        resident=True, mlscore=spec, mlscore_model=model,
                        mlscore_mode="enforce")
    enf.load_tables(tables)
    res_enf = []
    for w, v4, tf in chunks:
        o = enf.classify_prepared(
            enf.prepare_packed(w, v4, tcp_flags=tf), apply_stats=False
        ).result()
        res_enf.append(o.results)
    res_enf = np.concatenate(res_enf)
    truth = np.asarray(meta["attack_mask"], bool)
    post = np.zeros(len(trace), bool)
    post[meta["start"] :] = True
    atk = truth & post
    denied = (res_enf & 0xFF) == _DENY
    mitigated = float(denied[atk].mean()) if atk.any() else 0.0
    enf.mlscore.drain(force=True)  # fold tstat into the counters
    enforced_total = int(enf.mlscore.counter_values()
                         ["mlscore_enforced_total"])
    if enforced_total <= 0:
        raise RuntimeError("mlscore-bench: enforce mode rewrote nothing "
                           "on the synflood trace")
    # failsafe precedence: with EVERYTHING anomalous, failsafe-port
    # cells must keep their rule verdicts bit-exactly
    enf.mlscore.set_threshold(-(10 ** 6))
    fs_batch = testing.random_batch(np.random.default_rng(9), tables, bs)
    fs_batch.proto[:] = 6
    fs_ports = np.asarray([22, 6443, 2379, 2380, 10250, 10257, 10259],
                          np.int32)
    fs_batch.dst_port[:] = fs_ports[np.arange(bs) % len(fs_ports)]
    fs_batch.tcp_flags = np.full(bs, jaxpath.TCP_ACK, np.int32)
    w, v4 = fs_batch.pack_wire_subset(np.arange(bs, dtype=np.int64))
    o_enf = enf.classify_prepared(
        enf.prepare_packed(w, v4, tcp_flags=fs_batch.tcp_flags),
        apply_stats=False,
    ).result()
    ref = oracle.classify(tables, fs_batch)
    fs_mask = failsafe_lane_mask_np(fs_batch.proto, fs_batch.dst_port)
    if not np.array_equal(o_enf.results[fs_mask], ref.results[fs_mask]):
        raise RuntimeError(
            "mlscore-bench: enforce mode rewrote a failsafe-port cell "
            "(the failsaferules precedence contract)"
        )
    log(f"mlscore enforce: {mitigated:.3f} of post-onset attack lanes "
        f"denied ({enforced_total} rewrites); failsafe cells "
        "bit-identical to the rule verdicts")
    emit("enforce-mode mitigation (fraction of post-onset attack lanes "
         "denied, synflood trace)", mitigated, "ratio", vs_baseline=0.0)
    out["enforce_mitigation"] = mitigated
    enf.close()
    for c in (clf_on, clf_off):
        c.close()
    return out


def mlscore_bench_main() -> int:
    """``make mlscore-bench``: the anomaly-scoring tier standalone (CPU
    smoke off TPU) with the regression gates — detection precision >=
    INFW_MLSCORE_PRECISION_MIN (default 0.95) and recall >=
    INFW_MLSCORE_RECALL_MIN (default 0.9) on both labeled traces,
    classify retention with scoring on >= INFW_MLSCORE_RETENTION_MIN
    (default 0.95), and the statecheck mlscore config runs FIRST and
    gates record publication (the telemetry-bench discipline)."""
    precision_min = float(
        os.environ.get("INFW_MLSCORE_PRECISION_MIN", "0.95")
    )
    recall_min = float(os.environ.get("INFW_MLSCORE_RECALL_MIN", "0.9"))
    retention_min = float(
        os.environ.get("INFW_MLSCORE_RETENTION_MIN", "0.95")
    )
    from infw.analysis import statecheck

    rep = statecheck.run_config("mlscore", seed=0, n_ops=8,
                                shrink_on_failure=False)
    if not rep["ok"]:
        log(f"mlscore-bench FAIL: statecheck mlscore not green before "
            f"record publication: {rep['failure']}")
        return 1
    log(f"mlscore-bench: statecheck mlscore green "
        f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(2024)
    rec = bench_mlscore(rng, on_tpu)
    emit_compact_record()
    problems = []
    for mode in ("synflood", "portscan"):
        if not rec.get(f"precision_{mode}", 0.0) >= precision_min:
            problems.append(
                f"precision_{mode} {rec.get(f'precision_{mode}', 0):.3f}"
                f" < gate {precision_min}"
            )
        if not rec.get(f"recall_{mode}", 0.0) >= recall_min:
            problems.append(
                f"recall_{mode} {rec.get(f'recall_{mode}', 0):.3f} < "
                f"gate {recall_min}"
            )
    if not rec.get("retention", 0.0) >= retention_min:
        problems.append(
            f"retention {rec.get('retention', 0):.3f} < gate "
            f"{retention_min}"
        )
    if problems:
        for p in problems:
            log(f"mlscore-bench FAIL: {p}")
        return 1
    log("mlscore-bench OK: " + ", ".join(
        f"{k}={v:.3f}" for k, v in sorted(rec.items())
    ))
    return 0


def bench_payload(rng, on_tpu):
    """ISSUE-19 payload matching tier (``make payload-bench``, folded
    into bench-checked): the batched Aho-Corasick plane measured four
    ways:

    - ORACLE GATE before any timing line: shadow-mode verdicts with
      matching on bit-identical to the CPU oracle (shadow must never
      touch verdicts), and the device match bitmaps bit-identical to
      the NAIVE host substring oracle (cpu_ref.payload_match_ref)
      across the classic and resident fused serving paths;
    - AUTOMATON LADDER: standalone match throughput over 64/256/1024
      patterns x 64/128 prefix bytes (the AcSpec bucket grid),
      interleaved min-of-reps;
    - RETENTION (the telemetry-bench discipline): served classify
      throughput at a FIXED OFFERED LOAD — 70%% of the headers-only
      capacity, calibrated in-record — matching on vs headers-only on
      the resident serving loop, interleaved min-vs-min, gated at
      INFW_PAYLOAD_RETENTION_MIN (the 64-pattern / 64-byte rung);
    - ZERO-RECOMPILE HOT-SWAP: a warmed run with an in-bucket
      swap_patterns AND a shadow->enforce->shadow mode flip mid-stream
      must leave the fused executables' caches and the resident pool's
      allocation counter exactly where the prewarm left them (swaps
      flip value operands; mode is a device operand);
    - ENFORCE LEG: signature-bearing lanes are denied (ruleId 0) while
      failsafe-port cells keep their rule verdicts bit-exactly (the
      failsaferules precedence contract).

    Returns the record dict for the payload-bench gate."""
    import jax as _jax

    from infw.backend.cpu_ref import payload_match_ref
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig
    from infw.kernels.acmatch import (
        compile_patterns,
        jitted_acmatch,
        model_device,
    )
    from infw.kernels.mxu_score import DENY as _DENY, failsafe_lane_mask_np
    from infw.payload import (
        attack_payloads,
        benign_payloads,
        signature_patterns,
    )
    from infw.scheduler import prewarm_ladder

    out = {}
    n_entries = 100_000 if on_tpu else 20_000
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, v6_fraction=0.4,
        ifindexes=(2, 3),
    )
    bs = 256
    pats64 = signature_patterns(np.random.default_rng(11), 64, plen=64)

    def payload_mix(prng, n, pats, plen, attack_frac=0.1):
        """Benign HTTP-ish prefixes with a planted-signature minority —
        the loadgen --payload attack-mix shape."""
        k = max(1, int(n * attack_frac))
        pay_a, len_a = attack_payloads(prng, k, pats, plen=plen)
        pay_b, len_b = benign_payloads(prng, n - k, plen=plen)
        pay = np.concatenate([pay_a, pay_b])
        lens = np.concatenate([len_a, len_b])
        perm = prng.permutation(n)
        return (np.ascontiguousarray(pay[perm]),
                np.ascontiguousarray(lens[perm].astype(np.int32)))

    # -- oracle + bitmap bit-identity gate BEFORE any timing line -----------
    small = testing.random_tables(np.random.default_rng(7), n_entries=48,
                                  width=8)
    sbatch = testing.random_batch(np.random.default_rng(1501), small,
                                  bs * 4)
    sbatch.tcp_flags = np.full(len(sbatch), jaxpath.TCP_ACK, np.int32)
    spay, slen = payload_mix(np.random.default_rng(1502), len(sbatch),
                             pats64, 64, attack_frac=0.5)
    sref = oracle.classify(small, sbatch)
    for label, kw in (
        ("classic", dict(force_path="trie")),
        ("resident", dict(force_path="trie",
                          flow_table=FlowConfig.make(entries=1 << 12),
                          resident=True)),
    ):
        chk = TpuClassifier(payload=pats64, payload_plen=64,
                            payload_track=True, **kw)
        chk.load_tables(small)
        tier = chk.payload
        tier.set_keep_masks(len(sbatch) // bs + 1)
        n_div = 0
        for lo in range(0, len(sbatch), bs):
            idx = np.arange(lo, lo + bs, dtype=np.int64)
            sub = sbatch.take(idx)
            sub.payload = spay[lo:lo + bs]
            sub.payload_len = slen[lo:lo + bs]
            o = chk.classify(sub, apply_stats=False)
            n_div += int((o.results != sref.results[idx]).sum())
        if n_div:
            raise RuntimeError(
                f"payload-bench verdict mismatch on the {label} path: "
                f"{n_div} divergences vs the CPU oracle (shadow mode "
                "must never touch verdicts)"
            )
        masks = tier.recent_masks()
        if not masks:
            raise RuntimeError(
                f"payload-bench: no match bitmaps retained on the "
                f"{label} path (tracking broken?)"
            )
        for pay, plen, bitmap, hit in masks:
            want = payload_match_ref(
                tier.model.patterns, pay, plen, tier.spec.plen,
                tier.spec.pwords,
            )
            if not np.array_equal(np.asarray(bitmap, np.uint32), want):
                raise RuntimeError(
                    f"payload-bench bitmap oracle mismatch ({label}): "
                    "device Aho-Corasick diverged from the naive host "
                    "substring reference"
                )
            if not np.array_equal(np.asarray(hit, bool),
                                  (np.asarray(bitmap) != 0).any(axis=1)):
                raise RuntimeError(
                    f"payload-bench served-hit mismatch ({label}): the "
                    "fused merge and the standalone kernel disagree"
                )
        chk.close()
    log("payload: oracle gate clean (classic/resident bitmap + verdict "
        "bit-identity vs the naive host reference)")

    # -- automaton ladder: patterns x prefix width --------------------------
    reps = 5 if on_tpu else 3
    for npat in (64, 256, 1024):
        for plen in (64, 128):
            lpats = signature_patterns(
                np.random.default_rng(100 + npat), npat, plen=plen
            )
            model = compile_patterns(lpats, plen=plen)
            trans, mmap = model_device(model)
            f = jitted_acmatch(model.spec)
            pay, lens = payload_mix(np.random.default_rng(5), bs, lpats,
                                    plen, attack_frac=0.5)
            pay_d = _jax.device_put(pay)
            len_d = _jax.device_put(lens)
            np.asarray(f(trans, mmap, pay_d, len_d))  # warm
            best = 1e9
            for _ in range(reps):
                t0 = time.perf_counter()
                np.asarray(f(trans, mmap, pay_d, len_d))
                best = min(best, time.perf_counter() - t0)
            rate = bs / best
            path = "matmul" if model.spec.matmul else "gather"
            log(f"payload ladder: {npat:5d} patterns x {plen:3d} B "
                f"({model.spec.states} states, {path}): "
                f"{rate/1e3:.1f} K pkt/s standalone")
            emit(f"payload match throughput ({npat} patterns x {plen} B "
                 f"prefix, standalone automaton launch)", rate,
                 "packets/s", vs_baseline=0.0)
            out[f"ladder_{npat}x{plen}"] = float(rate)

    # -- retention at a fixed offered load (interleaved min-vs-min) ---------
    trace = testing.random_batch_fast(np.random.default_rng(1500), tables,
                                      bs * 40)
    trace.tcp_flags = np.full(len(trace), jaxpath.TCP_ACK, np.int32)
    tpay, tlen = payload_mix(np.random.default_rng(1503), len(trace),
                             pats64, 64)

    def chunked(tr):
        cs = []
        for lo in range(0, len(tr), bs):
            sub = np.arange(lo, lo + bs, dtype=np.int64)
            w, v4 = tr.pack_wire_subset(sub)
            cs.append((
                w, v4,
                np.ascontiguousarray(tr.tcp_flags[sub]),
                np.ascontiguousarray(tpay[lo:lo + bs]),
                np.ascontiguousarray(tlen[lo:lo + bs]),
            ))
        return cs

    chunks = chunked(trace)
    fcfg = FlowConfig.make(entries=1 << 14)
    clf_on = TpuClassifier(force_path="trie", flow_table=fcfg,
                           resident=True, payload=pats64,
                           payload_plen=64)
    clf_off = TpuClassifier(force_path="trie",
                            flow_table=FlowConfig.make(entries=1 << 14),
                            resident=True)
    for c in (clf_on, clf_off):
        c.load_tables(tables)
        prewarm_ladder(c, (bs,))

    def run_pass(clf, with_pay):
        clf.flow.reset()
        t0 = time.perf_counter()
        for w, v4, tf, pay, plen in chunks:
            clf.classify_prepared(
                clf.prepare_packed(
                    w, v4, tcp_flags=tf,
                    payload=pay if with_pay else None,
                    payload_len=plen if with_pay else None,
                ),
                apply_stats=False,
            ).result()
        return time.perf_counter() - t0

    run_pass(clf_on, True)  # warm the payload-fused shape
    clf_on.mark_resident_warm()
    clf_off.mark_resident_warm()
    best = {"on": 1e9, "off": 1e9}
    for _ in range(reps):
        best["off"] = min(best["off"], run_pass(clf_off, False))
        best["on"] = min(best["on"], run_pass(clf_on, True))
    raw_ab = best["off"] / max(best["on"], 1e-12)
    log(f"payload: RAW full-speed A/B — matching-on {best['on']*1e3:.1f} "
        f"ms vs headers-only {best['off']*1e3:.1f} ms over {len(trace)} "
        f"pkts ({raw_ab:.3f}, ungated reference)")
    emit("raw full-speed dispatch A/B with payload matching on "
         "(64 patterns x 64 B, resident fused serving loop, ungated "
         "reference)", raw_ab, "ratio", vs_baseline=0.0)
    out["raw_ab"] = float(raw_ab)

    cap_off = len(trace) / best["off"]
    offered = 0.7 * cap_off
    sched = np.arange(len(chunks)) * (bs / offered)
    sched_end = len(trace) / offered

    def run_offered(clf, with_pay):
        clf.flow.reset()
        t0 = time.perf_counter()
        for (w, v4, tf, pay, plen), s in zip(chunks, sched):
            now = time.perf_counter() - t0
            if now < s:
                time.sleep(s - now)
            clf.classify_prepared(
                clf.prepare_packed(
                    w, v4, tcp_flags=tf,
                    payload=pay if with_pay else None,
                    payload_len=plen if with_pay else None,
                ),
                apply_stats=False,
            ).result()
        return max(time.perf_counter() - t0, sched_end)

    best_o = {"on": 1e9, "off": 1e9}
    for _ in range(reps):
        best_o["off"] = min(best_o["off"], run_offered(clf_off, False))
        best_o["on"] = min(best_o["on"], run_offered(clf_on, True))
    ach_on = len(trace) / best_o["on"]
    ach_off = len(trace) / best_o["off"]
    retention = ach_on / max(ach_off, 1e-12)
    log(f"payload: served throughput at {offered/1e3:.1f} K pkt/s "
        f"offered (70% of headers-only capacity {cap_off/1e3:.1f} K): "
        f"on {ach_on/1e3:.1f} K vs off {ach_off/1e3:.1f} K -> "
        f"retention {retention:.3f}")
    emit("classify throughput retention with payload matching on "
         "(fixed offered load at 70% of headers-only capacity, "
         "resident serving loop, 64 patterns x 64 B prefix)",
         retention, "ratio", vs_baseline=0.0)
    out["retention"] = float(retention)

    # -- zero-recompile / zero-alloc hot-swap -------------------------------
    pspec = clf_on.payload.spec
    fn_t = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False,
        payload=pspec,
    )
    fn_t4 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", True, None, 0, False,
        payload=pspec,
    )
    fn_m = jitted_acmatch(pspec)
    cache0 = fn_t._cache_size() + fn_t4._cache_size() + fn_m._cache_size()
    v0 = clf_on.payload.version
    n_disp = 0
    while n_disp < 120:
        for w, v4, tf, pay, plen in chunks:
            clf_on.classify_prepared(
                clf_on.prepare_packed(w, v4, tcp_flags=tf, payload=pay,
                                      payload_len=plen),
                apply_stats=False,
            ).result()
            n_disp += 1
            if n_disp == 40:
                # in-bucket hot swap mid-stream: same AcSpec buckets,
                # only the device value operands flip
                clf_on.set_payload_patterns(signature_patterns(
                    np.random.default_rng(12), 64, plen=64,
                ))
            elif n_disp == 80:
                clf_on.set_payload_mode("enforce")
            elif n_disp == 100:
                clf_on.set_payload_mode("shadow")
            if n_disp >= 120:
                break
    grew = (
        fn_t._cache_size() + fn_t4._cache_size() + fn_m._cache_size()
    ) - cache0
    allocs = clf_on.resident.steady_allocs()
    if clf_on.payload.version != v0 + 1:
        raise RuntimeError("payload-bench: hot swap did not land "
                           "(pattern-set version unchanged)")
    if grew or allocs:
        raise RuntimeError(
            f"payload hot-swap not zero-cost: {grew} recompile(s), "
            f"{allocs} pool allocation(s) across {n_disp} warmed "
            "dispatches spanning a pattern swap + two mode flips"
        )
    log(f"payload hot-swap steady state: {n_disp} fused dispatches "
        "spanning an in-bucket pattern swap + shadow->enforce->shadow, "
        "0 recompiles, 0 pool allocations")
    emit("payload hot-swap recompiles + pool allocations per 120 warmed "
         "dispatches (in-bucket swap + mode flips mid-stream)",
         float(grew + allocs), "events", vs_baseline=0.0)
    out["swap_steady"] = float(grew + allocs)

    # -- enforce leg: mitigation lands, failsafe precedence holds -----------
    enf = TpuClassifier(force_path="trie",
                        flow_table=FlowConfig.make(entries=1 << 14),
                        resident=True, payload=pats64, payload_plen=64,
                        payload_mode="enforce")
    enf.load_tables(tables)
    fs_batch = testing.random_batch(np.random.default_rng(9), tables, bs)
    fs_batch.proto[:] = 6
    fs_ports = np.asarray([22, 6443, 2379, 2380, 10250, 10257, 10259],
                          np.int32)
    half = bs // 2
    fs_batch.dst_port[:half] = fs_ports[np.arange(half) % len(fs_ports)]
    fs_batch.dst_port[half:] = 33000 + np.arange(bs - half)
    fs_batch.tcp_flags = np.full(bs, jaxpath.TCP_ACK, np.int32)
    sig = pats64[0]
    fs_pay = np.zeros((bs, 64), np.uint8)
    fs_pay[:, 3:3 + len(sig)] = np.frombuffer(sig, np.uint8)
    w, v4 = fs_batch.pack_wire_subset(np.arange(bs, dtype=np.int64))
    o_enf = enf.classify_prepared(
        enf.prepare_packed(w, v4, tcp_flags=fs_batch.tcp_flags,
                           payload=fs_pay,
                           payload_len=np.full(bs, 64, np.int32)),
        apply_stats=False,
    ).result()
    ref = oracle.classify(tables, fs_batch)
    fs_mask = failsafe_lane_mask_np(fs_batch.proto, fs_batch.dst_port)
    if not np.array_equal(o_enf.results[fs_mask], ref.results[fs_mask]):
        raise RuntimeError(
            "payload-bench: enforce mode rewrote a failsafe-port cell "
            "(the failsaferules precedence contract)"
        )
    open_lanes = ~fs_mask & ((ref.results & 0xFF) != _DENY)
    denied = (o_enf.results & 0xFF) == _DENY
    mitigated = float(denied[open_lanes].mean()) if open_lanes.any() else 0.0
    enforced_total = int(
        enf.payload.counter_values()["payload_enforced_total"]
    )
    if enforced_total <= 0:
        raise RuntimeError("payload-bench: enforce mode rewrote nothing "
                           "on signature-bearing lanes")
    log(f"payload enforce: {mitigated:.3f} of open signature-bearing "
        f"lanes denied ({enforced_total} rewrites); failsafe cells "
        "bit-identical to the rule verdicts")
    emit("enforce-mode payload mitigation (fraction of open "
         "signature-bearing lanes denied)", mitigated, "ratio",
         vs_baseline=0.0)
    out["enforce_mitigation"] = mitigated
    enf.close()
    for c in (clf_on, clf_off):
        c.close()
    return out


def payload_bench_main() -> int:
    """``make payload-bench``: the payload matching tier standalone
    (CPU smoke off TPU) with the regression gates — classify retention
    with matching on >= INFW_PAYLOAD_RETENTION_MIN (default 0.9) at the
    64-pattern / 64-byte rung, the hot-swap zero-recompile pin, and the
    statecheck payload configs run FIRST and gate record publication
    (the telemetry-bench discipline)."""
    retention_min = float(
        os.environ.get("INFW_PAYLOAD_RETENTION_MIN", "0.9")
    )
    from infw.analysis import statecheck

    for cfg in ("payload", "payload-resident"):
        rep = statecheck.run_config(cfg, seed=0, n_ops=8,
                                    shrink_on_failure=False)
        if not rep["ok"]:
            log(f"payload-bench FAIL: statecheck {cfg} not green before "
                f"record publication: {rep['failure']}")
            return 1
        log(f"payload-bench: statecheck {cfg} green "
            f"({rep['ops']} ops, {rep['entries']} entries)")
    on_tpu = jax.default_backend() == "tpu"
    rec = bench_payload(np.random.default_rng(2025), on_tpu)
    emit_compact_record()
    problems = []
    if not rec.get("retention", 0.0) >= retention_min:
        problems.append(
            f"retention {rec.get('retention', 0):.3f} < gate "
            f"{retention_min}"
        )
    if rec.get("swap_steady", 1.0) != 0.0:
        problems.append(
            f"hot-swap steady state not zero-cost "
            f"({rec.get('swap_steady')})"
        )
    if not rec.get("enforce_mitigation", 0.0) > 0.0:
        problems.append("enforce mode mitigated nothing")
    if problems:
        for p in problems:
            log(f"payload-bench FAIL: {p}")
        return 1
    log("payload-bench OK: " + ", ".join(
        f"{k}={v:.3f}" for k, v in sorted(rec.items())
    ))
    return 0


# --- on-device verdict latency ---------------------------------------------


def bench_device_latency(tables, batch, on_tpu):
    """Device-resident per-batch verdict latency (round-4 weak #3: wire
    p50 through the tunnel is unmeasurable — 0.0 ms above a +-30-50 ms
    jitter floor is a statement about the link, not the dataplane).

    Methodology: k single-batch classifies CHAINED on device (iteration
    i+1's ports and ip words depend on i's verdicts — same honesty rules
    as the throughput loops), timed as a two-point slope; the slope IS
    the steady-state per-batch latency with zero host/link involvement.
    Reported per batch size alongside the wire numbers; the wire tier
    keeps the link-floor split for the host path."""
    from infw.constants import KIND_IPV4

    dt = jaxpath.device_tables(tables)
    for bs in (32, 64, 128, 256, 1024, 4096):
        sub = batch.slice(0, bs)
        db = jaxpath.device_batch(sub)
        word_sel = (
            jnp.arange(4, dtype=jnp.int32)[None, :]
            == jnp.where(db.kind == KIND_IPV4, 0, 3)[:, None]
        )

        @jax.jit
        def loop(k, dt, db, word_sel=word_sel):
            def step(i, carry):
                dport, ip, acc = carry
                res, _x, _s = jaxpath.classify(
                    dt, db._replace(dst_port=dport, ip_words=ip),
                    use_trie=False,
                )
                dport = (dport + (res & 1).astype(jnp.int32)) % 65536
                pert = (res & 0xF) ^ (i.astype(jnp.uint32) & 0xF)
                ip = jnp.where(word_sel, ip ^ pert[:, None], ip)
                return dport, ip, acc + jnp.sum(res.astype(jnp.uint32))

            return jax.lax.fori_loop(
                0, k, step, (db.dst_port, db.ip_words, jnp.uint32(0))
            )[2]

        int(loop(1, dt, db))  # compile
        k1, k2 = (16, 64) if on_tpu else (2, 6)

        def best_of(k, attempts=3):
            best = float("inf")
            for _ in range(attempts):
                t0 = time.perf_counter()
                int(loop(k, dt, db))
                best = min(best, time.perf_counter() - t0)
            return best

        b1 = best_of(k1)
        while True:
            b2 = best_of(k2)
            if b2 - b1 >= (0.5 if on_tpu else 0.05) or k2 >= 2_000_000:
                break
            k2 *= 4
        lat = (b2 - b1) / (k2 - k1)
        log(f"device latency @batch={bs}: {lat*1e6:.1f} us/batch "
            f"({lat/bs*1e9:.0f} ns/packet, slope k={k1}->{k2})")
        emit(
            f"verdict latency on-device @batch={bs} "
            "(chained slope, 1000-CIDR dense, no host/link)",
            lat * 1e3, "ms", vs_baseline=0.0,
        )


# --- BASELINE configs 1 and 2 (round-5 missing #2) -------------------------


def bench_baseline_config1(rng, on_tpu):
    """BASELINE config 1: the reference sample posture — one source
    CIDR, one TCP port-range rule, one interface — classified by the CPU
    reference backend (the native C++ classifier, the framework's
    differential oracle).  This is the native-component baseline the
    ladder's TPU tiers are compared against; reference analogue
    /root/reference/config/samples/."""
    from infw.backend.cpu_ref import CpuRefClassifier
    from infw.compiler import LpmKey, compile_tables_from_content

    rows = np.zeros((2, 7), np.int32)
    rows[1] = [1, 6, 800, 900, 0, 0, 1]  # ruleId 1, TCP 800-900, DENY
    content = {
        LpmKey(prefix_len=24 + 32, ingress_ifindex=2,
               ip_data=bytes([192, 168, 10, 0]) + bytes(12)): rows
    }
    tables = compile_tables_from_content(content, rule_width=2)
    clf = CpuRefClassifier()
    clf.load_tables(tables)
    n = 2**20 if on_tpu else 2**16
    batch = testing.random_batch_fast(rng, tables, n_packets=n)

    def results_of(sub):
        return clf.classify(sub, apply_stats=False).results

    spot_check(results_of, tables, batch, label="baseline-config1")
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        clf.classify(batch, apply_stats=False)
        best = min(best, time.perf_counter() - t0)
    thr = n / best
    log(f"baseline-config1: {thr/1e6:.2f} M pkts/s (native C++ reference, "
        f"best of 3, {n} packets)")
    emit(
        "BASELINE config 1: single CIDR x single TCP port-range rule, "
        "CPU reference classifier (native C++)",
        thr, "packets/s",
    )


def bench_baseline_config2(rng, on_tpu):
    """BASELINE config 2: 1K mixed-family (IPv4+IPv6) source CIDRs x 16
    ordered mixed TCP/UDP/ICMP rules — measured explicitly instead of
    being implied by the 1000x100 dense headline (round-5 missing #2)."""
    tables = testing.random_tables_fast(
        rng, n_entries=1000, width=16, v6_fraction=0.5, ifindexes=(2, 3)
    )
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    pt = jax.tree.map(jax.device_put, pallas_dense.build_pallas_tables(tables))
    db = jaxpath.device_batch(batch)
    interpret = not on_tpu
    block_b = pallas_dense.choose_block_b(pt.mdt.shape[1])
    fn = pallas_dense.jitted_classify_pallas(interpret, block_b)
    np.asarray(fn(pt, db)[0])  # compile

    def results_of(sub):
        return np.asarray(fn(pt, jaxpath.device_batch(sub))[0])

    spot_check(results_of, tables, batch, label="baseline-config2")

    def step(ptab, b):
        res, _xdp, _stats = pallas_dense.classify_pallas(
            ptab, b, interpret=interpret, block_b=block_b
        )
        return res

    thr = chained_throughput(
        step, pt, db, n_packets, on_tpu, "baseline-config2"
    )
    emit(
        "BASELINE config 2: 1K mixed-family CIDRs x 16 ordered "
        "TCP/UDP/ICMP rules (Pallas int8 dense)",
        thr, "packets/s",
    )


# --- config 2 headline -----------------------------------------------------


def bench_dense_headline(rng, on_tpu):
    tables = testing.random_tables(
        rng, n_entries=1000, width=100, ifindexes=(2, 3, 4)
    )
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)

    pt = jax.tree.map(jax.device_put, pallas_dense.build_pallas_tables(tables))
    db = jaxpath.device_batch(batch)
    interpret = not on_tpu
    block_b = pallas_dense.choose_block_b(pt.mdt.shape[1])
    fn = pallas_dense.jitted_classify_pallas(interpret, block_b)

    t0 = time.perf_counter()
    np.asarray(fn(pt, db)[0])
    log(f"dense: compile+first {time.perf_counter()-t0:.2f}s "
        f"(dtype={pt.mdt.dtype}, block_b={block_b})")

    def results_of(sub):
        return np.asarray(fn(pt, jaxpath.device_batch(sub))[0])

    spot_check(results_of, tables, batch, label="dense")

    def step(ptab, b):
        res, _xdp, _stats = pallas_dense.classify_pallas(
            ptab, b, interpret=interpret, block_b=block_b
        )
        return res

    thr = chained_throughput(step, pt, db, n_packets, on_tpu, "dense")
    return tables, batch, thr


def main():
    on_tpu = jax.default_backend() == "tpu"
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    if on_tpu:
        # Persistent XLA compile cache: repeated bench runs (and the
        # daemon tiers inside this one) skip the 30-60s first-compiles.
        # Timing methodology is unaffected — compiles are excluded from
        # every measured slope.
        from infw.platform import enable_jax_compile_cache

        enable_jax_compile_cache("/tmp/infw-jax-cache")
    rng = np.random.default_rng(2024)

    # Each tier is independent: a failure (tunnel flake, non-monotonic
    # timing) logs and moves on, so the guaranteed headline JSON line is
    # still the LAST stdout line for drivers that parse it.
    trie_tables = None
    try:
        trie_tables = bench_trie_100k(rng, on_tpu)
    except Exception as e:
        log(f"trie100k FAILED: {e}")
    if trie_tables is not None:
        try:
            bench_replay_10m(rng, trie_tables, on_tpu)
        except Exception as e:
            log(f"replay FAILED: {e}")
    try:
        bench_adversarial_1m(rng, on_tpu)
    except Exception as e:
        log(f"adv1m FAILED: {e}")
    try:
        # ISSUE-6 build-path lines: columnar-vs-per-key cold build A/B
        # @1M with the in-record denominator and bit-identity check
        bench_build(rng)
    except Exception as e:
        log(f"build bench FAILED: {e}")
    try:
        # ISSUE-6 10M tier: cold build, full reload, compressed-walk
        # classify throughput, 1-key joined diff-scatter patch, 1-key
        # structural overlay add (200K smoke off-TPU)
        bench_scale_10m(rng, on_tpu)
    except Exception as e:
        log(f"scale 10M FAILED: {e}")
    try:
        bench_8iface(rng, on_tpu)
    except Exception as e:
        log(f"8iface FAILED: {e}")
    try:
        # real multi-chip scaling when >1 device is visible (a single
        # tunneled chip logs a skip; the 8-virtual-device MULTICHIP
        # record comes from __graft_entry__.dryrun_multichip, which runs
        # the same ladder)
        bench_multichip(rng, on_tpu)
    except Exception as e:
        log(f"multichip FAILED: {e}")
    try:
        bench_baseline_config1(rng, on_tpu)
    except Exception as e:
        log(f"baseline config 1 FAILED: {e}")
    try:
        bench_baseline_config2(rng, on_tpu)
    except Exception as e:
        log(f"baseline config 2 FAILED: {e}")
    try:
        bench_incremental_update(rng, on_tpu)
    except Exception as e:
        log(f"incremental update FAILED: {e}")
    try:
        # the 1M tier, where the poptrie re-transform the overlay avoids
        # would cost seconds (round-4 weak #6: no 1M update line)
        bench_incremental_update(
            rng, on_tpu,
            n_entries=1_000_000 if on_tpu else 10_000,
            width=4, table_kw=dict(group_size=16),
        )
    except Exception as e:
        log(f"incremental update @1M FAILED: {e}")

    try:
        tables, batch, thr = bench_dense_headline(rng, on_tpu)
    except Exception as e:
        return fail(str(e))
    try:
        bench_wire_latency(tables, batch, on_tpu)
    except Exception as e:
        log(f"wire latency FAILED: {e}")
    try:
        bench_device_latency(tables, batch, on_tpu)
    except Exception as e:
        log(f"device latency FAILED: {e}")
    try:
        # ISSUE-7 SLO serving tier: open-loop p50/p99/p999 above link
        # floor at 3 offered loads + deadline-miss rate + batch-size
        # distribution + fixed-chunk A/B (also standalone as
        # `bench.py --slo-bench`, `make slo-bench`, with a p99 gate)
        bench_slo(rng, on_tpu)
    except Exception as e:
        log(f"slo tier FAILED: {e}")
    try:
        # ISSUE-9 update-storm churn tier: folded-txn-vs-sequential
        # per-edit A/B + sustained edits/s under fixed offered classify
        # load + p99 edit-visible latency + throughput retention (also
        # standalone as `bench.py --churn-bench`, `make churn-bench`,
        # with speedup/retention gates)
        bench_churn(rng, on_tpu)
    except Exception as e:
        log(f"churn tier FAILED: {e}")
    try:
        # ISSUE-10 multi-tenant arena tier: pre-staged hot-swap
        # (page-table flip) vs full re-upload A/B, mixed-tenant batch
        # vs sequential per-tenant dispatch, arena HBM footprint vs N
        # padded tables (also standalone as `bench.py --tenant-bench`,
        # `make tenant-bench`, with the swap-speedup gate)
        bench_tenant(rng, on_tpu)
    except Exception as e:
        log(f"tenant tier FAILED: {e}")
    try:
        # ISSUE-11 stateful flow tier: classify throughput at the
        # 0/50/90/99% established-flow ladder vs the stateless
        # baseline, eviction-storm line, oracle + zero-recompile gated
        # (also standalone as `bench.py --flow-bench`, `make
        # flow-bench`, with the 90%-point speedup gate)
        bench_flow(rng, on_tpu)
    except Exception as e:
        log(f"flow tier FAILED: {e}")

    # Truncation-proof record: every tier's metric line again in one
    # contiguous block, then ONE compact single-line JSON holding the
    # complete metric set (headline included) immediately before the
    # headline — a tail-limited driver capture that keeps only its last
    # lines can never again lose the trie/replay/8-iface lines
    # (round-5 weak #6: the multi-line re-emit block outgrew the tail).
    headline_metric = (
        "packet classifications/sec/chip @100K rules "
        "(1000 CIDRs x 100 rules, Pallas int8 dense)"
    )
    re_emit_recorded()
    emit_compact_record(headline_metric, thr)
    emit(headline_metric, thr, "packets/s", record=False)
    return 0


if __name__ == "__main__":
    if "--build-bench" in sys.argv:
        sys.exit(build_bench_main())
    if "--slo-bench" in sys.argv:
        sys.exit(slo_bench_main())
    if "--churn-bench" in sys.argv:
        sys.exit(churn_bench_main())
    if "--tenant-bench" in sys.argv:
        sys.exit(tenant_bench_main())
    if "--splice-bench" in sys.argv:
        sys.exit(splice_bench_main())
    if "--flow-bench" in sys.argv:
        sys.exit(flow_bench_main())
    if "--resident-bench" in sys.argv:
        sys.exit(resident_bench_main())
    if "--pipeline-bench" in sys.argv:
        sys.exit(pipeline_bench_main())
    if "--telemetry-bench" in sys.argv:
        sys.exit(telemetry_bench_main())
    if "--mlscore-bench" in sys.argv:
        sys.exit(mlscore_bench_main())
    if "--payload-bench" in sys.argv:
        sys.exit(payload_bench_main())
    sys.exit(main())
