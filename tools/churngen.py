#!/usr/bin/env python
"""Open-loop rule-edit (churn) generator for the daemon's edits dir.

The control-plane twin of tools/loadgen.py: where loadgen offers an
open-loop PACKET stream into ``<state-dir>/ingest/``, churngen offers an
open-loop EDIT stream into ``<state-dir>/edits/`` — BGP-style rule churn
at a fixed offered rate, for driving the update-storm dataplane
(``--patch-staleness-us`` batching, ``bench_churn``'s methodology)
against a live daemon.

Edits are sampled against the SAME seeded table the daemon is expected
to be serving (``--entries``/``--table-seed`` regenerate
``infw.testing.random_tables_fast`` deterministically, the bench-tier
substrate), so rules_edit/key_delete ops hit live identities and
cidr_add ops are genuinely structural.  The op mix is
rules-edit-dominated like a real control plane (defaults: 70% rules
edits, 15% CIDR adds, 10% deletes, 5% delete-then-readd pairs — the
fold's supersession edge).

Open-loop discipline (the coordinated-omission rule, verbatim from
loadgen): the drop schedule is computed up front against one anchor
timestamp and each write sleeps until its ABSOLUTE scheduled time, so a
slow consumer makes the generator fall visibly behind (reported at
exit) instead of silently stretching the offered churn rate.
Determinism per ``--seed`` covers keys, rules AND arrival times.

Usage:
    python tools/churngen.py --out <state-dir>/edits --rate 2000 \\
        --n 10000 [--entries 2000] [--table-seed 2024] \\
        [--file-ops 64] [--seed 7] [--dry-run]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from _common import setup_repo_path

setup_repo_path()

from infw import testing  # noqa: E402
from infw.compiler import LpmKey  # noqa: E402
from infw.txn import EditOp, write_edit_file  # noqa: E402

#: op mix: (kind, probability); "readd" expands to a delete+re-add pair
OP_MIX = (
    ("rules_edit", 0.70),
    ("cidr_add", 0.15),
    ("key_delete", 0.10),
    ("readd", 0.05),
)


def generate_ops(rng: np.random.Generator, n: int, tables, width: int):
    """Seeded open-loop edit stream over the live key population: keys
    leave on delete and return on (re)add, so sustained churn never
    edits a dead identity."""
    keys = list(tables.content)
    live = list(keys)
    idents = {k.masked_identity() for k in live}
    deleted: list = []
    kinds = [k for k, _p in OP_MIX]
    probs = np.array([p for _k, p in OP_MIX])
    probs /= probs.sum()
    ops = []
    serial = 0
    while len(ops) < n:
        kind = str(rng.choice(kinds, p=probs))
        if kind in ("rules_edit", "key_delete") and not live:
            kind = "cidr_add"
        if kind == "readd" and not deleted:
            kind = "key_delete" if live else "cidr_add"
        if kind == "rules_edit":
            k = live[int(rng.integers(0, len(live)))]
            ops.append(EditOp("rules_edit", k, testing.random_rules(rng, width)))
        elif kind == "key_delete":
            i = int(rng.integers(0, len(live)))
            k = live.pop(i)
            idents.discard(k.masked_identity())
            deleted.append(k)
            ops.append(EditOp("key_delete", k))
        elif kind == "readd":
            k = deleted.pop(int(rng.integers(0, len(deleted))))
            if k.masked_identity() in idents:
                continue
            idents.add(k.masked_identity())
            live.append(k)
            ops.append(EditOp("key_add", k, testing.random_rules(rng, width)))
        else:  # cidr_add: a fresh structural identity
            serial += 1
            k = LpmKey(
                prefix_len=56,
                ingress_ifindex=2,
                ip_data=bytes([
                    198, 18, (serial >> 8) & 0xFF, serial & 0xFF
                ]) + bytes(12),
            )
            if k.masked_identity() in idents:
                continue
            idents.add(k.masked_identity())
            live.append(k)
            ops.append(EditOp("cidr_add", k, testing.random_rules(rng, width)))
    return ops


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="infw-churngen", description=__doc__)
    p.add_argument("--out", required=True,
                   help="edits directory of the target daemon")
    p.add_argument("--rate", type=float, required=True,
                   help="offered churn, edits/second")
    p.add_argument("--n", type=int, required=True, help="total edits")
    p.add_argument("--entries", type=int, default=2000,
                   help="entry count of the seeded table the daemon "
                        "serves (edits target its keys)")
    p.add_argument("--table-seed", type=int, default=2024,
                   help="seed of the served table "
                        "(testing.random_tables_fast)")
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--file-ops", type=int, default=64,
                   help="ops per dropped edit file")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--dry-run", action="store_true",
                   help="print the schedule summary without writing or "
                        "sleeping")
    args = p.parse_args(argv)
    if args.rate <= 0 or args.n <= 0 or args.file_ops <= 0:
        p.error("--rate, --n and --file-ops must be positive")

    tables = testing.random_tables_fast(
        np.random.default_rng(args.table_seed), n_entries=args.entries,
        width=args.width, ifindexes=(2, 3, 4),
    )
    rng = np.random.default_rng(args.seed)
    offs = testing.poisson_arrivals(rng, args.rate, args.n)
    ops = generate_ops(rng, args.n, tables, args.width)

    fo = int(args.file_ops)
    n_files = -(-args.n // fo)
    file_starts = offs[::fo][:n_files]
    summary = {
        "n": int(args.n), "rate_eps": float(args.rate),
        "files": int(n_files), "file_ops": fo,
        "duration_s": float(offs[-1]), "seed": int(args.seed),
        "entries": int(args.entries), "table_seed": int(args.table_seed),
    }
    print(json.dumps(summary), flush=True)
    if args.dry_run:
        return 0

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "churngen-manifest.json"), "w") as f:
        json.dump({**summary,
                   "file_start_offsets_s": [float(x) for x in file_starts]},
                  f)
    t0 = time.monotonic()
    worst_lag = 0.0
    for i in range(n_files):
        target = t0 + float(file_starts[i])
        lag = time.monotonic() - target
        if lag < 0:
            time.sleep(-lag)
        else:
            worst_lag = max(worst_lag, lag)
        write_edit_file(
            os.path.join(args.out, f"churn{i:06d}.json"),
            ops[i * fo: (i + 1) * fo],
        )
    done = time.monotonic() - t0
    print(json.dumps({
        "offered_duration_s": float(offs[-1]),
        "actual_duration_s": done,
        "worst_schedule_lag_s": worst_lag,
        "fell_behind": worst_lag > 0.01,
    }), flush=True)
    if worst_lag > 0.01:
        print("churngen: WARNING fell behind its open-loop schedule by "
              f"{worst_lag*1e3:.1f} ms — offered churn was lower than "
              "requested; measured edit-visible latencies must use the "
              "manifest's scheduled offsets", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
