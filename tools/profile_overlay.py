#!/usr/bin/env python
"""Overlay cost check: classify throughput at the 100K tier with a dense
overlay of 0 / 64 / 512 / 1024 entries active (the structural-add side
table) — validates the OVERLAY_CAP sizing."""
import sys

from _common import jax_setup, setup_repo_path

setup_repo_path()

import numpy as np

from infw import testing
from infw.compiler import LpmKey, compile_tables_from_content
from infw.constants import KIND_IPV4
from infw.kernels import jaxpath

from bench import chained_throughput


def main():
    on_tpu = jax_setup()
    rng = np.random.default_rng(2024)
    n_entries = 100_000 if on_tpu else 2_000
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=8, ifindexes=(2, 3, 4))
    dt = jaxpath.device_tables(tables)
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    kinds = np.asarray(batch.kind)
    idx4 = np.nonzero(kinds == KIND_IPV4)[0]
    db = jaxpath.device_batch(batch.take(idx4))
    depth = jaxpath.v4_trie_depth(len(dt.trie_levels))
    dtv4 = dt._replace(trie_levels=dt.trie_levels[:depth])

    def mk_overlay(n):
        content = {}
        i = 0
        while len(content) < n:
            content[LpmKey(56, 2, bytes([203, 0, (i >> 8) & 255, i & 255])
                           + bytes(12))] = np.array(
                [[0] * 7, [1, 6, 443, 0, 0, 0, 1]], np.int32)
            i += 1
        ct = compile_tables_from_content(content, rule_width=4)
        return jaxpath.device_tables(ct, pad=True)

    results = {}
    for n_ov in (0, 64, 512, 1024):
        if n_ov == 0:
            def step(t, b):
                res, _x, _s = jaxpath.classify(t, b, use_trie=True)
                return res
        else:
            ov = mk_overlay(n_ov)

            def step(t, b, ov=ov):
                res, _x, _s = jaxpath.classify_with_overlay(
                    t, ov, b, use_trie=True)
                return res

        label = f"v4 overlay={n_ov}"
        results[label] = chained_throughput(
            step, dtv4, db, len(idx4), on_tpu, label)

    print("\n=== summary ===", file=sys.stderr, flush=True)
    for name, thr in results.items():
        print(f"{name}: {thr/1e6:.1f} M pkts/s ({1e9/thr:.1f} ns/pkt)",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
