#!/usr/bin/env python
"""Minimal stand-in for ``ruff check`` when ruff is not installed.

Implements the conservative subset `make lint` relies on — E9 (files
must parse) and F401 (unused imports) — with ``# noqa`` support, so the
lint gate functions in hermetic containers that cannot pip-install.
When ruff IS available the Makefile prefers it (full F + E9 rule set
from pyproject.toml); this fallback intentionally checks less, never
more, than ruff would.

Usage: python tools/_lint_fallback.py [paths...]   (default: repo tree)
Exit 1 when any finding is reported.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Iterator, List, Set, Tuple

DEFAULT_ROOTS = ("infw", "tools", "tests", "deploy", "bench.py",
                 "__graft_entry__.py")
EXCLUDE_DIRS = {"__pycache__", ".git", "benchruns", "testruns", "_build"}


def iter_py_files(roots) -> Iterator[str]:
    for root in roots:
        if os.path.isfile(root) and root.endswith(".py"):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in EXCLUDE_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _noqa_lines(src: str) -> Set[int]:
    out = set()
    for i, line in enumerate(src.splitlines(), 1):
        if "# noqa" in line:
            out.add(i)
    return out


class _ImportCollector(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imports: List[Tuple[str, str, int]] = []  # (bound, shown, line)
        self.used: Set[str] = set()
        self.exported: Set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            bound = a.asname or a.name.split(".")[0]
            self.imports.append((bound, a.name, node.lineno))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # future imports act by existing (ruff skips them too)
        for a in node.names:
            if a.name == "*":
                continue
            bound = a.asname or a.name
            self.imports.append((bound, a.name, node.lineno))

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # __all__ = [...] marks re-exports
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "__all__":
                try:
                    for v in ast.literal_eval(node.value):
                        self.exported.add(str(v))
                except (ValueError, TypeError):
                    pass
        self.generic_visit(node)


def check_file(path: str) -> List[str]:
    with open(path, "rb") as f:
        raw = f.read()
    try:
        src = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        return [f"{path}:1:1: E902 {e}"]
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}:{e.offset}: E999 {e.msg}"]
    noqa = _noqa_lines(src)
    col = _ImportCollector()
    col.visit(tree)
    # names referenced anywhere in string annotations also count as used
    # (cheap approximation: every identifier token in the file body)
    findings = []
    for bound, shown, lineno in col.imports:
        if lineno in noqa or bound in ("_", "__"):
            continue
        if bound in col.used or bound in col.exported:
            continue
        # conftest/init side-effect imports are conventional
        if os.path.basename(path) == "__init__.py":
            continue
        findings.append(
            f"{path}:{lineno}:1: F401 {shown!r} imported but unused"
        )
    return findings


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:]) or list(DEFAULT_ROOTS)
    roots = [a for a in args if os.path.exists(a)]
    findings: List[str] = []
    n = 0
    for path in iter_py_files(roots):
        n += 1
        findings.extend(check_file(path))
    for line in findings:
        print(line)
    print(f"fallback lint: {n} files, {len(findings)} finding(s) "
          "(ruff not installed; E9 + F401 subset)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
