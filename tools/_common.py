"""Shared arg/env bootstrap for the tools/ scripts.

Every script here used to copy-paste three things: the sys.path insert
that makes ``import infw`` work when run as ``python tools/<x>.py``, the
``on_tpu = jax.default_backend() == "tpu"`` + compile-cache preamble,
and ad-hoc ``argv[1]/argv[2]`` scale parsing.  One copy, imported as
``from _common import ...`` (the script's own directory is always on
sys.path when run as a script).
"""
from __future__ import annotations

import os
import sys
from typing import Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return _REPO_ROOT


def setup_repo_path() -> str:
    """Make ``import infw`` work from a script run in tools/."""
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)
    return _REPO_ROOT


def jax_setup(compile_cache: Optional[str] = "/tmp/infw-jax-cache") -> bool:
    """Import jax, enable the persistent compile cache on real TPU, and
    return ``on_tpu``.  Call after setup_repo_path()."""
    setup_repo_path()
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and compile_cache:
        from infw.platform import enable_jax_compile_cache

        enable_jax_compile_cache(compile_cache)
    return on_tpu


def scale_args(
    argv,
    tpu_entries: int,
    cpu_entries: int,
    default_width: int = 8,
    on_tpu: Optional[bool] = None,
) -> Tuple[int, int]:
    """The profile scripts' common ``[n_entries] [width]`` positional
    parsing with backend-dependent defaults."""
    if on_tpu is None:
        on_tpu = jax_setup()
    n_entries = (
        int(argv[1]) if len(argv) > 1 else (tpu_entries if on_tpu else cpu_entries)
    )
    width = int(argv[2]) if len(argv) > 2 else default_width
    return n_entries, width
