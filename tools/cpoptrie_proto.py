#!/usr/bin/env python
"""Prototype: compressed merged-node poptrie walk + joined target/rules
gather (round-5 ask #1).

Design under test (vs the current per-level poptrie walk in
infw/kernels/jaxpath.py):

1. ALL deep levels merge into ONE global node array, so a lane's walk
   state is (node_id, bit_pos) and path-compressed chains collapse:
   each node row is [child_base, target_base, skip_len, skip_bits,
   child_bm x8, target_bm x8] (20 u32 = 80B, inside the flat-gather
   cost window).  A step consumes skip_len (<=24) + 8 bits, so a /128
   chain is 4 steps instead of 14 levels.  Only chains with NO targets
   compress (leaf-pushed targets pin their nodes), preserving bit-exact
   LPM semantics.
2. The walk's winning target index gathers ONE joined row
   [tidx+1 (2xu16), packed rules (R*5 u16)] — the separate
   trie_targets resolve + rules gather collapse into one fat gather
   (row width is free up to ~512B, tools/profile_gather.py).

Verifies bit-exactness vs the production classify, then times v4/v6
with the chained-loop methodology.
"""
import os
import sys
import time

from _common import jax_setup, scale_args, setup_repo_path

setup_repo_path()

import numpy as np
import jax
import jax.numpy as jnp

from infw import testing
from infw.compiler import trie_level_strides
from infw.constants import KIND_IPV4, KIND_IPV6
from infw.kernels import jaxpath

from bench import chained_throughput

MAX_SKIP = 24  # skip+nibble <= 32 bits/step: extraction window stays in 2 words
MODE = os.environ.get('INFW_PROTO_MODE', 'full')
if MODE != 'full':
    MAX_SKIP = 0  # disable chain compression entirely



def build_cpoptrie(tables):
    """Host transform: slot-trie levels -> (l0, nodes, joined, d_max).

    l0:    (n0*65536, 2) int32 [global_node_id+1, joined_idx (0=none)]
    nodes: (N, 20) uint32 rows
    joined:(J, 2+R*5) uint16 [lo(tidx+1), hi(tidx+1), packed rules]
    """
    slot_levels = tables.trie_levels
    strides = trie_level_strides(len(slot_levels))
    assert strides[0] == 16 and all(s == 8 for s in strides[1:])
    packed = jaxpath.pack_rules_u16(tables.rules)
    assert packed is not None, "prototype assumes packed u16 rules"
    R5 = packed.shape[1] * 5
    packed2 = packed.reshape(packed.shape[0], R5)

    # joined rows: start with sentinel 0; dedupe by tidx (targets that
    # appear in many slots share one joined row)
    joined_rows = [np.zeros(2 + R5, np.uint16)]
    joined_of = {}  # tidx -> joined idx

    def joined_idx(tidx):
        j = joined_of.get(tidx)
        if j is None:
            j = len(joined_rows)
            row = np.empty(2 + R5, np.uint16)
            row[0] = (tidx + 1) & 0xFFFF
            row[1] = (tidx + 1) >> 16
            row[2:] = packed2[tidx]
            joined_rows.append(row)
            joined_of[tidx] = j
        return j

    nodes = []  # list of row lists; filled post-order-ish with patching

    def slots_of(level):
        return 1 << strides[level]

    import sys as _sys
    _sys.setrecursionlimit(100000)

    def emit(level, node_id, skip_len, skip_bits):
        """Emit the compressed node rooted at slot-trie (level, node_id),
        absorbing the given pending skip prefix.  Returns global id."""
        slots = slots_of(level)
        tbl = slot_levels[level]
        seg = tbl[node_id * slots:(node_id + 1) * slots]
        child = seg[:, 0]
        tgt = seg[:, 1]
        cmask = child != 0
        tmask = tgt > 0
        n_child = int(cmask.sum())
        n_tgt = int(tmask.sum())
        # chain compression: exactly one child, no targets, skip budget
        if (
            n_tgt == 0 and n_child == 1 and level + 1 < len(slot_levels)
            and skip_len + 8 <= MAX_SKIP
        ):
            nib = int(np.nonzero(cmask)[0][0])
            return emit(level + 1, int(child[nib]),
                        skip_len + 8, (skip_bits << 8) | nib)
        gid = len(nodes)
        nodes.append(None)  # reserve
        # children emitted in slot order, ids referenced via child_base +
        # rank; they are NOT contiguous here (prototype stores explicit
        # per-child ids in a side list and uses base=-rank trick instead)
        child_ids = []
        for nib in np.nonzero(cmask)[0]:
            if level + 1 < len(slot_levels):
                child_ids.append(emit(level + 1, int(child[nib]), 0, 0))
            else:
                child_ids.append(0)  # no deeper level: dead pointer
        # prototype contiguity: children were emitted depth-first so they
        # are NOT contiguous; re-emit via indirection is complex — instead
        # store children in a flat side array and make child_base point
        # into it (production would renumber; one extra u32 indirection
        # costs nothing here because the side array IS the id list)
        row = np.zeros(20, np.uint32)
        row[0] = 0  # child_base patched below
        row[2] = skip_len
        row[3] = skip_bits
        cb = np.packbits(cmask, bitorder="little")
        row[4:12] = np.ascontiguousarray(cb).view("<u4")
        tb = np.packbits(tmask, bitorder="little")
        row[12:20] = np.ascontiguousarray(tb).view("<u4")
        nodes[gid] = (row, child_ids,
                      [joined_idx(int(t) - 1) for t in tgt[tmask]])
        return gid

    # root level: per ifindex root node, 65536 slots
    n0 = slot_levels[0].shape[0] // 65536
    l0 = np.zeros((n0 * 65536, 2), np.int32)
    seg0 = slot_levels[0].reshape(-1, 2)
    for e0 in np.nonzero((seg0 != 0).any(axis=1))[0]:
        child, tgt = int(seg0[e0, 0]), int(seg0[e0, 1])
        if child and len(slot_levels) > 1:
            l0[e0, 0] = emit(1, child, 0, 0) + 1
        if tgt > 0:
            l0[e0, 1] = joined_idx(tgt - 1)

    # flatten: child lists -> contiguous via side array ("kids") with
    # child_base indexing kids, kids holding global ids.  The walk then
    # does nodes-row gather + kids gather (2 gathers/step).  Production
    # would renumber nodes so children are contiguous (1 gather/step);
    # for the prototype we emulate that cost by renumbering HERE.
    order = []          # new id -> old id, BFS so children contiguous
    kid_lists = {i: nodes[i][1] for i in range(len(nodes))}
    new_of = {}
    from collections import deque
    dq = deque()
    for e0 in np.nonzero(l0[:, 0])[0]:
        old = l0[e0, 0] - 1
        if old not in new_of:
            new_of[old] = len(order)
            order.append(old)
            dq.append(old)
    while dq:
        old = dq.popleft()
        for k in kid_lists[old]:
            if k not in new_of:
                new_of[k] = len(order)
                order.append(k)
                dq.append(k)
    # BFS does NOT guarantee a node's children contiguous if shared —
    # but this trie is a tree (each node one parent), and BFS emits each
    # parent's children as one consecutive run. true.
    N = len(order)
    node_arr = np.zeros((max(N, 1), 20), np.uint32)
    tgt_parts = []
    tbase = 0
    # target_base: global offsets into a flat per-node target list
    for new_id, old in enumerate(order):
        row, child_ids, tgt_joined = nodes[old]
        r = row.copy()
        if child_ids:
            r[0] = new_of[child_ids[0]]
            # assert contiguity
            for k, cid in enumerate(child_ids):
                assert new_of[cid] == r[0] + k, "children not contiguous"
        r[1] = tbase
        tbase += len(tgt_joined)
        tgt_parts.extend(tgt_joined)
        node_arr[new_id] = r
    tgt_arr = np.asarray(tgt_parts if tgt_parts else [0], np.int32)
    l0n = l0.copy()
    nz = l0[:, 0] > 0
    l0n[nz, 0] = np.vectorize(lambda o: new_of[o] + 1)(l0[nz, 0] - 1)

    joined = np.stack(joined_rows)
    # depth: longest step chain
    def depth(gid):
        _row, kids, _t = nodes[gid]
        return 1 + (max((depth(k) for k in kids), default=0))
    d_max = max((depth(l0[e0, 0] - 1)
                 for e0 in np.nonzero(l0[:, 0])[0]), default=0)
    return l0n, node_arr, tgt_arr, joined, d_max


def extract_bits(ip_words, pos, n):
    """(B,) values of the n bits at absolute bit offset pos (pos, n
    dynamic per lane, n <= 32, window spans <= 2 words) of the 128-bit
    address (4 big-endian u32 words, bit 0 = MSB of word 0).  Pure u32
    math — JAX without x64 silently narrows 64-bit dtypes."""
    w = jnp.clip(pos >> 5, 0, 4).astype(jnp.int32)
    # word pick as pure-VPU selects (a take_along_axis here lowers to a
    # real per-lane gather op per step — measured ~10x slower)
    zeros = jnp.zeros_like(ip_words[:, 0])

    def pick(widx):
        out = zeros
        for k in range(4):
            out = jnp.where(widx == k, ip_words[:, k], out)
        return out

    lo = pick(w).astype(jnp.uint32)
    hi = pick(w + 1).astype(jnp.uint32)
    off = (pos & 31).astype(jnp.uint32)
    n = n.astype(jnp.uint32)
    # top32 = first 32 bits of the window starting at bit `off` of lo
    hi_part = jnp.where(off == 0, jnp.uint32(0), hi >> (jnp.uint32(32) - off))
    top32 = (lo << off) | hi_part
    out = jnp.where(n == 0, jnp.uint32(0), top32 >> (jnp.uint32(32) - n))
    return out


def cwalk(l0, nodes, tgts, joined, root_lut, batch, d_max, cap_override=None):
    """The compressed walk; returns (B, 2+R*5) joined rows."""
    lut_size = root_lut.shape[0]
    if_ok = (batch.ifindex >= 0) & (batch.ifindex < lut_size)
    root = jnp.where(
        if_ok, jnp.take(root_lut, jnp.clip(batch.ifindex, 0, lut_size - 1)), 0)
    nib0 = (batch.ip_words[:, 0] >> np.uint32(16)).astype(jnp.int32)
    e0 = root * 65536 + nib0
    in0 = (e0 >= 0) & (e0 < l0.shape[0])
    rows0 = jnp.take(l0, e0, axis=0, mode="clip")
    root_j = jnp.where(in0 & (rows0[:, 1] > 0), rows0[:, 1], 0)  # joined idx
    alive = in0 & (rows0[:, 0] > 0)
    node = jnp.where(alive, rows0[:, 0] - 1, 0)
    pos = jnp.full_like(node, 16)
    cap_bits = jnp.where(batch.kind == KIND_IPV4, 32, 128)
    widx8 = jnp.arange(8, dtype=jnp.uint32)[None, :]
    # deep win: position into the flat per-node target list, resolved to
    # a joined idx ONCE after the loop (one gather, like the original)
    win_tpos = jnp.zeros_like(node)
    win_ok = jnp.zeros_like(alive)

    static_pos = 16
    for _step in range(d_max):
        in_n = (node >= 0) & (node < nodes.shape[0])
        alive = alive & in_n
        r = jnp.take(nodes, node, axis=0, mode="clip")
        skip_len = r[:, 2]
        if MODE == 'nocompress_static':
            # all skips are 0 by construction; static per-step position
            bit_start = static_pos
            w32 = bit_start // 32
            shift = 32 - 8 - (bit_start % 32)
            nib = ((batch.ip_words[:, w32] >> np.uint32(shift))
                   & np.uint32(0xFF)).astype(jnp.int32) if w32 < 4 else jnp.zeros_like(node)
            static_pos += 8
            pos = pos + 8
        else:
            skip_ok = jnp.where(
                skip_len > 0,
                extract_bits(batch.ip_words, pos, skip_len) == r[:, 3],
                True,
            )
            alive = alive & skip_ok
            pos = pos + skip_len.astype(jnp.int32)
            nib = extract_bits(
                batch.ip_words, pos, jnp.full_like(pos, 8)).astype(jnp.int32)
            pos = pos + 8
        w = (nib >> 5)[:, None].astype(jnp.uint32)
        below = (np.uint32(1) << (nib & 31).astype(jnp.uint32)) - 1
        cb = r[:, 4:12]
        tb = r[:, 12:20]
        pc_tb = jaxpath._popcount32(tb)
        pc_cb = jaxpath._popcount32(cb)
        prefix = jnp.sum(jnp.where(widx8 < w, pc_cb, 0), axis=1)
        tprefix = jnp.sum(jnp.where(widx8 < w, pc_tb, 0), axis=1)
        cw = jnp.sum(jnp.where(widx8 == w, cb, 0), axis=1)
        tw = jnp.sum(jnp.where(widx8 == w, tb, 0), axis=1)
        bit = (nib & 31).astype(jnp.uint32)
        ok_t = alive & (((tw >> bit) & 1) > 0) & (pos <= cap_bits)
        tpos = (r[:, 1] + tprefix + jaxpath._popcount32(tw & below)).astype(jnp.int32)
        win_tpos = jnp.where(ok_t, tpos, win_tpos)
        win_ok = win_ok | ok_t
        alive = alive & (((cw >> bit) & 1) > 0)
        node = jnp.where(
            alive, (r[:, 0] + prefix + jaxpath._popcount32(cw & below)).astype(jnp.int32), 0)

    in_t = win_ok & (win_tpos >= 0) & (win_tpos < tgts.shape[0])
    deep_j = jnp.where(
        in_t,
        jnp.take(tgts, jnp.clip(win_tpos, 0, tgts.shape[0] - 1)),
        0,
    )
    best = jnp.where(in_t & (deep_j > 0), deep_j, root_j)
    in_b = (best >= 0) & (best < joined.shape[0])
    return jnp.take(joined, jnp.where(in_b, best, 0), axis=0, mode="clip")


def classify_c(l0, nodes, tgts, joined, root_lut, batch, d_max, R):
    rows = cwalk(l0, nodes, tgts, joined, root_lut, batch, d_max)
    rules = rows[:, 2:].reshape(rows.shape[0], R, 5)
    # joined row 0 is all-zero -> rid 0 -> UNDEF, so no extra masking
    result = jaxpath.rule_scan(rules, batch)
    return jaxpath.finalize(result, batch)


def main():
    on_tpu = jax_setup()
    n_entries, width = scale_args(sys.argv, 100_000, 2_000, on_tpu=on_tpu)
    rng = np.random.default_rng(2024)
    t0 = time.perf_counter()
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=width, ifindexes=(2, 3, 4))
    print(f"table build {time.perf_counter()-t0:.1f}s", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    l0, node_arr, tgt_arr, joined, d_max = build_cpoptrie(tables)
    print(f"cpoptrie build {time.perf_counter()-t0:.1f}s: "
          f"nodes={len(node_arr)} joined={len(joined)} tgts={len(tgt_arr)} "
          f"d_max={d_max} (levels was {len(tables.trie_levels)})",
          file=sys.stderr, flush=True)

    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    R = tables.rule_width

    dev = dict(
        l0=jax.device_put(l0), nodes=jax.device_put(node_arr),
        tgts=jax.device_put(tgt_arr), joined=jax.device_put(joined),
        root_lut=jax.device_put(tables.root_lut.astype(np.int32)),
    )
    dt = jaxpath.device_tables(tables)

    # bit-exactness vs the production trie path
    db_all = jaxpath.device_batch(batch)
    fn = jax.jit(lambda b: classify_c(
        dev["l0"], dev["nodes"], dev["tgts"], dev["joined"],
        dev["root_lut"], b, d_max, R))
    t0 = time.perf_counter()
    got = np.asarray(fn(db_all)[0])
    print(f"compile+first {time.perf_counter()-t0:.1f}s", file=sys.stderr, flush=True)
    ref = np.asarray(jaxpath.jitted_classify(True)(dt, db_all)[0])
    mism = np.nonzero(got != ref)[0]
    if len(mism):
        i = mism[0]
        print(f"MISMATCH {len(mism)}/{len(got)} first@{i}: got={got[i]:x} "
              f"ref={ref[i]:x} kind={batch.kind[i]} if={batch.ifindex[i]}",
              file=sys.stderr, flush=True)
        return 1
    print("bit-exact vs production trie classify OK", file=sys.stderr, flush=True)

    kinds = np.asarray(batch.kind)
    results = {}
    for name, sel in (("v4", kinds == KIND_IPV4), ("v6", kinds == KIND_IPV6)):
        idx = np.nonzero(sel)[0]
        db = jaxpath.device_batch(batch.take(idx))

        def step(_dt, b):
            res, _x, _s = classify_c(
                dev["l0"], dev["nodes"], dev["tgts"], dev["joined"],
                dev["root_lut"], b, d_max, R)
            return res

        results[f"cpoptrie {name}"] = chained_throughput(
            step, dt, db, len(idx), on_tpu, f"cpoptrie {name}")

    print("\n=== summary ===", file=sys.stderr, flush=True)
    for name, thr in results.items():
        print(f"{name}: {thr/1e6:.1f} M pkts/s ({1e9/thr:.1f} ns/pkt)",
              file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
