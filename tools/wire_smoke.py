#!/usr/bin/env python
"""Wire-codec smoke: a 10K-packet frames-file replay through the real
daemon ingest on CPU (JAX_PLATFORMS=cpu), with the delta+varint codec
engaged, verified bit-exact against the LPM oracle — plus a host codec
round-trip.  The `make wire-check` target runs this after the codec unit
suite; it is the fast local gate for wire-format changes (the full bench
replay tier is the recorded TPU measurement).

Exit 0 on success; any verdict mismatch, codec ineligibility on the
smoke corpus, or decode failure is fatal.
"""
import os
import sys
import tempfile
import time

from _common import setup_repo_path

setup_repo_path()

import numpy as np  # noqa: E402


def main() -> int:
    from infw import oracle, testing
    from infw.backend.tpu import TpuClassifier
    from infw.daemon import (
        Daemon, parse_frames_buf, read_frames_any, write_frames_file_v2,
    )
    from infw.obs.events import EventRing, EventsLogger
    from infw.obs.pcap import build_frames_bulk
    from infw.packets import decode_delta_host, encode_delta_wire

    rng = np.random.default_rng(2024)
    t0 = time.perf_counter()
    # > dense limit so the trie path (the codec's home) serves the table
    tables = testing.random_tables_fast(
        rng, n_entries=6000, width=4, ifindexes=(2, 3, 4))
    batch = testing.random_batch_fast(rng, tables, n_packets=10_000)
    fb = build_frames_bulk(
        batch.kind, batch.ip_words, batch.proto, batch.dst_port,
        batch.icmp_type, batch.icmp_code, l4_ok=batch.l4_ok)
    fb.ifindex = np.asarray(batch.ifindex, np.uint32)
    print(f"smoke: table+batch built in {time.perf_counter()-t0:.1f}s")

    # host codec round-trip on the replay corpus's v4 share
    v4 = batch.take(np.nonzero(np.asarray(batch.kind) != 2)[0])
    v4.ip_words[:, 1:] = 0
    w4 = v4.pack_wire_v4()
    enc = encode_delta_wire(w4)
    if enc is None:
        print("FAIL: delta codec ineligible on the smoke corpus")
        return 1
    cols = decode_delta_host(enc)
    if not (cols[7] == w4[enc.perm, 3]).all():
        print("FAIL: host codec round-trip mismatch")
        return 1
    print(f"smoke: codec round-trip OK "
          f"({enc.wire_bytes / enc.n:.2f} B/packet, "
          f"plan={'fixed' + str(enc.fixed_w) if enc.fixed_w else 'varint'})")

    clf = TpuClassifier(wire_codec="auto")
    clf.load_tables(tables)
    with tempfile.TemporaryDirectory(prefix="infw-wire-smoke-") as sd:
        d = Daemon.__new__(Daemon)  # ingest-only harness (bench.py pattern)
        d.ingest_dir = os.path.join(sd, "ingest")
        d.out_dir = os.path.join(sd, "out")
        os.makedirs(d.ingest_dir)
        os.makedirs(d.out_dir)
        d.ingest_chunk = 4096
        d.pipeline_depth = 4
        d.max_tick_packets = 1 << 20
        d.debug_lookup = False
        d.h2d_overlap = True
        d.h2d_stage_depth = 2
        d.ring = EventRing(capacity=1 << 16)
        d.events_logger = EventsLogger(d.ring, lambda line: None)

        class _Syncer:
            classifier = clf

        d.syncer = _Syncer()
        path = os.path.join(d.ingest_dir, "smoke.frames")
        write_frames_file_v2(path, fb)
        parsed = parse_frames_buf(read_frames_any(path))
        t0 = time.perf_counter()
        done = d.process_ingest_once()
        dt = time.perf_counter() - t0
        if done != 1:
            print(f"FAIL: processed {done}/1 files")
            return 1
        stats = clf.wire_stats()
        if "delta" not in stats or stats["delta"][0] == 0:
            print(f"FAIL: delta codec never engaged (wire stats: {stats})")
            return 1
        rb = np.fromfile(
            os.path.join(d.out_dir, "smoke.frames.verdicts.bin"), dtype="<u4")
        ref = oracle.HashLpmOracle(tables).classify(parsed)
        if not (rb == ref.results).all():
            bad = int((rb != ref.results).sum())
            print(f"FAIL: {bad}/{len(rb)} verdicts differ from the oracle")
            return 1
        bpp = {k: round(v[1] / max(v[0], 1), 2) for k, v in stats.items()}
        print(f"smoke: 10K-packet replay OK in {dt:.1f}s "
              f"(wire bytes/packet by format: {bpp})")
    clf.close()
    print("wire-smoke PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
