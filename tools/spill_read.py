#!/usr/bin/env python
"""Spill consumer: reconstruct reference-format per-event text lines
from the binary deny-event spill (obs.events.BatchDenyRecord.SPILL_DTYPE).

The sustained-rate event path drains BatchDenyRecords as vectorized
binary rows (28B/event) precisely so the drain keeps up with the
classify rate — but the reference's event pipeline ends in
operator-readable per-event lines
(/root/reference/pkg/ebpf/ingress_node_firewall_events.go:110-166,
/root/reference/cmd/syslog/syslog.go:61-65), and until this tool the
only code that could read a spill back was a test (round-5 verdict
missing #1).  This decodes each row into the same line family the
per-record path emits: the header line
``ruleId N action X len L if NAME`` plus the address and L4 detail lines
the spill's columns can reconstruct (src address, dst port, ICMP
type/code; the frame-derived dst address and src port exist only on the
sub-threshold per-record path, which captures raw frame bytes).

Usage:
    python tools/spill_read.py <spill-file> [--iface-names 2=eth0,3=eth1]
    python tools/spill_read.py <spill-file> --follow   # tail -f style
    make spill-read SPILL=path/to/deny-events.bin

Reads in bounded chunks, so multi-GB spills stream in constant memory.
"""
from __future__ import annotations

import argparse
import ipaddress
import os
import sys
import time
from typing import Dict, Iterator, List

import numpy as np

from _common import setup_repo_path

setup_repo_path()

from infw.constants import (  # noqa: E402
    DENY,
    IPPROTO_ICMP,
    IPPROTO_ICMPV6,
    IPPROTO_SCTP,
    IPPROTO_TCP,
    IPPROTO_UDP,
    KIND_IPV4,
    KIND_IPV6,
    XDP_DROP,
    XDP_PASS,
)
from infw.obs.events import (  # noqa: E402
    BatchDenyRecord,
    convert_xdp_action_to_string,
)

_PROTO_NAMES = {IPPROTO_TCP: "tcp", IPPROTO_UDP: "udp", IPPROTO_SCTP: "sctp"}


def decode_spill_rows(
    rows: np.ndarray, iface_names: Dict[int, str] | None = None
) -> List[str]:
    """SPILL_DTYPE rows -> the reference-format event lines.

    Line shapes match obs.events.decode_event_lines for the fields the
    spill carries: header, then ``\\tipv4/ipv6 src addr A``, then
    ``\\ttcp/udp/sctp dstPort P`` or ``\\ticmpv4/icmpv6 type T code C``."""
    iface_names = iface_names or {}
    lines: List[str] = []
    rid = (rows["result"].astype(np.int64) >> 8) & 0xFFFFFF
    act = rows["result"].astype(np.int64) & 0xFF
    for i in range(len(rows)):
        r = rows[i]
        name = iface_names.get(int(r["ifindex"]), "?")
        xdp = XDP_DROP if act[i] == DENY else XDP_PASS
        lines.append(
            f"ruleId {int(rid[i])} action "
            f"{convert_xdp_action_to_string(xdp)} "
            f"len {int(r['pkt_len'])} if {name}"
        )
        kind = int(r["kind"])
        src_bytes = bytes(r["src"])
        if kind == KIND_IPV4:
            src = ".".join(str(b) for b in src_bytes[:4])
            lines.append(f"\tipv4 src addr {src}")
        elif kind == KIND_IPV6:
            src = str(ipaddress.IPv6Address(src_bytes))
            lines.append(f"\tipv6 src addr {src}")
        proto = int(r["proto"])
        if proto in _PROTO_NAMES:
            lines.append(
                f"\t{_PROTO_NAMES[proto]} dstPort {int(r['dst_port'])}"
            )
        elif proto == IPPROTO_ICMP:
            lines.append(
                f"\ticmpv4 type {int(r['icmp_type'])} "
                f"code {int(r['icmp_code'])}"
            )
        elif proto == IPPROTO_ICMPV6:
            lines.append(
                f"\ticmpv6 type {int(r['icmp_type'])} "
                f"code {int(r['icmp_code'])}"
            )
    return lines


def iter_spill_chunks(
    path: str, chunk_rows: int = 65536, follow: bool = False,
    poll_s: float = 0.2,
) -> Iterator[np.ndarray]:
    """Stream SPILL_DTYPE rows in bounded chunks; ``follow`` keeps
    polling for appended rows (the sidecar-tail posture).  A trailing
    partial row (a writer mid-append) is left for the next read."""
    row_b = BatchDenyRecord.SPILL_DTYPE.itemsize
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk_rows * row_b)
            usable = len(buf) - (len(buf) % row_b)
            if usable:
                yield np.frombuffer(
                    buf[:usable], BatchDenyRecord.SPILL_DTYPE
                )
            if len(buf) % row_b:
                f.seek(-(len(buf) % row_b), os.SEEK_CUR)
            if len(buf) < chunk_rows * row_b:
                if not follow:
                    return
                time.sleep(poll_s)


def _parse_iface_names(spec: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for part in filter(None, spec.split(",")):
        idx, _, name = part.partition("=")
        out[int(idx)] = name or "?"
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="spill_read",
        description="decode a binary deny-event spill into "
        "reference-format event lines",
    )
    p.add_argument("spill", help="path to the SPILL_DTYPE binary file")
    p.add_argument(
        "--iface-names", default="",
        help="ifindex=name[,ifindex=name...] mapping for the `if NAME` "
        "field (unknown indices print `?`, matching the events logger)",
    )
    p.add_argument("--follow", action="store_true",
                   help="keep polling for appended events (tail -f)")
    p.add_argument("--count", action="store_true",
                   help="print only the decoded event count")
    args = p.parse_args(argv)
    names = _parse_iface_names(args.iface_names)
    n = 0
    try:
        for rows in iter_spill_chunks(args.spill, follow=args.follow):
            n += len(rows)
            if args.count:
                continue
            sys.stdout.write(
                "\n".join(decode_spill_rows(rows, names)) + "\n"
            )
    except KeyboardInterrupt:
        pass
    if args.count:
        print(n)
    else:
        print(f"decoded {n} events", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
