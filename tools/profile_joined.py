#!/usr/bin/env python
"""Measure the production classify with the joined-targets walk vs the
legacy two-gather walk, per family, at the 100K tier."""
import sys

from _common import jax_setup, scale_args, setup_repo_path

setup_repo_path()

import numpy as np
import jax

from infw import testing
from infw.constants import KIND_IPV4, KIND_IPV6
from infw.kernels import jaxpath

from bench import chained_throughput


def main():
    on_tpu = jax_setup()
    n_entries, width = scale_args(sys.argv, 100_000, 2_000, on_tpu=on_tpu)
    rng = np.random.default_rng(2024)
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=width, ifindexes=(2, 3, 4))
    dt = jaxpath.device_tables(tables)
    built = jaxpath.build_joined(tables)
    print(f"joined active={built is not None} "
          f"rows={dt.joined.shape} targets={dt.trie_targets.shape}",
          file=sys.stderr, flush=True)
    dt_legacy = dt._replace(joined=jax.device_put(np.zeros((1, 1), np.uint16)))

    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    kinds = np.asarray(batch.kind)

    def full(tabs, b):
        res, _x, _s = jaxpath.classify(tabs, b, use_trie=True)
        return res

    results = {}
    for fam, sel in (("v4", kinds == KIND_IPV4), ("v6", kinds == KIND_IPV6)):
        idx = np.nonzero(sel)[0]
        db = jaxpath.device_batch(batch.take(idx))
        for name, tabs in (("joined", dt), ("legacy", dt_legacy)):
            t = tabs
            if fam == "v4":
                depth = jaxpath.v4_trie_depth(len(t.trie_levels))
                t = t._replace(trie_levels=t.trie_levels[:depth])
            key = f"{fam} {name}"
            try:
                results[key] = chained_throughput(
                    full, t, db, len(idx), on_tpu, key)
            except Exception as e:
                print(f"{key} FAILED: {e}", file=sys.stderr, flush=True)

    print("\n=== summary ===", file=sys.stderr, flush=True)
    for name, thr in results.items():
        print(f"{name}: {thr/1e6:.1f} M pkts/s ({1e9/thr:.1f} ns/pkt)",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
