#!/usr/bin/env python
"""Component isolation for the trie classify path (round-5 ask #1).

The round-4 profiling pinned 'walk alone 64.5 M pkts/s, walk+rules
17.8 M @100K' but never separated the rules GATHER from the scan's
TRANSPOSE from the scan arithmetic.  This script times, with the bench's
chained-loop methodology (results feed back into ip words + ports so
nothing hoists), the cumulative stages:

  A  walk only                       (tidx as the chained result)
  B  walk + rules gather + fold      (gather forced, no transpose/scan)
  C  walk + gather + transpose+fold  (adds the (B,R,5)->(5,R,B) transpose)
  D  walk + gather + full scan       (current classify, minus finalize)
  E  full classify                   (with finalize/stats)
  F  D but scan in B-major layout    (transpose-free scan variant)
  G  B with rules pre-flattened (T, R*5) u16 row gather
  H  B with rules padded to (T, 128) u16 rows (lane-aligned gather)

Run on the real chip: python tools/profile_trie.py [n_entries] [width]
"""
import sys

from _common import jax_setup, scale_args, setup_repo_path

setup_repo_path()

import numpy as np
import jax
import jax.numpy as jnp

from infw import testing
from infw.constants import IPPROTO_ICMP, IPPROTO_ICMPV6, IPPROTO_SCTP, IPPROTO_TCP, IPPROTO_UDP, KIND_IPV4
from infw.kernels import jaxpath

from bench import chained_throughput


def rule_scan_bmajor(rows, batch):
    """Transpose-free ordered first-match scan: same semantics as
    jaxpath.rule_scan but operating in (B, R) orientation — packets ride
    sublanes, rules ride lanes; no (B,R,5)->(5,R,B) shuffle."""
    r = rows.astype(jnp.int32)  # (B, R, 5)
    rid = r[:, :, 0] & 0xFF
    act = r[:, :, 0] >> 8
    rproto = r[:, :, 1] & 0xFF
    it = r[:, :, 1] >> 8
    ic = r[:, :, 2]
    ps = r[:, :, 3]
    pe = r[:, :, 4]
    proto = batch.proto[:, None]
    dport = batch.dst_port[:, None]
    valid = rid != 0
    proto_eq = (rproto != 0) & (rproto == proto)
    is_transport = (
        (rproto == IPPROTO_TCP) | (rproto == IPPROTO_UDP) | (rproto == IPPROTO_SCTP)
    )
    port_hit = jnp.where(pe == 0, dport == ps, (dport >= ps) & (dport < pe))
    fam = jnp.where(batch.kind == KIND_IPV4, IPPROTO_ICMP, IPPROTO_ICMPV6)[:, None]
    icmp_hit = (
        (rproto == fam) & (it == batch.icmp_type[:, None]) & (ic == batch.icmp_code[:, None])
    )
    hit = valid & ((proto_eq & ((is_transport & port_hit) | icmp_hit)) | (rproto == 0))
    R = rid.shape[1]
    idx = jnp.arange(R, dtype=jnp.int32)[None, :]
    first = jnp.min(jnp.where(hit, idx, R), axis=1)
    any_hit = first < R
    sel = hit & (idx == first[:, None])
    rid_f = jnp.sum(jnp.where(sel, rid, 0), axis=1)
    act_f = jnp.sum(jnp.where(sel, act, 0), axis=1)
    return jnp.where(
        any_hit,
        ((rid_f.astype(jnp.uint32) & 0xFFFFFF) << 8) | (act_f.astype(jnp.uint32) & 0xFF),
        0,
    ).astype(jnp.uint32)


def main():
    on_tpu = jax_setup()
    n_entries, width = scale_args(sys.argv, 100_000, 2_000, on_tpu=on_tpu)
    print(f"backend={jax.default_backend()} entries={n_entries} width={width}",
          file=sys.stderr, flush=True)
    rng = np.random.default_rng(2024)
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=width, ifindexes=(2, 3, 4))
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    # v4-only sub-batch with truncated walk depth — the family the daemon
    # actually steers; keeps every variant on identical work
    kinds = np.asarray(batch.kind)
    idx = np.nonzero(kinds == KIND_IPV4)[0]
    sub = batch.take(idx)
    db = jaxpath.device_batch(sub)
    dt = jaxpath.device_tables(tables)
    depth = jaxpath.v4_trie_depth(len(dt.trie_levels))
    dtv4 = dt._replace(trie_levels=dt.trie_levels[:depth])
    n = len(idx)
    print(f"v4 sub-batch {n} packets, walk depth {depth}", file=sys.stderr, flush=True)

    # pre-built alternate rule layouts
    rules_np = np.asarray(dt.rules)  # (T, R, 5) u16
    T, R, _ = rules_np.shape
    rules_flat = jax.device_put(rules_np.reshape(T, R * 5))
    rules_pad = np.zeros((T, 128), np.uint16)
    rules_pad[:, : R * 5] = rules_np.reshape(T, R * 5)
    rules_pad = jax.device_put(rules_pad)

    def fold16(x):  # cheap consume: (B, ...) u16 -> (B,) u32, forces the gather
        return jnp.sum(x.astype(jnp.uint32), axis=tuple(range(1, x.ndim)))

    def walk_only(tabs, b):
        return jaxpath.lpm_trie(tabs, b).astype(jnp.uint32)

    def walk_gather(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(tabs.rules, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None, None], rows, 0)
        return fold16(rows) + tidx.astype(jnp.uint32)

    def walk_gather_t(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(tabs.rules, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None, None], rows, 0)
        s = jnp.transpose(rows.astype(jnp.int32), (2, 1, 0))
        return jnp.sum(s.astype(jnp.uint32), axis=(0, 1)) + tidx.astype(jnp.uint32)

    def walk_scan(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(tabs.rules, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None, None], rows, 0)
        return jaxpath.rule_scan(rows, b)

    def full(tabs, b):
        res, _x, _s = jaxpath.classify(tabs, b, use_trie=True)
        return res

    def walk_scan_bmajor(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(tabs.rules, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None, None], rows, 0)
        return rule_scan_bmajor(rows, b)

    def gather_flat(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(rules_flat, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None], rows, 0)
        return fold16(rows) + tidx.astype(jnp.uint32)

    def gather_pad128(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(rules_pad, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None], rows, 0)
        return fold16(rows) + tidx.astype(jnp.uint32)

    variants = [
        ("A walk only", walk_only),
        ("B walk+gather+fold", walk_gather),
        ("C walk+gather+transpose", walk_gather_t),
        ("D walk+gather+scan", walk_scan),
        ("E full classify", full),
        ("F walk+gather+scan(Bmajor)", walk_scan_bmajor),
        ("G gather (T,R*5) flat", gather_flat),
        ("H gather (T,128) pad", gather_pad128),
    ]
    results = {}
    for name, fn in variants:
        try:
            thr = chained_throughput(fn, dtv4, db, n, on_tpu, name)
            results[name] = thr
        except Exception as e:
            print(f"{name} FAILED: {e}", file=sys.stderr, flush=True)
    print("\n=== summary ===", file=sys.stderr, flush=True)
    for name, thr in results.items():
        print(f"{name}: {thr/1e6:.1f} M pkts/s ({1e9/thr:.1f} ns/pkt)",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
