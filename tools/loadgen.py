#!/usr/bin/env python
"""Open-loop Poisson/burst load generator for the daemon's ingest dir.

Drives a RUNNING daemon the way the SLO tier (bench_slo) drives the
in-process scheduler: packets are scheduled by an arrival process
(``infw.testing.poisson_arrivals`` / ``burst_arrivals``) at a fixed
offered load, grouped into frames files of ``--file-packets`` packets,
and each file is dropped into ``<state-dir>/ingest/`` at its FIRST
packet's scheduled arrival time.

Open-loop discipline (the coordinated-omission rule): the drop schedule
is computed up front against one anchor timestamp and each write sleeps
until its ABSOLUTE scheduled time — never "write, then sleep the
interval" — so a slow consumer (or a slow writer) makes the generator
fall visibly behind schedule (reported at exit) instead of silently
stretching the offered load.  A closed-loop generator that paces off its
own completions would hide exactly the queueing a latency SLO exists to
measure.

The packet mix is synthetic (uniform random v4/v6 addresses and
protocols — deny rate depends on the loaded ruleset); determinism per
``--seed`` covers addresses, ports AND arrival times, so two runs offer
byte-identical streams on identical schedules.

Usage:
    python tools/loadgen.py --out <state-dir>/ingest --rate 100000 \\
        --n 1000000 [--burst 256] [--file-packets 4096] [--seed 7] \\
        [--ifindex 10] [--v6-fraction 0.3] [--dry-run]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from _common import setup_repo_path

setup_repo_path()

from infw import testing  # noqa: E402
from infw.daemon import write_frames_file_v2  # noqa: E402
from infw.obs.pcap import FramesBuf, build_frames_bulk  # noqa: E402


def synth_columns(rng: np.random.Generator, n: int, v6_fraction: float,
                  established_fraction: float = 0.0,
                  file_packets: int = 4096):
    """Uniform synthetic packet columns (no table bias — loadgen does
    not know the daemon's ruleset), flow-pool expanded.

    ``established_fraction`` > 0 switches on flow locality: the columns
    draw from a flow pool via the chunk-aware assignment
    (infw.testing.flow_locality_fids, chunked at ``file_packets`` so
    one dropped frames file / ring record is the cache's insert
    granularity) — the hit-rate-ladder workload for a daemon running
    --flow-table.  Byte-deterministic per (seed, arguments)."""
    if established_fraction > 0.0:
        fid, _fresh, n_flows = testing.flow_locality_fids(
            rng, n, established_fraction, chunk_packets=file_packets
        )
    else:
        fid = np.arange(n)
        n_flows = n
    kind = np.where(
        rng.random(n_flows) < v6_fraction, 2, 1
    ).astype(np.int32)
    ip = rng.integers(0, 256, (n_flows, 16), dtype=np.uint8)
    ip[kind == 1, 4:] = 0
    ip_words = np.ascontiguousarray(ip).view(">u4").astype(np.uint32)
    ip_words = ip_words.reshape(n_flows, 4)
    proto = np.asarray([6, 17, 132, 1, 58], np.int32)[
        rng.integers(0, 5, n_flows)
    ]
    dst_port = rng.integers(0, 65536, n_flows).astype(np.int32)
    icmp_type = rng.integers(0, 256, n_flows).astype(np.int32)
    icmp_code = rng.integers(0, 3, n_flows).astype(np.int32)
    return {
        "kind": kind[fid], "ip_words": ip_words[fid], "proto": proto[fid],
        "dst_port": dst_port[fid], "icmp_type": icmp_type[fid],
        "icmp_code": icmp_code[fid],
    }, n_flows


def synth_payload(rng: np.random.Generator, n: int, shape: str,
                  plen: int, pattern_seed: int, n_patterns: int,
                  attack_fraction: float, file_packets: int):
    """Payload-prefix columns for the --ring producer (ISSUE-19):
    ``http`` is the benign HTTP-ish request mix
    (infw.payload.benign_payloads); ``attack-mix`` overwrites a seeded
    fraction of lanes with signature-bearing prefixes
    (infw.payload.attack_payloads) drawn from the SAME deterministic
    pattern set a daemon gets from ``--payload <n_patterns>`` at the
    same seed — so the measuring side knows exactly which lanes must
    match.  Returns (pay (n, plen) uint8, plens (n,) int32, meta);
    the meta carries per-record ground-truth label bitmaps in the
    attack-label encoding (decode_attack_labels) plus the pattern-set
    coordinates.  Byte-deterministic per (seeded rng, arguments);
    the header stream is untouched (payload rides beside the wire)."""
    from infw.payload import (
        attack_payloads,
        benign_payloads,
        signature_patterns,
    )

    pay, plens = benign_payloads(rng, n, plen=plen)
    meta = {
        "payload_shape": shape,
        "payload_prefix_bytes": int(plen),
        # ring-record cost per lane: the uint8 prefix column plus the
        # int32 valid-byte count word
        "payload_bytes_per_packet": int(plen) + 4,
        "payload_pattern_seed": int(pattern_seed),
        "payload_patterns": int(n_patterns),
    }
    if shape == "attack-mix":
        pats = signature_patterns(
            np.random.default_rng(pattern_seed), n_patterns, plen=plen
        )
        mask = rng.random(n) < float(attack_fraction)
        k = int(mask.sum())
        if k:
            apay, alens = attack_payloads(rng, k, pats, plen=plen)
            pay[mask] = apay
            plens[mask] = alens
        meta["payload_signature_packets"] = k
        # same per-record hex-bitmap label encoding as the header
        # attacks: which lanes the generator planted signatures in
        # (decode_attack_labels recovers the mask).  NOTE ~15% of the
        # planted lanes deliberately straddle the truncation boundary
        # and must NOT match — the label marks "signature-bearing",
        # not "must match"; exact match truth is the host oracle
        # (infw.backend.cpu_ref.payload_match_ref) over these columns.
        meta["payload_labels"] = {
            "record_bitmaps_hex": encode_attack_labels(
                mask, file_packets
            ),
        }
    return pay, np.asarray(plens, np.int32), meta


def encode_attack_labels(mask: np.ndarray, file_packets: int) -> list:
    """Per-record ground-truth label bitmaps: the (n,) bool attack-lane
    mask packed little-bit-first per record window and hex-encoded —
    one string per dropped file / ring record, so a measuring consumer
    scores precision/recall against EXACTLY the lanes the generator
    overwrote (not just "packets from attacker IPs", which undercounts
    when an attacker address collides with background traffic).
    Byte-deterministic: same mask -> same strings."""
    mask = np.asarray(mask, bool)
    fp = max(int(file_packets), 1)
    out = []
    for lo in range(0, len(mask), fp):
        out.append(np.packbits(
            mask[lo : lo + fp], bitorder="little"
        ).tobytes().hex())
    return out


def decode_attack_labels(hex_bitmaps: list, n: int,
                         file_packets: int) -> np.ndarray:
    """Inverse of encode_attack_labels -> the (n,) bool mask."""
    fp = max(int(file_packets), 1)
    mask = np.zeros(n, bool)
    for i, h in enumerate(hex_bitmaps):
        lo = i * fp
        hi = min(lo + fp, n)
        bits = np.unpackbits(
            np.frombuffer(bytes.fromhex(h), np.uint8), bitorder="little"
        )[: hi - lo]
        mask[lo:hi] = bits.astype(bool)
    return mask


def attack_lane_src_ids(mask: np.ndarray, n_src: int) -> np.ndarray:
    """(n,) int32 attacker id per lane (-1 = background): attack lanes
    take source index (position in the attack sequence) % n_src — the
    deterministic assignment inject_attack uses, exposed so consumers
    can attribute each labeled lane to its attacker address without
    re-running the generator."""
    mask = np.asarray(mask, bool)
    ids = np.full(len(mask), -1, np.int32)
    idx = np.nonzero(mask)[0]
    ids[idx] = (np.arange(len(idx)) % max(int(n_src), 1)).astype(np.int32)
    return ids


def inject_attack(rng: np.random.Generator, c: dict, n: int, mode: str,
                  attack_fraction: float, attack_start: float,
                  n_attackers: int, file_packets: int):
    """Overwrite a seeded subset of the synthetic lanes with an attack
    (infw.testing.attack_trace_batch's modes, tables-free form): the
    attack begins at ``attack_start`` of the stream rounded down to a
    file/record boundary and claims ``attack_fraction`` of the lanes
    from then on.  Returns (tcp_flags, meta); byte-deterministic per
    (seeded rng, arguments).  Note deny verdicts depend on the DAEMON's
    loaded ruleset — the tables-free generator guarantees the top-talker
    / SYN-rate surfaces, and `--attack denystorm` aims every attack lane
    at one (src, dst_port) pair so a single deny rule covers it."""
    from infw.kernels.jaxpath import TCP_ACK, TCP_SYN

    cp = max(int(file_packets), 1)
    start = (int(n * float(attack_start)) // cp) * cp
    mask = (np.arange(n) >= start) & (
        rng.random(n) < float(attack_fraction)
    )
    k = int(mask.sum())
    n_src = 1 if mode == "portscan" else max(1, int(n_attackers))
    srcs = np.zeros((n_src, 4), np.uint32)
    srcs[:, 0] = rng.integers(1, 1 << 32, n_src, dtype=np.uint64)
    lane_src = np.arange(k) % n_src
    c["kind"][mask] = 1
    c["ip_words"][mask] = srcs[lane_src]
    c["proto"][mask] = 6
    c["icmp_type"][mask] = 0
    c["icmp_code"][mask] = 0
    flags = np.where(c["proto"] == 6, TCP_ACK, 0).astype(np.int32)
    if mode == "synflood":
        c["dst_port"][mask] = 443
        flags[mask] = TCP_SYN
    elif mode == "portscan":
        c["dst_port"][mask] = np.arange(k) % 65536
    else:  # denystorm: one (src, port) pair per attacker — rule-sized
        c["dst_port"][mask] = 80
    meta = {
        "attack": mode, "attack_start_packet": int(start),
        "attack_packets": k,
        "attackers": [
            ".".join(str(b) for b in int(s[0]).to_bytes(4, "big"))
            for s in srcs
        ],
        # per-RECORD ground-truth labels (ISSUE-14): honest
        # precision/recall needs per-lane truth, not just attacker IPs
        # — the onset record index, one hex bitmap of attack lanes per
        # dropped file / ring record (decode_attack_labels), and the
        # lane->attacker assignment stride (attack_lane_src_ids).
        # Features must never read these (benchruns/README.md label
        # discipline); they exist for the measuring consumer only.
        "labels": {
            "onset_record": int(start) // cp,
            "attack_src_stride": int(n_src),
            "record_bitmaps_hex": encode_attack_labels(mask, cp),
        },
    }
    return flags, meta


def synth_batch(rng: np.random.Generator, n: int, v6_fraction: float,
                ifindex: int, established_fraction: float = 0.0,
                file_packets: int = 4096, attack=None):
    """Synthetic columns -> frames buffer (the file-drop producer)."""
    c, n_flows = synth_columns(rng, n, v6_fraction,
                               established_fraction, file_packets)
    meta = {}
    if attack is not None:
        _flags, meta = inject_attack(
            rng, c, n, attack["mode"], attack["fraction"],
            attack["start"], attack["attackers"], file_packets,
        )
        # frames carry no TCP flag bytes (parse_frames_buf degrades
        # flags to 0) — SYN-rate telemetry needs the --ring producer;
        # the top-talker / deny-storm surfaces work on either path
    fb = build_frames_bulk(c["kind"], c["ip_words"], c["proto"],
                           c["dst_port"], c["icmp_type"], c["icmp_code"])
    fb.ifindex = np.full(n, int(ifindex), np.uint32)
    return fb, n_flows, meta


def synth_wire_batch(rng: np.random.Generator, n: int, v6_fraction: float,
                     ifindex: int, established_fraction: float = 0.0,
                     file_packets: int = 4096, attack=None):
    """Synthetic columns -> PacketBatch (the --ring producer: packed
    wire records, no frames round-trip).  pkt_len is synthesized
    deterministically; every synthetic proto is l4-parseable.  With
    ``attack``, the batch carries the injected TCP flags column (the
    ring record format ships it, so pure-SYN floods reach the daemon's
    flow/telemetry tiers intact)."""
    from infw.packets import PacketBatch

    c, n_flows = synth_columns(rng, n, v6_fraction,
                               established_fraction, file_packets)
    meta = {}
    flags = None
    if attack is not None:
        flags, meta = inject_attack(
            rng, c, n, attack["mode"], attack["fraction"],
            attack["start"], attack["attackers"], file_packets,
        )
    batch = PacketBatch(
        kind=c["kind"],
        l4_ok=np.ones(n, np.int32),
        ifindex=np.full(n, int(ifindex), np.int32),
        ip_words=np.ascontiguousarray(c["ip_words"], np.uint32),
        proto=c["proto"],
        dst_port=c["dst_port"],
        icmp_type=c["icmp_type"],
        icmp_code=c["icmp_code"],
        pkt_len=rng.integers(60, 1500, n).astype(np.int32),
    )
    if flags is not None:
        batch.tcp_flags = flags
    return batch, n_flows, meta


def _ring_main(args, rng, offs) -> int:
    """Ring producer: one packed-wire record per --file-packets window,
    written IN PLACE into the daemon's shared-memory ingest ring at its
    first packet's scheduled arrival time (open-loop; a full ring blocks
    and the stall is reported as schedule lag, never silently absorbed
    into a stretched offered load)."""
    from infw.ring import IngestRing

    batch, n_flows, attack_meta = synth_wire_batch(
        rng, args.n, args.v6_fraction, args.ifindex,
        established_fraction=args.established_fraction,
        file_packets=args.file_packets, attack=_attack_dict(args),
    )
    pay = plens = None
    payload_meta = {}
    if args.payload != "none":
        # a CHILD rng keyed off --seed: the header stream stays
        # byte-identical to the same run with --payload none (payload
        # rides beside the wire, never perturbs it)
        pay, plens, payload_meta = synth_payload(
            np.random.default_rng([args.seed, 0x7061796C]), args.n,
            args.payload, args.payload_plen, args.payload_seed,
            args.payload_patterns, args.payload_attack_fraction,
            args.file_packets,
        )
    fp = int(args.file_packets)
    n_rec = -(-args.n // fp)
    rec_starts = offs[::fp][:n_rec]
    summary = {
        "n": int(args.n), "rate_pps": float(args.rate),
        "process": f"burst:{args.burst}" if args.burst > 0 else "poisson",
        "mode": "ring", "records": int(n_rec), "file_packets": fp,
        "duration_s": float(offs[-1]), "seed": int(args.seed),
        "established_fraction": float(args.established_fraction),
        "n_flows": int(n_flows), **attack_meta, **payload_meta,
    }
    print(json.dumps(summary), flush=True)
    if args.dry_run:
        return 0
    ring = IngestRing.attach(args.ring)
    t0 = time.monotonic()
    worst_lag = 0.0
    # two DIFFERENT failure signals an open-loop producer must not
    # conflate: time reserve() spent blocked on a full ring is CONSUMER
    # backpressure (the daemon fell behind; by design the ring blocks
    # rather than drops), while lag beyond that is the PRODUCER falling
    # behind its own schedule (pack/copy too slow for --rate).  The
    # ring's blocked_us counter (this process's attach) provides the
    # split.
    worst_producer_lag = 0.0
    blocked_s = 0.0
    for i in range(n_rec):
        target = t0 + float(rec_starts[i])
        lag = time.monotonic() - target
        if lag < 0:
            time.sleep(-lag)
        else:
            worst_lag = max(worst_lag, lag)
            worst_producer_lag = max(worst_producer_lag, lag - blocked_s)
        lo, hi = i * fp, min((i + 1) * fp, args.n)
        # fused subset pack straight from the SoA columns, then one
        # in-place copy into the reserved (mapped) slot — the producer
        # allocates nothing per record beyond the pack scratch
        wire, v4_only = batch.pack_wire_subset(
            np.arange(lo, hi, dtype=np.int64)
        )
        flags = getattr(batch, "tcp_flags", None)
        if pay is None:
            wv, fl, token = ring.reserve(
                wire.shape[0], wire.shape[1],
                with_flags=flags is not None, timeout=30.0,
            )
        else:
            wv, fl, pv, lv, token = ring.reserve(
                wire.shape[0], wire.shape[1],
                with_flags=flags is not None,
                payload_width=pay.shape[1], timeout=30.0,
            )
            np.copyto(pv, pay[lo:hi])
            np.copyto(lv, plens[lo:hi])
        np.copyto(wv, wire)
        if fl is not None and flags is not None:
            np.copyto(fl, flags[lo:hi])
        ring.commit(token, v4_only=v4_only)
        blocked_s = ring.counter_values()["ring_blocked_us_total"] / 1e6
    done = time.monotonic() - t0
    print(json.dumps({
        "offered_duration_s": float(offs[-1]),
        "actual_duration_s": done,
        "worst_schedule_lag_s": worst_lag,
        "worst_producer_lag_s": worst_producer_lag,
        "ring_blocked_s": blocked_s,
        "ring_backpressured": blocked_s > 0.01,
        "fell_behind": worst_producer_lag > 0.01,
        **{k: int(v) for k, v in ring.counter_values().items()},
    }), flush=True)
    if blocked_s > 0.01:
        print("loadgen: WARNING ring backpressure blocked the producer "
              f"for {blocked_s*1e3:.1f} ms total (consumer fell behind) "
              "— offered load was lower than requested",
              file=sys.stderr)
    if worst_producer_lag > 0.01:
        print("loadgen: WARNING fell behind its open-loop schedule by "
              f"{worst_producer_lag*1e3:.1f} ms net of ring blocking "
              "(slow producer) — offered load was lower than requested",
              file=sys.stderr)
    return 0


def _attack_dict(args):
    if args.attack is None:
        return None
    return {"mode": args.attack, "fraction": args.attack_fraction,
            "start": args.attack_start, "attackers": args.attackers}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="infw-loadgen", description=__doc__)
    p.add_argument("--out", default=None,
                   help="ingest directory of the target daemon "
                        "(file-drop mode; exactly one of --out/--ring)")
    p.add_argument("--rate", type=float, required=True,
                   help="offered load, packets/second")
    p.add_argument("--n", type=int, required=True, help="total packets")
    p.add_argument("--burst", type=int, default=0,
                   help="burst size: >0 switches the arrival process "
                        "from Poisson to back-to-back bursts at the "
                        "same mean rate (testing.burst_arrivals)")
    p.add_argument("--file-packets", type=int, default=4096,
                   help="packets per dropped frames file")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--ifindex", type=int, default=10)
    p.add_argument("--v6-fraction", type=float, default=0.3)
    p.add_argument("--established-fraction", type=float, default=0.0,
                   help="flow locality: fraction of packets repeating a "
                        "flow from an earlier frames file (chunk-aware, "
                        "infw.testing.flow_locality_fids) — drive a "
                        "--flow-table daemon at a controlled hit rate")
    p.add_argument("--established-ladder", action="store_true",
                   help="emit the 0/50/90/99%% established-flow ladder: "
                        "four sub-directories <out>/ef00|ef50|ef90|ef99, "
                        "each a full manifest-disciplined drop schedule "
                        "at its rung's flow locality (byte-deterministic "
                        "per --seed)")
    p.add_argument("--ring", default=None,
                   help="RING PRODUCER MODE: instead of dropping frames "
                        "files, attach to a daemon's shared-memory "
                        "ingest ring (--ring on the daemon side, which "
                        "creates it) and write one PACKED WIRE record "
                        "per --file-packets window IN PLACE at its "
                        "scheduled time — no per-chunk file syscalls, "
                        "no per-chunk buffer allocation; a full ring "
                        "blocks (backpressure) and counts as schedule "
                        "lag.  Record format: see README 'Resident "
                        "serving'")
    p.add_argument("--attack", choices=("synflood", "portscan", "denystorm"),
                   default=None,
                   help="inject a seeded adversarial traffic mix (the "
                        "telemetry tier's workload, "
                        "infw.testing.attack_trace_batch modes): a "
                        "deterministic subset of lanes after "
                        "--attack-start becomes the attack.  synflood = "
                        "pure-SYN TCP from --attackers sources (SYN "
                        "flags ship in --ring mode; frames files carry "
                        "no flag bytes); portscan = one source sweeping "
                        "dst ports; denystorm = one (src, port 80) pair "
                        "per attacker, sized for a single deny rule on "
                        "the daemon side.  Manifest records mode, start "
                        "and attacker addresses")
    p.add_argument("--attack-fraction", type=float, default=0.4,
                   help="fraction of post-start lanes the attack claims "
                        "(default 0.4)")
    p.add_argument("--attack-start", type=float, default=0.25,
                   help="where the attack begins, as a fraction of the "
                        "stream, rounded down to a file/record boundary "
                        "(default 0.25)")
    p.add_argument("--attackers", type=int, default=2,
                   help="distinct attack sources (portscan always uses "
                        "1; default 2)")
    p.add_argument("--payload", choices=("none", "http", "attack-mix"),
                   default="none",
                   help="payload-prefix traffic shape (--ring mode "
                        "only; frames files carry no payload bytes): "
                        "http = benign HTTP-ish request prefixes "
                        "(infw.payload.benign_payloads); attack-mix = "
                        "the same plus a seeded "
                        "--payload-attack-fraction of lanes bearing "
                        "signatures from the deterministic pattern set "
                        "(--payload-seed/--payload-patterns — the same "
                        "set a daemon loads with --payload N at that "
                        "seed), labels in the manifest.  The target "
                        "daemon must run --payload so its ring slots "
                        "carry the column")
    p.add_argument("--payload-plen", type=int, default=64,
                   help="payload prefix bytes per packet (a "
                        "PAYLOAD_PREFIX_WIDTHS bucket: 64 or 128; "
                        "default 64); the manifest records the "
                        "resulting payload-column bytes/packet")
    p.add_argument("--payload-patterns", type=int, default=32,
                   help="signature pattern-set size for attack-mix "
                        "(default 32)")
    p.add_argument("--payload-seed", type=int, default=0,
                   help="pattern-set seed for attack-mix (default 0 — "
                        "matches the daemon's --payload default set)")
    p.add_argument("--payload-attack-fraction", type=float, default=0.1,
                   help="fraction of lanes carrying a planted "
                        "signature in attack-mix (default 0.1)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the schedule summary without writing or "
                        "sleeping")
    args = p.parse_args(argv)
    if not 0.0 <= args.attack_fraction <= 1.0:
        p.error("--attack-fraction must be in [0, 1]")
    if not 0.0 <= args.attack_start < 1.0:
        p.error("--attack-start must be in [0, 1)")
    if args.attackers < 1:
        p.error("--attackers must be >= 1")
    if args.rate <= 0 or args.n <= 0 or args.file_packets <= 0:
        p.error("--rate, --n and --file-packets must be positive")
    if not 0.0 <= args.established_fraction < 1.0:
        p.error("--established-fraction must be in [0, 1)")
    if (args.out is None) == (args.ring is None):
        p.error("exactly one of --out (file drops) or --ring (ring "
                "producer) is required")
    if args.payload != "none":
        if args.ring is None:
            p.error("--payload requires --ring (frames files carry no "
                    "payload bytes)")
        from infw.kernels.wire_decode import PAYLOAD_PREFIX_WIDTHS
        if args.payload_plen not in PAYLOAD_PREFIX_WIDTHS:
            p.error(f"--payload-plen must be one of "
                    f"{PAYLOAD_PREFIX_WIDTHS}")
        if args.payload_patterns < 1:
            p.error("--payload-patterns must be >= 1")
        if not 0.0 <= args.payload_attack_fraction <= 1.0:
            p.error("--payload-attack-fraction must be in [0, 1]")
    if args.ring and args.established_ladder:
        p.error("--established-ladder emits file-drop sub-runs; use "
                "--established-fraction with --ring")

    if args.established_ladder:
        # the hit-rate ladder: one full run per rung, each into its own
        # sub-directory with its own manifest (the measuring consumer
        # points a --flow-table daemon at one rung at a time)
        rc = 0
        base = list(argv) if argv is not None else sys.argv[1:]
        base = [a for i, a in enumerate(base)
                if a != "--established-ladder"
                and not (a == "--out" or (i > 0 and base[i - 1] == "--out"))
                and not a.startswith("--established-fraction")
                and not (i > 0 and base[i - 1] == "--established-fraction")]
        for ef in (0.0, 0.5, 0.9, 0.99):
            sub = os.path.join(args.out, f"ef{int(ef * 100):02d}")
            rc |= main(base + ["--out", sub,
                               "--established-fraction", str(ef)])
        return rc

    rng = np.random.default_rng(args.seed)
    if args.burst > 0:
        offs = testing.burst_arrivals(rng, args.rate, args.n,
                                      burst=args.burst)
    else:
        offs = testing.poisson_arrivals(rng, args.rate, args.n)
    if args.ring:
        return _ring_main(args, rng, offs)
    fb, n_flows, attack_meta = synth_batch(
        rng, args.n, args.v6_fraction, args.ifindex,
        established_fraction=args.established_fraction,
        file_packets=args.file_packets, attack=_attack_dict(args),
    )

    fp = int(args.file_packets)
    n_files = -(-args.n // fp)
    # each file drops at its FIRST packet's scheduled arrival; the
    # sidecar manifest records per-packet offsets so a measuring
    # consumer can reconstruct scheduled arrival times
    file_starts = offs[::fp][:n_files]
    summary = {
        "n": int(args.n), "rate_pps": float(args.rate),
        "process": f"burst:{args.burst}" if args.burst > 0 else "poisson",
        "files": int(n_files), "file_packets": fp,
        "duration_s": float(offs[-1]), "seed": int(args.seed),
        "established_fraction": float(args.established_fraction),
        "n_flows": int(n_flows), **attack_meta,
    }
    print(json.dumps(summary), flush=True)
    if args.dry_run:
        return 0

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "loadgen-manifest.json"), "w") as f:
        json.dump({**summary,
                   "file_start_offsets_s": [float(x) for x in file_starts]},
                  f)
    t0 = time.monotonic()
    worst_lag = 0.0
    for i in range(n_files):
        target = t0 + float(file_starts[i])
        lag = time.monotonic() - target
        if lag < 0:
            time.sleep(-lag)
        else:
            worst_lag = max(worst_lag, lag)
        # slice this file's window out of the contiguous frames buffer
        # (three array slices, no per-frame Python)
        lo = i * fp
        hi = min(lo + fp, args.n)
        start = int(fb.offsets[lo])
        end = int(fb.offsets[hi]) if hi < len(fb) else len(fb.buf)
        sub = FramesBuf.from_lengths(
            np.asarray(fb.buf[start:end]),
            np.asarray(fb.lengths[lo:hi]),
            np.asarray(fb.ifindex[lo:hi]),
        )
        write_frames_file_v2(
            os.path.join(args.out, f"load{i:06d}.frames"), sub
        )
    done = time.monotonic() - t0
    print(json.dumps({
        "offered_duration_s": float(offs[-1]),
        "actual_duration_s": done,
        "worst_schedule_lag_s": worst_lag,
        "fell_behind": worst_lag > 0.01,
    }), flush=True)
    if worst_lag > 0.01:
        print("loadgen: WARNING fell behind its open-loop schedule by "
              f"{worst_lag*1e3:.1f} ms — offered load was lower than "
              "requested; measured latencies must use the manifest's "
              "scheduled offsets, not file mtimes", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
