#!/usr/bin/env python
"""infw static-analysis CLI.

Subcommands:

  rules   Semantic analysis of rule tables (infw.analysis.rules): by
          default lints the shipped example specs; ``--spec FILE`` lints
          JSON documents (an IngressNodeFirewall, a list of them, or a
          NodeState-shaped {"interfaceIngressRules": ...} map);
          ``--acceptance`` runs the built-in injected-defect table and
          verifies the analyzer reports EXACTLY the injected findings
          with oracle-confirmed witnesses (the repo gate).
  jax     Hot-path audit (infw.analysis.jaxcheck) of every registered
          jitted entrypoint: x64 leaks, host callbacks, implicit
          host<->device transfers (jax.transfer_guard lint), recompile
          lint on the bench shape ladder, Pallas VMEM budget.  Run under
          JAX_PLATFORMS=cpu — no TPU needed.
          ``--inject-transfer-defect`` appends a deliberately defective
          host-operand entrypoint; the audit must then exit nonzero (the
          transfer-lint acceptance, wired into ``make state-check``).
  state   Patch-path model checker (infw.analysis.statecheck): seeded
          op sequences over the device-table edit state machine; after
          every op the incrementally-patched device state must be
          bit-identical to a cold rebuild and classify-equivalent to
          the CPU oracle.  On failure the case shrinks to a minimal
          paste-able reproducer (infw.analysis.shrink).
          ``--inject-defect [joined-pad|cskip]`` re-introduces a known
          bug — ``joined-pad`` (default) the PR-4 joined-placeholder
          bucket-padding bug (jaxpath._INJECT_JOINED_PAD_BUG);
          ``cskip`` a zeroed skip_bits word in the compressed layout's
          skip-node path (jaxpath._INJECT_CSKIP_BUG), caught by oracle
          divergence on the ctrie config — and verifies the checker
          catches it with a <= 3-op shrunk repro — exit 0 means CAUGHT.
  lock    Static concurrency verifier (infw.analysis.lockcheck): AST
          inventory of every Lock/RLock/Condition/Event in ``infw/``,
          the lock-acquisition graph (cycles reported with both witness
          code paths), guarded-field torn-publish detection, declared
          ordering contracts (infw.contracts: @must_precede + the
          flow->telemetry->mlscore LOCK_ORDER), and background-thread
          hygiene (every thread must go through infw._threads.spawn).
          False positives live in analysis/lockcheck_suppressions.txt
          with one-line justifications.  ``--inject-defect lockorder``
          reverses the nesting in a synthetic path; the cycle must be
          reported with BOTH witnesses (exit 0 = caught).
  sched   Deterministic interleaving explorer (infw.analysis.
          schedcheck): a cooperative scheduler shims the inventoried
          locks on live control-plane objects and replays seeded,
          preemption-bounded schedules over 2-thread production
          scenarios (CoW edit vs dedup sweep, edits-flush vs resident
          dispatch, telemetry drain vs patch, registry create vs
          racing edit).  Failures ddmin-shrink to a minimal schedule
          string (``s0@5:t1`` = start thread 0, force thread 1 at
          decision 5).  ``--inject-defect cowrace`` drops the
          allocator lock around the CoW donor refcount decrement; the
          explorer must find + shrink the race and check_arena's
          cowleak invariant must name it (exit 0 = caught).
  bounds  Kernel admission verifier (infw.analysis.boundscheck):
          abstract interpretation over the jaxpr of EVERY registered
          kernel entrypoint — interval + known-bits domain seeded from
          the declared tensor bounds (infw.contracts.TENSOR_BOUNDS,
          the same declarations the runtime invariant sweeps enforce)
          — proving every gather/scatter/dynamic_slice index fits its
          operand extent and every integer op fits its dtype.  Error
          findings replay a concretized boundary witness through
          production dispatch vs the CPU oracle; intentional modular
          arithmetic (SWAR popcount, one-hot packing) lives in
          analysis/boundscheck_suppressions.txt with REQUIRED
          justifications.  ``--inject-defect [clampgather|i8wrap]``
          re-introduces a known bounds bug and verifies the verifier
          reports it with a diverging witness (exit 0 = caught).
  acceptance
          Run the consolidated injected-defect registry
          (infw.analysis.defects) end to end: every ``--inject-defect``
          acceptance of every checker, each in a fresh subprocess;
          ``--checker``/``--defects`` select slices.

Exit status: 1 when any error-severity finding exists (or, with
``--strict``, any warning too); 0 otherwise.  ``--json`` prints one
machine-readable JSON document on stdout instead of text lines.

Silencing: ``--ignore CHECK[,CHECK...]`` drops findings by check id
(e.g. ``--ignore failsafe-violation`` when linting an intentional
deny-all spec); see README "Static analysis".
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from _common import repo_root, setup_repo_path

setup_repo_path()

from infw.analysis import defects as defect_registry  # noqa: E402


# --- rules subcommand -------------------------------------------------------


def _load_spec_docs(paths: List[str]):
    """JSON files -> (infs, content_maps)."""
    from infw.spec import IngressNodeFirewall, IngressNodeFirewallNodeState

    infs, states = [], []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        docs = doc if isinstance(doc, list) else [doc]
        for d in docs:
            kind = d.get("kind", IngressNodeFirewall.KIND)
            if kind == IngressNodeFirewall.KIND:
                infs.append(IngressNodeFirewall.from_dict(d))
            elif kind == IngressNodeFirewallNodeState.KIND or (
                "interfaceIngressRules" in d.get("spec", d)
            ):
                states.append(IngressNodeFirewallNodeState.from_dict(
                    d if "spec" in d else {"spec": d}
                ))
            else:
                print(f"warning: {path}: skipping kind {kind!r}",
                      file=sys.stderr)
    return infs, states


def _default_example_specs() -> List[str]:
    ex_dir = os.path.join(repo_root(), "examples")
    out = []
    for name in sorted(os.listdir(ex_dir)) if os.path.isdir(ex_dir) else []:
        if not name.endswith(".json"):
            continue
        path = os.path.join(ex_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError:
            continue
        if isinstance(doc, dict) and doc.get("kind") == "IngressNodeFirewall":
            out.append(path)
    return out


def _acceptance_content():
    """The injected-defect table of the repo gate: one shadowed rule and
    one Allow/Deny conflict, nothing else."""
    import numpy as np

    from infw.compiler import LpmKey
    from infw.constants import ALLOW, DENY, IPPROTO_TCP

    def rows(*specs):
        m = np.zeros((4, 7), np.int32)
        for order, proto, ps, pe, act in specs:
            m[order] = [order, proto, ps, pe, 0, 0, act]
        return m

    v4 = lambda a, b, c, d: bytes([a, b, c, d]) + bytes(12)
    key = lambda data, mask: LpmKey(mask + 32, 2, data)
    return {
        key(v4(10, 0, 0, 0), 8): rows((1, IPPROTO_TCP, 443, 0, ALLOW)),
        key(v4(10, 1, 0, 0), 16): rows((1, IPPROTO_TCP, 443, 0, DENY)),
        key(v4(192, 168, 0, 0), 16): rows(
            (1, IPPROTO_TCP, 1000, 2000, ALLOW),
            (2, IPPROTO_TCP, 1500, 0, DENY),
        ),
    }


def _run_acceptance(as_json: bool) -> int:
    from infw.analysis import rules as ar

    content = _acceptance_content()
    findings = ar.analyze_content(content)
    report = {
        "findings": [f.to_dict() for f in findings],
        "confirmed": [],
        "ok": False,
    }
    problems = []
    want = {("shadowed-rule", "if2 192.168.0.0/16"),
            ("allow-deny-conflict", "if2 10.1.0.0/16")}
    got = {(f.check, f.entry) for f in findings}
    if got != want:
        problems.append(f"expected exactly {sorted(want)}, got {sorted(got)}")
    replays = ar.replay_witnesses(content, findings)
    for f, ok, got_res in replays:
        report["confirmed"].append(
            {"check": f.check, "confirmed": ok, "got": got_res}
        )
        if not ok:
            problems.append(
                f"{f.check}: witness replay got {got_res:#x}, expected "
                f"{f.witness.expect_result:#x}"
            )
    if len(replays) != 2:
        problems.append(f"expected 2 witnesses to replay, got {len(replays)}")
    report["ok"] = not problems
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            _print_finding(f)
        for p in problems:
            print(f"ACCEPTANCE FAIL: {p}")
        if not problems:
            print("acceptance: 2 injected findings reported, both witnesses "
                  "oracle-confirmed")
    return 0 if not problems else 1


def _print_finding(f) -> None:
    loc = f" [{', '.join(f.objects)}]" if f.objects else ""
    print(f"{f.severity:7s} {f.check:22s} {f.entry}{loc}: {f.message}")
    if f.witness is not None:
        w = f.witness.to_dict()
        print(f"        witness: src={w['srcAddr']} if={w['ifindex']} "
              f"proto={w['proto']} dport={w['dstPort']} "
              f"icmp={w['icmpType']}/{w['icmpCode']} -> "
              f"rule {w['expectRuleId']} {w['expectAction']}")


def cmd_rules(args) -> int:
    from infw.analysis import rules as ar

    if args.acceptance:
        return _run_acceptance(args.json)

    findings = []
    groups = []  # (compiled content, findings) pairs for --confirm
    paths = args.spec or _default_example_specs()
    infs, states = _load_spec_docs(paths)
    if infs:
        findings.extend(ar.analyze_infs(infs, content_sink=groups))
    for ns in states:
        for iface, ingress in ns.spec.interface_ingress_rules.items():
            from infw.spec import IngressNodeFirewall, IngressNodeFirewallSpec

            synth = IngressNodeFirewall(
                spec=IngressNodeFirewallSpec(
                    interfaces=[iface], ingress=ingress
                )
            )
            synth.metadata.name = ns.metadata.name or "nodestate"
            findings.extend(ar.analyze_infs([synth], content_sink=groups))

    ignore = set((args.ignore or "").split(",")) - {""}
    findings = [f for f in findings if f.check not in ignore]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = sum(1 for f in findings if f.severity == "warning")

    confirmed = None
    if args.confirm:
        confirmed = []
        for content, group_findings in groups:
            kept = [f for f in group_findings if f.check not in ignore]
            for f, ok, got in ar.replay_witnesses(content, kept):
                confirmed.append((f, ok, got))
                if not ok:
                    n_err += 1
                    print(f"CONFIRM FAIL {f.check} {f.entry}: oracle "
                          f"returned {got:#x}, witness predicted "
                          f"{f.witness.expect_result:#x}", file=sys.stderr)

    if args.json:
        doc = {
            "specs": paths,
            "findings": [f.to_dict() for f in findings],
            "errors": n_err,
            "warnings": n_warn,
        }
        if confirmed is not None:
            doc["confirmed"] = [
                {"check": f.check, "entry": f.entry, "confirmed": ok,
                 "got": got}
                for f, ok, got in confirmed
            ]
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            _print_finding(f)
        tail = ""
        if confirmed is not None:
            n_ok = sum(1 for _, ok, _ in confirmed)
            tail = f", {n_ok}/{len(confirmed)} witnesses oracle-confirmed"
        print(f"rules: {len(paths)} spec file(s), {len(findings)} finding(s) "
              f"({n_err} error, {n_warn} warning){tail}")
    if n_err or (args.strict and n_warn):
        return 1
    return 0


# --- jax subcommand ---------------------------------------------------------


def cmd_jax(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from infw.analysis import jaxcheck

    ladder = tuple(
        int(x) for x in (args.ladder or "256,1024").split(",") if x
    )
    names = [x for x in (args.entries or "").split(",") if x] or None
    reports = jaxcheck.audit_all(
        names=names,
        ladder=ladder,
        vmem_budget=args.vmem_budget,
        execute=not args.no_execute,
        include_transfer_defect=args.inject_transfer_defect,
        include_donation_defect=getattr(
            args, "inject_donation_defect", False
        ),
    )
    summary = jaxcheck.summarize(reports)
    if args.json:
        print(json.dumps({
            "reports": [r.to_dict() for r in reports],
            "summary": summary,
        }, indent=2))
    else:
        for r in reports:
            status = "OK" if not any(
                f.severity in ("error", "warning") for f in r.findings
            ) else "FAIL"
            print(f"{status:4s} {r.entry:35s} kind={r.kind:6s} "
                  f"shapes={r.shapes} eqns={r.n_eqns} "
                  f"pallas={r.n_pallas_calls} vmem={r.vmem_bytes}B")
            for f in r.findings:
                print(f"     {f.severity}: [{f.check}] {f.message}")
                if f.detail:
                    for line in f.detail.splitlines():
                        print(f"       | {line}")
        print(f"jax: {summary}")
    if summary["error"] or (args.strict and summary["warning"]):
        return 1
    return 0


# --- state subcommand -------------------------------------------------------


#: default configurations of the state-check gate: the trie patch path,
#: the overlay routing, the wide-ruleId u32 path and the joined-gate-
#: tripped placeholder regime.  dense/fused/mesh run in the pytest suite
#: (tests/test_statecheck.py) — selectable here via --configs.
DEFAULT_STATE_CONFIGS = ("trie", "overlay", "wide", "nojoined", "ctrie",
                         "ctrie-overlay", "txn", "txn-ctrie", "arena",
                         "arena-ctrie", "arena-cow", "arena-splice",
                         "flow", "flow-ctrie",
                         "resident", "pipeline", "telemetry",
                         "telemetry-resident",
                         "payload", "payload-resident")


def _run_inject_defect(args, as_json: bool) -> int:
    """The injected-defect acceptance: re-introduce a known bug and
    prove the checker catches it with a shrunk reproducer within the
    per-defect op bound.  Exit 0 = caught.  ``joined-pad`` runs the
    PR-4 joined-placeholder bucket-padding bug on the 'nojoined' config
    (the placeholder layout regime); ``cskip`` zeroes the compressed
    layout's skip_bits words on the 'ctrie' config — the resident AND
    cold-rebuilt device state share the defect, so the catch is oracle
    divergence, proving the classify-equivalence half covers the
    skip-node path; ``fold`` drops delete-then-readd pairs in the
    transaction fold (infw.txn) on the 'txn' config — the corrupted
    fold feeds updater, resident state AND cold rebuild alike, so the
    catch again MUST be per-op-ground-truth oracle divergence, shrunk
    to a <= 2-op (delete, readd) reproducer.  The per-defect knobs
    (config, shrink bound, generator horizon, shrinker budget) and the
    expected-catch contract all come from the declarative registry
    (infw.analysis.defects)."""
    from infw.analysis import statecheck

    d = defect_registry.get(args.inject_defect)
    defect, config, bound = d.name, d.config, d.bound
    # multi-op defects (delete-then-readd in one txn; traffic-edit-
    # traffic on one seed) need a generator horizon that reliably
    # produces the pattern and a shrinker budget to reduce it back —
    # declared per defect as min_ops/shrink_runs in the registry
    n_ops = max(args.ops, d.min_ops)
    shrink_runs = d.shrink_runs
    if args.configs:
        print(f"note: --inject-defect {defect} always runs the "
              f"{config!r} config (the defect's layout regime); "
              "--configs ignored", file=sys.stderr)
    defect_registry.set_flag(d, True)
    try:
        report = statecheck.run_config(
            config, seed=args.seed, n_ops=n_ops,
            backend=args.backend, witness_b=args.witness,
            max_shrink_runs=shrink_runs,
        )
    finally:
        defect_registry.set_flag(d, False)
    problems = []
    if report["ok"]:
        problems.append(
            f"injected {defect} defect NOT caught by the equivalence "
            "engine"
        )
    else:
        shrunk = report.get("shrunk") or {}
        n = shrunk.get("ops", 10**9)
        if n > bound:
            problems.append(
                f"shrunk reproducer has {n} ops (acceptance bound: {bound})"
            )
    report["problems"] = problems
    report["caught"] = not problems
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        if not problems:
            f = report["failure"]
            shrunk = report.get("shrunk") or {}
            print(
                "inject-defect: CAUGHT "
                f"[{f['phase']}] {f['message']} — shrunk to "
                f"{shrunk.get('ops')} op(s), {shrunk.get('entries')} "
                f"entries, witness {shrunk.get('witness_b')}"
            )
            if shrunk.get("repro"):
                print(shrunk["repro"])
        for p in problems:
            print(f"INJECT-DEFECT FAIL: {p}")
    return 0 if not problems else 1


def cmd_state(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.inject_defect:
        return _run_inject_defect(args, args.json)
    from infw.analysis import statecheck

    if args.configs:
        names = [x for x in args.configs.split(",") if x]
    else:
        names = list(DEFAULT_STATE_CONFIGS)
    unknown = [n for n in names if n not in statecheck.CONFIGS]
    if unknown:
        print(f"unknown state config(s): {', '.join(unknown)} "
              f"(have: {', '.join(statecheck.CONFIGS)})", file=sys.stderr)
        return 2
    reports = []
    n_fail = 0
    for name in names:
        rep = statecheck.run_config(
            name, seed=args.seed, n_ops=args.ops, backend=args.backend,
            witness_b=args.witness,
        )
        reports.append(rep)
        if not rep["ok"]:
            n_fail += 1
        if not args.json:
            status = "OK  " if rep["ok"] else "FAIL"
            print(f"{status} {name:10s} seed={rep['seed']} "
                  f"ops={rep['ops']} entries={rep['entries']} "
                  f"backend={rep['backend']}")
            if not rep["ok"]:
                f = rep["failure"]
                print(f"     [{f['phase']}] step {f['step']}: {f['message']}")
                if f.get("detail"):
                    for line in f["detail"].splitlines():
                        print(f"       | {line}")
                shrunk = rep.get("shrunk")
                if shrunk:
                    print(f"     shrunk to {shrunk['ops']} op(s), "
                          f"{shrunk['entries']} entries, witness "
                          f"{shrunk['witness_b']}:")
                    for line in shrunk["repro"].splitlines():
                        print(f"       {line}")
    if args.json:
        print(json.dumps(
            {"reports": reports, "failures": n_fail, "ok": n_fail == 0},
            indent=2,
        ))
    else:
        print(f"state: {len(reports)} config(s), {n_fail} failure(s)")
    return 1 if n_fail else 0


# --- lock subcommand --------------------------------------------------------


def cmd_lock(args) -> int:
    from infw.analysis import lockcheck

    if args.inject_defect:
        # lockorder acceptance: append the reversed telemetry->flow
        # nesting path and require a reported cycle with BOTH witness
        # code paths.  Exit 0 = caught.
        rep = lockcheck.analyze_repo(inject_defect=args.inject_defect)
        cycles = [f for f in rep["findings"] if f["check"] == "lock-cycle"]
        caught = any(len(f.get("witnesses", ())) >= 2 for f in cycles)
        if args.json:
            print(json.dumps({"defect": args.inject_defect,
                              "caught": caught, "cycles": cycles},
                             indent=2))
        elif caught:
            f = cycles[0]
            print(f"CAUGHT {args.inject_defect}: cycle {f['subject']}")
            for w in f["witnesses"]:
                print(f"  witness: {w}")
        else:
            print(f"NOT CAUGHT {args.inject_defect}: no lock cycle "
                  f"with two witnesses reported")
        return 0 if caught else 1

    rep = lockcheck.analyze_repo()
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        for f in rep["findings"]:
            print(f"{f['severity']} [{f['check']}] {f['where']} "
                  f"{f['subject']}: {f['message']}")
            for w in f.get("witnesses", ()):
                print(f"  witness: {w}")
        print(f"lock: {len(rep['inventory'])} lock site(s), "
              f"{len(rep['stats'].get('edges', {}))} acquisition edge(s), "
              f"{rep['errors']} error(s), {rep['warnings']} warning(s), "
              f"{len(rep['suppressed'])} suppressed")
    if rep["errors"]:
        return 1
    if args.strict and rep["warnings"]:
        return 1
    return 0


# --- sched subcommand -------------------------------------------------------


def cmd_sched(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from infw.analysis import schedcheck

    if args.inject_defect:
        # acceptance (registry-declared, e.g. cowrace): inject the
        # defect's production-module flag; the explorer must find the
        # failing interleaving, shrink it to <= max_segments schedule
        # steps, and the declared invariant must name it.  Exit 0 =
        # caught.
        d = defect_registry.get(args.inject_defect)
        defect_registry.set_flag(d, True)
        try:
            res = schedcheck.explore(
                d.scenario,
                schedcheck.SCENARIOS[d.scenario],
                seed=args.seed, runs=max(args.runs, 120),
                bound=args.preemptions,
            )
        finally:
            defect_registry.set_flag(d, False)
        caught = (
            not res.ok and res.shrunk is not None
            and res.shrunk.segments <= d.max_segments
            and any(d.invariant_token in e
                    for e in res.shrunk.invariant_errors)
        )
        if args.json:
            print(json.dumps({"defect": args.inject_defect,
                              "caught": caught, "result": res.to_dict()},
                             indent=2))
        elif caught:
            s = res.shrunk
            print(f"CAUGHT {args.inject_defect}: schedule "
                  f"{s.schedule.to_str()} ({s.segments} step(s))")
            print(f"  trace: {schedcheck.format_trace(s.trace, s.thread_names)}")
            for e in s.invariant_errors:
                print(f"  invariant: {e}")
        else:
            print(f"NOT CAUGHT {args.inject_defect}: "
                  + ("no failing interleaving found" if res.ok
                     else "failure did not shrink to the "
                          f"{d.invariant_token} repro"))
        return 0 if caught else 1

    names = ([x for x in args.scenarios.split(",") if x]
             if args.scenarios else list(schedcheck.DEFAULT_SCENARIOS))
    unknown = [n for n in names if n not in schedcheck.SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)} "
              f"(have: {', '.join(schedcheck.SCENARIOS)})", file=sys.stderr)
        return 2
    results = schedcheck.explore_all(
        names, seed=args.seed, runs=args.runs, bound=args.preemptions,
    )
    n_fail = sum(1 for r in results if not r.ok)
    if args.json:
        print(json.dumps({"results": [r.to_dict() for r in results],
                          "failures": n_fail, "ok": n_fail == 0},
                         indent=2))
    else:
        for r in results:
            status = "OK  " if r.ok else "FAIL"
            print(f"{status} {r.scenario:20s} seed={args.seed} "
                  f"runs={r.runs} horizon={r.horizon}")
            if not r.ok and r.shrunk is not None:
                for line in r.shrunk.describe().splitlines():
                    print(f"     | {line}")
        print(f"sched: {len(results)} scenario(s), {n_fail} failure(s)")
    return 1 if n_fail else 0


# --- bounds subcommand ------------------------------------------------------


def _bounds_inject(args, as_json: bool) -> int:
    """Bounds injected-defect acceptance: flip the registry-declared
    TRACE-time flag, audit the defect's entry, and require (1) an
    unsuppressed error finding of the expected check and (2) a
    DIVERGING boundary witness — the executable half of the proof.
    Exit 0 = caught.  Run in a fresh process (the flags act at trace
    time; the Makefile/tests invoke the CLI, which is one)."""
    from infw.analysis import boundscheck

    d = defect_registry.get(args.inject_defect)
    defect_registry.set_flag(d, True)
    try:
        rep = boundscheck.audit_entry(
            _bounds_entry(d.entry), batch=args.batch, witness=True)
    finally:
        defect_registry.set_flag(d, False)
    hits = [f for f in rep.findings
            if f.severity == "error" and f.check == d.check]
    problems = []
    if rep.error:
        problems.append(f"audit failed: {rep.error}")
    if not hits:
        problems.append(
            f"injected {d.name} defect NOT caught: no unsuppressed "
            f"{d.check} error on {d.entry}")
    witnessed = [f for f in hits
                 if f.witness and f.witness.get("ran")
                 and f.witness.get("diverged")]
    if hits and not witnessed:
        w = hits[0].witness or {}
        problems.append(
            f"{d.check} reported but the boundary witness did not "
            f"diverge ({w.get('error') or w.get('detail') or 'no witness'})")
    if as_json:
        print(json.dumps({
            "defect": d.name, "caught": not problems,
            "problems": problems, "report": rep.to_dict(),
        }, indent=2))
    else:
        for f in witnessed:
            print(f"inject-defect: CAUGHT {d.name}: {f.check} "
                  f"{f.subject} {f.interval} — witness: "
                  f"{f.witness.get('detail')}")
        for p in problems:
            print(f"INJECT-DEFECT FAIL: {p}")
    return 0 if not problems else 1


def _bounds_entry(name: str):
    from infw import kernels

    for ep in kernels.kernel_entrypoints():
        if ep.name == name:
            return ep
    raise SystemExit(f"no registered entrypoint named {name!r}")


def cmd_bounds(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from infw.analysis import boundscheck

    if args.inject_defect:
        return _bounds_inject(args, args.json)

    names = [x for x in (args.entry or "").split(",") if x] or None
    reports = boundscheck.audit_all(
        names=names, batch=args.batch,
        witness=not args.no_witness)
    summary = boundscheck.summarize(reports)
    ignore = set((args.ignore or "").split(","))
    findings = [f for r in reports for f in r.findings
                if f.check not in ignore]
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity == "warning"]
    audit_errors = [r for r in reports if r.error]
    if args.json:
        print(json.dumps({
            "summary": summary,
            "reports": [r.to_dict() for r in reports],
        }, indent=2))
    else:
        for r in reports:
            if r.error:
                print(f"AUDIT-ERROR {r.entry}: {r.error}")
        for f in findings:
            sev = f.severity.upper()
            line = f"{sev} [{f.check}] {f.subject} {f.interval}"
            if f.extent:
                line += f" extent {f.extent}"
            print(line)
            if f.eqn:
                print(f"  {f.eqn}")
        sup = summary["suppressed"]
        print(f"bounds: {summary['entries']} entries, "
              f"{summary['index_sites']} index sites "
              f"({summary['proved']} proved, {summary['guarded']} "
              f"guarded, {summary['pallas_opaque']} pallas-opaque), "
              f"{len(errors)} error(s), {len(warnings)} warning(s), "
              f"{sup} suppressed")
    if errors or audit_errors:
        return 1
    if args.strict and warnings:
        return 1
    return 0


# --- acceptance subcommand --------------------------------------------------


def cmd_acceptance(args) -> int:
    """Loop the injected-defect registry (optionally one checker's
    slice) and run every acceptance in a FRESH subprocess — uniform
    for all checkers, and required for the bounds defects, whose flags
    act at trace time and whose witness replays would otherwise reuse
    this process's warm jit caches."""
    import subprocess

    wanted = [x for x in (args.checker or "").split(",") if x]
    rows = []
    for d in defect_registry.DEFECTS.values():
        if wanted and d.checker not in wanted:
            continue
        if args.defects and d.name not in args.defects.split(","):
            continue
        if d.checker == "jax":
            flag = ("--inject-transfer-defect" if d.name == "transfer"
                    else "--inject-donation-defect")
            entry = ("defect/implicit-transfer" if d.name == "transfer"
                     else "defect/undonated-buffer")
            argv = ["jax", "--strict", flag, "--entries", entry]
            want_rc = 1     # the audit must FAIL on the injection
        else:
            argv = [d.checker, "--inject-defect", d.name]
            want_rc = 0
        cmd = [sys.executable, os.path.abspath(__file__)] + argv
        proc = subprocess.run(
            cmd, capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        ok = proc.returncode == want_rc
        rows.append({"defect": d.name, "checker": d.checker, "ok": ok,
                     "returncode": proc.returncode,
                     "expect": d.expect})
        status = "CAUGHT " if ok else "MISSED "
        print(f"{status} {d.checker:6s} {d.name}")
        if not ok:
            tail = (proc.stdout + proc.stderr).strip().splitlines()[-6:]
            for line in tail:
                print(f"       | {line}")
    n_bad = sum(1 for r in rows if not r["ok"])
    if args.json:
        print(json.dumps({"acceptances": rows, "ok": n_bad == 0},
                         indent=2))
    else:
        print(f"acceptance: {len(rows)} defect(s), {n_bad} missed")
    return 1 if n_bad or not rows else 0


# --- main -------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="infw_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_rules = sub.add_parser("rules", help="rule-table semantic analysis")
    p_rules.add_argument("--spec", action="append", metavar="FILE",
                         help="JSON spec file(s); default: examples/*.json")
    p_rules.add_argument("--json", action="store_true")
    p_rules.add_argument("--strict", action="store_true",
                         help="warnings also exit nonzero")
    p_rules.add_argument("--ignore", metavar="CHECKS",
                         help="comma-separated check ids to drop")
    p_rules.add_argument("--confirm", action="store_true",
                         help="replay every witness against the CPU oracle "
                              "(a failed replay counts as an error)")
    p_rules.add_argument("--acceptance", action="store_true",
                         help="run the built-in injected-defect gate")
    p_rules.set_defaults(fn=cmd_rules)

    p_jax = sub.add_parser("jax", help="jitted hot-path audit")
    p_jax.add_argument("--json", action="store_true")
    p_jax.add_argument("--strict", action="store_true",
                       help="warnings also exit nonzero")
    p_jax.add_argument("--entries", metavar="NAMES",
                       help="comma-separated entrypoint subset")
    p_jax.add_argument("--ladder", metavar="SIZES",
                       help="batch-size ladder (default 256,1024)")
    p_jax.add_argument("--vmem-budget", type=int, metavar="BYTES")
    p_jax.add_argument("--no-execute", action="store_true",
                       help="trace-only (skip the run-twice recompile lint)")
    p_jax.add_argument("--inject-transfer-defect", action="store_true",
                       help="append a deliberately defective host-operand "
                            "entrypoint (the audit must then fail)")
    p_jax.add_argument("--inject-donation-defect", action="store_true",
                       help="append a declared-donation entrypoint whose "
                            "buffer XLA cannot alias (the donation lint "
                            "must then fail)")
    p_jax.set_defaults(fn=cmd_jax)

    p_state = sub.add_parser("state", help="patch-path model checker")
    p_state.add_argument("--json", action="store_true")
    p_state.add_argument("--strict", action="store_true",
                         help="accepted for UX parity with rules/jax "
                              "(every state failure is already an error)")
    p_state.add_argument("--seed", type=int, default=0,
                         help="case seed (default 0)")
    p_state.add_argument("--ops", type=int, default=8,
                         help="ops per sequence (default 8)")
    p_state.add_argument("--configs", metavar="NAMES",
                         help="comma-separated config subset "
                              f"(default {','.join(DEFAULT_STATE_CONFIGS)})")
    p_state.add_argument("--backend", choices=("tpu", "mesh"),
                         default="tpu",
                         help="classifier backend (mesh = replicated "
                              "MeshTpuClassifier; needs a multi-device "
                              "pool)")
    p_state.add_argument("--witness", type=int, metavar="B",
                         help="witness batch size override")
    p_state.add_argument("--inject-defect", nargs="?",
                         const="joined-pad", default=None,
                         choices=tuple(defect_registry.names("state")),
                         help="re-introduce a known bug — joined-pad "
                              "(default): the PR-4 joined-placeholder "
                              "bucket-padding bug; cskip: zeroed "
                              "skip_bits in the compressed skip-node "
                              "path; fold: delete-then-readd pairs "
                              "dropped by the transaction fold "
                              "(infw.txn) — and verify the checker "
                              "catches it (exit 0 = caught)")
    p_state.set_defaults(fn=cmd_state)

    p_lock = sub.add_parser("lock", help="static lock-order/guard "
                                         "analysis (lockcheck)")
    p_lock.add_argument("--json", action="store_true")
    p_lock.add_argument("--strict", action="store_true",
                        help="warnings are fatal too")
    p_lock.add_argument("--inject-defect", nargs="?", const="lockorder",
                        default=None,
                        choices=tuple(defect_registry.names("lock")),
                        help="reverse the flow->telemetry lock nesting "
                             "in one synthetic path and verify the "
                             "analyzer reports the cycle with BOTH "
                             "witness code paths (exit 0 = caught)")
    p_lock.set_defaults(fn=cmd_lock)

    p_sched = sub.add_parser("sched", help="deterministic interleaving "
                                           "explorer (schedcheck)")
    p_sched.add_argument("--json", action="store_true")
    p_sched.add_argument("--strict", action="store_true",
                         help="accepted for UX parity (every schedcheck "
                              "failure is already an error)")
    p_sched.add_argument("--seed", type=int, default=0,
                         help="exploration seed (default 0)")
    p_sched.add_argument("--runs", type=int, default=24,
                         help="schedules explored per scenario "
                              "(default 24)")
    p_sched.add_argument("--preemptions", type=int, default=2,
                         help="max forced preemptions per random "
                              "schedule (default 2)")
    p_sched.add_argument("--scenarios", metavar="NAMES",
                         help="comma-separated scenario subset "
                              "(default: the four production scenarios)")
    p_sched.add_argument("--inject-defect", nargs="?", const="cowrace",
                         default=None,
                         choices=tuple(defect_registry.names("sched")),
                         help="drop the allocator lock around the CoW "
                              "donor refcount decrement and verify the "
                              "explorer finds the interleaving, shrinks "
                              "it to <= 6 steps, and check_arena names "
                              "it (exit 0 = caught)")
    p_sched.set_defaults(fn=cmd_sched)

    p_bounds = sub.add_parser(
        "bounds", help="static gather/scatter bounds + overflow "
                       "verifier (boundscheck)")
    p_bounds.add_argument("--json", action="store_true")
    p_bounds.add_argument("--strict", action="store_true",
                          help="warnings are fatal too")
    p_bounds.add_argument("--entry", metavar="NAMES",
                          help="comma-separated entrypoint subset "
                               "(default: every registered entrypoint)")
    p_bounds.add_argument("--batch", type=int, default=256,
                          help="trace batch size (default 256)")
    p_bounds.add_argument("--ignore", metavar="CHECKS", default="",
                          help="comma-separated check ids to drop")
    p_bounds.add_argument("--no-witness", action="store_true",
                          help="skip the boundary-witness replay of "
                               "error findings (faster; proofs and "
                               "suppressions unaffected)")
    p_bounds.add_argument("--inject-defect", nargs="?",
                          const="clampgather", default=None,
                          choices=tuple(defect_registry.names("bounds")),
                          help="re-introduce a known bounds bug — "
                               "clampgather (default): drop the spliced "
                               "page-table & mask decode; i8wrap: "
                               "restage the AC carried DFA state "
                               "through int8 — and verify the verifier "
                               "reports it with a DIVERGING boundary "
                               "witness (exit 0 = caught; run in a "
                               "fresh process, the flags act at trace "
                               "time)")
    p_bounds.set_defaults(fn=cmd_bounds)

    p_acc = sub.add_parser(
        "acceptance", help="run the injected-defect registry "
                           "(infw.analysis.defects) end to end")
    p_acc.add_argument("--json", action="store_true")
    p_acc.add_argument("--checker", metavar="NAMES",
                       help="comma-separated checker subset "
                            "(state,lock,sched,jax,bounds; default all)")
    p_acc.add_argument("--defects", metavar="NAMES",
                       help="comma-separated defect subset")
    p_acc.set_defaults(fn=cmd_acceptance)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
