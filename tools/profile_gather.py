#!/usr/bin/env python
"""Gather microbenchmark: cost of take(tbl(N,W)u32, idx(B,)) per packet
as a function of row width W, table rows N, and index distribution.
Chained so nothing hoists: idx feeds on the gathered values.

Also: attempt a Pallas kernel doing jnp.take from a VMEM-resident table
(does Mosaic support vectorized dynamic gather at all, and how fast).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

B = 1 << 20


def timeit(fn, *args):
    fn(*args)[0].block_until_ready()
    k1, k2 = 3, 23
    def run(k):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            r = args
            for _ in range(k):
                r = fn(*r)
            r[0].block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best
    b1 = run(k1)
    while True:
        b2 = run(k2)
        if b2 - b1 > 0.3 or k2 > 3000:
            break
        k2 *= 3
    return (b2 - b1) / (k2 - k1)


def main():
    if jax.default_backend() == "tpu":
        from infw.platform import enable_jax_compile_cache
        enable_jax_compile_cache("/tmp/infw-jax-cache")
    rng = np.random.default_rng(7)

    print("=== XLA gather: rows (N,W) u32, random idx ===", file=sys.stderr)
    for N in (4096, 65536, 1 << 20):
        for W in (2, 8, 18, 32, 64):
            tbl = jax.device_put(
                rng.integers(0, 2**31, (N, W), dtype=np.int64).astype(np.uint32))
            idx0 = jax.device_put(
                rng.integers(0, N, B, dtype=np.int64).astype(np.int32))

            @jax.jit
            def step(idx, tbl=tbl, N=N):
                rows = jnp.take(tbl, idx, axis=0)
                s = jnp.sum(rows.astype(jnp.uint32), axis=1)
                return ((idx + s.astype(jnp.int32)) % N,)

            dt = timeit(step, idx0)
            print(f"N={N:8d} W={W:3d} ({W*4:4d}B rows): "
                  f"{dt/B*1e9:6.2f} ns/row  ({B*W*4/dt/1e9:6.1f} GB/s)",
                  file=sys.stderr, flush=True)

    print("=== sorted (locality) idx, N=65536 W=18 ===", file=sys.stderr)
    N, W = 65536, 18
    tbl = jax.device_put(
        rng.integers(0, 2**31, (N, W), dtype=np.int64).astype(np.uint32))
    idx_sorted = jax.device_put(
        np.sort(rng.integers(0, N, B, dtype=np.int64)).astype(np.int32))

    @jax.jit
    def step_s(idx, tbl=tbl):
        rows = jnp.take(tbl, idx, axis=0)
        s = jnp.sum(rows.astype(jnp.uint32), axis=1)
        # keep idx VALUES the same (sorted) but defeat memoization via xor 0
        return (idx + (s & 0).astype(jnp.int32),)

    dt = timeit(step_s, idx_sorted)
    print(f"sorted: {dt/B*1e9:6.2f} ns/row", file=sys.stderr, flush=True)

    print("=== Pallas in-VMEM gather attempt ===", file=sys.stderr)
    try:
        from jax.experimental import pallas as pl

        N2, W2 = 4096, 8   # 128KB table -> VMEM
        tblv = jax.device_put(
            rng.integers(0, 2**31, (N2, W2 * 16), dtype=np.int64).astype(np.uint32))
        idx0 = jax.device_put(
            rng.integers(0, N2, B, dtype=np.int64).astype(np.int32))

        BB = 1024

        def kern(idx_ref, tbl_ref, out_ref):
            idx = idx_ref[:]
            rows = jnp.take(tbl_ref[:], idx, axis=0)
            out_ref[:] = jnp.sum(rows.astype(jnp.uint32), axis=1, keepdims=True)

        @jax.jit
        def pstep(idx, tbl=tblv):
            s = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((B, 1), jnp.uint32),
                grid=(B // BB,),
                in_specs=[
                    pl.BlockSpec((BB,), lambda i: (i,)),
                    pl.BlockSpec((N2, W2 * 16), lambda i: (0, 0)),
                ],
                out_specs=pl.BlockSpec((BB, 1), lambda i: (i, 0)),
            )(idx, tbl)
            return ((idx + s[:, 0].astype(jnp.int32)) % N2,)

        dt = timeit(pstep, idx0)
        print(f"pallas vmem take N={N2} row={W2*64}B: {dt/B*1e9:6.2f} ns/row",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"pallas gather FAILED: {type(e).__name__}: {str(e)[:500]}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
