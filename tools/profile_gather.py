#!/usr/bin/env python
"""Gather microbenchmark (slim): cost of take(tbl(N,W)u32, idx(B,)) per
row vs row width, plus a Pallas in-VMEM gather attempt.  Uses the
bench's chained two-point-slope methodology."""
import sys
import time

from _common import jax_setup, setup_repo_path

setup_repo_path()

import numpy as np
import jax
import jax.numpy as jnp

B = 1 << 20


def slope(step, idx0, label):
    @jax.jit
    def loop(k, idx):
        def body(i, idx):
            return step(idx ^ i.astype(jnp.int32))
        return jax.lax.fori_loop(0, k, body, idx)

    loop(1, idx0).block_until_ready()
    idx_host = np.asarray(idx0)
    salt = [0]

    def best_of(k, attempts=3):
        best = float("inf")
        for _ in range(attempts):
            # fresh input CONTENT per attempt: the tunnel's dispatch
            # layer memoizes byte-identical executions, so re-timing the
            # same (k, idx0) would time cached replays
            salt[0] += 1
            idx = jax.device_put(idx_host ^ np.int32(salt[0]))
            idx.block_until_ready()
            t0 = time.perf_counter()
            loop(k, idx).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    k1, k2 = 3, 23
    b1 = best_of(k1)
    while True:
        b2 = best_of(k2)
        if b2 - b1 >= 0.5 or k2 >= 2000:
            break
        k2 *= 3
        b1 = best_of(k1)
    dt = (b2 - b1) / (k2 - k1)
    print(f"{label}: {dt/B*1e9:6.2f} ns/row ({B*1e-6/dt:6.1f} M rows/s)",
          file=sys.stderr, flush=True)
    return dt


def main():
    jax_setup()
    rng = np.random.default_rng(7)
    N = 65536
    idx0 = jax.device_put(rng.integers(0, N, B, dtype=np.int64).astype(np.int32))

    for W in (8, 32, 64, 128, 256):
        tbl = jax.device_put(
            rng.integers(0, 2**31, (N, W), dtype=np.int64).astype(np.uint32))

        def step(idx, tbl=tbl):
            rows = jnp.take(tbl, jnp.clip(idx, 0, N - 1), axis=0)
            s = jnp.sum(rows.astype(jnp.uint32), axis=1)
            return (idx + s.astype(jnp.int32)) % N

        slope(step, idx0, f"xla take N=65536 W={W} ({W*4}B)")

    print("=== Pallas in-VMEM gather attempt ===", file=sys.stderr, flush=True)
    try:
        from jax.experimental import pallas as pl

        N2 = 4096
        tblv = jax.device_put(
            rng.integers(0, 2**31, (N2, 128), dtype=np.int64).astype(np.uint32))
        idxs = jax.device_put(
            rng.integers(0, N2, B, dtype=np.int64).astype(np.int32))
        BB = 1024

        def kern(idx_ref, tbl_ref, out_ref):
            rows = jnp.take(tbl_ref[:], idx_ref[:], axis=0)
            out_ref[:] = jnp.sum(rows.astype(jnp.uint32), axis=1, keepdims=True)

        @jax.jit
        def pstep(idx):
            s = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((B, 1), jnp.uint32),
                grid=(B // BB,),
                in_specs=[
                    pl.BlockSpec((BB,), lambda i: (i,)),
                    pl.BlockSpec((N2, 128), lambda i: (0, 0)),
                ],
                out_specs=pl.BlockSpec((BB, 1), lambda i: (i, 0)),
            )(jnp.clip(idx, 0, N2 - 1), tblv)
            return (idx + s[:, 0].astype(jnp.int32)) % N2

        slope(pstep, idxs, "pallas vmem take N=4096 row=512B")
    except Exception as e:
        print(f"pallas gather FAILED: {type(e).__name__}: {str(e)[:600]}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
