#!/usr/bin/env python
"""Round-2 component isolation: v6 full-depth walk cost + flat-gather
variants feeding the REAL scan (tools/profile_trie.py found the 3D
(T,R,5) rules gather costs ~2.4x a flat (T,R*5) gather of the same
bytes).

  v4 I  walk + flat-gather + reshape + current scan
  v4 J  walk + pad128-gather + lane-sliced B-major scan
  v6 L  walk only (full depth)
  v6 M  current full classify
  v6 I6 walk + flat-gather + reshape + current scan
  v6 D8/D5 walk truncated to 8/5 levels (timing-only, wrong verdicts):
        depth scaling of the v6 walk
"""
import sys

from _common import jax_setup, scale_args, setup_repo_path

setup_repo_path()

import numpy as np
import jax
import jax.numpy as jnp

from infw import testing
from infw.constants import KIND_IPV4, KIND_IPV6
from infw.kernels import jaxpath

from bench import chained_throughput


def main():
    on_tpu = jax_setup()
    n_entries, width = scale_args(sys.argv, 100_000, 2_000, on_tpu=on_tpu)
    rng = np.random.default_rng(2024)
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=width, ifindexes=(2, 3, 4))
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    kinds = np.asarray(batch.kind)
    dt = jaxpath.device_tables(tables)
    print(f"levels={len(dt.trie_levels)}", file=sys.stderr, flush=True)

    rules_np = np.asarray(dt.rules)
    T, R, _ = rules_np.shape
    rules_flat = jax.device_put(rules_np.reshape(T, R * 5))
    rules_pad = np.zeros((T, 128), np.uint16)
    rules_pad[:, : R * 5] = rules_np.reshape(T, R * 5)
    rules_pad = jax.device_put(rules_pad)

    def scan_flat(tabs, b):
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(rules_flat, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None], rows, 0)
        return jaxpath.rule_scan(rows.reshape(-1, R, 5), b)

    def scan_pad_lane(tabs, b):
        from infw.constants import (
            IPPROTO_ICMP, IPPROTO_ICMPV6, IPPROTO_SCTP, IPPROTO_TCP, IPPROTO_UDP)
        tidx = jaxpath.lpm_trie(tabs, b)
        rows = jnp.take(rules_pad, jnp.clip(tidx, 0), axis=0)
        rows = jnp.where((tidx >= 0)[:, None], rows, 0).astype(jnp.int32)
        # lane-sliced B-major scan: field f of rule r at lane r*5+f would
        # interleave; pad layout keeps (R,5) flattened -> slice strided
        r3 = rows[:, : R * 5].reshape(-1, R, 5)
        rid = r3[:, :, 0] & 0xFF
        act = r3[:, :, 0] >> 8
        rproto = r3[:, :, 1] & 0xFF
        it = r3[:, :, 1] >> 8
        ic = r3[:, :, 2]
        ps = r3[:, :, 3]
        pe = r3[:, :, 4]
        proto = b.proto[:, None]
        dport = b.dst_port[:, None]
        valid = rid != 0
        proto_eq = (rproto != 0) & (rproto == proto)
        is_transport = (
            (rproto == IPPROTO_TCP) | (rproto == IPPROTO_UDP) | (rproto == IPPROTO_SCTP))
        port_hit = jnp.where(pe == 0, dport == ps, (dport >= ps) & (dport < pe))
        fam = jnp.where(b.kind == KIND_IPV4, IPPROTO_ICMP, IPPROTO_ICMPV6)[:, None]
        icmp_hit = ((rproto == fam) & (it == b.icmp_type[:, None])
                    & (ic == b.icmp_code[:, None]))
        hit = valid & ((proto_eq & ((is_transport & port_hit) | icmp_hit)) | (rproto == 0))
        idx = jnp.arange(R, dtype=jnp.int32)[None, :]
        first = jnp.min(jnp.where(hit, idx, R), axis=1)
        any_hit = first < R
        sel = hit & (idx == first[:, None])
        rid_f = jnp.sum(jnp.where(sel, rid, 0), axis=1)
        act_f = jnp.sum(jnp.where(sel, act, 0), axis=1)
        return jnp.where(
            any_hit,
            ((rid_f.astype(jnp.uint32) & 0xFFFFFF) << 8)
            | (act_f.astype(jnp.uint32) & 0xFF),
            0,
        ).astype(jnp.uint32)

    def walk_only(tabs, b):
        return jaxpath.lpm_trie(tabs, b).astype(jnp.uint32)

    def full(tabs, b):
        res, _x, _s = jaxpath.classify(tabs, b, use_trie=True)
        return res

    results = {}

    # --- v4, truncated depth ---
    idx4 = np.nonzero(kinds == KIND_IPV4)[0]
    db4 = jaxpath.device_batch(batch.take(idx4))
    depth = jaxpath.v4_trie_depth(len(dt.trie_levels))
    dtv4 = dt._replace(trie_levels=dt.trie_levels[:depth])
    for name, fn in (
        ("v4 I flat+scan", scan_flat),
        ("v4 J pad128+lane-scan", scan_pad_lane),
    ):
        try:
            results[name] = chained_throughput(fn, dtv4, db4, len(idx4), on_tpu, name)
        except Exception as e:
            print(f"{name} FAILED: {e}", file=sys.stderr, flush=True)

    # --- v6, full depth ---
    idx6 = np.nonzero(kinds == KIND_IPV6)[0]
    db6 = jaxpath.device_batch(batch.take(idx6))
    v6_variants = [
        ("v6 L walk only", walk_only, dt),
        ("v6 M full classify", full, dt),
        ("v6 I6 flat+scan", scan_flat, dt),
        ("v6 D8 walk@8lvl (timing only)", walk_only,
         dt._replace(trie_levels=dt.trie_levels[:8])),
        ("v6 D5 walk@5lvl (timing only)", walk_only,
         dt._replace(trie_levels=dt.trie_levels[:5])),
    ]
    for name, fn, tabs in v6_variants:
        try:
            results[name] = chained_throughput(fn, tabs, db6, len(idx6), on_tpu, name)
        except Exception as e:
            print(f"{name} FAILED: {e}", file=sys.stderr, flush=True)

    print("\n=== summary ===", file=sys.stderr, flush=True)
    for name, thr in results.items():
        print(f"{name}: {thr/1e6:.1f} M pkts/s ({1e9/thr:.1f} ns/pkt)",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
