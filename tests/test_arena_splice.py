"""Cross-slab structural compression: shared-subtree planes (ISSUE-17).

Covers the decompose/recompose round trip (residual trunk + canonical
planes reproduce the whole-slab canonical bytes bit-exactly), the
near-copy share path (similar-NOT-identical tenants share the trunk and
every unchanged subtree plane; only divergent subtrees cost planes),
the subtree-granular edit alphabet (patch inside a private plane,
unsplice of a shared plane, CoW of a shared trunk) with bystander
byte-stability, the dedup sweep's plane re-merge, the /metrics splice
gauges, cross-tenant isolation with teeth on both ArenaClassifier and
MeshArenaClassifier (8 virtual devices), the zero-recompile warm drift
lifecycle, and the spliceleak injected defect / arena-splice statecheck
config.
"""
import numpy as np
import pytest

import jax

from infw import oracle, testing
from infw.backend.tpu import ArenaClassifier
from infw.compiler import IncrementalTables, LpmKey, \
    compile_tables_from_content
from infw.kernels import jaxpath
from infw.analysis.statecheck import check_arena


def _splice_content(n16=16, seed=5, width=4):
    """One deep entry per /16 — alternating /24 subnet and /32 host,
    the two masks whose subtrees leaf-push to a single target row, so
    every l0 slot factors into exactly one plane-eligible subtree."""
    rng = np.random.default_rng(seed)
    content = {}
    for i in range(n16):
        mask = 24 if i % 2 else 32
        data = bytes([10, i, 1 + i % 254, i % 251]) + bytes(12)
        content[LpmKey(mask + 32, 2, data)] = testing.random_rules(
            rng, width
        )
    return content


def _sspec(tabs, pages=6, max_tenants=8, planes=256):
    return jaxpath.arena_spec_for(
        "ctrie", tabs, pages=pages, max_tenants=max_tenants,
        plane_slots=planes, plane_node_rows=8, plane_target_rows=8,
        plane_joined_rows=8, splice_slots=64,
    )


def _classify(al, tab, tenant_id, n=48, seed=3):
    b = testing.random_batch(np.random.default_rng(seed), tab, n)
    spec = al.spec
    sp = {"spec": spec} if spec.spliced else {}
    fn = jaxpath.jitted_classify_arena_wire_fused(
        spec.family, spec.pages, spec.d_max, **sp
    )
    fused = fn(al.arena, jax.device_put(b.pack_wire()),
               jax.device_put(np.full(n, tenant_id, np.int32)))
    res16, _stats = jaxpath.split_wire_outputs(np.asarray(fused), n)
    results, _xdp = jaxpath.host_finalize_wire(res16, np.asarray(b.kind))
    return results, oracle.classify(tab, b).results


def _spliced_pair(n16=16):
    """Two tenants over the SAME content via independent updaters —
    trunk shared, every subtree plane shared (refcount 2)."""
    content = _splice_content(n16)
    u0 = IncrementalTables.from_content(dict(content), rule_width=4)
    u1 = IncrementalTables.from_content(dict(content), rule_width=4)
    s0, s1 = u0.snapshot(), u1.snapshot()
    spec = _sspec([s0, s1])
    al = jaxpath.ArenaAllocator(spec)
    assert al.load_tenant(0, s0) == "assign"
    assert al.load_tenant(1, s1) == "share"
    u0.start_dirty_tracking()
    u1.start_dirty_tracking()
    return al, u0, u1, s0, s1


def _edit(u, k, port):
    r = np.asarray(u.content[k]).copy()
    r[1] = [1, 6, port, 0, 0, 0, 1]
    u.apply({k: r}, [])
    return u.peek_dirty(), u.snapshot()


# --- decompose / recompose round trip ---------------------------------------


def test_decompose_recompose_roundtrip():
    content = _splice_content()
    tab = compile_tables_from_content(dict(content), rule_width=4)
    spec = _sspec([tab])
    arrays, n_nodes = jaxpath._ctrie_canonical_slab(spec, tab)
    dec = jaxpath._decompose_ctrie_slab(spec, arrays, n_nodes)
    assert dec is not None
    trunk, metas = dec
    # every /16 subtree factored; its l0 slot carries the splice tag
    assert len(metas) == len(content)
    tl0 = trunk[0]
    tagged = sorted(
        int(v) - int(jaxpath.SPLICE_TAG)
        for v in tl0[:, 0] if int(v) >= int(jaxpath.SPLICE_TAG)
    )
    assert tagged == [m.slot for m in metas]
    # factored node/target/joined rows are ZEROED in the trunk (content-
    # canonical residual form: structurally-identical tenants produce
    # bit-identical trunks)
    for m in metas:
        assert not trunk[1][m.node_rows].any()
        assert not trunk[2][m.tpos].any()
        assert not trunk[3][m.tidx].any()
    planes = [(m.plane[0], m.plane[1], m.plane[2], m.n_local)
              for m in metas]
    whole = jaxpath._recompose_ctrie_slab(spec, trunk, metas, planes)
    for got, want in zip(whole, arrays):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert jaxpath.slab_content_hash(whole, n_nodes) == \
        jaxpath.slab_content_hash(arrays, n_nodes)


def test_offset_plane_roundtrip():
    """Canonical plane -> resident (pool-global) form -> back is the
    identity; the resident form's indices all land inside the plane
    pool region (what lets the shared descent walk planes unmodified)."""
    content = _splice_content()
    tab = compile_tables_from_content(dict(content), rule_width=4)
    spec = _sspec([tab])
    arrays, n_nodes = jaxpath._ctrie_canonical_slab(spec, tab)
    _trunk, metas = jaxpath._decompose_ctrie_slab(spec, arrays, n_nodes)
    m = metas[0]
    ps = 3
    resident = jaxpath._offset_plane_slab(spec, m.plane, m.n_local, ps)
    back = jaxpath._unoffset_plane_slab(spec, resident, m.n_local, ps)
    for got, want in zip(back, m.plane):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --- near-copy sharing / plane refcounts ------------------------------------


def test_spliced_share_plane_refcounts_and_gauges():
    al, _u0, _u1, s0, s1 = _spliced_pair()
    assert al.page_of(0) == al.page_of(1)  # one shared residual trunk
    assert al.distinct_planes() == 16      # each subtree stored ONCE
    assert len(al.tenant_splices(0)) == 16
    assert al.tenant_splices(0) == al.tenant_splices(1)
    assert al.counters["plane_hits"] == 16  # tenant 1 wrote no plane
    cnt = al.counter_values()
    for gauge in ("arena_subtree_planes", "arena_shared_subtrees",
                  "arena_splice_rows", "splice_unsplices",
                  "splice_merges"):
        assert gauge in cnt, gauge
    assert cnt["arena_subtree_planes"] == 16
    assert cnt["arena_shared_subtrees"] == 16  # refcount > 1 planes
    assert cnt["arena_splice_rows"] == 32
    assert check_arena(al) == []
    r0, w0 = _classify(al, s0, 0)
    r1, w1 = _classify(al, s1, 1)
    np.testing.assert_array_equal(r0, w0)
    np.testing.assert_array_equal(r1, w1)


def test_near_copy_costs_only_divergent_planes():
    """A k-edit near-copy shares the trunk and all unchanged planes —
    the whole point of structural compression."""
    content = _splice_content()
    u = IncrementalTables.from_content(dict(content), rule_width=4)
    s0 = u.snapshot()
    keys = sorted(content, key=lambda k: k.ip_data)
    spec = _sspec([s0])
    al = jaxpath.ArenaAllocator(spec)
    al.load_tenant(0, s0)
    writes0 = al.counters["plane_writes"]
    for i in range(2):
        r = np.asarray(u.content[keys[i]]).copy()
        r[1] = [1, 6, 7000 + i, 0, 0, 0, 2]
        u.apply({keys[i]: r}, [])
    s1 = u.snapshot()
    # "share" is reserved for all-planes-hit loads; the near-copy still
    # lands on the SHARED residual trunk (content-addressed hash hit)
    al.load_tenant(1, s1)
    assert al.page_of(0) == al.page_of(1)
    # 2 divergent subtrees cost 2 plane writes; 14 planes re-shared
    assert al.counters["plane_writes"] - writes0 == 2
    assert al.distinct_planes() == 18
    assert check_arena(al) == []
    r0, w0 = _classify(al, s0, 0)
    r1, w1 = _classify(al, s1, 1)
    np.testing.assert_array_equal(r0, w0)
    np.testing.assert_array_equal(r1, w1)


# --- subtree-granular edits / bystander isolation ---------------------------


def test_unsplice_edit_isolates_bystander():
    al, u0, _u1, _s0, s1 = _spliced_pair()
    k = sorted(u0.content, key=lambda kk: kk.ip_data)[0]
    shared_plane = al.tenant_splices(0)[0] if 0 in al.tenant_splices(0) \
        else list(al.tenant_splices(0).values())[0]
    before1 = dict(al.tenant_splices(1))
    hint, snap = _edit(u0, k, 443)
    assert al.load_tenant(0, snap, hint=hint) == "unsplice"
    # the editor repointed ONE slot at a private plane; the bystander's
    # splice map is untouched and the old plane survives for it
    m0, m1 = al.tenant_splices(0), al.tenant_splices(1)
    assert m1 == before1
    diff = [s for s in m0 if m0[s] != m1[s]]
    assert len(diff) == 1
    assert al.page_of(0) == al.page_of(1)  # trunk still shared
    assert al.counters["splice_unsplices"] == 1
    assert check_arena(al) == []
    r0, w0 = _classify(al, snap, 0)
    np.testing.assert_array_equal(r0, w0)
    r1, w1 = _classify(al, s1, 1)
    np.testing.assert_array_equal(r1, w1)
    del shared_plane
    # a second edit of the SAME subtree now lands in the private plane
    hint2, snap2 = _edit(u0, k, 8443)
    assert al.load_tenant(0, snap2, hint=hint2) == "patch"
    assert al.tenant_splices(0) == m0
    assert check_arena(al) == []
    r0b, w0b = _classify(al, snap2, 0)
    np.testing.assert_array_equal(r0b, w0b)
    r1b, _ = _classify(al, s1, 1)
    np.testing.assert_array_equal(r1b, r1)  # bystander byte-stable


def test_dedup_sweep_remerges_reconverged_planes():
    al, u0, _u1, _s0, _s1 = _spliced_pair()
    k = sorted(u0.content, key=lambda kk: kk.ip_data)[0]
    orig = np.asarray(u0.content[k]).copy()
    hint, snap = _edit(u0, k, 9090)
    assert al.load_tenant(0, snap, hint=hint) == "unsplice"
    u0.clear_dirty()
    assert al.distinct_planes() == 17
    # edit BACK: the private plane's content re-converges with the
    # shared one; the sweep re-merges it (splice-row flip, no write)
    u0.apply({k: orig}, [])
    assert al.load_tenant(0, u0.snapshot(), hint=u0.peek_dirty()) \
        == "patch"
    rep = al.dedup_sweep()
    assert rep["plane_merged"] == 1
    assert al.distinct_planes() == 16
    assert al.tenant_splices(0) == al.tenant_splices(1)
    assert al.counters["splice_merges"] == 1
    assert check_arena(al) == []


def test_spliceleak_defect_caught_by_invariants():
    al, u0, _u1, _s0, _s1 = _spliced_pair()
    k = sorted(u0.content, key=lambda kk: kk.ip_data)[0]
    hint, snap = _edit(u0, k, 1234)
    jaxpath._INJECT_SPLICELEAK_BUG = True
    try:
        assert al.load_tenant(0, snap, hint=hint) == "unsplice"
        viols = check_arena(al)
    finally:
        jaxpath._INJECT_SPLICELEAK_BUG = False
    assert any("spliceleak" in v for v in viols), viols


# --- classifier-level isolation with teeth ----------------------------------


@pytest.mark.slow
def test_classifier_splice_isolation_oracle():
    """Two near-copy tenants through ArenaClassifier + TenantRegistry:
    both bit-identical to their oracles; a deep-key edit by one rides
    the splice path and diverges ONLY that tenant (the other compared
    byte-stable against its pre-edit output, not just the oracle)."""
    from infw.syncer import TenantRegistry

    content = _splice_content(n16=12, seed=7)
    base = compile_tables_from_content(dict(content), rule_width=4)
    spec = _sspec([base], pages=6, max_tenants=6)
    clf = ArenaClassifier(spec, interpret=True, fused_deep=False)
    reg = TenantRegistry(clf, rule_width=4)
    reg.create_tenant("a", dict(content))
    reg.create_tenant("b", dict(content))
    al = clf.allocator
    assert al.page_of(0) == al.page_of(1)
    assert al.distinct_planes() == 12
    ba = testing.random_batch(np.random.default_rng(11), base, 64)
    want = oracle.classify(base, ba).results
    out_a0 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    out_b0 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a0.results, want)
    np.testing.assert_array_equal(out_b0.results, want)
    k = sorted(content, key=lambda kk: kk.ip_data)[0]
    r = np.asarray(content[k]).copy()
    r[1] = [1, 0, 0, 0, 0, 0, 1]
    reg.update_tenant("b", {k: r}, [])
    # the edit stayed subtree-granular: trunk still shared, one plane
    # diverged — never an overlay detour, never a whole-slab clone
    assert al.page_of(0) == al.page_of(1)
    assert al.tenant_splices(0) != al.tenant_splices(1)
    merged = compile_tables_from_content(
        {**dict(content), k: r}, rule_width=4
    )
    out_b1 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(
        out_b1.results, oracle.classify(merged, ba).results
    )
    out_a1 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a1.results, out_a0.results)
    assert check_arena(al) == []
    clf.close()


@pytest.mark.slow
def test_mesh_splice_isolation():
    """The same share -> unsplice -> diverge-only-the-editor flow on
    MeshArenaClassifier (8 virtual devices): the splice table and plane
    pool are replicated like the page table, so the per-packet gather
    stays device-local."""
    from infw.backend.mesh import MeshArenaClassifier
    from infw.syncer import TenantRegistry

    if len(jax.devices()) < 8:
        pytest.skip("needs >= 8 virtual devices")
    content = _splice_content(n16=12, seed=9)
    base = compile_tables_from_content(dict(content), rule_width=4)
    spec = _sspec([base], pages=8, max_tenants=8)
    clf = MeshArenaClassifier(spec, data_shards=8)
    reg = TenantRegistry(clf, rule_width=4)
    reg.create_tenant("a", dict(content))
    reg.create_tenant("b", dict(content))
    al = clf.allocator
    assert al.page_of(0) == al.page_of(1)
    assert al.distinct_planes() == 12
    ba = testing.random_batch(np.random.default_rng(13), base, 64)
    want = oracle.classify(base, ba).results
    out_a0 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    out_b0 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a0.results, want)
    np.testing.assert_array_equal(out_b0.results, want)
    k = sorted(content, key=lambda kk: kk.ip_data)[0]
    r = np.asarray(content[k]).copy()
    r[1] = [1, 0, 0, 0, 0, 0, 2]
    reg.update_tenant("b", {k: r}, [])
    assert al.page_of(0) == al.page_of(1)
    assert al.tenant_splices(0) != al.tenant_splices(1)
    merged = compile_tables_from_content(
        {**dict(content), k: r}, rule_width=4
    )
    out_b1 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(
        out_b1.results, oracle.classify(merged, ba).results
    )
    out_a1 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a1.results, out_a0.results)
    assert check_arena(al) == []
    clf.close()


# --- zero-recompile warm drift lifecycle ------------------------------------


@pytest.mark.slow
def test_zero_recompile_warm_splice_lifecycle():
    """Once the spliced arena is warm (one load, one unsplice edit, one
    classify), the whole drift alphabet — near-copy create, unsplice,
    patch, classify — compiles and allocates nothing."""
    al, u0, u1, _s0, _s1 = _spliced_pair()
    keys = sorted(u0.content, key=lambda kk: kk.ip_data)
    hint, snap = _edit(u0, keys[0], 1111)
    assert al.load_tenant(0, snap, hint=hint) == "unsplice"  # warm edit
    b = testing.random_batch(np.random.default_rng(1), snap, 64)
    wire = jax.device_put(b.pack_wire())
    fn = jaxpath.jitted_classify_arena_wire_fused(
        "ctrie", al.spec.pages, al.spec.d_max, spec=al.spec
    )

    def classify(t):
        np.asarray(fn(al.arena, wire,
                      jax.device_put(np.full(64, t, np.int32))))

    classify(0)  # the one allowed compile of the classify factory
    scatter0 = jaxpath._scatter_rows_jit()._cache_size()
    fn0 = fn._cache_size()
    # near-copy create (trunk share + divergent plane), unsplice, patch
    hint2, snap2 = _edit(u1, keys[1], 2222)
    assert al.load_tenant(1, snap2, hint=hint2) == "unsplice"
    u1.clear_dirty()
    assert al.load_tenant(2, snap2) == "share"
    hint3, snap3 = _edit(u0, keys[0], 3333)
    assert al.load_tenant(0, snap3, hint=hint3) == "patch"
    for t in (0, 1, 2):
        classify(t)
    al.destroy_tenant(2)
    assert fn._cache_size() == fn0
    grew = jaxpath._scatter_rows_jit()._cache_size() - scatter0
    assert grew == 0, (
        f"{grew} scatter executable(s) compiled on the warm spliced "
        "drift lifecycle"
    )
    assert check_arena(al) == []


# --- statecheck config / defect acceptance ----------------------------------


@pytest.mark.slow
def test_statecheck_arena_splice_config_green():
    from infw.analysis import statecheck

    rep = statecheck.run_config("arena-splice", seed=0, n_ops=8,
                                shrink_on_failure=False)
    assert rep["ok"], rep


@pytest.mark.slow
def test_spliceleak_defect_caught_and_shrunk():
    from infw.analysis import statecheck

    jaxpath._INJECT_SPLICELEAK_BUG = True
    try:
        rep = statecheck.run_config("arena-splice", seed=0, n_ops=12,
                                    max_shrink_runs=64)
    finally:
        jaxpath._INJECT_SPLICELEAK_BUG = False
    assert not rep["ok"]
    assert rep["failure"]["phase"] == "invariant"
    assert rep["shrunk"]["ops"] <= 4
