"""Update-storm transaction tests (infw.txn + the ISSUE-9 wiring).

Covers: net-effect fold semantics (supersession, annihilation,
delete-then-readd, overlay eligibility, the injected fold defect);
TxnBatcher bounded-staleness policy; TxnApplier end-to-end (one folded
patch generation, oracle parity, rebuild escalation, overlay overflow
spill); the zero-recompile contract across transaction sizes
1/8/64/512; flush racing a generation swap (double-buffer contract);
the mesh-replicated transaction broadcast; the scheduler's
flush-occupies-a-pipeline-slot interleaving; the daemon's edits-dir
protocol (incl. scheduler mode) and the churngen determinism contract.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from infw import oracle, testing
from infw import txn as txn_mod
from infw.compiler import (
    IncrementalTables,
    LpmKey,
    compile_tables_from_content,
)
from infw.constants import IPPROTO_TCP
from infw.kernels import jaxpath
from infw.txn import (
    EditOp,
    FoldedTxn,
    TxnApplier,
    TxnBatcher,
    TxnStats,
    fold_ops,
    op_from_json,
    op_to_json,
    read_edit_file,
    write_edit_file,
)


def _key(a, b=0, c=0, mask=24, ifx=2):
    return LpmKey(mask + 32, ifx, bytes([10, a, b, c]) + bytes(12))


def _rules(port, action=2, width=4):
    rows = np.zeros((width, 7), np.int32)
    rows[1] = [1, IPPROTO_TCP, port, 0, 0, 0, action]
    return rows


def _content(n, width=4):
    return {
        _key(i // 256, i % 256): _rules(80 + (i % 1000), width=width)
        for i in range(n)
    }


# --- fold semantics ----------------------------------------------------------


def test_fold_supersession_last_writer_wins():
    k = _key(1)
    ops = [
        EditOp("rules_edit", k, _rules(80)),
        EditOp("rules_edit", k, _rules(81)),
        EditOp("order_change", k, _rules(82)),
    ]
    f = fold_ops(ops, {k.masked_identity()})
    assert f.n_ops == 3 and f.n_folded == 2
    assert list(f.upserts) == [k]
    assert int(f.upserts[k][1, 2]) == 82
    assert not f.deletes and not f.new_keys


def test_fold_add_then_delete_annihilates():
    k = _key(2)
    ops = [EditOp("cidr_add", k, _rules(80)), EditOp("key_delete", k)]
    f = fold_ops(ops, set())
    assert f.n_ops == 2 and f.n_effects == 0 and f.n_folded == 2


def test_fold_delete_of_live_key_ships():
    k = _key(3)
    f = fold_ops([EditOp("key_delete", k)], {k.masked_identity()})
    assert f.deletes == [k] and not f.upserts


def test_fold_delete_then_readd_is_upsert():
    """The supersession edge the injected defect corrupts: a live key
    deleted and re-added in one transaction folds to an in-place upsert
    of the re-add's rules (content-identical to sequential
    application)."""
    k = _key(4)
    ops = [EditOp("key_delete", k), EditOp("key_add", k, _rules(443))]
    f = fold_ops(ops, {k.masked_identity()})
    assert not f.deletes and list(f.upserts) == [k]
    assert int(f.upserts[k][1, 2]) == 443


def test_fold_new_key_kind_marks_overlay_eligibility():
    ka, kc = _key(5), _key(6)
    f = fold_ops(
        [EditOp("key_add", ka, _rules(1)), EditOp("cidr_add", kc, _rules(2))],
        set(),
    )
    assert f.new_keys[ka][1] == "key_add"
    assert f.new_keys[kc][1] == "cidr_add"


def test_fold_injected_defect_drops_readd():
    k = _key(7)
    ops = [EditOp("key_delete", k), EditOp("key_add", k, _rules(443))]
    txn_mod._INJECT_FOLD_BUG = True
    try:
        f = fold_ops(ops, {k.masked_identity()})
    finally:
        txn_mod._INJECT_FOLD_BUG = False
    # the buggy fold loses BOTH ops: the stale pre-delete rules survive
    assert f.n_effects == 0
    assert isinstance(f, FoldedTxn)


# --- batcher policy ----------------------------------------------------------


def test_batcher_deadline_and_batch_thresholds():
    now = [0.0]
    b = TxnBatcher(staleness_s=0.010, max_ops=4, clock=lambda: now[0])
    assert b.should_flush() is None
    b.queue(EditOp("rules_edit", _key(1), _rules(1)))
    assert b.should_flush() is None  # fresh and small: keep coalescing
    now[0] = 0.005
    assert b.should_flush() is None
    now[0] = 0.011
    assert b.should_flush() == "deadline"
    items = b.drain()
    assert len(items) == 1 and items[0][1] == 0.0
    assert b.should_flush() is None and len(b) == 0
    for i in range(4):
        b.queue(EditOp("rules_edit", _key(1), _rules(i)))
    assert b.should_flush() == "batch"  # batch beats deadline ordering


def test_batcher_validation():
    with pytest.raises(ValueError):
        TxnBatcher(staleness_s=0)
    with pytest.raises(ValueError):
        TxnBatcher(max_ops=0)


def test_txn_stats_counters_and_staleness_hist():
    s = TxnStats()
    s.note_flush(10, 4, 12, "deadline", False,
                 staleness_s=[50e-6, 5e-3, 2.0])
    s.note_flush(3, 0, 3, "batch", True)
    vals = s.counter_values()
    assert vals["patch_txn_total"] == 2
    assert vals["patch_txn_ops_total"] == 13
    assert vals["patch_txn_ops_folded_total"] == 4
    assert vals["patch_txn_dirty_rows_total"] == 15
    assert vals["patch_txn_escalations_total"] == 1
    assert vals["patch_txn_flush_deadline_total"] == 1
    assert vals["patch_txn_flush_batch_total"] == 1
    assert vals["patch_txn_staleness_us_bucket_le_100"] == 1
    assert vals["patch_txn_staleness_us_bucket_le_10000"] == 1
    assert vals["patch_txn_staleness_us_bucket_inf"] == 1


# --- edit-file protocol ------------------------------------------------------


def test_edit_file_round_trip(tmp_path):
    ops = [
        EditOp("rules_edit", _key(1), _rules(80)),
        EditOp("key_delete", _key(2)),
        EditOp("cidr_add", _key(3, mask=20), _rules(443, action=1)),
    ]
    path = str(tmp_path / "e.json")
    write_edit_file(path, ops)
    got = read_edit_file(path)
    assert len(got) == 3
    for a, b in zip(ops, got):
        assert a.kind == b.kind and a.key == b.key
        if a.rules is None:
            assert b.rules is None
        else:
            np.testing.assert_array_equal(a.rules, b.rules)
    # json forms are canonical too
    assert [op_to_json(o) for o in ops] == [op_to_json(o) for o in got]
    assert op_from_json(op_to_json(ops[0])).key == ops[0].key


def test_editop_validation():
    with pytest.raises(ValueError):
        EditOp("rules_edit", _key(1))  # rules required
    with pytest.raises(ValueError):
        EditOp("bogus", _key(1), _rules(1))


# --- the apply half ----------------------------------------------------------


def _mk_applier(n=60, force_path="trie", **kw):
    from infw.backend.tpu import TpuClassifier

    content = _content(n)
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = TpuClassifier(interpret=True, force_path=force_path)
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    return TxnApplier(clf, it, **kw), content


def _truth(applier):
    merged = dict(applier.updater.content)
    merged.update(applier.overlay)
    return merged


def _assert_oracle_parity(applier, seed=11, b=256):
    tables = compile_tables_from_content(_truth(applier), rule_width=4)
    batch = testing.random_batch(np.random.default_rng(seed), tables, b)
    ref = oracle.classify(tables, batch)
    out = applier.clf.classify(batch, apply_stats=False)
    np.testing.assert_array_equal(out.results, ref.results)


@pytest.mark.parametrize("force_path", ["trie", "ctrie"])
def test_applier_mixed_txn_one_patch_generation(force_path):
    """A mixed folded transaction (edits + delete + delete-then-readd)
    lands as ONE generation, on the patch path for rules-only content,
    and serves oracle-exact verdicts."""
    stats = TxnStats()
    applier, content = _mk_applier(force_path=force_path, stats=stats)
    keys = sorted(content, key=lambda k: k.ip_data)
    ops = [
        EditOp("rules_edit", keys[0], _rules(8080)),
        EditOp("rules_edit", keys[0], _rules(8081)),   # supersedes
        EditOp("key_delete", keys[1]),
        EditOp("key_add", keys[1], _rules(9090)),      # folds to upsert
        EditOp("rules_edit", keys[2], _rules(7070)),
    ]
    rep = applier.apply(ops, reason="batch")
    assert rep.n_ops == 5 and rep.n_folded == 2
    assert rep.mode == "patch" and not rep.escalated
    assert rep.dirty_rows > 0
    assert int(np.asarray(applier.updater.content[keys[1]])[1, 2]) == 9090
    _assert_oracle_parity(applier)
    assert stats.counter_values()["patch_txn_total"] == 1


def test_applier_structural_txn_and_escalation():
    """Adds/deletes ride the same flush; a mask the trie cannot absorb
    escalates to the columnar rebuild with the report saying so."""
    applier, content = _mk_applier()
    keys = sorted(content, key=lambda k: k.ip_data)
    rep = applier.apply([
        EditOp("key_add", _key(200, mask=24), _rules(1)),
        EditOp("key_delete", keys[0]),
    ])
    assert not rep.escalated
    _assert_oracle_parity(applier, seed=12)
    # a v6 /128 forces trie levels the /24-deep instance lacks
    deep = LpmKey(128 + 32, 2, bytes(range(16)))
    rep = applier.apply([EditOp("key_add", deep, _rules(2))])
    assert rep.escalated and rep.mode == "full"
    _assert_oracle_parity(applier, seed=13)


def test_applier_overlay_overflow_mid_txn_spills_to_main():
    """cidr_adds route to the overlay while it has room; the overflow
    mid-transaction spills the WHOLE overlay into the main table (one
    structural merge), never refuses."""
    applier, _content_ = _mk_applier(
        n=60, overlay_cap=4, overlay_min_main=10
    )
    adds = [
        EditOp("cidr_add", _key(100 + i, mask=26), _rules(1000 + i))
        for i in range(4)
    ]
    rep = applier.apply(adds)
    assert len(applier.overlay) == 4 and rep.mode == "patch"
    _assert_oracle_parity(applier, seed=14)
    more = [
        EditOp("cidr_add", _key(120 + i, mask=26), _rules(2000 + i))
        for i in range(3)
    ]
    rep = applier.apply(more)
    # overflow: everything merged structurally, overlay empty
    assert applier.overlay == {}
    idents = set(applier.updater._ident_to_t)
    for op in adds + more:
        assert op.key.masked_identity() in idents
    _assert_oracle_parity(applier, seed=15)


def test_applier_mesh_replicated_broadcast():
    """One transaction flush against the replicated mesh classifier:
    the fused patch broadcasts through the NamedSharding placement, the
    load stays on the patch path, verdicts stay oracle-exact."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device pool")
    from infw.backend.mesh import MeshTpuClassifier

    content = _content(60)
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = MeshTpuClassifier(
        data_shards=4, rules_shards=1, interpret=True, force_path="trie"
    )
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    applier = TxnApplier(clf, it)
    keys = sorted(content, key=lambda k: k.ip_data)
    rep = applier.apply([
        EditOp("rules_edit", keys[i], _rules(6000 + i)) for i in range(8)
    ])
    assert rep.mode == "patch" and rep.dirty_rows > 0
    _assert_oracle_parity(applier, seed=16)
    clf.close()


# --- zero-recompile contract across transaction sizes ------------------------


def _txn_scatter_cache_sizes():
    return (
        jaxpath._scatter_rows_jit()._cache_size()
        + jaxpath.jitted_txn_scatter(4)._cache_size()
        + jaxpath.jitted_txn_scatter(5)._cache_size()
    )


def test_txn_patch_zero_scatter_compiles_across_sizes():
    """The dirty-row-count ladder prewarm (warm_txn_scatters +
    warm_scatters max_rows) must cover every executable shape a
    rules-only transaction of 1..512 edits can launch: after the load's
    warm, flushes at sizes 1/8/64/512 compile NOTHING."""
    from infw.backend.tpu import TpuClassifier

    content = _content(2500)
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = TpuClassifier(interpret=True, force_path="trie")
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    applier = TxnApplier(clf, it)
    keys = sorted(content, key=lambda k: k.ip_data)
    size0 = _txn_scatter_cache_sizes()
    pos = 0
    for txn_size in (1, 8, 64, 512):
        ops = [
            EditOp("rules_edit", keys[pos + i], _rules(3000 + i))
            for i in range(txn_size)
        ]
        pos += txn_size
        rep = applier.apply(ops)
        assert rep.mode == "patch", (
            f"txn of {txn_size} fell off the patch path"
        )
    grew = _txn_scatter_cache_sizes() - size0
    assert grew == 0, (
        f"{grew} scatter executable(s) compiled across transaction "
        "sizes 1/8/64/512 — the dirty-row ladder prewarm missed a shape"
    )
    _assert_oracle_parity(applier, seed=17, b=512)


# --- flush racing a generation swap ------------------------------------------


def test_flush_racing_generation_swap_double_buffers():
    """A plan prepared against generation A must classify with A's
    verdicts even when a transaction flush installs generation B before
    the launch — and the next dispatch must see B (the double-buffer
    swap contract under churn)."""
    from infw.backend.tpu import TpuClassifier
    from infw.constants import ALLOW, DENY
    from infw.packets import PacketBatch

    k = _key(1)
    content = dict(_content(60))
    content[k] = _rules(80, action=DENY)
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = TpuClassifier(interpret=True, force_path="trie")
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    applier = TxnApplier(clf, it)

    batch = PacketBatch(
        kind=np.array([1], np.int32),
        l4_ok=np.array([1], np.int32),
        ifindex=np.array([2], np.int32),
        ip_words=np.array(
            [[(10 << 24) | (1 << 16) | 7, 0, 0, 0]], np.uint32
        ),
        proto=np.array([IPPROTO_TCP], np.int32),
        dst_port=np.array([80], np.int32),
        icmp_type=np.array([0], np.int32),
        icmp_code=np.array([0], np.int32),
        pkt_len=np.array([64], np.int32),
    )
    wire, v4o = batch.pack_wire_subset(np.asarray([0], np.int64))
    plan = clf.prepare_packed(wire, v4o)          # staged against gen A
    rep = applier.apply(
        [EditOp("rules_edit", k, _rules(80, action=ALLOW))]
    )
    assert rep.mode == "patch"
    out_a = clf.classify_prepared(plan, apply_stats=False).result()
    assert int(out_a.results[0]) & 0xFF == DENY, (
        "in-flight plan must finish on the generation it was staged "
        "against"
    )
    out_b = clf.classify(batch, apply_stats=False)
    assert int(out_b.results[0]) & 0xFF == ALLOW, (
        "post-flush dispatch must see the new generation"
    )
    clf.close()


# --- scheduler interleaving --------------------------------------------------


def test_scheduler_flush_occupies_pipeline_slot():
    """A tripped bounded-staleness flush runs DURING serving, holding
    one pipeline slot: the serve completes, the flush lands exactly
    once, and verdicts stay oracle-exact (the edit touches a key the
    witness stream never matches)."""
    from infw.backend.tpu import TpuClassifier
    from infw.scheduler import ContinuousScheduler, DeadlinePolicy

    content = _content(60)
    tables = compile_tables_from_content(content, rule_width=4)
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = TpuClassifier(interpret=True, force_path="trie")
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    applier = TxnApplier(clf, it)
    batcher = TxnBatcher(staleness_s=1e-4, max_ops=64)
    flushes = []

    def flush(items, reason):
        applier.apply([op for op, _ts in items], reason=reason,
                      enqueue_ts=[ts for _op, ts in items])
        flushes.append((len(items), reason))

    keys = sorted(content, key=lambda k: k.ip_data)
    for i in range(6):
        batcher.queue(EditOp("rules_edit", keys[i], _rules(5000 + i)))
    batch = testing.random_batch(np.random.default_rng(5), tables, 192)
    ref = oracle.classify(tables, batch)
    sched = ContinuousScheduler(
        clf, DeadlinePolicy(0.5, 64), pipeline_depth=2,
        txn_batcher=batcher, txn_flush=flush,
    )
    res = sched.serve(batch, np.zeros(192))
    assert flushes and sum(n for n, _r in flushes) == 6
    assert flushes[0][1] in ("deadline", "batch")
    # the witness stream predates the edit keys' port space: verdicts
    # must match the pre-edit oracle bit-exactly
    np.testing.assert_array_equal(res.results, ref.results)
    assert len(batcher) == 0
    clf.close()


def test_scheduler_flush_error_surfaces():
    from infw.backend.tpu import TpuClassifier
    from infw.scheduler import ContinuousScheduler, DeadlinePolicy

    content = _content(40)
    tables = compile_tables_from_content(content, rule_width=4)
    clf = TpuClassifier(interpret=True, force_path="trie")
    clf.load_tables(tables)
    batcher = TxnBatcher(staleness_s=1e-4, max_ops=4)
    batcher.queue(EditOp("rules_edit", _key(1), _rules(1)))

    def bad_flush(items, reason):
        raise RuntimeError("flush exploded")

    sched = ContinuousScheduler(
        clf, DeadlinePolicy(0.5, 64), pipeline_depth=2,
        txn_batcher=batcher, txn_flush=bad_flush,
    )
    batch = testing.random_batch(np.random.default_rng(6), tables, 64)
    with pytest.raises(RuntimeError, match="flush exploded"):
        sched.serve(batch, np.zeros(64))
    clf.close()


# --- daemon edits-dir protocol ----------------------------------------------


def _drop_json(path, doc):
    with open(path + ".tmp", "w") as f:
        json.dump(doc, f)
    os.replace(path + ".tmp", path)


def _wait(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _mk_daemon(tmp_path, **kw):
    from infw.daemon import Daemon
    from infw.interfaces import Interface, InterfaceRegistry

    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    d = Daemon(
        state_dir=str(tmp_path / "state"), node_name="n0",
        namespace="ns", backend="cpu", poll_period_s=0.05,
        registry=reg, metrics_port=0, health_port=0,
        file_poll_interval_s=0.02, **kw,
    )
    d.start()
    return d


def _sync_daemon_rules(d):
    from test_daemon import node_state

    ns_doc = node_state(name="n0", namespace="ns").to_dict()
    _drop_json(os.path.join(d.nodestates_dir, "n0.json"), ns_doc)
    assert _wait(lambda: d.syncer.classifier is not None
                 and d.syncer.classifier.tables is not None)


@pytest.mark.parametrize("sched_mode", [False, True])
def test_daemon_edits_dir_applies_transaction(tmp_path, sched_mode):
    """Edit files dropped into <state-dir>/edits/ coalesce and flush as
    one folded transaction between admissions: the edited verdict goes
    live, txn counters land on /metrics, and the PatchTxnRecord line
    reaches the event log.  sched_mode runs the same protocol under the
    deadline scheduler's tick (edits applied between admissions)."""
    import urllib.request

    from infw.daemon import write_frames_file
    from infw.obs.pcap import build_frame

    kw = dict(patch_staleness_us=200.0)
    if sched_mode:
        kw.update(deadline_us=50000.0, max_batch=256)
    d = _mk_daemon(tmp_path, **kw)
    try:
        _sync_daemon_rules(d)
        content = d.syncer.get_classifier_map_content_for_test()
        (key, rows), = [
            (k, v) for k, v in content.items()
            if k.ip_data[:1] == bytes([10])
        ]
        new_rows = np.asarray(rows, np.int32).copy()
        new_rows[1, 2] = 81  # deny :81 instead of :80
        op = EditOp("rules_edit", key, new_rows)
        write_edit_file(
            os.path.join(d.edits_dir, "e0.json"), [op]
        )
        assert _wait(lambda: d.txn_stats.counter_values()[
            "patch_txn_total"] >= 1)
        # the edited rule is live: :81 now denies, :80 passes
        frames = [
            build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 81),
            build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80),
        ]
        write_frames_file(
            os.path.join(d.ingest_dir, "t1.frames"), frames, 10
        )
        vp = os.path.join(d.out_dir, "t1.frames.verdicts.json")
        assert _wait(lambda: os.path.exists(vp))
        with open(vp) as f:
            summary = json.load(f)
        assert summary["drop"] == 1 and summary["pass"] == 1
        port = d.actual_metrics_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as r:
            text = r.read().decode()
        assert "patch_txn_total" in text
        assert "patch_txn_staleness_us_bucket" in text
        assert _wait(lambda: "patch-txn:" in open(d.events_path).read())
    finally:
        d.stop()


def test_daemon_bad_edit_file_consumed(tmp_path):
    d = _mk_daemon(tmp_path)
    try:
        _sync_daemon_rules(d)
        bad = os.path.join(d.edits_dir, "bad.json")
        with open(bad, "w") as f:
            f.write("{not json")
        good_op = None
        content = d.syncer.get_classifier_map_content_for_test()
        k = next(iter(content))
        good_op = EditOp("rules_edit", k, _rules(82))
        write_edit_file(os.path.join(d.edits_dir, "good.json"), [good_op])
        # the bad file is consumed and the good one applied
        assert _wait(lambda: not os.path.exists(bad))
        assert _wait(lambda: d.txn_stats.counter_values()[
            "patch_txn_total"] >= 1)
    finally:
        d.stop()


# --- churngen ----------------------------------------------------------------


def test_churngen_deterministic(tmp_path):
    """Same seed -> byte-identical edit files (the open-loop generator
    contract), parseable by the daemon-side reader."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    outs = []
    for name in ("a", "b"):
        out = str(tmp_path / name)
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "churngen.py"),
             "--out", out, "--rate", "1000000", "--n", "48",
             "--entries", "40", "--file-ops", "16", "--seed", "3"],
            capture_output=True, text=True, env=env,
            cwd=os.path.join(repo, "tools"),
        )
        assert r.returncode == 0, r.stderr
        outs.append(out)
    files_a = sorted(
        f for f in os.listdir(outs[0])
        if f.startswith("churn") and not f.endswith("-manifest.json")
    )
    files_b = sorted(
        f for f in os.listdir(outs[1])
        if f.startswith("churn") and not f.endswith("-manifest.json")
    )
    assert files_a == files_b and len(files_a) == 3
    for fn in files_a:
        a = open(os.path.join(outs[0], fn), "rb").read()
        b = open(os.path.join(outs[1], fn), "rb").read()
        assert a == b
        ops = read_edit_file(os.path.join(outs[0], fn))
        assert len(ops) == 16
        assert all(op.kind in txn_mod.TXN_EDIT_KINDS for op in ops)
