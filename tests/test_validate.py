"""T1: admission validation semantics (reference pkg/webhook/webhook.go and
its envtest suite pkg/webhook/webhook_suite_test.go accept/reject matrix)."""
import pytest

from infw import validate
from infw.spec import (
    ACTION_ALLOW,
    ACTION_DENY,
    IngressNodeFirewall,
    IngressNodeFirewallICMPRule,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallRules,
    IngressNodeFirewallSpec,
    IngressNodeProtocolConfig,
    ObjectMeta,
)


def inf(name="fw", cidrs=("10.0.0.0/24",), rules=(), interfaces=("eth0",), selector=None):
    return IngressNodeFirewall(
        metadata=ObjectMeta(name=name),
        spec=IngressNodeFirewallSpec(
            node_selector=dict(selector or {}),
            ingress=[
                IngressNodeFirewallRules(source_cidrs=list(cidrs), rules=list(rules))
            ],
            interfaces=list(interfaces),
        ),
    )


def tcp_rule(order, ports, action=ACTION_DENY):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol="TCP", tcp=IngressNodeFirewallProtoRule(ports=ports)
        ),
        action=action,
    )


def udp_rule(order, ports, action=ACTION_DENY):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol="UDP", udp=IngressNodeFirewallProtoRule(ports=ports)
        ),
        action=action,
    )


def icmp_rule(order, t=8, c=0, action=ACTION_DENY):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol="ICMP", icmp=IngressNodeFirewallICMPRule(icmp_type=t, icmp_code=c)
        ),
        action=action,
    )


def test_valid_tcp_rule_accepted():
    assert validate.validate_ingress_node_firewall(inf(rules=[tcp_rule(1, 8080)])) == []


def test_valid_range_rule_accepted():
    assert validate.validate_ingress_node_firewall(inf(rules=[tcp_rule(1, "800-900")])) == []


def test_valid_icmp_rule_accepted():
    assert validate.validate_ingress_node_firewall(inf(rules=[icmp_rule(1)])) == []


def test_invalid_cidr_rejected():
    errs = validate.validate_ingress_node_firewall(inf(cidrs=["10.0.0.0"]))
    assert any("CIDR" in e for e in errs)


def test_empty_cidrs_rejected():
    # Schema tier (MinItems:=1) fires first, like the API server would.
    errs = validate.validate_ingress_node_firewall(inf(cidrs=[]))
    assert any("should have at least 1 items" in e for e in errs)
    # The webhook-tier check still exists beneath it.
    errs = validate.validate_inf_rules(inf(cidrs=[]), [])
    assert any("at least one sourceCIDR" in e for e in errs)


def test_ipv6_cidr_accepted():
    assert validate.validate_ingress_node_firewall(inf(cidrs=["2002:db8::/32"])) == []


def test_blank_interface_rejected():
    errs = validate.validate_ingress_node_firewall(inf(interfaces=[""]))
    assert any("blank" in e for e in errs)


def test_long_interface_rejected():
    errs = validate.validate_ingress_node_firewall(inf(interfaces=["x" * 17]))
    assert any("too long" in e for e in errs)


def test_numeric_leading_interface_rejected():
    errs = validate.validate_ingress_node_firewall(inf(interfaces=["3eth0"]))
    assert any("can't start with a number" in e for e in errs)


def test_duplicate_order_rejected():
    errs = validate.validate_ingress_node_firewall(
        inf(rules=[tcp_rule(1, 8080), tcp_rule(1, 9090)])
    )
    assert any("unique order" in e for e in errs)


def test_too_many_rules_rejected():
    rules = [tcp_rule(i, 1000 + i) for i in range(1, 102)]
    errs = validate.validate_ingress_node_firewall(inf(rules=rules))
    assert any("no more than 100 rules" in e for e in errs)


def test_icmp_rule_with_ports_rejected():
    bad = icmp_rule(1)
    bad.protocol_config.tcp = IngressNodeFirewallProtoRule(ports=80)
    # Schema tier: the tcp union member is forbidden for protocol ICMP.
    errs = validate.validate_ingress_node_firewall(inf(rules=[bad]))
    assert any("tcp is required when protocol is TCP, and forbidden otherwise" in e for e in errs)
    # Webhook tier beneath it still rejects on its own.
    errs = validate.validate_inf_rules(inf(rules=[bad]), [])
    assert any("ports are erroneously defined" in e for e in errs)


def test_tcp_rule_without_ports_rejected():
    bad = IngressNodeFirewallProtocolRule(
        order=1, protocol_config=IngressNodeProtocolConfig(protocol="TCP")
    )
    errs = validate.validate_ingress_node_firewall(inf(rules=[bad]))
    assert any("tcp is required when protocol is TCP" in e for e in errs)
    errs = validate.validate_inf_rules(inf(rules=[bad]), [])
    assert any("no port defined" in e for e in errs)


def test_tcp_rule_with_icmp_rejected():
    bad = tcp_rule(1, 80)
    bad.protocol_config.icmp = IngressNodeFirewallICMPRule()
    errs = validate.validate_ingress_node_firewall(inf(rules=[bad]))
    assert any("icmp is required when protocol is ICMP, and forbidden otherwise" in e for e in errs)
    errs = validate.validate_inf_rules(inf(rules=[bad]), [])
    assert any("ICMP type/code defined" in e for e in errs)


@pytest.mark.parametrize("port", [6443, 2380, 2379, 22, 10250, 10259, 10257])
def test_deny_on_tcp_failsafe_port_rejected(port):
    errs = validate.validate_ingress_node_firewall(inf(rules=[tcp_rule(1, port)]))
    assert any("conflict with access to" in e for e in errs)


def test_deny_on_udp_failsafe_port_rejected():
    errs = validate.validate_ingress_node_firewall(inf(rules=[udp_rule(1, 68)]))
    assert any("conflict with access to DHCP" in e for e in errs)


def test_allow_on_failsafe_port_accepted():
    assert (
        validate.validate_ingress_node_firewall(
            inf(rules=[tcp_rule(1, 22, action=ACTION_ALLOW)])
        )
        == []
    )


def test_deny_range_covering_failsafe_rejected_closed_interval():
    # The webhook's range check is closed [start, end] (webhook.go:316-318):
    # 6000-6443 conflicts even though the dataplane range match is half-open.
    errs = validate.validate_ingress_node_firewall(inf(rules=[tcp_rule(1, "6000-6443")]))
    assert any("port range is in conflict" in e for e in errs)


def test_deny_range_not_covering_failsafe_accepted():
    assert validate.validate_ingress_node_firewall(inf(rules=[tcp_rule(1, "6444-6500")])) == []


def test_cross_inf_order_overlap_rejected():
    existing = inf(name="other", rules=[tcp_rule(1, 8080)])
    new = inf(name="new", rules=[tcp_rule(1, 9090)])
    errs = validate.validate_ingress_node_firewall(new, existing=[existing])
    assert any("conflicts with IngressNodeFirewall" in e for e in errs)


def test_cross_inf_no_overlap_with_different_selector():
    existing = inf(name="other", rules=[tcp_rule(1, 8080)], selector={"role": "worker"})
    new = inf(name="new", rules=[tcp_rule(1, 9090)])
    assert validate.validate_ingress_node_firewall(new, existing=[existing]) == []


def test_cross_inf_no_overlap_with_different_cidr():
    existing = inf(name="other", cidrs=["192.168.0.0/16"], rules=[tcp_rule(1, 8080)])
    new = inf(name="new", rules=[tcp_rule(1, 9090)])
    assert validate.validate_ingress_node_firewall(new, existing=[existing]) == []


def test_same_object_update_not_conflicting_with_itself():
    existing = inf(name="same", rules=[tcp_rule(1, 8080)])
    new = inf(name="same", rules=[tcp_rule(1, 9090)])
    assert validate.validate_ingress_node_firewall(new, existing=[existing]) == []
