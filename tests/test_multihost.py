"""Multi-host layer (DCN story) validated in single-process mode on the
virtual 8-device mesh: mesh layout invariants (rules axis stays
process-local), local-data assembly via make_array_from_process_local_data,
and the full multihost classify path bit-exact vs the oracle."""
import os
import numpy as np
import pytest

from infw import oracle, testing
from infw.parallel import mesh as meshmod
from infw.parallel import multihost as mh


def test_init_distributed_single_process_noop(monkeypatch):
    monkeypatch.delenv("INFW_COORDINATOR", raising=False)
    assert mh.init_distributed() is False
    # explicit n=1 is also a no-op regardless of coordinator
    assert mh.init_distributed("127.0.0.1:9999", 1, 0) is False


def test_global_mesh_rules_axis_is_process_local():
    m = mh.make_global_mesh(rules_shards=4)
    assert m.shape == {"data": 2, "rules": 4}
    # every rules-group row lives in one process (ICI containment)
    for row in m.devices:
        assert len({d.process_index for d in row}) == 1


def test_global_mesh_rejects_non_dividing_shards():
    with pytest.raises(ValueError):
        mh.make_global_mesh(rules_shards=3)


def test_process_local_rows_cover_batch():
    m = mh.make_global_mesh(rules_shards=4)
    lo, hi = mh.process_local_rows(m, 1024)
    # single process: every data shard is local
    assert (lo, hi) == (0, 1024)


def test_classify_multihost_trie_matches_oracle():
    rng = np.random.default_rng(17)
    tables = testing.random_tables_fast(
        rng, n_entries=500, width=8, group_size=6
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=1024)
    m = mh.make_global_mesh(rules_shards=4)
    placed = meshmod.shard_tables_trie(tables, m)
    results, xdp, stats = mh.classify_multihost_trie(m, placed, batch)
    ref = oracle.classify(tables, batch)
    np.testing.assert_array_equal(results, ref.results)
    np.testing.assert_array_equal(xdp, ref.xdp)
    from infw.kernels import jaxpath

    got = testing.stats_dict_from_array(jaxpath.merge_stats_host(stats))
    assert got == ref.stats


def test_classify_multihost_streams_batches_against_placed_tables():
    rng = np.random.default_rng(19)
    tables = testing.random_tables_fast(rng, n_entries=64, width=8)
    m = mh.make_global_mesh(rules_shards=2)
    placed = meshmod.shard_tables_trie(tables, m)
    for seed in (1, 2):
        b = testing.random_batch_fast(
            np.random.default_rng(seed), tables, n_packets=256
        )
        results, xdp, _ = mh.classify_multihost_trie(m, placed, b)
        ref = oracle.classify(tables, b)
        np.testing.assert_array_equal(results, ref.results)
        np.testing.assert_array_equal(xdp, ref.xdp)


def test_classify_multihost_trie_tail_chunk():
    """Arbitrary-length tail chunks (the daemon's last ingest chunk) are
    padded to the data-shard grid and trimmed on readback."""
    rng = np.random.default_rng(23)
    tables = testing.random_tables_fast(rng, n_entries=64, width=8)
    m = mh.make_global_mesh(rules_shards=4)  # data=2 shards
    placed = meshmod.shard_tables_trie(tables, m)
    batch = testing.random_batch_fast(rng, tables, n_packets=1001)
    results, xdp, _ = mh.classify_multihost_trie(m, placed, batch)
    assert len(results) == 1001 and len(xdp) == 1001
    ref = oracle.classify(tables, batch)
    np.testing.assert_array_equal(results, ref.results)
    np.testing.assert_array_equal(xdp, ref.xdp)


def test_two_process_group_classify_matches_oracle(tmp_path):
    """REAL multi-process validation: two daemon-like processes join a
    jax.distributed group (Gloo over localhost — the DCN stand-in), build
    the global mesh, each contributes its own half of the packets, and
    the assembled verdicts must be bit-exact vs the oracle with stats
    replicated on every host."""
    import socket
    import subprocess
    import sys as _sys
    import time as _time

    from infw.kernels import jaxpath

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__), "_mh_worker.py")
    procs = []
    logs = [tmp_path / "rank0.log", tmp_path / "rank1.log"]
    try:
        for r in (0, 1):
            with open(logs[r], "wb") as lf:  # child dups the fd; parent closes
                procs.append(subprocess.Popen(
                    [_sys.executable, worker, str(r), str(port), str(tmp_path)],
                    stdout=lf, stderr=subprocess.STDOUT,
                ))
        # poll both: if either worker dies early, fail immediately with
        # ITS log instead of burning the full timeout on the survivor
        deadline = _time.time() + 180
        while _time.time() < deadline:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                break
            if any(rc is not None and rc != 0 for rc in rcs):
                break
            _time.sleep(0.3)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)  # reap; poll() after bare kill() is racy
    rcs = [p.poll() for p in procs]
    # report the rank that actually FAILED, not a survivor we killed
    culprits = [r for r, rc in enumerate(rcs) if rc not in (0, None, -9)] or [
        r for r, rc in enumerate(rcs) if rc != 0
    ]
    assert all(rc == 0 for rc in rcs), "".join(
        f"\nrank {r} rc={rcs[r]}:\n{logs[r].read_text()[-3000:]}"
        for r in culprits
    )

    r0 = np.load(tmp_path / "rank0.npz")
    r1 = np.load(tmp_path / "rank1.npz")
    import _mh_params as mp

    rng = np.random.default_rng(mp.SEED)
    tables = testing.random_tables(rng, n_entries=mp.N_ENTRIES, width=mp.WIDTH,
                                   overlap_fraction=mp.OVERLAP)
    batch = testing.random_batch(rng, tables, n_packets=mp.N_PACKETS)
    ref = oracle.classify(tables, batch)
    half = mp.N_PACKETS // 2
    assert (int(r0["lo"]), int(r0["hi"])) == (0, half)
    assert (int(r1["lo"]), int(r1["hi"])) == (half, mp.N_PACKETS)
    res = np.concatenate([r0["res"], r1["res"]])
    xdp = np.concatenate([r0["xdp"], r1["xdp"]])
    np.testing.assert_array_equal(res, ref.results)
    np.testing.assert_array_equal(xdp, ref.xdp)
    # the stats psum is the one DCN collective: replicated and exact
    np.testing.assert_array_equal(r0["stats"], r1["stats"])
    got = testing.stats_dict_from_array(
        jaxpath.merge_stats_host(np.asarray(r0["stats"]))
    )
    assert got == ref.stats
