"""Daemon tests: NodeState controller semantics (mock seam + finalizer
dance, the port of ingressnodefirewallnodestate_controller_test.go) and a
file-driven daemon e2e — state dir in, verdicts/metrics/events out (the
role of the reference's functional e2e suite on a single node)."""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

import infw.nodestate_controller as nsc_mod
from infw.constants import IPPROTO_TCP
from infw.daemon import Daemon, read_frames_file, write_frames_file
from infw.interfaces import Interface, InterfaceRegistry
from infw.nodestate_controller import (
    INGRESS_NODE_FIREWALL_FINALIZER,
    NodeStateReconciler,
)
from infw.obs.pcap import build_frame
from infw.spec import (
    ACTION_DENY,
    IngressNodeFirewallNodeState,
    IngressNodeFirewallNodeStateSpec,
    ObjectMeta,
)
from infw.store import InMemoryStore, NotFoundError
from test_syncer import ingress, tcp_rule

NS = "ingress-node-firewall-system"
NODE = "tpu-worker-0"


class MockSyncer:
    """ebpfSingletonMock (ingressnodefirewallnodestate_controller_test.go:22-31):
    captures the last rules map instead of touching the dataplane."""

    def __init__(self):
        self.calls = []

    def sync_interface_ingress_rules(self, rules, is_delete):
        self.calls.append((rules, is_delete))


def node_state(name=NODE, namespace=NS, rules=None):
    return IngressNodeFirewallNodeState(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=IngressNodeFirewallNodeStateSpec(
            interface_ingress_rules=rules
            or {"dummy0": [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]}
        ),
    )


@pytest.fixture
def store():
    return InMemoryStore()


@pytest.fixture
def mock_syncer(monkeypatch):
    m = MockSyncer()
    monkeypatch.setattr(nsc_mod, "mock", m)
    yield m
    monkeypatch.setattr(nsc_mod, "mock", None)


def test_nodestate_filters_other_nodes(store, mock_syncer):
    r = NodeStateReconciler(store, syncer=None, node_name=NODE, namespace=NS)
    store.create(node_state(name="other-node"))
    r.reconcile("other-node", NS)      # not our node
    r.reconcile(NODE, "other-ns")      # not our namespace
    assert mock_syncer.calls == []


def test_nodestate_sync_and_finalizer(store, mock_syncer):
    r = NodeStateReconciler(store, syncer=None, node_name=NODE, namespace=NS)
    store.create(node_state())
    r.reconcile(NODE, NS)
    assert len(mock_syncer.calls) >= 1
    rules, is_delete = mock_syncer.calls[-1]
    assert not is_delete and "dummy0" in rules
    obj = store.get(IngressNodeFirewallNodeState.KIND, NODE, NS)
    assert INGRESS_NODE_FIREWALL_FINALIZER in obj.metadata.finalizers


def test_nodestate_deletion_syncs_delete_then_removes_finalizer(store, mock_syncer):
    r = NodeStateReconciler(store, syncer=None, node_name=NODE, namespace=NS)
    store.create(node_state())
    r.reconcile(NODE, NS)
    store.delete(IngressNodeFirewallNodeState.KIND, NODE, NS)  # sets deletion ts
    r.reconcile(NODE, NS)
    assert mock_syncer.calls[-1][1] is True  # is_delete
    with pytest.raises(NotFoundError):  # finalizer removed -> object GC'd
        store.get(IngressNodeFirewallNodeState.KIND, NODE, NS)


def test_nodestate_missing_object_is_noop(store, mock_syncer):
    r = NodeStateReconciler(store, syncer=None, node_name=NODE, namespace=NS)
    r.reconcile(NODE, NS)
    assert mock_syncer.calls == []


def test_nodestate_deletion_retry_after_transient_sync_failure(store, monkeypatch):
    """A transient failure of the is_delete sync must not wedge the object:
    a repeated delete() re-notifies watchers so the finalizer teardown is
    retried (the role controller-runtime's error requeue plays in the
    reference)."""
    from infw.syncer import SyncError as SE

    class FlakySyncer:
        def __init__(self):
            self.fail_next = 1

        def sync_interface_ingress_rules(self, rules, is_delete):
            if is_delete and self.fail_next > 0:
                self.fail_next -= 1
                raise SE("transient")

    flaky = FlakySyncer()
    r = NodeStateReconciler(store, syncer=flaky, node_name=NODE, namespace=NS)
    store.watch(
        IngressNodeFirewallNodeState.KIND,
        lambda ev, obj: _safe_reconcile(r, obj),
    )
    store.create(node_state())
    # first delete: teardown raises, finalizer stays, object wedged-but-alive
    store.delete(IngressNodeFirewallNodeState.KIND, NODE, NS)
    assert store.get(IngressNodeFirewallNodeState.KIND, NODE, NS)
    # retry (manager's next full reconcile deletes stale objects again)
    store.delete(IngressNodeFirewallNodeState.KIND, NODE, NS)
    with pytest.raises(NotFoundError):
        store.get(IngressNodeFirewallNodeState.KIND, NODE, NS)


def _safe_reconcile(r, obj):
    from infw.syncer import SyncError as SE

    try:
        r.reconcile(obj.metadata.name, obj.metadata.namespace)
    except SE:
        pass


# --- frames-file format -------------------------------------------------------

def test_frames_file_roundtrip(tmp_path):
    frames = [
        build_frame("192.0.2.1", "10.0.0.1", IPPROTO_TCP, 1, 80),
        build_frame("192.0.2.2", "10.0.0.1", IPPROTO_TCP, 2, 81),
    ]
    path = str(tmp_path / "x.frames")
    write_frames_file(path, frames, ifindex=[2, 3])
    got_frames, got_idx = read_frames_file(path)
    assert got_frames == frames and got_idx == [2, 3]


# --- daemon e2e ---------------------------------------------------------------

@pytest.fixture
def daemon(tmp_path):
    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    d = Daemon(
        state_dir=str(tmp_path / "state"),
        node_name=NODE,
        namespace=NS,
        backend="cpu",
        poll_period_s=0.05,
        debug_lookup=True,
        registry=reg,
        metrics_port=0,
        health_port=0,
        file_poll_interval_s=0.02,
    )
    d.start()
    yield d
    d.stop()


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _http_get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_daemon_end_to_end(daemon):
    # 1. apply desired state via the state dir (the "kubectl apply")
    ns_doc = node_state().to_dict()
    path = os.path.join(daemon.nodestates_dir, f"{NODE}.json")
    with open(path + ".tmp", "w") as f:
        json.dump(ns_doc, f)
    os.replace(path + ".tmp", path)
    assert _wait(lambda: daemon.syncer.classifier is not None
                 and daemon.syncer.classifier.tables is not None)
    assert daemon.syncer.attached_interfaces() == {"dummy0"}

    # 2. replay traffic through the ingest dir
    frames = [
        build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80),  # deny
        build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 81),  # pass
    ]
    write_frames_file(os.path.join(daemon.ingest_dir, "t1.frames"), frames, 10)
    verdict_path = os.path.join(daemon.out_dir, "t1.frames.verdicts.json")
    assert _wait(lambda: os.path.exists(verdict_path))
    with open(verdict_path) as f:
        summary = json.load(f)
    assert summary["drop"] == 1 and summary["pass"] == 1

    # 3. metrics endpoint (e2e.go:1143-1356 curls the daemon /metrics)
    port = daemon.actual_metrics_port
    daemon.stats.update_metrics(daemon.syncer.classifier)
    text = _http_get(port, "/metrics")
    assert "ingressnodefirewall_node_packet_deny_total 1" in text
    assert _http_get(port, "/healthz") == "ok"

    # 4. deny events land in the event log (sidecar-stdout equivalent)
    assert _wait(lambda: os.path.exists(daemon.events_path)
                 and "ruleId 1 action Drop" in open(daemon.events_path).read())
    content = open(daemon.events_path).read()
    assert "\tipv4 src addr 10.1.2.3" in content
    assert "\ttcp srcPort 999 dstPort 80" in content

    # 5. debug lookup buffer exposed over HTTP
    keys = json.loads(_http_get(port, "/debug/lookup-keys"))
    assert len(keys) == 2 and keys[0]["ifindex"] == 10

    # 6. state file deletion = CR deletion -> dataplane reset
    os.remove(os.path.join(daemon.nodestates_dir, f"{NODE}.json"))
    assert _wait(lambda: daemon.syncer.classifier is None)


def test_daemon_restart_readopts(tmp_path):
    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    state = str(tmp_path / "state")
    kw = dict(state_dir=state, node_name=NODE, namespace=NS, backend="cpu",
              registry=reg, metrics_port=0, health_port=0,
              file_poll_interval_s=0.02, poll_period_s=0.05)
    d = Daemon(**kw)
    d.start()
    with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
        json.dump(node_state().to_dict(), f)
    assert _wait(lambda: d.syncer.classifier is not None
                 and d.syncer.classifier.tables is not None)
    d.stop()  # SIGTERM: detach but keep checkpoint

    d2 = Daemon(**kw)
    d2.start()
    try:
        # first sync (same file still present) re-adopts from checkpoint
        assert _wait(lambda: d2.syncer.classifier is not None
                     and d2.syncer.classifier.tables is not None)
        assert d2.syncer.attached_interfaces() == {"dummy0"}
    finally:
        d2.stop()


def test_schema_invalid_nodestate_file_rejected_and_isolated(daemon):
    """The state-dir protocol has no API server: the daemon applies the
    schema tier itself, and a persistently bad file must not abort the
    scan or block a *different* file for the same node (ADVICE r1)."""
    bad = node_state().to_dict()
    bad["spec"]["interfaceIngressRules"]["dummy0"][0]["rules"][0][
        "protocolConfig"]["protocol"] = "Tcp"
    # Different filename, same metadata.name -> still targets this node.
    path = os.path.join(daemon.nodestates_dir, "aaa-bad.json")
    with open(path + ".tmp", "w") as f:
        json.dump(bad, f)
    os.replace(path + ".tmp", path)
    time.sleep(0.2)
    # never synced: schema tier rejected it before compile
    assert daemon.syncer.classifier is None or daemon.syncer.classifier.tables is None

    # the bad file stays on disk; a good file later in sort order must
    # still be scanned and synced
    good = node_state().to_dict()
    p2 = os.path.join(daemon.nodestates_dir, f"{NODE}.json")
    with open(p2 + ".tmp", "w") as f:
        json.dump(good, f)
    os.replace(p2 + ".tmp", p2)
    assert _wait(lambda: daemon.syncer.classifier is not None
                 and daemon.syncer.classifier.tables is not None)
    assert os.path.exists(path)


def test_deleting_rejected_file_does_not_reset_dataplane(daemon):
    """A rejected (schema-invalid) file is not desired state; removing it
    must not be treated as CR deletion."""
    good = node_state().to_dict()
    p = os.path.join(daemon.nodestates_dir, f"{NODE}.json")
    with open(p + ".tmp", "w") as f:
        json.dump(good, f)
    os.replace(p + ".tmp", p)
    assert _wait(lambda: daemon.syncer.classifier is not None
                 and daemon.syncer.classifier.tables is not None)

    bad = node_state().to_dict()
    bad["spec"]["interfaceIngressRules"]["dummy0"][0]["rules"][0]["order"] = 0
    pbad = os.path.join(daemon.nodestates_dir, "zzz-bad.json")
    with open(pbad + ".tmp", "w") as f:
        json.dump(bad, f)
    os.replace(pbad + ".tmp", pbad)
    time.sleep(0.2)
    os.remove(pbad)
    time.sleep(0.2)
    assert daemon.syncer.classifier is not None
    assert daemon.syncer.classifier.tables is not None


def test_deny_event_with_large_ifindex(tmp_path):
    """A deny on an interface with ifindex > 65535 must flow through
    process_ingest_once and the event pipeline without the old u16
    EventHdr pack crash (struct.error)."""
    reg = InterfaceRegistry()
    reg.add(Interface(name="big0", index=70000))
    d = Daemon(
        state_dir=str(tmp_path / "state"),
        node_name=NODE, namespace=NS, backend="cpu",
        poll_period_s=0.05, registry=reg, metrics_port=0, health_port=0,
        file_poll_interval_s=0.02,
    )
    d.start()
    try:
        ns = node_state(
            rules={"big0": [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]}
        )
        p = os.path.join(d.nodestates_dir, f"{NODE}.json")
        with open(p + ".tmp", "w") as f:
            json.dump(ns.to_dict(), f)
        os.replace(p + ".tmp", p)
        assert _wait(lambda: d.syncer.classifier is not None
                     and d.syncer.classifier.tables is not None)
        frames = [build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80)]
        write_frames_file(os.path.join(d.ingest_dir, "t.frames"), frames, 70000)
        vp = os.path.join(d.out_dir, "t.frames.verdicts.json")
        assert _wait(lambda: os.path.exists(vp))
        with open(vp) as f:
            assert json.load(f)["drop"] == 1
        assert _wait(lambda: os.path.exists(d.events_path)
                     and "ruleId 1 action Drop" in open(d.events_path).read())
        assert "if big0" in open(d.events_path).read()
    finally:
        d.stop()


@pytest.mark.parametrize("mode", ["deferred", "sync"])
def test_ingest_failure_isolated_and_stats_exactly_once(tmp_path, mode):
    """Failure semantics of the cross-file-batched ingest: a TRANSIENT
    fault on a merged job self-heals within the tick (per-file retry
    dispatch), while a PERSISTENT fault attributable to one file poisons
    only that file — it stays on disk for the next tick, job-mates
    complete — and statistics land exactly once across every retry.
    Covered for both failure surfaces: a deferred .result() raise (async
    TPU backend) and a synchronous classify_async raise (eager CPU)."""
    from infw.backend.base import PendingClassify

    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    d = Daemon(
        state_dir=str(tmp_path / "state"),
        node_name=NODE, namespace=NS, backend="cpu",
        poll_period_s=0.05, registry=reg, metrics_port=0, health_port=0,
        file_poll_interval_s=60.0,  # drive ticks manually
    )
    try:
        with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
            json.dump(node_state().to_dict(), f)
        d.scan_nodestates_once()
        clf = d.syncer.classifier
        assert clf is not None and clf.tables is not None

        deny = lambda: build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80)
        write_frames_file(os.path.join(d.ingest_dir, "aaa.frames"), [deny()] * 3, 10)
        write_frames_file(os.path.join(d.ingest_dir, "bbb.frames"), [deny()] * 2, 10)

        orig = clf.classify_async
        fail_when = {"pred": lambda batch: True}

        def flaky(batch, apply_stats=True):
            if fail_when["pred"](batch):
                if mode == "sync":
                    raise RuntimeError("device fell over at dispatch")

                def explode():
                    raise RuntimeError("device fell over")

                return PendingClassify(explode)
            return orig(batch, apply_stats=apply_stats)

        clf.classify_async = flaky

        # --- transient fault: fail exactly one dispatch (the merged job);
        # the per-file retries complete everything within the tick ---
        boom = {"left": 1}

        def once(batch):
            if boom["left"]:
                boom["left"] -= 1
                return True
            return False

        fail_when["pred"] = once
        assert d.process_ingest_once() == 2
        assert not os.path.exists(os.path.join(d.ingest_dir, "aaa.frames"))
        snap = clf.stats.snapshot()
        assert snap[1, 2] == 5  # 3 + 2 denies, exactly once despite the retry

        # --- persistent per-file fault: every batch containing aaa2's
        # (content-marked) packets fails — the merged job AND aaa2's
        # per-file retry — so only aaa2 is poisoned; bbb2 completes and
        # is counted once ---
        mark = lambda: build_frame("10.1.2.9", "203.0.113.1", IPPROTO_TCP, 999, 80)
        MARK_W0 = (10 << 24) | (1 << 16) | (2 << 8) | 9
        write_frames_file(os.path.join(d.ingest_dir, "aaa2.frames"), [mark()] * 3, 10)
        write_frames_file(os.path.join(d.ingest_dir, "bbb2.frames"), [deny()] * 2, 10)
        fail_when["pred"] = lambda batch: bool(
            (np.asarray(batch.ip_words)[:, 0] == MARK_W0).any()
        )
        assert d.process_ingest_once() == 1  # only bbb2
        assert os.path.exists(os.path.join(d.ingest_dir, "aaa2.frames"))
        assert not os.path.exists(
            os.path.join(d.out_dir, "aaa2.frames.verdicts.json")
        )
        assert os.path.exists(os.path.join(d.out_dir, "bbb2.frames.verdicts.json"))
        snap = clf.stats.snapshot()
        assert snap[1, 2] == 7  # +bbb2 only

        fail_when["pred"] = lambda batch: False
        assert d.process_ingest_once() == 1  # retry tick consumes aaa2
        assert not os.path.exists(os.path.join(d.ingest_dir, "aaa2.frames"))
        snap = clf.stats.snapshot()
        assert snap[1, 2] == 10  # every deny counted exactly once
    finally:
        d.stop()


def test_pipelined_ingest_multi_chunk(tmp_path):
    """A file larger than ingest_chunk is split into in-flight sub-batches;
    verdict order and stats must match the single-shot path."""
    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    d = Daemon(
        state_dir=str(tmp_path / "state"),
        node_name=NODE, namespace=NS, backend="cpu",
        poll_period_s=0.05, registry=reg, metrics_port=0, health_port=0,
        file_poll_interval_s=0.02, ingest_chunk=7, pipeline_depth=3,
    )
    d.start()
    try:
        ns_doc = node_state().to_dict()
        p = os.path.join(d.nodestates_dir, f"{NODE}.json")
        with open(p + ".tmp", "w") as f:
            json.dump(ns_doc, f)
        os.replace(p + ".tmp", p)
        assert _wait(lambda: d.syncer.classifier is not None
                     and d.syncer.classifier.tables is not None)
        # 20 packets -> 3 chunks at chunk=7; alternate deny/pass
        frames = [
            build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999,
                        80 if i % 2 == 0 else 81)
            for i in range(20)
        ]
        write_frames_file(os.path.join(d.ingest_dir, "big.frames"), frames, 10)
        vp = os.path.join(d.out_dir, "big.frames.verdicts.json")
        assert _wait(lambda: os.path.exists(vp))
        with open(vp) as f:
            summary = json.load(f)
        assert summary["packets"] == 20
        assert summary["drop"] == 10 and summary["pass"] == 10
        # per-packet verdicts live in the binary sidecar, in file order
        # across chunk boundaries
        rb = np.fromfile(
            os.path.join(d.out_dir, summary["results_file"]), dtype="<u4"
        )
        assert len(rb) == 20
        assert rb[:4].tolist() == [257, 0, 257, 0]
    finally:
        d.stop()


def test_cross_file_batched_ingest_tpu_backend(tmp_path):
    """Multiple frames files in one tick share merged device jobs (packed
    wire path); per-file verdict sidecars, stats and events must be
    identical to processing them alone."""
    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    d = Daemon(
        state_dir=str(tmp_path / "state"),
        node_name=NODE, namespace=NS, backend="tpu",
        poll_period_s=0.05, registry=reg, metrics_port=0, health_port=0,
        file_poll_interval_s=60.0, ingest_chunk=64,
    )
    try:
        with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
            json.dump(node_state().to_dict(), f)
        d.scan_nodestates_once()
        clf = d.syncer.classifier
        assert clf.supports_packed()

        mk = lambda dport: build_frame(
            "10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, dport
        )
        v6 = build_frame("2001:db8::1", "2001:db8::2", IPPROTO_TCP, 999, 80)
        # three files, mixed families, sizes straddling the chunk size
        write_frames_file(os.path.join(d.ingest_dir, "f0.frames"),
                          [mk(80)] * 40 + [v6] * 10, 10)
        write_frames_file(os.path.join(d.ingest_dir, "f1.frames"),
                          [mk(81)] * 50 + [mk(80)] * 30, 10)
        write_frames_file(os.path.join(d.ingest_dir, "f2.frames"),
                          [v6] * 5, 10)
        assert d.process_ingest_once() == 3
        got = {}
        for fn in ("f0", "f1", "f2"):
            with open(os.path.join(d.out_dir, fn + ".frames.verdicts.json")) as f:
                got[fn] = json.load(f)
        # rule: deny tcp/80 from 10.1.0.0/16 (v4 only), everything else passes
        assert (got["f0"]["drop"], got["f0"]["pass"]) == (40, 10)
        assert (got["f1"]["drop"], got["f1"]["pass"]) == (30, 50)
        assert (got["f2"]["drop"], got["f2"]["pass"]) == (0, 5)
        rb = np.fromfile(
            os.path.join(d.out_dir, got["f1"]["results_file"]), dtype="<u4"
        )
        assert (rb[:50] == 0).all() and (rb[50:] == 257).all()
        snap = clf.stats.snapshot()
        assert snap[1, 2] == 70  # 40 + 30 denies across merged jobs
    finally:
        d.stop()
