"""Hot-path auditor tests (infw.analysis.jaxcheck + the kernel
entrypoint registry).

The full audit of every registered entrypoint runs in `make entry-check`
/ `make static-check`; the tier-1 subset here exercises the registry
contract and each detector on live jaxprs within the CI time budget.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infw.analysis import jaxcheck
from infw.kernels import kernel_entrypoints


def _by_name():
    return {ep.name: ep for ep in kernel_entrypoints()}


def test_registry_covers_the_dispatch_surface():
    names = {ep.name for ep in kernel_entrypoints()}
    # classify, wire decode and the fused walk must all be registered
    # (the ISSUE contract: an unregistered entrypoint is invisible to
    # the static gate)
    assert {"classify/xla-dense", "classify/xla-trie",
            "classify-wire/xla-trie-fused", "wire-decode/delta-fused",
            "classify/pallas-dense", "classify/pallas-walk",
            "classify-wire/xla-ctrie-fused",
            "classify-wire/xla-ctrie-overlay-fused",
            "classify/pallas-cwalk",
            "patch/txn-scatter-dense",
            "patch/ctrie-joined-scatter"} <= names


def test_builders_return_stable_jitted_objects():
    for ep in kernel_entrypoints():
        fn0, _ = ep.build(128)
        fn1, _ = ep.build(128)
        assert fn0 is fn1, ep.name


def test_audit_xla_dense_clean():
    rep, = jaxcheck.audit_all(
        names=["classify/xla-dense"], ladder=(128, 256)
    )
    assert rep.shapes == [128, 256]
    assert rep.n_pallas_calls == 0
    assert [f for f in rep.findings if f.severity != "info"] == []


def test_audit_pallas_dense_vmem_estimate():
    rep, = jaxcheck.audit_all(
        names=["classify/pallas-dense"], ladder=(256,), execute=False
    )
    assert rep.n_pallas_calls >= 1
    assert rep.vmem_bytes > 0
    assert [f for f in rep.findings if f.severity != "info"] == []
    # a 1-byte budget must fail with the offending block specs attached
    rep_bad, = jaxcheck.audit_all(
        names=["classify/pallas-dense"], ladder=(256,), vmem_budget=1,
        execute=False,
    )
    bad = [f for f in rep_bad.findings if f.check == "vmem-budget"]
    assert bad and bad[0].severity == "error" and "block" in bad[0].detail


def test_wide_dtype_detector():
    def leaky(x):
        return x.astype(jnp.float64) * 2.0

    with jax.experimental.enable_x64():
        jaxpr = jax.make_jaxpr(leaky)(np.ones(4, np.float32))
        findings = jaxcheck.check_wide_dtypes("t", jaxpr)
    assert findings and findings[0].check == "x64-leak"
    assert "float64" in findings[0].message

    clean = jax.make_jaxpr(lambda x: x * 2)(np.ones(4, np.int32))
    assert jaxcheck.check_wide_dtypes("t", clean) == []


def test_host_callback_detector():
    def with_cb(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    jaxpr = jax.make_jaxpr(with_cb)(np.ones(4, np.float32))
    findings = jaxcheck.check_host_callbacks("t", jaxpr)
    assert findings and findings[0].severity == "error"

    clean = jax.make_jaxpr(lambda x: x + 1)(np.ones(4, np.int32))
    assert jaxcheck.check_host_callbacks("t", clean) == []


def test_recompile_lint_counts_cache_growth():
    ep = _by_name()["classify/xla-dense"]
    findings = jaxcheck._recompile_lint(ep, ladder=(128, 256))
    assert [f for f in findings if f.severity == "error"] == []


def test_summarize_and_json_shapes():
    reports = jaxcheck.audit_all(
        names=["classify/xla-dense"], ladder=(128,), execute=False
    )
    s = jaxcheck.summarize(reports)
    assert s["entries"] == 1
    doc = reports[0].to_dict()
    assert doc["entry"] == "classify/xla-dense"
    assert isinstance(doc["findings"], list)


@pytest.mark.slow
def test_full_registry_audit_clean():
    reports = jaxcheck.audit_all(ladder=(256, 1024))
    s = jaxcheck.summarize(reports)
    assert s["error"] == 0 and s["warning"] == 0, [
        f.to_dict() for f in jaxcheck.all_findings(reports)
    ]


def test_transfer_lint_clean_entrypoint():
    """A registered entrypoint with committed device operands runs clean
    under jax.transfer_guard('disallow')."""
    ep = _by_name()["classify/xla-dense"]
    findings = jaxcheck._transfer_lint(ep, ladder=(128,))
    assert findings == [], [f.to_dict() for f in findings]


def test_transfer_lint_catches_host_operand():
    """The deliberately defective entrypoint (host-resident numpy
    operand) must produce an error-severity implicit-transfer finding —
    the injected acceptance of the transfer lint."""
    ep = jaxcheck.transfer_defect_entrypoint()
    findings = jaxcheck._transfer_lint(ep, ladder=(128,))
    assert findings, "implicit transfer not caught"
    assert all(f.check == "implicit-transfer" for f in findings)
    assert all(f.severity == "error" for f in findings)
    # and through the audit_all plumbing the strict audit fails
    reports = jaxcheck.audit_all(
        names=["defect/implicit-transfer"], ladder=(128,),
        include_transfer_defect=True,
    )
    assert jaxcheck.summarize(reports)["error"] >= 1
