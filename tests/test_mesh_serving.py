"""Multi-chip serving parity: the MeshTpuClassifier must be bit-exact
against the single-chip TpuClassifier and the CPU oracle on every mesh
configuration and wire path (runs on the virtual 8-device CPU mesh the
conftest forces).

Covers the ISSUE-4 edge cases: target count not divisible by
rules_shards (padding sentinel rows), empty table, v4-only batches, a
mid-stream load_tables reshard (both the full re-place of the sharded
partition and the replicated config's diff-scatter patch), the overlay
broadcast, wide ruleIds, and the daemon factory / --mesh spec wiring.
"""
import numpy as np
import pytest

from infw import oracle, testing
from infw.backend.mesh import (
    MeshTpuClassifier,
    parse_mesh_spec,
    resolve_mesh_spec,
)
from infw.backend.tpu import TpuClassifier
from infw.compiler import (
    IncrementalTables,
    LpmKey,
    compile_tables_from_content,
)
from infw.constants import KIND_IPV6


def _single(tables, **kw):
    clf = TpuClassifier(interpret=True, **kw)
    clf.load_tables(tables)
    return clf


def _mesh(tables, data, rules, **kw):
    clf = MeshTpuClassifier(
        data_shards=data, rules_shards=rules, interpret=True, **kw
    )
    if tables is not None:
        clf.load_tables(tables)
    return clf


def _assert_parity(mesh_clf, single_clf, tables, batch, oracle_check=True):
    got = mesh_clf.classify(batch, apply_stats=False)
    want = single_clf.classify(batch, apply_stats=False)
    np.testing.assert_array_equal(got.results, want.results)
    np.testing.assert_array_equal(got.xdp, want.xdp)
    np.testing.assert_array_equal(got.stats_delta, want.stats_delta)
    if oracle_check:
        ref = oracle.classify(tables, batch)
        np.testing.assert_array_equal(got.results, ref.results)
        np.testing.assert_array_equal(got.xdp, ref.xdp)
        assert testing.stats_dict_from_array(got.stats_delta) == ref.stats
    return got


@pytest.mark.parametrize("data,rules", [(8, 1), (4, 2), (2, 4)])
def test_mesh_dense_parity(data, rules):
    """Dense path: replicated int8 Pallas kernel under shard_map
    (rules=1) and the target-sharded XLA dense partial (rules>1), all
    bit-exact vs single chip and oracle — one merged stats_delta."""
    rng = np.random.default_rng(5)
    tables = testing.random_tables(rng, n_entries=60, width=8)
    batch = testing.random_batch(rng, tables, n_packets=301)
    _assert_parity(
        _mesh(tables, data, rules), _single(tables), tables, batch
    )


@pytest.mark.parametrize("data,rules", [(8, 1), (2, 4)])
def test_mesh_trie_parity(data, rules):
    """Trie path: replicated XLA walk (rules=1) and per-shard tries over
    "rules" (rules>1) vs single chip and oracle."""
    rng = np.random.default_rng(7)
    tables = testing.random_tables(
        rng, n_entries=90, width=8, overlap_fraction=0.5
    )
    batch = testing.random_batch(rng, tables, n_packets=333)
    _assert_parity(
        _mesh(tables, data, rules, force_path="trie"),
        _single(tables, force_path="trie"), tables, batch,
    )


def test_mesh_targets_not_divisible_by_rules_shards():
    """37 targets over 4 rule shards: the shard padding rows carry the
    mask_len == -1 sentinel and must never match."""
    rng = np.random.default_rng(11)
    tables = testing.random_tables(rng, n_entries=37, width=8)
    batch = testing.random_batch(rng, tables, n_packets=256)
    _assert_parity(
        _mesh(tables, 2, 4), _single(tables), tables, batch
    )
    _assert_parity(
        _mesh(tables, 2, 4, force_path="trie"),
        _single(tables, force_path="trie"), tables, batch,
    )


def test_mesh_empty_table():
    """An empty ruleset classifies everything to UNDEF/PASS on every
    mesh configuration, like the single chip."""
    rng = np.random.default_rng(13)
    seed = testing.random_tables(rng, n_entries=8, width=4)
    empty = compile_tables_from_content({}, rule_width=4)
    batch = testing.random_batch(rng, seed, n_packets=128)
    for data, rules in ((8, 1), (2, 4)):
        for force in (None, "trie"):
            m = _mesh(empty, data, rules, force_path=force)
            s = _single(empty, force_path=force)
            _assert_parity(m, s, empty, batch)


def test_mesh_v4_only_batch():
    """A v4-only compactable batch takes the compact wire (and the
    wire8 format on the replicated trie config) — parity end to end."""
    rng = np.random.default_rng(17)
    tables = testing.random_tables_fast(
        rng, n_entries=3000, width=4, v6_fraction=0.0, ifindexes=(2, 3)
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=640)
    batch.ip_words[:, 1:] = 0
    keep = np.asarray(batch.kind) != KIND_IPV6
    batch = batch.take(np.nonzero(keep)[0])
    m = _mesh(tables, 8, 1, force_path="trie")
    s = _single(tables, force_path="trie")
    _assert_parity(m, s, tables, batch, oracle_check=False)
    ref = oracle.classify(tables, batch)
    got = m.classify(batch, apply_stats=False)
    np.testing.assert_array_equal(got.results, ref.results)
    # the compact (B, 4) wire must have engaged the 8 B/packet format
    assert "wire8" in m.wire_stats(), m.wire_stats()


@pytest.mark.slow
def test_mesh_packed_contract_and_depth_steering():
    """The daemon's exact hot loop — v6_depth_groups + prepare_packed /
    classify_prepared staged plans — against the mesh, including the
    fused Pallas deep walk for the full-depth class (replicated config),
    bit-exact vs the single chip running the same flow."""
    rng = np.random.default_rng(23)
    tables = testing.random_tables_fast(
        rng, n_entries=3000, width=8, group_size=6, ifindexes=(2, 3, 4)
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=1024)
    m = _mesh(tables, 8, 1, force_path="trie", fused_deep=True)
    s = _single(tables, force_path="trie", fused_deep=True)
    assert m._active[5] is not None, "fused walk must build on the mesh"
    assert m.supports_packed()

    def run(clf):
        res = np.zeros(len(batch), np.uint32)
        stats = None
        kinds = np.asarray(batch.kind)
        idx6 = np.nonzero(kinds == KIND_IPV6)[0]
        groups = clf.v6_depth_groups(batch.ifindex, batch.ip_words, idx6)
        groups.append((None, np.nonzero(kinds != KIND_IPV6)[0]))
        walked = False
        for key, idx in groups:
            if len(idx) == 0:
                continue
            wire, v4 = batch.pack_wire_subset(
                np.ascontiguousarray(idx, np.int64)
            )
            plan = clf.prepare_packed(wire, v4, depth=key)
            out = clf.classify_prepared(plan, apply_stats=False).result()
            res[idx] = out.results
            stats = (out.stats_delta if stats is None
                     else stats + out.stats_delta)
            if key is not None and key[0] is None:
                walked = True
        return res, stats, walked

    res_m, stats_m, walked = run(m)
    res_s, stats_s, _ = run(s)
    assert walked, "the full-depth steering class must appear"
    np.testing.assert_array_equal(res_m, res_s)
    np.testing.assert_array_equal(stats_m, stats_s)


def test_mesh_midstream_reshard_rules_sharded():
    """A load_tables against a live rules-sharded mesh re-partitions the
    per-shard tries; verdicts flip to the new ruleset, bit-exact."""
    rng = np.random.default_rng(29)
    t1 = testing.random_tables(rng, n_entries=50, width=8)
    t2 = testing.random_tables(rng, n_entries=73, width=8)
    batch = testing.random_batch(rng, t1, n_packets=256)
    m = _mesh(t1, 2, 4, force_path="trie")
    s = _single(t1, force_path="trie")
    _assert_parity(m, s, t1, batch)
    m.load_tables(t2)
    s.load_tables(t2)
    _assert_parity(m, s, t2, batch)


@pytest.mark.slow
def test_mesh_midstream_patch_replicated():
    """On the replicated config a 1-key rules edit must take the
    diff-scatter patch path (kilobytes broadcast, not a full re-put) and
    stay bit-exact; a structural CIDR add ships as the broadcast
    overlay, the main table untouched."""
    rng = np.random.default_rng(31)
    tables = testing.random_tables_fast(
        rng, n_entries=2000, width=8, ifindexes=(2, 3)
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=512)
    it = IncrementalTables.from_content(tables.content, rule_width=8)
    m = _mesh(None, 8, 1, force_path="trie")
    s = TpuClassifier(interpret=True, force_path="trie")
    m.load_tables(it.snapshot())
    s.load_tables(it.snapshot())
    it.clear_dirty()

    key = list(it.content)[7]
    rows = it.content[key].copy()
    rows[0, 6] = 1 if rows[0, 6] == 2 else 2
    it.apply({key: rows})
    m.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
    s.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
    it.clear_dirty()
    assert m._last_load[0] == "patch", m._last_load
    _assert_parity(m, s, it.snapshot(), batch, oracle_check=False)

    assert m.supports_overlay
    ov_key = LpmKey(
        prefix_len=24 + 32, ingress_ifindex=2,
        ip_data=bytes([203, 0, 113, 0]) + bytes(12),
    )
    ovrows = np.zeros((8, 7), np.int32)
    ovrows[1] = [1, 6, 443, 0, 0, 0, 1]
    ov = compile_tables_from_content({ov_key: ovrows}, rule_width=8)
    m.load_tables(it.snapshot(), dirty_hint=it.peek_dirty(), overlay=ov)
    s.load_tables(it.snapshot(), dirty_hint=it.peek_dirty(), overlay=ov)
    assert m._last_load[0] == "patch", m._last_load
    _assert_parity(m, s, it.snapshot(), batch, oracle_check=False)


def test_mesh_overlay_refused_on_rules_sharded():
    rng = np.random.default_rng(37)
    tables = testing.random_tables(rng, n_entries=40, width=4)
    ov = compile_tables_from_content(
        {
            LpmKey(prefix_len=24 + 32, ingress_ifindex=2,
                   ip_data=bytes([198, 18, 0, 0]) + bytes(12)):
            np.array([[0] * 7, [1, 6, 80, 0, 0, 0, 1]] + [[0] * 7] * 2,
                     np.int32),
        },
        rule_width=4,
    )
    m = _mesh(None, 2, 4, force_path="trie")
    assert not m.supports_overlay
    with pytest.raises(ValueError, match="overlay"):
        m.load_tables(tables, overlay=ov)


def test_mesh_wide_ruleids():
    """ruleIds > 255 cannot ride the 2B wire result: the mesh must take
    the u32 path (sharded tries / replicated classify) losslessly."""
    rng = np.random.default_rng(41)
    seed = testing.random_tables(rng, n_entries=40, width=8)
    content = {}
    for i, (k, v) in enumerate(seed.content.items()):
        rows = v.copy()
        rows[rows[:, 0] > 0, 0] = 300 + i
        content[k] = rows
    tables = compile_tables_from_content(content, rule_width=8)
    batch = testing.random_batch(rng, seed, n_packets=200)
    for data, rules in ((8, 1), (2, 4)):
        m = _mesh(tables, data, rules, force_path="trie")
        s = _single(tables, force_path="trie")
        assert not m.supports_packed()
        _assert_parity(m, s, tables, batch, oracle_check=False)
        ref = oracle.classify(tables, batch)
        got = m.classify(batch, apply_stats=False)
        np.testing.assert_array_equal(got.results, ref.results)


def test_mesh_cpu_ref_parity_10k():
    """Scale tier: a 10K nested/overlapping table on the widest mesh vs
    the native C++ reference classifier."""
    from infw.backend.cpu_ref import CpuRefClassifier

    rng = np.random.default_rng(43)
    tables = testing.random_tables_fast(
        rng, n_entries=10_000, width=8, group_size=6
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=2048)
    ref = CpuRefClassifier()
    ref.load_tables(tables)
    want = ref.classify(batch, apply_stats=False)
    for data, rules in ((8, 1), (2, 4)):
        m = _mesh(tables, data, rules, force_path="trie")
        got = m.classify(batch, apply_stats=False)
        np.testing.assert_array_equal(got.results, want.results)
        np.testing.assert_array_equal(got.xdp, want.xdp)
        np.testing.assert_array_equal(got.stats_delta, want.stats_delta)


# --- daemon wiring -----------------------------------------------------------


def test_parse_mesh_spec():
    assert parse_mesh_spec("4x2") == (4, 2)
    assert parse_mesh_spec(" 8 ") == (8, 1)
    assert parse_mesh_spec("2X2") == (2, 2)
    for bad in ("", "x", "4x", "ax2", "0x2", "4x0", "-4"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


def test_resolve_mesh_spec_fallback():
    assert resolve_mesh_spec("1x1") is None  # explicit single chip
    assert resolve_mesh_spec("64x2") is None  # pool too small -> fallback
    m = resolve_mesh_spec("4x2")
    assert m is not None and dict(m.shape) == {"data": 4, "rules": 2}


def test_factory_mesh_selection():
    from infw.daemon import make_classifier_factory

    f = make_classifier_factory("tpu", mesh="4x2")
    assert f.func is MeshTpuClassifier
    # too-large spec falls back to the single-chip class
    f2 = make_classifier_factory("tpu", mesh="64x1")
    assert f2 is TpuClassifier
    # cpu backend ignores the knob
    from infw.backend.cpu_ref import CpuRefClassifier

    assert make_classifier_factory("cpu", mesh="4x2") is CpuRefClassifier


def test_daemon_ingest_on_mesh(tmp_path):
    """One real ingest tick through the daemon's staged pipeline against
    the mesh classifier: frames file in, verdicts bit-exact vs oracle."""
    from infw.daemon import Daemon, write_frames_file_v2
    from infw.obs.events import EventRing, EventsLogger
    from infw.obs.pcap import build_frames_bulk

    rng = np.random.default_rng(47)
    tables = testing.random_tables_fast(
        rng, n_entries=2000, width=8, ifindexes=(2, 3)
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=4096)
    fb = build_frames_bulk(
        batch.kind, batch.ip_words, batch.proto, batch.dst_port,
        batch.icmp_type, batch.icmp_code, l4_ok=batch.l4_ok,
    )
    fb.ifindex = np.asarray(batch.ifindex, np.uint32)

    clf = MeshTpuClassifier(
        data_shards=4, rules_shards=2, interpret=True, force_path="trie"
    )
    clf.load_tables(tables)

    d = Daemon.__new__(Daemon)  # ingest-only harness (bench pattern)
    d.ingest_dir = str(tmp_path / "ingest")
    d.out_dir = str(tmp_path / "out")
    import os

    os.makedirs(d.ingest_dir)
    os.makedirs(d.out_dir)
    d.ingest_chunk = 1024
    d.pipeline_depth = 4
    d.max_tick_packets = 1 << 20
    d.debug_lookup = False
    d.h2d_overlap = True
    d.h2d_stage_depth = 2
    d.ring = EventRing(capacity=1 << 12)
    d.events_logger = EventsLogger(d.ring, lambda line: None)

    class _Syncer:
        classifier = clf

    d.syncer = _Syncer()
    write_frames_file_v2(str(tmp_path / "ingest" / "a.frames"), fb)
    assert d.process_ingest_once() == 1

    verdicts = np.fromfile(
        str(tmp_path / "out" / "a.frames.verdicts.bin"), "<u4"
    )
    from infw.obs.pcap import parse_frames_buf

    parsed = parse_frames_buf(fb)
    ref = oracle.classify(tables, parsed)
    np.testing.assert_array_equal(verdicts, ref.results)


# --- regression: joined-placeholder patch corruption -------------------------


def test_patch_keeps_inactive_joined_placeholder():
    """A diff-based (structural) patch of a table whose joined layout is
    INACTIVE must keep the (1, 1) placeholder: bucket-padding it flips
    classify into the joined walk with a zero-width rules tail (the
    crash the mesh parity suite originally surfaced)."""
    from infw.kernels import jaxpath

    rng = np.random.default_rng(53)
    tables = testing.random_tables_fast(
        rng, n_entries=3000, width=8, group_size=6, ifindexes=(2, 3, 4)
    )
    assert jaxpath.build_joined(tables) is None  # inactive on this table
    it = IncrementalTables.from_content(tables.content, rule_width=8)
    snap = it.snapshot()
    dev = jaxpath.device_tables(tables, pad=True)
    assert dev.joined.shape == (1, 1)
    patched = jaxpath.patch_device_tables(dev, tables, snap)
    assert patched is not None
    nd, _rows = patched
    assert nd.joined.shape == (1, 1)
    batch = testing.random_batch_fast(rng, tables, n_packets=256)
    res, _xdp, _stats = jaxpath.jitted_classify(True)(
        nd, jaxpath.device_batch(batch)
    )
    ref = oracle.classify(snap, batch)
    np.testing.assert_array_equal(np.asarray(res), ref.results)
