"""Worker process for the 2-process multi-host integration test.

Launched by tests/test_multihost.py as `python _mh_worker.py <rank> <port>
<outdir>`: joins a real jax.distributed process group (Gloo collectives
over localhost — the CPU stand-in for DCN), builds the global
("data", "rules") mesh over 2 hosts x 4 virtual devices, classifies its
process-local half of a deterministic global batch against rules-sharded
tries, and writes its rows + stats for the parent to verify.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

rank, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
# preserve inherited XLA flags; replace only the device-count setting
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=4"]
)

import jax

# jax is pre-imported by sitecustomize in this image, so the env var alone
# is too late — force the platform through the config too.
jax.config.update("jax_platforms", "cpu")

from infw import testing
from infw.parallel import multihost
from infw.parallel.mesh import shard_tables_trie

import _mh_params as mp

ok = multihost.init_distributed(f"localhost:{port}", 2, rank)
assert ok, "process group did not initialize"
assert len(jax.devices()) == 8 and jax.local_device_count() == 4, (
    jax.devices(), jax.local_device_count(),
)

rng = np.random.default_rng(mp.SEED)
tables = testing.random_tables(rng, n_entries=mp.N_ENTRIES, width=mp.WIDTH,
                               overlap_fraction=mp.OVERLAP)
batch = testing.random_batch(rng, tables, n_packets=mp.N_PACKETS)  # same on both ranks

mesh = multihost.make_global_mesh()  # data=2 (one shard per host) x rules=4
assert mesh.shape == {"data": 2, "rules": 4}
# every "rules" group must be contained in one process (ICI containment)
for row in mesh.devices:
    assert len({d.process_index for d in row}) == 1

lo, hi = multihost.process_local_rows(mesh, len(batch))
local = batch.slice(lo, hi)
placed = shard_tables_trie(tables, mesh)
res, xdp, stats = multihost.classify_multihost_trie(mesh, placed, local, len(batch))
np.savez(
    os.path.join(outdir, f"rank{rank}.npz"),
    res=res, xdp=xdp, stats=stats, lo=lo, hi=hi,
)
print(f"rank {rank} rows [{lo},{hi}) done", flush=True)
