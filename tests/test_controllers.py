"""Controller tests — the port of the reference's envtest suites
(/root/reference/controllers/ingressnodefirewall_controller_test.go and
ingressnodefirewall_controller_rules_test.go): the in-memory Store plays
the API server, reconcile() is driven directly (what envtest's watch loop
does), and the merge matrix covers multi-INF overlap incl. duplicate-order
SyncError expectations.
"""
import pytest

from infw.controllers import (
    DEFAULT_CONFIG_NAME,
    IngressNodeFirewallConfigReconciler,
    IngressNodeFirewallReconciler,
    MergeError,
    merge_firewall_protocol_rules,
    merge_rule_set,
)
from infw.spec import (
    ACTION_ALLOW,
    ACTION_DENY,
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
    IngressNodeFirewallConfigSpec,
    IngressNodeFirewallNodeState,
    IngressNodeFirewallSpec,
    NODE_STATE_SYNC_ERROR,
    NODE_STATE_SYNC_OK,
    ObjectMeta,
    SYNC_STATUS_ERROR,
    SYNC_STATUS_OK,
)
from infw.store import DaemonSet, DaemonSetStatus, InMemoryStore, Node, NotFoundError
from test_syncer import ingress, tcp_rule, udp_rule

NS = "ingress-node-firewall-system"


def node(name, labels):
    return Node(metadata=ObjectMeta(name=name, labels=labels))


def inf(name, selector, ingress_rules, interfaces=("eth0",)):
    return IngressNodeFirewall(
        metadata=ObjectMeta(name=name),
        spec=IngressNodeFirewallSpec(
            node_selector=dict(selector),
            ingress=list(ingress_rules),
            interfaces=list(interfaces),
        ),
    )


@pytest.fixture
def store():
    return InMemoryStore()


@pytest.fixture
def reconciler(store):
    return IngressNodeFirewallReconciler(store, namespace=NS)


WORKER = {"node-role.kubernetes.io/worker": ""}


# --- fan-out lifecycle (ingressnodefirewall_controller_test.go:115-289) -------

def test_fanout_creates_nodestate_per_matching_node(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(node("worker-1", WORKER))
    store.create(node("cp-0", {"node-role.kubernetes.io/control-plane": ""}))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))

    reconciler.reconcile()
    states = store.list(IngressNodeFirewallNodeState.KIND, namespace=NS)
    assert sorted(s.metadata.name for s in states) == ["worker-0", "worker-1"]
    for s in states:
        assert s.status.sync_status == NODE_STATE_SYNC_OK
        assert set(s.spec.interface_ingress_rules) == {"eth0"}
        assert s.metadata.owner_references[0].name == "fw1"
    assert store.get(IngressNodeFirewall.KIND, "fw1").status.sync_status == SYNC_STATUS_OK


def test_fanout_node_label_move_and_delete(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    reconciler.reconcile()
    assert len(store.list(IngressNodeFirewallNodeState.KIND, namespace=NS)) == 1

    # label move: node no longer matches -> NodeState deleted
    n = store.get(Node.KIND, "worker-0")
    n.metadata.labels = {"other": ""}
    store.update(n)
    reconciler.reconcile()
    assert store.list(IngressNodeFirewallNodeState.KIND, namespace=NS) == []

    # label back -> recreated
    n.metadata.labels = dict(WORKER)
    store.update(n)
    reconciler.reconcile()
    assert len(store.list(IngressNodeFirewallNodeState.KIND, namespace=NS)) == 1

    # INF deleted -> NodeState deleted
    store.delete(IngressNodeFirewall.KIND, "fw1")
    reconciler.reconcile()
    assert store.list(IngressNodeFirewallNodeState.KIND, namespace=NS) == []


def test_fanout_empty_interfaces_is_sync_error(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(
        inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])],
            interfaces=())
    )
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    assert s.status.sync_status == NODE_STATE_SYNC_ERROR
    assert "empty list" in s.status.sync_error_message
    assert store.get(IngressNodeFirewall.KIND, "fw1").status.sync_status == SYNC_STATUS_ERROR


def test_fanout_spec_update_propagates(store, reconciler):
    store.create(node("worker-0", WORKER))
    fw = inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])])
    store.create(fw)
    reconciler.reconcile()

    fw.spec.ingress = [ingress(["10.0.0.0/8"], [tcp_rule(1, 443, ACTION_DENY)])]
    store.update(fw)
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    [entry] = s.spec.interface_ingress_rules["eth0"]
    assert entry.rules[0].protocol_config.tcp.ports == 443


# --- multi-INF merge matrix (controller_rules_test.go:60+) --------------------

def test_merge_two_infs_distinct_cidrs(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    store.create(inf("fw2", WORKER, [ingress(["172.16.0.0/12"], [tcp_rule(1, 22, ACTION_DENY)])]))
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    entries = s.spec.interface_ingress_rules["eth0"]
    assert sorted(e.source_cidrs[0] for e in entries) == ["10.0.0.0/8", "172.16.0.0/12"]
    assert {o.name for o in s.metadata.owner_references} == {"fw1", "fw2"}


def test_merge_same_cidr_disjoint_orders(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    store.create(inf("fw2", WORKER, [ingress(["10.0.0.0/8"], [udp_rule(2, 53, ACTION_ALLOW)])]))
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    [entry] = s.spec.interface_ingress_rules["eth0"]
    assert sorted(r.order for r in entry.rules) == [1, 2]
    assert s.status.sync_status == NODE_STATE_SYNC_OK


def test_merge_same_cidr_duplicate_order_is_sync_error(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    store.create(inf("fw2", WORKER, [ingress(["10.0.0.0/8"], [udp_rule(1, 53, ACTION_ALLOW)])]))
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    assert s.status.sync_status == NODE_STATE_SYNC_ERROR
    assert "duplicate order 1" in s.status.sync_error_message
    # Rollup follows INF processing order (buildNodeStates:352-361): fw1
    # completed its merge before fw2 introduced the conflict, so only fw2
    # reports Error on this pass.
    assert store.get(IngressNodeFirewall.KIND, "fw1").status.sync_status == SYNC_STATUS_OK
    assert store.get(IngressNodeFirewall.KIND, "fw2").status.sync_status == SYNC_STATUS_ERROR


def test_merge_error_node_does_not_poison_other_nodes(store, reconciler):
    """Only the conflicted node goes SyncError; a node matched by just one
    of the INFs still syncs fine."""
    store.create(node("worker-0", WORKER))
    store.create(node("special-0", {"special": ""}))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    store.create(inf("fw2", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 22, ACTION_DENY)])]))
    store.create(inf("fw3", {"special": ""}, [ingress(["10.0.0.0/8"], [tcp_rule(1, 22, ACTION_DENY)])]))
    reconciler.reconcile()
    assert (
        store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS).status.sync_status
        == NODE_STATE_SYNC_ERROR
    )
    assert (
        store.get(IngressNodeFirewallNodeState.KIND, "special-0", NS).status.sync_status
        == NODE_STATE_SYNC_OK
    )
    assert store.get(IngressNodeFirewall.KIND, "fw3").status.sync_status == SYNC_STATUS_OK


def test_merge_multi_cidr_inf_expands_to_singleton_entries(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(
        inf("fw1", WORKER,
            [ingress(["10.0.0.0/8", "192.168.0.0/16"], [tcp_rule(1, 80, ACTION_DENY)])])
    )
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    entries = s.spec.interface_ingress_rules["eth0"]
    assert all(len(e.source_cidrs) == 1 for e in entries)
    assert sorted(e.source_cidrs[0] for e in entries) == ["10.0.0.0/8", "192.168.0.0/16"]


def test_merge_multiple_interfaces(store, reconciler):
    store.create(node("worker-0", WORKER))
    store.create(
        inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])],
            interfaces=("eth0", "eth1"))
    )
    reconciler.reconcile()
    s = store.get(IngressNodeFirewallNodeState.KIND, "worker-0", NS)
    assert set(s.spec.interface_ingress_rules) == {"eth0", "eth1"}


# --- merge unit behavior (mergeRuleSet/mergeFirewallProtocolRules) ------------

def test_merge_rule_set_invalid_a():
    bad_a = [ingress(["1.0.0.0/8", "2.0.0.0/8"], [tcp_rule(1, 1, ACTION_DENY)])]
    with pytest.raises(MergeError, match="invalid SourceCIDRs"):
        merge_rule_set(bad_a, [ingress(["1.0.0.0/8"], [tcp_rule(2, 2, ACTION_DENY)])])


def test_merge_protocol_rules_duplicate_in_a():
    a = [tcp_rule(1, 1, ACTION_DENY), tcp_rule(1, 2, ACTION_DENY)]
    with pytest.raises(MergeError, match="rules in A"):
        merge_firewall_protocol_rules(a, [])


def test_merge_protocol_rules_duplicate_within_b():
    with pytest.raises(MergeError, match="rules in B"):
        merge_firewall_protocol_rules(
            [], [tcp_rule(3, 1, ACTION_DENY), tcp_rule(3, 2, ACTION_DENY)]
        )


# --- config controller (ingressnodefirewallconfig_controller.go) --------------

def cfg_obj(name=DEFAULT_CONFIG_NAME, debug=None, selector=None):
    return IngressNodeFirewallConfig(
        metadata=ObjectMeta(name=name, namespace=NS),
        spec=IngressNodeFirewallConfigSpec(
            node_selector=dict(selector or {}), debug=debug
        ),
    )


def conds(cfg):
    return {c.type: c.status for c in cfg.status.conditions}


def test_config_renders_daemonset_and_progresses(store):
    r = IngressNodeFirewallConfigReconciler(store, namespace=NS, daemon_image="img:1")
    store.create(cfg_obj(debug=True, selector={"tpu": "v5e"}))
    res = r.reconcile(DEFAULT_CONFIG_NAME)

    ds = store.get(DaemonSet.KIND, "ingress-node-firewall-daemon", NS)
    assert ds.spec["image"] == "img:1"
    assert ds.spec["env"]["ENABLE_LPM_LOOKUP_DBG"] == "1"
    assert ds.spec["env"]["NAMESPACE"] == NS
    assert ds.spec["nodeSelector"] == {"tpu": "v5e"}
    assert ds.metadata.owner_references[0].name == DEFAULT_CONFIG_NAME

    # daemon not ready yet -> Progressing + 5s requeue
    ds.status = DaemonSetStatus(desired_number_scheduled=2, number_ready=1)
    store.update_status(ds)
    res = r.reconcile(DEFAULT_CONFIG_NAME)
    assert res.requeue_after == 5.0
    cfg = store.get(IngressNodeFirewallConfig.KIND, DEFAULT_CONFIG_NAME, NS)
    assert conds(cfg)["Progressing"] == "True"
    assert conds(cfg)["Available"] == "False"

    # daemon ready -> Available
    ds.status = DaemonSetStatus(desired_number_scheduled=2, number_ready=2)
    store.update_status(ds)
    res = r.reconcile(DEFAULT_CONFIG_NAME)
    assert res.requeue_after is None
    cfg = store.get(IngressNodeFirewallConfig.KIND, DEFAULT_CONFIG_NAME, NS)
    assert conds(cfg)["Available"] == "True"


def test_config_singleton_name_enforced(store):
    r = IngressNodeFirewallConfigReconciler(store, namespace=NS)
    store.create(cfg_obj(name="wrong-name"))
    res = r.reconcile("wrong-name")
    assert res.requeue_after is None
    with pytest.raises(NotFoundError):
        store.get(DaemonSet.KIND, "ingress-node-firewall-daemon", NS)


def test_config_apply_idempotent(store):
    r = IngressNodeFirewallConfigReconciler(store, namespace=NS)
    store.create(cfg_obj())
    r.reconcile(DEFAULT_CONFIG_NAME)
    rv1 = store.get(DaemonSet.KIND, "ingress-node-firewall-daemon", NS).metadata.resource_version
    r.reconcile(DEFAULT_CONFIG_NAME)
    rv2 = store.get(DaemonSet.KIND, "ingress-node-firewall-daemon", NS).metadata.resource_version
    assert rv1 == rv2  # unchanged render does not rewrite the object


def test_config_missing_is_noop(store):
    r = IngressNodeFirewallConfigReconciler(store, namespace=NS)
    assert r.reconcile(DEFAULT_CONFIG_NAME).requeue_after is None
