"""Authenticated metrics fronting (the kube-rbac-proxy sidecar role,
/root/reference/bindata/manifests/daemon/daemonset.yaml:68-113): bearer
auth, deny-by-default routing, token rotation without restart, TLS."""
import http.server
import os
import ssl
import subprocess
import threading
import urllib.error
import urllib.request

import pytest

from infw.obs.metricsproxy import MetricsProxy

EXPOSITION = "ingressnodefirewall_node_packet_deny_total 7\n"


@pytest.fixture
def upstream():
    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = EXPOSITION.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def _get(url, token=None, ctx=None):
    req = urllib.request.Request(url)
    if token is not None:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=5, context=ctx)


def test_bearer_auth_and_routing(tmp_path, upstream):
    tok = tmp_path / "token"
    tok.write_text("s3cret\n")
    proxy = MetricsProxy(upstream=upstream, token_file=str(tok),
                         listen_host="127.0.0.1", listen_port=0)
    proxy.start()
    base = f"http://127.0.0.1:{proxy.port}"
    try:
        # correct token -> relayed exposition
        with _get(f"{base}/metrics", "s3cret") as r:
            assert r.read().decode() == EXPOSITION
        # no token / wrong token -> 401 with WWW-Authenticate
        for t in (None, "wrong"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{base}/metrics", t)
            assert e.value.code == 401
            assert e.value.headers.get("WWW-Authenticate") == "Bearer"
        # authenticated but non-metrics path -> 404 (deny by default)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}/healthz", "s3cret")
        assert e.value.code == 404
        # token rotation without restart: file re-read per request
        tok.write_text("rotated")
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}/metrics", "s3cret")
        assert e.value.code == 401
        with _get(f"{base}/metrics", "rotated") as r:
            assert r.read().decode() == EXPOSITION
        # missing token file -> fail closed (503), never open
        os.remove(tok)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}/metrics", "rotated")
        assert e.value.code == 503
    finally:
        proxy.stop()


def test_tls_fronting(tmp_path, upstream):
    crt, key = str(tmp_path / "tls.crt"), str(tmp_path / "tls.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", crt, "-days", "1", "-nodes", "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    tok = tmp_path / "token"
    tok.write_text("t")
    proxy = MetricsProxy(upstream=upstream, token_file=str(tok),
                         listen_host="127.0.0.1", listen_port=0,
                         certfile=crt, keyfile=key)
    assert proxy.tls
    proxy.start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with _get(f"https://127.0.0.1:{proxy.port}/metrics", "t", ctx) as r:
            assert r.read().decode() == EXPOSITION
        # plaintext client against the TLS listener fails the handshake
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            _get(f"http://127.0.0.1:{proxy.port}/metrics", "t")
    finally:
        proxy.stop()


def test_upstream_down_is_502(tmp_path):
    tok = tmp_path / "token"
    tok.write_text("t")
    proxy = MetricsProxy(upstream="127.0.0.1:1", token_file=str(tok),
                         listen_host="127.0.0.1", listen_port=0)
    proxy.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"http://127.0.0.1:{proxy.port}/metrics", "t")
        assert e.value.code == 502
    finally:
        proxy.stop()


def test_post_rejected_405(tmp_path, upstream):
    tok = tmp_path / "token"
    tok.write_text("t")
    proxy = MetricsProxy(upstream=upstream, token_file=str(tok),
                         listen_host="127.0.0.1", listen_port=0)
    proxy.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{proxy.port}/metrics", data=b"x", method="POST")
        req.add_header("Authorization", "Bearer t")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 405
    finally:
        proxy.stop()


def test_stalled_tls_client_does_not_block_scrapes(tmp_path, upstream):
    """A TCP client that never sends a ClientHello must not wedge other
    scrapes (the handshake runs on the per-connection handler thread,
    not in accept())."""
    import socket

    crt, key = str(tmp_path / "tls.crt"), str(tmp_path / "tls.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", crt, "-days", "1", "-nodes", "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    tok = tmp_path / "token"
    tok.write_text("t")
    proxy = MetricsProxy(upstream=upstream, token_file=str(tok),
                         listen_host="127.0.0.1", listen_port=0,
                         certfile=crt, keyfile=key)
    proxy.start()
    stalled = socket.create_connection(("127.0.0.1", proxy.port))
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with _get(f"https://127.0.0.1:{proxy.port}/metrics", "t", ctx) as r:
            assert r.read().decode() == EXPOSITION
    finally:
        stalled.close()
        proxy.stop()


def test_ensure_self_signed_generates_and_reuses(tmp_path, upstream):
    """The default-on TLS bootstrap (ensure_self_signed): mints a pair
    once (key 0600), reuses it on the next call, and the proxy serves
    TLS with it — the path compose/launch.py takes when no operator
    pair exists."""
    import stat

    from infw.obs.metricsproxy import ensure_self_signed

    d = str(tmp_path / "tls")
    crt, key = ensure_self_signed(d)
    assert os.path.exists(crt) and os.path.exists(key)
    assert stat.S_IMODE(os.stat(key).st_mode) == 0o600
    m1 = (os.path.getmtime(crt), os.path.getmtime(key))
    crt2, key2 = ensure_self_signed(d)  # idempotent: no regeneration
    assert (crt2, key2) == (crt, key)
    assert (os.path.getmtime(crt), os.path.getmtime(key)) == m1

    tok = tmp_path / "token"
    tok.write_text("t")
    proxy = MetricsProxy(upstream=upstream, token_file=str(tok),
                         listen_host="127.0.0.1", listen_port=0,
                         certfile=crt, keyfile=key)
    assert proxy.tls
    proxy.start()
    try:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with _get(f"https://127.0.0.1:{proxy.port}/metrics", "t", ctx) as r:
            assert r.read().decode() == EXPOSITION
        # plaintext against the default-on TLS listener fails closed
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            _get(f"http://127.0.0.1:{proxy.port}/metrics", "t")
    finally:
        proxy.stop()
