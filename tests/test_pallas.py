"""T3: Pallas dense kernel vs oracle (interpret mode on CPU; the same
kernel compiles via Mosaic on real TPU — exercised by bench.py and
__graft_entry__)."""
import numpy as np
import pytest

from infw import oracle, testing
from infw.compiler import LpmKey, compile_tables_from_content
from infw.kernels import jaxpath, pallas_dense


def assert_pallas_matches(tables, batch, dtype=pallas_dense.DEFAULT_DTYPE):
    ref = oracle.classify(tables, batch)
    pt = pallas_dense.build_pallas_tables(tables, dtype=dtype)
    db = jaxpath.device_batch(batch)
    res, xdp, stats = pallas_dense.jitted_classify_pallas(True)(pt, db)
    np.testing.assert_array_equal(np.asarray(res), ref.results)
    np.testing.assert_array_equal(np.asarray(xdp), ref.xdp)
    got = testing.stats_dict_from_array(jaxpath.merge_stats_host(np.asarray(stats)))
    assert got == ref.stats


@pytest.mark.parametrize("dtype", ["int8", "bf16"])
@pytest.mark.parametrize("seed", [0, 5])
def test_pallas_random_differential(seed, dtype):
    rng = np.random.default_rng(seed)
    tables = testing.random_tables(rng, n_entries=40, width=12)
    batch = testing.random_batch(rng, tables, n_packets=300)
    assert_pallas_matches(tables, batch, dtype=dtype)


def test_pallas_non_block_multiple_batch():
    rng = np.random.default_rng(3)
    tables = testing.random_tables(rng, n_entries=10, width=8)
    batch = testing.random_batch(rng, tables, n_packets=77)
    assert_pallas_matches(tables, batch)


def test_pallas_empty_table():
    tables = compile_tables_from_content({}, rule_width=4)
    rng = np.random.default_rng(7)
    batch = testing.random_batch(rng, tables, n_packets=50)
    assert_pallas_matches(tables, batch)


def test_pallas_full_rule_width():
    # All 100 rule slots populated (the reference's MAX_RULES_PER_TARGET).
    rows = np.zeros((100, 7), np.int32)
    for order in range(1, 100):
        rows[order] = [order, 6, order * 100, 0, 0, 0, 1 + order % 2]
    content = {LpmKey(32, 2, bytes(16)): rows}
    tables = compile_tables_from_content(content, rule_width=100)
    from infw.packets import make_batch

    batch = make_batch(
        src=["1.1.1.1"] * 4,
        proto=[6] * 4,
        dst_port=[100, 5000, 9900, 77],
        ifindex=[2] * 4,
    )
    ref = oracle.classify(tables, batch)
    assert [(int(r) >> 8) for r in ref.results] == [1, 50, 99, 0]
    assert_pallas_matches(tables, batch)


def test_pallas_rejects_oversized_table():
    rng = np.random.default_rng(0)
    tables = testing.random_tables(rng, n_entries=20, width=4)
    tables.mask_len.resize(5000, refcheck=False)  # simulate huge T
    object.__setattr__(tables, "num_entries", 5000)
    with pytest.raises(ValueError):
        pallas_dense.build_pallas_tables(tables)
