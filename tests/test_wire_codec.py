"""The delta+varint compressed wire codec (packets.encode_delta_wire /
decode_delta_host / kernels.wire_decode): round-trip bit-exactness vs the
pack_wire CPU oracle, fail-closed decode on truncated/corrupt/adversarial
streams, device-decode parity (XLA varint, fixed-stride, Pallas scan),
classifier dispatch on mixed v4/v6 + out-of-band mixes, and the
--wire-codec knob's precedence chain."""
import copy
import os

import numpy as np
import pytest

from infw import oracle, testing
from infw.packets import (
    DeltaDecodeError,
    DeltaWire,
    decode_delta_host,
    delta_section_offsets,
    encode_delta_wire,
    varint_encode,
)

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _v4_wire(rng, n_entries=4000, n_packets=6000, ifindexes=(2, 3, 9)):
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=4, v6_fraction=0.0,
        ifindexes=ifindexes)
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    v4 = batch.take(np.nonzero(np.asarray(batch.kind) != 2)[0])
    v4.ip_words[:, 1:] = 0  # pack_wire_v4 caller contract
    return tables, v4, v4.pack_wire_v4()


# --- host codec --------------------------------------------------------------


def test_roundtrip_matches_wire_fields():
    """encode -> decode_delta_host reproduces every classification field
    of the wire rows (in sorted order, inverse-permutable to chunk
    order); the l4 word keeps the narrow_wire overlay semantics."""
    rng = np.random.default_rng(17)
    _t, v4, w4 = _v4_wire(rng)
    enc = encode_delta_wire(w4)
    assert enc is not None
    assert enc.wire_bytes < 8 * enc.n, "must beat the wire8 floor here"
    kind, l4_ok, ifindex, proto, dst_port, itype, icode, ip = (
        decode_delta_host(enc)
    )
    p = enc.perm
    np.testing.assert_array_equal(kind, (w4[p, 0] & 3).astype(np.int32))
    np.testing.assert_array_equal(l4_ok, ((w4[p, 0] >> 2) & 1).astype(np.int32))
    np.testing.assert_array_equal(
        proto, ((w4[p, 0] >> 3) & 0xFF).astype(np.int32))
    np.testing.assert_array_equal(ifindex, w4[p, 2].astype(np.int32))
    np.testing.assert_array_equal(ip, w4[p, 3])
    is_icmp = np.isin(proto, (1, 58))
    np.testing.assert_array_equal(
        dst_port[~is_icmp], (w4[p, 1] & 0xFFFF).astype(np.int32)[~is_icmp])
    np.testing.assert_array_equal(
        itype[is_icmp], ((w4[p, 0] >> 11) & 0xFF).astype(np.int32)[is_icmp])
    np.testing.assert_array_equal(
        icode[is_icmp], ((w4[p, 0] >> 19) & 0xFF).astype(np.int32)[is_icmp])


def test_single_packet_and_max_delta():
    w = np.zeros((2, 4), np.uint32)
    w[:, 0] = 1 | (1 << 2) | (6 << 3)
    w[:, 2] = 2
    w[1, 3] = 0xFFFFFFFF  # maximum possible sorted delta
    enc = encode_delta_wire(w)
    cols = decode_delta_host(enc)
    np.testing.assert_array_equal(cols[7], [0, 0xFFFFFFFF])
    one = encode_delta_wire(w[:1])
    assert one.n == 1
    np.testing.assert_array_equal(decode_delta_host(one)[7], [0])


def test_empty_chunk_not_encoded():
    """n == 0 never reaches the codec (the dispatcher's wire8 path covers
    it); the encoder refuses rather than inventing a zero-length
    stream."""
    assert encode_delta_wire(np.zeros((0, 4), np.uint32)) is None


def test_eligibility_fallbacks():
    """>15 interfaces or a non-4-word wire disqualify the chunk (the
    dispatcher then falls down the wire8/narrow chain)."""
    rng = np.random.default_rng(23)
    w = np.zeros((64, 4), np.uint32)
    w[:, 0] = 1 | (1 << 2) | (6 << 3)
    w[:, 2] = np.arange(64) % 20 + 2  # 20 distinct ifindexes
    w[:, 3] = rng.integers(0, 2**32, 64)
    assert encode_delta_wire(w) is None
    assert encode_delta_wire(np.zeros((4, 7), np.uint32)) is None


def test_auto_gate_rejects_uncompressible():
    """With max_bytes_per_pkt (the auto-codec gate) a stream that cannot
    beat the budget returns None instead of shipping a worse payload."""
    rng = np.random.default_rng(29)
    w = np.zeros((50, 4), np.uint32)
    # adversarial meta churn: every packet a distinct proto and ifindex
    # pattern, IPs spread over the full 32-bit space -> ~7-8 B/packet
    w[:, 0] = 1 | (1 << 2) | ((np.arange(50) % 200).astype(np.uint32) << 3)
    w[:, 2] = 2
    w[:, 3] = rng.integers(0, 2**32, 50)
    enc = encode_delta_wire(w)
    assert enc is not None  # unconstrained encode always works
    gated = encode_delta_wire(w, max_bytes_per_pkt=4.0)
    assert gated is None


def test_fixed_stride_plans():
    """Clustered deltas select the 1- or 2-byte fixed stride; the decode
    is bit-exact either way."""
    rng = np.random.default_rng(31)
    for hi, want_w in ((200, 1), (60000, 2)):
        w = np.zeros((3000, 4), np.uint32)
        w[:, 0] = 1 | (1 << 2) | (6 << 3)
        w[:, 2] = 2
        w[:, 3] = np.cumsum(rng.integers(0, hi, 3000)).astype(np.uint32)
        rng.shuffle(w)
        enc = encode_delta_wire(w)
        assert enc.fixed_w == want_w, f"hi={hi}"
        cols = decode_delta_host(enc)
        np.testing.assert_array_equal(cols[7], np.sort(w[:, 3]))


def test_varint_encode_known_values():
    np.testing.assert_array_equal(varint_encode(np.array([0])), [0x00])
    np.testing.assert_array_equal(varint_encode(np.array([127])), [0x7F])
    np.testing.assert_array_equal(varint_encode(np.array([128])), [0x80, 0x01])
    np.testing.assert_array_equal(
        varint_encode(np.array([0xFFFFFFFF])),
        [0xFF, 0xFF, 0xFF, 0xFF, 0x0F])


# --- fail-closed decode ------------------------------------------------------


def _encoded(rng=None, **kw):
    rng = rng or np.random.default_rng(41)
    _t, _v4, w4 = _v4_wire(rng, **kw)
    enc = encode_delta_wire(w4)
    assert enc is not None
    return enc


def test_bit_flip_always_raises():
    """Any single bit flip anywhere in the payload must raise — never
    decode to different values (the crc is the integrity boundary)."""
    rng = np.random.default_rng(43)
    enc = _encoded(rng)
    for i in rng.choice(len(enc.payload), size=128, replace=False):
        e2 = copy.deepcopy(enc)
        e2.payload[int(i)] ^= 1 << int(rng.integers(8))
        with pytest.raises(DeltaDecodeError):
            decode_delta_host(e2)


def test_truncated_and_extended_streams_raise():
    enc = _encoded()
    for cut in (1, 3, len(enc.payload) // 2):
        e2 = copy.deepcopy(enc)
        e2.payload = e2.payload[:-cut]
        with pytest.raises(DeltaDecodeError):
            decode_delta_host(e2)
    e3 = copy.deepcopy(enc)
    e3.payload = np.concatenate([e3.payload, np.zeros(4, np.uint8)])
    with pytest.raises(DeltaDecodeError):
        decode_delta_host(e3)


def test_adversarial_crc_fixup_still_fails_structurally():
    """An attacker who recomputes the crc over a corrupted payload still
    hits the structural checks: dangling continuation bytes, wrong value
    counts, >5-byte runs, 32-bit overflow."""
    from infw.packets import _delta_crc

    enc = _encoded()
    assert enc.fixed_w == 0, "corpus must take the varint plan"
    off_b, off_c = delta_section_offsets(enc.n, enc.dict_mode)

    # dangling continuation: set the continuation bit on the last byte
    e2 = copy.deepcopy(enc)
    e2.payload[-1] |= 0x80
    e2.crc = _delta_crc(e2.payload, e2.dict_vals, e2.ifmap)
    with pytest.raises(DeltaDecodeError):
        decode_delta_host(e2)

    # value-count mismatch: clear a continuation bit mid-stream (splits
    # one value into two -> n+1 values)
    e3 = copy.deepcopy(enc)
    sec = e3.payload[off_c:]
    cont_pos = np.nonzero(sec & 0x80)[0]
    e3.payload[off_c + cont_pos[0]] &= 0x7F
    e3.crc = _delta_crc(e3.payload, e3.dict_vals, e3.ifmap)
    with pytest.raises(DeltaDecodeError):
        decode_delta_host(e3)

    # >5-byte run / 32-bit overflow: an all-continuation prefix
    e4 = copy.deepcopy(enc)
    e4.payload = np.concatenate([
        e4.payload[:off_c],
        np.full(6, 0xFF, np.uint8), np.zeros(1, np.uint8),
        e4.payload[off_c:],
    ])
    e4.crc = _delta_crc(e4.payload, e4.dict_vals, e4.ifmap)
    with pytest.raises(DeltaDecodeError):
        decode_delta_host(e4)

    # out-of-range dictionary index (dict section is exercised only in
    # dict_mode > 0)
    if enc.dict_mode:
        e5 = copy.deepcopy(enc)
        e5.payload[0] = 0xFF
        e5.dict_vals = e5.dict_vals[:4]
        e5.crc = _delta_crc(e5.payload, e5.dict_vals, e5.ifmap)
        with pytest.raises(DeltaDecodeError):
            decode_delta_host(e5)

    # delta overflow past 2^32: fix up a legal-looking stream whose
    # cumulative sum wraps
    big = varint_encode(np.array([0xFFFFFFFF, 2], np.uint64))
    e6 = DeltaWire(
        payload=np.concatenate([
            np.zeros(delta_section_offsets(2, 0)[1], np.uint8), big]),
        dict_vals=np.array([1 | (1 << 2) | (6 << 3)], np.uint32),
        ifmap=np.full(16, -1, np.int32), perm=np.arange(2, dtype=np.int64),
        n=2, dict_mode=0, fixed_w=0, crc=0,
    )
    e6.crc = _delta_crc(e6.payload, e6.dict_vals, e6.ifmap)
    with pytest.raises(DeltaDecodeError):
        decode_delta_host(e6)


# --- device decode -----------------------------------------------------------


def _device_decode(enc, use_pallas=False):
    import jax.numpy as jnp

    from infw.kernels import wire_decode

    return wire_decode.decode_delta(
        jnp.asarray(wire_decode.pad_payload(enc.payload)),
        jnp.asarray(wire_decode.pad_dict(enc.dict_vals)),
        jnp.asarray(enc.ifmap),
        n=enc.n, dict_mode=enc.dict_mode, fixed_w=enc.fixed_w,
        use_pallas=use_pallas, interpret=True,
    )


def test_device_decode_matches_host_oracle():
    """The XLA parallel varint decode is bit-exact vs decode_delta_host
    on a varint-plan corpus, and the fixed-stride + Pallas-scan variants
    on a clustered corpus."""
    rng = np.random.default_rng(47)
    enc = _encoded(rng)
    assert enc.fixed_w == 0
    host = decode_delta_host(enc)
    db = _device_decode(enc)
    names = ("kind", "l4_ok", "ifindex", "proto", "dst_port",
             "icmp_type", "icmp_code")
    for nm, h in zip(names, host[:7]):
        np.testing.assert_array_equal(np.asarray(getattr(db, nm)), h,
                                      err_msg=nm)
    np.testing.assert_array_equal(np.asarray(db.ip_words[:, 0]), host[7])
    assert int(np.asarray(db.pkt_len).max(initial=0)) == 0  # never ships

    w = np.zeros((3000, 4), np.uint32)
    w[:, 0] = 1 | (1 << 2) | (6 << 3)
    w[:, 2] = 2
    w[:, 3] = np.cumsum(rng.integers(0, 60000, 3000)).astype(np.uint32)
    encf = encode_delta_wire(w)
    assert encf.fixed_w > 0
    hostf = decode_delta_host(encf)
    for up in (False, True):
        dbf = _device_decode(encf, use_pallas=up)
        np.testing.assert_array_equal(
            np.asarray(dbf.ip_words[:, 0]), hostf[7],
            err_msg=f"pallas={up}")


# --- classifier dispatch -----------------------------------------------------


def test_classifier_delta_dispatch_mixed_families_bit_exact():
    """End-to-end through TpuClassifier on a mixed v4/v6 + out-of-band
    batch (malformed kinds, unsupported L4, OOB ifindexes): the delta
    codec serves the v4-compact chunk, v6 falls to the narrow wire, and
    every verdict + statistic matches the oracle."""
    from infw.backend.tpu import TpuClassifier
    from infw.kernels import wire_decode

    rng = np.random.default_rng(53)
    tables = testing.random_tables_fast(
        rng, n_entries=6000, width=4, v6_fraction=0.4, ifindexes=(2, 3, 9))
    batch = testing.random_batch_fast(rng, tables, n_packets=5000)
    # OOB mix: out-of-domain ifindexes on a slice (resolve to no subtree)
    batch.ifindex[::97] = 4000
    # honor the pack_wire_v4 caller contract for the v4 chunk (the host
    # parser guarantees zero high words; the synthetic generator may not)
    batch.ip_words[np.asarray(batch.kind) != 2, 1:] = 0
    ref = oracle.HashLpmOracle(tables).classify(batch)

    wire_decode.jitted_classify_delta_fused.cache_clear()
    clf = TpuClassifier(force_path="trie", wire_codec="auto")
    clf.load_tables(tables)
    # family-split dispatch like the daemon: v4-compactable chunk packed
    kinds = np.asarray(batch.kind)
    results = np.zeros(len(batch), np.uint32)
    for want_v6 in (False, True):
        g = np.nonzero((kinds == 2) == want_v6)[0]
        wire, v4_only = batch.pack_wire_subset(
            np.ascontiguousarray(g, np.int64))
        out = clf.classify_async_packed(
            wire, v4_only, apply_stats=False).result()
        results[g] = out.results
    assert wire_decode.jitted_classify_delta_fused.cache_info().currsize > 0, \
        "the delta path must engage for the v4 chunk"
    np.testing.assert_array_equal(results, ref.results)
    clf.close()


def test_classifier_delta_with_overlay_bit_exact():
    """The overlay combine (structural CIDR adds) composes with the
    delta decode exactly like the wire paths."""
    from infw.backend.tpu import TpuClassifier
    from infw.compiler import LpmKey, compile_tables_from_content

    rng = np.random.default_rng(59)
    tables, v4, w4 = _v4_wire(rng, n_entries=6000, n_packets=3000)
    # overlay entry covering some of the batch's source space
    ip0 = int(np.asarray(v4.ip_words)[0, 0])
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 0, 0, 0, 0, 0, 1]  # catch-all DENY
    ov = compile_tables_from_content({
        LpmKey(prefix_len=8 + 32, ingress_ifindex=int(v4.ifindex[0]),
               ip_data=bytes([(ip0 >> 24) & 0xFF]) + bytes(15)): rows
    }, rule_width=4)

    clf = TpuClassifier(force_path="trie", wire_codec="delta")
    clf.load_tables(tables, overlay=ov)
    out = clf.classify(v4, apply_stats=False)
    # oracle over the union of main + overlay content
    merged = dict(tables.content)
    merged.update(ov.content)
    ref_tables = compile_tables_from_content(merged, rule_width=4)
    ref = oracle.HashLpmOracle(ref_tables).classify(v4)
    np.testing.assert_array_equal(out.results, ref.results)
    clf.close()


def test_wire_codec_knob_precedence():
    """Constructor arg beats INFW_WIRE_CODEC env beats the auto default —
    the --no-fused-deep precedence pattern; unknown codecs fail loudly."""
    from infw.backend.tpu import TpuClassifier
    from infw.daemon import make_classifier_factory

    old = os.environ.pop("INFW_WIRE_CODEC", None)
    try:
        assert TpuClassifier()._wire_codec == "auto"
        os.environ["INFW_WIRE_CODEC"] = "wire8"
        assert TpuClassifier()._wire_codec == "wire8"
        assert TpuClassifier(wire_codec="delta")._wire_codec == "delta"
        factory = make_classifier_factory("tpu", wire_codec="delta")
        assert factory()._wire_codec == "delta"  # CLI plumb beats env
        with pytest.raises(ValueError):
            TpuClassifier(wire_codec="zstd")
    finally:
        os.environ.pop("INFW_WIRE_CODEC", None)
        if old is not None:
            os.environ["INFW_WIRE_CODEC"] = old


def test_daemon_cli_beats_env(tmp_path):
    """The daemon's --wire-codec flag wins over INFW_WIRE_CODEC (argparse
    default comes from the env, an explicit flag replaces it)."""
    import argparse

    from infw import daemon as daemon_mod

    old = os.environ.pop("INFW_WIRE_CODEC", None)
    try:
        os.environ["INFW_WIRE_CODEC"] = "wire8"
        p = argparse.ArgumentParser()
        p.add_argument(
            "--wire-codec", choices=["auto", "wire8", "delta"],
            default=os.environ.get("INFW_WIRE_CODEC") or None)
        assert p.parse_args([]).wire_codec == "wire8"
        assert p.parse_args(["--wire-codec", "delta"]).wire_codec == "delta"
        # and the Daemon plumbs the value into the TPU factory
        d = daemon_mod.Daemon(
            state_dir=str(tmp_path), node_name="n", backend="tpu",
            metrics_port=0, health_port=0, wire_codec="delta")
        try:
            clf = d.syncer._factory()
            assert clf._wire_codec == "delta"
            clf.close()
        finally:
            d.stop()
    finally:
        os.environ.pop("INFW_WIRE_CODEC", None)
        if old is not None:
            os.environ["INFW_WIRE_CODEC"] = old


def test_daemon_ingest_delta_end_to_end(tmp_path):
    """10K-packet frames-file replay through the real daemon ingest with
    the delta codec engaged: verdict sidecar bit-exact vs the oracle on
    the PARSED batch, double-buffered staging on."""
    from infw.backend.tpu import TpuClassifier
    from infw.daemon import (
        Daemon, parse_frames_buf, read_frames_any, write_frames_file_v2,
    )
    from infw.obs.events import EventRing, EventsLogger
    from infw.obs.pcap import build_frames_bulk

    rng = np.random.default_rng(61)
    tables = testing.random_tables_fast(
        rng, n_entries=6000, width=4, ifindexes=(2, 3, 4))
    batch = testing.random_batch_fast(rng, tables, n_packets=10_000)
    fb = build_frames_bulk(
        batch.kind, batch.ip_words, batch.proto, batch.dst_port,
        batch.icmp_type, batch.icmp_code, l4_ok=batch.l4_ok)
    fb.ifindex = np.asarray(batch.ifindex, np.uint32)

    clf = TpuClassifier(wire_codec="auto")
    clf.load_tables(tables)
    d = Daemon.__new__(Daemon)
    d.ingest_dir = os.path.join(str(tmp_path), "ingest")
    d.out_dir = os.path.join(str(tmp_path), "out")
    os.makedirs(d.ingest_dir)
    os.makedirs(d.out_dir)
    d.ingest_chunk = 4096
    d.pipeline_depth = 4
    d.max_tick_packets = 1 << 20
    d.debug_lookup = False
    d.h2d_overlap = True
    d.h2d_stage_depth = 2
    d.ring = EventRing(capacity=1 << 16)
    d.events_logger = EventsLogger(d.ring, lambda line: None)

    class _Syncer:
        classifier = clf

    d.syncer = _Syncer()
    path = os.path.join(d.ingest_dir, "a.frames")
    write_frames_file_v2(path + ".keep", fb)
    os.replace(path + ".keep", path)
    # keep a parsed copy BEFORE ingest consumes the file
    parsed = parse_frames_buf(read_frames_any(path))
    assert d.process_ingest_once() == 1
    stats = clf.wire_stats()
    assert "delta" in stats and stats["delta"][0] > 0, stats
    assert stats["delta"][1] < 8 * stats["delta"][0], \
        "delta payload must beat the wire8 floor on this corpus"
    rb = np.fromfile(
        os.path.join(d.out_dir, "a.frames.verdicts.bin"), dtype="<u4")
    ref = oracle.HashLpmOracle(tables).classify(parsed)
    np.testing.assert_array_equal(rb, ref.results)
    clf.close()
