"""Static concurrency verifier (ISSUE-18, infw.analysis.lockcheck):
graph construction on fixture modules, cycle witness output, the
guarded-field rule, ordering contracts (call and store: forms), thread
hygiene, suppression parsing, and the repo-wide sweep + lockorder
injected-defect acceptances.
"""
import textwrap

import pytest

from infw.analysis import lockcheck


def _corpus(**files):
    return lockcheck.build_corpus(files=[
        (f"fix/{name}.py", textwrap.dedent(src))
        for name, src in files.items()
    ])


def _analyze(**files):
    findings, stats = lockcheck.analyze(_corpus(**files),
                                        declared_order=[])
    return findings, stats


def _by_check(findings, check):
    return [f for f in findings if f.check == check]


# --- inventory + acquisition graph ------------------------------------------


FIXTURE_AB = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def outer(self, b: "B"):
            with self._lock:
                b.inner()

    class B:
        def __init__(self):
            self._lock = threading.Lock()

        def inner(self):
            with self._lock:
                pass
"""


def test_inventory_and_graph_edges():
    findings, stats = _analyze(mod=FIXTURE_AB)
    assert stats["lock_sites"] == 2
    assert "A._lock -> B._lock" in stats["edges"]
    wit = stats["edges"]["A._lock -> B._lock"]
    assert "A.outer" in wit and "B.inner" in wit
    assert not _by_check(findings, "lock-cycle")


def test_explicit_acquire_release_tracked():
    findings, stats = _analyze(mod="""
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self, b: "B"):
                self._lock.acquire()
                b.inner()
                self._lock.release()

        class B:
            def __init__(self):
                self._lock = threading.Lock()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert "A._lock -> B._lock" in stats["edges"]


def test_cycle_reported_with_both_witnesses():
    findings, _ = _analyze(mod=textwrap.dedent(FIXTURE_AB) + textwrap.dedent("""
        def reverse(a: "A", b: "B"):
            with b._lock:
                a.outer(b)
    """))
    cycles = _by_check(findings, "lock-cycle")
    assert len(cycles) == 1
    assert cycles[0].severity == "error"
    assert len(cycles[0].witnesses) == 2
    blob = "\n".join(cycles[0].witnesses)
    assert "A.outer" in blob and "reverse" in blob


def test_self_deadlock_on_plain_lock_not_rlock():
    findings, _ = _analyze(mod="""
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert any("self-deadlock" in f.check for f in findings)
    findings, _ = _analyze(mod="""
        import threading

        class A:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert not any("self-deadlock" in f.check for f in findings)


# --- guarded fields ----------------------------------------------------------


def test_guarded_field_torn_publish_flagged():
    findings, _ = _analyze(mod="""
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def locked_bump(self):
                with self._lock:
                    self._n += 1

            def unlocked_bump(self):
                self._n += 1
    """)
    gf = _by_check(findings, "guarded-field")
    assert [f.subject for f in gf] == ["A._n"]
    assert "unlocked_bump" in gf[0].message


def test_guarded_field_init_and_locked_helpers_exempt():
    findings, _ = _analyze(mod="""
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # init writes never count as unlocked

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self._n += 1  # all callsites hold the lock

            def read_locked(self):
                with self._lock:
                    return self._n
    """)
    assert not _by_check(findings, "guarded-field")


# --- ordering contracts -------------------------------------------------------


def test_must_precede_pass_and_fail():
    ok, _ = _analyze(mod="""
        from infw.contracts import must_precede

        class A:
            @must_precede("pre_flip", "flip")
            def install(self, pre_flip):
                pre_flip()
                self.flip()

            def flip(self):
                pass
    """)
    assert not _by_check(ok, "ordering-contract")
    bad, _ = _analyze(mod="""
        from infw.contracts import must_precede

        class A:
            @must_precede("pre_flip", "flip")
            def install(self, pre_flip):
                self.flip()
                pre_flip()

            def flip(self):
                pass
    """)
    oc = _by_check(bad, "ordering-contract")
    assert len(oc) == 1 and oc[0].severity == "error"


def test_must_precede_store_form():
    bad, _ = _analyze(mod="""
        from infw.contracts import must_precede

        class R:
            @must_precede("load", "store:_names")
            def create(self, name):
                self._names[name] = 1
                self.load()

            def load(self):
                pass
    """)
    assert _by_check(bad, "ordering-contract")
    ok, _ = _analyze(mod="""
        from infw.contracts import must_precede

        class R:
            @must_precede("load", "store:_names")
            def create(self, name):
                self.load()
                self._names[name] = 1

            def load(self):
                pass
    """)
    assert not _by_check(ok, "ordering-contract")


# --- thread hygiene -----------------------------------------------------------


def test_thread_hygiene_flags_raw_thread_even_nested():
    findings, _ = _analyze(mod="""
        import threading

        def outer():
            def inner():
                threading.Thread(target=print, daemon=True).start()
            inner()
    """)
    th = _by_check(findings, "thread-hygiene")
    assert len(th) == 1 and th[0].severity == "error"
    ok, _ = _analyze(mod="""
        from infw._threads import spawn

        def outer():
            spawn(print, name="x")
    """)
    assert not _by_check(ok, "thread-hygiene")


# --- suppressions ------------------------------------------------------------


def test_suppression_file_requires_justification(tmp_path):
    good = tmp_path / "s.txt"
    good.write_text(
        "# comment\n"
        "guarded-field A._n  # reviewed: benign\n"
    )
    supp = lockcheck.load_suppressions(str(good))
    assert supp == [("guarded-field", "A._n", "reviewed: benign")]
    bad = tmp_path / "b.txt"
    bad.write_text("guarded-field A._n\n")
    with pytest.raises(ValueError):
        lockcheck.load_suppressions(str(bad))


# --- repo-wide sweep + injected defect ---------------------------------------


def test_repo_sweep_zero_unsuppressed_findings():
    rep = lockcheck.analyze_repo()
    assert rep["errors"] == 0, rep["findings"]
    assert rep["warnings"] == 0, rep["findings"]
    # the shipped suppressions are consumed (cowrace defect shims)
    assert len(rep["suppressed"]) == 2
    assert len(rep["inventory"]) >= 19
    # the declared flow -> telemetry edge is actually measured
    assert "FlowTier._lock -> TelemetryTier._lock" in rep["stats"]["edges"]


def test_lockorder_injection_caught_with_both_witnesses():
    rep = lockcheck.analyze_repo(inject_defect="lockorder")
    cycles = [f for f in rep["findings"] if f["check"] == "lock-cycle"]
    assert cycles, rep["findings"]
    assert any(len(f["witnesses"]) >= 2 for f in cycles)
    blob = "\n".join(w for f in cycles for w in f["witnesses"])
    assert "resident_dispatch" in blob
    assert "_defect_lockorder" in blob
    # the synthetic edge also contradicts the declared LOCK_ORDER
    assert any(f["check"] == "lock-order" for f in rep["findings"])
