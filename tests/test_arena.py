"""Multi-tenant paged table arena (ISSUE-10).

Covers the arena core (slab baking, page-table steering, allocator
lifecycle), mixed-tenant classify bit-identity vs per-tenant CPU
oracles through the production wire dispatch (XLA dense + ctrie, the
paged Pallas walk, single-chip and mesh), the per-slab incremental
patch path, the zero-recompile warm-arena contract across tenant
counts and lifecycle ops, the 8-iface mixed-ifindex regression (old
path semantics preserved bit-identically when the interfaces run AS
tenants), the tenant registry / scheduler / daemon integration, and
the statecheck arena configs incl. the pageflip injected defect.
"""
import os

import numpy as np
import pytest

from infw import oracle, packets, testing
from infw.backend.tpu import ArenaClassifier, TpuClassifier
from infw.compiler import IncrementalTables, compile_tables_from_content
from infw.kernels import jaxpath, pallas_walk

import jax


def _tenants(n, entries=24, v6=0.4, seed0=100, width=4):
    return {
        t: testing.random_tables(
            np.random.default_rng(seed0 + t), n_entries=entries,
            width=width, v6_fraction=v6,
        )
        for t in range(n)
    }


def _mixed(tabs, per=40, seed=7):
    parts, tags, want = [], [], []
    for t, tab in sorted(tabs.items()):
        b = testing.random_batch(np.random.default_rng(seed + t), tab, per)
        parts.append(b)
        tags.append(np.full(per, t, np.int32))
        want.append(oracle.classify(tab, b).results)
    return packets.concat(parts), np.concatenate(tags), np.concatenate(want)


def _classify_arena(alloc, wire, tenant, n, kind):
    spec = alloc.spec
    d_max = spec.d_max if spec.family == "ctrie" else 0
    fn = jaxpath.jitted_classify_arena_wire_fused(
        spec.family, spec.pages, d_max
    )
    fused = fn(alloc.arena, jax.device_put(wire), jax.device_put(tenant))
    res16, stats = jaxpath.split_wire_outputs(np.asarray(fused), n)
    results, xdp = jaxpath.host_finalize_wire(res16, kind)
    return results, xdp, stats


# --- spec / geometry ---------------------------------------------------------


def test_spec_validation():
    with pytest.raises(ValueError, match="4 pages"):
        jaxpath.make_arena_spec("dense", 2, 4, 16, 4)
    with pytest.raises(ValueError, match="family"):
        jaxpath.make_arena_spec("trie", 4, 4, 16, 4)
    s = jaxpath.make_arena_spec("ctrie", 4, 8, 17, 4, node_rows=130)
    assert s.entries == 32          # row bucket
    assert s.node_rows == 256       # 128-row tiles
    assert s.joined_rows == s.entries + 1


def test_capacity_errors():
    tabs = _tenants(1)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=4,
                                  max_tenants=2)
    al = jaxpath.ArenaAllocator(spec)
    big = testing.random_tables(
        np.random.default_rng(0), n_entries=4 * spec.entries, width=4
    )
    with pytest.raises(jaxpath.ArenaCapacityError):
        al.load_tenant(0, big)
    with pytest.raises(jaxpath.ArenaCapacityError):
        al.load_tenant(99, tabs[0])  # tenant id out of range
    al.load_tenant(0, tabs[0])
    # identical content shares ONE page (content addressing), so page
    # exhaustion needs DISTINCT rulesets
    al.load_tenant(1, tabs[0])
    assert al.page_of(1) == al.page_of(0)
    assert al.free_pages() == 3
    distinct = [
        testing.random_tables(np.random.default_rng(7000 + i),
                              n_entries=16, width=4)
        for i in range(4)
    ]
    al.stage(distinct[0])
    al.stage(distinct[1])
    al.stage(distinct[2])
    with pytest.raises(jaxpath.ArenaCapacityError, match="out of pages"):
        al.stage(distinct[3])
    # re-staging resident content never needs a page
    assert al.stage(distinct[0]) in range(spec.pages)


# --- mixed-tenant classify bit-identity -------------------------------------


@pytest.mark.parametrize("family", ["dense", "ctrie"])
def test_mixed_tenant_oracle_identity(family):
    tabs = _tenants(5)
    spec = jaxpath.arena_spec_for(family, tabs.values(), pages=8,
                                  max_tenants=16)
    al = jaxpath.ArenaAllocator(spec)
    for t, tab in tabs.items():
        assert al.load_tenant(t, tab) == "assign"
    batch, tenant, want = _mixed(tabs)
    results, xdp, _ = _classify_arena(
        al, batch.pack_wire(), tenant, len(batch), np.asarray(batch.kind)
    )
    np.testing.assert_array_equal(results, want)
    # unknown / absent tenant ids classify to UNDEF, never leak a slab
    weird = np.array([99, -1, 7, 1000], np.int32)
    r2, _x, _s = _classify_arena(
        al, batch.pack_wire()[:4], weird, 4, np.asarray(batch.kind[:4])
    )
    assert (r2 == 0).all()


def test_swap_destroy_compact():
    tabs = _tenants(4)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=8,
                                  max_tenants=8)
    al = jaxpath.ArenaAllocator(spec)
    for t, tab in tabs.items():
        al.load_tenant(t, tab)
    batch, tenant, want = _mixed(tabs)
    new1 = testing.random_tables(np.random.default_rng(77), n_entries=20,
                                 width=4, v6_fraction=0.4)
    page = al.stage(new1)
    al.activate(1, page, new1)
    per = len(batch) // 4
    want2 = want.copy()
    want2[per:2 * per] = oracle.classify(
        new1, batch.slice(per, 2 * per)
    ).results
    results, _x, _s = _classify_arena(
        al, batch.pack_wire(), tenant, len(batch), np.asarray(batch.kind)
    )
    np.testing.assert_array_equal(results, want2)
    al.destroy_tenant(0)
    results, _x, _s = _classify_arena(
        al, batch.pack_wire(), tenant, len(batch), np.asarray(batch.kind)
    )
    assert (results[:per] == 0).all()
    np.testing.assert_array_equal(results[per:], want2[per:])
    # compaction repacks low pages; verdicts unchanged
    moved = al.compact()
    assert moved >= 1
    results, _x, _s = _classify_arena(
        al, batch.pack_wire(), tenant, len(batch), np.asarray(batch.kind)
    )
    np.testing.assert_array_equal(results[per:], want2[per:])
    from infw.analysis.statecheck import check_arena

    assert check_arena(al) == []


def test_activate_free_list_consistency():
    """Review regression: ping-pong re-activation between two pages
    (the bench A/B and the standby-page pattern) must never leave a
    page both free and mapped or duplicate free-list entries, and an
    activate with no tables record must not let compact() rebake the
    PRE-swap ruleset."""
    from infw.analysis.statecheck import check_arena

    tabs = _tenants(2)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=4,
                                  max_tenants=4)
    al = jaxpath.ArenaAllocator(spec)
    al.load_tenant(0, tabs[0])
    pg_a = al.stage(tabs[1])
    pg_b = al.page_of(0)
    for i in range(5):  # ping-pong: claim back the freed page each flip
        al.activate(0, pg_a if i % 2 == 0 else pg_b)
        assert check_arena(al) == []
        assert sorted(al._free) == sorted(set(al._free))
    # odd flip count: tenant 0 ends on pg_a, the tabs[1] slab.
    # activating a page live for ANOTHER tenant now SHARES it
    # (refcounted page-table rows, ISSUE-15) instead of refusing
    al.load_tenant(1, tabs[1])
    al.activate(0, al.page_of(1))
    assert al.page_of(0) == al.page_of(1)
    assert al.page_refcount(al.page_of(1)) == 2
    assert check_arena(al) == []
    # tables-less activate drops the stale record; the canonical host
    # mirror still lets compact() move the page correctly
    assert al.tables_of(0) is None
    al.compact()
    assert check_arena(al) == []
    b = testing.random_batch(np.random.default_rng(3), tabs[1], 48)
    results, _x, _s = _classify_arena(
        al, b.pack_wire(), np.zeros(48, np.int32), 48, np.asarray(b.kind)
    )
    np.testing.assert_array_equal(
        results, oracle.classify(tabs[1], b).results
    )


def test_registry_concurrent_edit_during_create():
    """Review regression: an edit racing a create must get a clean
    TenantError (the name publishes only after the load succeeds),
    never a None updater."""
    from infw.syncer import TenantError, TenantRegistry

    tabs = _tenants(1)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=4,
                                  max_tenants=4)
    reg = TenantRegistry(
        ArenaClassifier(spec, interpret=True, fused_deep=False),
        rule_width=4,
    )
    reg._creating["x"] = 0  # a create in flight
    with pytest.raises(TenantError, match="unknown"):
        reg.update_tenant("x", {}, [])
    with pytest.raises(TenantError, match="exists"):
        reg.create_tenant("x", {})
    del reg._creating["x"]
    reg.create_tenant("x", dict(tabs[0].content))
    assert reg.tenant_id("x") == 0


# --- per-slab incremental patch ---------------------------------------------


@pytest.mark.parametrize("family", ["dense", "ctrie"])
def test_rules_only_patch_per_slab(family):
    tab = testing.random_tables(np.random.default_rng(5), n_entries=24,
                                width=4, v6_fraction=0.4)
    upd = IncrementalTables.from_content(dict(tab.content), rule_width=4)
    snap0 = upd.snapshot()
    spec = jaxpath.arena_spec_for(family, [snap0], pages=4, max_tenants=4)
    al = jaxpath.ArenaAllocator(spec)
    al.load_tenant(0, snap0)
    upd.start_dirty_tracking()
    k = sorted(snap0.content, key=lambda k: (k.ingress_ifindex, k.ip_data))[0]
    r = np.asarray(snap0.content[k]).copy()
    r[1] = [1, 6, 80, 0, 0, 0, 2]
    upd.apply({k: r}, [])
    hint = upd.peek_dirty()
    snap1 = upd.snapshot()
    assert al.load_tenant(0, snap1, hint=hint) == "patch"
    # patched pool bit-identical to a fresh bake of the new snapshot
    al2 = jaxpath.ArenaAllocator(spec)
    al2.load_tenant(0, snap1)
    for name in al._host:
        np.testing.assert_array_equal(
            np.asarray(getattr(al.arena, name)),
            np.asarray(getattr(al2.arena, name)),
            err_msg=name,
        )
    b = testing.random_batch(np.random.default_rng(1), snap1, 64)
    results, _x, _s = _classify_arena(
        al, b.pack_wire(), np.zeros(64, np.int32), 64, np.asarray(b.kind)
    )
    np.testing.assert_array_equal(
        results, oracle.classify(snap1, b).results
    )


# --- paged Pallas walk -------------------------------------------------------


def test_pallas_arena_walk_bit_identity():
    tabs = _tenants(4, v6=0.6)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=8,
                                  max_tenants=8)
    al = jaxpath.ArenaAllocator(spec)
    for t, tab in tabs.items():
        al.load_tenant(t, tab)
    planes = pallas_walk.build_arena_cwalk_planes(al.host_nodes())
    assert planes is not None
    batch, tenant, want = _mixed(tabs)
    fn = pallas_walk.jitted_classify_arena_cwalk_wire_fused(
        spec.pages, spec.d_max, True
    )
    fused = fn(al.arena, planes, jax.device_put(batch.pack_wire()),
               jax.device_put(tenant))
    res16, _stats = jaxpath.split_wire_outputs(np.asarray(fused), len(batch))
    results, _xdp = jaxpath.host_finalize_wire(
        res16, np.asarray(batch.kind)
    )
    np.testing.assert_array_equal(results, want)


def test_fused_planes_track_swaps_incrementally():
    """Review regression: with the fused paged walk on, a tenant swap
    must (a) refresh ONLY the written slab's plane rows (not O(pool)),
    (b) refresh BEFORE the page-table flip, and the post-swap classify
    must serve the NEW ruleset through the Pallas path."""
    tabs = _tenants(3, v6=0.6)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=8,
                                  max_tenants=8)
    clf = ArenaClassifier(spec, interpret=True, fused_deep=True)
    for t, tab in tabs.items():
        clf.load_tenant(t, tab)
    batch, tenant, want = _mixed(tabs)
    np.testing.assert_array_equal(
        clf.classify_tenants(batch, tenant).results, want
    )
    planes_before = clf._planes
    new1 = testing.random_tables(np.random.default_rng(88), n_entries=20,
                                 width=4, v6_fraction=0.6)
    clf.swap_tenant(1, new1)
    # incremental path: a fresh planes array was scattered, not rebuilt
    # from a zeroed pool (same shape, different object)
    assert clf._planes is not planes_before
    assert clf._planes.shape == planes_before.shape
    per = len(batch) // 3
    want2 = want.copy()
    want2[per:2 * per] = oracle.classify(
        new1, batch.slice(per, 2 * per)
    ).results
    np.testing.assert_array_equal(
        clf.classify_tenants(batch, tenant).results, want2
    )
    # planes must also be bit-identical to a cold full-pool build
    cold = pallas_walk.build_arena_cwalk_planes(clf.allocator.host_nodes())
    np.testing.assert_array_equal(
        np.asarray(clf._planes), np.asarray(cold)
    )
    clf.close()


def test_scheduler_refuses_tenant_tags_on_plain_backend():
    from infw.scheduler import ContinuousScheduler, FixedChunkPolicy

    tab = _tenants(1)[0]
    clf = TpuClassifier(interpret=True, fused_deep=False)
    clf.load_tables(tab)
    sched = ContinuousScheduler(clf, FixedChunkPolicy(16))
    b = testing.random_batch(np.random.default_rng(0), tab, 16)
    with pytest.raises(ValueError, match="tenant contract"):
        sched.serve(b, np.zeros(16), tenant_of=np.zeros(16, np.int32))
    clf.close()


def test_pallas_arena_vmem_gate():
    assert pallas_walk.build_arena_cwalk_planes(
        np.zeros((1 << 20, 20), np.uint32), vmem_budget=1 << 20
    ) is None


# --- ArenaClassifier (production dispatch) ----------------------------------


def test_arena_classifier_fused_and_overlay():
    tabs = _tenants(3, v6=0.5)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=8,
                                  max_tenants=8)
    ov_spec = jaxpath.make_arena_spec("dense", 4, 8, 16, 4)
    clf = ArenaClassifier(spec, overlay_spec=ov_spec, interpret=True,
                          fused_deep=True)
    for t, tab in tabs.items():
        clf.load_tenant(t, tab)
    batch, tenant, want = _mixed(tabs)
    out = clf.classify_tenants(batch, tenant)
    np.testing.assert_array_equal(out.results, want)
    # per-tenant overlay: tenant 0 gains a longer-prefix key via the
    # dense side-pool; combine must pick it by prefix length
    k0 = sorted(tabs[0].content, key=lambda k: -k.prefix_len)
    merged = dict(tabs[0].content)
    ov_rng = np.random.default_rng(3)
    ov_tab = testing.random_tables(ov_rng, n_entries=4, width=4,
                                   v6_fraction=0.0)
    ov_content = {
        k: v for k, v in ov_tab.content.items()
        if k.masked_identity() not in
        {kk.masked_identity() for kk in merged}
    }
    assert ov_content
    clf.load_tenant_overlay(0, compile_tables_from_content(
        ov_content, rule_width=4))
    merged.update(ov_content)
    combined = compile_tables_from_content(merged, rule_width=4)
    b0 = testing.random_batch(np.random.default_rng(11), combined, 96)
    out = clf.classify_tenants(b0, np.zeros(96, np.int32))
    np.testing.assert_array_equal(
        out.results, oracle.classify(combined, b0).results
    )
    # clearing the overlay restores the base table
    clf.load_tenant_overlay(0, None)
    out = clf.classify_tenants(b0, np.zeros(96, np.int32))
    np.testing.assert_array_equal(
        out.results, oracle.classify(tabs[0], b0).results
    )
    counters = clf.tenant_counters()
    assert counters["tenant_active_slabs"] == 3
    assert counters["tenant_0_packets_total"] > 0
    # allow/deny orientation pinned against the oracle (review
    # regression: the two were swapped): result action byte 2 = ALLOW,
    # 1 = DENY
    act = oracle.classify(tabs[0], b0).results & 0xFF
    assert counters["tenant_0_allow_total"] >= int((act == 2).sum())
    assert counters["tenant_0_deny_total"] >= int((act == 1).sum())
    total_pk = counters["tenant_0_packets_total"]
    assert counters["tenant_0_allow_total"] + counters[
        "tenant_0_deny_total"
    ] <= total_pk


# --- zero-recompile warm-arena contract -------------------------------------


@pytest.mark.slow
def test_zero_recompiles_across_tenant_counts_and_lifecycle():
    """The recompile lint (the scheduler/test_statecheck _cache_size
    pattern): on a warm arena, growing the ACTIVE tenant count through
    1/8/64 (dense additionally 512), hot-swapping, patching and
    classifying must compile NOTHING new — every executable is keyed on
    pool geometry, and the allocator warm ladder covers every scatter
    shape the lifecycle can emit."""
    # dense family at 512 tenants (slabs are small); ctrie at 64
    cases = [("dense", 512 + 2, (1, 8, 64, 512)),
             ("ctrie", 64 + 2, (1, 8, 64))]
    for family, pages, counts in cases:
        mk = lambda t: testing.random_tables(
            np.random.default_rng(50 + (t % 2)), n_entries=12, width=4,
            v6_fraction=0.3,
        )
        tabs = {0: mk(0), 1: mk(1)}
        spec = jaxpath.arena_spec_for(
            family, tabs.values(), pages=pages, max_tenants=pages,
            headroom=2.0,
        )
        al = jaxpath.ArenaAllocator(spec)
        d_max = spec.d_max if family == "ctrie" else 0
        fn = jaxpath.jitted_classify_arena_wire_fused(
            family, spec.pages, d_max
        )
        al.load_tenant(0, mk(0))
        b = testing.random_batch(np.random.default_rng(1), tabs[0], 64)
        wire = jax.device_put(b.pack_wire())

        def classify(n_live):
            tenant = jax.device_put(
                (np.arange(64) % max(n_live, 1)).astype(np.int32)
            )
            np.asarray(fn(al.arena, wire, tenant))

        classify(1)  # the one allowed compile of the classify factory
        scatter0 = jaxpath._scatter_rows_jit()._cache_size()
        fn0 = fn._cache_size()
        loaded = 1
        for n_live in counts:
            while loaded < n_live:
                al.load_tenant(loaded, mk(loaded))
                loaded += 1
            classify(n_live)
        # lifecycle on the warm arena: swap, patch, destroy, classify
        upd = IncrementalTables.from_content(
            dict(mk(0).content), rule_width=4
        )
        al.swap_tenant(0, upd.snapshot())
        upd.start_dirty_tracking()
        k = list(upd.content)[0]
        r = np.asarray(upd.content[k]).copy()
        r[1] = [1, 6, 81, 0, 0, 0, 1]
        upd.apply({k: r}, [])
        hint = upd.peek_dirty()
        # tenant 0 shares its page with every even tenant (identical
        # content), so the rules-only edit lands as a CoW clone — which
        # must be exactly as compile-free as the in-place patch (the
        # clone rides the warmed full-slab fused scatter)
        assert al.load_tenant(0, upd.snapshot(), hint=hint) == "cow"
        al.destroy_tenant(counts[-1] - 1)
        classify(counts[-1] - 1)
        assert fn._cache_size() == fn0, family
        grew = jaxpath._scatter_rows_jit()._cache_size() - scatter0
        assert grew == 0, (
            f"{family}: {grew} scatter executable(s) compiled on the "
            "warm arena lifecycle"
        )


# --- 8-iface mixed-ifindex regression (bugfix sweep) ------------------------


def test_8iface_mixed_ifindex_as_tenants():
    """The pre-arena multi-interface posture (BENCH_r04's 8-iface
    mixed-ifindex path: ONE table keyed by ifindex) must be exactly
    reproducible AS tenants on the arena — one tenant per interface,
    each packet tagged with its interface's tenant — bit-identical
    verdicts to the single-table mixed-ifindex classify."""
    rng = np.random.default_rng(42)
    ifaces = list(range(2, 10))
    per_if = {}
    content = {}
    for i in ifaces:
        t = testing.random_tables(
            np.random.default_rng(1000 + i), n_entries=12, width=4,
            v6_fraction=0.3, ifindexes=(i,),
        )
        per_if[i] = t
        content.update(t.content)
    combined = compile_tables_from_content(content, rule_width=4)
    old_clf = TpuClassifier(interpret=True, force_path="trie",
                            fused_deep=False)
    old_clf.load_tables(combined)
    spec = jaxpath.arena_spec_for("ctrie", per_if.values(), pages=12,
                                  max_tenants=16)
    al = jaxpath.ArenaAllocator(spec)
    for j, i in enumerate(ifaces):
        al.load_tenant(j, per_if[i])
    parts = []
    for i in ifaces:
        parts.append(
            testing.random_batch(np.random.default_rng(7 + i), per_if[i], 24)
        )
    batch = packets.concat(parts)
    # the tenant column is DERIVED from each packet's ifindex — exactly
    # how the old one-table mixed-ifindex path routes (random batches
    # include noise packets on other interfaces; those must land in the
    # interface-owning tenant's slab, or nowhere for unknown ifindexes)
    ifx = np.asarray(batch.ifindex, np.int64)
    tenant = np.where(
        (ifx >= 2) & (ifx < 2 + len(ifaces)), ifx - 2, -1
    ).astype(np.int32)
    want = old_clf.classify(batch, apply_stats=False).results
    results, xdp, _ = _classify_arena(
        al, batch.pack_wire(), tenant, len(batch), np.asarray(batch.kind)
    )
    np.testing.assert_array_equal(results, want)
    old_clf.close()


# --- mesh ------------------------------------------------------------------


@pytest.mark.parametrize("rules_shards", [1, 2])
def test_mesh_arena_parity(rules_shards):
    from infw.backend.mesh import MeshArenaClassifier

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    tabs = _tenants(4)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=8,
                                  max_tenants=8)
    clf = MeshArenaClassifier(
        spec, data_shards=4 // rules_shards, rules_shards=rules_shards
    )
    for t, tab in tabs.items():
        clf.load_tenant(t, tab)
    batch, tenant, want = _mixed(tabs)
    out = clf.classify_tenants(batch, tenant)
    np.testing.assert_array_equal(out.results, want)
    # hot swap through the replicated scatter path
    new0 = testing.random_tables(np.random.default_rng(55), n_entries=16,
                                 width=4, v6_fraction=0.3)
    clf.swap_tenant(0, new0)
    per = len(batch) // 4
    want2 = want.copy()
    want2[:per] = oracle.classify(new0, batch.slice(0, per)).results
    out = clf.classify_tenants(batch, tenant)
    np.testing.assert_array_equal(out.results, want2)
    clf.close()


def test_mesh_mixed_batch_64_tenants():
    """The ISSUE-10 acceptance shape on the mesh: ONE mixed-tenant
    classify batch over >= 64 tenants, bit-identical to the per-tenant
    CPU oracles through the production mesh wire dispatch (dense
    family keeps the 64-page pool cheap on the virtual CPU mesh)."""
    from infw.backend.mesh import MeshArenaClassifier

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    tabs = _tenants(64, entries=8, v6=0.25)
    spec = jaxpath.arena_spec_for("dense", tabs.values(), pages=66,
                                  max_tenants=66)
    clf = MeshArenaClassifier(spec, data_shards=4, rules_shards=2)
    for t, tab in tabs.items():
        clf.load_tenant(t, tab)
    batch, tenant, want = _mixed(tabs, per=8)
    out = clf.classify_tenants(batch, tenant)
    np.testing.assert_array_equal(out.results, want)
    clf.close()


# --- registry / scheduler / daemon integration ------------------------------


def test_tenant_registry_lifecycle_and_events():
    from infw.obs.events import EventRing, TenantSwapRecord
    from infw.syncer import TenantError, TenantRegistry
    from infw.txn import EditOp as TxnOp

    tabs = _tenants(2)
    ring = EventRing(capacity=64)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=6,
                                  max_tenants=8)
    reg = TenantRegistry(
        ArenaClassifier(spec, interpret=True, fused_deep=False),
        rule_width=4, event_ring=ring,
    )
    for t, tab in tabs.items():
        reg.create_tenant(f"t{t}", dict(tab.content))
    with pytest.raises(TenantError):
        reg.create_tenant("t0", {})
    with pytest.raises(TenantError):
        reg.tenant_id("nope")
    # folded per-tenant transaction through the production fold
    k = sorted(tabs[0].content, key=lambda k: (k.ingress_ifindex,
                                               k.ip_data))[0]
    r = np.asarray(tabs[0].content[k]).copy()
    r[1] = [1, 17, 53, 0, 0, 0, 2]
    assert reg.apply_edit_transaction(
        "t0", [TxnOp(kind="key_delete", key=k),
               TxnOp(kind="key_add", key=k, rules=r)]
    ) in ("patch", "rewrite")
    snap = reg._updaters[reg.tenant_id("t0")].snapshot()
    b = testing.random_batch(np.random.default_rng(9), snap, 64)
    out = reg.classify_mixed(b, ["t0"] * 64)
    np.testing.assert_array_equal(
        out.results, oracle.classify(snap, b).results
    )
    reg.swap_tenant("t1", dict(tabs[0].content))
    reg.destroy_tenant("t1")
    kinds = [rec.kind for rec in ring.pop_all()
             if isinstance(rec, TenantSwapRecord)]
    assert kinds == ["create", "create", "swap", "destroy"]
    c = reg.counter_values()
    assert c["tenant_registered"] == 1
    assert c["tenant_swaps_total"] == 1


def test_scheduler_tenant_tagged_admissions():
    from infw.scheduler import ContinuousScheduler, FixedChunkPolicy

    tabs = _tenants(3)
    spec = jaxpath.arena_spec_for("ctrie", tabs.values(), pages=6,
                                  max_tenants=8)
    clf = ArenaClassifier(spec, interpret=True, fused_deep=False)
    for t, tab in tabs.items():
        clf.load_tenant(t, tab)
    batch, tenant, want = _mixed(tabs, per=32)
    sched = ContinuousScheduler(clf, FixedChunkPolicy(48))
    res = sched.serve(
        batch, np.zeros(len(batch)), tenant_of=tenant
    )
    np.testing.assert_array_equal(res.results, want)
    assert sched.stats.counter_values()[
        "scheduler_admitted_packets_total"
    ] == len(batch)
    clf.close()


def test_daemon_tenant_mode(tmp_path):
    from infw.compiler import build_key
    from infw.daemon import Daemon
    from infw.packets import make_batch
    from infw.txn import EditOp as TxnOp, write_edit_file

    d = str(tmp_path)
    dm = Daemon(state_dir=d, node_name="n1", tenants=4)
    edits = os.path.join(d, "tenants", "acme", "edits")
    os.makedirs(edits, exist_ok=True)
    rules = np.zeros((16, 7), np.int32)
    rules[1] = [1, 6, 443, 0, 0, 0, 2]
    write_edit_file(
        os.path.join(edits, "e1.json"),
        [TxnOp(kind="key_add", key=build_key(2, "10.1.0.0/16"),
               rules=rules)],
    )
    # a bad file is consumed, never wedging the scan
    with open(os.path.join(edits, "bad.json"), "w") as f:
        f.write("{not json")
    assert dm.scan_tenant_edits_once() == 1
    assert os.listdir(edits) == []
    assert dm.tenant_registry.tenant_names() == ["acme"]
    b = make_batch(src=["10.1.2.3", "10.2.0.1"], proto=[6, 6],
                   ifindex=[2, 2], dst_port=[443, 443])
    out = dm.tenant_registry.classify_mixed(b, ["acme", "acme"])
    assert out.results.tolist() == [0x102, 0]  # deny rule 1; no match
    text = dm.metrics_registry.render_text()
    assert "tenant_active_slabs" in text


def test_daemon_tenants_flag_validation(capsys):
    from infw.daemon import main as daemon_main

    with pytest.raises(SystemExit):
        daemon_main(["--state-dir", "/tmp/x-infw-t", "--tenants", "0"])


# --- statecheck arena configs + pageflip defect -----------------------------


@pytest.mark.slow
def test_statecheck_arena_configs():
    from infw.analysis import statecheck

    for cfg in ("arena", "arena-ctrie"):
        rep = statecheck.run_config(cfg, seed=1, n_ops=5,
                                    shrink_on_failure=False)
        assert rep["ok"], rep


@pytest.mark.slow
def test_pageflip_defect_caught_and_shrunk():
    from infw.analysis import statecheck
    from infw.analysis.shrink import shrink_case

    base, ops = statecheck.build_case("arena-ctrie", 0, 8)
    assert any(op.kind == "tenant_swap" for op in ops)
    jaxpath._INJECT_PAGEFLIP_BUG = True
    try:
        failure = statecheck.run_ops(base, ops, "arena-ctrie", seed=0)
        assert failure is not None, "pageflip defect not caught"
        repro = shrink_case(
            base, list(ops), "arena-ctrie", failure,
            witness_b=64, backend="tpu", seed=0, max_runs=24,
        )
        assert len(repro.ops) <= 3, repro.code()
        assert "tenant_swap" in repro.code()
    finally:
        jaxpath._INJECT_PAGEFLIP_BUG = False
    # clean run of the SAME case must pass (the defect flag is the only
    # difference)
    assert statecheck.run_ops(base, ops, "arena-ctrie", seed=0) is None
