"""Spill consumer (tools/spill_read.py): deny -> binary spill -> decode
round-trips to the reference-format event lines (round-5 verdict missing
#1 — until this tool, only a test could read SPILL_DTYPE back)."""
import os
import subprocess
import sys

import numpy as np

from infw.obs import events as ev
from infw.packets import make_batch

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
sys.path.insert(0, TOOLS)

import spill_read  # noqa: E402


def _spill_from_denies(tmp_path, batch, results):
    ring = ev.EventRing(capacity=len(results) + 10)
    n = ev.emit_deny_events(
        ring, results, np.asarray(batch.ifindex),
        np.asarray(batch.pkt_len), batch=batch,
    )
    spill = str(tmp_path / "deny-events.bin")
    logger = ev.EventsLogger(ring, lambda _l: None, spill_path=spill)
    assert logger.drain_once() == n
    return spill, n


def test_spill_round_trip_lines(tmp_path):
    """deny verdicts -> BatchDenyRecord -> binary spill -> spill_read
    must reproduce the exact line family the per-event path emits for
    the fields the spill carries (header + src addr + L4 detail)."""
    n = ev.BATCH_EMIT_THRESHOLD + 8
    srcs = ["10.1.2.3"] * (n - 3) + ["2001:db8::42", "10.9.9.9", "10.8.8.8"]
    protos = [6] * (n - 3) + [17, 1, 58]
    ports = [443] * (n - 3) + [53, 0, 0]
    batch = make_batch(
        src=srcs, proto=protos, dst_port=ports,
        ifindex=[2] * (n - 1) + [7],
        icmp_type=[0] * (n - 2) + [8, 135],
        icmp_code=[0] * (n - 2) + [0, 1],
    )
    results = np.full(n, (9 << 8) | 1, np.uint32)  # ruleId 9, DENY
    spill, seen = _spill_from_denies(tmp_path, batch, results)
    assert seen == n

    rows = np.fromfile(spill, dtype=ev.BatchDenyRecord.SPILL_DTYPE)
    lines = spill_read.decode_spill_rows(rows, {2: "eth0"})

    # header lines: one per event, reference format incl. iface name
    headers = [l for l in lines if l.startswith("ruleId")]
    assert len(headers) == n
    assert headers[0] == f"ruleId 9 action Drop len {int(batch.pkt_len[0])} if eth0"
    assert headers[-1].endswith("if ?")  # unmapped ifindex 7 -> "?"

    # address lines in both families
    assert "\tipv4 src addr 10.1.2.3" in lines
    assert "\tipv6 src addr 2001:db8::42" in lines
    # L4 detail: transport ports and both ICMP families
    assert "\ttcp dstPort 443" in lines
    assert "\tudp dstPort 53" in lines
    assert "\ticmpv4 type 8 code 0" in lines
    assert "\ticmpv6 type 135 code 1" in lines


def test_spill_round_trip_matches_per_event_header(tmp_path):
    """The header line must be BYTE-IDENTICAL to what the per-event
    (sub-threshold) path would log for the same verdicts — the spill
    consumer and decode_event_lines speak one format."""
    n = ev.BATCH_EMIT_THRESHOLD + 1
    batch = make_batch(
        src=["192.0.2.55"] * n, proto=[6] * n, dst_port=[8080] * n,
        ifindex=[3] * n,
    )
    results = np.full(n, (42 << 8) | 1, np.uint32)
    spill, _ = _spill_from_denies(tmp_path, batch, results)
    rows = np.fromfile(spill, dtype=ev.BatchDenyRecord.SPILL_DTYPE)
    got = spill_read.decode_spill_rows(rows[:1], {3: "bond0"})

    hdr = ev.EventHdr(
        if_id=3, rule_id=42, action=ev.get_action(int(results[0])),
        pkt_length=int(batch.pkt_len[0]),
    )
    ref_lines = ev.decode_event_lines(
        ev.EventRecord(hdr=hdr, packet=b""), "bond0"
    )
    assert got[0] == ref_lines[0]


def test_spill_cli_streams_and_counts(tmp_path, capsys):
    n = ev.BATCH_EMIT_THRESHOLD + 5
    batch = make_batch(
        src=["10.0.0.1"] * n, proto=[6] * n, dst_port=[80] * n,
        ifindex=[2] * n,
    )
    results = np.full(n, (1 << 8) | 1, np.uint32)
    spill, _ = _spill_from_denies(tmp_path, batch, results)

    rc = spill_read.main([spill, "--count"])
    assert rc == 0
    assert capsys.readouterr().out.strip() == str(n)

    rc = spill_read.main([spill, "--iface-names", "2=eth0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("ruleId 1 action Drop") == n
    assert "\ttcp dstPort 80" in out

    # truncated trailing row (writer mid-append) is not decoded
    row_b = ev.BatchDenyRecord.SPILL_DTYPE.itemsize
    with open(spill, "ab") as f:
        f.write(b"\x00" * (row_b // 2))
    rc = spill_read.main([spill, "--count"])
    assert capsys.readouterr().out.strip() == str(n)


def test_spill_cli_subprocess_entrypoint(tmp_path):
    """The Makefile target path: `python tools/spill_read.py FILE`."""
    n = ev.BATCH_EMIT_THRESHOLD + 2
    batch = make_batch(
        src=["10.2.2.2"] * n, proto=[17] * n, dst_port=[5353] * n,
        ifindex=[4] * n,
    )
    results = np.full(n, (3 << 8) | 1, np.uint32)
    spill, _ = _spill_from_denies(tmp_path, batch, results)
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "spill_read.py"), spill,
         "--iface-names", "4=ens1"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    assert out.stdout.count("if ens1") == n
    assert f"decoded {n} events" in out.stderr
