"""Fused Pallas deep-walk kernel (interpret mode on CPU; the same
kernel compiles via Mosaic on real TPU — exercised by bench.py):
bit-exactness vs the CPU oracle and the XLA trie walk on deep-heavy
adversarial v6 mixes, the deep-tail extraction contract, OOB/fail-closed
lanes, and the steering partition (covers everything, never
double-classifies)."""
import numpy as np
import pytest

from infw import oracle, testing
from infw.backend.tpu import TpuClassifier
from infw.constants import KIND_IPV6, XDP_PASS
from infw.kernels import jaxpath, pallas_walk


def _tables_and_batch(seed=42, n_entries=3000, n_packets=2048, width=8,
                      v6_fraction=0.3):
    rng = np.random.default_rng(seed)
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=width, group_size=6,
        v6_fraction=v6_fraction,
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    return tables, batch


def _xla_results(tables, batch):
    dt = jaxpath.device_tables(tables)
    return jaxpath.jitted_classify(True)(dt, jaxpath.device_batch(batch))


def test_walk_full_structure_matches_xla_and_oracle():
    """Mixed-depth mix: full walk tables (no extraction) must match the
    XLA trie path on EVERY packet (v4, shallow v6, deep v6, malformed)
    and the scalar oracle on a prefix."""
    tables, batch = _tables_and_batch()
    wt = pallas_walk.build_walk_tables(tables, vmem_budget=64 << 20)
    assert wt is not None
    res, xdp, stats = pallas_walk.jitted_classify_walk(True)(
        wt, jaxpath.device_batch(batch)
    )
    res2, xdp2, stats2 = _xla_results(tables, batch)
    np.testing.assert_array_equal(np.asarray(res), np.asarray(res2))
    np.testing.assert_array_equal(np.asarray(xdp), np.asarray(xdp2))
    np.testing.assert_array_equal(np.asarray(stats), np.asarray(stats2))
    ref = oracle.classify(tables, batch.slice(0, 800))
    np.testing.assert_array_equal(np.asarray(res)[:800], ref.results)
    np.testing.assert_array_equal(np.asarray(xdp)[:800], ref.xdp)


def test_walk_all_deep_class_matches_oracle():
    """All-deep adversarial mix: every packet in the batch belongs to the
    full-depth steering class, classified through EXTRACTED walk tables."""
    tables, batch = _tables_and_batch(seed=7, n_entries=5000,
                                      n_packets=4096, v6_fraction=0.6)
    classes = jaxpath.tune_depth_classes(tables)
    assert len(classes) >= 2, "table too shallow for extraction coverage"
    thr = classes[-2]
    lut = jaxpath.build_depth_lut(tables)
    idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
    deep = [g for d, g in jaxpath.depth_group_indices(
        np.asarray(tables.root_lut, np.int64), lut, classes,
        batch.ifindex, batch.ip_words, idx6,
    ) if d is None]
    assert deep and len(deep[0]) > 50, "mix generated no deep packets"
    sub = batch.take(deep[0])

    wt = pallas_walk.build_walk_tables(tables, min_depth=thr,
                                       vmem_budget=64 << 20)
    assert wt is not None
    res, xdp, _ = pallas_walk.jitted_classify_walk(True)(
        wt, jaxpath.device_batch(sub)
    )
    ref = oracle.classify(tables, sub)
    np.testing.assert_array_equal(np.asarray(res), ref.results)
    np.testing.assert_array_equal(np.asarray(xdp), ref.xdp)


def test_walk_extraction_shrinks_working_set():
    """The deep-tail extraction must actually shrink the VMEM working
    set (that is the 1M-tier fit story), not just remap it."""
    tables, _ = _tables_and_batch(seed=7, n_entries=5000, n_packets=64)
    classes = jaxpath.tune_depth_classes(tables)
    full = pallas_walk.build_walk_tables_meta(tables, vmem_budget=256 << 20)
    deep = pallas_walk.build_walk_tables_meta(
        tables, min_depth=classes[-2], vmem_budget=256 << 20
    )
    assert full is not None and deep is not None
    assert deep[1]["vmem_bytes"] < full[1]["vmem_bytes"]
    # extraction must keep a strict subset of the rule rows resident
    assert len(deep[1]["tidx_sorted"]) < len(full[1]["tidx_sorted"])


def test_walk_positions_tail_matches_oracle():
    """When the RULE_STRIDE-padded byte planes exceed the VMEM budget
    (the 1M-tier shape), the kernel falls back to the positions tail:
    level walk fused, rules via ONE XLA fat-row gather from the
    compacted joined u16 — still bit-exact."""
    rng = np.random.default_rng(2024)
    tables = testing.random_tables_fast(
        rng, n_entries=10_000, width=4, group_size=16
    )
    classes = jaxpath.tune_depth_classes(tables)
    built = pallas_walk.build_walk_tables_meta(
        tables, min_depth=classes[-2]
    )
    assert built is not None
    wt, meta = built
    assert meta["tail"] == "positions"
    assert wt.joined.shape[0] == 1  # placeholder
    assert wt.joined_u16.shape[0] > 1

    batch = testing.random_batch_fast(rng, tables, n_packets=4096)
    lut = jaxpath.build_depth_lut(tables)
    idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
    deep = [g for d, g in jaxpath.depth_group_indices(
        np.asarray(tables.root_lut, np.int64), lut, classes,
        batch.ifindex, batch.ip_words, idx6,
    ) if d is None]
    assert deep and len(deep[0]) > 50
    sub = batch.take(deep[0])
    res, xdp, stats = pallas_walk.jitted_classify_walk(True)(
        wt, jaxpath.device_batch(sub)
    )
    res2, xdp2, stats2 = _xla_results(tables, sub)
    np.testing.assert_array_equal(np.asarray(res), np.asarray(res2))
    np.testing.assert_array_equal(np.asarray(xdp), np.asarray(xdp2))
    np.testing.assert_array_equal(np.asarray(stats), np.asarray(stats2))

    # rules-only patch rewrites joined_u16 rows in place
    tidx_resident = meta["tidx_sorted"]
    assert len(tidx_resident)
    patched = pallas_walk.patch_walk_joined(
        wt, meta, tables, tidx_resident[:1]
    )
    assert patched is not None and patched is not wt
    # unchanged rules -> identical rows -> identical verdicts
    res3, _x3, _s3 = pallas_walk.jitted_classify_walk(True)(
        patched, jaxpath.device_batch(sub)
    )
    np.testing.assert_array_equal(np.asarray(res3), np.asarray(res))


@pytest.mark.slow
def test_walk_oob_and_fail_closed():
    """Invalidated lanes resolve deterministically to UNDEF -> XDP_PASS
    (the kernel's no-match semantics, kernel.c:453), never to a stale or
    wrong verdict: out-of-range ifindex, unknown ifindex, malformed
    frames, and — with extraction — shallow packets outside the deep
    class."""
    from infw.packets import make_batch

    tables, batch = _tables_and_batch(seed=3, n_entries=1500)
    wt = pallas_walk.build_walk_tables(tables, vmem_budget=64 << 20)

    b = make_batch(
        src=["2001:db8::1", "10.0.0.1", "2001:db8::2"],
        proto=[6, 17, 6],
        dst_port=[80, 53, 443],
        ifindex=[10_000_000, -3, 9999],  # OOB / negative / unknown
    )
    res, xdp, _ = pallas_walk.jitted_classify_walk(True)(
        wt, jaxpath.device_batch(b)
    )
    assert (np.asarray(res) == 0).all()
    assert (np.asarray(xdp) == XDP_PASS).all()

    # malformed packets keep the XLA path's verdicts exactly
    res_all, xdp_all, _ = pallas_walk.jitted_classify_walk(True)(
        wt, jaxpath.device_batch(batch)
    )
    res_x, xdp_x, _ = _xla_results(tables, batch)
    np.testing.assert_array_equal(np.asarray(res_all), np.asarray(res_x))
    np.testing.assert_array_equal(np.asarray(xdp_all), np.asarray(xdp_x))

    # extraction: packets OUTSIDE the deep class read the UNDEF sentinel
    classes = jaxpath.tune_depth_classes(tables)
    if len(classes) >= 2:
        wt_deep = pallas_walk.build_walk_tables(
            tables, min_depth=classes[-2], vmem_budget=64 << 20
        )
        lut = jaxpath.build_depth_lut(tables)
        idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
        shallow = [g for d, g in jaxpath.depth_group_indices(
            np.asarray(tables.root_lut, np.int64), lut, classes,
            batch.ifindex, batch.ip_words, idx6,
        ) if d is not None]
        if shallow and len(shallow[0]):
            sub = batch.take(shallow[0][:64])
            res_s, xdp_s, _ = pallas_walk.jitted_classify_walk(True)(
                wt_deep, jaxpath.device_batch(sub)
            )
            assert (np.asarray(res_s) == 0).all()
            assert (np.asarray(xdp_s) == XDP_PASS).all()


def test_walk_wire_path_matches_batch_path():
    tables, batch = _tables_and_batch(seed=9, n_entries=800,
                                      n_packets=512)
    wt = pallas_walk.build_walk_tables(tables, vmem_budget=64 << 20)
    import jax.numpy as jnp

    wire = batch.pack_wire()
    fused = np.asarray(
        pallas_walk.jitted_classify_walk_wire_fused(True)(wt, jnp.asarray(wire))
    )
    res_b, _xdp, stats_b = pallas_walk.jitted_classify_walk(True)(
        wt, jaxpath.device_batch(batch)
    )
    got16, got_stats = jaxpath.split_wire_outputs(fused, len(batch))
    np.testing.assert_array_equal(
        got16.astype(np.uint32), np.asarray(res_b).astype(np.uint32) & 0xFFFF
    )
    np.testing.assert_array_equal(got_stats, np.asarray(stats_b))


def test_steering_partition_covers_exactly_once():
    """The per-class partition must cover every v6 packet exactly once
    and never double-classify (disjoint positions, union == idx)."""
    tables, batch = _tables_and_batch(seed=5, n_entries=4000,
                                      n_packets=4096, v6_fraction=0.5)
    classes = jaxpath.tune_depth_classes(tables)
    lut = jaxpath.build_depth_lut(tables)
    idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
    groups = jaxpath.depth_group_indices(
        np.asarray(tables.root_lut, np.int64), lut, classes,
        batch.ifindex, batch.ip_words, idx6,
    )
    all_pos = np.concatenate([g for _d, g in groups]) if groups else idx6[:0]
    assert len(all_pos) == len(idx6), "partition must cover every packet"
    assert len(np.unique(all_pos)) == len(all_pos), "no double-classify"
    np.testing.assert_array_equal(np.sort(all_pos), np.sort(idx6))
    # class labels are strictly increasing with None (full depth) last
    labels = [d for d, _g in groups]
    assert labels == sorted(
        labels, key=lambda d: (d is None, -1 if d is None else d)
    )


def test_tuned_depth_classes_shape():
    tables, _ = _tables_and_batch(seed=5, n_entries=4000, n_packets=64,
                                  v6_fraction=0.5)
    classes = jaxpath.tune_depth_classes(tables)
    full = len(tables.trie_levels) - 1
    assert classes[-1] == full
    assert list(classes) == sorted(set(classes))
    assert all(t < full for t in classes[:-1])
    assert classes[0] == 0  # the cheap no-deep-levels class survives tuning
    # memoized per instance
    assert jaxpath.tune_depth_classes(tables) is classes


def test_backend_fused_dispatch_matches_xla():
    """TpuClassifier(fused_deep=True) must produce verdicts identical to
    the XLA path for every depth group of a steered v6 batch, and the
    fused walk tables must actually be installed for the trie path."""
    tables, batch = _tables_and_batch(seed=11, n_entries=2500,
                                      n_packets=2048)
    results = {}
    for fused in (False, True):
        clf = TpuClassifier(force_path="trie", fused_deep=fused)
        clf.load_tables(tables)
        assert (clf._active[5] is not None) == fused
        idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
        res = {}
        for (d, gen), g in clf.v6_depth_groups(
            batch.ifindex, batch.ip_words, idx6
        ):
            if len(g) == 0:
                continue
            wire, v4 = batch.pack_wire_subset(g)
            out = clf.classify_async_packed(wire, v4, depth=(d, gen)).result()
            res.update(zip(g.tolist(), out.results.tolist()))
        results[fused] = res
        clf.close()
    assert results[True] == results[False]


def test_backend_structural_edit_defers_walk_rebuild():
    """A structural incremental edit (CIDR delete) must NOT pay the full
    walk rebuild on the blocking load path: the load installs with the
    walk absent (XLA fallback serves the deep class) and a background
    rebuild installs fresh walk tables for the same generation."""
    import time

    from infw.compiler import IncrementalTables

    tables, _batch = _tables_and_batch(seed=21, n_entries=2000)
    it = IncrementalTables.from_content(tables.content, rule_width=8)
    clf = TpuClassifier(force_path="trie", fused_deep=True)
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    assert clf._active[5] is not None

    key = next(iter(it.content))
    it.apply({}, deletes=[key])
    clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
    it.clear_dirty()
    # the background rebuild installs for THIS generation (poll briefly)
    deadline = time.time() + 60
    while time.time() < deadline and clf._active[5] is None:
        time.sleep(0.05)
    assert clf._active[5] is not None, "background walk rebuild never landed"
    assert clf._walk_meta is not None
    clf.close()


def test_backend_walk_survives_nonintersecting_rule_patch():
    """A rules-only 1-key edit whose target is OUTSIDE the extracted
    deep tail must carry the resident walk tables forward (no rebuild);
    an edit INSIDE it must swap them out for fresh ones."""
    from infw.compiler import IncrementalTables

    tables, batch = _tables_and_batch(seed=13, n_entries=2500)
    it = IncrementalTables.from_content(tables.content, rule_width=8)
    clf = TpuClassifier(force_path="trie", fused_deep=True)
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    walk0 = clf._active[5]
    assert walk0 is not None
    tidx_resident = clf._walk_meta["tidx_sorted"]

    keys_by_t = {
        it._ident_to_t[k.masked_identity()]: k for k in it.content
    }
    resident = set(tidx_resident.tolist())
    outside = next((t for t in sorted(keys_by_t) if t not in resident), None)
    inside = next((t for t in sorted(keys_by_t) if t in resident), None)
    if outside is None:
        pytest.skip("every target resident in the deep tail on this seed")

    def flip(t):
        key = keys_by_t[t]
        rows = it.content[key].copy()
        rows[0, 6] = 1 if rows[0, 6] == 2 else 2
        it.apply({key: rows})
        clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
        it.clear_dirty()

    flip(outside)
    assert clf._last_load[0] == "patch"
    assert clf._active[5] is walk0, "non-intersecting edit must not rebuild"

    if inside is not None:
        walk1 = clf._active[5]
        flip(inside)
        assert clf._last_load[0] == "patch"
        assert clf._active[5] is not walk1, (
            "dirty deep-tail rules must refresh the resident joined planes"
        )
        # levels carry over by reference (rules-only edit, trie untouched)
        assert clf._active[5].levels[0] is walk1.levels[0]
        # and the patched walk serves fresh rule bytes: deep class verdicts
        # still match the oracle
        lut = jaxpath.build_depth_lut(clf.tables)
        classes = jaxpath.tune_depth_classes(clf.tables)
        idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
        deep = [g for d, g in jaxpath.depth_group_indices(
            np.asarray(clf.tables.root_lut, np.int64), lut, classes,
            batch.ifindex, batch.ip_words, idx6,
        ) if d is None]
        if deep and len(deep[0]):
            sub = batch.take(deep[0])
            res, _x, _s = pallas_walk.jitted_classify_walk(True)(
                clf._active[5], jaxpath.device_batch(sub)
            )
            ref = oracle.classify(clf.tables, sub)
            np.testing.assert_array_equal(np.asarray(res), ref.results)
    clf.close()


# --- ISSUE-6: compressed (skip-node) walk ----------------------------------


def _ctrie_setup(seed=3, n_entries=2500, n_packets=1024, v6_fraction=0.7,
                 width=4):
    rng = np.random.default_rng(seed)
    tables = testing.random_tables_fast(
        rng, n_entries=n_entries, width=width, group_size=6,
        v6_fraction=v6_fraction,
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=n_packets)
    return tables, batch


def test_cpoptrie_has_skip_nodes_and_shrinks_depth():
    """The clean /48-heavy distribution is chain-dominated: path
    compression must produce real skip nodes and a d_max strictly below
    the per-level walk depth."""
    rng = np.random.default_rng(11)
    tables = testing.clean_tables_scale(rng, 20_000)
    _l0, nodes, _targets, d_max = jaxpath.build_cpoptrie(tables)
    assert d_max < len(tables.trie_levels), (
        f"no level compression: d_max {d_max} vs "
        f"{len(tables.trie_levels)} levels"
    )
    assert int(nodes[:, 2].max()) > 0, "no skip nodes in a chain-heavy trie"


def test_ctrie_xla_matches_trie_and_oracle():
    """XLA compressed walk == the per-level trie classify == the CPU
    oracle on a deep v6-heavy mix (results, xdp, stats)."""
    tables, batch = _ctrie_setup()
    cdev, d_max = jaxpath.device_ctrie(tables)
    db = jaxpath.device_batch(batch)
    res, xdp, stats = jaxpath.jitted_classify_ctrie(d_max)(cdev, db)
    res2, xdp2, stats2 = _xla_results(tables, batch)
    np.testing.assert_array_equal(np.asarray(res), np.asarray(res2))
    np.testing.assert_array_equal(np.asarray(xdp), np.asarray(xdp2))
    np.testing.assert_array_equal(np.asarray(stats), np.asarray(stats2))
    ref = oracle.classify(tables, batch.slice(0, 600))
    np.testing.assert_array_equal(np.asarray(res)[:600], ref.results)


def test_cwalk_fused_matches_ctrie_everywhere():
    """The fused Pallas skip-node kernel (full coverage, min_depth=None)
    must be bit-identical to the XLA compressed walk — including
    malformed lanes, v4 cap truncation and root-level (best0) hits."""
    tables, batch = _ctrie_setup(seed=9, n_entries=1500, n_packets=384)
    built = pallas_walk.build_cwalk_tables_meta(
        tables, vmem_budget=256 << 20
    )
    assert built is not None
    wt, meta = built
    res, xdp, stats = pallas_walk.jitted_classify_cwalk(
        meta["d_max"], True
    )(wt, jaxpath.device_batch(batch))
    cdev, d_max = jaxpath.device_ctrie(tables)
    res2, xdp2, stats2 = jaxpath.jitted_classify_ctrie(d_max)(
        cdev, jaxpath.device_batch(batch)
    )
    np.testing.assert_array_equal(np.asarray(res), np.asarray(res2))
    np.testing.assert_array_equal(np.asarray(xdp), np.asarray(xdp2))
    np.testing.assert_array_equal(np.asarray(stats), np.asarray(stats2))


def test_cwalk_extraction_deep_class_matches_oracle():
    """Extracted compressed walk: every full-depth-class packet must
    classify identically to the oracle through the skip-node descent."""
    tables, batch = _ctrie_setup(seed=21, n_entries=4000, v6_fraction=0.8)
    classes = jaxpath.tune_depth_classes(tables)
    assert len(classes) >= 2
    thr = classes[-2]
    built = pallas_walk.build_cwalk_tables_meta(
        tables, min_depth=thr, vmem_budget=256 << 20
    )
    assert built is not None
    wt, meta = built
    lut = jaxpath.build_depth_lut(tables)
    idx6 = np.nonzero(np.asarray(batch.kind) == KIND_IPV6)[0]
    deep = [
        idx for d, idx in jaxpath.depth_group_indices(
            np.asarray(tables.root_lut, np.int64), lut, classes,
            batch.ifindex, batch.ip_words, idx6,
        ) if d is None
    ]
    assert deep and len(deep[0]), "no full-depth packets in the mix"
    sub = batch.take(deep[0])
    res, xdp, _stats = pallas_walk.jitted_classify_cwalk(
        meta["d_max"], True
    )(wt, jaxpath.device_batch(sub))
    ref = oracle.HashLpmOracle(tables).classify(sub)
    np.testing.assert_array_equal(np.asarray(res), ref.results)
    np.testing.assert_array_equal(np.asarray(xdp), ref.xdp)


def test_patch_cwalk_joined_matches_rebuild():
    """A rules-only edit patched into the resident cwalk joined matrix
    must equal a cold rebuild of the new tables."""
    from infw.compiler import IncrementalTables

    tables, _batch = _ctrie_setup(seed=5, n_entries=800)
    it = IncrementalTables.from_content(dict(tables.content), rule_width=4)
    snap = it.snapshot()
    it.clear_dirty()  # device baseline established (hints valid from here)
    built = pallas_walk.build_cwalk_tables_meta(snap, vmem_budget=256 << 20)
    assert built is not None
    wt, meta = built
    key = list(it.content)[17]
    rows = np.asarray(it.content[key]).copy()
    rows[1, 6] = 1 if rows[1, 6] == 2 else 2
    it.apply({key: rows})
    hint = it.peek_dirty()
    dirty = np.unique(np.asarray(hint.get("dense", ()), np.int64))
    assert len(dirty)
    snap2 = it.snapshot()
    patched = pallas_walk.patch_cwalk_joined(wt, meta, snap2, dirty)
    assert patched is not None
    rebuilt = pallas_walk.build_cwalk_tables_meta(
        snap2, vmem_budget=256 << 20
    )[0]
    np.testing.assert_array_equal(
        np.asarray(patched.joined), np.asarray(rebuilt.joined)
    )
    for name in ("l0", "root_lut", "nodes", "targets"):
        np.testing.assert_array_equal(
            np.asarray(getattr(patched, name)),
            np.asarray(getattr(rebuilt, name)),
            err_msg=name,
        )


def test_ctrie_rules_patch_seeds_host_caches():
    """A rules-only ctrie edit must carry the host caches forward: the
    structural transforms are shared by reference, the packed-rules and
    per-tidx joined caches are patched at the dirty rows — and both
    patched caches are bit-identical to a cache-stripped rebuild.
    Without the seeding every 1-key edit repacks the full rules tensor
    (seconds of host work at the 10M tier for a kilobyte scatter)."""
    from infw.analysis.statecheck import _cold_clone
    from infw.backend.tpu import TpuClassifier
    from infw.compiler import IncrementalTables

    tables, _batch = _ctrie_setup(seed=11, n_entries=600)
    it = IncrementalTables.from_content(dict(tables.content), rule_width=4)
    snap = it.snapshot()
    it.clear_dirty()  # device baseline established
    clf = TpuClassifier(force_path="ctrie", interpret=True)
    try:
        clf.load_tables(snap)
        it.clear_dirty()
        assert clf.active_path == "ctrie"
        old = clf._tables
        assert getattr(old, "_cpoptrie_cache", None) is not None
        key = list(it.content)[7]
        rows = np.asarray(it.content[key]).copy()
        rows[1, 6] = 1 if rows[1, 6] == 2 else 2
        it.apply({key: rows})
        snap2 = it.snapshot()
        clf.load_tables(snap2, dirty_hint=it.peek_dirty())
        it.clear_dirty()
        mode, _rows = clf._last_load
        assert mode == "patch", mode
        new = clf._tables
        # structural transforms shared by reference (they never read
        # rules, and the hint proved the trie untouched)
        assert getattr(new, "_cpoptrie_cache", None) is (
            getattr(old, "_cpoptrie_cache", None)
        )
        assert getattr(new, "_poptrie_cache", None) is (
            getattr(old, "_poptrie_cache", None)
        )
        # patched caches equal a clean (cache-stripped) rebuild
        jt = getattr(new, "_joined_tidx_cache", None)
        assert jt is not None and not isinstance(jt, str)
        np.testing.assert_array_equal(
            jt, jaxpath.joined_by_tidx(_cold_clone(snap2))
        )
        pk = getattr(new, "_packed_rules_cache", None)
        assert pk is not None
        np.testing.assert_array_equal(
            pk, jaxpath._packed_rules_flat(_cold_clone(snap2))
        )
    finally:
        clf.close()


def test_ctrie_skip_defect_injection_diverges():
    """The cskip defect (zeroed skip_bits) must actually flip verdicts
    on a chain-heavy table — the acceptance gate's substrate is real."""
    rng = np.random.default_rng(13)
    tables = testing.clean_tables_scale(rng, 5_000)
    batch = testing.random_batch_fast(rng, tables, n_packets=1024)
    db = jaxpath.device_batch(batch)
    cdev, d_max = jaxpath.device_ctrie(tables)
    res_ok, _x, _s = jaxpath.jitted_classify_ctrie(d_max)(cdev, db)
    jaxpath._INJECT_CSKIP_BUG = True
    try:
        cdev_bad, d_bad = jaxpath.device_ctrie(tables)
        res_bad, _x2, _s2 = jaxpath.jitted_classify_ctrie(d_bad)(
            cdev_bad, db
        )
    finally:
        jaxpath._INJECT_CSKIP_BUG = False
    assert not np.array_equal(np.asarray(res_ok), np.asarray(res_bad)), (
        "zeroing skip_bits changed nothing — the defect injection is dead"
    )


def test_backend_ctrie_fused_dispatch_matches_xla():
    """Production dispatch on the compressed path: steered packed
    classify through TpuClassifier(force_path='ctrie', fused_deep=True)
    must match the plain XLA trie classify on every packet."""
    tables, batch = _ctrie_setup(seed=29, n_entries=1000, n_packets=512,
                                 v6_fraction=0.6)
    clf = TpuClassifier(force_path="ctrie", interpret=True, fused_deep=True)
    try:
        clf.load_tables(tables)
        assert clf.active_path == "ctrie"
        assert clf._active[5] is not None, "fused cwalk did not build"
        res_ref = np.asarray(_xla_results(tables, batch)[0])
        results = np.zeros(len(batch), np.uint32)
        kinds = np.asarray(batch.kind)
        v6 = np.nonzero(kinds == KIND_IPV6)[0]
        jobs = [(None, np.nonzero(kinds != KIND_IPV6)[0])]
        jobs += [
            (d, i) for d, i in clf.v6_depth_groups(
                batch.ifindex, batch.ip_words, v6
            ) if len(i)
        ]
        for depth, idx in jobs:
            wire, v4o = batch.pack_wire_subset(np.asarray(idx, np.int64))
            out = clf.classify_async_packed(
                wire, v4o, apply_stats=False, depth=depth
            ).result()
            results[idx] = out.results
        np.testing.assert_array_equal(results, res_ref)
    finally:
        clf.close()
