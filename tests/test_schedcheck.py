"""Deterministic interleaving explorer (ISSUE-18, infw.analysis
.schedcheck): schedule-string roundtrip, deterministic replay of toy
races, shrinker 1-minimality, deadlock reporting, ring hwm counter
determinism, and the four production scenarios + the cowrace injected
defect (slow-marked per the tier-1 budget discipline).
"""
import threading

import numpy as np
import pytest

from infw._threads import sched_point
from infw.analysis import schedcheck
from infw.analysis.schedcheck import Schedule, run_scenario


# --- toy scenarios ------------------------------------------------------------


def toy_race_factory():
    """Classic lost update: unlocked read-modify-write with a yield
    point between the read and the write."""
    state = {"n": 0}

    def bump():
        v = state["n"]
        sched_point("read")
        state["n"] = v + 1

    def invariant():
        if state["n"] != 2:
            return [f"lost update: n={state['n']} != 2"]
        return []

    return {
        "threads": [("a", bump), ("b", bump)],
        "invariant": invariant,
        "objects": (),
    }


class _TwoLocks:
    def __init__(self):
        self._lock = threading.Lock()
        self._other = threading.Lock()


def toy_deadlock_factory():
    o = _TwoLocks()

    def ab():
        with o._lock:
            with o._other:
                pass

    def ba():
        with o._other:
            with o._lock:
                pass

    return {
        "threads": [("ab", ab), ("ba", ba)],
        "invariant": lambda: [],
        "objects": (o,),
    }


# --- schedule strings ---------------------------------------------------------


def test_schedule_string_roundtrip():
    for s in (Schedule(0), Schedule(1, ((3, 0),)),
              Schedule(0, ((1, 1), (7, 0)))):
        assert Schedule.from_str(s.to_str()) == s
    assert Schedule.from_str("s0@5:t1") == Schedule(0, ((5, 1),))
    for bad in ("", "t1", "s0@x:t1", "s0 @1:t1 junk"):
        with pytest.raises(ValueError):
            Schedule.from_str(bad)


# --- toy race: detection, determinism, shrinking ------------------------------


def test_serial_schedules_pass_toy_race():
    for start in (0, 1):
        r = run_scenario(toy_race_factory, Schedule(start))
        assert r.ok, r.describe()


def test_toy_race_found_and_replay_is_deterministic():
    res = schedcheck.explore("toy-race", toy_race_factory, seed=0, runs=16)
    assert not res.ok
    assert res.shrunk is not None and not res.shrunk.ok
    # a repro is only a repro if replaying its schedule string is
    # bit-identical: same trace, same failure
    sch = Schedule.from_str(res.shrunk.schedule.to_str())
    r1 = run_scenario(toy_race_factory, sch)
    r2 = run_scenario(toy_race_factory, sch)
    assert r1.trace == r2.trace == res.shrunk.trace
    assert r1.invariant_errors == r2.invariant_errors
    assert not r1.ok


def test_shrunk_schedule_is_one_minimal():
    res = schedcheck.explore("toy-race", toy_race_factory, seed=3, runs=16,
                             bound=4)
    assert not res.ok
    shrunk = res.shrunk.schedule
    # dropping ANY surviving preemption must lose the repro
    for i in range(len(shrunk.preemptions)):
        cand = Schedule(shrunk.start,
                        shrunk.preemptions[:i] + shrunk.preemptions[i + 1:])
        assert run_scenario(toy_race_factory, cand).ok, (
            f"preemption {i} of {shrunk.to_str()} is not load-bearing")


def test_toy_deadlock_reported_with_held_and_wanted():
    res = schedcheck.explore("toy-deadlock", toy_deadlock_factory,
                             seed=0, runs=16)
    assert not res.ok
    dl = res.shrunk.deadlock
    assert dl, res.shrunk.describe()
    blob = "; ".join(dl)
    assert "waiting on" in blob and "holding" in blob
    assert "_TwoLocks._lock" in blob and "_TwoLocks._other" in blob


# --- ring hwm counters under forced preemption --------------------------------


def test_ring_depth_hwm_deterministic_under_preemption(tmp_path):
    """The split prod/cons high-water marks (single-writer discipline)
    must report the true max depth under every single-preemption
    interleaving of two pushes against a drain — the schedule is forced
    exactly at the ring-hwm-prod / ring-hwm-cons RMW points."""
    from infw.ring import IngestRing

    def factory():
        ring = IngestRing.create(str(tmp_path / "hwm.ring"), slots=4,
                                 slot_packets=8)
        chunks = []

        def producer():
            for _ in range(2):
                ring.push(np.zeros((2, 7), np.uint32))

        def consumer():
            while (c := ring.pop(timeout=0.0)) is not None:
                chunks.append(c)  # no release: depth stays monotonic

        def invariant():
            errs = []
            cv = ring.counter_values()
            # both pushes always complete and nothing is released, so
            # the depth reaches 2 exactly once on every interleaving
            if cv["ring_depth_hwm"] != 2:
                errs.append(f"ring_depth_hwm {cv['ring_depth_hwm']} != 2")
            if cv["ring_pushed_total"] != 2:
                errs.append("pushes lost")
            for c in chunks:
                c.release()
            ring.close()
            return errs

        return {"threads": [("prod", producer), ("cons", consumer)],
                "invariant": invariant, "objects": ()}

    serial = run_scenario(factory, Schedule(0))
    assert serial.ok, serial.describe()
    horizon = len(serial.trace)
    assert horizon >= 2  # both hwm sched_points were exercised
    for i in range(horizon):
        for t in (0, 1):
            r = run_scenario(factory, Schedule(0, ((i, t),)))
            assert r.ok, r.describe()


# --- production scenarios -----------------------------------------------------


def test_drain_vs_patch_serial_leg():
    # the cheap tier-1 leg: one serial run of the lightest production
    # scenario (no arena/JAX compilation in its body)
    r = run_scenario(schedcheck.SCENARIOS["drain-vs-patch"], Schedule(0))
    assert r.ok, r.describe()


@pytest.mark.slow
@pytest.mark.parametrize("name", schedcheck.DEFAULT_SCENARIOS)
def test_production_scenario_green(name):
    res = schedcheck.explore(name, schedcheck.SCENARIOS[name],
                             seed=0, runs=24, bound=2)
    assert res.ok, res.shrunk.describe() if res.shrunk else "no repro"
    assert res.runs >= 2  # at least the serial schedules ran
    assert res.horizon > 0


@pytest.mark.slow
def test_cowrace_injection_caught_and_shrunk():
    from infw.kernels import jaxpath

    assert not jaxpath._inject_cowrace_bug()
    jaxpath._INJECT_COWRACE_BUG = True
    try:
        res = schedcheck.explore(
            "cow-vs-destroy", schedcheck.SCENARIOS["cow-vs-destroy"],
            seed=0, runs=120, bound=2,
        )
    finally:
        jaxpath._INJECT_COWRACE_BUG = False
    assert not res.ok
    assert res.shrunk is not None
    assert res.shrunk.segments <= 6, res.shrunk.describe()
    assert any("cowleak" in e for e in res.shrunk.invariant_errors), (
        res.shrunk.describe())
    # and the defect is OFF again: the same exploration budget is green
    res2 = schedcheck.explore(
        "cow-vs-destroy", schedcheck.SCENARIOS["cow-vs-destroy"],
        seed=0, runs=30, bound=2,
    )
    assert res2.ok, res2.shrunk.describe() if res2.shrunk else ""
