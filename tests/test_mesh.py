"""Multi-chip sharding: classification on a ("data","rules") mesh must be
bit-exact vs the oracle (runs on the virtual 8-device CPU mesh)."""
import jax
import numpy as np
import pytest

from infw import oracle, testing
from infw.parallel import mesh as meshmod


def test_make_mesh_validation_unified():
    """make_mesh used to silently truncate to the first n devices (and
    reshape-crash when asked for more than exist); make_global_mesh
    duplicated the divisibility check with a different message.  Both
    now share validate_mesh_axes: raise on oversubscription, on
    rules_shards > n_devices, and on non-divisible axes — with one
    wording."""
    from infw.parallel import multihost

    n = len(jax.devices())
    with pytest.raises(ValueError, match="visible"):
        meshmod.make_mesh(n * 2)
    with pytest.raises(ValueError, match="cannot be wider"):
        meshmod.make_mesh(4, rules_shards=8)
    with pytest.raises(ValueError, match="not divisible"):
        meshmod.make_mesh(6, rules_shards=4)
    with pytest.raises(ValueError, match="must be positive"):
        meshmod.make_mesh(4, rules_shards=0)
    # make_global_mesh: same rule set applied to the local device count
    with pytest.raises(ValueError, match="cannot be wider"):
        multihost.make_global_mesh(rules_shards=n * 2)
    with pytest.raises(ValueError, match="not divisible"):
        multihost.make_global_mesh(rules_shards=3)
    m = meshmod.make_mesh(8, rules_shards=2)
    assert dict(m.shape) == {"data": 4, "rules": 2}


@pytest.mark.parametrize("rules_shards", [1, 2, 4])
def test_sharded_classify_matches_oracle(rules_shards):
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    m = meshmod.make_mesh(8, rules_shards=rules_shards)
    rng = np.random.default_rng(11)
    tables = testing.random_tables(rng, n_entries=37, width=10)
    batch = testing.random_batch(rng, tables, n_packets=301)
    ref = oracle.classify(tables, batch)
    results, xdp, stats = meshmod.classify_on_mesh(m, tables, batch)
    np.testing.assert_array_equal(results, ref.results)
    np.testing.assert_array_equal(xdp, ref.xdp)
    from infw.kernels import jaxpath
    got = testing.stats_dict_from_array(jaxpath.merge_stats_host(stats))
    assert got == ref.stats


def test_mesh_trie_sharded_matches_oracle():
    """Trie-sharded rules axis (the 1M-rule-scale path): entries
    partitioned across shards, winner by pmax over mask_len scores."""
    from infw.kernels import jaxpath

    rng = np.random.default_rng(31)
    tables = testing.random_tables(rng, n_entries=120, width=8, overlap_fraction=0.5)
    batch = testing.random_batch(rng, tables, n_packets=512)
    m = meshmod.make_mesh(8, rules_shards=4)
    results, xdp, stats = meshmod.classify_on_mesh_trie(m, tables, batch)
    ref = oracle.classify(tables, batch)
    np.testing.assert_array_equal(results, ref.results)
    np.testing.assert_array_equal(xdp, ref.xdp)
    got = testing.stats_dict_from_array(jaxpath.merge_stats_host(stats))
    assert got == ref.stats


def test_mesh_trie_sharded_10k_adversarial():
    """Scale tier (VERDICT r2 #7): a 10K-entry nested/overlapping table
    sharded over the rules axis, where per-shard trie depth padding and
    the pmax winner combine are actually stressed (shards compile
    different node counts but identical static depth), bit-exact vs the
    native C++ reference classifier."""
    from infw.backend.cpu_ref import CpuRefClassifier
    from infw.kernels import jaxpath

    rng = np.random.default_rng(41)
    tables = testing.random_tables_fast(
        rng, n_entries=10_000, width=8, group_size=6
    )
    assert tables.levels >= 7  # deep v6 prefixes present
    batch = testing.random_batch_fast(rng, tables, n_packets=4096)

    ref = CpuRefClassifier()
    ref.load_tables(tables)
    want = ref.classify(batch)

    m = meshmod.make_mesh(8, rules_shards=4)
    placed = meshmod.shard_tables_trie(tables, m)
    # per-shard tries genuinely differ in size but share static depth
    assert placed.trie_levels[0].shape[0] == 4
    results, xdp, stats = meshmod.classify_on_mesh_trie(
        m, tables, batch, placed=placed
    )
    np.testing.assert_array_equal(results, want.results)
    np.testing.assert_array_equal(xdp, want.xdp)
    got = jaxpath.merge_stats_host(stats)
    np.testing.assert_array_equal(got, want.stats_delta)

    # second batch against the placed handle (stream-of-batches usage)
    batch2 = testing.random_batch_fast(rng, tables, n_packets=1024)
    want2 = ref.classify(batch2)
    results2, _, _ = meshmod.classify_on_mesh_trie(
        m, tables, batch2, placed=placed
    )
    np.testing.assert_array_equal(results2, want2.results)
