"""BASELINE config 5: large adversarial overlap set, differential.

The JAX variable-stride trie path is checked verdict-for-verdict against
the native C++ reference classifier on a deliberately nested/overlapping
CIDR table far above the dense limit.  The full-size (150K-entry) run is
gated behind INFW_BIG_TESTS=1 (several GB of host RAM and ~1 min); a
scaled-down version always runs in CI.
"""
import os

import numpy as np
import pytest

from infw import testing
from infw.backend.cpu_ref import CpuRefClassifier
from infw.kernels import jaxpath


def _differential(n_entries: int, n_packets: int, seed: int = 23):
    rng = np.random.default_rng(seed)
    tables = testing.random_tables(
        rng, n_entries=n_entries, width=8, overlap_fraction=0.6
    )
    batch = testing.random_batch(rng, tables, n_packets=n_packets)

    ref = CpuRefClassifier()
    ref.load_tables(tables)
    want = ref.classify(batch)

    dt = jaxpath.device_tables(tables)
    db = jaxpath.device_batch(batch)
    res, xdp, stats = jaxpath.jitted_classify(True)(dt, db)
    np.testing.assert_array_equal(np.asarray(res), want.results)
    np.testing.assert_array_equal(np.asarray(xdp), want.xdp)
    got_stats = jaxpath.merge_stats_host(np.asarray(stats))
    np.testing.assert_array_equal(got_stats, want.stats_delta)
    return tables


def test_adversarial_overlap_10k():
    """Always-on scaled version: 10K nested CIDRs, trie vs native C++."""
    tables = _differential(n_entries=10_000, n_packets=4096)
    assert tables.levels >= 7  # deep prefixes present


@pytest.mark.skipif(
    os.environ.get("INFW_BIG_TESTS") != "1",
    reason="set INFW_BIG_TESTS=1 for the 150K-entry adversarial run",
)
def test_adversarial_overlap_150k():
    _differential(n_entries=150_000, n_packets=8192)
