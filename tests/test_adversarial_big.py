"""BASELINE config 5: large adversarial overlap set, differential.

The JAX variable-stride trie path is checked verdict-for-verdict against
the native C++ reference classifier on a deliberately nested/overlapping
CIDR table far above the dense limit.  The full-size (150K-entry) run is
gated behind INFW_BIG_TESTS=1 (several GB of host RAM and ~1 min); a
scaled-down version always runs in CI.
"""
import os

import numpy as np
import pytest

from infw import testing
from infw.backend.cpu_ref import CpuRefClassifier
from infw.kernels import jaxpath


def _differential(n_entries: int, n_packets: int, seed: int = 23):
    rng = np.random.default_rng(seed)
    tables = testing.random_tables(
        rng, n_entries=n_entries, width=8, overlap_fraction=0.6
    )
    batch = testing.random_batch(rng, tables, n_packets=n_packets)

    ref = CpuRefClassifier()
    ref.load_tables(tables)
    want = ref.classify(batch)

    dt = jaxpath.device_tables(tables)
    db = jaxpath.device_batch(batch)
    res, xdp, stats = jaxpath.jitted_classify(True)(dt, db)
    np.testing.assert_array_equal(np.asarray(res), want.results)
    np.testing.assert_array_equal(np.asarray(xdp), want.xdp)
    got_stats = jaxpath.merge_stats_host(np.asarray(stats))
    np.testing.assert_array_equal(got_stats, want.stats_delta)
    return tables


def test_adversarial_overlap_10k():
    """Always-on scaled version: 10K nested CIDRs, trie vs native C++."""
    tables = _differential(n_entries=10_000, n_packets=4096)
    assert tables.levels >= 7  # deep prefixes present


@pytest.mark.skipif(
    os.environ.get("INFW_BIG_TESTS") != "1",
    reason="set INFW_BIG_TESTS=1 for the 150K-entry adversarial run",
)
def test_adversarial_overlap_150k():
    _differential(n_entries=150_000, n_packets=8192)


@pytest.mark.skipif(
    os.environ.get("INFW_BIG_TESTS") != "1", reason="INFW_BIG_TESTS=1 to enable"
)
def test_seed_sweep_differential():
    """Multi-seed robustness sweep: every backend path (oracle, native
    C++, the dense and trie device paths, and the packed wire path) must
    agree verdict-for-verdict across many random table/batch draws — the
    fixed-seed differential tests cannot catch seed-dependent edge cases
    (mask-length boundaries, slot ties, family mixes) that this does."""
    from infw import oracle
    from infw.backend.tpu import TpuClassifier

    for seed in range(40, 56):
        rng = np.random.default_rng(seed)
        tables = testing.random_tables(
            rng,
            n_entries=int(rng.integers(5, 400)),
            width=int(rng.integers(2, 16)),
            overlap_fraction=float(rng.random() * 0.8),
        )
        batch = testing.random_batch(rng, tables, n_packets=512)
        want = oracle.classify(tables, batch)

        ref = CpuRefClassifier()
        ref.load_tables(tables)
        got = ref.classify(batch)
        np.testing.assert_array_equal(got.results, want.results, err_msg=f"cpp seed {seed}")

        for path in ("dense", "trie"):
            clf = TpuClassifier(force_path=path)
            clf.load_tables(tables)
            out = clf.classify(batch, apply_stats=False)
            np.testing.assert_array_equal(
                out.results, want.results, err_msg=f"{path} seed {seed}"
            )
            np.testing.assert_array_equal(
                out.xdp, want.xdp, err_msg=f"{path} seed {seed}"
            )
            if clf.supports_packed():
                idx = np.arange(len(batch), dtype=np.int64)
                wire, v4_only = batch.pack_wire_subset(idx)
                pk = clf.classify_async_packed(
                    wire, v4_only, apply_stats=False
                ).result()
                np.testing.assert_array_equal(
                    pk.results, want.results, err_msg=f"{path}-packed seed {seed}"
                )
                # xdp too: the packed path rebuilds it host-side from the
                # kind recovered out of wire w0
                np.testing.assert_array_equal(
                    pk.xdp, want.xdp, err_msg=f"{path}-packed seed {seed}"
                )
            clf.close()
