"""Rule-table semantic analyzer tests (infw.analysis.rules).

The property core: every finding's witness 5-tuple must replay bit-exact
against the NATIVE CPU reference classifier (backend/cpu_ref) — the
analyzer's claims are statements about what the dataplane does, so they
are checked against the dataplane, not against the analyzer's own model.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from infw import failsaferules, testing
from infw.analysis import rules as ar
from infw.backend.cpu_ref import CpuRefClassifier
from infw.compiler import LpmKey, compile_tables_from_content
from infw.constants import ALLOW, DENY, IPPROTO_TCP, IPPROTO_UDP
from infw.spec import (
    ACTION_ALLOW,
    ACTION_DENY,
    IngressNodeFirewall,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallRules,
    IngressNodeProtocolConfig,
    PROTOCOL_TYPE_TCP,
    PROTOCOL_TYPE_UNSET,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def row(order, proto, ps, pe, it, ic, act):
    r = np.zeros(7, np.int32)
    r[:] = [order, proto, ps, pe, it, ic, act]
    return r


def rows(width, *rs):
    m = np.zeros((width, 7), np.int32)
    for r in rs:
        m[r[0]] = r
    return m


def v4(a, b, c, d):
    return bytes([a, b, c, d]) + bytes(12)


def key(data, mask, ifx=2):
    return LpmKey(mask + 32, ifx, data)


def cpu_ref_for(content):
    clf = CpuRefClassifier()
    clf.load_tables(compile_tables_from_content(dict(content)))
    return clf


# --- the acceptance gate ----------------------------------------------------


ACCEPTANCE = {
    key(v4(10, 0, 0, 0), 8): rows(4, row(1, IPPROTO_TCP, 443, 0, 0, 0, ALLOW)),
    key(v4(10, 1, 0, 0), 16): rows(4, row(1, IPPROTO_TCP, 443, 0, 0, 0, DENY)),
    key(v4(192, 168, 0, 0), 16): rows(
        4,
        row(1, IPPROTO_TCP, 1000, 2000, 0, 0, ALLOW),
        row(2, IPPROTO_TCP, 1500, 0, 0, 0, DENY),
    ),
}


def test_acceptance_exactly_two_findings():
    findings = ar.analyze_content(ACCEPTANCE)
    got = {(f.check, f.entry) for f in findings}
    assert got == {
        ("shadowed-rule", "if2 192.168.0.0/16"),
        ("allow-deny-conflict", "if2 10.1.0.0/16"),
    }
    # both witnesses confirmed by the oracle AND the native reference
    for clf in (None, cpu_ref_for(ACCEPTANCE)):
        replays = ar.replay_witnesses(ACCEPTANCE, findings, classifier=clf)
        assert len(replays) == 2
        assert all(ok for _, ok, _ in replays), replays


def test_acceptance_cli_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "infw_lint.py"),
         "rules", "--acceptance", "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["ok"] is True
    assert len(doc["findings"]) == 2
    assert all(c["confirmed"] for c in doc["confirmed"])


# --- witness property: analyzer claims == dataplane behavior ----------------


@pytest.mark.parametrize("seed", [3, 17, 29])
def test_witnesses_replay_on_native_reference(seed):
    """Every witness produced on an adversarial random table classifies
    to exactly the predicted packed result on the native C++ reference
    (in particular: every shadowed rule's witness yields the SHADOWING
    rule's verdict, never the shadowed rule's)."""
    rng = np.random.default_rng(seed)
    tables = testing.random_tables_fast(rng, n_entries=200, width=8)
    findings = ar.analyze_content(tables.content)
    with_w = [f for f in findings if f.witness is not None]
    assert with_w, "adversarial tables should produce witnessed findings"
    replays = ar.replay_witnesses(
        tables.content, findings, classifier=cpu_ref_for(tables.content)
    )
    bad = [(f.check, f.entry, got, f.witness.expect_result)
           for f, ok, got in replays if not ok]
    assert not bad, bad
    # shadowed-rule witnesses specifically must NOT hit the shadowed rule
    for f in with_w:
        if f.check == "shadowed-rule":
            assert f.witness.expect_rule_id != f.orders[1]


def test_clean_adversarial_table_reports_zero_findings():
    rng = np.random.default_rng(5)
    tables = testing.clean_tables_fast(rng, n_entries=50_000, width=4)
    assert tables.num_entries == 50_000
    findings = ar.analyze_content(tables.content)
    assert findings == []


@pytest.mark.slow
def test_clean_adversarial_table_1m_zero_findings():
    rng = np.random.default_rng(5)
    tables = testing.clean_tables_fast(rng, n_entries=1_000_000, width=4)
    findings = ar.analyze_content(tables.content)
    assert findings == []


# --- individual checks ------------------------------------------------------


def test_lpm_dead_cidr_with_conflicting_verdicts():
    content = {
        key(v4(10, 0, 0, 0), 24): rows(4, row(1, IPPROTO_TCP, 80, 0, 0, 0, ALLOW)),
        key(v4(10, 0, 0, 0), 25): rows(4, row(1, IPPROTO_TCP, 80, 0, 0, 0, DENY)),
        key(v4(10, 0, 0, 128), 25): rows(4, row(1, IPPROTO_TCP, 80, 0, 0, 0, DENY)),
    }
    findings = ar.analyze_content(content)
    dead = [f for f in findings if f.check == "lpm-dead-cidr"]
    assert len(dead) == 1
    assert dead[0].entry == "if2 10.0.0.0/24"
    assert dead[0].severity == "warning"  # covering verdicts differ
    # the witness proves traffic lands on the /25's verdict
    (f, ok, got), = ar.replay_witnesses(content, dead)
    assert ok and (got & 0xFF) == DENY


def test_lpm_dead_requires_full_cover():
    content = {
        key(v4(10, 0, 0, 0), 24): rows(4, row(1, IPPROTO_TCP, 80, 0, 0, 0, ALLOW)),
        key(v4(10, 0, 0, 0), 25): rows(4, row(1, IPPROTO_TCP, 80, 0, 0, 0, DENY)),
    }
    assert not [f for f in ar.analyze_content(content)
                if f.check == "lpm-dead-cidr"]


def test_catchall_deny_is_failsafe_violation():
    content = {key(bytes(16), 0): rows(4, row(1, 0, 0, 0, 0, 0, DENY))}
    findings = ar.analyze_content(content)
    fs = [f for f in findings if f.check == "failsafe-violation"]
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "6443" in fs[0].message
    (f, ok, got), = ar.replay_witnesses(content, fs)
    assert ok and (got & 0xFF) == DENY


def test_allow_before_catchall_deny_is_failsafe_proof():
    """The recommended failsafe posture: explicit Allows over every
    failsafe port, then deny-all — the analyzer must prove it safe."""
    specs = [row(90, 0, 0, 0, 0, 0, DENY)]
    order = 1
    for fs in failsaferules.get_tcp():
        specs.insert(0, row(order, IPPROTO_TCP, fs.port, 0, 0, 0, ALLOW))
        order += 1
    for fs in failsaferules.get_udp():
        specs.insert(0, row(order, IPPROTO_UDP, fs.port, 0, 0, 0, ALLOW))
        order += 1
    content = {key(bytes(16), 0): rows(91, *specs)}
    findings = ar.analyze_content(content)
    assert not [f for f in findings if f.check == "failsafe-violation"]
    # and the failsafe Allow set itself is shadow-free (regression pin
    # for the shipped failsaferules list: no duplicate/covered ports)
    assert not [f for f in findings
                if f.check in ("shadowed-rule", "redundant-rule")]


def test_shipped_failsafe_list_is_duplicate_free():
    tcp = [fs.port for fs in failsaferules.get_tcp()]
    udp = [fs.port for fs in failsaferules.get_udp()]
    assert len(set(tcp)) == len(tcp)
    assert len(set(udp)) == len(udp)


def test_range_asymmetry_warning():
    content = {
        key(v4(10, 9, 0, 0), 16): rows(
            4, row(1, IPPROTO_TCP, 5000, 6443, 0, 0, DENY)
        ),
    }
    findings = ar.analyze_content(content)
    asym = [f for f in findings if f.check == "range-asymmetry"]
    assert len(asym) == 1
    # the witness shows port 6443 is NOT denied by this rule (half-open)
    (f, ok, got), = ar.replay_witnesses(content, asym)
    assert ok
    assert (got & 0xFF) != DENY
    # and no failsafe violation: 6443 is outside the half-open range
    assert not [f for f in findings if f.check == "failsafe-violation"]


def test_redundant_vs_shadowed_severity():
    content = {
        key(v4(10, 8, 0, 0), 16): rows(
            8,
            row(1, IPPROTO_TCP, 100, 200, 0, 0, DENY),
            row(2, IPPROTO_TCP, 150, 0, 0, 0, DENY),    # redundant
            row(3, IPPROTO_TCP, 120, 0, 0, 0, ALLOW),   # shadowed
        ),
    }
    by_check = {}
    for f in ar.analyze_content(content):
        by_check.setdefault(f.check, []).append(f)
    assert [f.orders for f in by_check["redundant-rule"]] == [(1, 2)]
    assert by_check["redundant-rule"][0].severity == "info"
    assert [f.orders for f in by_check["shadowed-rule"]] == [(1, 3)]
    assert by_check["shadowed-rule"][0].severity == "error"


def test_unmatchable_rule_info():
    content = {
        key(v4(10, 7, 0, 0), 16): rows(
            4,
            row(1, IPPROTO_TCP, 500, 500, 0, 0, DENY),  # empty half-open range
            row(2, 47, 0, 0, 0, 0, DENY),               # GRE: scan never matches
        ),
    }
    checks = [f.check for f in ar.analyze_content(content)]
    assert checks.count("unmatchable-rule") == 2


# --- spec-level wrapper -----------------------------------------------------


def tcp_rule(order, ports, action):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol=PROTOCOL_TYPE_TCP,
            tcp=IngressNodeFirewallProtoRule(ports=ports),
        ),
        action=action,
    )


def make_inf(name, cidr_rules, interfaces=("eth0",), selector=None):
    return IngressNodeFirewall.from_dict({
        "metadata": {"name": name},
        "spec": {
            "nodeSelector": {"matchLabels": selector or {"fw": "on"}},
            "interfaces": list(interfaces),
            "ingress": [
                {"sourceCIDRs": [cidr],
                 "rules": [r.to_dict() for r in rules]}
                for cidr, rules in cidr_rules
            ],
        },
    })


def test_cross_object_conflict_attribution():
    inf_a = make_inf("allow-web", [("10.0.0.0/8", [tcp_rule(1, 443, ACTION_ALLOW)])])
    inf_b = make_inf("deny-sub", [("10.1.0.0/16", [tcp_rule(2, 443, ACTION_DENY)])])
    findings = ar.analyze_infs([inf_a, inf_b])
    conf = [f for f in findings if f.check == "cross-object-conflict"]
    assert len(conf) == 1
    assert set(conf[0].objects) == {"allow-web", "deny-sub"}
    assert conf[0].witness is not None


def test_same_object_conflict_keeps_plain_check_id():
    inf = make_inf("one", [
        ("10.0.0.0/8", [tcp_rule(1, 443, ACTION_ALLOW)]),
        ("10.1.0.0/16", [tcp_rule(2, 443, ACTION_DENY)]),
    ])
    findings = ar.analyze_infs([inf])
    assert [f.check for f in findings] == ["allow-deny-conflict"]


def test_duplicate_order_across_objects():
    inf_a = make_inf("a", [("10.0.0.0/8", [tcp_rule(1, 80, ACTION_ALLOW)])])
    inf_b = make_inf("b", [("10.0.0.0/8", [tcp_rule(1, 81, ACTION_DENY)])])
    findings = ar.analyze_infs([inf_a, inf_b])
    dup = [f for f in findings if f.check == "duplicate-order"]
    assert len(dup) == 1 and set(dup[0].objects) == {"a", "b"}


def test_aliasing_cidrs_flagged():
    inf = make_inf("alias", [
        ("10.0.0.1/8", [tcp_rule(1, 80, ACTION_ALLOW)]),
        ("10.0.0.2/8", [tcp_rule(2, 81, ACTION_DENY)]),
    ])
    findings = ar.analyze_infs([inf])
    assert [f.check for f in findings if f.check == "aliasing-cidrs"]


def test_shipped_denyall_example_is_flagged():
    with open(os.path.join(REPO, "examples",
                           "ingressnodefirewall-denyall.json")) as f:
        inf = IngressNodeFirewall.from_dict(json.load(f))
    findings = ar.analyze_infs([inf])
    fs = [f for f in findings if f.check == "failsafe-violation"]
    assert len(fs) == 1
    assert fs[0].objects == ("ingressnodefirewall-denyall",)


# --- syncer pre-sync gate ---------------------------------------------------


def _catchall_deny_rules():
    return [IngressNodeFirewallRules(
        source_cidrs=["0.0.0.0/0"],
        rules=[IngressNodeFirewallProtocolRule(
            order=1,
            protocol_config=IngressNodeProtocolConfig(
                protocol=PROTOCOL_TYPE_UNSET
            ),
            action=ACTION_DENY,
        )],
    )]


@pytest.fixture
def gate_registry():
    from infw.interfaces import Interface, InterfaceRegistry

    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    return reg


def test_syncer_gate_events_mode(gate_registry):
    from infw.obs.events import AnalysisEventRecord, EventRing
    from infw.syncer import DataplaneSyncer

    ring = EventRing(capacity=64)
    s = DataplaneSyncer(
        classifier_factory=CpuRefClassifier,
        registry=gate_registry,
        analysis_mode="events",
        analysis_ring=ring,
    )
    s.sync_interface_ingress_rules({"dummy0": _catchall_deny_rules()}, False)
    # sync succeeded (events mode never blocks) and findings were emitted
    assert s.classifier is not None
    assert any(f.check == "failsafe-violation"
               for f in s.last_analysis_findings)
    recs = ring.pop_all()
    assert any(isinstance(r, AnalysisEventRecord)
               and r.check == "failsafe-violation" for r in recs)
    assert all(r.lines() for r in recs if isinstance(r, AnalysisEventRecord))


def test_syncer_gate_block_mode(gate_registry):
    from infw.syncer import DataplaneSyncer, SyncError

    s = DataplaneSyncer(
        classifier_factory=CpuRefClassifier,
        registry=gate_registry,
        analysis_mode="block",
    )
    with pytest.raises(SyncError, match="failsafe-violation"):
        s.sync_interface_ingress_rules(
            {"dummy0": _catchall_deny_rules()}, False
        )
    # the gate fired BEFORE any interface mutation
    assert s.attached_interfaces() == set()
    # a clean ruleset syncs fine in block mode
    s.sync_interface_ingress_rules({"dummy0": [IngressNodeFirewallRules(
        source_cidrs=["192.0.2.0/24"],
        rules=[tcp_rule(1, 80, ACTION_DENY)],
    )]}, False)
    assert s.attached_interfaces() == {"dummy0"}


def test_syncer_gate_off_by_default(gate_registry):
    from infw.syncer import DataplaneSyncer

    s = DataplaneSyncer(
        classifier_factory=CpuRefClassifier, registry=gate_registry
    )
    s.sync_interface_ingress_rules({"dummy0": _catchall_deny_rules()}, False)
    assert s.last_analysis_findings == []


def test_events_logger_drains_analysis_records():
    from infw.obs.events import EventRing, EventsLogger, emit_analysis_findings

    ring = EventRing(capacity=8)
    n = emit_analysis_findings(ring, ar.analyze_content(ACCEPTANCE))
    assert n == 2
    lines = []
    logger = EventsLogger(ring, lines.append)
    assert logger.drain_once() == 2
    assert any("shadowed-rule" in line for line in lines)
