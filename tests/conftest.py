"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
compile and execute in CI without TPU hardware (the driver separately
dry-runs the multichip path via __graft_entry__.dryrun_multichip).

INFW_TPU_E2E=1 keeps the REAL device platform instead — used to run the
e2e reachability tables (and any other gated tests) against the actual
TPU dataplane, the analogue of pointing the reference's functional suite
at a live cluster instead of envtest.
"""
import os

_KEEP_DEVICE = os.environ.get("INFW_TPU_E2E") == "1"

# Force, don't setdefault: the environment presets JAX_PLATFORMS=axon (the
# real TPU tunnel) and tests must run on the virtual CPU mesh.  jax is
# already imported at interpreter start (sitecustomize), so the env var
# alone is too late — update the config as well.
if not _KEEP_DEVICE:
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

if not _KEEP_DEVICE:
    jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
