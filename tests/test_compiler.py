"""T1: rule compiler semantics (reference loader.go:429-547 encoding)."""
import numpy as np
import pytest

from infw import compiler
from infw.constants import ALLOW, DENY, IPPROTO_ICMP, IPPROTO_ICMPV6, IPPROTO_TCP, IPPROTO_UDP
from infw.interfaces import Interface, InterfaceRegistry
from infw.spec import (
    IngressNodeFirewallICMPRule,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallRules,
    IngressNodeProtocolConfig,
)


def proto_rule(order, protocol, action="Allow", **kw):
    pc = IngressNodeProtocolConfig(protocol=protocol)
    if protocol in ("TCP", "UDP", "SCTP"):
        pr = IngressNodeFirewallProtoRule(ports=kw.get("ports", 80))
        setattr(pc, protocol.lower(), pr)
    elif protocol == "ICMP":
        pc.icmp = IngressNodeFirewallICMPRule(
            icmp_type=kw.get("t", 8), icmp_code=kw.get("c", 0)
        )
    elif protocol == "ICMPv6":
        pc.icmpv6 = IngressNodeFirewallICMPRule(
            icmp_type=kw.get("t", 128), icmp_code=kw.get("c", 0)
        )
    return IngressNodeFirewallProtocolRule(order=order, protocol_config=pc, action=action)


def test_rule_row_index_is_order_and_ruleid_is_order():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(5, "TCP", ports=8080, action="Deny")]
    )
    rows = compiler.encode_rules(ing)
    assert rows[5, compiler.COL_RULE_ID] == 5
    assert rows[5, compiler.COL_PROTOCOL] == IPPROTO_TCP
    assert rows[5, compiler.COL_PORT_START] == 8080
    assert rows[5, compiler.COL_PORT_END] == 0  # single port -> end==0
    assert rows[5, compiler.COL_ACTION] == DENY
    # all other slots empty (ruleId 0 == INVALID_RULE_ID)
    assert rows[[0, 1, 4, 6], compiler.COL_RULE_ID].sum() == 0


def test_range_encoding():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "UDP", ports="100-200")]
    )
    rows = compiler.encode_rules(ing)
    assert rows[1, compiler.COL_PROTOCOL] == IPPROTO_UDP
    assert rows[1, compiler.COL_PORT_START] == 100
    assert rows[1, compiler.COL_PORT_END] == 200
    assert rows[1, compiler.COL_ACTION] == ALLOW


def test_icmp_encoding():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"],
        rules=[proto_rule(2, "ICMP", t=8, c=0), proto_rule(3, "ICMPv6", t=128, c=0)],
    )
    rows = compiler.encode_rules(ing)
    assert rows[2, compiler.COL_PROTOCOL] == IPPROTO_ICMP
    assert rows[2, compiler.COL_ICMP_TYPE] == 8
    assert rows[3, compiler.COL_PROTOCOL] == IPPROTO_ICMPV6
    assert rows[3, compiler.COL_ICMP_TYPE] == 128


def test_unset_protocol_is_catch_all():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"],
        rules=[
            IngressNodeFirewallProtocolRule(
                order=1, protocol_config=IngressNodeProtocolConfig(protocol=""), action="Deny"
            )
        ],
    )
    rows = compiler.encode_rules(ing)
    assert rows[1, compiler.COL_PROTOCOL] == 0
    assert rows[1, compiler.COL_ACTION] == DENY


def test_order_out_of_range_is_error():
    # order >= width would be an array-OOB panic in the reference loader.
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(100, "TCP", ports=80)]
    )
    with pytest.raises(compiler.CompileError):
        compiler.encode_rules(ing, width=100)


def test_invalid_action_is_error():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "TCP", ports=80, action="Nope")]
    )
    with pytest.raises(compiler.CompileError):
        compiler.encode_rules(ing)


def test_build_key_v4():
    key = compiler.build_key(7, "192.168.1.5/24")
    assert key.prefix_len == 24 + 32
    assert key.ingress_ifindex == 7
    # Unmasked address bytes in the key data (loader.go:537-541).
    assert key.ip_data[:4] == bytes([192, 168, 1, 5])
    assert key.ip_data[4:] == bytes(12)


def test_build_key_v6():
    key = compiler.build_key(3, "2002:db8::1/32")
    assert key.prefix_len == 32 + 32
    assert key.ip_data[:4] == bytes([0x20, 0x02, 0x0D, 0xB8])


def test_build_key_invalid_cidr():
    with pytest.raises(compiler.CompileError):
        compiler.build_key(1, "192.168.1.5")


def test_masked_identity_collision_last_wins():
    # Two keys with the same effective prefix collapse into one trie entry,
    # the later insert winning (kernel LPM map update semantics).
    ing_a = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.1/8"], rules=[proto_rule(1, "TCP", ports=80, action="Deny")]
    )
    ing_b = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.2/8"], rules=[proto_rule(1, "TCP", ports=80, action="Allow")]
    )
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    tables = compiler.compile_tables({"eth0": [ing_a, ing_b]}, reg)
    assert tables.num_entries == 1
    assert tables.rules[0, 1, compiler.COL_ACTION] == ALLOW


def test_bond_expansion():
    reg = InterfaceRegistry()
    reg.add(Interface(name="bond0", index=10, type="bond"))
    reg.add(Interface(name="eth1", index=11, master="bond0"))
    reg.add(Interface(name="eth2", index=12, master="bond0"))
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "TCP", ports=80)]
    )
    tables = compiler.compile_tables({"bond0": [ing]}, reg)
    assert tables.num_entries == 2
    assert sorted(int(w) for w in tables.key_words[:, 0]) == [11, 12]


def test_invalid_interface_skipped():
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2, up=False))  # down -> invalid -> skip
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "TCP", ports=80)]
    )
    tables = compiler.compile_tables({"eth0": [ing]}, reg)
    assert tables.num_entries == 0


def test_compiled_tables_roundtrip(tmp_path):
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24", "2002:db8::/32"],
        rules=[proto_rule(1, "TCP", ports="80-90", action="Deny")],
    )
    tables = compiler.compile_tables({"eth0": [ing]}, reg)
    path = str(tmp_path / "tables.npz")
    tables.save(path)
    loaded = compiler.CompiledTables.load(path)
    assert loaded.num_entries == tables.num_entries
    np.testing.assert_array_equal(loaded.rules, tables.rules)
    assert len(loaded.trie_levels) == len(tables.trie_levels)
    for a, b in zip(loaded.trie_levels, tables.trie_levels):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(loaded.root_lut, tables.root_lut)
    assert set(loaded.content.keys()) == set(tables.content.keys())


def test_min_rule_width():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(17, "TCP", ports=80)]
    )
    assert compiler.min_rule_width({"eth0": [ing]}) == 18


# --- incremental table updates (loader.go:200-218,633 granularity) -----------

def _random_content(rng, n, ifindexes=(2, 3)):
    from infw.compiler import LpmKey, RULE_COLS
    content = {}
    while len(content) < n:
        mask = int(rng.integers(8, 33))
        ip = bytes([10, rng.integers(0, 256), rng.integers(0, 256),
                    rng.integers(0, 256)]) + bytes(12)
        # mask the address
        ipi = int.from_bytes(ip[:4], "big") & (0xFFFFFFFF << (32 - mask))
        ip = ipi.to_bytes(4, "big") + bytes(12)
        key = LpmKey(32 + mask, int(rng.choice(ifindexes)), ip)
        rows = np.zeros((3, RULE_COLS), np.int32)
        rows[1] = [1, 6, int(rng.integers(1, 65000)), 0, 0, 0, int(rng.integers(1, 3))]
        content[key] = rows
    return content


def _assert_tables_equivalent(a, b, rng, n_packets=400):
    """Same verdicts from both compiled tables on random traffic, through
    the COMPILED arrays (not the content dict): the native classifier
    exercises the dense key/mask/rules tensors, the XLA trie path the
    leaf-pushed trie levels."""
    from infw import testing
    from infw.backend.cpu_ref import CpuRefClassifier
    from infw.kernels import jaxpath

    batch = testing.random_batch(rng, a if a.num_entries else b, n_packets=n_packets)
    ca, cb = CpuRefClassifier(), CpuRefClassifier()
    ca.load_tables(a)
    cb.load_tables(b)
    np.testing.assert_array_equal(
        ca.classify(batch).results, cb.classify(batch).results
    )
    dbatch = jaxpath.device_batch(batch)
    ra = np.asarray(jaxpath.jitted_classify(True)(jaxpath.device_tables(a), dbatch)[0])
    rb = np.asarray(jaxpath.jitted_classify(True)(jaxpath.device_tables(b), dbatch)[0])
    np.testing.assert_array_equal(ra, rb)


def test_incremental_add_matches_full_rebuild():
    from infw.compiler import IncrementalTables, compile_tables_from_content
    rng = np.random.default_rng(61)
    base = _random_content(rng, 60)
    extra = _random_content(rng, 10, ifindexes=(2,))
    it = IncrementalTables.from_content(base, rule_width=4)
    it.apply(extra)
    merged = dict(base); merged.update(extra)
    full = compile_tables_from_content(merged, rule_width=4)
    _assert_tables_equivalent(it.snapshot(), full, rng)


def test_incremental_delete_restores_shorter_prefix():
    """Deleting a /24 must re-expose the covering /16 in the same trie
    node (node-local re-push)."""
    from infw.compiler import IncrementalTables, LpmKey, RULE_COLS
    from infw import oracle
    from infw.packets import make_batch

    def rows(action):
        r = np.zeros((2, RULE_COLS), np.int32)
        r[1] = [1, 6, 80, 0, 0, 0, action]
        return r

    k16 = LpmKey(32 + 16, 2, bytes([10, 1, 0, 0]) + bytes(12))
    k24 = LpmKey(32 + 24, 2, bytes([10, 1, 7, 0]) + bytes(12))
    it = IncrementalTables.from_content({k16: rows(2), k24: rows(1)}, rule_width=2)
    b = make_batch(src=["10.1.7.9"], proto=[6], dst_port=[80], ifindex=[2])
    assert oracle.classify(it.snapshot(), b).results[0] == (1 << 8) | 1  # /24 deny
    it.apply({}, deletes=[k24])
    assert oracle.classify(it.snapshot(), b).results[0] == (1 << 8) | 2  # /16 allow
    # the tombstoned dense row is padding
    t = it.snapshot()
    assert (t.mask_len == -1).sum() == 1


def test_incremental_update_in_place():
    from infw.compiler import IncrementalTables, LpmKey, RULE_COLS
    from infw import oracle
    from infw.packets import make_batch

    k = LpmKey(32 + 24, 2, bytes([10, 2, 3, 0]) + bytes(12))
    r1 = np.zeros((2, RULE_COLS), np.int32); r1[1] = [1, 6, 80, 0, 0, 0, 1]
    r2 = np.zeros((2, RULE_COLS), np.int32); r2[1] = [1, 6, 80, 0, 0, 0, 2]
    it = IncrementalTables.from_content({k: r1}, rule_width=2)
    b = make_batch(src=["10.2.3.4"], proto=[6], dst_port=[80], ifindex=[2])
    assert oracle.classify(it.snapshot(), b).results[0] & 0xFF == 1
    it.apply({k: r2})
    assert oracle.classify(it.snapshot(), b).results[0] & 0xFF == 2
    assert it.snapshot().num_entries == 1  # no growth


def test_incremental_slot_reuse_after_delete():
    from infw.compiler import IncrementalTables, compile_tables_from_content
    rng = np.random.default_rng(62)
    content = _random_content(rng, 30)
    it = IncrementalTables.from_content(content, rule_width=4)
    keys = list(content)
    it.apply({}, deletes=keys[:5])
    extra = _random_content(rng, 5, ifindexes=(3,))
    it.apply(extra)
    assert it.snapshot().num_entries == 30  # tombstones reused, no growth
    merged = {k: v for k, v in content.items() if k not in keys[:5]}
    merged.update(extra)
    full = compile_tables_from_content(merged, rule_width=4)
    _assert_tables_equivalent(it.snapshot(), full, rng)


def test_incremental_random_churn_matches_full():
    """Many rounds of random add/update/delete stay equivalent to a fresh
    full compile of the same logical content."""
    from infw.compiler import IncrementalTables, compile_tables_from_content
    rng = np.random.default_rng(63)
    content = _random_content(rng, 50)
    it = IncrementalTables.from_content(content, rule_width=4)
    for round_ in range(8):
        keys = list(content)
        dels = [keys[int(i)] for i in rng.choice(len(keys), size=5, replace=False)]
        for k in dels:
            del content[k]
        adds = _random_content(rng, 6)
        content.update(adds)
        it.apply(adds, deletes=dels)
    full = compile_tables_from_content(content, rule_width=4)
    _assert_tables_equivalent(it.snapshot(), full, rng, n_packets=800)


def test_compaction_reclaims_tombstones():
    from infw.compiler import IncrementalTables
    rng = np.random.default_rng(64)
    content = _random_content(rng, 200)
    it = IncrementalTables.from_content(content, rule_width=4)
    keys = list(content)
    it.apply({}, deletes=keys[:150])
    survivors = {k: v for k, v in content.items() if k not in keys[:150]}
    assert it.snapshot().num_entries == 200  # tombstones still present
    assert it.maybe_compact()
    assert it.snapshot().num_entries == len(survivors)
    from infw.compiler import compile_tables_from_content
    full = compile_tables_from_content(survivors, rule_width=4)
    _assert_tables_equivalent(it.snapshot(), full, rng)
    # further incremental updates still work after compaction
    extra = _random_content(rng, 5)
    it.apply(extra)
    survivors.update(extra)
    full = compile_tables_from_content(survivors, rule_width=4)
    _assert_tables_equivalent(it.snapshot(), full, rng)


def test_apply_aliasing_new_key_upserts_dedupe_last_wins():
    """Two distinct LpmKeys sharing a masked identity in ONE apply() call
    must collapse into a single live dense row with the last writer's
    rules (kernel LPM map update semantics, matching from_content), with
    dense and trie paths agreeing and no undeletable orphan row."""
    from infw.compiler import (
        IncrementalTables, LpmKey, RULE_COLS, trie_levels_for_mask,
    )
    from infw import oracle
    from infw.kernels import jaxpath
    from infw.packets import make_batch

    def rows(action):
        r = np.zeros((2, RULE_COLS), np.int32)
        r[1] = [1, 6, 80, 0, 0, 0, action]
        return r

    it = IncrementalTables.from_content(
        {}, rule_width=2, min_trie_levels=trie_levels_for_mask(32 + 8)
    )
    ka = LpmKey(32 + 8, 2, bytes([10, 0, 0, 1]) + bytes(12))  # 10.0.0.1/8
    kb = LpmKey(32 + 8, 2, bytes([10, 0, 0, 2]) + bytes(12))  # 10.0.0.2/8
    it.apply({ka: rows(1), kb: rows(2)})
    t = it.snapshot()
    assert t.num_entries == 1  # one live row, not two aliases
    b = make_batch(src=["10.9.9.9"], proto=[6], dst_port=[80], ifindex=[2])
    assert oracle.classify(t, b).results[0] & 0xFF == 2  # last writer (Allow)
    db = jaxpath.device_batch(b)
    dt = jaxpath.device_tables(t)
    for use_trie in (False, True):
        got = int(np.asarray(jaxpath.jitted_classify(use_trie)(dt, db)[0])[0])
        assert got & 0xFF == 2
    # deleting via the LOSING alias still removes the entry (no orphan)
    it.apply({}, deletes=[ka])
    assert oracle.classify(it.snapshot(), b).results[0] == 0


def test_apply_atomic_on_invalid_key():
    """A bad key in an upsert batch must leave the updater unchanged."""
    from infw.compiler import CompileError, IncrementalTables, LpmKey, RULE_COLS
    rng = np.random.default_rng(65)
    content = _random_content(rng, 20)
    it = IncrementalTables.from_content(content, rule_width=4)
    before = it.snapshot()
    bad = LpmKey(200, 2, bytes(16))  # prefix_len out of range
    good = _random_content(rng, 1)
    with pytest.raises(CompileError):
        it.apply({**good, bad: np.zeros((2, RULE_COLS), np.int32)})
    after = it.snapshot()
    assert set(after.content) == set(before.content)
    np.testing.assert_array_equal(after.mask_len, before.mask_len)


def test_consumed_snapshot_guards_mutation():
    """snapshot(consume=True) hands the buffers to the snapshot; any
    further use of the builder must fail loudly, never silently corrupt
    the supposedly immutable CompiledTables."""
    from infw.compiler import CompileError, IncrementalTables

    rng = np.random.default_rng(66)
    content = _random_content(rng, 20)
    it = IncrementalTables.from_content(content, rule_width=4)
    snap = it.snapshot(consume=True)
    assert snap.num_entries == len(snap.content)
    with pytest.raises(CompileError):
        it.apply(_random_content(rng, 1))
    with pytest.raises(CompileError):
        it.snapshot()


def test_poptrie_structural_invariants():
    """build_poptrie's implicit child numbering must be self-consistent:
    at every level the child-bitmap popcounts sum to the next level's
    node count, child_base is their exclusive prefix sum, target_base
    carries the global concat offsets, and the targets array length is
    1 (sentinel) + all target bits."""
    import numpy as np

    from infw import testing
    from infw.kernels.jaxpath import build_poptrie

    rng = np.random.default_rng(17)
    tables = testing.random_tables_fast(
        rng, n_entries=4000, width=4, group_size=6, ifindexes=(2, 5, 9)
    )
    levels, targets = build_poptrie(tables)

    def pops(words):
        return np.unpackbits(
            np.ascontiguousarray(words).view(np.uint8), bitorder="little"
        ).reshape(words.shape[0], -1).sum(axis=1)

    # level 0 child ids (stored +1) must reference renumbered level-1 ids
    lvl0_children = levels[0][:, 0]
    n1 = levels[1].shape[0]
    live0 = lvl0_children[lvl0_children > 0]
    assert len(np.unique(live0)) == len(live0)  # single-parent
    if len(live0):
        assert int(live0.max()) <= n1

    t_off = 1
    for l in range(1, len(levels)):
        rows = levels[l]
        cb = rows[:, 2:10]
        tb = rows[:, 10:18]
        ccounts = pops(cb)
        tcounts = pops(tb)
        # child_base = exclusive prefix sum of child counts
        np.testing.assert_array_equal(
            rows[:, 0].astype(np.int64),
            np.concatenate([[0], np.cumsum(ccounts)[:-1]]),
        )
        # target_base carries the global offset
        np.testing.assert_array_equal(
            rows[:, 1].astype(np.int64),
            t_off + np.concatenate([[0], np.cumsum(tcounts)[:-1]]),
        )
        t_off += int(tcounts.sum())
        # every implied child id is a valid next-level node
        if l + 1 < len(levels):
            assert int(ccounts.sum()) == levels[l + 1].shape[0]
        else:
            assert int(ccounts.sum()) == 0  # deepest level has no children
    assert len(targets) == t_off
    assert targets[0] == 0 and (targets[1:] > 0).all()


# --- ISSUE-6: vectorized columnar compiler ---------------------------------


def _tensor_equal(a, b):
    """Bit-identity of two CompiledTables' tensor halves."""
    for name in ("key_words", "mask_words", "mask_len", "rules", "root_lut"):
        np.testing.assert_array_equal(
            getattr(a, name), getattr(b, name), err_msg=name
        )
    assert len(a.trie_levels) == len(b.trie_levels)
    for i, (x, y) in enumerate(zip(a.trie_levels, b.trie_levels)):
        np.testing.assert_array_equal(x, y, err_msg=f"trie_levels[{i}]")
    assert a.num_entries == b.num_entries
    assert a.rule_width == b.rule_width


@pytest.mark.parametrize("kind", ["general", "gate-tripped", "aliased"])
def test_from_columns_bit_identical_to_legacy(kind):
    """The cross-check suite of the ISSUE-6 satellite: the vectorized
    columnar build (the new compile_tables_from_content default) must be
    byte-for-byte the retired per-key reference — dedup order,
    last-writer-wins values, trie node numbering, leaf-push winners."""
    from infw import testing

    rng = np.random.default_rng(17)
    if kind == "general":
        content = dict(testing.random_tables(
            rng, n_entries=120, width=6, v6_fraction=0.5
        ).content)
    elif kind == "gate-tripped":
        content = dict(testing.gate_tripped_tables(
            rng, n_entries=64, width=4
        ).content)
    else:
        # masked-identity aliases: same identity under different unmasked
        # bytes — the dedup semantics (first-occurrence order, last
        # writer wins) must survive vectorization
        r1 = np.zeros((4, 7), np.int32); r1[1] = [1, 6, 80, 0, 0, 0, 1]
        r2 = np.zeros((4, 7), np.int32); r2[1] = [1, 6, 81, 0, 0, 0, 2]
        r3 = np.zeros((4, 7), np.int32); r3[1] = [1, 17, 53, 0, 0, 0, 2]
        content = {
            compiler.LpmKey(56, 2, bytes([10, 0, 0, 1]) + bytes(12)): r1,
            compiler.LpmKey(56, 2, bytes([10, 0, 0, 2]) + bytes(12)): r2,
            compiler.LpmKey(64, 3, bytes([10, 1, 2, 3]) + bytes(12)): r3,
            compiler.LpmKey(32, 2, bytes(16)): r1,
        }
    new = compiler.IncrementalTables.from_content(
        content, rule_width=6
    ).snapshot(consume=True)
    ref = compiler.IncrementalTables.from_content_legacy(
        content, rule_width=6
    ).snapshot(consume=True)
    _tensor_equal(new, ref)
    assert list(new.content.keys()) == list(ref.content.keys())
    for k in ref.content:
        np.testing.assert_array_equal(new.content[k], ref.content[k])


def test_sorted_bulk_matches_incremental_inserts(monkeypatch):
    """The sorted-prefix bulk trie build must number nodes exactly like
    the incremental per-level path (the implicit-numbering contract the
    poptrie transform depends on).  The entry count sits above the
    E > 4096 bulk-engagement threshold and the spy asserts the bulk
    path really ran — a sub-threshold table would compare incremental
    to incremental and prove nothing about _bulk_insert_sorted."""
    from infw import testing

    rng = np.random.default_rng(23)
    # random_tables collapses colliding keys, so ask for enough that the
    # surviving unique count still clears 4096
    content = dict(testing.random_tables(
        rng, n_entries=8000, width=4, v6_fraction=0.6
    ).content)
    cols = compiler.columns_from_content(content, 4)
    assert len(content) > 4096
    calls = []
    real = compiler.VarTrie._bulk_insert_sorted

    def spy(self, *args, **kwargs):
        calls.append(1)
        return real(self, *args, **kwargs)

    monkeypatch.setattr(compiler.VarTrie, "_bulk_insert_sorted", spy)
    bulk = compiler.IncrementalTables.from_columns(cols, rule_width=4)
    assert calls, "bulk path did not engage (E must exceed the threshold)"
    # legacy pins trie.sorted_bulk = False: incremental per-level walks
    legacy = compiler.IncrementalTables.from_content_legacy(
        content, rule_width=4
    )
    _tensor_equal(bulk.snapshot(), legacy.snapshot())


def test_clean_columns_fast_matches_content_path():
    """clean_columns_fast -> compile_tables_from_columns equals the same
    columns routed through a content dict (the generator really is just
    the distribution, not a different compiler)."""
    from infw import testing

    rng = np.random.default_rng(5)
    cols = testing.clean_columns_fast(rng, 5_000)
    a = compiler.compile_tables_from_columns(cols, rule_width=4)
    content = compiler._content_dict_from_cols(
        np.asarray(cols.prefix_len), np.asarray(cols.ifindex),
        cols.ip, cols.rules,
    )
    b = compiler.compile_tables_from_content(content, rule_width=4)
    _tensor_equal(a, b)


def test_lazy_content_materializes_and_edits():
    """A from_columns updater must behave exactly like a dict-built one
    on its first incremental edit (the lazy ident/content maps)."""
    from infw import testing

    rng = np.random.default_rng(3)
    content = dict(testing.random_tables(
        rng, n_entries=40, width=4, v6_fraction=0.4
    ).content)
    cols = compiler.columns_from_content(content, 4)
    it = compiler.IncrementalTables.from_columns(cols, rule_width=4)
    key = next(iter(content))
    rows = content[key].copy()
    rows[1] = [1, 6, 8443, 0, 0, 0, 1]
    it.apply({key: rows})
    want = dict(content)
    want[key] = rows
    ref = compiler.IncrementalTables.from_content_legacy(
        want, rule_width=4
    )
    np.testing.assert_array_equal(
        it.snapshot().mask_len, ref.snapshot().mask_len
    )
    np.testing.assert_array_equal(it.content[key], rows)


def test_to_bytes_from_bytes_round_trip():
    """ISSUE-6 small fix: the in-memory snapshot round-trip (columnar
    npz, no per-key loops on either side) restores every tensor and the
    lazily-keyed content."""
    from infw import testing

    rng = np.random.default_rng(9)
    tables = testing.random_tables_fast(
        rng, n_entries=2_000, width=4, v6_fraction=0.5
    )
    blob = tables.to_bytes()
    assert isinstance(blob, bytes) and len(blob) > 0
    loaded = compiler.CompiledTables.from_bytes(blob)
    _tensor_equal(loaded, tables)
    # content restores lazily (LazyContent) but equals the original map
    assert set(loaded.content.keys()) == set(tables.content.keys())
    k = next(iter(tables.content))
    np.testing.assert_array_equal(loaded.content[k], tables.content[k])


@pytest.mark.slow
def test_snapshot_round_trip_at_scale():
    """The 1M-row snapshot round-trip regression: to_bytes/from_bytes
    must stay vectorized (no per-key Python on either side) — bounded
    here at ~40s wall on a cold CI host; the retired per-key packer
    cost minutes."""
    import time as _t

    from infw import testing

    rng = np.random.default_rng(31)
    tables = testing.clean_tables_scale(rng, 1_000_000)
    t0 = _t.perf_counter()
    blob = tables.to_bytes()
    loaded = compiler.CompiledTables.from_bytes(blob)
    dt = _t.perf_counter() - t0
    assert dt < 40.0, f"scale round-trip took {dt:.1f}s — vectorization lost"
    _tensor_equal(loaded, tables)
    assert len(loaded.content) == tables.num_entries
