"""T1: rule compiler semantics (reference loader.go:429-547 encoding)."""
import numpy as np
import pytest

from infw import compiler
from infw.constants import ALLOW, DENY, IPPROTO_ICMP, IPPROTO_ICMPV6, IPPROTO_TCP, IPPROTO_UDP
from infw.interfaces import Interface, InterfaceRegistry
from infw.spec import (
    IngressNodeFirewallICMPRule,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallRules,
    IngressNodeProtocolConfig,
)


def proto_rule(order, protocol, action="Allow", **kw):
    pc = IngressNodeProtocolConfig(protocol=protocol)
    if protocol in ("TCP", "UDP", "SCTP"):
        pr = IngressNodeFirewallProtoRule(ports=kw.get("ports", 80))
        setattr(pc, protocol.lower(), pr)
    elif protocol == "ICMP":
        pc.icmp = IngressNodeFirewallICMPRule(
            icmp_type=kw.get("t", 8), icmp_code=kw.get("c", 0)
        )
    elif protocol == "ICMPv6":
        pc.icmpv6 = IngressNodeFirewallICMPRule(
            icmp_type=kw.get("t", 128), icmp_code=kw.get("c", 0)
        )
    return IngressNodeFirewallProtocolRule(order=order, protocol_config=pc, action=action)


def test_rule_row_index_is_order_and_ruleid_is_order():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(5, "TCP", ports=8080, action="Deny")]
    )
    rows = compiler.encode_rules(ing)
    assert rows[5, compiler.COL_RULE_ID] == 5
    assert rows[5, compiler.COL_PROTOCOL] == IPPROTO_TCP
    assert rows[5, compiler.COL_PORT_START] == 8080
    assert rows[5, compiler.COL_PORT_END] == 0  # single port -> end==0
    assert rows[5, compiler.COL_ACTION] == DENY
    # all other slots empty (ruleId 0 == INVALID_RULE_ID)
    assert rows[[0, 1, 4, 6], compiler.COL_RULE_ID].sum() == 0


def test_range_encoding():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "UDP", ports="100-200")]
    )
    rows = compiler.encode_rules(ing)
    assert rows[1, compiler.COL_PROTOCOL] == IPPROTO_UDP
    assert rows[1, compiler.COL_PORT_START] == 100
    assert rows[1, compiler.COL_PORT_END] == 200
    assert rows[1, compiler.COL_ACTION] == ALLOW


def test_icmp_encoding():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"],
        rules=[proto_rule(2, "ICMP", t=8, c=0), proto_rule(3, "ICMPv6", t=128, c=0)],
    )
    rows = compiler.encode_rules(ing)
    assert rows[2, compiler.COL_PROTOCOL] == IPPROTO_ICMP
    assert rows[2, compiler.COL_ICMP_TYPE] == 8
    assert rows[3, compiler.COL_PROTOCOL] == IPPROTO_ICMPV6
    assert rows[3, compiler.COL_ICMP_TYPE] == 128


def test_unset_protocol_is_catch_all():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"],
        rules=[
            IngressNodeFirewallProtocolRule(
                order=1, protocol_config=IngressNodeProtocolConfig(protocol=""), action="Deny"
            )
        ],
    )
    rows = compiler.encode_rules(ing)
    assert rows[1, compiler.COL_PROTOCOL] == 0
    assert rows[1, compiler.COL_ACTION] == DENY


def test_order_out_of_range_is_error():
    # order >= width would be an array-OOB panic in the reference loader.
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(100, "TCP", ports=80)]
    )
    with pytest.raises(compiler.CompileError):
        compiler.encode_rules(ing, width=100)


def test_invalid_action_is_error():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "TCP", ports=80, action="Nope")]
    )
    with pytest.raises(compiler.CompileError):
        compiler.encode_rules(ing)


def test_build_key_v4():
    key = compiler.build_key(7, "192.168.1.5/24")
    assert key.prefix_len == 24 + 32
    assert key.ingress_ifindex == 7
    # Unmasked address bytes in the key data (loader.go:537-541).
    assert key.ip_data[:4] == bytes([192, 168, 1, 5])
    assert key.ip_data[4:] == bytes(12)


def test_build_key_v6():
    key = compiler.build_key(3, "2002:db8::1/32")
    assert key.prefix_len == 32 + 32
    assert key.ip_data[:4] == bytes([0x20, 0x02, 0x0D, 0xB8])


def test_build_key_invalid_cidr():
    with pytest.raises(compiler.CompileError):
        compiler.build_key(1, "192.168.1.5")


def test_masked_identity_collision_last_wins():
    # Two keys with the same effective prefix collapse into one trie entry,
    # the later insert winning (kernel LPM map update semantics).
    ing_a = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.1/8"], rules=[proto_rule(1, "TCP", ports=80, action="Deny")]
    )
    ing_b = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.2/8"], rules=[proto_rule(1, "TCP", ports=80, action="Allow")]
    )
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    tables = compiler.compile_tables({"eth0": [ing_a, ing_b]}, reg)
    assert tables.num_entries == 1
    assert tables.rules[0, 1, compiler.COL_ACTION] == ALLOW


def test_bond_expansion():
    reg = InterfaceRegistry()
    reg.add(Interface(name="bond0", index=10, type="bond"))
    reg.add(Interface(name="eth1", index=11, master="bond0"))
    reg.add(Interface(name="eth2", index=12, master="bond0"))
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "TCP", ports=80)]
    )
    tables = compiler.compile_tables({"bond0": [ing]}, reg)
    assert tables.num_entries == 2
    assert sorted(int(w) for w in tables.key_words[:, 0]) == [11, 12]


def test_invalid_interface_skipped():
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2, up=False))  # down -> invalid -> skip
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(1, "TCP", ports=80)]
    )
    tables = compiler.compile_tables({"eth0": [ing]}, reg)
    assert tables.num_entries == 0


def test_compiled_tables_roundtrip(tmp_path):
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24", "2002:db8::/32"],
        rules=[proto_rule(1, "TCP", ports="80-90", action="Deny")],
    )
    tables = compiler.compile_tables({"eth0": [ing]}, reg)
    path = str(tmp_path / "tables.npz")
    tables.save(path)
    loaded = compiler.CompiledTables.load(path)
    assert loaded.num_entries == tables.num_entries
    np.testing.assert_array_equal(loaded.rules, tables.rules)
    assert len(loaded.trie_levels) == len(tables.trie_levels)
    for a, b in zip(loaded.trie_levels, tables.trie_levels):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(loaded.root_lut, tables.root_lut)
    assert set(loaded.content.keys()) == set(tables.content.keys())


def test_min_rule_width():
    ing = IngressNodeFirewallRules(
        source_cidrs=["10.0.0.0/24"], rules=[proto_rule(17, "TCP", ports=80)]
    )
    assert compiler.min_rule_width({"eth0": [ing]}) == 18
