"""Schema-tier (OpenAPI/CEL) validation tests.

These invariants come from the kubebuilder markers on the reference API
types (/root/reference/api/v1alpha1/ingressnodefirewall_types.go:26-38,
51-61, 93-97, 128-130) — the tier the API server enforces before the
webhook runs.
"""
import pytest

from infw import schema, validate
from infw.compiler import CompileError, encode_rules
from infw.spec import (
    IngressNodeFirewall,
    IngressNodeFirewallICMPRule,
    IngressNodeFirewallNodeState,
    IngressNodeFirewallNodeStateSpec,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallRules,
    IngressNodeFirewallSpec,
    IngressNodeProtocolConfig,
    ObjectMeta,
)


def mk_inf(rules, cidrs=("10.0.0.0/8",), name="inf-schema"):
    return IngressNodeFirewall(
        metadata=ObjectMeta(name=name),
        spec=IngressNodeFirewallSpec(
            interfaces=["eth0"],
            ingress=[
                IngressNodeFirewallRules(
                    source_cidrs=list(cidrs), rules=list(rules)
                )
            ],
        ),
    )


def tcp_rule(order=1, ports=80, action="Deny", protocol="TCP"):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol=protocol, tcp=IngressNodeFirewallProtoRule(ports=ports)
        ),
        action=action,
    )


def icmp_rule(order=1, icmp_type=8, icmp_code=0, action="Deny", v6=False):
    icmp = IngressNodeFirewallICMPRule(icmp_type=icmp_type, icmp_code=icmp_code)
    pc = (
        IngressNodeProtocolConfig(protocol="ICMPv6", icmpv6=icmp)
        if v6
        else IngressNodeProtocolConfig(protocol="ICMP", icmp=icmp)
    )
    return IngressNodeFirewallProtocolRule(order=order, protocol_config=pc, action=action)


class TestProtocolEnum:
    def test_misspelled_protocol_rejected(self):
        # VERDICT round-1 confirmed bug: "Tcp" used to pass with zero errors
        # and silently compile to a protocol-0 catch-all.
        inf = mk_inf([tcp_rule(protocol="Tcp")])
        errs = validate.validate_ingress_node_firewall(inf)
        assert any('Unsupported value: "Tcp"' in e for e in errs)

    @pytest.mark.parametrize("proto", ["tcp", "TCP6", "icmp", "Udp", "ICMPV6"])
    def test_bad_protocol_values(self, proto):
        inf = mk_inf([tcp_rule(protocol=proto)])
        errs = validate.validate_ingress_node_firewall(inf)
        assert any(f'Unsupported value: "{proto}"' in e for e in errs)

    def test_empty_protocol_is_legal_catch_all(self):
        rule = IngressNodeFirewallProtocolRule(
            order=1, protocol_config=IngressNodeProtocolConfig(protocol=""),
            action="Deny",
        )
        assert validate.validate_ingress_node_firewall(mk_inf([rule])) == []

    def test_all_enum_values_accepted(self):
        rules = [
            tcp_rule(order=1),
            IngressNodeFirewallProtocolRule(
                order=2,
                protocol_config=IngressNodeProtocolConfig(
                    protocol="UDP", udp=IngressNodeFirewallProtoRule(ports=5000)
                ),
                action="Deny",
            ),
            IngressNodeFirewallProtocolRule(
                order=3,
                protocol_config=IngressNodeProtocolConfig(
                    protocol="SCTP", sctp=IngressNodeFirewallProtoRule(ports=5001)
                ),
                action="Deny",
            ),
            icmp_rule(order=4),
            icmp_rule(order=5, v6=True),
        ]
        assert validate.validate_ingress_node_firewall(mk_inf(rules)) == []


class TestOrderMinimum:
    def test_order_zero_rejected_at_admission(self):
        errs = validate.validate_ingress_node_firewall(mk_inf([tcp_rule(order=0)]))
        assert any("order in body should be greater than or equal to 1" in e for e in errs)

    def test_negative_order_rejected(self):
        errs = validate.validate_ingress_node_firewall(mk_inf([tcp_rule(order=-3)]))
        assert any("greater than or equal to 1" in e for e in errs)

    def test_order_one_ok(self):
        assert validate.validate_ingress_node_firewall(mk_inf([tcp_rule(order=1)])) == []


class TestIcmpBounds:
    @pytest.mark.parametrize("field,val", [("type", 256), ("type", -1), ("code", 256), ("code", 999)])
    def test_out_of_bounds_rejected(self, field, val):
        kw = {"icmp_type": val} if field == "type" else {"icmp_code": val}
        errs = validate.validate_ingress_node_firewall(mk_inf([icmp_rule(**kw)]))
        assert any("in body should be" in e and "icmp" in e for e in errs)

    def test_icmpv6_bounds_checked_too(self):
        errs = validate.validate_ingress_node_firewall(
            mk_inf([icmp_rule(icmp_type=256, v6=True)])
        )
        assert any("icmpv6.icmpType" in e for e in errs)

    @pytest.mark.parametrize("val", [0, 255])
    def test_boundary_values_accepted(self, val):
        assert (
            validate.validate_ingress_node_firewall(
                mk_inf([icmp_rule(icmp_type=val, icmp_code=val)])
            )
            == []
        )


class TestActionEnum:
    @pytest.mark.parametrize("action", ["allow", "DENY", "Drop", ""])
    def test_bad_action_rejected(self, action):
        errs = validate.validate_ingress_node_firewall(mk_inf([tcp_rule(action=action)]))
        assert any(f'Unsupported value: "{action}"' in e for e in errs)

    @pytest.mark.parametrize("action", ["Allow", "Deny"])
    def test_enum_actions_accepted(self, action):
        assert validate.validate_ingress_node_firewall(mk_inf([tcp_rule(action=action)])) == []


class TestUnionCelRules:
    """The five XValidation rules (types.go:52-56)."""

    def test_tcp_required_when_protocol_tcp(self):
        rule = IngressNodeFirewallProtocolRule(
            order=1, protocol_config=IngressNodeProtocolConfig(protocol="TCP"),
            action="Deny",
        )
        errs = validate.validate_ingress_node_firewall(mk_inf([rule]))
        assert any("tcp is required when protocol is TCP, and forbidden otherwise" in e for e in errs)

    def test_tcp_forbidden_when_protocol_icmp(self):
        rule = IngressNodeFirewallProtocolRule(
            order=1,
            protocol_config=IngressNodeProtocolConfig(
                protocol="ICMP",
                icmp=IngressNodeFirewallICMPRule(icmp_type=8),
                tcp=IngressNodeFirewallProtoRule(ports=80),
            ),
            action="Deny",
        )
        errs = validate.validate_ingress_node_firewall(mk_inf([rule]))
        assert any("tcp is required when protocol is TCP, and forbidden otherwise" in e for e in errs)

    @pytest.mark.parametrize(
        "proto,member_msg",
        [
            ("UDP", "udp is required when protocol is UDP"),
            ("SCTP", "sctp is required when protocol is SCTP"),
            ("ICMP", "icmp is required when protocol is ICMP,"),
            ("ICMPv6", "icmpv6 is required when protocol is ICMPv6"),
        ],
    )
    def test_member_required_per_discriminator(self, proto, member_msg):
        rule = IngressNodeFirewallProtocolRule(
            order=1, protocol_config=IngressNodeProtocolConfig(protocol=proto),
            action="Deny",
        )
        errs = validate.validate_ingress_node_firewall(mk_inf([rule]))
        assert any(member_msg in e for e in errs)

    def test_members_forbidden_when_protocol_unset(self):
        rule = IngressNodeFirewallProtocolRule(
            order=1,
            protocol_config=IngressNodeProtocolConfig(
                protocol="", udp=IngressNodeFirewallProtoRule(ports=53)
            ),
            action="Deny",
        )
        errs = validate.validate_ingress_node_firewall(mk_inf([rule]))
        assert any("udp is required when protocol is UDP" in e for e in errs)


class TestCompilerGuards:
    def test_unknown_protocol_is_compile_error_not_catch_all(self):
        ingress = mk_inf([tcp_rule(protocol="Tcp")]).spec.ingress[0]
        with pytest.raises(CompileError, match="unknown protocol 'Tcp'"):
            encode_rules(ingress)


class TestNodeStateSchema:
    def test_nodestate_rules_share_schema_tier(self):
        ns = IngressNodeFirewallNodeState(
            metadata=ObjectMeta(name="node-a"),
            spec=IngressNodeFirewallNodeStateSpec(
                interface_ingress_rules={
                    "eth0": [
                        IngressNodeFirewallRules(
                            source_cidrs=["10.0.0.0/8"],
                            rules=[tcp_rule(order=0, protocol="Tcp")],
                        )
                    ]
                }
            ),
        )
        errs = schema.validate_nodestate_schema(ns)
        assert any("order in body should be greater than or equal to 1" in e for e in errs)
        assert any('Unsupported value: "Tcp"' in e for e in errs)
