"""Events sidecar composition: daemon + separate follower process.

The reference suite asserts per-drop records by regexing the events
sidecar's container logs (/root/reference/test/e2e/events/events.go:
140-205); here the sidecar is a real child process
(`python -m infw.obs.sidecar`) whose stdout is captured and regexed the
same way, over both transports (unixgram socket — the faithful analogue
of cmd/syslog/syslog.go — and events.log tail)."""
import json
import os
import re
import subprocess
import sys
import time

import pytest

from infw.daemon import Daemon, write_frames_file_v2
from infw.interfaces import Interface, InterfaceRegistry
from infw.obs.pcap import FramesBuf, build_frame
from infw.obs.sidecar import UnixDatagramSink, tail_file

NODE = "node-a"

DROP_LINE = re.compile(
    r"ruleId (\d+) action Drop len (\d+) if (\S+)"
)
V4_LINE = re.compile(r"ipv4 src addr ([\d.]+) dst addr ([\d.]+)")
TCP_LINE = re.compile(r"tcp srcPort (\d+) dstPort (\d+)")


def _nodestate_doc():
    return {
        "apiVersion": "ingressnodefirewall.openshift.io/v1alpha1",
        "kind": "IngressNodeFirewallNodeState",
        "metadata": {"name": NODE, "namespace": "ingress-node-firewall-system"},
        "spec": {"interfaceIngressRules": {"eth0": [
            {"sourceCIDRs": ["10.0.0.0/8"],
             "rules": [{"order": 1, "protocolConfig": {"protocol": "TCP",
                        "tcp": {"ports": "80"}}, "action": "Deny"}]}
        ]}},
    }


def _start_daemon(tmp_path, **kw):
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2, up=True))
    d = Daemon(
        state_dir=str(tmp_path), node_name=NODE, backend="cpu", registry=reg,
        metrics_port=0, health_port=0, poll_period_s=5,
        file_poll_interval_s=0.05, **kw,
    )
    d.start()
    return d


def _apply_and_replay(d, tmp_path, n_drops=3):
    p = os.path.join(d.nodestates_dir, f"{NODE}.json")
    with open(p + ".tmp", "w") as f:
        json.dump(_nodestate_doc(), f)
    os.replace(p + ".tmp", p)
    deadline = time.time() + 20
    while time.time() < deadline:
        if d.syncer.classifier is not None and d.syncer.classifier.tables is not None:
            break
        time.sleep(0.02)
    assert d.syncer.classifier.tables is not None
    frames = [
        build_frame(f"10.0.0.{i+1}", "9.9.9.9", 6, 4000 + i, 80)
        for i in range(n_drops)
    ] + [build_frame("10.0.0.9", "9.9.9.9", 6, 4999, 81)]  # pass
    fb = FramesBuf.from_frames(frames, 2)
    write_frames_file_v2(os.path.join(d.ingest_dir, "t.frames"), fb)
    vp = os.path.join(d.out_dir, "t.frames.verdicts.json")
    while time.time() < deadline and not os.path.exists(vp):
        time.sleep(0.02)
    assert os.path.exists(vp)


def _wait_for(path, pattern, timeout=15):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            text = open(path).read()
            if pattern.search(text):
                return text
        time.sleep(0.05)
    raise AssertionError(
        f"pattern {pattern.pattern!r} never appeared in {path}: "
        f"{open(path).read() if os.path.exists(path) else '<missing>'!r}"
    )


def _assert_drop_records(text, n_drops):
    """The reference's per-drop assertions (events.go:140-205): one record
    per denied packet with rule/iface/addresses/ports decoded."""
    drops = DROP_LINE.findall(text)
    assert len(drops) == n_drops, text
    assert all(r == ("1", "54", "eth0") for r in drops)
    assert len(V4_LINE.findall(text)) == n_drops
    tcp = TCP_LINE.findall(text)
    assert [p[1] for p in tcp] == ["80"] * n_drops
    assert sorted(p[0] for p in tcp) == [str(4000 + i) for i in range(n_drops)]
    # allow verdicts generate no event (kernel.c:450)
    assert "dstPort 81" not in text


@pytest.mark.parametrize("transport", ["socket", "tail"])
def test_sidecar_process_composition(tmp_path, transport):
    sock_path = os.path.join(str(tmp_path), "events.sock")
    out_path = os.path.join(str(tmp_path), "sidecar.out")
    events_log = os.path.join(str(tmp_path), "events.log")

    argv = ["--socket", sock_path] if transport == "socket" else \
        ["--tail", events_log]
    with open(out_path, "wb") as out:
        proc = subprocess.Popen(
            [sys.executable, "-m", "infw.obs.sidecar", *argv],
            stdout=out, stderr=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    d = None
    try:
        if transport == "socket":
            # wait for the follower to bind before the daemon sends
            deadline = time.time() + 10
            while time.time() < deadline and not os.path.exists(sock_path):
                time.sleep(0.02)
            assert os.path.exists(sock_path)
            d = _start_daemon(tmp_path, events_socket=sock_path)
        else:
            d = _start_daemon(tmp_path)
        _apply_and_replay(d, tmp_path)
        text = _wait_for(out_path, TCP_LINE)
        time.sleep(0.3)  # let the remaining lines flush
        _assert_drop_records(open(out_path).read(), n_drops=3)
        if transport == "socket":
            # events.log still has the full record (in-process sink kept)
            assert DROP_LINE.search(open(events_log).read())
    finally:
        if d is not None:
            d.stop()
        proc.terminate()
        proc.wait(timeout=10)


def test_unix_sink_tolerates_dead_sidecar(tmp_path):
    """A missing/dead follower never blocks or crashes the daemon —
    datagrams are dropped and counted (the perf-ring overflow posture)."""
    sink = UnixDatagramSink(os.path.join(str(tmp_path), "nobody.sock"))
    for _ in range(5):
        sink("ruleId 1 action Drop len 54 if eth0")
    assert sink.dropped == 5
    sink.close()


def test_tail_file_survives_rotation(tmp_path):
    path = os.path.join(str(tmp_path), "ev.log")
    out_path = os.path.join(str(tmp_path), "out.txt")
    import threading

    stop = threading.Event()
    out = open(out_path, "w")
    t = threading.Thread(
        target=tail_file,
        args=(path, out, 0.02, stop.is_set),
    )
    t.start()
    try:
        with open(path, "w", buffering=1) as f:
            f.write("line-1\n")
        time.sleep(0.3)
        os.replace(path + "", path + ".old")  # rotate
        with open(path, "w", buffering=1) as f:
            f.write("line-2\n")
        deadline = time.time() + 10
        while time.time() < deadline:
            if "line-2" in open(out_path).read():
                break
            time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        out.close()
    text = open(out_path).read()
    assert "line-1" in text and "line-2" in text


def _tail_in_thread(path, out_path, from_start=True):
    import threading

    stop = threading.Event()
    out = open(out_path, "w")
    t = threading.Thread(
        target=tail_file, args=(path, out, 0.02, stop.is_set, from_start)
    )
    t.start()
    return stop, t, out


def test_tail_file_holds_partial_lines(tmp_path):
    """A record written in two OS-level appends must come out as ONE
    line, never a split record a regexing consumer would miss."""
    path = os.path.join(str(tmp_path), "ev.log")
    out_path = os.path.join(str(tmp_path), "out.txt")
    stop, t, out = _tail_in_thread(path, out_path)
    try:
        with open(path, "a") as f:
            f.write("ruleId 1 action Drop ")
            f.flush()
            time.sleep(0.3)  # tailer sees the fragment now
            f.write("len 54 if eth0\n")
        deadline = time.time() + 10
        while time.time() < deadline and "eth0" not in open(out_path).read():
            time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        out.close()
    assert open(out_path).read() == "ruleId 1 action Drop len 54 if eth0\n"


def test_tail_file_from_end_remove_recreate(tmp_path):
    """--from-end must still emit everything in a file recreated after
    remove-style rotation (a fresh log is new content, not history)."""
    path = os.path.join(str(tmp_path), "ev.log")
    out_path = os.path.join(str(tmp_path), "out.txt")
    with open(path, "w") as f:
        f.write("old-history\n")
    stop, t, out = _tail_in_thread(path, out_path, from_start=False)
    try:
        time.sleep(0.3)  # tailer is at EOF of the old file
        os.remove(path)
        time.sleep(0.3)
        with open(path, "w") as f:
            f.write("after-rotate-1\n")
        deadline = time.time() + 10
        while time.time() < deadline and "after-rotate-1" not in open(out_path).read():
            time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=10)
        out.close()
    text = open(out_path).read()
    assert "after-rotate-1" in text
    assert "old-history" not in text  # --from-end: history stays skipped
