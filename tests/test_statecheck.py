"""Patch-path model checker (infw.analysis.statecheck / shrink).

Covers: seeded op-sequence equivalence (incrementally-patched device
state bit-identical to a cold rebuild + classify-equivalent to the CPU
oracle) across the dense/trie/overlay/wide/fused configurations and the
mesh-replicated broadcast patch path; the device-table invariant
contracts (standalone and as the runtime INFW_CHECK_INVARIANTS hook);
shrinker determinism (same failing case -> same minimal repro); and the
injected-defect acceptance — re-introducing the PR-4 joined-placeholder
bucket-padding bug must be caught with a <= 3-op shrunk reproducer.
"""
import numpy as np
import pytest

from infw import testing
from infw.analysis import statecheck
from infw.analysis.shrink import shrink_case
from infw.compiler import IncrementalTables, LpmKey
from infw.constants import IPPROTO_TCP
from infw.kernels import jaxpath


@pytest.fixture
def inject_joined_pad_bug():
    jaxpath._INJECT_JOINED_PAD_BUG = True
    try:
        yield
    finally:
        jaxpath._INJECT_JOINED_PAD_BUG = False


# --- operation model --------------------------------------------------------


def test_op_generation_deterministic():
    """Same seed -> byte-identical base content and op sequence (the
    precondition for reproducible failures and shrink determinism)."""
    base1, ops1 = statecheck.build_case("trie", seed=7, n_ops=12)
    base2, ops2 = statecheck.build_case("trie", seed=7, n_ops=12)
    assert list(base1) == list(base2)
    for k in base1:
        np.testing.assert_array_equal(base1[k], base2[k])
    assert [op.code() for op in ops1] == [op.code() for op in ops2]
    # a different seed gives a different sequence
    _, ops3 = statecheck.build_case("trie", seed=8, n_ops=12)
    assert [op.code() for op in ops1] != [op.code() for op in ops3]


def test_op_alphabet_reachable():
    """The generator emits every kind of the edit alphabet over a
    moderate horizon."""
    _, ops = statecheck.build_case("overlay", seed=3, n_ops=60)
    kinds = {op.kind for op in ops}
    assert kinds == set(statecheck.EDIT_KINDS)


def test_editop_code_round_trips():
    _, ops = statecheck.build_case("trie", seed=5, n_ops=8)
    env = {"statecheck": statecheck, "LpmKey": LpmKey, "np": np}
    for op in ops:
        clone = eval(op.code(), env)
        assert clone.kind == op.kind
        if op.key is not None:
            assert clone.key == op.key
        if op.rules is not None:
            np.testing.assert_array_equal(clone.rules, op.rules)


# --- seeded op-sequence equivalence ----------------------------------------


@pytest.mark.parametrize("config,n_ops", [
    ("dense", 4),
    # the trie config re-runs in `make state-check --strict`; its jit
    # bill is the tier-1 budget's, so the seeded sweep is slow-marked
    pytest.param("trie", 3, marks=pytest.mark.slow),
    ("overlay", 5), ("wide", 4),
    ("nojoined", 4),
])
def test_equivalence_clean_tree(config, n_ops):
    rep = statecheck.run_config(
        config, seed=4, n_ops=n_ops, shrink_on_failure=False
    )
    assert rep["ok"], rep["failure"]


@pytest.mark.slow
def test_equivalence_fused_walk():
    """The fused deep-walk config: rules-only edits patch the resident
    joined byte planes; structural edits rebuild in the background —
    both must stay bit-identical to a cold walk build and oracle-exact
    through the depth-steered packed classify."""
    rep = statecheck.run_config(
        "fused", seed=2, n_ops=2, shrink_on_failure=False
    )
    assert rep["ok"], rep["failure"]


@pytest.mark.slow
def test_equivalence_mesh_replicated():
    """The mesh-replicated broadcast patch path (NamedSharding-as-device
    diff-scatter) through the same engine."""
    if len(__import__("jax").devices()) < 2:
        pytest.skip("needs a multi-device pool")
    rep = statecheck.run_config(
        "trie", seed=2, n_ops=2, backend="mesh", shrink_on_failure=False
    )
    assert rep["ok"], rep["failure"]


# --- invariant contracts ----------------------------------------------------


def _clean_dev():
    rng = np.random.default_rng(31)
    tables = testing.random_tables(rng, n_entries=40, width=4)
    return jaxpath.device_tables(tables, pad=True)


def test_check_device_tables_clean():
    assert statecheck.check_device_tables(_clean_dev()) == []


def test_check_device_tables_flags_bucket_padded_placeholder():
    """The PR-4 bug class as a static contract violation: an inactive
    placeholder bucket-padded to (8, 1) reads as an ACTIVE joined plane
    of width 1."""
    import jax.numpy as jnp

    dev = _clean_dev()._replace(joined=jnp.zeros((8, 1), jnp.uint16))
    viols = statecheck.check_device_tables(dev)
    assert any("joined" in v and "width 1" in v for v in viols), viols


def test_check_device_tables_flags_fill_and_mask_violations():
    import jax.numpy as jnp

    dev = _clean_dev()
    # a tombstone row carrying key bytes violates the fill contract
    kw = np.asarray(dev.key_words).copy()
    ml = np.asarray(dev.mask_len)
    dead = int(np.nonzero(ml < 0)[0][0])
    kw[dead, 1] = 7
    viols = statecheck.check_device_tables(
        dev._replace(key_words=jnp.asarray(kw))
    )
    assert any("fill" in v for v in viols), viols
    # a mask_words row diverging from the mask_len reconstruction
    mw = np.asarray(dev.mask_words).copy()
    live = int(np.nonzero(ml >= 0)[0][0])
    mw[live, 2] ^= 1
    viols = statecheck.check_device_tables(
        dev._replace(mask_words=jnp.asarray(mw))
    )
    assert any("mask_words" in v for v in viols), viols


def test_assert_patched_tables_is_permanent_and_cheap():
    """The always-on shape contract: clean padded tables pass; the
    bucket-padded placeholder raises at the mutation site."""
    import jax.numpy as jnp

    dev = _clean_dev()
    jaxpath.assert_patched_tables(dev)  # no raise
    bad = dev._replace(joined=jnp.zeros((8, 1), jnp.uint16))
    with pytest.raises(jaxpath.DeviceTableInvariantError):
        jaxpath.assert_patched_tables(bad)


def test_placeholder_survives_structural_patch():
    """Satellite regression (the PR-4 fix as a guarded contract): on a
    gate-tripped table the inactive (1, 1) placeholder must survive a
    structural diff-based patch exactly, and the patched state must stay
    bit-identical to a fresh upload."""
    import jax

    rng = np.random.default_rng(3)
    tables = testing.gate_tripped_tables(rng)
    it = IncrementalTables.from_content(dict(tables.content), rule_width=4)
    prev = it.snapshot()
    it.clear_dirty()
    dev = jaxpath.device_tables(prev, pad=True)
    assert tuple(dev.joined.shape) == (1, 1)
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, IPPROTO_TCP, 80, 0, 0, 0, 2]
    it.apply({LpmKey(24 + 32, 2, bytes([11, 0, 0, 0]) + bytes(12)): rows})
    new = it.snapshot()
    patched = jaxpath.patch_device_tables(dev, prev, new, hint=it.peek_dirty())
    assert patched is not None
    assert tuple(patched[0].joined.shape) == (1, 1)
    fresh = jaxpath.device_tables(new, pad=True)
    for a, b in zip(jax.tree.leaves(patched[0]), jax.tree.leaves(fresh)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_injected_bug_raises_at_mutation_site(inject_joined_pad_bug):
    """With the PR-4 defect injected, the permanent contract refuses the
    patch result before it can install."""
    rng = np.random.default_rng(3)
    tables = testing.gate_tripped_tables(rng)
    it = IncrementalTables.from_content(dict(tables.content), rule_width=4)
    prev = it.snapshot()
    it.clear_dirty()
    dev = jaxpath.device_tables(prev, pad=True)
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, IPPROTO_TCP, 80, 0, 0, 0, 2]
    it.apply({LpmKey(24 + 32, 2, bytes([11, 0, 0, 0]) + bytes(12)): rows})
    with pytest.raises(jaxpath.DeviceTableInvariantError):
        jaxpath.patch_device_tables(
            dev, prev, it.snapshot(), hint=it.peek_dirty()
        )


def test_runtime_invariant_hook_catches_bypassed_corruption(
    inject_joined_pad_bug, monkeypatch
):
    """Layered defense: with the mutation-site assert bypassed, the
    opt-in INFW_CHECK_INVARIANTS hook (the deep statecheck pass) still
    refuses to install the corrupted generation."""
    from infw.backend.tpu import TpuClassifier

    monkeypatch.setattr(jaxpath, "assert_patched_tables", lambda dev: None)
    rng = np.random.default_rng(3)
    tables = testing.gate_tripped_tables(rng)
    it = IncrementalTables.from_content(dict(tables.content), rule_width=4)
    clf = TpuClassifier(
        interpret=True, force_path="trie", check_invariants=True
    )
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, IPPROTO_TCP, 80, 0, 0, 0, 2]
    it.apply({LpmKey(24 + 32, 2, bytes([11, 0, 0, 0]) + bytes(12)): rows})
    with pytest.raises(statecheck.InvariantViolation):
        clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())


# --- injected-defect acceptance + shrinker ---------------------------------


@pytest.mark.slow
def test_injected_defect_caught_and_shrunk(inject_joined_pad_bug):
    """The acceptance gate: the checker catches the re-introduced PR-4
    bug and shrinks the case to <= 3 ops; the shrinker is deterministic
    (same failing case -> identical minimal repro)."""
    base, ops = statecheck.build_case("nojoined", seed=0, n_ops=6)
    failure = statecheck.run_ops(base, ops, "nojoined", seed=0)
    assert failure is not None
    assert "joined" in failure.message
    r1 = shrink_case(base, list(ops), "nojoined", failure,
                     witness_b=192, seed=0, max_runs=32)
    assert len(r1.ops) <= 3
    assert r1.failure is not None
    # determinism: an identical second shrink produces the same repro
    r2 = shrink_case(base, list(ops), "nojoined", failure,
                     witness_b=192, seed=0, max_runs=32)
    assert r1.code() == r2.code()
    # the repro is paste-able and still fails standalone
    env = {}
    exec_lines = r1.code().replace("assert failure is None, failure", "")
    exec(exec_lines, env)
    assert env["failure"] is not None


# --- warm-scatter coverage (first-edit recompile lint) ----------------------


def _scatter_cache_size():
    return jaxpath._scatter_rows_jit()._cache_size()


def _one_key_edit(it, content):
    k = sorted(content, key=lambda k: (k.ingress_ifindex, k.ip_data))[0]
    rows = np.asarray(it.content[k]).copy()
    rows[1, 2] = int(rows[1, 2]) % 60000 + 7
    it.apply({k: rows})
    return {k: rows}


@pytest.mark.parametrize("variant", ["u16", "wide", "nojoined"])
def test_patch_ladder_no_hidden_first_edit_compile(variant):
    """warm_patch_scatters must cover every patchable array layout —
    u16-joined, the wide-ruleId u32 path, the gate-tripped placeholder
    regime — so the first incremental edit after a load compiles
    NOTHING (the _cache_size recompile lint, mirroring the PR-4
    wire-latency fix)."""
    from infw.backend.tpu import TpuClassifier

    rng = np.random.default_rng(41)
    if variant == "nojoined":
        content = dict(testing.gate_tripped_tables(rng).content)
    else:
        content = {}
        for i in range(40):
            rows = np.zeros((4, 7), np.int32)
            rid = 70000 if (variant == "wide" and i == 0) else 1
            rows[1] = [rid, IPPROTO_TCP, 80 + i, 0, 0, 0, 1]
            content[LpmKey(24 + 32, 2, bytes([10, 1, i, 0]) + bytes(12))] = rows
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = TpuClassifier(interpret=True, force_path="trie")
    clf.load_tables(it.snapshot())
    it.clear_dirty()
    size0 = _scatter_cache_size()
    _one_key_edit(it, content)
    clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
    it.clear_dirty()
    assert clf._last_load[0] == "patch"
    # structural one-key add: trie-level scatters must be warmed too
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, IPPROTO_TCP, 99, 0, 0, 0, 2]
    it.apply({LpmKey(24 + 32, 2, bytes([12, 0, 0, 0]) + bytes(12)): rows})
    clf.load_tables(it.snapshot(), dirty_hint=it.peek_dirty())
    it.clear_dirty()
    grew = _scatter_cache_size() - size0
    assert grew == 0, (
        f"{grew} scatter executable(s) compiled on the first edits — "
        "warm_patch_scatters missed a patchable layout"
    )


def test_fused_walk_patch_uses_warmed_scatter():
    """patch_walk_joined must route through the shared capped scatter:
    after warm_walk_patch_scatters, a rules-only joined patch of the
    resident walk compiles nothing."""
    from infw.kernels import pallas_walk

    rng = np.random.default_rng(47)
    tables = testing.random_tables_fast(
        rng, n_entries=512, width=4, v6_fraction=0.9
    )
    built = pallas_walk.build_walk_tables_meta(tables)
    if built is None:
        pytest.skip("fused walk declined the fixture")
    wt, meta = built
    pallas_walk.warm_walk_patch_scatters(wt)
    size0 = _scatter_cache_size()
    t_vals = meta.get("t_vals")
    assert t_vals is not None
    live = np.nonzero(t_vals > 0)[0]
    dirty = np.asarray([int(t_vals[live[0]] - 1)], np.int64)
    patched = pallas_walk.patch_walk_joined(wt, meta, tables, dirty)
    assert patched is not None and patched is not wt
    assert _scatter_cache_size() == size0, (
        "the fused-walk joined patch compiled a fresh scatter executable"
    )


# --- 1M-scale tier ----------------------------------------------------------


@pytest.mark.slow
def test_statecheck_1m_tier():
    """One seeded edit burst at the 1M tier: the equivalence engine
    (cold-rebuild bit-identity + HashLpmOracle witness classify) holds
    at production scale."""
    rng = np.random.default_rng(13)
    tables = testing.random_tables_fast(rng, n_entries=1_000_000, width=4)
    cfg = statecheck.StateConfig(
        "trie-1m", n_entries=1_000_000, width=4, witness_b=2048
    )
    keys = list(tables.content)
    edit_rows = np.zeros((4, 7), np.int32)
    edit_rows[1] = [1, IPPROTO_TCP, 4242, 0, 0, 0, 2]
    add_rows = np.zeros((4, 7), np.int32)
    add_rows[1] = [1, IPPROTO_TCP, 53, 0, 0, 0, 1]
    ops = [
        statecheck.EditOp(kind="rules_edit", key=keys[17], rules=edit_rows),
        statecheck.EditOp(
            kind="key_add",
            key=LpmKey(24 + 32, 2, bytes([10, 200, 1, 0]) + bytes(12)),
            rules=add_rows,
        ),
        statecheck.EditOp(kind="key_delete", key=keys[99]),
    ]
    failure = statecheck.run_ops(tables.content, ops, cfg, seed=13)
    assert failure is None, failure


# --- ISSUE-6: compressed (ctrie) layout configs -----------------------------


@pytest.fixture
def inject_cskip_bug():
    jaxpath._INJECT_CSKIP_BUG = True
    try:
        yield
    finally:
        jaxpath._INJECT_CSKIP_BUG = False


@pytest.mark.parametrize("config,n_ops", [
    # ctrie re-runs in `make state-check --strict` — slow-marked for the
    # tier-1 budget; the cheaper ctrie-overlay sweep stays in tier-1
    pytest.param("ctrie", 3, marks=pytest.mark.slow),
    ("ctrie-overlay", 3),
])
def test_equivalence_ctrie(config, n_ops):
    """The full EditOp alphabet over the compressed layout: every
    incremental edit's resident (CTrieTables, d_max) must equal a cold
    device_ctrie rebuild bit-for-bit and classify like the oracle."""
    rep = statecheck.run_config(
        config, seed=4, n_ops=n_ops, shrink_on_failure=False
    )
    assert rep["ok"], rep["failure"]


@pytest.mark.slow
def test_equivalence_ctrie_fused():
    """The fused compressed (skip-node Pallas) walk config — this
    config's first sweep caught a real bug in the walk carry-forward
    (the per-tidx joined matrix is FULL, so no rules edit may skip the
    patch; the level walk's intersection shortcut does not transfer)."""
    rep = statecheck.run_config(
        "ctrie-fused", seed=0, n_ops=2, witness_b=96,
        shrink_on_failure=False,
    )
    assert rep["ok"], rep["failure"]


def _clean_ctrie():
    rng = np.random.default_rng(41)
    tables = testing.random_tables(rng, n_entries=60, width=4,
                                   v6_fraction=0.5)
    return jaxpath.device_ctrie(tables, pad=True)[0]


def test_check_ctrie_tables_clean():
    assert statecheck.check_ctrie_tables(_clean_ctrie()) == []


def test_check_ctrie_tables_flags_corruption():
    """Each contract class trips on a targeted corruption: skip bounds,
    child range, target bound, joined self-index, sentinel."""
    import jax.numpy as jnp

    cdev = _clean_ctrie()
    nodes = np.asarray(cdev.nodes).copy()
    nodes[0, 2] = 48  # skip_len > CPOP_MAX_SKIP
    viols = statecheck.check_ctrie_tables(
        cdev._replace(nodes=jnp.asarray(nodes))
    )
    assert any("CPOP_MAX_SKIP" in v for v in viols), viols

    nodes = np.asarray(cdev.nodes).copy()
    nodes[0, 0] = 2**31 - 1
    nodes[0, 4] = 0xFFFFFFFF  # child range shoots past the node array
    viols = statecheck.check_ctrie_tables(
        cdev._replace(nodes=jnp.asarray(nodes))
    )
    assert any("child range" in v for v in viols), viols

    joined = np.asarray(cdev.joined).copy()
    if joined.shape[0] > 2:
        joined[2, 0] = 9999  # self-index broken
        viols = statecheck.check_ctrie_tables(
            cdev._replace(joined=jnp.asarray(joined))
        )
        assert any("self-index" in v for v in viols), viols

    joined = np.asarray(cdev.joined).copy()
    joined[0, 3] = 7  # UNDEF sentinel must stay all-zero
    viols = statecheck.check_ctrie_tables(
        cdev._replace(joined=jnp.asarray(joined))
    )
    assert any("sentinel" in v for v in viols), viols


def test_injected_cskip_defect_caught(inject_cskip_bug):
    """The skip-node acceptance: under the zeroed-skip-bits defect the
    resident AND cold-rebuilt device state share the bug, so the raw
    compare stays green and the catch MUST come from classify
    divergence vs the CPU oracle — proving the equivalence engine's
    oracle half covers the skip-node path.  (The <= 3-op shrunk-repro
    bound runs in `make state-check`'s cskip acceptance; shrinking is
    skipped here to keep the tier-1 budget.)"""
    rep = statecheck.run_config(
        "ctrie", seed=0, n_ops=2, shrink_on_failure=False
    )
    assert not rep["ok"], "cskip defect not caught"
    assert rep["failure"]["phase"] in ("classify", "stats"), rep["failure"]


def test_backend_ctrie_invariant_hook_blocks_corruption():
    """INFW_CHECK_INVARIANTS routes the compressed layout through
    check_ctrie_tables at the install boundary."""
    from infw.backend.tpu import TpuClassifier

    rng = np.random.default_rng(53)
    tables = testing.random_tables(rng, n_entries=50, width=4,
                                   v6_fraction=0.5)
    clf = TpuClassifier(force_path="ctrie", interpret=True,
                        check_invariants=True)
    try:
        clf.load_tables(tables)  # clean install passes
        assert clf.active_path == "ctrie"
        viols = statecheck.check_ctrie_tables(clf._active[1][0])
        assert viols == []
    finally:
        clf.close()


# --- 10M-scale tier ---------------------------------------------------------


@pytest.mark.slow
def test_statecheck_ctrie_scale_tier():
    """The 10M-scale tier (ISSUE 6): clean_columns_fast generation ->
    vectorized cold build -> compressed production dispatch -> one
    1-key rules patch, with the patched resident state proven
    bit-identical to a cold device_ctrie rebuild and witness verdicts
    proven against the hash oracle.  INFW_SCALE_TEST_ENTRIES overrides
    the entry count (default 10M; needs ~50 GB RSS — set 2000000 on
    smaller hosts)."""
    import os
    import time

    from infw.backend.tpu import TpuClassifier
    from infw.compiler import IncrementalTables
    from infw import oracle

    n = int(os.environ.get("INFW_SCALE_TEST_ENTRIES", 10_000_000))
    rng = np.random.default_rng(61)
    cols = testing.clean_columns_fast(rng, n)
    t0 = time.perf_counter()
    it = IncrementalTables.from_columns(cols, rule_width=4)
    snap = it.snapshot()
    t_build = time.perf_counter() - t0
    assert t_build < 120.0, f"cold build took {t_build:.0f}s at {n} entries"
    clf = TpuClassifier(force_path="ctrie", interpret=True)
    try:
        clf.load_tables(snap)
        it.clear_dirty()
        assert clf.active_path == "ctrie"
        key = LpmKey(int(cols.prefix_len[7]), int(cols.ifindex[7]),
                     cols.ip[7].tobytes())
        rows = np.asarray(it.content[key]).copy()
        rows[1, 6] = 1 if rows[1, 6] == 2 else 2
        it.apply({key: rows})
        snap2 = it.snapshot()
        clf.load_tables(snap2, dirty_hint=it.peek_dirty())
        it.clear_dirty()
        assert clf._last_load[0] == "patch", clf._last_load
        cdev, d_max = clf._active[1]
        assert statecheck.check_ctrie_tables(cdev) == []
        clone = statecheck._cold_clone(snap2)
        fresh = jaxpath.device_ctrie(clone, clf._device, pad=True)
        assert fresh is not None and fresh[1] == d_max
        m = statecheck._first_mismatch(cdev, fresh[0])
        assert m is None, m
        batch = testing.random_batch_fast(rng, snap2, n_packets=2048)
        out = clf.classify(batch, apply_stats=False)
        ref = oracle.HashLpmOracle(snap2).classify(batch)
        np.testing.assert_array_equal(out.results, ref.results)
        np.testing.assert_array_equal(out.xdp, ref.xdp)
    finally:
        clf.close()


# --- ISSUE-9: batched multi-edit transaction configs ------------------------


def test_equivalence_txn():
    """Transaction mode: single-key ops buffer at txn_flush boundaries
    and apply as ONE folded flush (infw.txn.fold_ops) — every settled
    state must be bit-identical to a cold rebuild and oracle-exact
    against the per-op ground truth.  (The longer-horizon sweep incl.
    the compressed layout runs in `make state-check`; the tier-1 run
    keeps one fast config.)"""
    rep = statecheck.run_config(
        "txn", seed=4, n_ops=3, shrink_on_failure=False
    )
    assert rep["ok"], rep["failure"]


@pytest.mark.slow
def test_equivalence_txn_ctrie():
    rep = statecheck.run_config(
        "txn-ctrie", seed=4, n_ops=4, shrink_on_failure=False
    )
    assert rep["ok"], rep["failure"]


def test_txn_generator_emits_boundaries():
    _, ops = statecheck.build_case("txn", seed=3, n_ops=40)
    kinds = [op.kind for op in ops]
    assert statecheck.TXN_FLUSH in kinds
    # boundary records round-trip through the repro printer like any op
    b = next(op for op in ops if op.kind == statecheck.TXN_FLUSH)
    env = {"statecheck": statecheck, "LpmKey": LpmKey, "np": np}
    assert eval(b.code(), env).kind == statecheck.TXN_FLUSH


def test_txn_fold_defect_caught_by_ground_truth_oracle():
    """The minimal fold-defect case: delete + readd of a live key in
    ONE transaction.  With infw.txn._INJECT_FOLD_BUG the pair folds to
    a no-op — the updater, the resident device state AND the cold
    rebuild all keep the stale rules, so raw bit-identity cannot catch
    it; the per-op ground-truth oracle must (the cskip pattern)."""
    from infw import txn as txn_mod

    full, _ = statecheck.build_case("txn", seed=0, n_ops=0)
    keys = sorted(full, key=lambda k: (k.ingress_ifindex, k.ip_data))
    base = {k: full[k] for k in keys[:8]}  # small = fast compiles
    k = keys[0]
    rows = np.asarray(base[k]).copy()
    pop = np.nonzero(rows[:, 0])[0]
    assert len(pop), "fixture key has no populated rule row"
    rows[pop[0], 6] = 1 if rows[pop[0], 6] == 2 else 2  # flip the action
    ops = [
        statecheck.EditOp(kind="key_delete", key=k),
        statecheck.EditOp(kind="key_add", key=k, rules=rows),
    ]
    assert statecheck.run_ops(base, ops, "txn", seed=0,
                              witness_b=64) is None
    txn_mod._INJECT_FOLD_BUG = True
    try:
        f = statecheck.run_ops(base, ops, "txn", seed=0, witness_b=64)
    finally:
        txn_mod._INJECT_FOLD_BUG = False
    assert f is not None, "injected fold defect not caught"
    assert f.phase in ("classify", "stats"), f
