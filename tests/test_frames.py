"""Vectorized frames path: parse_frames_buf must be bit-exact with the
scalar parse_frame (same kernel.c quirks), build_frames_bulk must be its
inverse for every field the classifier consumes, and the v2 columnar
frames file must round-trip.  These are the replay-scale equivalents of
the reference's gopacket decode checks."""
import os
import struct

import numpy as np
import pytest

from infw import testing
from infw.daemon import (
    read_frames_any,
    write_frames_file,
    write_frames_file_v2,
)
from infw.obs.pcap import (
    FramesBuf,
    build_frame,
    build_frames_bulk,
    parse_frames,
    parse_frames_buf,
)


def _random_frames(rng, n=500):
    """Adversarial frame mix: valid v4/v6 × all L4s, truncations at every
    header boundary, unknown ethertypes and protocols, empty frames."""
    frames = []
    for _ in range(n):
        r = rng.random()
        if r < 0.08:
            frames.append(bytes(rng.integers(0, 256, rng.integers(0, 14),
                                             dtype=np.uint8)))
            continue
        proto = int(rng.choice([6, 17, 132, 1, 58, 47, 0, 255]))
        v6 = rng.random() < 0.4
        src = (
            f"{rng.integers(1,255)}.{rng.integers(0,255)}."
            f"{rng.integers(0,255)}.{rng.integers(1,255)}"
            if not v6
            else "2001:db8::%x" % rng.integers(1, 1 << 16)
        )
        f = build_frame(
            src, "10.0.0.1" if not v6 else "2001:db8::1", proto,
            src_port=int(rng.integers(0, 65536)),
            dst_port=int(rng.integers(0, 65536)),
            icmp_type=int(rng.integers(0, 256)),
            icmp_code=int(rng.integers(0, 4)),
            payload=bytes(rng.integers(0, 256, rng.integers(0, 32),
                                       dtype=np.uint8)),
        )
        if rng.random() < 0.25:
            f = f[: rng.integers(0, len(f) + 1)]  # truncate anywhere
        if rng.random() < 0.05:
            f = f[:12] + b"\x08\x06" + f[14:]  # ARP ethertype
        frames.append(f)
    return frames


def test_parse_frames_buf_matches_scalar():
    rng = np.random.default_rng(11)
    frames = _random_frames(rng)
    ifx = rng.integers(1, 1 << 20, len(frames))
    want = parse_frames(frames, list(ifx))
    got = parse_frames_buf(FramesBuf.from_frames(frames, ifx))
    for field in ("kind", "l4_ok", "ifindex", "ip_words", "proto",
                  "dst_port", "icmp_type", "icmp_code", "pkt_len"):
        np.testing.assert_array_equal(
            getattr(got, field), getattr(want, field), err_msg=field
        )


_FIELDS = ("kind", "l4_ok", "ifindex", "ip_words", "proto",
           "dst_port", "icmp_type", "icmp_code", "pkt_len")


def _native_available() -> bool:
    """Probe once: only a missing/broken toolchain skips the native
    differential tests — a regression in the parser itself must FAIL."""
    try:
        from infw.backend.cpu_ref import load_library

        load_library()
        return True
    except Exception:
        return False


_HAS_NATIVE = _native_available()
needs_native = pytest.mark.skipif(
    not _HAS_NATIVE, reason="native toolchain unavailable"
)


@needs_native
def test_parse_native_and_numpy_agree():
    """Both parse_frames_buf implementations against the scalar on the
    adversarial mix — the native path must not diverge from NumPy on any
    truncation/ethertype/protocol edge."""
    from infw.obs.pcap import _parse_frames_buf_native, _parse_frames_buf_np

    rng = np.random.default_rng(12)
    frames = _random_frames(rng, n=2000)
    ifx = rng.integers(1, 1 << 20, len(frames))
    fb = FramesBuf.from_frames(frames, ifx)
    want = parse_frames(frames, list(ifx))
    got_np = _parse_frames_buf_np(fb)
    for field in _FIELDS:
        np.testing.assert_array_equal(
            getattr(got_np, field), getattr(want, field), err_msg=f"np:{field}"
        )
    got_nat = _parse_frames_buf_native(fb)
    for field in _FIELDS:
        np.testing.assert_array_equal(
            getattr(got_nat, field), getattr(want, field), err_msg=f"native:{field}"
        )


@needs_native
def test_parse_native_threaded_matches_numpy():
    """Above the 64K single-thread threshold the native parser shards
    across threads; shard boundaries must not corrupt any row."""
    from infw.obs.pcap import _parse_frames_buf_native, _parse_frames_buf_np

    rng = np.random.default_rng(13)
    tables = testing.random_tables_fast(rng, n_entries=200, width=8)
    batch = testing.random_batch_fast(rng, tables, n_packets=100_000)
    fb = build_frames_bulk(
        batch.kind, batch.ip_words, batch.proto, batch.dst_port,
        batch.icmp_type, batch.icmp_code, l4_ok=batch.l4_ok,
    )
    fb.ifindex = np.asarray(batch.ifindex, np.uint32)
    want = _parse_frames_buf_np(fb)
    got = _parse_frames_buf_native(fb)
    for field in _FIELDS:
        np.testing.assert_array_equal(
            getattr(got, field), getattr(want, field), err_msg=field
        )


def _subset_cases(batch):
    kinds = np.asarray(batch.kind)
    rng = np.random.default_rng(3)
    return {
        "non-v6": np.nonzero(kinds != 2)[0],
        "v6": np.nonzero(kinds == 2)[0],
        "mixed-shuffled": rng.permutation(len(batch)),
    }


@needs_native
def test_pack_wire_subset_native_matches_fallback():
    """The fused native take+pack must emit byte-identical wire arrays
    and the same (compact, v4_only) decisions as the composed NumPy path
    on every subset shape the daemon dispatches."""
    import infw.packets as packets

    rng = np.random.default_rng(14)
    tables = testing.random_tables_fast(rng, n_entries=300, width=8)
    batch = testing.random_batch_fast(rng, tables, n_packets=120_000)
    for name, idx in _subset_cases(batch).items():
        if not len(idx):
            continue
        got_wire, got_v4 = batch._pack_wire_subset_native(
            np.ascontiguousarray(idx, np.int64)
        )
        sub = batch.take(idx)
        compact = sub.is_v4_compactable()
        want_wire = sub.pack_wire_v4() if compact else sub.pack_wire()
        want_v4 = not bool((np.asarray(sub.kind) == 2).any())
        assert got_wire.shape == want_wire.shape, name
        np.testing.assert_array_equal(got_wire, want_wire, err_msg=name)
        assert got_v4 == want_v4, name


def test_pack_wire_subset_fallback_when_native_off(monkeypatch):
    import infw.packets as packets

    monkeypatch.setattr(packets, "_native_pack_unavailable", True)
    rng = np.random.default_rng(15)
    tables = testing.random_tables_fast(rng, n_entries=50, width=4)
    batch = testing.random_batch_fast(rng, tables, n_packets=500)
    idx = np.arange(len(batch))
    wire, v4_only = batch.pack_wire_subset(idx)
    sub = batch.take(idx)
    want = sub.pack_wire_v4() if sub.is_v4_compactable() else sub.pack_wire()
    np.testing.assert_array_equal(wire, want)


def test_parse_frames_buf_empty():
    got = parse_frames_buf(FramesBuf.from_frames([], []))
    assert len(got) == 0


def test_framesbuf_getitem():
    frames = [b"", b"abc", b"0123456789"]
    fb = FramesBuf.from_frames(frames, 7)
    assert [fb[i] for i in range(3)] == frames
    assert len(fb) == 3


def test_build_frames_bulk_roundtrip():
    """Synth → parse recovers every classifier-relevant field."""
    rng = np.random.default_rng(5)
    tables = testing.random_tables_fast(rng, n_entries=200, width=8)
    batch = testing.random_batch_fast(rng, tables, n_packets=5000)
    fb = build_frames_bulk(
        batch.kind, batch.ip_words, batch.proto, batch.dst_port,
        batch.icmp_type, batch.icmp_code, l4_ok=batch.l4_ok,
    )
    fb.ifindex = np.asarray(batch.ifindex, np.uint32)
    got = parse_frames_buf(fb)
    np.testing.assert_array_equal(got.kind, batch.kind)
    np.testing.assert_array_equal(got.ifindex, batch.ifindex)
    known = np.isin(batch.proto, [6, 17, 132, 1, 58])
    l4ok = (batch.l4_ok != 0) & known & np.isin(batch.kind, [1, 2])
    np.testing.assert_array_equal(got.l4_ok, l4ok.astype(np.int32))
    is_ip = np.isin(batch.kind, [1, 2])
    np.testing.assert_array_equal(got.ip_words[is_ip], batch.ip_words[is_ip])
    np.testing.assert_array_equal(got.proto[is_ip], batch.proto[is_ip])
    tr = l4ok & np.isin(batch.proto, [6, 17, 132])
    np.testing.assert_array_equal(got.dst_port[tr], batch.dst_port[tr])
    ic = l4ok & np.isin(batch.proto, [1, 58])
    np.testing.assert_array_equal(got.icmp_type[ic], batch.icmp_type[ic])
    np.testing.assert_array_equal(got.icmp_code[ic], batch.icmp_code[ic])


def test_frames_file_v2_roundtrip(tmp_path):
    rng = np.random.default_rng(3)
    frames = _random_frames(rng, n=64)
    ifx = rng.integers(1, 1 << 20, len(frames))
    fb = FramesBuf.from_frames(frames, ifx)
    p = os.path.join(tmp_path, "x.frames")
    write_frames_file_v2(p, fb)
    got = read_frames_any(p)
    np.testing.assert_array_equal(got.ifindex, fb.ifindex)
    np.testing.assert_array_equal(got.lengths, fb.lengths)
    np.testing.assert_array_equal(got.buf, fb.buf)
    assert [got[i] for i in range(len(got))] == frames


def test_frames_file_v1_via_read_any(tmp_path):
    frames = [build_frame("1.2.3.4", "10.0.0.1", 6, 1, 80), b"xx"]
    p = os.path.join(tmp_path, "x.frames")
    write_frames_file(p, frames, [4, 70000])
    fb = read_frames_any(p)
    assert [fb[i] for i in range(2)] == frames
    assert fb.ifindex.tolist() == [4, 70000]


def test_read_frames_any_rejects_garbage(tmp_path):
    p = os.path.join(tmp_path, "bad.frames")
    with open(p, "wb") as f:
        f.write(b"not a frames file at all")
    with pytest.raises(ValueError):
        read_frames_any(p)


def test_read_frames_any_bounds_v2_count(tmp_path):
    """A corrupt v2 header whose u32 count is near 2^32 must be rejected
    BEFORE any allocation is attempted (round-3 advisor finding): the
    count is bounded against the file size, not trusted."""
    from infw.daemon import _FRAMES_MAGIC2

    p = os.path.join(tmp_path, "huge.frames")
    with open(p, "wb") as f:
        f.write(_FRAMES_MAGIC2)
        f.write(struct.pack("<I", 0xFFFFFF00))
        f.write(b"\x00" * 64)  # far too small for the declared count
    with pytest.raises(ValueError, match="exceeds file size"):
        read_frames_any(p)


def test_parse_frames_buf_tiny_buffer():
    """A file of only tiny/malformed frames (total buffer < 16B) must
    parse, not crash the ingest tick."""
    fb = FramesBuf.from_frames([b"\x01\x02", b""], [3, 4])
    got = parse_frames_buf(fb)
    assert got.kind.tolist() == [0, 0]  # KIND_MALFORMED
    assert got.pkt_len.tolist() == [2, 0]
