"""T1: port/range parsing semantics (reference pkg/utils/utils.go)."""
import pytest

from infw import portutils
from infw.spec import IngressNodeFirewallProtoRule as Proto


def test_int_port_is_not_range():
    assert not portutils.is_range(Proto(ports=80))


def test_string_single_port_is_not_range():
    assert not portutils.is_range(Proto(ports="80"))


def test_string_range_detected():
    assert portutils.is_range(Proto(ports="80-100"))


def test_get_port_int():
    assert portutils.get_port(Proto(ports=80)) == 80


def test_get_port_string():
    assert portutils.get_port(Proto(ports="8080")) == 8080


def test_get_port_rejects_range():
    with pytest.raises(portutils.PortParseError):
        portutils.get_port(Proto(ports="80-100"))


def test_get_port_rejects_zero():
    with pytest.raises(portutils.PortParseError):
        portutils.get_port(Proto(ports=0))


def test_get_port_rejects_over_uint16():
    with pytest.raises(portutils.PortParseError):
        portutils.get_port(Proto(ports=65536))


def test_get_port_rejects_garbage():
    with pytest.raises(portutils.PortParseError):
        portutils.get_port(Proto(ports="http"))


def test_get_range_ok():
    assert portutils.get_range(Proto(ports="80-100")) == (80, 100)


def test_get_range_rejects_non_range():
    with pytest.raises(portutils.PortParseError):
        portutils.get_range(Proto(ports=80))


def test_get_range_rejects_start_gt_end():
    with pytest.raises(portutils.PortParseError):
        portutils.get_range(Proto(ports="100-80"))


def test_get_range_rejects_equal():
    with pytest.raises(portutils.PortParseError):
        portutils.get_range(Proto(ports="80-80"))


def test_get_range_rejects_start_zero():
    with pytest.raises(portutils.PortParseError):
        portutils.get_range(Proto(ports="0-80"))


def test_get_range_rejects_bad_end():
    with pytest.raises(portutils.PortParseError):
        portutils.get_range(Proto(ports="80-lots"))
