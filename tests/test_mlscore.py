"""MXU anomaly-scoring tier (ISSUE-14): quantized inference kernels vs
the numpy oracle, shadow/enforce policy semantics (incl. the failsafe
precedence proof), versioned model artifacts + hot swap, labeled
loadgen manifests, and the statecheck mlscore configs.

Tier-1 keeps the cheap oracle/policy/artifact/label tests plus one
small device-kernel parity test; the jit-heavy classifier-path,
cross-path-identity and statecheck sweeps are slow-marked and run in
``make test``, ``make state-check`` (mlscore configs + the mlquant
acceptance) and ``make mlscore-bench`` (oracle + detection +
retention + steady-state gates).
"""
import json
import os
import sys

import numpy as np
import pytest

from infw import oracle, testing
from infw.constants import ALLOW, DENY
from infw.kernels import mxu_score as M
from infw.kernels.jaxpath import TCP_ACK, TCP_SYN
from infw.kernels.mxu_score import (
    SCORE_FEATURES,
    HostScoreModel,
    ScoreSpec,
    ScoreState,
    clamp_stress_model,
    default_model,
    failsafe_lane_mask_np,
    validate_model,
    zero_state_host,
    zero_tparams,
)
from infw.mlscore import (
    AnomalyTier,
    ScoreSnapshot,
    load_model,
    save_model,
    summarize_snapshot,
)

#: one small spec shared across tests so the jitted update compiles once
SPEC = ScoreSpec.make(trees=4, depth=3, slots=32, ways=2, cms_depth=2,
                      cms_width=64, sat=511, hidden=4)


def _tables(n=48, seed=3, width=8):
    rng = np.random.default_rng(seed)
    return testing.random_tables(rng, n_entries=n, width=width)


def _traffic(tables, seed, b=48, syn_frac=0.3):
    rng = np.random.default_rng(seed)
    batch = testing.random_batch(rng, tables, b)
    batch.tcp_flags = np.where(
        rng.random(b) < syn_frac, TCP_SYN, TCP_ACK
    ).astype(np.int32)
    res = (
        rng.integers(0, 3, b).astype(np.uint32)
        | (rng.integers(1, 9, b).astype(np.uint32) << 8)
    )
    return batch, batch.pack_wire(), res


def _device_state(spec):
    import jax

    return ScoreState(*(jax.device_put(a) for a in zero_state_host(spec)))


def _model_operands(spec, model):
    import jax

    return M.model_device(model), jax.device_put(zero_tparams(spec))


# --- spec / model validation -------------------------------------------------


def test_spec_validation():
    assert ScoreSpec.make(slots=100).slots == 128   # pow2 bucketing
    assert ScoreSpec.make(cms_width=100).cms_width == 128
    assert ScoreSpec.make(depth=3).leaves == 8
    for kw in (dict(trees=0), dict(trees=17), dict(depth=0),
               dict(depth=7), dict(ways=0), dict(ways=9),
               dict(cms_depth=0), dict(sat=0), dict(hidden=-1),
               dict(hidden=65), dict(max_tenants=0)):
        with pytest.raises(ValueError):
            ScoreSpec.make(**kw)


def test_model_validation_contract():
    m = default_model(SPEC)
    validate_model(m)  # clean passes
    with pytest.raises(ValueError, match="fidx"):
        validate_model(m._replace(fidx=m.fidx.astype(np.int64)))
    bad = m.fidx.copy()
    bad[0, 0] = SCORE_FEATURES
    with pytest.raises(ValueError, match="out of range"):
        validate_model(m._replace(fidx=bad))
    with pytest.raises(ValueError, match="qshift"):
        validate_model(m._replace(qshift=np.asarray([40, 0], np.int32)))
    with pytest.raises(ValueError, match="leaf"):
        validate_model(m._replace(leaf=m.leaf[:-1]))


def test_clamp_stress_model_requires_head():
    with pytest.raises(ValueError):
        clamp_stress_model(ScoreSpec.make(hidden=0))


# --- quantized inference semantics (pure numpy, no jit) ----------------------


def test_forest_inference_hand_case():
    """One hand-built feature row through the default forest: the
    synflood tree (tree 0) fires iff syn_frac>=192 AND pkts>=24 AND the
    lane is a pure SYN — the leaf one-hot matmul semantics pinned
    without any state machinery."""
    spec = ScoreSpec.make(hidden=0)
    host = HostScoreModel(spec, default_model(spec))
    row = np.zeros((1, SCORE_FEATURES), np.int32)
    row[0, 12] = 256   # syn_frac_q8
    row[0, 0] = 30     # pkts
    row[0, 6] = 1      # pure-SYN lane
    assert host.infer(row)[0] == 120
    row[0, 6] = 0      # same source stats, non-SYN lane
    assert host.infer(row)[0] == 0
    row[0, 6], row[0, 0] = 1, 10   # source too small
    assert host.infer(row)[0] == 0


def test_mlp_head_requant_clamp_semantics():
    """The fixed-point head: features shift+clamp to int8, hidden layer
    accumulates int32, requantizes with the [0,127] clamp — the exact
    arithmetic the mlquant defect corrupts."""
    spec = SPEC
    m = clamp_stress_model(spec)
    host = HostScoreModel(spec, m)
    row = np.zeros((1, SCORE_FEATURES), np.int32)
    row[0, 8] = 1500   # pkt_len: clamps to 127 at input, * 3 = 381 -> 127
    assert host.infer(row)[0] == 127
    row[0, 8] = 10     # 10 * 3 = 30, under the clamp
    assert host.infer(row)[0] == 30


def test_default_model_detects_synthetic_attacks():
    """Host-model detection smoke on the seeded labeled traces — the
    cheap (numpy-only) half of the bench_mlscore quality gate.  Trace
    length matches bench_mlscore (60 chunks): recall is measured over
    EVERY attack record including the pre-detection onset window, so a
    short trace under-weights steady state and fails the gate even
    though the detector is fine."""
    tables = testing.random_tables_fast(
        np.random.default_rng(5), n_entries=2000, width=8,
        v6_fraction=0.4, ifindexes=(2, 3),
    )
    spec = ScoreSpec.make()
    bs = 256
    for mode in ("synflood", "portscan"):
        trace, meta = testing.attack_trace_batch(
            np.random.default_rng(1400), tables, bs * 60, mode=mode,
            chunk_packets=bs,
        )
        host = HostScoreModel(spec, default_model(spec))
        flags = np.asarray(trace.tcp_flags, np.int32)
        truth = np.asarray(meta["attack_mask"], bool)
        pred = np.zeros(len(trace), bool)
        for lo in range(0, len(trace), bs):
            sub = np.arange(lo, lo + bs, dtype=np.int64)
            w, _v4 = trace.pack_wire_subset(sub)
            _s, anom, _r = host.update(
                w, np.full(len(sub), ALLOW, np.uint32), None, flags[sub]
            )
            pred[lo : lo + bs] = anom
        tp = int((pred & truth).sum())
        fp = int((pred & ~truth).sum())
        fn = int((~pred & truth).sum())
        assert tp / max(tp + fp, 1) >= 0.95, (mode, tp, fp)
        assert tp / max(tp + fn, 1) >= 0.9, (mode, tp, fn)


# --- device kernel vs host oracle (one small jit compile) --------------------


def test_score_kernel_matches_model_bit_exact():
    """Device update vs HostScoreModel over several admissions with
    duplicate sources, LRU churn (tiny table) and the clamp-stressed
    MLP head: every state tensor, per-lane score, anomaly flag and
    policy verdict must match bit for bit."""
    import jax

    model = clamp_stress_model(SPEC)
    host = HostScoreModel(SPEC, model, zero_tparams(SPEC))
    st = _device_state(SPEC)
    mdev, tpd = _model_operands(SPEC, model)
    fn = M.jitted_score_update(SPEC)
    tables = _tables()
    for i in range(5):
        batch, wire, res = _traffic(tables, 100 + (i % 2), b=48)
        st, score, anom, res_out = fn(
            st, mdev, tpd,
            jax.device_put(np.ascontiguousarray(wire, np.uint32)),
            jax.device_put(np.zeros(48, np.int32)),
            jax.device_put(batch.tcp_flags),
            jax.device_put(res),
        )
        hs, ha, hr = host.update(wire, res, None, batch.tcp_flags)
        assert np.array_equal(np.asarray(score), hs), i
        assert np.array_equal(np.asarray(anom), ha), i
        assert np.array_equal(np.asarray(res_out), hr), i
        cols = {k: np.asarray(getattr(st, k)) for k in st._fields}
        for k, want in host.columns().items():
            assert np.array_equal(cols[k], want), (i, k)


def test_mlquant_defect_diverges_from_model():
    """The injected mlquant defect (device drops the requant clamp)
    must split device from model on clamp-stressed traffic — the
    statecheck acceptance's catch surface."""
    import jax

    model = clamp_stress_model(SPEC)
    tables = _tables()
    batch, wire, res = _traffic(tables, 7)
    M._INJECT_MLQUANT_BUG = True
    M.jitted_score_update.cache_clear()
    try:
        fn = M.jitted_score_update(SPEC)
        st = _device_state(SPEC)
        mdev, tpd = _model_operands(SPEC, model)
        host = HostScoreModel(SPEC, model, zero_tparams(SPEC))
        st, score, _a, _r = fn(
            st, mdev, tpd,
            jax.device_put(np.ascontiguousarray(wire, np.uint32)),
            jax.device_put(np.zeros(len(batch), np.int32)),
            jax.device_put(batch.tcp_flags), jax.device_put(res),
        )
        hs, _ha, _hr = host.update(wire, res, None, batch.tcp_flags)
        assert not np.array_equal(np.asarray(score), hs)
    finally:
        M._INJECT_MLQUANT_BUG = False
        M.jitted_score_update.cache_clear()


# --- policy: enforce semantics + the failsafe precedence proof ---------------


def test_enforce_rewrite_semantics():
    """Enforce rewrites over-threshold Allow lanes to Deny (ruleId 0),
    keeps existing rule Denies (their ruleId survives), and shadow mode
    never touches anything."""
    tables = _tables()
    batch, wire, _ = _traffic(tables, 21, b=32)
    res = np.full(32, ALLOW, np.uint32)
    res[:8] = (5 << 8) | DENY  # existing rule denies keep their ruleId
    # everything anomalous
    tp = zero_tparams(SPEC, threshold=-(10 ** 6), enforce=True)
    host = HostScoreModel(SPEC, clamp_stress_model(SPEC), tp)
    _s, anom, out = host.update(wire, res, None, batch.tcp_flags)
    elig = (batch.kind == 1) | (batch.kind == 2)
    fs = failsafe_lane_mask_np(batch.proto, batch.dst_port)
    assert (out[:8] == res[:8]).all()          # rule denies untouched
    lanes = elig & ~fs
    lanes[:8] = False
    assert (out[lanes] == M.ANOMALY_DENY_RESULT).all()
    assert (out[~elig] == res[~elig]).all()    # ineligible untouched
    # shadow: same state trajectory, verdicts untouched
    host2 = HostScoreModel(SPEC, clamp_stress_model(SPEC),
                           zero_tparams(SPEC, threshold=-(10 ** 6)))
    _s2, anom2, out2 = host2.update(wire, res, None, batch.tcp_flags)
    assert np.array_equal(out2, res)
    assert np.array_equal(anom, anom2)


def test_failsafe_precedence_proof_backed():
    """The proof-backed failsafe test: (1) the analyzer's coverage
    proof (analysis/rules.py failsafe-violation over the SAME
    infw.failsaferules port list) certifies the base ruleset reaches no
    failsafe Deny; (2) with an everything-is-anomalous enforcing model,
    a witness sweep over EVERY failsafe cell still serves the rule
    verdict — enforcement can never manufacture the violation the
    proof excluded."""
    from infw import failsaferules
    from infw.analysis import rules as rules_mod
    from infw.compiler import LpmKey, compile_tables_from_content

    # an allow-everything base table: one /0 catch-all rule (proto 0 =
    # protocol-unset, kernel.c:254-257) — the coverage proof must be
    # clean on it
    rules = np.zeros((4, 7), np.int32)
    rules[1] = [1, 0, 0, 0, 0, 0, ALLOW]
    content = {LpmKey(32, 2, bytes(16)): rules}
    tables = compile_tables_from_content(content, rule_width=4)
    findings = rules_mod.analyze_tables(tables)
    assert not [
        f for f in findings if f.check == "failsafe-violation"
    ], "coverage proof must certify the base table"
    # witness sweep: one lane per failsafe cell + one non-failsafe lane
    cells = [(6, fs.port) for fs in failsaferules.get_tcp()]
    cells += [(17, fs.port) for fs in failsaferules.get_udp()]
    cells.append((6, 8080))  # the control lane: MUST be rewritten
    b = len(cells)
    batch = testing.random_batch(np.random.default_rng(2), tables, b)
    batch.kind[:] = 1
    batch.ip_words[:, 1:] = 0
    batch.ifindex[:] = 2
    batch.l4_ok[:] = 1
    batch.proto[:] = [p for p, _ in cells]
    batch.dst_port[:] = [pt for _, pt in cells]
    batch.icmp_type[:] = 0
    batch.icmp_code[:] = 0
    batch.tcp_flags = np.full(b, TCP_ACK, np.int32)
    ref = oracle.classify(tables, batch)
    assert ((ref.results & 0xFF) == ALLOW).all()
    tp = zero_tparams(SPEC, threshold=-(10 ** 6), enforce=True)
    host = HostScoreModel(SPEC, clamp_stress_model(SPEC), tp)
    _s, _a, out = host.update(
        batch.pack_wire(), ref.results, None, batch.tcp_flags
    )
    assert np.array_equal(out[:-1], ref.results[:-1]), (
        "enforce rewrote a failsafe cell"
    )
    assert out[-1] == M.ANOMALY_DENY_RESULT, (
        "the non-failsafe control lane must be rewritten"
    )


# --- versioned artifacts -----------------------------------------------------


def test_model_artifact_round_trip(tmp_path):
    m = clamp_stress_model(SPEC)
    path = str(tmp_path / "m1.npz")
    mpath = save_model(m, path, version="v7")
    assert os.path.exists(path) and mpath == path + ".json"
    with open(mpath) as f:
        manifest = json.load(f)
    assert manifest["version"] == "v7"
    assert manifest["spec"]["slots"] == SPEC.slots
    loaded = load_model(path)
    assert loaded.spec == SPEC and loaded.version == "v7"
    for k, a in m.arrays().items():
        assert np.array_equal(a, getattr(loaded, k)), k


def test_model_artifact_rejects_corruption(tmp_path):
    m = default_model(SPEC)
    path = str(tmp_path / "m2.npz")
    save_model(m, path)
    with open(path, "ab") as f:
        f.write(b"x")  # checksum breaks
    with pytest.raises(ValueError, match="checksum"):
        load_model(path)
    os.unlink(path + ".json")
    with pytest.raises(ValueError, match="manifest"):
        load_model(path)


# --- the AnomalyTier ---------------------------------------------------------


def test_tier_drain_exactly_once_and_records():
    tier = AnomalyTier(SPEC, model=clamp_stress_model(SPEC),
                       threshold=-(10 ** 6))

    class Ring:
        def __init__(self):
            self.recs = []

        def push(self, r):
            self.recs.append(r)

    ring = Ring()
    tier.attach_ring(ring)
    tables = _tables()
    batch, wire, res = _traffic(tables, 31, b=32)
    tier.update(wire, res, tflags_np=batch.tcp_flags)
    recs = tier.drain(force=True)
    assert len(recs) == 1 and recs[0].seq == 1
    assert tier.drain_seq == 1
    [t0] = [t for t in recs[0].tenants if t["tenant"] == 0]
    assert t0["scored"] > 0 and t0["anom"] > 0 and not t0["enforce"]
    assert recs[0].top, "anomalous sources must surface"
    lines = recs[0].lines()
    assert lines[0].startswith("anomaly-verdict seq=1")
    assert any("anomalous-src" in ln for ln in lines)
    assert ring.recs == recs
    # window reset: tstat + anomhits clear, rates persist
    cols = tier.columns()
    assert cols["tstat"].sum() == 0
    assert cols["scols"][:, 6].sum() == 0
    assert cols["scols"][:, 0].sum() > 0
    # drain again: seq advances, empty window
    recs2 = tier.drain(force=True)
    assert recs2[0].seq == 2 and not recs2[0].tenants


def test_tier_policy_knobs_and_track_guard():
    tier = AnomalyTier(SPEC)
    tier.set_threshold(5, tenant=0)
    tier.set_mode("enforce", tenant=0)
    tp = tier.tparams()
    assert tp[0, 0] == 5 and tp[0, 1] == 1
    with pytest.raises(ValueError):
        AnomalyTier(SPEC, mode="enforce", track_model=True)
    t2 = AnomalyTier(SPEC, track_model=True)
    with pytest.raises(ValueError):
        t2.set_mode("enforce")
    with pytest.raises(ValueError):
        AnomalyTier(SPEC, mode="blocky")


def test_tier_model_hot_swap_fires_hook():
    tier = AnomalyTier(SPEC, model=default_model(SPEC))
    fired = []
    tier.on_swap = lambda: fired.append(1)
    tier.swap_model(clamp_stress_model(SPEC), version="v2")
    assert fired == [1]
    assert tier.model_version == "v2"
    assert tier.counter_values()["mlscore_model_swaps_total"] == 1
    # geometry change is a rebuild, not a swap
    other = default_model(ScoreSpec.make(slots=SPEC.slots * 2,
                                         hidden=SPEC.hidden))
    with pytest.raises(ValueError, match="geometry"):
        tier.swap_model(other)


def test_summarize_snapshot_orders_sources():
    skeys = np.zeros((8, 6), np.uint32)
    scols = np.zeros((8, 8), np.int32)
    skeys[3] = [0, 0x01020304, 0, 0, 0, 1]
    skeys[5] = [0, 0x05060708, 0, 0, 0, 1]
    scols[3, 0], scols[3, 6] = 40, 9
    scols[5, 0], scols[5, 6] = 10, 17
    tstat = np.zeros((1, 4), np.int32)
    tstat[0] = [64, 26, 0, 240]
    rec = summarize_snapshot(ScoreSnapshot(
        seq=4, admissions=12, skeys=skeys, scols=scols, tstat=tstat,
        tparams=zero_tparams(ScoreSpec.make(max_tenants=1)),
    ))
    assert rec.top[0]["src"] == "5.6.7.8"  # most anomaly hits first
    assert rec.top[0]["anom_hits"] == 17
    assert rec.top[1]["src"] == "1.2.3.4"
    assert rec.tenants[0]["max_score"] == 240


# --- loadgen ground-truth labels (ISSUE-14 satellite) ------------------------


def _loadgen():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import loadgen

    return loadgen


def test_loadgen_label_round_trip_and_determinism(capsys):
    lg = _loadgen()
    args = ["--rate", "100000", "--n", "4096", "--out", "/nonexistent",
            "--attack", "portscan", "--file-packets", "512",
            "--seed", "13", "--dry-run"]
    assert lg.main(args) == 0
    first = capsys.readouterr().out
    assert lg.main(args) == 0
    assert capsys.readouterr().out == first  # byte-deterministic
    man = json.loads(first.splitlines()[0])
    lab = man["labels"]
    assert lab["onset_record"] == man["attack_start_packet"] // 512
    assert len(lab["record_bitmaps_hex"]) == man["files"]
    mask = lg.decode_attack_labels(
        lab["record_bitmaps_hex"], man["n"], man["file_packets"]
    )
    assert int(mask.sum()) == man["attack_packets"]
    assert not mask[: man["attack_start_packet"]].any()
    assert lg.encode_attack_labels(mask, 512) == lab["record_bitmaps_hex"]
    ids = lg.attack_lane_src_ids(mask, lab["attack_src_stride"])
    assert (ids[mask] >= 0).all() and (ids[~mask] == -1).all()
    assert lab["attack_src_stride"] == 1  # portscan is single-source


def test_loadgen_ring_manifest_carries_labels(capsys):
    lg = _loadgen()
    assert lg.main(["--rate", "100000", "--n", "2048", "--ring", "/tmp/x",
                    "--attack", "synflood", "--file-packets", "256",
                    "--seed", "5", "--dry-run"]) == 0
    man = json.loads(capsys.readouterr().out.splitlines()[0])
    assert man["mode"] == "ring" and "labels" in man
    assert len(man["labels"]["record_bitmaps_hex"]) == man["records"]


# --- daemon control plane ----------------------------------------------------


def test_daemon_mlscore_flag_validation(tmp_path):
    from infw.daemon import main as daemon_main

    base = ["--state-dir", str(tmp_path), "--node-name", "n"]
    with pytest.raises(SystemExit):
        daemon_main(base + ["--backend", "cpu", "--mlscore"])
    with pytest.raises(SystemExit):  # enforce without the tier
        daemon_main(base + ["--backend", "tpu",
                            "--mlscore-mode", "enforce"])
    with pytest.raises(SystemExit):  # missing artifact fails the launch
        daemon_main(base + ["--backend", "tpu", "--mlscore",
                            str(tmp_path / "missing.npz")])
    with pytest.raises(SystemExit):  # bad mode choice
        daemon_main(base + ["--backend", "tpu", "--mlscore",
                            "--mlscore-mode", "blocky"])


def test_entrypoints_registered():
    from infw.kernels import kernel_entrypoints

    names = {e.name for e in kernel_entrypoints()}
    assert "mlscore/score-update" in names
    assert "classify-wire/resident-mlscore-fused" in names
    by_name = {e.name: e for e in kernel_entrypoints()}
    assert by_name["mlscore/score-update"].donate == (0,)
    assert by_name["classify-wire/resident-mlscore-fused"].donate == (
        0, 3, 4
    )


# --- classifier integration (jit-heavy: make test / mlscore-bench) -----------


@pytest.mark.slow
def test_cross_path_score_identity_shadow_and_enforce():
    """The ISSUE-14 cross-path gate: fused-resident scoring vs the
    multi-dispatch follow-on launch must produce bit-identical scores,
    state and (in enforce mode) identical rewritten verdicts + flow
    columns on the same trace."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig

    tables = _tables(n=48)
    model = clamp_stress_model(SPEC)
    for mode, thr in (("shadow", None), ("enforce", -1000)):
        clf_res = TpuClassifier(
            force_path="trie", flow_table=FlowConfig.make(entries=1024),
            resident=True, mlscore=SPEC, mlscore_model=model,
            mlscore_mode=mode,
        )
        clf_mul = TpuClassifier(
            force_path="trie", flow_table=FlowConfig.make(entries=1024),
            mlscore=SPEC, mlscore_model=model, mlscore_mode=mode,
        )
        for c in (clf_res, clf_mul):
            c.load_tables(tables)
            c.mlscore.set_keep_masks(8)
            if thr is not None:
                c.mlscore.set_threshold(thr)
        for i in range(4):
            batch, _w, _r = _traffic(tables, 200 + i, b=64)
            w, v4 = batch.pack_wire_subset(np.arange(64, dtype=np.int64))
            o1 = clf_res.classify_prepared(
                clf_res.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
                apply_stats=False,
            ).result()
            o2 = clf_mul.classify_prepared(
                clf_mul.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
                apply_stats=False,
            ).result()
            assert np.array_equal(o1.results, o2.results), (mode, i)
            assert np.array_equal(o1.xdp, o2.xdp), (mode, i)
            assert np.array_equal(o1.stats_delta, o2.stats_delta), (
                mode, i
            )
        c1, c2 = clf_res.mlscore.columns(), clf_mul.mlscore.columns()
        for k in c1:
            assert np.array_equal(c1[k], c2[k]), (mode, k)
        # per-lane scores: the fused readback saturates at int16, the
        # classic launch returns raw int32 — compare on the clamp
        m1 = clf_res.mlscore.recent_masks()
        m2 = clf_mul.mlscore.recent_masks()
        assert len(m1) == len(m2) == 4
        for (_e1, a1, s1), (_e2, a2, s2) in zip(m1, m2):
            assert np.array_equal(a1, a2), mode
            assert np.array_equal(s1, np.clip(s2, -32768, 32767)), mode
        f1, f2 = clf_res.flow.flow_columns(), clf_mul.flow.flow_columns()
        for k in f1:
            assert np.array_equal(f1[k], f2[k]), (mode, k)
        if mode == "enforce":
            assert int(
                clf_res.mlscore.counter_values()["mlscore_enforced_total"]
            ) == 0  # counted at drain
            rec = clf_res.mlscore.drain(force=True)[0]
            assert any(t["enforced"] > 0 for t in rec.tenants)
        clf_res.close()
        clf_mul.close()


@pytest.mark.slow
def test_shadow_mode_verdicts_and_oracle():
    """Shadow scoring must never perturb verdicts, XDP or stats vs the
    scoring-off path and the CPU oracle (the bench gate's cheap twin),
    while the tracked HostScoreModel matches the device tensors."""
    from infw.backend.tpu import TpuClassifier

    tables = _tables(n=48)
    clf = TpuClassifier(force_path="trie", mlscore=SPEC,
                        mlscore_model=clamp_stress_model(SPEC),
                        mlscore_track_model=True)
    off = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    off.load_tables(tables)
    for i in range(3):
        batch, _w, _r = _traffic(tables, 300 + i, b=64)
        w, v4 = batch.pack_wire_subset(np.arange(64, dtype=np.int64))
        o1 = clf.classify_prepared(
            clf.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
            apply_stats=False,
        ).result()
        o2 = off.classify_prepared(
            off.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
            apply_stats=False,
        ).result()
        ref = oracle.classify(tables, batch)
        assert np.array_equal(o1.results, o2.results)
        assert np.array_equal(o1.results, ref.results)
        assert np.array_equal(o1.stats_delta, o2.stats_delta)
    cols = clf.mlscore.columns()
    for k, want in clf.mlscore.model.columns().items():
        assert np.array_equal(cols[k], want), k
    clf.close()
    off.close()


@pytest.mark.slow
def test_model_swap_invalidates_flow_cache():
    """A model hot-swap must behave like a rule patch: in enforce mode
    the flow table caches enforced verdicts, and the swap's generation
    bump makes them stale — the same lanes re-serve under the NEW
    model's policy on the very next admission."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig

    tables = _tables(n=48)
    clf = TpuClassifier(
        force_path="trie", flow_table=FlowConfig.make(entries=1024),
        resident=True, mlscore=SPEC,
        mlscore_model=clamp_stress_model(SPEC), mlscore_mode="enforce",
    )
    clf.load_tables(tables)
    clf.mlscore.set_threshold(-1000)   # everything anomalous
    batch, _w, _r = _traffic(tables, 41, b=64)
    batch.tcp_flags = np.full(64, TCP_ACK, np.int32)
    w, v4 = batch.pack_wire_subset(np.arange(64, dtype=np.int64))
    o1 = clf.classify_prepared(
        clf.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
        apply_stats=False,
    ).result()
    fs = failsafe_lane_mask_np(batch.proto, batch.dst_port)
    elig = ((batch.kind == 1) | (batch.kind == 2)) & ~fs
    denied = (o1.results & 0xFF) == DENY
    assert denied[elig].all(), "enforce-all must deny eligible lanes"
    gen0 = int(np.asarray(clf.flow._gens_host)[0])
    # swap to a never-fires model and raise the threshold: the cached
    # enforced denies must NOT survive the swap — a policy flip AND a
    # model swap each bump the generation (both change what the tier
    # would decide now)
    clf.mlscore.set_threshold(10 ** 6)
    clf.set_score_model(default_model(SPEC), version="calm")
    assert int(np.asarray(clf.flow._gens_host)[0]) == gen0 + 2
    o2 = clf.classify_prepared(
        clf.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
        apply_stats=False,
    ).result()
    ref = oracle.classify(tables, batch)
    assert np.array_equal(o2.results, ref.results), (
        "post-swap verdicts must re-derive from the rules"
    )
    clf.close()


@pytest.mark.slow
def test_zero_recompile_warm_lifecycle():
    """After the scheduler prewarm, serving with scoring on must never
    compile: the fused score variants' and the classic launch's caches
    stay exactly where the ladder left them (the resident-bench
    discipline)."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig
    from infw.kernels import jaxpath
    from infw.scheduler import prewarm_ladder

    tables = _tables(n=48)
    fcfg = FlowConfig.make(entries=1024)
    clf = TpuClassifier(force_path="trie", flow_table=fcfg,
                        resident=True, mlscore=SPEC,
                        mlscore_model=default_model(SPEC))
    clf.load_tables(tables)
    prewarm_ladder(clf, (32, 64))
    fn7 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", False, None, 0, False,
        score=SPEC,
    )
    fn4 = jaxpath.jitted_resident_step(
        fcfg.entries, fcfg.ways, "trie", True, None, 0, False,
        score=SPEC,
    )
    fnc = M.jitted_score_update(SPEC)
    cache0 = fn7._cache_size() + fn4._cache_size() + fnc._cache_size()
    allocs0 = clf.resident.steady_allocs()
    for i in range(6):
        batch, _w, _r = _traffic(tables, 500 + i, b=32 if i % 2 else 64)
        w, v4 = batch.pack_wire_subset(
            np.arange(len(batch), dtype=np.int64)
        )
        clf.classify_prepared(
            clf.prepare_packed(w, v4, tcp_flags=batch.tcp_flags),
            apply_stats=False,
        ).result()
    assert fn7._cache_size() + fn4._cache_size() + fnc._cache_size() \
        == cache0
    assert clf.resident.steady_allocs() == allocs0
    clf.close()


@pytest.mark.slow
def test_daemon_models_dir_hot_swap(tmp_path):
    """The <state-dir>/models/ hot-swap dir: a dropped npz+manifest
    pair swaps the live model and is consumed; a corrupt artifact is
    consumed, logged and the old model keeps serving."""
    from infw.daemon import Daemon
    from infw.interfaces import Interface, InterfaceRegistry

    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    spec = ScoreSpec.make()
    d = Daemon(
        state_dir=str(tmp_path / "state"), node_name="t",
        backend="tpu", registry=reg, metrics_port=0, health_port=0,
        file_poll_interval_s=0.02,
        mlscore=(spec, default_model(spec)), mlscore_mode="shadow",
    )
    assert os.path.isdir(d.models_dir)
    clf = d.syncer._factory()
    d.syncer._classifier = clf  # the test_resident daemon idiom
    assert clf.mlscore is not None
    assert clf.mlscore.model_version == "default"
    m2 = default_model(spec)._replace(version="hot-v2")
    save_model(m2, os.path.join(d.models_dir, "m2.npz"))
    d._mlscore_maintenance()
    assert clf.mlscore.model_version == "hot-v2"
    assert os.listdir(d.models_dir) == []  # consumed
    # corrupt artifact: consumed, version unchanged
    p = os.path.join(d.models_dir, "bad.npz")
    save_model(m2._replace(version="bad"), p)
    with open(p, "ab") as f:
        f.write(b"junk")
    d._mlscore_maintenance()
    assert clf.mlscore.model_version == "hot-v2"
    assert os.listdir(d.models_dir) == []
    # a classifier REBUILD (escalation/re-place) constructs its tier
    # from the factory's launch-time model — the consumed hot-swapped
    # artifact must be re-applied, not silently reverted
    clf2 = d.syncer._factory()
    d.syncer._classifier = clf2
    assert clf2.mlscore.model_version == "default"  # fresh from factory
    d._mlscore_maintenance()
    assert clf2.mlscore.model_version == "hot-v2"
    d.stop()


@pytest.mark.slow
def test_statecheck_mlscore_configs_green():
    from infw.analysis import statecheck

    for cfg in ("mlscore", "mlscore-resident"):
        rep = statecheck.run_config(cfg, seed=0, n_ops=6,
                                    shrink_on_failure=False)
        assert rep["ok"], (cfg, rep.get("failure"))


@pytest.mark.slow
def test_statecheck_catches_mlquant_defect():
    from infw.analysis import statecheck

    M._INJECT_MLQUANT_BUG = True
    M.jitted_score_update.cache_clear()
    try:
        rep = statecheck.run_config("mlscore", seed=0, n_ops=6,
                                    shrink_on_failure=False)
    finally:
        M._INJECT_MLQUANT_BUG = False
        M.jitted_score_update.cache_clear()
    assert not rep["ok"]
    assert "mlscore-model" in rep["failure"]["phase"]
