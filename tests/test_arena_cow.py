"""Content-addressed copy-on-write arena (ISSUE-15).

Covers the content-hash share path (N tenants on one baseline cost one
slab; create-from-known-content is a page-table flip with NO slab
write), the CoW clone-then-patch path (an edit on a shared page lands
in a private clone — bit-identical to a fresh bake — while every other
sharer's verdicts stay byte-stable), the refcount edge cases the PR-10
review flagged (activate of a live page = sharing, destroy of a
sharer, compaction moving a shared page with every row flipped before
reclaim), the shared-delta overlay routing in the tenant registry, the
background dedup sweep, cross-tenant isolation under sharing on both
ArenaClassifier and MeshArenaClassifier, and the cowleak injected
defect / arena-cow statecheck config.
"""
import numpy as np
import pytest

import jax

from infw import oracle, testing
from infw.backend.tpu import ArenaClassifier
from infw.compiler import IncrementalTables, compile_tables_from_content
from infw.kernels import jaxpath
from infw.analysis.statecheck import check_arena


def _mk(seed, n=18, width=4, v6=0.4):
    return testing.random_tables(
        np.random.default_rng(seed), n_entries=n, width=width,
        v6_fraction=v6,
    )


def _spec(family, tabs, pages=8, max_tenants=8):
    return jaxpath.arena_spec_for(family, tabs, pages=pages,
                                  max_tenants=max_tenants)


def _classify(al, tab, tenant_id, n=48, seed=3):
    b = testing.random_batch(np.random.default_rng(seed), tab, n)
    spec = al.spec
    d_max = spec.d_max if spec.family == "ctrie" else 0
    fn = jaxpath.jitted_classify_arena_wire_fused(
        spec.family, spec.pages, d_max
    )
    fused = fn(al.arena, jax.device_put(b.pack_wire()),
               jax.device_put(np.full(n, tenant_id, np.int32)))
    res16, _stats = jaxpath.split_wire_outputs(np.asarray(fused), n)
    results, _xdp = jaxpath.host_finalize_wire(res16, np.asarray(b.kind))
    return results, oracle.classify(tab, b).results


def _shared_pair(family="ctrie", n=18):
    """Two tenants on ONE shared page via independent updaters over the
    same content — the CoW test substrate."""
    base = _mk(40, n=n)
    u0 = IncrementalTables.from_content(dict(base.content), rule_width=4)
    u1 = IncrementalTables.from_content(dict(base.content), rule_width=4)
    s0, s1 = u0.snapshot(), u1.snapshot()
    spec = _spec(family, [s0, s1])
    al = jaxpath.ArenaAllocator(spec)
    assert al.load_tenant(0, s0) == "assign"
    # a DIFFERENT tables object with identical content shares: the hash
    # is over the baked slab arrays, not object identity
    assert al.load_tenant(1, s1) == "share"
    u0.start_dirty_tracking()
    u1.start_dirty_tracking()
    return al, u0, u1, s0, s1


# --- content-addressed sharing ----------------------------------------------


@pytest.mark.parametrize("family", ["dense", "ctrie"])
def test_content_hash_share_and_refcounts(family):
    al, _u0, _u1, s0, s1 = _shared_pair(family)
    assert al.page_of(0) == al.page_of(1)
    assert al.page_refcount(al.page_of(0)) == 2
    assert al.counters["slab_writes"] == 1  # ONE physical bake
    assert al.counters["shared_hits"] == 1
    assert al.distinct_slabs() == 1
    assert check_arena(al) == []
    r0, w0 = _classify(al, s0, 0)
    r1, w1 = _classify(al, s1, 1)
    np.testing.assert_array_equal(r0, w0)
    np.testing.assert_array_equal(r1, w1)


def test_create_from_known_content_writes_no_slab():
    """The capacity lever: 20 tenants over 2 distinct rulesets cost 2
    slab bakes; every other create is a hash probe + page-table flip."""
    tabs = [_mk(60), _mk(61)]
    spec = _spec("ctrie", tabs, pages=8, max_tenants=24)
    al = jaxpath.ArenaAllocator(spec)
    for t in range(20):
        al.load_tenant(t, tabs[t % 2])
    assert al.counters["slab_writes"] == 2
    assert al.distinct_slabs() == 2
    assert al.free_pages() == spec.pages - 2
    assert al.page_refcount(al.page_of(0)) == 10
    assert check_arena(al) == []


# --- CoW clone-then-patch ---------------------------------------------------


@pytest.mark.parametrize("family", ["dense", "ctrie"])
def test_cow_clone_then_patch(family):
    al, u0, _u1, _s0, s1 = _shared_pair(family)
    donor = al.page_of(0)
    before = {
        name: np.asarray(getattr(al.arena, name)).copy()
        for name in al._host if name != "page_table"
    }
    k = sorted(u0.content, key=lambda kk: (kk.ingress_ifindex,
                                           kk.ip_data))[0]
    r = np.asarray(u0.content[k]).copy()
    r[1] = [1, 6, 443, 0, 0, 0, 1]
    u0.apply({k: r}, [])
    hint = u0.peek_dirty()
    snap = u0.snapshot()
    assert al.load_tenant(0, snap, hint=hint) == "cow"
    # the editing tenant moved to a private page; the donor survives
    # with its refcount DECREMENTED (the cowleak invariant)
    assert al.page_of(0) != donor
    assert al.page_of(1) == donor
    assert al.page_refcount(donor) == 1
    assert al.page_refcount(al.page_of(0)) == 1
    assert al.counters["cow_clones"] == 1
    assert check_arena(al) == []
    # donor slab rows byte-stable: the other sharer never saw the edit
    rows = dict(zip(al._array_names(), al._slab_rows()))
    for name, nrows in rows.items():
        np.testing.assert_array_equal(
            np.asarray(getattr(al.arena, name))[
                donor * nrows : (donor + 1) * nrows
            ],
            before[name][donor * nrows : (donor + 1) * nrows],
            err_msg=f"donor {name} rows changed under CoW",
        )
    # the clone is bit-identical to a FRESH bake of the new snapshot
    al2 = jaxpath.ArenaAllocator(al.spec)
    al2.load_tenant(0, snap)
    pg, pg2 = al.page_of(0), al2.page_of(0)
    c1 = al._canonical_of_page(pg)
    c2 = al2._canonical_of_page(pg2)
    for a, b in zip(c1, c2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # verdicts: editor diverged, sharer byte-stable
    r0, w0 = _classify(al, snap, 0)
    np.testing.assert_array_equal(r0, w0)
    r1, w1 = _classify(al, s1, 1)
    np.testing.assert_array_equal(r1, w1)


def test_cowleak_defect_caught_by_invariants():
    al, u0, _u1, _s0, _s1 = _shared_pair("ctrie")
    k = list(u0.content)[0]
    r = np.asarray(u0.content[k]).copy()
    r[1] = [1, 17, 53, 0, 0, 0, 2]
    u0.apply({k: r}, [])
    jaxpath._INJECT_COWLEAK_BUG = True
    try:
        assert al.load_tenant(0, u0.snapshot(),
                              hint=u0.peek_dirty()) == "cow"
        viols = check_arena(al)
    finally:
        jaxpath._INJECT_COWLEAK_BUG = False
    assert any("cowleak" in v or "refcount" in v for v in viols)


# --- refcount edge cases (the PR-10 review sweep, now under sharing) --------


def test_activate_live_page_shares_and_destroy_sharer():
    tabs = [_mk(70), _mk(71)]
    spec = _spec("ctrie", tabs)
    al = jaxpath.ArenaAllocator(spec)
    al.load_tenant(0, tabs[0])
    al.load_tenant(1, tabs[1])
    # activate() of a page live for ANOTHER tenant shares it
    old_page1 = al.page_of(1)
    al.activate(1, al.page_of(0), tabs[0])
    assert al.page_of(1) == al.page_of(0)
    assert al.page_refcount(al.page_of(0)) == 2
    # tenant 1's previous private page dropped to refcount 0 and freed
    assert old_page1 in al._free
    assert check_arena(al) == []
    # destroy of a SHARING tenant: the page survives for the other
    al.destroy_tenant(0)
    assert al.page_refcount(al.page_of(1)) == 1
    assert check_arena(al) == []
    r1, w1 = _classify(al, tabs[0], 1)
    np.testing.assert_array_equal(r1, w1)
    # destroy of the LAST sharer frees the page
    page = al.page_of(1)
    al.destroy_tenant(1)
    assert al.page_refcount(page) == 0
    assert page in al._free
    assert check_arena(al) == []


def test_compact_moves_shared_page_all_rows_flip():
    tabs = [_mk(80), _mk(81)]
    spec = _spec("ctrie", tabs)
    al = jaxpath.ArenaAllocator(spec)
    al.load_tenant(0, tabs[0])      # page 0
    al.load_tenant(1, tabs[1])      # page 1
    al.load_tenant(2, tabs[1])      # shares page 1
    al.destroy_tenant(0)            # frees page 0 below the shared page
    src = al.page_of(1)
    assert al.page_of(2) == src and src > 0
    moved = al.compact()
    # BOTH sharers' page-table rows flipped; the donor page reclaimed
    # only after (it is back on the free list, not referenced)
    assert moved == 2
    tgt = al.page_of(1)
    assert tgt < src and al.page_of(2) == tgt
    assert al.page_refcount(tgt) == 2
    assert src in al._free
    assert check_arena(al) == []
    for t in (1, 2):
        r, w = _classify(al, tabs[1], t)
        np.testing.assert_array_equal(r, w)
    # a staged page (live hold) is pinned: its id is a reservation
    held = al.stage(_mk(82, n=8))
    al.destroy_tenant(1)
    al.destroy_tenant(2)
    assert al.compact() == 0
    assert al.page_holds(held) == 1 and held not in al._free
    al.release(held)
    assert held in al._free
    assert check_arena(al) == []


# --- dedup sweep ------------------------------------------------------------


def test_dedup_sweep_remerges_reconverged_pages():
    al, u0, _u1, _s0, _s1 = _shared_pair("ctrie")
    k = sorted(u0.content, key=lambda kk: (kk.ingress_ifindex,
                                           kk.ip_data))[0]
    orig = np.asarray(u0.content[k]).copy()
    r = orig.copy()
    r[1] = [1, 6, 8080, 0, 0, 0, 2]
    u0.apply({k: r}, [])
    assert al.load_tenant(0, u0.snapshot(), hint=u0.peek_dirty()) == "cow"
    u0.clear_dirty()
    assert al.distinct_slabs() == 2
    # edit BACK to the shared baseline: the private clone's content
    # re-converges (an in-place patch — the page is private now)
    u0.apply({k: orig}, [])
    assert al.load_tenant(0, u0.snapshot(), hint=u0.peek_dirty()) == "patch"
    rep = al.dedup_sweep()
    assert rep["merged"] == 1 and rep["moved"] == [0]
    assert al.page_of(0) == al.page_of(1)
    assert al.page_refcount(al.page_of(0)) == 2
    assert al.distinct_slabs() == 1
    assert al.counters["dedup_merges"] == 1
    assert check_arena(al) == []
    # idempotent when converged
    assert al.dedup_sweep() == {"hashed": 0, "merged": 0, "moved": []}


# --- registry: shared-delta overlay routing ---------------------------------


def test_overlay_delta_routing_paths():
    """Cheap (classify-free) pin of the shared-delta routing decision:
    brand-new prefixes of a shared-page tenant ride the overlay (no
    clone, refcount stays), base-key edits force the clone and fold the
    overlay back.  The end-to-end verdict checks live in the slow
    test_registry_shared_delta_rides_overlay_then_clone."""
    from infw.syncer import TenantRegistry

    base = _mk(90, n=12)
    spec = _spec("ctrie", [base], pages=6, max_tenants=6)
    ov_spec = jaxpath.make_arena_spec(
        "dense", pages=6, max_tenants=6, entries=16, rule_slots=4
    )
    clf = ArenaClassifier(spec, overlay_spec=ov_spec, interpret=True,
                          fused_deep=False)
    reg = TenantRegistry(clf, rule_width=4)
    reg.create_tenant("a", dict(base.content))
    reg.create_tenant("b", dict(base.content))
    tid_b = reg.tenant_id("b")
    al = clf.allocator
    assert al.page_refcount(al.page_of(tid_b)) == 2
    (k_new, r_new), = _mk(91, n=1, v6=0.0).content.items()
    assert reg.update_tenant("b", {k_new: np.asarray(r_new)}, []) == "overlay"
    assert al.page_refcount(al.page_of(tid_b)) == 2
    assert al.counters["cow_clones"] == 0
    assert clf.overlay_allocator.page_of(tid_b) is not None
    # deleting the overlay-resident key is overlay-eligible too
    assert reg.update_tenant("b", {}, [k_new]) == "overlay"
    assert clf.overlay_allocator.page_of(tid_b) is None
    assert reg.update_tenant("b", {k_new: np.asarray(r_new)}, []) == "overlay"
    # a base-key edit is NOT overlay-expressible: clone + fold-back
    k0 = sorted(base.content, key=lambda kk: (kk.ingress_ifindex,
                                              kk.ip_data))[0]
    r0 = np.asarray(base.content[k0]).copy()
    r0[1] = [1, 6, 22, 0, 0, 0, 1]
    # ...and deleting the overlay key in the SAME clone-forcing edit
    # must not fold a resurrected copy back into the slab
    assert reg.update_tenant("b", {k0: r0}, [k_new]) != "overlay"
    assert al.page_refcount(al.page_of(tid_b)) == 1
    assert clf.overlay_allocator.page_of(tid_b) is None
    ident = k_new.masked_identity()
    upd_b = reg._updaters[tid_b]
    assert ident not in upd_b._ident_to_t  # deleted, not resurrected
    assert check_arena(al) == []
    clf.close()


@pytest.mark.slow
def test_registry_shared_delta_rides_overlay_then_clone():
    from infw.syncer import TenantRegistry

    base = _mk(90)
    spec = _spec("ctrie", [base], pages=6, max_tenants=6)
    ov_spec = jaxpath.make_arena_spec(
        "dense", pages=6, max_tenants=6, entries=16, rule_slots=4
    )
    clf = ArenaClassifier(spec, overlay_spec=ov_spec, interpret=True,
                          fused_deep=False)
    reg = TenantRegistry(clf, rule_width=4)
    reg.create_tenant("a", dict(base.content))
    reg.create_tenant("b", dict(base.content))
    tid_b = reg.tenant_id("b")
    assert clf.allocator.page_refcount(clf.allocator.page_of(tid_b)) == 2
    # a brand-new prefix for b rides the overlay side-pool: NO clone,
    # the shared main slab stays refcount 2
    newk = testing.random_tables(
        np.random.default_rng(911), n_entries=1, width=4, v6_fraction=0.0
    )
    (k_new, r_new), = newk.content.items()
    assert reg.update_tenant("b", {k_new: np.asarray(r_new)}, []) == "overlay"
    assert clf.allocator.page_refcount(clf.allocator.page_of(tid_b)) == 2
    assert clf.allocator.counters["cow_clones"] == 0
    assert clf.overlay_allocator.page_of(tid_b) is not None
    # b classifies against base + delta; a stays on the pristine base
    merged = compile_tables_from_content(
        {**dict(base.content), k_new: np.asarray(r_new)}, rule_width=4
    )
    bb = testing.random_batch(np.random.default_rng(5), merged, 64)
    out = reg.classify_mixed(bb, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(
        out.results, oracle.classify(merged, bb).results
    )
    ba = testing.random_batch(np.random.default_rng(6), base, 48)
    out_a = reg.classify_mixed(ba, ["a"] * 48, apply_stats=False)
    np.testing.assert_array_equal(
        out_a.results, oracle.classify(base, ba).results
    )
    # editing a BASE key is not overlay-eligible (the strict longest-
    # prefix tie): it forces the deferred clone, folding the overlay
    # delta back into b's private slab
    k0 = sorted(base.content, key=lambda kk: (kk.ingress_ifindex,
                                              kk.ip_data))[0]
    r0 = np.asarray(base.content[k0]).copy()
    r0[1] = [1, 6, 22, 0, 0, 0, 1]
    path = reg.update_tenant("b", {k0: r0}, [])
    assert path != "overlay"
    assert clf.allocator.page_refcount(clf.allocator.page_of(tid_b)) == 1
    assert clf.overlay_allocator.page_of(tid_b) is None  # folded + cleared
    merged2 = compile_tables_from_content(
        {**dict(base.content), k_new: np.asarray(r_new), k0: r0},
        rule_width=4,
    )
    b2 = testing.random_batch(np.random.default_rng(7), merged2, 64)
    out2 = reg.classify_mixed(b2, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(
        out2.results, oracle.classify(merged2, b2).results
    )
    # a never moved
    out_a2 = reg.classify_mixed(ba, ["a"] * 48, apply_stats=False)
    np.testing.assert_array_equal(out_a2.results, out_a.results)
    assert check_arena(clf.allocator) == []
    clf.close()


# --- classifier-level isolation under sharing --------------------------------


@pytest.mark.slow
def test_classifier_cow_isolation_oracle():
    """Two tenants on one shared page classify bit-identically to their
    per-tenant CPU oracles; an edit by one diverges ONLY that tenant —
    the other's verdicts are byte-stable across the clone (compared
    against the pre-edit output, not just the oracle)."""
    from infw.syncer import TenantRegistry

    base = _mk(95, n=24)
    spec = _spec("ctrie", [base], pages=6, max_tenants=6)
    clf = ArenaClassifier(spec, interpret=True, fused_deep=False)
    reg = TenantRegistry(clf, rule_width=4)
    reg.create_tenant("a", dict(base.content))
    reg.create_tenant("b", dict(base.content))
    assert clf.allocator.page_of(0) == clf.allocator.page_of(1)
    ba = testing.random_batch(np.random.default_rng(11), base, 64)
    want = oracle.classify(base, ba).results
    out_a0 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    out_b0 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a0.results, want)
    np.testing.assert_array_equal(out_b0.results, want)
    k = sorted(base.content, key=lambda kk: (kk.ingress_ifindex,
                                             kk.ip_data))[0]
    r = np.asarray(base.content[k]).copy()
    r[1] = [1, 0, 0, 0, 0, 0, 1]
    reg.update_tenant("b", {k: r}, [])
    assert clf.allocator.page_of(0) != clf.allocator.page_of(1)
    merged = compile_tables_from_content(
        {**dict(base.content), k: r}, rule_width=4
    )
    out_b1 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(
        out_b1.results, oracle.classify(merged, ba).results
    )
    out_a1 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a1.results, out_a0.results)
    assert check_arena(clf.allocator) == []
    clf.close()


@pytest.mark.slow
def test_mesh_cow_isolation():
    """The same share -> edit -> diverge-only-the-editor flow on the
    mesh classifier (8 virtual devices): lifecycle scatters broadcast
    replicated, shared pages placed by the same partition rules."""
    from infw.backend.mesh import MeshArenaClassifier
    from infw.syncer import TenantRegistry

    if len(jax.devices()) < 8:
        pytest.skip("needs >= 8 virtual devices")
    base = _mk(97, n=20)
    spec = _spec("ctrie", [base], pages=8, max_tenants=8)
    clf = MeshArenaClassifier(spec, data_shards=8)
    reg = TenantRegistry(clf, rule_width=4)
    reg.create_tenant("a", dict(base.content))
    reg.create_tenant("b", dict(base.content))
    al = clf.allocator
    assert al.page_of(0) == al.page_of(1)
    assert al.page_refcount(al.page_of(0)) == 2
    ba = testing.random_batch(np.random.default_rng(13), base, 64)
    want = oracle.classify(base, ba).results
    out_a0 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    out_b0 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a0.results, want)
    np.testing.assert_array_equal(out_b0.results, want)
    k = sorted(base.content, key=lambda kk: (kk.ingress_ifindex,
                                             kk.ip_data))[0]
    r = np.asarray(base.content[k]).copy()
    r[1] = [1, 0, 0, 0, 0, 0, 2]
    reg.update_tenant("b", {k: r}, [])
    assert al.page_of(0) != al.page_of(1)
    merged = compile_tables_from_content(
        {**dict(base.content), k: r}, rule_width=4
    )
    out_b1 = reg.classify_mixed(ba, ["b"] * 64, apply_stats=False)
    np.testing.assert_array_equal(
        out_b1.results, oracle.classify(merged, ba).results
    )
    out_a1 = reg.classify_mixed(ba, ["a"] * 64, apply_stats=False)
    np.testing.assert_array_equal(out_a1.results, out_a0.results)
    assert check_arena(al) == []
    clf.close()


# --- statecheck config / defect acceptance ----------------------------------


@pytest.mark.slow
def test_statecheck_arena_cow_config_green():
    from infw.analysis import statecheck

    rep = statecheck.run_config("arena-cow", seed=0, n_ops=8,
                                shrink_on_failure=False)
    assert rep["ok"], rep


@pytest.mark.slow
def test_cowleak_defect_caught_and_shrunk():
    from infw.analysis import statecheck

    jaxpath._INJECT_COWLEAK_BUG = True
    try:
        rep = statecheck.run_config("arena-cow", seed=0, n_ops=12,
                                    max_shrink_runs=64)
    finally:
        jaxpath._INJECT_COWLEAK_BUG = False
    assert not rep["ok"]
    assert rep["failure"]["phase"] == "invariant"
    assert rep["shrunk"]["ops"] <= 3
