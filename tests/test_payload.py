"""Payload-matching tier (ISSUE-19): Aho-Corasick lowering vs two
independent host references, device gather/matmul kernel parity,
prefix-truncation semantics, versioned pattern artifacts + hot swap,
the PayloadTier facade, the ring payload column and the daemon factory
gating.

Tier-1 keeps the cheap host-side construction/semantics/artifact tests
plus two small device-kernel parity tests; the jit-heavy classifier
serving paths (classic + resident fused + superbatch, enforce/failsafe
precedence on device) and the statecheck sweeps are slow-marked and
run in ``make test``, ``make state-check`` (payload configs + the
aclink acceptance) and ``make payload-bench`` (oracle + retention +
hot-swap + enforce gates).
"""
import os

import numpy as np
import pytest

from infw import oracle, testing
from infw.backend.cpu_ref import HostAcAutomaton, payload_match_ref
from infw.kernels import acmatch
from infw.kernels.acmatch import (
    AcSpec,
    compile_patterns,
    host_match_bitmap,
    host_payload_rewrite,
    jitted_acmatch,
    model_device,
    validate_patterns,
)
from infw.kernels.jaxpath import TCP_ACK
from infw.kernels.wire_decode import (
    pad_payload_prefix,
    payload_prefix_bucket,
)
from infw.payload import (
    PayloadTier,
    attack_payloads,
    benign_payloads,
    load_patterns,
    save_patterns,
    signature_patterns,
)

#: overlapping-suffix set — the failure-link surface (suffix patterns
#: must be reported by states their failure chains reach)
OVERLAP = [b"/etc/passwd", b"etc/passwd", b"passwd", b"ab", b"b",
           b"abab"]


def _host_dfa_bitmap(model, pay, plen):
    """Walk the COMPILED dense DFA on the host — a third reference
    beside the naive scan and HostAcAutomaton, pinning exactly what the
    device kernel computes."""
    pay = np.asarray(pay, np.uint8)
    out = np.zeros((pay.shape[0], model.spec.pwords), np.uint32)
    for i in range(pay.shape[0]):
        s = 0
        n = int(min(plen[i], model.spec.plen))
        for c in pay[i, :n]:
            s = int(model.delta[s, int(c)])
            out[i] |= model.matchmap[s]
    return out


# --- spec / validation -------------------------------------------------------


def test_acspec_buckets():
    s = AcSpec.make(65, 33)
    assert s.states == 128 and s.patterns == 64 and s.pwords == 2
    assert AcSpec.make(1, 1).states == 64
    assert AcSpec.make(1, 1).patterns == 32
    # matmul defaults on for tiny automata, off past the threshold
    assert AcSpec.make(64, 32).matmul
    assert not AcSpec.make(acmatch.MATMUL_MAX_STATES + 1, 32).matmul
    with pytest.raises(ValueError):
        AcSpec.make(64, 32, plen=96)
    # same-bucket pattern sets share a spec (the hot-swap key)
    a = compile_patterns([b"abc", b"xy"], plen=64).spec
    b = compile_patterns([b"zzz", b"qq", b"p"], plen=64).spec
    assert a == b


def test_validate_patterns_rejects():
    with pytest.raises(ValueError):
        validate_patterns([], 64)
    with pytest.raises(ValueError):
        validate_patterns([b""], 64)
    with pytest.raises(ValueError):
        validate_patterns([b"x" * 65], 64)  # could never fire
    with pytest.raises(ValueError):
        validate_patterns([b"ab", b"ab"], 64)
    with pytest.raises(ValueError):
        validate_patterns(["ab"], 64)
    validate_patterns([b"x" * 64, b"y"], 64)  # exactly plen is fine


def test_compile_refuses_oversized_hot_swap():
    spec = compile_patterns([b"ab", b"cd"], plen=64).spec
    big = signature_patterns(np.random.default_rng(0), 40, plen=64)
    with pytest.raises(ValueError):
        compile_patterns(big, plen=64, spec=spec)
    with pytest.raises(ValueError):
        compile_patterns([b"ab"], plen=128, spec=spec)


# --- construction vs independent references ----------------------------------


def test_compiled_dfa_matches_naive_and_host_ac():
    rng = np.random.default_rng(3)
    pats = OVERLAP + signature_patterns(rng, 20, plen=64)[8:]
    model = compile_patterns(pats, plen=64)
    pay, plen = attack_payloads(rng, 64, pats, plen=64)
    want = payload_match_ref(pats, pay, plen, 64, model.spec.pwords)
    got = _host_dfa_bitmap(model, pay, plen)
    assert np.array_equal(got, want)
    # third angle: the link-walking host automaton on the same prefixes
    ac = HostAcAutomaton(pats)
    for i in range(pay.shape[0]):
        n = int(min(plen[i], 64))
        idx = ac.matches(pay[i, :n].tobytes())
        ref = {
            j for j in range(len(pats))
            if want[i, j // 32] >> (j % 32) & 1
        }
        assert idx == ref
    # host_match_bitmap is the naive reference, re-exported
    assert np.array_equal(host_match_bitmap(model, pay, plen), want)


def test_aclink_defect_diverges_from_naive_oracle():
    """The injected construction defect MUST be visible to the naive
    reference (the statecheck catch) — if this compare ever passes with
    the flag on, the defect registry's bound is meaningless."""
    pats = OVERLAP
    # the dropped fold lands on the FIRST BFS state whose failure chain
    # carries output — here the "ab" state, which must also report the
    # suffix pattern "b"; sweep payloads exercising every chain so the
    # witness stays robust to BFS-order changes
    pay = np.zeros((2, 64), np.uint8)
    pay[0, :4] = np.frombuffer(b"abab", np.uint8)
    pay[1, :11] = np.frombuffer(b"/etc/passwd", np.uint8)
    plen = np.asarray([64, 64], np.int32)
    want = payload_match_ref(pats, pay, plen, 64, 1)
    acmatch._INJECT_ACLINK_BUG = True
    try:
        bad = compile_patterns(pats, plen=64)
    finally:
        acmatch._INJECT_ACLINK_BUG = False
    assert not np.array_equal(_host_dfa_bitmap(bad, pay, plen), want)
    good = compile_patterns(pats, plen=64)
    assert np.array_equal(_host_dfa_bitmap(good, pay, plen), want)


# --- device kernel parity ----------------------------------------------------


def test_device_gather_matches_oracle():
    rng = np.random.default_rng(5)
    pats = signature_patterns(rng, 33, plen=64)  # 2 match words
    model = compile_patterns(pats, plen=64, matmul=False)
    trans, mmap = model_device(model)
    pay, plen = attack_payloads(rng, 32, pats, plen=64)
    got = np.asarray(jitted_acmatch(model.spec)(
        trans, mmap, pay, plen.astype(np.int32)
    ))
    want = payload_match_ref(pats, pay, plen, 64, model.spec.pwords)
    assert np.array_equal(got.astype(np.uint32), want)


def test_device_matmul_matches_gather():
    rng = np.random.default_rng(6)
    pats = [b"ab", b"b", b"cde", b"\x00\x01"]  # tiny -> matmul bucket
    m_mm = compile_patterns(pats, plen=64, matmul=True)
    m_ga = compile_patterns(pats, plen=64, matmul=False)
    assert m_mm.spec.matmul and not m_ga.spec.matmul
    pay, plen = attack_payloads(rng, 16, pats, plen=64)
    got_mm = np.asarray(jitted_acmatch(m_mm.spec)(
        *model_device(m_mm), pay, plen.astype(np.int32)
    ))
    got_ga = np.asarray(jitted_acmatch(m_ga.spec)(
        *model_device(m_ga), pay, plen.astype(np.int32)
    ))
    want = payload_match_ref(pats, pay, plen, 64, m_mm.spec.pwords)
    assert np.array_equal(got_mm.astype(np.uint32), want)
    assert np.array_equal(got_ga.astype(np.uint32), want)


def test_truncation_boundary_semantics():
    """A pattern occurrence must end wholly within
    min(plen[i], prefix) — straddling the cut or the valid-length
    boundary claims nothing, and zero padding never walks the
    automaton."""
    pats = [b"abcd", b"d"]
    model = compile_patterns(pats, plen=64, matmul=False)
    pay = np.zeros((4, 64), np.uint8)
    pay[0, 60:64] = np.frombuffer(b"abcd", np.uint8)  # ends AT the cut
    pay[1, 62:64] = np.frombuffer(b"ab", np.uint8)    # straddles it
    pay[2, 10:14] = np.frombuffer(b"abcd", np.uint8)  # past plen=12
    pay[3, 0:4] = np.frombuffer(b"abcd", np.uint8)    # pad region zero
    plen = np.asarray([64, 64, 12, 4], np.int32)
    got = np.asarray(jitted_acmatch(model.spec)(
        *model_device(model), pay, plen
    )).astype(np.uint32)
    want = payload_match_ref(pats, pay, plen, 64, model.spec.pwords)
    assert np.array_equal(got, want)
    assert got[0, 0] == 0b11   # both occurrences end AT the cut
    assert got[1, 0] == 0      # straddles the prefix cut
    assert got[2, 0] == 0      # every occurrence ends past plen=12
    assert got[3, 0] == 0b11   # both end exactly at plen=4


def test_enforce_rewrite_failsafe_precedence_host():
    from infw.constants import ALLOW, DENY

    pats = [b"sig"]
    model = compile_patterns(pats, plen=64)
    bitmap = np.asarray([[1], [1], [0], [1]], np.uint32)
    res = np.asarray(
        [ALLOW | (7 << 8), ALLOW | (8 << 8), ALLOW, DENY | (3 << 8)],
        np.uint32,
    )
    proto = np.full(4, 6, np.int32)
    dst_port = np.asarray([22, 8080, 8080, 8080], np.int32)  # 22 = fs
    out = host_payload_rewrite(model, res, bitmap, True, proto, dst_port)
    assert out[0] == res[0]          # failsafe cell never rewritten
    assert out[1] == acmatch.PAYLOAD_DENY_RESULT
    assert out[2] == res[2]          # no match -> untouched
    assert out[3] == res[3]          # already DENY -> untouched
    # shadow never touches verdicts
    assert np.array_equal(
        host_payload_rewrite(model, res, bitmap, False, proto, dst_port),
        res,
    )


# --- wire format / packets / ring -------------------------------------------


def test_pad_payload_prefix_buckets():
    assert payload_prefix_bucket(1) == 64
    assert payload_prefix_bucket(64) == 64
    assert payload_prefix_bucket(65) == 128
    assert payload_prefix_bucket(4096) == 128
    pay = np.arange(3 * 40, dtype=np.uint8).reshape(3, 40)
    out, lens = pad_payload_prefix(pay, np.asarray([40, 10, 99]))
    assert out.shape == (3, 64) and out.dtype == np.uint8
    assert np.array_equal(out[:, :40], pay)
    assert not out[:, 40:].any()
    assert lens.tolist() == [40, 10, 64]  # clamped to the bucket
    wide = np.zeros((2, 200), np.uint8)
    out2, lens2 = pad_payload_prefix(wide, np.asarray([150, 5]))
    assert out2.shape == (2, 128)
    assert lens2.tolist() == [128, 5]


def test_packet_batch_payload_columns():
    tabs = testing.random_tables(np.random.default_rng(1), n_entries=16)
    batch = testing.random_batch(np.random.default_rng(2), tabs, 8)
    batch.payload = np.arange(8 * 64, dtype=np.uint8).reshape(8, 64)
    batch.payload_len = np.full(8, 64, np.int32)
    s = batch.slice(2, 6)
    assert np.array_equal(s.payload, batch.payload[2:6])
    assert np.array_equal(s.payload_len, batch.payload_len[2:6])
    idx = np.asarray([7, 0, 3])
    t = batch.take(idx)
    assert np.array_equal(t.payload, batch.payload[idx])
    assert np.array_equal(t.payload_len, batch.payload_len[idx])


def test_ring_payload_roundtrip(tmp_path):
    from infw.ring import IngestRing

    path = str(tmp_path / "ingest.ring")
    ring = IngestRing.create(path, slots=4, slot_packets=64,
                             payload_width=64)
    prod = IngestRing.attach(path)
    w = np.arange(16 * 7, dtype=np.uint32).reshape(16, 7)
    fl = np.arange(16, dtype=np.int32)
    pay = np.arange(16 * 64, dtype=np.uint8).reshape(16, 64)
    plen = np.full(16, 33, np.int32)
    prod.push(w, v4_only=False, tcp_flags=fl, payload=pay,
              payload_len=plen)
    prod.push(w, v4_only=True)  # payload-free record on the same ring
    chunk = ring.pop()
    assert np.array_equal(chunk.wire, w)
    assert np.array_equal(chunk.tcp_flags, fl)
    assert np.array_equal(chunk.payload, pay)
    assert np.array_equal(chunk.payload_len, plen)
    chunk.release()
    chunk2 = ring.pop()
    assert chunk2.payload is None and chunk2.payload_len is None
    chunk2.release()
    prod.close()
    ring.close()


# --- artifacts ---------------------------------------------------------------


def test_pattern_artifact_roundtrip(tmp_path):
    pats = signature_patterns(np.random.default_rng(4), 12, plen=64)
    path = str(tmp_path / "sigs.npz")
    mpath = save_patterns(pats, path, plen=64, version="v7")
    assert os.path.exists(mpath)
    got, spec, version = load_patterns(path)
    assert got == [bytes(p) for p in pats]
    assert version == "v7"
    assert spec == compile_patterns(pats, plen=64).spec


def test_pattern_artifact_rejects_corruption(tmp_path):
    pats = [b"abc", b"de"]
    path = str(tmp_path / "sigs.npz")
    save_patterns(pats, path, plen=64)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:-1] + bytes([raw[-1] ^ 0xFF]))
    with pytest.raises(ValueError, match="checksum"):
        load_patterns(path)
    os.unlink(path + ".json")
    with pytest.raises(ValueError, match="manifest"):
        load_patterns(path)


# --- the tier facade ---------------------------------------------------------


def test_tier_swap_mode_and_counters():
    pats = signature_patterns(np.random.default_rng(0), 8, plen=64)
    tier = PayloadTier(pats, plen=64, mode="shadow", keep_masks=4)
    assert tier.version == 0 and not tier.enforce
    spec0 = tier.spec
    fired = []
    tier.on_swap = lambda: fired.append(1)
    tier._masks.append(("stale",))
    tier.swap_patterns(signature_patterns(np.random.default_rng(1), 8,
                                          plen=64))
    assert tier.version == 1 and tier.spec == spec0
    assert fired == [1]
    # retained masks were matched by the OLD automaton — must be gone
    assert not tier._masks
    cv = tier.counter_values()
    assert cv["payload_pattern_swaps_total"] == 1
    assert cv["payload_patternset_version"] == 1
    assert cv["payload_patterns"] == 8
    # geometry-changing swap refuses (would recompile under the hood)
    with pytest.raises(ValueError):
        tier.swap_patterns(
            signature_patterns(np.random.default_rng(2), 8, plen=128),
            plen=128,
        )
    tier.set_mode("enforce")
    assert tier.enforce
    with pytest.raises(ValueError):
        tier.set_mode("observe")
    with pytest.raises(ValueError):
        PayloadTier(pats, mode="observe")


def test_tier_match_vs_oracle():
    pats = signature_patterns(np.random.default_rng(0), 8, plen=64)
    tier = PayloadTier(pats, plen=64)
    pay, plen = attack_payloads(np.random.default_rng(1), 16, pats,
                                plen=64)
    got = np.asarray(tier.match(pay, plen)).astype(np.uint32)
    want = payload_match_ref(pats, pay, plen, 64, tier.spec.pwords)
    assert np.array_equal(got, want)


# --- generators --------------------------------------------------------------


def test_traffic_generators_deterministic():
    pats = signature_patterns(np.random.default_rng(9), 16, plen=64)
    assert pats == signature_patterns(np.random.default_rng(9), 16,
                                      plen=64)
    assert len(set(pats)) == 16
    assert all(1 <= len(p) <= 64 for p in pats)
    pay, lens = benign_payloads(np.random.default_rng(3), 32, plen=64)
    assert pay.shape == (32, 64) and (lens <= 64).all() and (lens > 0).all()
    a1 = attack_payloads(np.random.default_rng(5), 32, pats, plen=64)
    a2 = attack_payloads(np.random.default_rng(5), 32, pats, plen=64)
    assert np.array_equal(a1[0], a2[0]) and np.array_equal(a1[1], a2[1])
    # the planted signatures are real: a solid majority must match
    # (the deliberate boundary-straddlers are the ~15% exception)
    hits = payload_match_ref(pats, a1[0], a1[1], 64, 1)
    assert (hits != 0).any(axis=1).mean() > 0.6


def test_loadgen_payload_shapes():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        from loadgen import decode_attack_labels, synth_payload
    finally:
        sys.path.pop(0)
    rng = np.random.default_rng(2)
    pay, lens, meta = synth_payload(rng, 100, "attack-mix", 64, 0, 16,
                                    0.3, 32)
    assert pay.shape == (100, 64) and lens.shape == (100,)
    assert meta["payload_bytes_per_packet"] == 68
    mask = decode_attack_labels(
        meta["payload_labels"]["record_bitmaps_hex"], 100, 32
    )
    assert int(mask.sum()) == meta["payload_signature_packets"] > 0
    # labeled lanes carry the seeded pattern set's signatures
    pats = signature_patterns(np.random.default_rng(0), 16, plen=64)
    hits = payload_match_ref(pats, pay[mask], lens[mask], 64, 1)
    assert (hits != 0).any(axis=1).mean() > 0.6
    _pay2, _lens2, meta2 = synth_payload(
        np.random.default_rng(2), 50, "http", 64, 0, 16, 0.3, 32
    )
    assert "payload_labels" not in meta2


# --- daemon factory gating ---------------------------------------------------


def test_factory_payload_gating():
    from infw.daemon import make_classifier_factory
    from infw.flow import FlowConfig

    pats = signature_patterns(np.random.default_rng(0), 4, plen=64)
    cpu = make_classifier_factory(backend="cpu", payload=pats)()
    assert getattr(cpu, "payload", None) is None  # headers-only on cpu
    tpu = make_classifier_factory(
        backend="tpu", resident=True,
        flow_table=FlowConfig.make(entries=256), payload=pats,
        payload_mode="enforce",
    )()
    assert tpu.payload is not None and tpu.payload.enforce
    tpu.close()


# --- jit-heavy serving paths (slow tier) -------------------------------------


def _served_tables():
    return testing.random_tables_fast(
        np.random.default_rng(3), n_entries=300, width=4,
        v6_fraction=0.4, ifindexes=(2, 3),
    )


@pytest.mark.slow
def test_classifier_paths_payload_oracle():
    """Classic + resident fused serving paths: shadow verdicts stay
    bit-identical to the CPU oracle, device bitmaps to the naive host
    reference, and the served hit bits to the standalone kernel."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig

    tabs = _served_tables()
    pats = signature_patterns(np.random.default_rng(11), 8, plen=64)
    bs = 64
    batch = testing.random_batch(np.random.default_rng(21), tabs, bs * 3)
    batch.tcp_flags = np.full(len(batch), TCP_ACK, np.int32)
    pay_a, len_a = attack_payloads(np.random.default_rng(22), bs, pats,
                                   plen=64)
    pay_b, len_b = benign_payloads(np.random.default_rng(23), bs * 2,
                                   plen=64)
    batch.payload = np.concatenate([pay_a, pay_b])
    batch.payload_len = np.concatenate([len_a, len_b]).astype(np.int32)
    ref = oracle.classify(tabs, batch)
    for kw in (
        dict(force_path="trie"),
        dict(force_path="trie", resident=True,
             flow_table=FlowConfig.make(entries=512)),
    ):
        clf = TpuClassifier(payload=pats, payload_plen=64,
                            payload_track=True, **kw)
        clf.load_tables(tabs)
        for lo in range(0, len(batch), bs):
            out = clf.classify(batch.slice(lo, lo + bs),
                               apply_stats=False)
            assert np.array_equal(out.results,
                                  ref.results[lo:lo + bs])
        for pay, plen, bitmap, hit in clf.payload.recent_masks():
            want = payload_match_ref(pats, pay, plen, 64,
                                     clf.payload.spec.pwords)
            assert np.array_equal(np.asarray(bitmap, np.uint32), want)
            assert np.array_equal(np.asarray(hit, bool),
                                  (want != 0).any(axis=1))
        clf.close()


@pytest.mark.slow
def test_enforce_failsafe_precedence_fused():
    """Enforce mode on the resident fused path: signature lanes at open
    ports are denied, failsafe cells keep their rule verdicts."""
    from infw.backend.tpu import TpuClassifier
    from infw.constants import DENY
    from infw.flow import FlowConfig
    from infw.kernels.mxu_score import failsafe_lane_mask_np

    tabs = _served_tables()
    pats = [b"evil-sig"]
    bs = 64
    batch = testing.random_batch(np.random.default_rng(31), tabs, bs)
    batch.proto[:] = 6
    batch.dst_port[: bs // 2] = 22  # SSH failsafe cell
    batch.dst_port[bs // 2:] = 8080
    batch.tcp_flags = np.full(bs, TCP_ACK, np.int32)
    pay = np.zeros((bs, 64), np.uint8)
    pay[:, 7:15] = np.frombuffer(b"evil-sig", np.uint8)
    batch.payload = pay
    batch.payload_len = np.full(bs, 64, np.int32)
    ref = oracle.classify(tabs, batch)
    clf = TpuClassifier(force_path="trie", resident=True,
                        flow_table=FlowConfig.make(entries=512),
                        payload=pats, payload_plen=64,
                        payload_mode="enforce")
    clf.load_tables(tabs)
    out = clf.classify(batch, apply_stats=False)
    fs = failsafe_lane_mask_np(batch.proto, batch.dst_port)
    assert fs[: bs // 2].all() and not fs[bs // 2:].any()
    assert np.array_equal(out.results[fs], ref.results[fs])
    open_hit = ~fs & ((ref.results & 0xFF) != DENY)
    assert ((out.results[open_hit] & 0xFF) == DENY).all()
    assert clf.payload.counter_values()["payload_enforced_total"] > 0
    clf.close()


@pytest.mark.slow
def test_daemon_ring_superbatch_payload(tmp_path):
    """Ring ingest with the payload column through the superbatch epoch
    loop: lanes/matches counted, patterns-dir hot swap consumed and
    re-applied to a rebuilt classifier generation."""
    from infw.daemon import Daemon
    from infw.flow import FlowConfig
    from infw.ring import IngestRing

    rng = np.random.default_rng(7)
    pats = signature_patterns(rng, 8, plen=64)
    ringp = str(tmp_path / "ingest.ring")
    daemon = Daemon(
        state_dir=str(tmp_path), node_name="n1", backend="tpu",
        resident=True, ring=ringp, superbatch_k=2, metrics_port=0,
        health_port=0, file_poll_interval_s=10.0,
        flow_table=FlowConfig.make(entries=512),
        payload=pats, payload_mode="enforce", payload_plen=64,
    )
    try:
        tabs = _served_tables()
        clf = daemon.syncer._factory()
        clf.load_tables(tabs)
        daemon.syncer._classifier = clf
        bs, n_chunks = 64, 3
        batch = testing.random_batch_fast(
            np.random.default_rng(41), tabs, bs * n_chunks
        )
        wire = batch.pack_wire()
        tflags = (np.zeros(len(batch), np.int32)
                  if batch.tcp_flags is None
                  else np.asarray(batch.tcp_flags, np.int32))
        pay = rng.integers(0, 256, size=(len(batch), 64), dtype=np.uint8)
        sig = pats[0]
        for i in range(0, len(batch), 2):
            pay[i, 5:5 + len(sig)] = np.frombuffer(sig, np.uint8)
        plen = np.full(len(batch), 64, np.int32)
        prod = IngestRing.attach(ringp)
        for lo in range(0, len(batch), bs):
            prod.push(np.ascontiguousarray(wire[lo:lo + bs]),
                      v4_only=False,
                      tcp_flags=np.ascontiguousarray(tflags[lo:lo + bs]),
                      payload=np.ascontiguousarray(pay[lo:lo + bs]),
                      payload_len=np.ascontiguousarray(plen[lo:lo + bs]))
        n = daemon.process_ring_once(budget=10 ** 9)
        assert n == bs * n_chunks
        assert (clf.resident_counters()
                ["resident_superbatch_dispatches_total"] >= 1)
        cv = daemon._payload_counters.counter_values()
        assert cv["payload_admissions_total"] == n_chunks
        assert cv["payload_lanes_total"] == bs * n_chunks
        assert cv["payload_matched_total"] >= bs * n_chunks // 2
        assert cv["payload_enforced_total"] > 0
        v0 = cv["payload_patternset_version"]

        new_pats = signature_patterns(np.random.default_rng(9), 8,
                                      plen=64)
        save_patterns(new_pats,
                      os.path.join(daemon.patterns_dir, "s1.npz"),
                      plen=64, version="v-test-1")
        daemon._payload_maintenance()
        assert not os.listdir(daemon.patterns_dir)
        cv2 = daemon._payload_counters.counter_values()
        assert cv2["payload_patternset_version"] == v0 + 1
        # a rebuilt classifier generation gets the swapped set
        clf2 = daemon.syncer._factory()
        clf2.load_tables(tabs)
        daemon.syncer._classifier = clf2
        daemon._payload_maintenance()
        assert clf2.payload.version == 1
        assert "payload_matched_total" in \
            daemon.metrics_registry.render_text()
        prod.close()
    finally:
        daemon.stop()


@pytest.mark.slow
def test_statecheck_payload_configs():
    from infw.analysis import statecheck

    for cfg in ("payload", "payload-resident"):
        rep = statecheck.run_config(cfg, seed=0, n_ops=6,
                                    shrink_on_failure=False)
        assert rep["ok"], rep
