"""Kernel admission verifier (infw.analysis.boundscheck).

Covers: the abstract domain (interval + maybe-bits, joins, dtype
clamping), per-primitive transfer functions driven through tiny traced
jaxprs (arithmetic hulls, narrowing converts, masked decodes, gather/
scatter proof and guard recognition, select_n dead-branch pruning
through jnp.take's internal wraparound), integer-wrap detection at the
int8/int32/uint32 edges with the intentional-modular exemption,
fixpoint termination on loop carries, declared-bound seeding
(infw.contracts.TENSOR_BOUNDS), the shared justification-required
suppression loader, and the declarative injected-defect registry
(infw.analysis.defects).  Slow-marked: the full-fleet sweep over every
registered entrypoint (zero unsuppressed findings — the make
bounds-check gate) and the two injected-defect acceptances through the
CLI in fresh subprocesses (the flags act at trace time).
"""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infw import contracts
from infw.analysis import _suppress, defects
from infw.analysis import boundscheck as bc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "infw_lint.py")


def interp(fn, *seeds, args=None):
    """Trace ``fn`` at the seeds' shapes and abstractly interpret it.

    ``seeds`` align with the positional args: an AbsVal seeds that
    argument's interval; a concrete array seeds dtype-top at its
    shape.  Returns (ctx, out_absvals)."""
    if args is None:
        args = []
        for s in seeds:
            if isinstance(s, bc.AbsVal):
                args.append(jnp.zeros((8,), s.dtype))
            else:
                args.append(s)
    closed = jax.make_jaxpr(fn)(*args)
    flat = []
    for s, v in zip(seeds, closed.jaxpr.invars):
        if isinstance(s, bc.AbsVal):
            flat.append(s)
        else:
            dt = v.aval.dtype
            flat.append(bc.AbsVal(dt, is_float=np.dtype(dt).kind == "f"))
    ctx = bc._Ctx("test")
    outs = bc.interp_closed_jaxpr(closed, flat, ctx)
    return ctx, outs


def errors(ctx, check=None):
    return [f for f in ctx.findings.values()
            if f.severity == "error" and (check is None or f.check == check)]


# --- abstract domain --------------------------------------------------------


def test_absval_clamps_to_dtype():
    a = bc.AbsVal(np.int8, -1000, 1000)
    assert (a.lo, a.hi) == (-128, 127)
    assert not a.informative()
    b = bc.AbsVal(np.int32, 0, 100)
    assert b.informative() and b.bits == 0x7F


def test_absval_bits_cap_hi():
    a = bc.AbsVal(np.int32, 0, 1000, bits=0xFF)
    assert a.hi == 0xFF


def test_join_widens_interval_and_ors_bits():
    a = bc.AbsVal(np.int32, 0, 3)
    b = bc.AbsVal(np.int32, 8, 15)
    j = bc._join(a, b)
    assert (j.lo, j.hi) == (0, 15)


# --- arithmetic transfer ----------------------------------------------------


def test_add_interval_hull():
    ctx, (out,) = interp(
        lambda x, y: x + y,
        bc.AbsVal(np.int32, 0, 10), bc.AbsVal(np.int32, 5, 7))
    assert (out.lo, out.hi) == (5, 17)
    assert not errors(ctx)


def test_mul_corner_hull():
    ctx, (out,) = interp(
        lambda x, y: x * y,
        bc.AbsVal(np.int32, -3, 4), bc.AbsVal(np.int32, -5, 6))
    assert (out.lo, out.hi) == (-20, 24)


def test_and_mask_bounds_result():
    """value & mask decodes are what the bits half of the domain is
    for: the result is bounded by the mask even when the value is top."""
    ctx, (out,) = interp(lambda x: x & 0xFF, bc.AbsVal(np.int32))
    assert (out.lo, out.hi) == (0, 0xFF)


def test_cumsum_scales_by_axis_length():
    ctx, (out,) = interp(lambda x: jnp.cumsum(x), bc.AbsVal(np.int32, 0, 3))
    assert (out.lo, out.hi) == (0, 24)          # 8 lanes * 3
    assert not errors(ctx)


def test_cumsum_int8_accumulation_wrap_flagged():
    ctx, _ = interp(lambda x: jnp.cumsum(x), bc.AbsVal(np.int8, 0, 100))
    errs = errors(ctx, "int-wrap")
    assert len(errs) == 1 and "cumsum" in errs[0].subject


def test_clip_narrows_and_min_max_hull():
    ctx, (out,) = interp(
        lambda x: jnp.clip(x, 0, 15), bc.AbsVal(np.int32))
    assert (out.lo, out.hi) == (0, 15)


# --- integer wrap detection at the dtype edges ------------------------------


def test_int8_add_wrap_flagged():
    ctx, _ = interp(
        lambda x, y: x + y,
        bc.AbsVal(np.int8, 0, 100), bc.AbsVal(np.int8, 0, 100))
    errs = errors(ctx, "int-wrap")
    assert len(errs) == 1 and "add" in errs[0].subject


def test_int32_mul_const_wrap_flagged_with_const_tag():
    ctx, _ = interp(
        lambda x: x * jnp.int32(65536),
        bc.AbsVal(np.int32, 0, 2**20))
    errs = errors(ctx, "int-wrap")
    assert len(errs) == 1
    assert ":c65536" in errs[0].subject


def test_uint32_sub_wrap_flagged():
    ctx, _ = interp(
        lambda x, y: x - y,
        bc.AbsVal(np.uint32, 0, 10), bc.AbsVal(np.uint32, 0, 20))
    assert len(errors(ctx, "int-wrap")) == 1


def test_in_range_arith_not_flagged():
    ctx, _ = interp(
        lambda x, y: x * y,
        bc.AbsVal(np.int32, 0, 1000), bc.AbsVal(np.int32, 0, 1000))
    assert not errors(ctx)


def test_intentional_modular_not_flagged():
    """An operand already spanning the full dtype ring means modular
    arithmetic on purpose (hash state, u32 counters) — no finding."""
    ctx, _ = interp(
        lambda x: x * jnp.uint32(16777619),    # FNV-1a prime step
        bc.AbsVal(np.uint32))
    assert not errors(ctx)


def test_narrowing_convert_flagged_and_value_preserving_not():
    ctx, _ = interp(
        lambda x: x.astype(jnp.int8), bc.AbsVal(np.int32, 0, 300))
    errs = errors(ctx, "int-wrap")
    assert len(errs) == 1 and "convert" in errs[0].subject
    ctx2, (out,) = interp(
        lambda x: x.astype(jnp.int8), bc.AbsVal(np.int32, 0, 100))
    assert not errors(ctx2) and (out.lo, out.hi) == (0, 100)


# --- gather/scatter proof and guard recognition -----------------------------


def test_seeded_in_range_gather_proved():
    t = jnp.arange(64, dtype=jnp.int32)
    ctx, _ = interp(
        lambda t, i: jnp.take(t, i),
        t, bc.AbsVal(np.int32, 0, 63), args=[t, jnp.zeros((8,), jnp.int32)])
    assert not errors(ctx)
    assert ctx.stats["proved"] >= 1


def test_unbounded_gather_flagged():
    t = jnp.arange(64, dtype=jnp.int32)
    ctx, _ = interp(
        lambda t, i: t[i],
        t, bc.AbsVal(np.int32), args=[t, jnp.zeros((8,), jnp.int32)])
    assert len(errors(ctx, "oob-gather")) == 1


def test_guarded_gather_recognized():
    """The production idiom: range-test the raw index, clip it for the
    gather, select on the test — the tested bounds flow through clip
    by shared reference, so the site counts as guarded, not flagged."""
    t = jnp.arange(64, dtype=jnp.int32)

    def fn(t, i):
        ok = (i >= 0) & (i < 64)
        return jnp.where(ok, jnp.take(t, jnp.clip(i, 0, 63)), 0)

    ctx, _ = interp(fn, t, bc.AbsVal(np.int32),
                    args=[t, jnp.zeros((8,), jnp.int32)])
    assert not errors(ctx)


def test_take_internal_wraparound_not_flagged():
    """jnp.take lowers to ``where(i < 0, i + n, i)`` + a fill-mode
    gather; with the index seeded non-negative the wraparound add and
    the fill path are both abstractly dead — no int-wrap, no fill
    join, site proved (the select_n dead-branch pruning test)."""
    t = jnp.arange(100, dtype=jnp.int32)
    ctx, _ = interp(
        lambda t, i: jnp.take(t, i),
        t, bc.AbsVal(np.int32, 0, 99), args=[t, jnp.zeros((8,), jnp.int32)])
    assert not errors(ctx)
    assert ctx.stats["proved"] >= 1


def test_masked_decode_proves_gather():
    """The splice page-table idiom: a declared-bits row decodes via
    ``& mask`` into a provable index."""
    t = jnp.arange(16, dtype=jnp.int32)
    ctx, _ = interp(
        lambda t, v: jnp.take(t, v & 0xF),
        t, bc.AbsVal(np.int32), args=[t, jnp.zeros((8,), jnp.int32)])
    assert not errors(ctx)


# --- fixpoint termination ---------------------------------------------------


def test_scan_carry_fixpoint_terminates_and_widens():
    """A strictly growing loop carry must widen to dtype-top within
    WIDEN_AFTER joins instead of iterating the interval lattice — the
    termination bound of the fixpoint."""

    def fn(x):
        def step(c, _):
            return c + x, ()
        out, _ = jax.lax.scan(step, jnp.int32(0), None, length=1000)
        return out

    ctx, (out,) = interp(fn, bc.AbsVal(np.int32, 1, 1),
                         args=[jnp.int32(1)])
    assert out.hi == np.iinfo(np.int32).max


def test_fori_loop_bounded_carry_stays_bounded():
    def fn(t):
        def body(_, c):
            return jnp.clip(c + 1, 0, 7)
        return jax.lax.fori_loop(0, 100, body, jnp.int32(0))

    ctx, (out,) = interp(fn, bc.AbsVal(np.int32, 0, 0),
                         args=[jnp.int32(0)])
    assert 0 <= out.lo and out.hi <= 7


# --- declared-bound seeding -------------------------------------------------


def test_tensor_bounds_roles_resolve():
    b = contracts.resolve_bounds("flow-page-table",
                                 np.zeros(8, np.int32), spec=4)
    assert b[""] == contracts.TensorBound(-1, 3)
    assert contracts.resolve_bounds("no-such-role", None) == {}


def test_check_declared_bounds_runtime_half():
    ok = contracts.check_declared_bounds(
        "flow-page-table", np.array([-1, 0, 3], np.int32), spec=4)
    assert ok == []
    bad = contracts.check_declared_bounds(
        "flow-page-table", np.array([4], np.int32), spec=4)
    assert bad and "escape" in bad[0]


def test_seed_absvals_applies_declared_interval():
    arr = np.zeros(8, np.int32)
    flat = bc.seed_absvals(
        (arr, arr), ((1, "flow-page-table", lambda: 4),))
    assert flat[0].lo == np.iinfo(np.int32).min     # unseeded: top
    assert (flat[1].lo, flat[1].hi) == (-1, 3)      # declared


# --- suppression loader -----------------------------------------------------


def test_suppression_requires_justification(tmp_path):
    p = tmp_path / "s.txt"
    p.write_text("int-wrap foo:*\n")
    with pytest.raises(ValueError):
        _suppress.load_suppressions(str(p))


def test_suppression_scoped_by_check_and_glob(tmp_path):
    p = tmp_path / "s.txt"
    p.write_text("int-wrap *:mul:*  # modular on purpose\n")
    supp = _suppress.load_suppressions(str(p))
    assert _suppress.match(supp, "int-wrap", "e:mul:uint32@f.py:1")
    assert not _suppress.match(supp, "oob-gather", "e:mul:uint32@f.py:1")
    assert not _suppress.match(supp, "int-wrap", "e:add:uint32@f.py:1")


def test_shipped_suppressions_load_and_are_justified():
    supp = _suppress.load_suppressions(bc.default_suppressions_path())
    assert supp, "shipped suppression file must exist and be non-empty"
    assert all(s[2] for s in supp)
    assert all(s[0] in ("int-wrap", "oob-gather", "oob-scatter")
               for s in supp)


# --- injected-defect registry -----------------------------------------------


def test_defect_registry_flags_resolve():
    import importlib

    for d in defects.DEFECTS.values():
        if d.module:
            mod = importlib.import_module(d.module)
            assert hasattr(mod, d.flag), (d.name, d.flag)
            assert getattr(mod, d.flag) is False, (
                f"{d.name}: injection flag must ship off")
        assert d.expect


def test_defect_registry_checker_slices():
    assert set(defects.names("bounds")) == {"clampgather", "i8wrap"}
    assert "joined-pad" in defects.names("state")
    assert defects.names("lock") == ["lockorder"]
    assert defects.names("sched") == ["cowrace"]
    for d in defects.by_checker("bounds"):
        assert d.entry and d.check and d.env


def test_defect_set_flag_roundtrip():
    import importlib

    d = defects.get("i8wrap")
    mod = importlib.import_module(d.module)
    defects.set_flag(d, True)
    try:
        assert getattr(mod, d.flag) is True
    finally:
        defects.set_flag(d, False)
    assert getattr(mod, d.flag) is False


# --- fleet sweep + CLI acceptances (slow) -----------------------------------


def _cli(*argv):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, LINT, *argv], capture_output=True, text=True,
        env=env, cwd=REPO)


@pytest.mark.slow
def test_full_fleet_sweep_clean():
    """Every registered entrypoint audits clean: zero unsuppressed
    findings, zero audit errors, every index site proved/guarded/
    dead — the make bounds-check gate, in-process."""
    reports = bc.audit_all(witness=False)
    summary = bc.summarize(reports)
    assert summary["audit_errors"] == 0, [r.error for r in reports if r.error]
    assert summary["errors"] == 0, [
        f.subject for r in reports for f in r.findings
        if f.severity == "error"]
    assert summary["entries"] >= 30
    assert summary["proved"] >= 250
    assert summary["guarded"] >= 200
    assert summary["suppressed"] >= 60
    # every suppressed finding names its justification
    for r in reports:
        for f in r.suppressed:
            assert f.suppressed_by


@pytest.mark.slow
def test_wrap_findings_carry_source_attribution():
    """Suppressed wrap residue must point at the kernel line (the
    sharply-scoped suppression subjects), not the jax internals."""
    reports = bc.audit_all(witness=False)
    tagged = [f for r in reports for f in r.suppressed
              if f.check == "int-wrap"]
    assert tagged
    assert all("@" in f.subject and ".py:" in f.subject for f in tagged)


@pytest.mark.slow
def test_cli_bounds_strict_clean():
    proc = _cli("bounds", "--strict", "--no-witness")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stdout


@pytest.mark.slow
def test_cli_clampgather_acceptance():
    """Fresh process (the flag acts at trace time): the dropped
    & _SPLICE_PAGE_MASK decode must be reported as oob-gather AND
    concretized by a diverging bank-1 witness."""
    proc = _cli("bounds", "--inject-defect", "clampgather")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CAUGHT clampgather" in proc.stdout
    assert "oob-gather" in proc.stdout
    assert "diverge" in proc.stdout


@pytest.mark.slow
def test_cli_i8wrap_acceptance():
    proc = _cli("bounds", "--inject-defect", "i8wrap")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CAUGHT i8wrap" in proc.stdout
    assert "int-wrap" in proc.stdout
    assert "diverge" in proc.stdout


@pytest.mark.slow
def test_cli_acceptance_loop_bounds_slice():
    proc = _cli("acceptance", "--checker", "bounds")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 missed" in proc.stdout
