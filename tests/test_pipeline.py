"""Pipelined admissions: superbatch epoch loop + two-slot overlap
(ISSUE-16).

Covers: bit-identity of the K-stacked device epoch program
(jitted_resident_superbatch via prepare_packed_super /
classify_prepared_super) vs K sequential fused dispatches AND the CPU
oracle — verdicts, statistics and the donated flow columns — including
out-of-order row materialize (the host flow-model mirror must drain in
device-epoch order); superbatch eligibility (shape-class gating,
degrade-never-refuse); the daemon's ring gather (same-shape chunks
coalesce into one superbatch dispatch, mismatches carry over); slot
parity accounting; ring occupancy/backpressure gauges; the DeviceStripe
round-robin mesh leg; and the donation-lint registration of the
superbatch entrypoints (while-loop carry aliasing, defect acceptance).

The jit-heavy superbatch/striping legs are slow-marked: tier-1 carries
the cheap contract tests, `make state-check` (statecheck pipeline
config + slotepoch defect) and `make pipeline-bench` carry the
exhaustive bit-identity and steady-state coverage.
"""
import os

import numpy as np
import pytest

from infw import oracle, testing
from infw.backend.tpu import TpuClassifier
from infw.flow import FlowConfig
from infw.ring import IngestRing

ENTRIES = 512  # the shared test_resident geometry: compiles amortize


def _tables(seed=3, n=300, width=4, v6=0.4):
    return testing.random_tables_fast(
        np.random.default_rng(seed), n_entries=n, width=width,
        v6_fraction=v6, ifindexes=(2, 3),
    )


def _resident(tabs, **kw):
    clf = TpuClassifier(
        interpret=True, flow_table=FlowConfig.make(entries=ENTRIES),
        resident=True, force_path="trie", **kw,
    )
    clf.load_tables(tabs)
    return clf


def _chunks(tabs, bs, n_chunks, seed=41):
    batch = testing.random_batch_fast(
        np.random.default_rng(seed), tabs, bs * n_chunks
    )
    wire = batch.pack_wire()
    tflags = (np.zeros(len(batch), np.int32) if batch.tcp_flags is None
              else np.asarray(batch.tcp_flags, np.int32))
    return batch, [
        (np.ascontiguousarray(wire[lo:lo + bs]),
         np.ascontiguousarray(tflags[lo:lo + bs]))
        for lo in range(0, len(batch), bs)
    ]


def _super_plan(clf, chunks, g, k):
    stack = np.stack([chunks[g + j][0] for j in range(k)])
    fstack = np.stack([chunks[g + j][1] for j in range(k)])
    plan = clf.prepare_packed_super(stack, False, tcp_flags_stack=fstack)
    assert plan is not None
    return plan


@pytest.mark.slow
@pytest.mark.parametrize("order", ["forward", "reverse"])
def test_superbatch_bit_identity(order):
    """One K=4 epoch-loop dispatch == 4 sequential fused dispatches ==
    the CPU oracle: verdicts, stats deltas and all donated flow columns
    — with rows materialized forward AND in reverse (the mirror queue
    must drain in device-epoch order regardless)."""
    tabs = _tables()
    k, bs = 4, 32
    batch, chunks = _chunks(tabs, bs, k)
    ref = oracle.classify(tabs, batch)
    sup = _resident(tabs)
    seq = _resident(tabs)

    seq_outs = []
    for w, tf in chunks:
        seq_outs.append(seq.classify_prepared(
            seq.prepare_packed(w, False, tcp_flags=tf), apply_stats=False
        ).result())
    rows = sup.classify_prepared_super(
        _super_plan(sup, chunks, 0, k), apply_stats=False
    )
    idx = range(k) if order == "forward" else range(k - 1, -1, -1)
    outs = {j: rows[j].result() for j in idx}
    for j in range(k):
        want = ref.results[j * bs:(j + 1) * bs]
        assert np.array_equal(outs[j].results, want)
        assert np.array_equal(outs[j].results, seq_outs[j].results)
        assert np.array_equal(outs[j].stats_delta, seq_outs[j].stats_delta)
    fc_sup = sup.flow.flow_columns()
    fc_seq = seq.flow.flow_columns()
    for name in fc_sup:
        assert np.array_equal(fc_sup[name], fc_seq[name]), name
    sup.close()
    seq.close()


@pytest.mark.slow
def test_superbatch_mixed_with_singles_slot_parity():
    """Superbatch and single dispatches interleave on the same tier:
    the epoch chain stays unbroken across both pipeline slots and the
    slot parity counters account for every single dispatch."""
    tabs = _tables()
    k, bs = 2, 32
    batch, chunks = _chunks(tabs, bs, 6, seed=43)
    ref = oracle.classify(tabs, batch)
    clf = _resident(tabs)
    outs = []
    # single, superbatch(2), single, single, superbatch would need 7;
    # drive: 1 single, K=2 super, 1 single, K=2 super over 6 chunks
    outs.append(clf.classify_prepared(
        clf.prepare_packed(chunks[0][0], False, tcp_flags=chunks[0][1]),
        apply_stats=False,
    ).result())
    outs.extend(r.result() for r in clf.classify_prepared_super(
        _super_plan(clf, chunks, 1, k), apply_stats=False
    ))
    outs.append(clf.classify_prepared(
        clf.prepare_packed(chunks[3][0], False, tcp_flags=chunks[3][1]),
        apply_stats=False,
    ).result())
    outs.extend(r.result() for r in clf.classify_prepared_super(
        _super_plan(clf, chunks, 4, k), apply_stats=False
    ))
    got = np.concatenate([o.results for o in outs])
    assert np.array_equal(got, ref.results)
    ctr = clf.resident_counters()
    assert (ctr["resident_slot0_dispatches_total"]
            + ctr["resident_slot1_dispatches_total"]) == 2
    assert ctr["resident_superbatch_dispatches_total"] == 2
    assert ctr["resident_superbatch_admissions_total"] == 4
    clf.close()


def test_superbatch_eligibility_gating():
    """prepare_packed_super degrades (returns None) instead of raising:
    non-resident classifier, 2-D wire, unsupported width."""
    tabs = _tables()
    clf = _resident(tabs)
    multi = TpuClassifier(
        interpret=True, flow_table=FlowConfig.make(entries=ENTRIES),
        force_path="trie",
    )
    multi.load_tables(tabs)
    _b, chunks = _chunks(tabs, 16, 2, seed=47)
    stack = np.stack([chunks[0][0], chunks[1][0]])
    assert multi.prepare_packed_super(stack, False) is None  # no resident
    assert clf.prepare_packed_super(chunks[0][0], False) is None  # 2-D
    assert clf.prepare_packed_super(stack[:, :, :5], False) is None
    assert clf.prepare_packed_super(stack, False) is not None
    clf.close()
    multi.close()


@pytest.mark.slow
def test_daemon_ring_superbatch_gather(tmp_path):
    """Daemon --ring with --superbatch-k: same-shape committed chunks
    coalesce into one epoch-loop dispatch (counted), a mismatched chunk
    carries to the next gather, every slot releases, stats land once."""
    from infw.daemon import Daemon

    ringp = str(tmp_path / "ingest.ring")
    daemon = Daemon(
        state_dir=str(tmp_path), node_name="n1", backend="tpu",
        resident=True, ring=ringp, superbatch_k=4, metrics_port=0,
        health_port=0, file_poll_interval_s=10.0,
        flow_table=FlowConfig.make(entries=ENTRIES),
    )
    try:
        tabs = _tables()
        clf = daemon.syncer._factory()
        clf.load_tables(tabs)
        daemon.syncer._classifier = clf
        batch, chunks = _chunks(tabs, 64, 5, seed=61)
        prod = IngestRing.attach(ringp)
        for w, tf in chunks[:4]:  # one shape class: one K=4 superbatch
            prod.push(w, v4_only=False, tcp_flags=tf)
        # a different shape class: must dispatch singly, not wedge
        w5, tf5 = chunks[4]
        prod.push(w5[:32], v4_only=False, tcp_flags=tf5[:32])
        n = daemon.process_ring_once(budget=10**9)
        assert n == 4 * 64 + 32
        assert daemon.ingest_ring.tail == daemon.ingest_ring.head
        ctr = clf.resident_counters()
        assert ctr["resident_superbatch_dispatches_total"] == 1
        assert ctr["resident_superbatch_admissions_total"] == 4
        ref = oracle.classify(tabs, batch.take(np.arange(4 * 64 + 32)))
        from infw.testing import stats_dict_from_array

        assert stats_dict_from_array(clf.stats.snapshot()) == ref.stats
        prod.close()
    finally:
        daemon.stop()


def test_ring_observability_gauges(tmp_path):
    """Occupancy high-watermark and producer-blocked time export as
    ring_* gauges, per process side: depth_hwm tracks the deepest
    committed backlog; blocked_us accumulates only when reserve waits
    on a full ring."""
    ring = IngestRing.create(str(tmp_path / "g.ring"), slots=2,
                             slot_packets=8)
    w = np.zeros((4, 7), np.uint32)
    ring.push(w)
    ring.push(w)  # full: depth 2
    cv = ring.counter_values()
    assert cv["ring_depth_hwm"] == 2
    assert cv["ring_blocked_us_total"] == 0
    with pytest.raises(TimeoutError):
        ring.push(w, timeout=0.05)  # blocks on the full ring
    assert ring.counter_values()["ring_blocked_us_total"] > 0
    chunk = ring.pop(timeout=1.0)
    chunk.release()
    ring.close()


def test_loadgen_ring_manifest_splits_backpressure():
    """tools/loadgen.py --ring manifest: ring-full blocking and genuine
    open-loop schedule lag are separate fields (the bugfix contract —
    producer stalls must not be misattributed to the dataplane)."""
    # the manifest keys are written by _ring_main; assert on the source
    # contract rather than spawning a daemon+producer pair here (the
    # subprocess path is covered by test_resident's loadgen leg)
    src = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "loadgen.py")).read()
    for key in ("worst_producer_lag_s", "ring_blocked_s",
                "ring_backpressured", "fell_behind"):
        assert key in src, key


@pytest.mark.slow
def test_device_stripe_round_robin():
    """DeviceStripe: whole admissions round-robin across per-device
    classifiers with independent flow state; verdicts match the oracle
    and the width rides counter_values."""
    from infw.backend.mesh import DeviceStripe

    tabs = _tables()
    stripe = DeviceStripe(
        width=2, interpret=True,
        flow_table=FlowConfig.make(entries=ENTRIES), resident=True,
        force_path="trie",
    )
    try:
        stripe.load_tables(tabs)
        batch, chunks = _chunks(tabs, 32, 4, seed=71)
        ref = oracle.classify(tabs, batch)
        outs = []
        for w, tf in chunks:
            clf = stripe.next_classifier()
            outs.append(clf.classify_prepared(
                clf.prepare_packed(w, False, tcp_flags=tf),
                apply_stats=False,
            ).result())
        got = np.concatenate([o.results for o in outs])
        assert np.array_equal(got, ref.results)
        cv = stripe.counter_values()
        assert cv["stripe_width"] == 2
        per_dev = [c.resident.counters["dispatches"]
                   for c in stripe.classifiers]
        assert all(d == 2 for d in per_dev), per_dev
    finally:
        stripe.close()


def test_superbatch_entrypoints_registered():
    """The epoch-loop entrypoints are registered with donate=
    declarations matching the single-step aliasing contract, and the
    loop-free defect fixture trips the superbatch-loop lint."""
    from infw.analysis import jaxcheck
    from infw.kernels import kernel_entrypoints

    eps = {e.name: e for e in kernel_entrypoints()}
    assert eps["classify-wire/resident-superbatch-fused"].donate == (0, 3)
    assert eps[
        "classify-wire/resident-superbatch-telemetry-fused"
    ].donate == (0, 3, 4)
    finds = jaxcheck._donation_lint(
        jaxcheck.superbatch_defect_entrypoint(), (16,)
    )
    assert any(f.check == "superbatch-loop" and f.severity == "error"
               for f in finds)
