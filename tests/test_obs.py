"""Observability tests: frame parse round-trips vs the batch oracle,
deny-event pipeline line formats, and the statistics poller/exposition
(reference: pkg/metrics/statistics.go behaviors + the e2e suites'
metrics/events assertions, e2e.go:1143-1356,1560-1620)."""
import re
import time

import numpy as np

from infw import oracle
from infw.backend.cpu_ref import CpuRefClassifier
from infw.constants import (
    DENY,
    IPPROTO_ICMP,
    IPPROTO_TCP,
    IPPROTO_UDP,
    KIND_IPV4,
    KIND_IPV6,
    KIND_MALFORMED,
    KIND_OTHER,
    XDP_DROP,
)
from infw.obs import pcap
from infw.obs.events import (
    EventHdr,
    EventRing,
    EventsLogger,
    decode_event_lines,
    emit_deny_events,
)
from infw.obs.statistics import (
    Statistics,
    add_uint64,
    get_prometheus_statistic_names,
)
from infw.spec import ACTION_DENY
from infw.syncer import DataplaneSyncer
from infw.interfaces import Interface, InterfaceRegistry
from test_syncer import ingress, tcp_rule

# --- pcap parse/build ---------------------------------------------------------

def test_frame_roundtrip_v4_tcp():
    f = pcap.build_frame("192.0.2.1", "10.0.0.1", IPPROTO_TCP, 1234, 80)
    kind, ok, words, proto, dport, it, ic, plen = pcap.parse_frame(f)
    assert (kind, ok, proto, dport) == (KIND_IPV4, 1, IPPROTO_TCP, 80)
    assert words[0] == int.from_bytes(bytes([192, 0, 2, 1]), "big")
    assert words[1:] == (0, 0, 0)
    assert plen == len(f)


def test_frame_roundtrip_v6_icmp6():
    f = pcap.build_frame("2001:db8::1", "2001:db8::2", 58, icmp_type=128, icmp_code=0)
    kind, ok, words, proto, dport, it, ic, plen = pcap.parse_frame(f)
    assert (kind, ok, proto, it, ic) == (KIND_IPV6, 1, 58, 128, 0)


def test_frame_edge_cases():
    # short ethernet -> malformed (kernel.c:423-426 -> XDP_DROP)
    assert pcap.parse_frame(b"\x00" * 10)[0] == KIND_MALFORMED
    # unknown ethertype -> KIND_OTHER -> PASS
    arp = b"\x02" * 12 + b"\x08\x06" + b"\x00" * 28
    assert pcap.parse_frame(arp)[0] == KIND_OTHER
    # truncated L4 -> l4_ok = 0 (ip_extract_l4info -1 -> UNDEF -> PASS)
    f = pcap.build_frame("192.0.2.1", "10.0.0.1", IPPROTO_TCP, 1, 2)[:-10]
    kind, ok, *_ = pcap.parse_frame(f)
    assert (kind, ok) == (KIND_IPV4, 0)
    # unknown L4 proto (GRE 47) -> l4_ok = 0
    f = pcap.build_frame("192.0.2.1", "10.0.0.1", 47)
    kind, ok, *_ = pcap.parse_frame(f)
    assert (kind, ok) == (KIND_IPV4, 0)


def test_parse_frames_batch_verdicts_match_oracle():
    """Raw frames -> batch -> classify: the full observability-path parse
    agrees with the dataplane."""
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    s = DataplaneSyncer(classifier_factory=CpuRefClassifier, registry=reg)
    s.sync_interface_ingress_rules(
        {"eth0": [ingress(["192.0.2.0/24"], [tcp_rule(1, 80, ACTION_DENY)])]}, False
    )
    frames = [
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 80),
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 81),
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_UDP, 999, 80),
        pcap.build_frame("203.0.113.9", "10.0.0.1", IPPROTO_TCP, 999, 80),
        b"\x00" * 8,  # malformed -> DROP
    ]
    batch = pcap.parse_frames(frames, ifindex=2)
    out = s.classifier.classify(batch)
    assert list(out.xdp) == [1, 2, 2, 2, 1]
    o = oracle.classify(s.classifier.tables, batch)
    assert list(o.xdp) == list(out.xdp)


# --- event pipeline -----------------------------------------------------------

def test_event_hdr_wire_roundtrip():
    hdr = EventHdr(if_id=3, rule_id=7, action=XDP_DROP, pkt_length=99)
    assert EventHdr.unpack(hdr.pack()) == hdr
    # ifId is u32: Linux ifindexes beyond 65535 (many-netns hosts; the
    # compiler admits up to MAX_IFINDEX = 1<<20) must survive the header
    big = EventHdr(if_id=1 << 20, rule_id=7, action=XDP_DROP, pkt_length=99)
    assert EventHdr.unpack(big.pack()) == big


def test_emit_and_decode_deny_events():
    frames = [
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 80),
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 81),
        pcap.build_frame("2001:db8::7", "2001:db8::1", IPPROTO_ICMP + 57, icmp_type=128),
    ]
    batch = pcap.parse_frames(frames, ifindex=2)
    # results: rule 5 deny, allow, rule 6 deny
    results = np.array([(5 << 8) | DENY, 2, (6 << 8) | DENY], np.uint32)
    ring = EventRing()
    n = emit_deny_events(ring, results, batch.ifindex, batch.pkt_len, frames)
    assert n == 2 and len(ring) == 2

    recs = ring.pop_all()
    lines = decode_event_lines(recs[0], "eth0")
    assert lines[0] == f"ruleId 5 action Drop len {len(frames[0])} if eth0"
    assert lines[1] == "\tipv4 src addr 192.0.2.9 dst addr 10.0.0.1"
    assert lines[2] == "\ttcp srcPort 999 dstPort 80"

    lines6 = decode_event_lines(recs[1], "eth0")
    assert lines6[1] == "\tipv6 src addr 2001:db8::7 dst addr 2001:db8::1"
    assert lines6[2] == "\ticmpv6 type 128 code 0"


def test_event_ring_overflow_lost_samples():
    from infw.obs.events import EventRecord

    ring = EventRing(capacity=2)
    for _ in range(5):
        ring.push(EventRecord(hdr=EventHdr(1, 1, 1, 1), packet=b""))
    assert len(ring) == 2
    assert ring.lost_samples == 3


def test_events_logger_drains_to_sink():
    ring = EventRing()
    frames = [pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 80)]
    batch = pcap.parse_frames(frames, ifindex=2)
    emit_deny_events(
        ring, np.array([(1 << 8) | DENY], np.uint32), batch.ifindex, batch.pkt_len, frames
    )
    out = []
    logger = EventsLogger(ring, out.append, iface_names={2: "eth0"}, poll_interval_s=0.01)
    logger.start()
    deadline = time.time() + 2
    while not out and time.time() < deadline:
        time.sleep(0.01)
    logger.stop()
    assert any(re.match(r"ruleId 1 action Drop len \d+ if eth0", l) for l in out)


# --- statistics ---------------------------------------------------------------

def test_add_uint64_overflow():
    assert add_uint64(1, 2) == (3, True)
    assert add_uint64(0, 5) == (5, True)
    v, ok = add_uint64((1 << 64) - 1, 2)
    assert not ok


def test_statistic_names():
    assert get_prometheus_statistic_names() == [
        "ingressnodefirewall_node_packet_allow_total",
        "ingressnodefirewall_node_packet_allow_bytes",
        "ingressnodefirewall_node_packet_deny_total",
        "ingressnodefirewall_node_packet_deny_bytes",
    ]


def test_statistics_update_and_exposition():
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    s = DataplaneSyncer(classifier_factory=CpuRefClassifier, registry=reg)
    s.sync_interface_ingress_rules(
        {"eth0": [ingress(["192.0.2.0/24"], [tcp_rule(1, 80, ACTION_DENY)])]}, False
    )
    frames = [
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 80),  # deny
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 80),  # deny
        pcap.build_frame("192.0.2.9", "10.0.0.1", IPPROTO_TCP, 999, 81),  # no match
    ]
    batch = pcap.parse_frames(frames, ifindex=2)
    s.classifier.classify(batch)

    stats = Statistics(poll_period_s=3600)
    stats.update_metrics(s.classifier)
    vals = stats.values()
    assert vals["packet_deny_total"] == 2
    assert vals["packet_deny_bytes"] == 2 * len(frames[0])
    assert vals["packet_allow_total"] == 0  # no-match PASS is rule 0: not counted

    text = stats.render_prometheus_text()
    assert "# TYPE ingressnodefirewall_node_packet_deny_total gauge" in text
    assert re.search(r"^ingressnodefirewall_node_packet_deny_total 2$", text, re.M)


def test_statistics_poller_start_stop():
    class FakeClassifier:
        def __init__(self):
            from infw.backend.base import StatsAccumulator

            self._stats = StatsAccumulator()

        @property
        def stats(self):
            return self._stats

    stats = Statistics(poll_period_s=0.01)
    c = FakeClassifier()
    stats.start_poll(c)
    assert stats.is_polling
    stats.start_poll(c)  # no-op double start (statistics.go:89-92)
    time.sleep(0.05)
    stats.stop_poll()
    assert not stats.is_polling
    stats.stop_poll()  # no-op double stop


def test_statistics_registry_exposition():
    """register() feeds a real process-level registry: one exposition
    call renders the sum over every registered poller (the shared
    Prometheus-registry role, statistics.go:79-86), and unregister
    removes an instance."""
    from infw.obs import statistics as st

    class _FakeClf:
        def __init__(self, deny):
            import numpy as np

            snap = np.zeros((16, 4), np.int64)
            snap[1] = [0, 0, deny, deny * 100]
            self._snap = snap

        @property
        def stats(self):
            outer = self

            class _S:
                def snapshot(self):
                    return outer._snap
            return _S()

    reg = st.Registry()  # isolated from the process-level default
    a, b = st.Statistics(), st.Statistics()
    a.register(reg); a.register(reg)  # regOnce: idempotent per registry
    b.register(reg)
    a.update_metrics(_FakeClf(2))
    b.update_metrics(_FakeClf(3))
    text = st.render_registry_text(reg)
    assert "ingressnodefirewall_node_packet_deny_total 5" in text
    assert "ingressnodefirewall_node_packet_deny_bytes 500" in text
    b.unregister()
    text = reg.render_text()
    assert "ingressnodefirewall_node_packet_deny_total 2" in text
    a.unregister()
    b.unregister()  # no-op double unregister
    assert "deny_total 0" in reg.render_text()


def test_statistics_registry_weakrefs():
    """A collector registered and then dropped without unregister (a
    crash-looped daemon construction) must fall out of the exposition
    with the instance instead of inflating sums forever (round-3 advisor
    finding)."""
    import gc

    from infw.obs import statistics as st

    reg = st.Registry()
    a = st.Statistics()
    a.register(reg)
    with a._lock:
        a._values["packet_deny_total"] = 7
    assert "deny_total 7" in reg.render_text()
    del a
    gc.collect()
    assert reg.collectors() == []
    assert "deny_total 0" in reg.render_text()


def test_statistics_register_moves_between_registries():
    from infw.obs import statistics as st

    r1, r2 = st.Registry(), st.Registry()
    a = st.Statistics()
    a.register(r1)
    a.register(r2)  # move: must leave r1
    assert r1.collectors() == []
    assert r2.collectors() == [a]
    a.unregister()
    assert r2.collectors() == []


def test_batch_deny_record_pipeline(tmp_path):
    """Replay-scale deny sets travel as ONE BatchDenyRecord (vectorized
    columns), drain as binary spill rows, and export loss/queue counters
    on the metrics registry (round-4 weak #2)."""
    import numpy as np

    from infw.obs import events as ev
    from infw.obs.statistics import Registry
    from infw.packets import make_batch

    n = ev.BATCH_EMIT_THRESHOLD * 2
    batch = make_batch(
        src=["10.0.0.1"] * n, proto=[6] * n, ifindex=[2] * n,
        dst_port=[80] * n)
    results = np.full(n, (7 << 8) | 1, np.uint32)  # ruleId 7, DENY
    ring = ev.EventRing(capacity=n + 10)
    seen = ev.emit_deny_events(
        ring, results, np.asarray(batch.ifindex), np.asarray(batch.pkt_len),
        batch=batch)
    assert seen == n
    assert len(ring) == n
    assert ring.lost_samples == 0

    spill = str(tmp_path / "deny.bin")
    lines = []
    logger = ev.EventsLogger(ring, lines.append, spill_path=spill)
    assert logger.drain_once() == n
    assert logger.spilled_total == n
    rows = np.fromfile(spill, dtype=ev.BatchDenyRecord.SPILL_DTYPE)
    assert len(rows) == n
    assert int(rows["result"][0]) == (7 << 8) | 1
    assert bytes(rows["src"][0][:4]) == bytes([10, 0, 0, 1])
    assert int(rows["dst_port"][0]) == 80
    assert any("spilled" in l for l in lines)

    # partial-fit batch: overflow is accounted, prefix delivered
    small = ev.EventRing(capacity=100)
    ev.emit_deny_events(
        small, results, np.asarray(batch.ifindex),
        np.asarray(batch.pkt_len), batch=batch)
    assert len(small) == 100
    assert small.lost_samples == n - 100

    reg = Registry()
    reg.register_counters(small)
    text = reg.render_text()
    assert f"ingressnodefirewall_node_events_lost_total {n - 100}" in text
    assert "ingressnodefirewall_node_events_queued_total 100" in text
    assert "# TYPE ingressnodefirewall_node_events_lost_total counter" in text


def test_batch_deny_record_lines_without_spill():
    """No spill sink: batch records render compact per-event lines with
    the src address decoded from the parsed columns."""
    import numpy as np

    from infw.obs import events as ev
    from infw.packets import make_batch

    n = ev.BATCH_EMIT_THRESHOLD + 1
    batch = make_batch(
        src=["192.0.2.9"] * n, proto=[17] * n, ifindex=[3] * n,
        dst_port=[53] * n)
    results = np.full(n, (2 << 8) | 1, np.uint32)
    ring = ev.EventRing(capacity=2 * n)
    ev.emit_deny_events(
        ring, results, np.asarray(batch.ifindex), np.asarray(batch.pkt_len),
        batch=batch)
    lines = []
    logger = ev.EventsLogger(ring, lines.append, iface_names={3: "eth1"})
    assert logger.drain_once() == n
    assert any("ruleId 2 action Drop" in l and "if eth1" in l for l in lines)
    assert any("ipv4 src addr 192.0.2.9" in l for l in lines)
