"""Syncer lifecycle tests — the port of the reference's root-gated
dataplane integration suite (/root/reference/pkg/ebpfsyncer/ebpfsyncer_test.go):
veth pairs + netcat probes become synthetic packet batches; reachability
tables become golden verdict vectors; bpffs pins become the compiled-table
checkpoint; the `once = sync.Once{}` restart trick becomes
reset_singleton_for_test().
"""
import pytest

from infw import syncer as syncer_mod
from infw.backend.cpu_ref import CpuRefClassifier
from infw.constants import DENY, XDP_DROP, XDP_PASS
from infw.interfaces import Interface, InterfaceRegistry
from infw.packets import make_batch
from infw.spec import (
    ACTION_ALLOW,
    ACTION_DENY,
    IngressNodeFirewallICMPRule,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallRules,
    IngressNodeProtocolConfig,
    PROTOCOL_TYPE_ICMP,
    PROTOCOL_TYPE_TCP,
    PROTOCOL_TYPE_UDP,
    PROTOCOL_TYPE_UNSET,
)
from infw.syncer import AttachBusyError, DataplaneSyncer, SyncError


class CountingClassifier(CpuRefClassifier):
    """CpuRefClassifier that counts device table loads (the re-sync
    idempotency probe: unchanged rules must not reload,
    ebpfsyncer_test.go:598-726)."""

    def __init__(self):
        super().__init__()
        self.load_count = 0

    def load_tables(self, tables, dirty_hint=None):
        self.load_count += 1
        super().load_tables(tables, dirty_hint=dirty_hint)


def tcp_rule(order, ports, action):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol=PROTOCOL_TYPE_TCP, tcp=IngressNodeFirewallProtoRule(ports=ports)
        ),
        action=action,
    )


def udp_rule(order, ports, action):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol=PROTOCOL_TYPE_UDP, udp=IngressNodeFirewallProtoRule(ports=ports)
        ),
        action=action,
    )


def icmp_rule(order, itype, icode, action):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(
            protocol=PROTOCOL_TYPE_ICMP,
            icmp=IngressNodeFirewallICMPRule(icmp_type=itype, icmp_code=icode),
        ),
        action=action,
    )


def catchall_rule(order, action):
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(protocol=PROTOCOL_TYPE_UNSET),
        action=action,
    )


def ingress(cidrs, rules):
    return IngressNodeFirewallRules(source_cidrs=list(cidrs), rules=list(rules))


@pytest.fixture
def registry():
    """The veth fixture (ebpfsyncer_test.go:1253-1317): dummy0..2."""
    reg = InterfaceRegistry()
    for i, name in enumerate(["dummy0", "dummy1", "dummy2"]):
        reg.add(Interface(name=name, index=10 + i))
    return reg


@pytest.fixture
def make_syncer(registry, tmp_path):
    def _make(**kw):
        kw.setdefault("classifier_factory", CountingClassifier)
        kw.setdefault("registry", registry)
        kw.setdefault("checkpoint_dir", str(tmp_path / "ck"))
        kw.setdefault("ebusy_retry_interval_s", 0.001)
        return DataplaneSyncer(**kw)

    return _make


IF0, IF1 = 10, 11  # dummy0, dummy1 indices


# --- reachability verdict tables (TestSyncInterfaceIngressRulesWithHTTP,
# ebpfsyncer_test.go:41-447) -------------------------------------------------

def verdicts(s, src, proto, dport, ifidx, itype=None, icode=None):
    batch = make_batch(
        src=src,
        proto=proto,
        dst_port=dport,
        ifindex=ifidx,
        icmp_type=itype,
        icmp_code=icode,
    )
    return list(s.classifier.classify(batch).xdp)


def test_deny_tcp_port_from_cidr(make_syncer):
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])]},
        False,
    )
    got = verdicts(
        s,
        src=["192.0.2.1", "192.0.2.1", "192.0.2.5", "198.51.100.1"],
        proto=[6, 6, 6, 6],
        dport=[80, 81, 80, 80],
        ifidx=[IF0, IF0, IF0, IF0],
    )
    #            in-CIDR:80→DROP  in-CIDR:81→PASS  out-of-CIDR→PASS ×2
    assert got == [XDP_DROP, XDP_PASS, XDP_PASS, XDP_PASS]


def test_allow_then_catchall_deny(make_syncer):
    """Ordered first-match: Allow tcp/80 at order 1, protocol-catch-all Deny
    at order 2 (kernel.c:229-257 scan semantics)."""
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {
            "dummy0": [
                ingress(
                    ["192.0.2.0/24"],
                    [tcp_rule(1, 80, ACTION_ALLOW), catchall_rule(2, ACTION_DENY)],
                )
            ]
        },
        False,
    )
    got = verdicts(
        s,
        src=["192.0.2.7"] * 4,
        proto=[6, 6, 17, 1],
        dport=[80, 443, 53, 0],
        ifidx=[IF0] * 4,
        itype=[0, 0, 0, 8],
    )
    assert got == [XDP_PASS, XDP_DROP, XDP_DROP, XDP_DROP]


def test_port_range_half_open(make_syncer):
    """Kernel range match is [start, end) (kernel.c:241)."""
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["10.0.0.0/8"], [tcp_rule(1, "800-900", ACTION_DENY)])]},
        False,
    )
    got = verdicts(
        s,
        src=["10.1.2.3"] * 4,
        proto=[6] * 4,
        dport=[799, 800, 899, 900],
        ifidx=[IF0] * 4,
    )
    assert got == [XDP_PASS, XDP_DROP, XDP_DROP, XDP_PASS]


def test_icmp_and_udp_rules(make_syncer):
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {
            "dummy0": [
                ingress(
                    ["192.0.2.0/30"],
                    [icmp_rule(1, 8, 0, ACTION_DENY), udp_rule(2, 53, ACTION_DENY)],
                )
            ]
        },
        False,
    )
    got = verdicts(
        s,
        src=["192.0.2.1"] * 4,
        proto=[1, 1, 17, 17],
        dport=[0, 0, 53, 54],
        ifidx=[IF0] * 4,
        itype=[8, 9, 0, 0],
        icode=[0, 0, 0, 0],
    )
    # echo-request dropped, type 9 passes; udp 53 dropped, 54 passes
    assert got == [XDP_DROP, XDP_PASS, XDP_DROP, XDP_PASS]


def test_ipv6_cidr(make_syncer):
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["2001:db8::/64"], [tcp_rule(1, 80, ACTION_DENY)])]},
        False,
    )
    got = verdicts(
        s,
        src=["2001:db8::5", "2001:db9::5"],
        proto=[6, 6],
        dport=[80, 80],
        ifidx=[IF0, IF0],
    )
    assert got == [XDP_DROP, XDP_PASS]


def test_per_interface_isolation(make_syncer):
    """Rules keyed by ingress ifindex: traffic on dummy1 is unaffected by
    dummy0's table (multi-interface TCs, ebpfsyncer_test.go:449-596)."""
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {
            "dummy0": [ingress(["0.0.0.0/0"], [tcp_rule(1, 80, ACTION_DENY)])],
            "dummy1": [ingress(["0.0.0.0/0"], [tcp_rule(1, 443, ACTION_DENY)])],
        },
        False,
    )
    got = verdicts(
        s,
        src=["198.51.100.9"] * 4,
        proto=[6] * 4,
        dport=[80, 443, 80, 443],
        ifidx=[IF0, IF0, IF1, IF1],
    )
    assert got == [XDP_DROP, XDP_PASS, XDP_PASS, XDP_DROP]
    assert s.attached_interfaces() == {"dummy0", "dummy1"}


# --- attach/detach + idempotency ---------------------------------------------

def test_attach_detach_lifecycle(make_syncer, registry):
    s = make_syncer()
    rules = {"dummy0": [ingress(["1.1.1.0/24"], [tcp_rule(1, 22, ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules, False)
    assert registry.get("dummy0").xdp_attached
    assert not registry.get("dummy1").xdp_attached

    # moving the ruleset to dummy1 detaches the now-unmanaged dummy0
    rules2 = {"dummy1": [ingress(["1.1.1.0/24"], [tcp_rule(1, 22, ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules2, False)
    assert not registry.get("dummy0").xdp_attached
    assert registry.get("dummy1").xdp_attached
    assert s.attached_interfaces() == {"dummy1"}


def test_invalid_interface_skipped(make_syncer, registry):
    """Invalid (down/loopback/missing) interfaces are skipped without
    failing the sync (ebpfsyncer.go:185-191, loader.go:141-148)."""
    registry.add(Interface(name="downif", index=99, up=False))
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {
            "downif": [ingress(["1.1.1.0/24"], [tcp_rule(1, 22, ACTION_DENY)])],
            "dummy0": [ingress(["2.2.2.0/24"], [tcp_rule(1, 22, ACTION_DENY)])],
        },
        False,
    )
    assert s.attached_interfaces() == {"dummy0"}
    content = s.get_classifier_map_content_for_test()
    assert all(k.ingress_ifindex == IF0 for k in content)


def test_resync_idempotent_no_reload(make_syncer):
    """Unchanged desired state must not touch the device tables
    (re-sync idempotency, ebpfsyncer_test.go:598-726)."""
    s = make_syncer()
    rules = {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules, False)
    assert s.classifier.load_count == 1
    s.sync_interface_ingress_rules(rules, False)
    s.sync_interface_ingress_rules(rules, False)
    assert s.classifier.load_count == 1

    # a rule change does reload
    rules2 = {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 81, ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules2, False)
    assert s.classifier.load_count == 2


def test_map_content_whitebox(make_syncer):
    """White-box table content assertions
    (TestVerifyBPFKeysAfterInterfaceIngressRulesUpdate,
    ebpfsyncer_test.go:727-989)."""
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {
            "dummy0": [
                ingress(["192.0.2.0/30", "10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])
            ]
        },
        False,
    )
    content = s.get_classifier_map_content_for_test()
    idents = {(k.prefix_len, k.ingress_ifindex) for k in content}
    assert idents == {(30 + 32, IF0), (8 + 32, IF0)}
    for rules in content.values():
        assert rules[1, 0] == 1          # ruleId == order
        assert rules[1, 1] == 6          # IPPROTO_TCP
        assert rules[1, 2] == 80 and rules[1, 3] == 0  # single port: end==0
        assert rules[1, 6] == DENY

    # update: drop one CIDR — its key must be purged
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]},
        False,
    )
    content = s.get_classifier_map_content_for_test()
    assert {(k.prefix_len, k.ingress_ifindex) for k in content} == {(40, IF0)}


def test_delete_resets_all(make_syncer, registry, tmp_path):
    s = make_syncer()
    rules = {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules, False)
    assert (tmp_path / "ck" / "tables.npz").exists()

    s.sync_interface_ingress_rules(rules, True)
    assert s.classifier is None
    assert s.attached_interfaces() == set()
    assert not registry.get("dummy0").xdp_attached
    assert not (tmp_path / "ck" / "tables.npz").exists()
    with pytest.raises(SyncError):
        s.get_classifier_map_content_for_test()


def test_ebusy_retry(make_syncer, registry):
    """Attach retries on busy interfaces (ebpfsyncer.go:193-207)."""
    fails = {"n": 3}

    def flaky_attach(name):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise AttachBusyError(name)
        registry.set_xdp(name, True)

    s = make_syncer(attach_fn=flaky_attach)
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["1.0.0.0/8"], [tcp_rule(1, 1, ACTION_DENY)])]}, False
    )
    assert s.attached_interfaces() == {"dummy0"}

    fails["n"] = 10**9  # forever-busy: sync fails after max retries
    s2 = make_syncer(attach_fn=flaky_attach)
    with pytest.raises(SyncError):
        s2.sync_interface_ingress_rules(
            {"dummy1": [ingress(["1.0.0.0/8"], [tcp_rule(1, 1, ACTION_DENY)])]}, False
        )


# --- restart recovery (checkpoint re-adoption) --------------------------------

def test_restart_readoption(make_syncer, registry, tmp_path):
    """Crash-restart recovery (TestInterfaceAttachments TC1,
    ebpfsyncer_test.go:1045-1053): a new syncer over the same checkpoint
    dir re-adopts tables + attachments without recompiling."""
    rules = {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])]}
    s = make_syncer()
    s.sync_interface_ingress_rules(rules, False)
    before = verdicts(s, src=["192.0.2.1"], proto=[6], dport=[80], ifidx=[IF0])
    s.shutdown()  # daemon dies; checkpoint ("pins") survives

    s2 = make_syncer()
    s2.sync_interface_ingress_rules(rules, False)
    # one load for adoption, none for the no-op diff
    assert s2.classifier.load_count == 1
    assert s2.attached_interfaces() == {"dummy0"}
    after = verdicts(s2, src=["192.0.2.1"], proto=[6], dport=[80], ifidx=[IF0])
    assert before == after == [XDP_DROP]


def test_resync_idempotent_with_aliasing_cidrs(make_syncer):
    """CIDRs that collapse after masking (10.0.0.0/8 vs 10.1.0.0/8) must
    still diff as unchanged across identical syncs, and the test-content
    API must report the entry the device actually enforces (last writer
    wins, kernel map-update semantics)."""
    s = make_syncer()
    rules = {
        "dummy0": [
            ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)]),
            ingress(["10.1.0.0/8"], [tcp_rule(1, 443, ACTION_DENY)]),
        ]
    }
    s.sync_interface_ingress_rules(rules, False)
    s.sync_interface_ingress_rules(rules, False)
    s.sync_interface_ingress_rules(rules, False)
    assert s.classifier.load_count == 1

    content = s.get_classifier_map_content_for_test()
    assert len(content) == 1
    [rows] = content.values()
    assert rows[1, 2] == 443  # last writer won
    got = verdicts(s, src=["10.2.3.4"] * 2, proto=[6, 6], dport=[80, 443], ifidx=[IF0] * 2)
    assert got == [XDP_PASS, XDP_DROP]


def test_restart_readoption_skips_down_interface(make_syncer, registry, tmp_path):
    """An interface that went down while the daemon was dead must not be
    re-attached on restart (matches the attach-path validity check)."""
    rules = {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])]}
    s = make_syncer()
    s.sync_interface_ingress_rules(rules, False)
    s.shutdown()
    registry.get("dummy0").up = False

    s2 = make_syncer()
    s2.sync_interface_ingress_rules({}, False)
    assert s2.attached_interfaces() == set()
    assert not registry.get("dummy0").xdp_attached


def test_restart_readoption_interface_gone(make_syncer, registry, tmp_path):
    """A checkpointed interface that vanished before restart is skipped
    with a warning, not a sync failure."""
    rules = {"dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])]}
    s = make_syncer()
    s.sync_interface_ingress_rules(rules, False)
    s.shutdown()
    registry.remove("dummy0")

    s2 = make_syncer()
    s2.sync_interface_ingress_rules({}, False)  # must not raise
    assert s2.attached_interfaces() == set()


def test_manifest_tracks_detach_without_rule_change(make_syncer, registry, tmp_path):
    """Detaching an interface whose table content contributes nothing must
    still update the checkpoint manifest, or a restart re-adopts it."""
    rules_both = {
        "dummy0": [ingress(["192.0.2.0/30"], [tcp_rule(1, 80, ACTION_DENY)])],
        "dummy1": [],
    }
    s = make_syncer()
    s.sync_interface_ingress_rules(rules_both, False)
    assert s.attached_interfaces() == {"dummy0", "dummy1"}

    rules_one = {"dummy0": rules_both["dummy0"]}
    s.sync_interface_ingress_rules(rules_one, False)  # content unchanged
    s.shutdown()

    s2 = make_syncer()
    s2.sync_interface_ingress_rules(rules_one, False)
    assert s2.attached_interfaces() == {"dummy0"}
    assert not registry.get("dummy1").xdp_attached


def test_shutdown_stops_stats_poller(make_syncer):
    events = []

    class Poller:
        def stop_poll(self):
            events.append("stop")

        def start_poll(self, classifier):
            events.append("start")

    s = make_syncer(stats_poller=Poller())
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["1.0.0.0/8"], [tcp_rule(1, 1, ACTION_DENY)])]}, False
    )
    s.shutdown()
    assert events == ["stop", "start", "stop"]


def test_singleton_semantics(make_syncer):
    syncer_mod.reset_singleton_for_test()
    a = syncer_mod.get_syncer(classifier_factory=CountingClassifier)
    b = syncer_mod.get_syncer()
    assert a is b
    syncer_mod.reset_singleton_for_test()
    c = syncer_mod.get_syncer(classifier_factory=CountingClassifier)
    assert c is not a


# --- stats poller pause/resume ------------------------------------------------

def test_stats_poller_paused_around_sync(make_syncer):
    events = []

    class Poller:
        def stop_poll(self):
            events.append("stop")

        def start_poll(self, classifier):
            events.append(("start", classifier is not None))

    s = make_syncer(stats_poller=Poller())
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["1.0.0.0/8"], [tcp_rule(1, 1, ACTION_DENY)])]}, False
    )
    assert events == ["stop", ("start", True)]


def test_compile_error_leaves_dataplane_untouched():
    """A schema-valid but compile-invalid update (bad port string) must not
    detach interfaces or drop the last-good rules: compilation happens
    before any attach-set mutation."""
    from infw.compiler import CompileError

    reg = InterfaceRegistry()
    for i, name in enumerate(["dummy0", "dummy1", "dummy2"]):
        reg.add(Interface(name=name, index=10 + i))
    s = DataplaneSyncer(classifier_factory=CpuRefClassifier, registry=reg)
    good = {"dummy0": [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]}
    s.sync_interface_ingress_rules(good, False)
    assert s.attached_interfaces() == {"dummy0"}
    before = s.get_classifier_map_content_for_test()

    bad_rule = tcp_rule(1, "80-abc", ACTION_DENY)
    bad = {"dummy1": [ingress(["10.0.0.0/8"], [bad_rule])]}
    with pytest.raises((SyncError, CompileError)):
        s.sync_interface_ingress_rules(bad, False)
    # dummy0 still attached, dummy1 never attached, content unchanged
    assert s.attached_interfaces() == {"dummy0"}
    after = s.get_classifier_map_content_for_test()
    assert set(before) == set(after)


def test_tpu_syncer_incremental_sync_takes_patch_path(make_syncer):
    """A one-CIDR edit through the full sync boundary with the TPU
    backend must engage the incremental device patch (dirty hints flow
    syncer -> classifier), and verdicts must track the edit."""
    from infw.backend.tpu import TpuClassifier

    s = make_syncer(
        classifier_factory=lambda: TpuClassifier(force_path="trie")
    )
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["10.1.0.0/16", "10.2.0.0/16"],
                            [tcp_rule(1, "80", ACTION_ALLOW)])]},
        False,
    )
    assert s.classifier._last_load[0] == "full"
    assert verdicts(s, ["10.1.9.9"], [6], [80], [IF0]) == [XDP_PASS]
    # flip the action on one rule set: same keys, patched rows.  The edit
    # flips TOWARD Deny so a lost patch (no-match default = PASS) cannot
    # masquerade as success.
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["10.1.0.0/16", "10.2.0.0/16"],
                            [tcp_rule(1, "80", ACTION_DENY)])]},
        False,
    )
    mode, n_rows = s.classifier._last_load
    assert mode == "patch" and n_rows > 0
    assert verdicts(s, ["10.1.9.9"], [6], [80], [IF0]) == [XDP_DROP]
    # add a CIDR: appends flow through the same hint path (again Deny, so
    # a dropped append would fail loudly as PASS)
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["10.1.0.0/16", "10.2.0.0/16", "10.3.0.0/16"],
                            [tcp_rule(1, "80", ACTION_DENY)])]},
        False,
    )
    assert s.classifier._last_load[0] == "patch"
    assert verdicts(s, ["10.3.9.9"], [6], [80], [IF0]) == [XDP_DROP]


def test_incremental_sync_journals_instead_of_full_checkpoint(
    make_syncer, tmp_path
):
    """A 1-key edit appends a journal record (O(delta)) instead of
    rewriting the full base npz; restart replays base + journal and the
    recovered table enforces the latest rules."""
    import os

    ck = tmp_path / "ck"
    s = make_syncer()
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(["192.0.2.0/30", "198.51.100.0/24"],
                            [tcp_rule(1, 80, ACTION_ALLOW)])]},
        False,
    )
    base_mtime = os.path.getmtime(ck / "tables.npz")
    assert not (ck / "journal").exists() or not os.listdir(ck / "journal")
    # three incremental edits: base untouched, journal grows
    for i, action in enumerate([ACTION_DENY, ACTION_ALLOW, ACTION_DENY]):
        s.sync_interface_ingress_rules(
            {"dummy0": [ingress(["192.0.2.0/30", "198.51.100.0/24"],
                                [tcp_rule(1, 80, action)])]},
            False,
        )
    assert os.path.getmtime(ck / "tables.npz") == base_mtime
    assert len(os.listdir(ck / "journal")) == 3
    s.shutdown()

    s2 = make_syncer()
    s2.sync_interface_ingress_rules(  # adoption; rules unchanged => no reload
        {"dummy0": [ingress(["192.0.2.0/30", "198.51.100.0/24"],
                            [tcp_rule(1, 80, ACTION_DENY)])]},
        False,
    )
    assert s2.classifier.load_count == 1  # re-adopt only, diff is clean
    got = verdicts(s2, src=["192.0.2.1"], proto=[6], dport=[80], ifidx=[IF0])
    assert got == [XDP_DROP]  # the journaled final state, not the base


def test_journal_overflow_compacts_to_base(make_syncer, tmp_path):
    import os

    ck = tmp_path / "ck"
    s = make_syncer()
    s.JOURNAL_MAX = 4
    rules = lambda p: {"dummy0": [ingress(["10.0.0.0/8"],
                                          [tcp_rule(1, str(p), ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules(80), False)
    for p in range(81, 81 + 4):
        s.sync_interface_ingress_rules(rules(p), False)
    assert len(os.listdir(ck / "journal")) == 4
    base_mtime = os.path.getmtime(ck / "tables.npz")
    s.sync_interface_ingress_rules(rules(99), False)  # overflow: compact
    assert os.path.getmtime(ck / "tables.npz") > base_mtime
    assert os.listdir(ck / "journal") == []
    # and the compacted base alone recovers the latest state
    s.shutdown()
    s2 = make_syncer()
    got = None
    s2.sync_interface_ingress_rules(rules(99), False)
    assert s2.classifier.load_count == 1
    got = verdicts(s2, src=["10.1.1.1"], proto=[6], dport=[99], ifidx=[IF0])
    assert got == [XDP_DROP]


def test_corrupt_journal_record_stops_replay_at_prefix(make_syncer, tmp_path):
    """A torn journal record must not poison recovery: records before it
    still apply, the corrupt one and everything after are ignored."""
    import os

    ck = tmp_path / "ck"
    s = make_syncer()
    rules = lambda p: {"dummy0": [ingress(["10.0.0.0/8"],
                                          [tcp_rule(1, str(p), ACTION_DENY)])]}
    s.sync_interface_ingress_rules(rules(80), False)
    s.sync_interface_ingress_rules(rules(81), False)
    s.sync_interface_ingress_rules(rules(82), False)
    files = sorted(os.listdir(ck / "journal"))
    assert len(files) == 2
    (ck / "journal" / files[1]).write_text("{torn")
    s.shutdown()
    s2 = make_syncer()
    s2.sync_interface_ingress_rules(rules(81), False)  # matches replayed prefix
    assert s2.classifier.load_count == 1
    got = verdicts(s2, src=["10.1.1.1"] * 2, proto=[6] * 2, dport=[81, 82],
                   ifidx=[IF0] * 2)
    assert got == [XDP_DROP, XDP_PASS]


def test_pending_delta_survives_failed_load_into_journal(make_syncer, tmp_path):
    """Sync A applies a delta to the updater but the device load fails;
    sync B succeeds with an empty diff-vs-updater.  The checkpoint must
    still learn sync A's delta (journaled by B), or a restart would
    enforce stale rules."""
    import os

    ck = tmp_path / "ck"
    s = make_syncer()
    rules = lambda a: {"dummy0": [ingress(["10.0.0.0/8"],
                                          [tcp_rule(1, "80", a)])]}
    s.sync_interface_ingress_rules(rules(ACTION_ALLOW), False)

    real_load = s.classifier.load_tables
    calls = {"n": 0}

    def flaky_load(tables, dirty_hint=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient device error")
        real_load(tables, dirty_hint=dirty_hint)

    s.classifier.load_tables = flaky_load
    with pytest.raises(Exception):
        s.sync_interface_ingress_rules(rules(ACTION_DENY), False)
    assert not (ck / "journal").exists() or not os.listdir(ck / "journal")
    # retry succeeds; the earlier delta must land in the journal
    s.sync_interface_ingress_rules(rules(ACTION_DENY), False)
    assert len(os.listdir(ck / "journal")) == 1
    s.shutdown()

    s2 = make_syncer()
    s2.sync_interface_ingress_rules(rules(ACTION_DENY), False)
    assert s2.classifier.load_count == 1  # adopt only: checkpoint was current
    got = verdicts(s2, src=["10.1.1.1"], proto=[6], dport=[80], ifidx=[IF0])
    assert got == [XDP_DROP]


# --- structural-add overlay (round-5 ask #2) --------------------------------


def _many_cidrs(n):
    return [f"10.{(i >> 8) & 255}.{i & 255}.0/24" for i in range(n)]


def test_overlay_structural_add_fast_path(make_syncer):
    """A NEW CIDR added to a trie-scale table routes to the dense
    overlay: the main device table takes a zero-or-tiny patch (no
    poptrie re-transform), and verdicts combine both tables with
    longest-prefix semantics."""
    from infw.backend.tpu import TpuClassifier

    s = make_syncer(
        classifier_factory=lambda: TpuClassifier(force_path="trie")
    )
    n = DataplaneSyncer.OVERLAY_MIN_MAIN + 50
    cidrs = _many_cidrs(n)
    rules = [tcp_rule(1, "80", ACTION_DENY)]
    s.sync_interface_ingress_rules({"dummy0": [ingress(cidrs, rules)]}, False)
    assert s.classifier._last_load[0] == "full"
    assert verdicts(s, ["192.0.9.9"], [6], [80], [IF0]) == [XDP_PASS]

    # add one new CIDR -> overlay (Deny, so a dropped add fails loudly)
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(cidrs + ["192.0.9.0/24"], rules)]}, False)
    assert len(s._overlay) == 1
    mode, n_rows = s.classifier._last_load
    assert mode == "patch", "main table must not re-upload for an add"
    assert verdicts(s, ["192.0.9.9"], [6], [80], [IF0]) == [XDP_DROP]
    assert verdicts(s, ["10.0.1.1"], [6], [80], [IF0]) == [XDP_DROP]
    assert verdicts(s, ["192.0.10.9"], [6], [80], [IF0]) == [XDP_PASS]

    # longest-prefix across tables: a /25 overlay Allow nested in an
    # existing main /24 Deny must win for its half of the space
    s.sync_interface_ingress_rules(
        {"dummy0": [
            ingress(cidrs + ["192.0.9.0/24"], rules),
            ingress(["10.0.1.0/25"], [tcp_rule(1, "80", ACTION_ALLOW)]),
        ]},
        False,
    )
    assert verdicts(s, ["10.0.1.1"], [6], [80], [IF0]) == [XDP_PASS]
    assert verdicts(s, ["10.0.1.200"], [6], [80], [IF0]) == [XDP_DROP]

    # rules edit of an overlay key patches the overlay in place
    s.sync_interface_ingress_rules(
        {"dummy0": [
            ingress(cidrs + ["192.0.9.0/24"], rules),
            ingress(["10.0.1.0/25"], [tcp_rule(1, "80", ACTION_DENY)]),
        ]},
        False,
    )
    assert verdicts(s, ["10.0.1.1"], [6], [80], [IF0]) == [XDP_DROP]

    # deleting the overlay keys drains the overlay without touching main
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(cidrs, rules)]}, False)
    assert len(s._overlay) == 0
    assert verdicts(s, ["192.0.9.9"], [6], [80], [IF0]) == [XDP_PASS]
    assert verdicts(s, ["10.0.1.1"], [6], [80], [IF0]) == [XDP_DROP]

    # content introspection reflects the union view throughout
    assert len(s.get_classifier_map_content_for_test()) == n


def test_overlay_overflow_merges_into_main(make_syncer):
    from infw.backend.tpu import TpuClassifier

    s = make_syncer(
        classifier_factory=lambda: TpuClassifier(force_path="trie")
    )
    s.OVERLAY_CAP = 3  # instance override
    n = DataplaneSyncer.OVERLAY_MIN_MAIN + 10
    cidrs = _many_cidrs(n)
    rules = [tcp_rule(1, "80", ACTION_DENY)]
    s.sync_interface_ingress_rules({"dummy0": [ingress(cidrs, rules)]}, False)
    extra = [f"192.0.{i}.0/24" for i in range(5)]
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(cidrs + extra[:2], rules)]}, False)
    assert len(s._overlay) == 2
    # 3 more would exceed the cap: everything merges into main
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(cidrs + extra, rules)]}, False)
    assert len(s._overlay) == 0
    for ip, want in (("192.0.0.1", XDP_DROP), ("192.0.4.1", XDP_DROP),
                     ("192.9.0.1", XDP_PASS)):
        assert verdicts(s, [ip], [6], [80], [IF0]) == [want]


def test_overlay_survives_restart(make_syncer, registry, tmp_path):
    """The overlay sidecar restores across a daemon restart even after a
    base checkpoint rewrite that excluded overlay keys."""
    from infw.backend.tpu import TpuClassifier

    factory = lambda: TpuClassifier(force_path="trie")
    s = make_syncer(classifier_factory=factory)
    n = DataplaneSyncer.OVERLAY_MIN_MAIN + 10
    cidrs = _many_cidrs(n)
    rules = [tcp_rule(1, "80", ACTION_DENY)]
    s.sync_interface_ingress_rules({"dummy0": [ingress(cidrs, rules)]}, False)
    s.sync_interface_ingress_rules(
        {"dummy0": [ingress(cidrs + ["192.0.9.0/24"], rules)]}, False)
    assert len(s._overlay) == 1
    s.shutdown()

    s2 = DataplaneSyncer(
        classifier_factory=factory, registry=registry,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    s2.sync_interface_ingress_rules(
        {"dummy0": [ingress(cidrs + ["192.0.9.0/24"], rules)]}, False)
    assert verdicts(s2, ["192.0.9.9"], [6], [80], [IF0]) == [XDP_DROP]
    assert verdicts(s2, ["10.0.1.1"], [6], [80], [IF0]) == [XDP_DROP]
