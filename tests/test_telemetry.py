"""Device-resident telemetry plane (ISSUE-13): sketch kernels vs the
numpy oracle, the decimated drain's exactly-once contract, token-bucket
sampling, serving-path tracing, the attack-trace workloads, and the
statecheck telemetry configs.

Tier-1 keeps the cheap oracle/parity/policy tests; the jit-heavy
classifier-path and statecheck sweeps are slow-marked and run in
``make test``, ``make state-check`` (telemetry configs + the sketchsat
acceptance) and ``make telemetry-bench`` (retention + steady-state +
detection gates).
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from infw import testing
from infw.kernels.sketch import (
    HostSketchModel,
    SketchSpec,
    SketchState,
    jitted_sketch_clear,
    jitted_sketch_update,
    zero_state_host,
)
from infw.obs.telemetry import (
    SPAN_STAGES,
    SketchSnapshot,
    SpanHistograms,
    SpanTracer,
    TelemetryTier,
    TokenBucket,
    summarize_snapshot,
)

#: one small spec shared across tests so the jitted update compiles once
SPEC = SketchSpec.make(depth=3, width=64, topk=16, ways=2, sat=9,
                       max_tenants=3)


def _tables(n=256, seed=3):
    rng = np.random.default_rng(seed)
    return testing.random_tables_fast(
        rng, n_entries=n, width=4, v6_fraction=0.4, ifindexes=(2, 3)
    )


def _device_state(spec):
    import jax

    return SketchState(*(jax.device_put(a) for a in zero_state_host(spec)))


# --- kernel vs model oracle ---------------------------------------------------


def test_sketch_kernel_matches_model_bit_exact():
    """Count-min adds (with the saturation clamp engaged by the tiny
    sat), top-K refresh/replace/eviction churn (tiny table), tenant
    counters — device tensors vs HostSketchModel, bit for bit, across
    repeated seeded batches with duplicate keys and invalid tenants."""
    import jax

    tables = _tables()
    rng = np.random.default_rng(7)
    model = HostSketchModel(SPEC)
    state = _device_state(SPEC)
    fn = jitted_sketch_update(SPEC)
    for it in range(5):
        b = testing.random_batch(rng, tables, 96)
        wire = b.pack_wire().astype(np.uint32)
        res = rng.integers(0, 1 << 16, len(b)).astype(np.uint32)
        tenant = rng.integers(-1, 4, len(b)).astype(np.int32)
        tflags = rng.integers(0, 32, len(b)).astype(np.int32)
        state = fn(state, jax.device_put(wire), jax.device_put(tenant),
                   jax.device_put(tflags), jax.device_put(res))
        model.update(wire, res, tenant, tflags)
        for name in state._fields:
            assert np.array_equal(
                np.asarray(getattr(state, name)), model.columns()[name]
            ), (it, name)
    # the tiny sat must have engaged, or the clamp path went untested
    assert model.cms.max() == SPEC.sat
    # donated clear: both sides back to zero
    state = jitted_sketch_clear()(state)
    model.clear()
    for name in state._fields:
        assert np.array_equal(
            np.asarray(getattr(state, name)), model.columns()[name]
        )


def test_sketchsat_defect_diverges_from_model():
    """The injected saturation-clamp drop (device side only) must break
    the bit-identity the previous test pins — the surface the statecheck
    sketchsat acceptance shrinks on."""
    import jax

    import infw.kernels.sketch as sketch_mod

    spec = SketchSpec.make(depth=2, width=32, topk=8, ways=2, sat=3)
    tables = _tables()
    b = testing.random_batch(np.random.default_rng(8), tables, 128)
    wire = b.pack_wire().astype(np.uint32)
    res = np.zeros(len(b), np.uint32)
    zeros = np.zeros(len(b), np.int32)
    model = HostSketchModel(spec)
    state = _device_state(spec)
    sketch_mod._INJECT_SKETCH_SAT_BUG = True
    try:
        fn = jitted_sketch_update(spec)
        state = fn(state, jax.device_put(wire), jax.device_put(zeros),
                   jax.device_put(zeros), jax.device_put(res))
    finally:
        sketch_mod._INJECT_SKETCH_SAT_BUG = False
        jitted_sketch_update.cache_clear()  # the cached fn baked the bug
    model.update(wire, res)
    assert not np.array_equal(np.asarray(state.cms), model.cms)
    assert int(np.asarray(state.cms).max()) > spec.sat


def test_sketch_spec_validation():
    with pytest.raises(ValueError):
        SketchSpec.make(depth=0)
    with pytest.raises(ValueError):
        SketchSpec.make(ways=9)
    with pytest.raises(ValueError):
        SketchSpec.make(sat=0)
    s = SketchSpec.make(width=100, topk=10)
    assert s.width == 128 and s.topk == 16  # pow2 bucketing


# --- summarizer ---------------------------------------------------------------


def test_summarize_snapshot_flags_and_top_talkers():
    spec = SketchSpec.make(depth=2, width=32, topk=8, ways=2,
                           max_tenants=4)
    s = zero_state_host(spec)
    # tenant 1: deny storm; tenant 2: syn flood; tenant 3: quiet
    s.tcnt[1] = [100, 10, 90, 0]
    s.tcnt[2] = [100, 95, 5, 80]
    s.tcnt[3] = [10, 10, 0, 0]
    # two heavy hitters: a v4 deny talker and a v6 allow talker
    s.keys[5] = [1, 0x0A000001, 0, 0, 0, (1 << 8) | 1]
    s.cnt[5] = 90
    s.keys[2] = [2, 0x20010DB8, 0, 0, 1, (2 << 8) | 2]
    s.cnt[2] = 40
    snap = SketchSnapshot(seq=7, admissions=12, cms=s.cms, keys=s.keys,
                          cnt=s.cnt, tcnt=s.tcnt)
    rec = summarize_snapshot(snap, top_n=4, min_packets=32)
    assert rec.seq == 7 and rec.admissions == 12
    by_t = {t["tenant"]: t for t in rec.tenants}
    assert by_t[1]["deny_storm"] and not by_t[1]["syn_flood"]
    assert by_t[2]["syn_flood"] and not by_t[2]["deny_storm"]
    assert 3 in by_t and not by_t[3]["deny_storm"]  # under min_packets
    assert [h["count"] for h in rec.top] == [90, 40]
    assert rec.top[0]["src"] == "10.0.0.1" and rec.top[0]["verdict"] == "deny"
    assert rec.top[1]["src"].startswith("2001:db8")
    # the record renders operator lines (the events-log consumer)
    text = "\n".join(rec.lines())
    assert "DENY-STORM" in text and "SYN-FLOOD" in text
    assert "10.0.0.1" in text


# --- token bucket / sampling --------------------------------------------------


def test_token_bucket_never_exceeds_budget():
    tb = TokenBucket(rate=10.0, burst=5.0)
    granted = tb.take(100, now=0.0)
    assert granted == 5  # the burst cap
    assert tb.take(100, now=0.0) == 0
    # 1s later: exactly rate tokens refilled, capped at burst
    assert tb.take(100, now=1.0) == 5
    # over any window, grants <= burst + rate * elapsed (hard ceiling)
    tb2 = TokenBucket(rate=7.0, burst=3.0)
    total = 0
    for i in range(200):
        total += tb2.take(5, now=i * 0.1)
    assert total <= 3 + 7 * (199 * 0.1) + 1


def test_tier_sample_allow_accounts_suppression():
    tier = TelemetryTier(SPEC, track_model=False)
    tier._sample_rate, tier._sample_burst = 2.0, 4.0
    g1 = tier.sample_allow(0, 10, now=0.0)
    g2 = tier.sample_allow(0, 10, now=0.0)
    assert g1 == 4 and g2 == 0
    # independent per-tenant buckets
    assert tier.sample_allow(1, 3, now=0.0) == 3
    vals = tier.counter_values()
    assert vals["telemetry_sampled_events_total"] == 7
    assert vals["telemetry_suppressed_events_total"] == 16


# --- the decimated drain ------------------------------------------------------


def _update_tier(tier, tables, rng, n=64, tenant_hi=3):
    b = testing.random_batch(rng, tables, n)
    wire = b.pack_wire().astype(np.uint32)
    res = rng.integers(0, 1 << 16, n).astype(np.uint32)
    tenant = rng.integers(0, tenant_hi, n).astype(np.int32)
    tier.update(wire, res, tenant_np=tenant)


def test_drain_decimation_and_seq():
    """One drain per drain_every admissions, seq gap-free, device and
    model both zeroed after — and counts land in EXACTLY one window
    (window admission counts sum to the total)."""
    tier = TelemetryTier(SPEC, track_model=True, drain_every=4)
    tables = _tables()
    rng = np.random.default_rng(11)
    recs = []

    class Ring:
        def push(self, r):
            recs.append(r)

    tier.attach_ring(Ring())
    for _ in range(10):
        _update_tier(tier, tables, rng)
    # 10 admissions at drain_every=4 -> 2 auto-drains
    assert [r.seq for r in recs] == [1, 2]
    assert sum(r.admissions for r in recs) == 8
    recs2 = tier.drain(force=True)
    assert recs2[0].seq == 3 and recs2[0].admissions == 2
    cols = tier.columns()
    assert all((cols[n] == 0).all() for n in cols)
    assert all(
        (tier.model.columns()[n] == 0).all() for n in tier.model.columns()
    )


def test_drain_exactly_once_under_concurrent_updates():
    """Updates from several threads racing forced drains: every seq is
    emitted exactly once with no gaps, every admission lands in exactly
    one window, and the device tensors still match the model at the
    settled end (the generation-stamp discipline)."""
    tier = TelemetryTier(SPEC, track_model=True, drain_every=6)
    tables = _tables()
    recs = []
    lock = threading.Lock()

    class Ring:
        def push(self, r):
            with lock:
                recs.append(r)

    tier.attach_ring(Ring())
    errs = []

    def traffic(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(12):
                _update_tier(tier, tables, rng, n=32)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    def drainer():
        try:
            for _ in range(8):
                tier.drain(force=True)
                time.sleep(0.001)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=traffic, args=(s,)) for s in (1, 2)]
    threads.append(threading.Thread(target=drainer))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    final = tier.drain(force=True)[0]
    with lock:
        # drain() publishes on the ring itself — the returned record is
        # the ring's last entry, not an extra one
        assert recs[-1] is final
        seqs = [r.seq for r in recs]
        total = sum(r.admissions for r in recs)
    assert seqs == list(range(1, len(seqs) + 1))  # exactly-once, gap-free
    assert total == 24  # every admission in exactly one window
    cols = tier.columns()
    mcols = tier.model.columns()
    for name in cols:
        assert np.array_equal(cols[name], mcols[name]), name


# --- tracing ------------------------------------------------------------------


def test_span_histograms_render_prometheus():
    h = SpanHistograms()
    h.observe("dispatch", 3.0)
    h.observe("dispatch", 1000.0)
    h.observe("ingest", 0.5)
    text = h.render_histograms()
    assert "# TYPE ingressnodefirewall_node_span_us histogram" in text
    assert 'span_us_bucket{stage="dispatch",le="+Inf"} 2' in text
    assert 'span_us_bucket{stage="dispatch",le="4"} 1' in text
    assert 'span_us_count{stage="dispatch"} 2' in text
    assert 'span_us_count{stage="ingest"} 1' in text
    # cumulative buckets are monotone
    v = h.values()["dispatch"]
    assert v["count"] == 2 and v["sum_us"] == pytest.approx(1003.0)


def test_histograms_survive_registry_reload():
    """The weak-registry discipline (obs.statistics): a LIVE tracer's
    histograms survive re-registration and repeated renders; a dropped
    provider disappears from the exposition instead of double
    counting."""
    import gc

    from infw.obs.statistics import Registry

    reg = Registry()
    h = SpanHistograms()
    h.observe("pack", 10.0)
    reg.register_histograms(h)
    reg.register_histograms(h)  # idempotent
    t1 = reg.render_text()
    assert t1.count('span_us_count{stage="pack"} 1') == 1
    # re-register into a fresh registry (the daemon-reload shape): the
    # provider moves, no duplicate series, counts intact
    reg2 = Registry()
    reg2.register_histograms(h)
    h.observe("pack", 20.0)
    assert 'span_us_count{stage="pack"} 2' in reg2.render_text()
    # dropped provider vanishes from the old registry
    del h
    gc.collect()
    assert "span_us" not in reg.render_text()


def test_tracer_slow_sampling_token_bucket():
    recs = []

    class Ring:
        def push(self, r):
            recs.append(r)

    tr = SpanTracer(ring=Ring(), slow_us=100.0, sample_rate=0.0,
                    sample_burst=2.0)
    for _ in range(5):
        t = tr.begin(8)
        t.add("dispatch", 0.001)  # 1000us, slow
        tr.finish(t, now=0.0)
    # only the burst budget of slow records was sampled
    assert len(recs) == 2
    assert tr.counters["slow_sampled"] == 2
    assert tr.counters["slow_suppressed"] == 3
    assert tr.counters["traces"] == 5
    assert recs[0].n_packets == 8 and recs[0].spans_us["dispatch"] > 100
    # fast traces observe histograms but never sample
    t = tr.begin(1)
    t.add("dispatch", 1e-6)
    tr.finish(t, now=0.0)
    assert len(recs) == 2
    assert "trace-span" in recs[0].lines()[0]
    assert all(s in SPAN_STAGES for s in ("ingest", "drain"))


# --- attack traces / loadgen --------------------------------------------------


def test_attack_trace_modes_deterministic():
    tables = _tables(400, seed=9)
    for mode in testing.ATTACK_MODES:
        b1, m1 = testing.attack_trace_batch(
            np.random.default_rng(4), tables, 2048, mode=mode,
            chunk_packets=512,
        )
        b2, m2 = testing.attack_trace_batch(
            np.random.default_rng(4), tables, 2048, mode=mode,
            chunk_packets=512,
        )
        assert np.array_equal(b1.pack_wire(), b2.pack_wire())
        assert np.array_equal(b1.tcp_flags, b2.tcp_flags)
        assert m1["start"] == 512  # chunk-aligned onset
        assert m1["n_attack"] > 0
        mask = m1["attack_mask"]
        assert not mask[: m1["start"]].any()
        if mode == "synflood":
            from infw.kernels.jaxpath import TCP_SYN

            assert (b1.tcp_flags[mask] == TCP_SYN).all()
        if mode == "portscan":
            assert len(m1["attackers"]) == 1
        if mode == "denystorm":
            from infw import oracle

            ref = oracle.classify(tables, b1)
            atk = (np.asarray(ref.results)[mask] & 0xFF) == 1
            assert atk.all()  # every attack lane oracle-denies


def test_loadgen_attack_modes(tmp_path):
    import importlib.util
    import sys

    tools_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    spec = importlib.util.spec_from_file_location(
        "infw_loadgen_atk", os.path.join(tools_dir, "loadgen.py")
    )
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)

    out1, out2 = str(tmp_path / "a"), str(tmp_path / "b")
    args = ["--rate", "1000000", "--n", "2048", "--file-packets", "512",
            "--seed", "11", "--attack", "synflood",
            "--attack-fraction", "0.5", "--attack-start", "0.25"]
    assert lg.main(["--out", out1] + args) == 0
    assert lg.main(["--out", out2] + args) == 0
    files = sorted(f for f in os.listdir(out1) if f.endswith(".frames"))
    for fn in files:  # byte-deterministic with the attack injected
        assert open(os.path.join(out1, fn), "rb").read() == \
            open(os.path.join(out2, fn), "rb").read()
    with open(os.path.join(out1, "loadgen-manifest.json")) as f:
        man = json.load(f)
    assert man["attack"] == "synflood"
    assert man["attack_start_packet"] == 512
    assert len(man["attackers"]) == 2 and man["attack_packets"] > 0
    # bad knobs fail the launch
    with pytest.raises(SystemExit):
        lg.main(["--out", str(tmp_path / "x"), "--rate", "1", "--n", "1",
                 "--attack", "synflood", "--attack-fraction", "1.5"])


def test_daemon_telemetry_flag_validation(tmp_path):
    from infw.daemon import main as daemon_main

    base = ["--state-dir", str(tmp_path), "--node-name", "n"]
    with pytest.raises(SystemExit):
        daemon_main(base + ["--backend", "cpu", "--telemetry", "2048"])
    with pytest.raises(SystemExit):
        daemon_main(base + ["--backend", "tpu", "--telemetry", "4"])
    with pytest.raises(SystemExit):
        daemon_main(base + ["--backend", "tpu", "--telemetry", "junk"])
    with pytest.raises(SystemExit):
        daemon_main(base + ["--backend", "tpu", "--telemetry-drain", "0"])
    with pytest.raises(SystemExit):
        daemon_main(base + ["--backend", "tpu", "--trace-slow-us", "-1"])


# --- classifier integration (jit-heavy: make test / telemetry-bench) ---------


def _run_chunks(clf, tables, n_chunks=4, bs=64):
    clf.load_tables(tables)
    out = None
    for i in range(n_chunks):
        b = testing.random_batch(np.random.default_rng(100 + i), tables, bs)
        b.tcp_flags = np.random.default_rng(i).integers(
            0, 32, len(b)
        ).astype(np.int32)
        w, v4 = b.pack_wire_subset(np.arange(len(b)))
        out = clf.classify_prepared(
            clf.prepare_packed(w, v4, tcp_flags=b.tcp_flags),
            apply_stats=False,
        ).result()
    return out


@pytest.mark.slow
def test_classifier_paths_update_identically():
    """Classic wire, flow-probe and resident-fused dispatch must leave
    bit-identical telemetry state (device == model on each, and equal
    across paths for the same traffic) — the in-program sketch is the
    same function the follow-on launch runs."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig

    tables = _tables(300, seed=5)
    spec = SketchSpec.make(depth=3, width=128, topk=32, ways=2,
                           max_tenants=2)
    states = {}
    for label, kw in (
        ("classic", {}),
        ("flow", {"flow_table": FlowConfig.make(entries=512)}),
        ("resident", {"flow_table": FlowConfig.make(entries=512),
                      "resident": True}),
    ):
        clf = TpuClassifier(interpret=True, force_path="trie",
                            telemetry=spec, telemetry_track_model=True,
                            **kw)
        _run_chunks(clf, tables)
        cols = clf.telemetry.columns()
        mcols = clf.telemetry.model.columns()
        for name in cols:
            assert np.array_equal(cols[name], mcols[name]), (label, name)
        states[label] = cols
        clf.close()
    for name in states["classic"]:
        assert np.array_equal(states["classic"][name],
                              states["flow"][name]), name
        assert np.array_equal(states["classic"][name],
                              states["resident"][name]), name


@pytest.mark.slow
def test_verdicts_unchanged_with_telemetry():
    """Telemetry on vs off: verdicts and stats bit-identical (the
    sketch is observation, never policy)."""
    from infw.backend.tpu import TpuClassifier

    tables = _tables(300, seed=6)
    a = TpuClassifier(interpret=True, force_path="trie",
                      telemetry=SketchSpec.make(width=128, topk=16))
    b = TpuClassifier(interpret=True, force_path="trie")
    oa = _run_chunks(a, tables)
    ob = _run_chunks(b, tables)
    assert np.array_equal(oa.results, ob.results)
    assert np.array_equal(oa.stats_delta, ob.stats_delta)
    a.close()
    b.close()


@pytest.mark.slow
def test_drain_exactly_once_under_concurrent_patch():
    """The satellite contract: summary records stay exactly-once (seq
    gap-free, every admission in one window) while rule patches land
    concurrently with traffic and forced drains, and the device sketch
    still matches the model at the settled end."""
    from infw.backend.tpu import TpuClassifier
    from infw.compiler import IncrementalTables

    tables = _tables(200, seed=12)
    clf = TpuClassifier(interpret=True, force_path="trie",
                        telemetry=SketchSpec.make(
                            depth=2, width=64, topk=16, ways=2),
                        telemetry_track_model=True)
    clf.load_tables(tables)
    tier = clf.telemetry
    recs = []
    lock = threading.Lock()

    class Ring:
        def push(self, r):
            with lock:
                recs.append(r)

    tier.attach_ring(Ring())
    errs = []
    stop = threading.Event()

    def patcher():
        try:
            upd = IncrementalTables.from_content(
                dict(tables.content), rule_width=4
            )
            rng = np.random.default_rng(77)
            for _ in range(6):
                keys = list(upd.content)
                k = keys[int(rng.integers(0, len(keys)))]
                upd.apply({k: testing.random_rules(rng, 4)}, [])
                clf.load_tables(upd.snapshot(),
                                dirty_hint=upd.peek_dirty())
                upd.clear_dirty()
                time.sleep(0.01)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    def drainer():
        while not stop.is_set():
            tier.drain(force=True)
            time.sleep(0.005)

    tp = threading.Thread(target=patcher)
    td = threading.Thread(target=drainer)
    tp.start()
    td.start()
    for i in range(10):
        b = testing.random_batch(np.random.default_rng(500 + i),
                                 tables, 48)
        w, v4 = b.pack_wire_subset(np.arange(len(b)))
        clf.classify_prepared(
            clf.prepare_packed(w, v4), apply_stats=False
        ).result()
    tp.join()
    stop.set()
    td.join()
    assert not errs
    final = tier.drain(force=True)[0]
    with lock:
        assert recs[-1] is final
        seqs = [r.seq for r in recs]
        total = sum(r.admissions for r in recs)
    assert seqs == list(range(1, len(seqs) + 1))
    assert total == tier.admissions
    cols = tier.columns()
    mcols = tier.model.columns()
    for name in cols:
        assert np.array_equal(cols[name], mcols[name]), name
    clf.close()


@pytest.mark.slow
def test_zero_recompile_warm_telemetry_lifecycle():
    """After the scheduler ladder prewarm, serving dispatches with
    telemetry on compile nothing — neither the fused resident sketch
    variant nor the classic follow-on update (the _cache_size
    discipline)."""
    from infw.backend.tpu import TpuClassifier
    from infw.flow import FlowConfig
    from infw.kernels import jaxpath
    from infw.scheduler import prewarm_ladder

    tables = _tables(300, seed=13)
    spec = SketchSpec.make(depth=2, width=128, topk=16, ways=2)
    fcfg = FlowConfig.make(entries=512)
    clf = TpuClassifier(interpret=True, force_path="trie",
                        flow_table=fcfg, resident=True, telemetry=spec)
    clf.load_tables(tables)
    prewarm_ladder(clf, (32, 64))
    fns = [
        jaxpath.jitted_resident_step(fcfg.entries, fcfg.ways, "trie",
                                     v4, None, 0, False, sketch=spec)
        for v4 in (False, True)
    ] + [jitted_sketch_update(spec)]
    c0 = sum(f._cache_size() for f in fns)
    for i in range(6):
        b = testing.random_batch(np.random.default_rng(900 + i),
                                 tables, 32 if i % 2 else 64)
        w, v4 = b.pack_wire_subset(np.arange(len(b)))
        clf.classify_prepared(
            clf.prepare_packed(w, v4), apply_stats=False
        ).result()
    assert sum(f._cache_size() for f in fns) == c0
    assert clf.resident.steady_allocs() == 0
    clf.close()


@pytest.mark.slow
def test_statecheck_telemetry_configs_clean():
    from infw.analysis import statecheck

    for name in ("telemetry", "telemetry-resident"):
        rep = statecheck.run_config(name, seed=0, n_ops=8,
                                    shrink_on_failure=False)
        assert rep["ok"], (name, rep["failure"])


@pytest.mark.slow
def test_statecheck_sketchsat_defect_caught():
    import infw.kernels.sketch as sketch_mod
    from infw.analysis import statecheck

    sketch_mod._INJECT_SKETCH_SAT_BUG = True
    try:
        jitted_sketch_update.cache_clear()
        rep = statecheck.run_config("telemetry", seed=0, n_ops=6,
                                    shrink_on_failure=False)
    finally:
        sketch_mod._INJECT_SKETCH_SAT_BUG = False
        jitted_sketch_update.cache_clear()
    assert not rep["ok"]
    assert rep["failure"]["phase"] == "telemetry-model"


@pytest.mark.slow
def test_scheduler_tracer_observes_spans():
    """ContinuousScheduler with a tracer: every admitted job charges
    pack/dispatch/materialize/drain spans, and the histograms render on
    a registry like any other provider."""
    from infw.backend.tpu import TpuClassifier
    from infw.obs.statistics import Registry
    from infw.scheduler import ContinuousScheduler, FixedChunkPolicy

    tables = _tables(200, seed=14)
    clf = TpuClassifier(interpret=True, force_path="trie")
    clf.load_tables(tables)
    tracer = SpanTracer(slow_us=1e12)
    sched = ContinuousScheduler(clf, FixedChunkPolicy(64), tracer=tracer)
    batch = testing.random_batch(np.random.default_rng(15), tables, 256)
    offs = np.zeros(256)
    res = sched.serve(batch, offs)
    assert len(res.results) == 256
    vals = tracer.histograms.values()
    for stage in ("pack", "dispatch", "materialize", "drain"):
        assert vals[stage]["count"] >= 1, stage
    reg = Registry()
    reg.register_histograms(tracer.histograms)
    assert 'span_us_count{stage="pack"}' in reg.render_text()
    clf.close()
