"""deploy/ tree validation: every shipped sample CR must pass the schema
and admission tiers (the reference's samples are applied against a live
API server in its e2e flow; here the validation library IS that gate),
the bundle descriptor must stay consistent with infw.spec, and the
compose launchers must be syntactically sound."""
import json
import os
import subprocess

import pytest

from infw.spec import (
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
)
from infw.validate import validate_ingress_node_firewall

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY = os.path.join(REPO, "deploy")
SAMPLES = os.path.join(DEPLOY, "samples")


def _load_docs(path):
    with open(path) as f:
        doc = json.load(f)
    return doc if isinstance(doc, list) else [doc]


def _all_sample_docs():
    docs = []
    for fn in sorted(os.listdir(SAMPLES)):
        for doc in _load_docs(os.path.join(SAMPLES, fn)):
            docs.append((fn, doc))
    return docs


def test_samples_cover_reference_set():
    names = set(os.listdir(SAMPLES))
    assert {
        "ingress-node-firewall-config.json",
        "ingressnodefirewall-demo.json",
        "ingressnodefirewall-demo-2.json",
        "ingressnodefirewall-demo-3.json",
        "ingressnodefirewall-denyall.json",
    } <= names


@pytest.mark.parametrize(
    "fn,doc", _all_sample_docs(), ids=lambda x: x if isinstance(x, str) else ""
)
def test_sample_parses_and_validates(fn, doc):
    if doc["kind"] == "IngressNodeFirewallConfig":
        obj = IngressNodeFirewallConfig.from_dict(doc)
        assert obj.metadata.name == "ingressnodefirewallconfig"
        return
    assert doc["kind"] == "IngressNodeFirewall"
    inf = IngressNodeFirewall.from_dict(doc)
    errs = validate_ingress_node_firewall(inf)
    assert errs == [], f"{fn}: {errs}"


def test_demo3_pair_trips_cross_inf_order_check():
    """Reference-faithful quirk: the demo-3 pair shares nodeSelector,
    CIDR 172.20.0.0/24 AND order 20 (only the interface differs), and the
    reference webhook's cross-INF check ignores interfaces
    (webhook.go:330-365) — so applying -b after -a must produce exactly
    the order-conflict error, reference message format included."""
    a, b = [
        IngressNodeFirewall.from_dict(d)
        for d in _load_docs(
            os.path.join(SAMPLES, "ingressnodefirewall-demo-3.json")
        )
    ]
    assert validate_ingress_node_firewall(a) == []
    errs = validate_ingress_node_firewall(b, existing=[a])
    assert len(errs) == 1
    assert "order is not unique for sourceCIDR '172.20.0.0/24'" in errs[0]
    assert "ingressnodefirewall-demo-3-a" in errs[0]


def test_bundle_manifest_consistent():
    with open(os.path.join(DEPLOY, "bundle", "manifest.json")) as f:
        m = json.load(f)
    kinds = {api["kind"] for api in m["providedAPIs"]}
    assert kinds == {
        "IngressNodeFirewall",
        "IngressNodeFirewallConfig",
        "IngressNodeFirewallNodeState",
    }
    for api in m["providedAPIs"]:
        for ex in api.get("exampleFiles", []):
            p = os.path.normpath(os.path.join(DEPLOY, "bundle", ex))
            assert os.path.exists(p), f"dangling exampleFile {ex}"
    # declared daemon entry must name the real module and ports
    daemon = m["components"]["daemon"]
    assert "infw.daemon" in daemon["run"]
    assert daemon["ports"] == {"metrics": 39301, "health": 39300}
    assert "NODE_NAME" in daemon["env"]["required"]


@pytest.mark.parametrize("script", ["single-node.sh", "multi-host.sh"])
def test_compose_scripts_parse(script):
    p = os.path.join(DEPLOY, "compose", script)
    assert os.access(p, os.X_OK), f"{script} must be executable"
    subprocess.run(["bash", "-n", p], check=True)
