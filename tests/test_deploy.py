"""deploy/ tree validation: every shipped sample CR must pass the schema
and admission tiers (the reference's samples are applied against a live
API server in its e2e flow; here the validation library IS that gate),
the bundle descriptor must stay consistent with infw.spec, and the
compose launchers must be syntactically sound."""
import json
import os
import subprocess

import pytest

from infw.spec import (
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
)
from infw.validate import validate_ingress_node_firewall

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY = os.path.join(REPO, "deploy")
SAMPLES = os.path.join(DEPLOY, "samples")


def _load_docs(path):
    with open(path) as f:
        doc = json.load(f)
    return doc if isinstance(doc, list) else [doc]


def _all_sample_docs():
    docs = []
    for fn in sorted(os.listdir(SAMPLES)):
        for doc in _load_docs(os.path.join(SAMPLES, fn)):
            docs.append((fn, doc))
    return docs


def test_samples_cover_reference_set():
    names = set(os.listdir(SAMPLES))
    assert {
        "ingress-node-firewall-config.json",
        "ingressnodefirewall-demo.json",
        "ingressnodefirewall-demo-2.json",
        "ingressnodefirewall-demo-3.json",
        "ingressnodefirewall-denyall.json",
    } <= names


@pytest.mark.parametrize(
    "fn,doc", _all_sample_docs(), ids=lambda x: x if isinstance(x, str) else ""
)
def test_sample_parses_and_validates(fn, doc):
    if doc["kind"] == "IngressNodeFirewallConfig":
        obj = IngressNodeFirewallConfig.from_dict(doc)
        assert obj.metadata.name == "ingressnodefirewallconfig"
        return
    assert doc["kind"] == "IngressNodeFirewall"
    inf = IngressNodeFirewall.from_dict(doc)
    errs = validate_ingress_node_firewall(inf)
    assert errs == [], f"{fn}: {errs}"


def test_demo3_pair_trips_cross_inf_order_check():
    """Reference-faithful quirk: the demo-3 pair shares nodeSelector,
    CIDR 172.20.0.0/24 AND order 20 (only the interface differs), and the
    reference webhook's cross-INF check ignores interfaces
    (webhook.go:330-365) — so applying -b after -a must produce exactly
    the order-conflict error, reference message format included."""
    a, b = [
        IngressNodeFirewall.from_dict(d)
        for d in _load_docs(
            os.path.join(SAMPLES, "ingressnodefirewall-demo-3.json")
        )
    ]
    assert validate_ingress_node_firewall(a) == []
    errs = validate_ingress_node_firewall(b, existing=[a])
    assert len(errs) == 1
    assert "order is not unique for sourceCIDR '172.20.0.0/24'" in errs[0]
    assert "ingressnodefirewall-demo-3-a" in errs[0]


def test_bundle_manifest_consistent():
    with open(os.path.join(DEPLOY, "bundle", "manifest.json")) as f:
        m = json.load(f)
    kinds = {api["kind"] for api in m["providedAPIs"]}
    assert kinds == {
        "IngressNodeFirewall",
        "IngressNodeFirewallConfig",
        "IngressNodeFirewallNodeState",
    }
    for api in m["providedAPIs"]:
        for ex in api.get("exampleFiles", []):
            p = os.path.normpath(os.path.join(DEPLOY, "bundle", ex))
            assert os.path.exists(p), f"dangling exampleFile {ex}"
    # declared daemon entry must name the real module and ports
    daemon = m["components"]["daemon"]
    assert "infw.daemon" in daemon["run"]
    assert daemon["ports"] == {"metrics": 39301, "health": 39300}
    assert "NODE_NAME" in daemon["env"]["required"]


@pytest.mark.parametrize("script", ["single-node.sh", "multi-host.sh"])
def test_compose_scripts_parse(script):
    p = os.path.join(DEPLOY, "compose", script)
    assert os.access(p, os.X_OK), f"{script} must be executable"
    subprocess.run(["bash", "-n", p], check=True)


def _scrubbed_env():
    """os.environ minus INFW_* so inherited multihost vars on the test
    host cannot satisfy (or pollute) the bundle env contract."""
    return {k: v for k, v in os.environ.items()
            if not k.startswith("INFW_")}


def _launcher_dry_run(*args, env=None):
    import sys
    return subprocess.run(
        [sys.executable, os.path.join(DEPLOY, "launch.py"), "--dry-run",
         *args],
        capture_output=True, text=True,
        env=env if env is not None else dict(os.environ),
    )


def test_multihost_component_plan():
    """The multi-host composition is bundle-declared (round-4 weak #5):
    --component daemon-multihost + the coordinator flags produce a
    single-component plan whose env carries the jax.distributed contract
    (envFromFlags -> INFW_COORDINATOR/INFW_NUM_PROCESSES/INFW_PROCESS_ID,
    the daemonset env-injection role).  Env is scrubbed so the asserted
    values can only come from the flags."""
    r = _launcher_dry_run(
        "--component", "daemon-multihost",
        "--coordinator", "h0:8476", "--num-processes", "4",
        "--process-id", "1", "--state-dir", "/tmp/infw-mh-test",
        "--backend", "cpu", "--node-name", "mh-node",
        env=_scrubbed_env(),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 components" in r.stdout
    assert "infw.daemon" in r.stdout
    assert "--backend cpu" in r.stdout
    assert "env INFW_COORDINATOR=h0:8476" in r.stdout
    assert "env INFW_NUM_PROCESSES=4" in r.stdout
    assert "env INFW_PROCESS_ID=1" in r.stdout


def test_multihost_component_requires_contract():
    """Without the coordinator flags (and with the env scrubbed) the
    bundle env contract must reject the launch, naming the missing
    variables."""
    r = _launcher_dry_run(
        "--component", "daemon-multihost", "--state-dir", "/tmp/x",
        "--node-name", "mh-node", env=_scrubbed_env(),
    )
    assert r.returncode != 0
    assert "INFW_COORDINATOR" in r.stderr + r.stdout


def test_multihost_flags_without_component_rejected():
    """Multihost flags that no launched component consumes must fail the
    launch instead of silently starting a single-host composition (the
    coordinator would wait forever for this rank)."""
    r = _launcher_dry_run(
        "--coordinator", "h0:8476", "--num-processes", "4",
        "--process-id", "1", "--state-dir", "/tmp/x",
        "--node-name", "n", env=_scrubbed_env(),
    )
    assert r.returncode != 0
    assert "not consumed" in r.stderr + r.stdout


def test_ephemeral_ports_cover_declared_ports():
    """--ephemeral-ports keys off the bundle's declared ports, so the
    multihost daemon gets the same treatment as the default daemon."""
    r = _launcher_dry_run(
        "--component", "daemon-multihost",
        "--coordinator", "h0:8476", "--num-processes", "4",
        "--process-id", "0", "--state-dir", "/tmp/x",
        "--node-name", "n", "--ephemeral-ports", env=_scrubbed_env(),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "--metrics-port 0" in r.stdout


def test_multihost_script_routes_through_launcher():
    """multi-host.sh must not hand-roll the daemon run line: it execs the
    bundle launcher with the multihost component."""
    with open(os.path.join(DEPLOY, "compose", "multi-host.sh")) as f:
        body = f.read()
    assert "launch.py" in body
    assert "--component daemon-multihost" in body
    assert "python -m infw.daemon" not in body


def test_unknown_component_rejected():
    r = _launcher_dry_run("--component", "no-such", "--state-dir", "/tmp/x")
    assert r.returncode != 0
    assert "unknown component" in r.stderr + r.stdout


def test_metrics_proxy_tls_on_by_default():
    """The metrics proxy launches with TLS by default (the reference's
    kube-rbac-proxy always terminates TLS): both the --component and the
    --with-metrics-proxy paths carry --certfile/--keyfile pointing under
    the state dir's tls/ directory."""
    for args in (
        ("--component", "metrics-proxy"),
        ("--with-metrics-proxy",),
    ):
        r = _launcher_dry_run(
            *args, "--state-dir", "/tmp/infw-tls-plan",
            "--node-name", "n0", env=_scrubbed_env(),
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "--certfile /tmp/infw-tls-plan/tls/metrics-tls.crt" in r.stdout
        assert "--keyfile /tmp/infw-tls-plan/tls/metrics-tls.key" in r.stdout


def test_metrics_proxy_plaintext_requires_explicit_opt_out():
    """--insecure-metrics (or INFW_INSECURE_METRICS=1) is the ONLY way to
    a plaintext proxy; the flag removes the TLS pair from the run line."""
    r = _launcher_dry_run(
        "--component", "metrics-proxy", "--insecure-metrics",
        "--state-dir", "/tmp/infw-tls-plan", "--node-name", "n0",
        env=_scrubbed_env(),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "--certfile" not in r.stdout
    env = _scrubbed_env()
    env["INFW_INSECURE_METRICS"] = "1"
    r2 = _launcher_dry_run(
        "--component", "metrics-proxy",
        "--state-dir", "/tmp/infw-tls-plan", "--node-name", "n0", env=env,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "--certfile" not in r2.stdout


def test_with_metrics_proxy_joins_default_composition():
    """--with-metrics-proxy appends the standalone proxy to the default
    launch order (the explicit request is the standalone-guard consent);
    without it the default composition stays proxy-free."""
    r = _launcher_dry_run(
        "--with-metrics-proxy", "--state-dir", "/tmp/infw-tls-plan",
        "--node-name", "n0", env=_scrubbed_env(),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "4 components" in r.stdout
    assert "infw.obs.metricsproxy" in r.stdout
    r2 = _launcher_dry_run(
        "--state-dir", "/tmp/infw-tls-plan", "--node-name", "n0",
        env=_scrubbed_env(),
    )
    assert r2.returncode == 0
    assert "infw.obs.metricsproxy" not in r2.stdout


def test_single_node_script_defaults_metrics_proxy_tls():
    """single-node.sh fronts metrics with the TLS proxy by default and
    routes the plaintext opt-out through --insecure-metrics."""
    with open(os.path.join(DEPLOY, "compose", "single-node.sh")) as f:
        body = f.read()
    assert "--with-metrics-proxy" in body
    assert "--insecure-metrics" in body
    assert "INFW_INSECURE_METRICS" in body
