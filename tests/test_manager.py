"""Manager runtime tests: admission webhook seam, watch-driven
reconciles, config requeue, NodeState file export, and the full
manager->file->daemon composition (the port of the reference's e2e
operator-deployment flow onto the file protocol)."""
import json
import os
import time

import pytest

from infw.manager import Manager, main as manager_main
from infw.platform import get_platform_info
from infw.spec import (
    ACTION_ALLOW,
    ACTION_DENY,
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
    IngressNodeFirewallConfigSpec,
    IngressNodeFirewallNodeState,
    IngressNodeFirewallSpec,
    ObjectMeta,
)
from infw.store import (
    AdmissionError,
    DaemonSet,
    DaemonSetStatus,
    InMemoryStore,
    Node,
    NotFoundError,
)
from infw.controllers import DEFAULT_CONFIG_NAME
from test_syncer import ingress, tcp_rule, udp_rule

NS = "ingress-node-firewall-system"
WORKER = {"role": "worker"}


def inf(name, selector, ingress_rules, interfaces=("eth0",)):
    return IngressNodeFirewall(
        metadata=ObjectMeta(name=name),
        spec=IngressNodeFirewallSpec(
            node_selector=dict(selector),
            ingress=list(ingress_rules),
            interfaces=list(interfaces),
        ),
    )


@pytest.fixture
def mgr(tmp_path):
    m = Manager(namespace=NS, export_dir=str(tmp_path / "export"))
    yield m
    m.stop()


# --- admission webhook seam ---------------------------------------------------

def test_admission_rejects_invalid_interface(mgr):
    bad = inf("fw", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])],
              interfaces=("3eth",))
    with pytest.raises(AdmissionError, match="can't start with a number"):
        mgr.store.create(bad)


def test_admission_rejects_failsafe_conflict(mgr):
    # TCP 6443 (kube API) is failsafe: a Deny rule covering it is rejected
    # (webhook.go:199-243).
    bad = inf("fw", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, "6000-7000", ACTION_DENY)])])
    with pytest.raises(AdmissionError, match="conflict with access"):
        mgr.store.create(bad)
    # Allow over the same range is fine (webhook.go:219-223).
    ok = inf("fw", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, "6000-7000", ACTION_ALLOW)])])
    mgr.store.create(ok)


def test_admission_rejects_cross_inf_order_overlap(mgr):
    mgr.store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    with pytest.raises(AdmissionError, match="conflicts with IngressNodeFirewall"):
        mgr.store.create(
            inf("fw2", WORKER, [ingress(["10.0.0.0/8"], [udp_rule(1, 53, ACTION_DENY)])])
        )
    # distinct orders are admitted
    mgr.store.create(
        inf("fw2", WORKER, [ingress(["10.0.0.0/8"], [udp_rule(2, 53, ACTION_DENY)])])
    )


def test_admission_self_update_allowed(mgr):
    fw = inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])])
    mgr.store.create(fw)
    fw.spec.ingress[0].rules[0].protocol_config.tcp.ports = 81
    mgr.store.update(fw)  # must not conflict with itself


# --- watch-driven reconciles + export ----------------------------------------

def test_watch_driven_fanout_and_export(mgr, tmp_path):
    mgr.store.create(Node(metadata=ObjectMeta(name="w0", labels=WORKER)))
    mgr.store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    mgr.drain()
    ns_obj = mgr.store.get(IngressNodeFirewallNodeState.KIND, "w0", NS)
    assert "eth0" in ns_obj.spec.interface_ingress_rules

    export = tmp_path / "export" / "nodestates" / "w0.json"
    assert export.exists()
    doc = json.loads(export.read_text())
    assert doc["metadata"]["name"] == "w0"

    # INF deletion -> NodeState deleted -> export file removed
    mgr.store.delete(IngressNodeFirewall.KIND, "fw1")
    mgr.drain()
    assert not export.exists()


def test_out_of_band_nodestate_deletion_repaired(mgr):
    """Owns(&NodeState) semantics: deleting a NodeState out-of-band while
    its INF still selects the node must recreate it on the next drain."""
    mgr.store.create(Node(metadata=ObjectMeta(name="w0", labels=WORKER)))
    mgr.store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    mgr.drain()
    mgr.store.delete(IngressNodeFirewallNodeState.KIND, "w0", NS)
    mgr.drain()
    assert mgr.store.get(IngressNodeFirewallNodeState.KIND, "w0", NS)


def test_stopped_manager_cancels_watches(tmp_path):
    store = InMemoryStore()
    m = Manager(store=store, namespace=NS, export_dir=str(tmp_path / "e"))
    m.stop()
    store.create(Node(metadata=ObjectMeta(name="w0", labels=WORKER)))
    store.create(inf("fw1", WORKER, [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]))
    assert m._queue.qsize() == 0  # no events land on the dead queue


def test_config_reconcile_conditions(mgr):
    mgr.store.create(
        IngressNodeFirewallConfig(
            metadata=ObjectMeta(name=DEFAULT_CONFIG_NAME, namespace=NS),
            spec=IngressNodeFirewallConfigSpec(),
        )
    )
    mgr.drain()
    ds = mgr.store.get(DaemonSet.KIND, "ingress-node-firewall-daemon", NS)
    ds.status = DaemonSetStatus(desired_number_scheduled=1, number_ready=1)
    mgr.store.update_status(ds)
    mgr.enqueue_config(DEFAULT_CONFIG_NAME)
    mgr.drain()
    cfg = mgr.store.get(IngressNodeFirewallConfig.KIND, DEFAULT_CONFIG_NAME, NS)
    assert {c.type: c.status for c in cfg.status.conditions}["Available"] == "True"


# --- full manager -> file -> daemon composition -------------------------------

def test_manager_daemon_file_composition(tmp_path):
    from infw.daemon import Daemon
    from infw.interfaces import Interface, InterfaceRegistry
    from infw.obs.pcap import build_frame
    from infw.daemon import write_frames_file

    shared = str(tmp_path / "shared")
    mgr = Manager(namespace=NS, export_dir=shared)
    reg = InterfaceRegistry()
    reg.add(Interface(name="eth0", index=2))
    daemon = Daemon(
        state_dir=shared, node_name="w0", namespace=NS, backend="cpu",
        registry=reg, metrics_port=0, health_port=0, file_poll_interval_s=0.02,
        poll_period_s=0.05,
    )
    daemon.start()
    try:
        mgr.store.create(Node(metadata=ObjectMeta(name="w0", labels=WORKER)))
        mgr.store.create(
            inf("fw1", WORKER, [ingress(["0.0.0.0/0"], [tcp_rule(1, 8080, ACTION_DENY)])])
        )
        mgr.drain()

        deadline = time.time() + 5
        while time.time() < deadline and (
            daemon.syncer.classifier is None or daemon.syncer.classifier.tables is None
        ):
            time.sleep(0.02)
        assert daemon.syncer.classifier is not None

        frames = [build_frame("1.2.3.4", "10.0.0.1", 6, 1, 8080),
                  build_frame("1.2.3.4", "10.0.0.1", 6, 1, 8081)]
        write_frames_file(os.path.join(daemon.ingest_dir, "x.frames"), frames, 2)
        vp = os.path.join(daemon.out_dir, "x.frames.verdicts.json")
        while time.time() < deadline and not os.path.exists(vp):
            time.sleep(0.02)
        with open(vp) as f:
            summary = json.load(f)
        assert summary["drop"] == 1 and summary["pass"] == 1
    finally:
        daemon.stop()
        mgr.stop()


# --- CLI env contract ---------------------------------------------------------

def test_main_requires_env(monkeypatch, capsys):
    monkeypatch.delenv("DAEMONSET_IMAGE", raising=False)
    monkeypatch.delenv("DAEMONSET_NAMESPACE", raising=False)
    with pytest.raises(SystemExit):
        manager_main([])
    assert "DAEMONSET_IMAGE" in capsys.readouterr().err


def test_platform_info():
    info = get_platform_info()
    assert info.backend  # cpu in tests
    assert info.num_devices >= 1
    assert isinstance(info.is_tpu, bool)


# --- apply-dir (kubectl-apply seam) ------------------------------------------

def _write_cr(path, doc):
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def test_apply_dir_create_update_delete(tmp_path):
    m = Manager(namespace=NS, export_dir=str(tmp_path / "export"),
                apply_dir=str(tmp_path / "apply"))
    try:
        m.store.create(Node(metadata=ObjectMeta(name="w0", labels=WORKER)))
        doc = inf("fw1", WORKER,
                  [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]).to_dict()
        _write_cr(tmp_path / "apply" / "fw1.json", doc)
        m.scan_apply_dir_once()
        m.drain()
        got = m.store.get(IngressNodeFirewall.KIND, "fw1")
        assert got is not None
        with open(tmp_path / "apply" / "fw1.status.json") as f:
            assert json.load(f) == {"applied": True, "errors": []}
        # fan-out produced the exported NodeState
        assert os.path.exists(tmp_path / "export" / "nodestates" / "w0.json")

        # update: edit the file -> rules change flows through (content
        # hash, so no mtime-granularity games needed)
        doc["spec"]["ingress"][0]["rules"][0]["action"] = "Allow"
        _write_cr(tmp_path / "apply" / "fw1.json", doc)
        m.scan_apply_dir_once()
        got = m.store.get(IngressNodeFirewall.KIND, "fw1")
        assert got.spec.ingress[0].rules[0].action == ACTION_ALLOW

        # rename within the file: old CR must not be orphaned
        doc["metadata"]["name"] = "fw1b"
        _write_cr(tmp_path / "apply" / "fw1.json", doc)
        m.scan_apply_dir_once()
        with pytest.raises(NotFoundError):
            m.store.get(IngressNodeFirewall.KIND, "fw1")
        assert m.store.get(IngressNodeFirewall.KIND, "fw1b") is not None

        # break the file, then remove it: the live CR (from the last good
        # apply) must still be deleted — a rejected edit does not orphan it
        with open(tmp_path / "apply" / "fw1.json", "w") as f:
            f.write("{nope")
        m.scan_apply_dir_once()
        assert m.store.get(IngressNodeFirewall.KIND, "fw1b") is not None
        os.remove(tmp_path / "apply" / "fw1.json")
        m.scan_apply_dir_once()
        m.drain()
        with pytest.raises(NotFoundError):
            m.store.get(IngressNodeFirewall.KIND, "fw1b")
        assert not os.path.exists(tmp_path / "apply" / "fw1.status.json")
    finally:
        m.stop()


def test_apply_dir_rename_to_rejected_keeps_old_cr(tmp_path):
    """A file edit that renames its CR to something admission rejects must
    NOT fail open: the previously-enforcing object stays (the reference
    webhook rejects atomically, leaving the old object intact) — round-3
    advisor medium finding."""
    m = Manager(namespace=NS, apply_dir=str(tmp_path / "apply"))
    try:
        doc = inf("fw1", WORKER,
                  [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]).to_dict()
        _write_cr(tmp_path / "apply" / "fw.json", doc)
        m.scan_apply_dir_once()
        assert m.store.get(IngressNodeFirewall.KIND, "fw1") is not None

        # rename AND break it (deny on failsafe port 22 is rejected)
        doc["metadata"]["name"] = "fw2"
        doc["spec"]["ingress"][0]["rules"][0]["protocolConfig"]["tcp"]["ports"] = "22"
        _write_cr(tmp_path / "apply" / "fw.json", doc)
        m.scan_apply_dir_once()
        # old object still enforcing, successor rejected
        assert m.store.get(IngressNodeFirewall.KIND, "fw1") is not None
        with pytest.raises(NotFoundError):
            m.store.get(IngressNodeFirewall.KIND, "fw2")
        with open(tmp_path / "apply" / "fw.status.json") as f:
            assert json.load(f)["applied"] is False

        # removing the file still deletes the live (old) CR — the mapping
        # survived the rejected rename
        os.remove(tmp_path / "apply" / "fw.json")
        m.scan_apply_dir_once()
        with pytest.raises(NotFoundError):
            m.store.get(IngressNodeFirewall.KIND, "fw1")
    finally:
        m.stop()


def test_apply_dir_rename_conflicting_with_self_succeeds(tmp_path):
    """A rename whose successor order-conflicts only with its own
    predecessor (identical spec, new name) must land: the scan retries
    with the predecessor removed, and restores it only if the successor
    still fails on its own."""
    m = Manager(namespace=NS, apply_dir=str(tmp_path / "apply"))
    try:
        doc = inf("fwa", WORKER,
                  [ingress(["10.0.0.0/8"], [tcp_rule(1, 80, ACTION_DENY)])]).to_dict()
        _write_cr(tmp_path / "apply" / "fw.json", doc)
        m.scan_apply_dir_once()
        doc["metadata"]["name"] = "fwb"  # same spec: overlaps fwa's orders
        _write_cr(tmp_path / "apply" / "fw.json", doc)
        m.scan_apply_dir_once()
        assert m.store.get(IngressNodeFirewall.KIND, "fwb") is not None
        with pytest.raises(NotFoundError):
            m.store.get(IngressNodeFirewall.KIND, "fwa")
        with open(tmp_path / "apply" / "fw.status.json") as f:
            assert json.load(f)["applied"] is True
    finally:
        m.stop()


def test_apply_dir_rejection_writes_status(tmp_path):
    m = Manager(namespace=NS, apply_dir=str(tmp_path / "apply"))
    try:
        bad = inf("fw-bad", WORKER,
                  [ingress(["10.0.0.0/8"], [tcp_rule(1, 22, ACTION_DENY)])]).to_dict()
        _write_cr(tmp_path / "apply" / "fw-bad.json", bad)  # failsafe port 22
        m.scan_apply_dir_once()
        with pytest.raises(NotFoundError):
            m.store.get(IngressNodeFirewall.KIND, "fw-bad")
        with open(tmp_path / "apply" / "fw-bad.status.json") as f:
            st = json.load(f)
        assert st["applied"] is False and st["errors"]
        # garbage file: rejected, not fatal
        with open(tmp_path / "apply" / "junk.json", "w") as f:
            f.write("{nope")
        m.scan_apply_dir_once()
        with open(tmp_path / "apply" / "junk.status.json") as f:
            assert json.load(f)["applied"] is False
    finally:
        m.stop()


def test_full_compose_stack_cr_to_sidecar_event(tmp_path):
    """The reference's whole e2e flow as REAL processes, brought up FROM
    THE BUNDLE: deploy/launch.py reads deploy/bundle/manifest.json and
    spawns sidecar + manager(--apply-dir) + daemon (the OLM-install role,
    /root/reference/bundle/).  An IngressNodeFirewall CR dropped in the
    apply dir must travel admission -> fan-out -> NodeState export ->
    daemon sync -> classify, and the deny event must come out of the
    SIDECAR's log in the reference's line format (cmd/syslog +
    test/e2e/events regex flow)."""
    import re
    import subprocess
    import sys as _sys

    state = tmp_path / "state"
    sock = str(tmp_path / "events.sock")
    env = dict(os.environ, NODE_NAME="composed-node",
               DAEMONSET_IMAGE="infw:latest", DAEMONSET_NAMESPACE=NS,
               JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    logs = {n: state / f"{n}.log"
            for n in ("events-sidecar", "manager", "daemon")}
    launcher = subprocess.Popen(
        [_sys.executable, os.path.join(repo, "deploy", "launch.py"),
         "--state-dir", str(state), "--backend", "cpu",
         "--node-name", "composed-node", "--events-socket", sock,
         "--ephemeral-ports"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    procs = {"launcher": launcher}
    try:
        # generous: under a loaded machine (the INFW_BIG_TESTS run
        # allocates GBs right before this test) process spawn + jax
        # import can exceed 30s
        deadline = time.time() + 120
        while time.time() < deadline and not (state / "apply").is_dir():
            time.sleep(0.1)
        assert (state / "apply").is_dir(), (
            "launcher stack did not come up; launcher output:\n"
            + (launcher.stdout.read().decode(errors="replace")
               if launcher.poll() is not None else "(still starting)")
        )

        # a CR that trips the failsafe webhook: rejected with the verdict
        # in its status file (the API-call error of webhook.go, as a file)
        bad = inf("fw-bad", WORKER,
                  [ingress(["10.0.0.0/8"], [tcp_rule(1, 22, ACTION_DENY)])]).to_dict()
        _write_cr(state / "apply" / "fw-bad.json", bad)
        stp = state / "apply" / "fw-bad.status.json"
        while time.time() < deadline and not stp.exists():
            time.sleep(0.1)
        with open(stp) as f:
            st = json.load(f)
        assert st["applied"] is False
        assert any("conflict" in e for e in st["errors"]), st  # failsafe SSH

        # the REAL path: a valid CR (empty selector = all nodes) travels
        # admission -> fan-out against the self-registered Node ->
        # NodeState export -> daemon sync.  No manual NodeState anywhere.
        good = inf("fw-good", {},
                   [ingress(["10.1.0.0/16"], [tcp_rule(1, 80, ACTION_DENY)])],
                   interfaces=("eth0",)).to_dict()
        _write_cr(state / "apply" / "fw-good.json", good)
        nsp = state / "nodestates" / "composed-node.json"
        deadline = time.time() + 30  # fresh budget: startup consumed the first
        while time.time() < deadline and not nsp.exists():
            time.sleep(0.1)
        assert nsp.exists(), logs["manager"].read_text()[-2000:]

        from infw.daemon import write_frames_file_v2
        from infw.obs.pcap import FramesBuf, build_frame

        fb = FramesBuf.from_frames(
            [build_frame("10.1.2.3", "9.9.9.9", 6, 1234, 80)], 2
        )
        vp = state / "out" / "e.frames.verdicts.json"
        deadline = time.time() + 60
        wrote = False
        while time.time() < deadline and not vp.exists():
            if not wrote and (state / "ingest").is_dir():
                write_frames_file_v2(str(state / "ingest" / "e.frames"), fb)
                wrote = True
            time.sleep(0.2)
        assert vp.exists(), logs["daemon"].read_text()[-2000:]
        with open(vp) as f:
            assert json.load(f)["drop"] == 1

        # the deny event must surface on the SIDECAR's log
        pat = re.compile(r"ruleId 1 action Drop len \d+ if ")
        sidecar_log = logs["events-sidecar"]
        while time.time() < deadline:
            if sidecar_log.exists() and pat.search(
                sidecar_log.read_text(errors="replace")
            ):
                break
            time.sleep(0.2)
        assert pat.search(sidecar_log.read_text(errors="replace")), (
            sidecar_log.read_text(errors="replace")[-2000:]
        )
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=15)


def test_apply_dir_config_kind_and_unsupported(tmp_path):
    """The apply seam routes by kind: an IngressNodeFirewallConfig drives
    the config reconciler (daemonset render); unsupported kinds are
    rejected with the reason in the status file."""
    m = Manager(namespace=NS, apply_dir=str(tmp_path / "apply"))
    try:
        _write_cr(tmp_path / "apply" / "config.json", {
            "apiVersion": "ingressnodefirewall.openshift.io/v1alpha1",
            "kind": "IngressNodeFirewallConfig",
            "metadata": {"name": DEFAULT_CONFIG_NAME},
            "spec": {"nodeSelector": {}, "debug": True},
        })
        m.scan_apply_dir_once()
        m.drain()
        cfg = m.store.get(
            IngressNodeFirewallConfig.KIND, DEFAULT_CONFIG_NAME, NS
        )
        assert cfg.spec.debug is True  # namespace defaulted to the manager's
        ds = m.store.get(DaemonSet.KIND, "ingress-node-firewall-daemon", NS)
        assert ds is not None  # config reconcile rendered the daemonset

        _write_cr(tmp_path / "apply" / "node.json",
                  {"kind": "Node", "metadata": {"name": "n0"}})
        m.scan_apply_dir_once()
        with open(tmp_path / "apply" / "node.status.json") as f:
            st = json.load(f)
        assert st["applied"] is False
        assert any("unsupported kind" in e for e in st["errors"])
    finally:
        m.stop()
