"""Shared dataset recipe for the 2-process multi-host test: the worker
and the verifying parent must build the identical tables/batch."""
SEED = 77
N_ENTRIES = 80
WIDTH = 8
OVERLAP = 0.4
N_PACKETS = 512
