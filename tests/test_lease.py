"""Single-writer lease (leader election) tests.

The reference manager runs with controller-runtime leader election
(/root/reference/main.go:76-85): one leader reconciles, a second
instance stands by, an expired lease is taken over, and leadership loss
is fatal.  These tests drive the same contract through infw.lease and
two Manager instances sharing one store."""
import os
import threading
import time

import pytest

from infw.lease import FileLease, InMemoryLease
from infw.manager import Manager
from infw.spec import IngressNodeFirewall, ObjectMeta
from infw.store import InMemoryStore, Node


def _mk_inf(name="fw-a"):
    return IngressNodeFirewall.from_dict({
        "apiVersion": "ingressnodefirewall.tpu/v1alpha1",
        "kind": "IngressNodeFirewall",
        "metadata": {"name": name},
        "spec": {
            "interfaces": ["eth0"],
            "ingress": [{
                "sourceCIDRs": ["10.0.0.0/8"],
                "rules": [{
                    "order": 1,
                    "protocolConfig": {
                        "protocol": "TCP", "tcp": {"ports": "8080"}},
                    "action": "Deny",
                }],
            }],
        },
    })


# -- lease primitives --------------------------------------------------------


@pytest.mark.parametrize("mk", [
    lambda tmp: InMemoryLease(duration_s=0.5),
    lambda tmp: FileLease(os.path.join(tmp, "l.lease"), duration_s=0.5,
                          settle_s=0.01),
])
def test_lease_contract(mk, tmp_path):
    lease = mk(str(tmp_path))
    assert lease.try_acquire("a")
    assert lease.holder()[0] == "a"
    # held: another holder is refused
    assert not lease.try_acquire("b")
    # re-entrant refresh for the owner
    assert lease.try_acquire("a")
    # renewal works while held
    assert lease.renew("a")
    # non-holder cannot renew
    assert not lease.renew("b")
    # expiry -> steal
    time.sleep(0.6)
    assert lease.try_acquire("b")
    assert lease.holder()[0] == "b"
    # original holder's renewal now fails (leadership lost)
    assert not lease.renew("a")
    lease.release("b")
    assert lease.holder() is None
    # release by a non-holder is a no-op
    assert lease.try_acquire("a")
    lease.release("b")
    assert lease.holder()[0] == "a"


def test_file_lease_survives_torn_write(tmp_path):
    path = os.path.join(str(tmp_path), "l.lease")
    lease = FileLease(path, duration_s=0.5, settle_s=0.01)
    with open(path, "w") as f:
        f.write("{garbage")
    # torn/corrupt lease file reads as expired garbage: steal succeeds
    assert lease.try_acquire("a")
    assert lease.holder()[0] == "a"


def test_file_lease_concurrent_steal_single_winner(tmp_path):
    """Two stealers race an expired lease; write-then-verify must elect
    at most one winner."""
    path = os.path.join(str(tmp_path), "l.lease")
    l1 = FileLease(path, duration_s=0.2, settle_s=0.05)
    l2 = FileLease(path, duration_s=0.2, settle_s=0.05)
    assert l1.try_acquire("old")
    time.sleep(0.3)
    results = {}
    barrier = threading.Barrier(2)

    def steal(lease, name):
        barrier.wait()
        results[name] = lease.try_acquire(name)

    ts = [threading.Thread(target=steal, args=(l, n))
          for l, n in ((l1, "s1"), (l2, "s2"))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sum(results.values()) <= 1
    # and the file's holder is whoever won (if anyone)
    winners = [n for n, ok in results.items() if ok]
    if winners:
        assert l1.holder()[0] == winners[0]


# -- two managers, one store -------------------------------------------------


def _managers(store, lease):
    common = dict(store=store, enable_webhook=False,
                  metrics_port=0, health_port=0)
    m1 = Manager(lease=lease, lease_holder="m1", **common)
    m2 = Manager(lease=lease, lease_holder="m2", **common)
    return m1, m2


def test_second_manager_stands_by(tmp_path):
    store = InMemoryStore()
    store.create(Node(metadata=ObjectMeta(name="n1")))
    lease = InMemoryLease(duration_s=2.0)
    m1, m2 = _managers(store, lease)
    try:
        assert m1.start() is True
        # second instance: bounded standby wait fails while m1 holds
        assert m2.start(lease_timeout=0.3) is False
        assert not m2.is_leader

        # only the leader reconciles: the standby's worker never started
        store.create(_mk_inf())
        deadline = time.time() + 10
        while time.time() < deadline:
            if store.list("IngressNodeFirewallNodeState"):
                break
            time.sleep(0.05)
        assert store.list("IngressNodeFirewallNodeState"), "leader must fan out"
        assert m1.reconcile_counts["fanout"] > 0
        assert m2.reconcile_counts["fanout"] == 0
    finally:
        m1.stop()
        m2.stop()


def test_takeover_after_leader_crash(tmp_path):
    """A crashed leader (stops renewing, never releases) is taken over
    after at most duration_s; the new leader reconciles."""
    store = InMemoryStore()
    store.create(Node(metadata=ObjectMeta(name="n1")))
    lease = InMemoryLease(duration_s=0.6)
    m1, m2 = _managers(store, lease)
    try:
        assert m1.start() is True
        # crash: stop threads without releasing the lease (simulates
        # process death — stop() would release cleanly)
        m1._stop.set()
        for cancel in m1._watch_cancels:
            cancel()

        t0 = time.time()
        assert m2.start(lease_timeout=5.0) is True
        took = time.time() - t0
        assert took < 3.0, f"takeover took {took:.1f}s"
        assert m2.is_leader

        store.create(_mk_inf("fw-b"))
        deadline = time.time() + 10
        while time.time() < deadline:
            if m2.reconcile_counts["fanout"] > 0:
                break
            time.sleep(0.05)
        assert m2.reconcile_counts["fanout"] > 0
    finally:
        m1.stop()
        m2.stop()


def test_lease_loss_stops_manager():
    """Renewal failure after an expiry steal demotes the running leader:
    lease_lost is set and the manager stops (leader-loss-is-fatal)."""
    store = InMemoryStore()
    lease = InMemoryLease(duration_s=0.4)
    m1 = Manager(store=store, enable_webhook=False, lease=lease,
                 lease_holder="m1", metrics_port=0, health_port=0)
    try:
        assert m1.start() is True
        # freeze m1's renewals by stealing after expiry (a GC-pause /
        # partition analogue): force the expiry then grab the lease
        with getattr(lease, "_lock"):
            lease._expires_at = 0.0
        assert lease.try_acquire("intruder")
        deadline = time.time() + 5
        while time.time() < deadline and not m1.lease_lost:
            time.sleep(0.05)
        assert m1.lease_lost
        assert not m1.is_leader
    finally:
        m1.stop()
