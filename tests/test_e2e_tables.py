"""T4: declarative reachability tables through the FULL stack
(admission webhook -> fan-out controller -> NodeState -> syncer ->
classifier), the port of the reference functional suite's table-driven
cases (/root/reference/test/e2e/functional/tests/e2e.go:177-980): netcat/
ping probes become synthesized frames; connectivity == PASS verdict."""
import os

import pytest

from infw.e2e import (
    Harness,
    Pod,
    Reachable,
    SourceCIDRsEntry,
    TestRule,
    allow_port,
    deny_all,
    deny_icmp,
    deny_port,
)
from infw.spec import (
    PROTOCOL_TYPE_ICMP,
    PROTOCOL_TYPE_SCTP,
    PROTOCOL_TYPE_TCP,
    PROTOCOL_TYPE_UDP,
)

SERVER_ONE_PORT = 80
SERVER_TWO_PORT = 8080
ALLOWED_PORT = 40000
SERVER_ONE_PORT_RANGE = "79-81"

PODS = [
    Pod("client-one", ipv4="172.16.1.8", ipv6="2001:db8:10::8"),
    # client-three lives in client-one's /24 and /64: CIDR-matched, not
    # pod-identity-matched.
    Pod("client-three", ipv4="172.16.1.77", ipv6="2001:db8:10::77"),
    Pod("client-two", ipv4="172.16.2.9", ipv6="2001:db8:20::9"),
    Pod("server-one", ipv4="172.16.9.1", ipv6="2001:db8:90::1"),
    Pod("server-two", ipv4="172.16.9.2", ipv6="2001:db8:90::2"),
]

TRANSPORT = [PROTOCOL_TYPE_TCP, PROTOCOL_TYPE_UDP, PROTOCOL_TYPE_SCTP]


def _backends():
    """CPU reference always; the REAL device path when INFW_TPU_E2E=1
    (VERDICT r3 #4: the reference's table engine drives the real XDP
    dataplane, so ours must also run against the TPU classifier, not only
    the C++ oracle).  Run on hardware with:
        INFW_TPU_E2E=1 python -m pytest tests/test_e2e_tables.py -v
    """
    yield "cpu"
    if os.environ.get("INFW_TPU_E2E") == "1":
        yield "tpu"


@pytest.fixture(params=list(_backends()))
def harness(request):
    if request.param == "tpu":
        from infw.backend.tpu import TpuClassifier

        h = Harness(PODS, classifier_factory=TpuClassifier)
    else:
        h = Harness(PODS)
    yield h
    h.close()


@pytest.mark.parametrize("proto", TRANSPORT)
def test_deny_server_port_from_client_one_cidr(harness, proto):
    """'deny a single port' case: client-one's /24 is blocked on the
    server port; other ports and other clients unaffected."""
    tpl = deny_port(SERVER_ONE_PORT)
    harness.apply_rules(
        [TestRule([SourceCIDRsEntry("client-one")], [tpl])],
        protocols={tpl: [proto]},
    )
    failures = harness.check_reachability(
        [
            Reachable("client-one", "server-one", SERVER_ONE_PORT, False, proto),
            Reachable("client-one", "server-one", ALLOWED_PORT, True, proto),
            # same /24 (and /64) as client-one: also blocked — the rule
            # matches the CIDR, not the pod identity
            Reachable("client-three", "server-one", SERVER_ONE_PORT, False, proto),
            # client-two is in a different /24: unaffected
            Reachable("client-two", "server-one", SERVER_ONE_PORT, True, proto),
        ],
        families=(4, 6),
    )
    assert failures == []


def test_deny_port_range(harness):
    """'deny a port range' case with the half-open dataplane semantics:
    range 79-81 covers 79 and 80, NOT 81 (kernel.c:241)."""
    tpl = deny_port(SERVER_ONE_PORT_RANGE)
    harness.apply_rules(
        [TestRule([SourceCIDRsEntry("client-one")], [tpl])],
        protocols={tpl: [PROTOCOL_TYPE_TCP]},
    )
    failures = harness.check_reachability(
        [
            Reachable("client-one", "server-one", 79, False),
            Reachable("client-one", "server-one", 80, False),
            Reachable("client-one", "server-one", 81, True),
            Reachable("client-one", "server-one", 78, True),
        ]
    )
    assert failures == []


def test_allow_overrides_later_deny_all(harness):
    """'allow one port, deny everything else' case: ordered first-match —
    the Allow at a lower order shadows the catch-all Deny."""
    allow = allow_port(ALLOWED_PORT)
    deny = deny_all()
    harness.apply_rules(
        [TestRule([SourceCIDRsEntry("client-one")], [allow, deny])],
        protocols={allow: [PROTOCOL_TYPE_TCP], deny: [PROTOCOL_TYPE_TCP]},
    )
    failures = harness.check_reachability(
        [
            Reachable("client-one", "server-one", ALLOWED_PORT, True),
            Reachable("client-one", "server-one", SERVER_ONE_PORT, False),
            Reachable("client-one", "server-two", SERVER_TWO_PORT, False),
            Reachable("client-two", "server-one", SERVER_ONE_PORT, True),
        ]
    )
    assert failures == []


def test_deny_icmp_echo(harness):
    """ICMP case: echo-request (type 8 code 0) blocked for the CIDR;
    other ICMP types pass; v6 uses ICMPv6 type 128."""
    v4 = deny_icmp(8, 0)
    v6 = deny_icmp(128, 0)
    harness.apply_rules(
        [TestRule([SourceCIDRsEntry("client-one")], [v4, v6])],
        protocols={v4: [PROTOCOL_TYPE_ICMP], v6: ["ICMPv6"]},
    )
    failures = harness.check_reachability(
        [
            Reachable("client-one", "server-one", 0, False, PROTOCOL_TYPE_ICMP,
                      icmp_type=8),
            Reachable("client-one", "server-one", 0, True, PROTOCOL_TYPE_ICMP,
                      icmp_type=0),  # echo-reply unaffected
            Reachable("client-two", "server-one", 0, True, PROTOCOL_TYPE_ICMP,
                      icmp_type=8),
        ]
    )
    assert failures == []
    # v6: type 128 denied via the ICMPv6 rule
    assert harness.probe(
        Reachable("client-one", "server-one", 0, False, PROTOCOL_TYPE_ICMP,
                  icmp_type=128), family=6
    ) is False


def test_multi_cidr_multi_rule_generation(harness):
    """Two sourceCIDR entries + two protocol templates: orders are
    generated unique per CIDR (the harness's order counter), both client
    CIDRs end up covered."""
    deny1 = deny_port(SERVER_ONE_PORT)
    deny2 = deny_port(SERVER_TWO_PORT)
    harness.apply_rules(
        [
            TestRule(
                [SourceCIDRsEntry("client-one"), SourceCIDRsEntry("client-two")],
                [deny1, deny2],
            )
        ],
        protocols={deny1: [PROTOCOL_TYPE_TCP, PROTOCOL_TYPE_UDP],
                   deny2: [PROTOCOL_TYPE_TCP]},
    )
    failures = harness.check_reachability(
        [
            Reachable("client-one", "server-one", SERVER_ONE_PORT, False),
            Reachable("client-one", "server-one", SERVER_ONE_PORT, False, PROTOCOL_TYPE_UDP),
            Reachable("client-two", "server-two", SERVER_TWO_PORT, False),
            Reachable("client-one", "server-two", ALLOWED_PORT, True),
        ]
    )
    assert failures == []


def test_rules_update_reconfigures_dataplane(harness):
    """Disruption-style case (e2e.go:982-1140): after the INF changes,
    the dataplane reflects the new policy (policy persistence across
    reconfiguration)."""
    tpl = deny_port(SERVER_ONE_PORT)
    harness.apply_rules(
        [TestRule([SourceCIDRsEntry("client-one")], [tpl])],
        protocols={tpl: [PROTOCOL_TYPE_TCP]},
    )
    assert not harness.probe(Reachable("client-one", "server-one", SERVER_ONE_PORT, False))

    from infw.spec import IngressNodeFirewall
    inf = harness.manager.store.get(IngressNodeFirewall.KIND, "e2e-inf")
    inf.spec.ingress[0].rules[0].protocol_config.tcp.ports = SERVER_TWO_PORT
    harness.manager.store.update(inf)
    harness.resync()
    assert harness.probe(Reachable("client-one", "server-one", SERVER_ONE_PORT, True))
    assert not harness.probe(Reachable("client-one", "server-one", SERVER_TWO_PORT, False))
