"""T3: differential testing — JAX kernels vs the NumPy oracle.

The reference's crown-jewel tier drives real traffic through veth pairs and
asserts reachability (ebpfsyncer_test.go:41-447); here synthetic adversarial
tables + packet batches are classified by every accelerated path and must
match the scalar oracle bit-for-bit (results, XDP verdicts, statistics).
"""
import numpy as np
import pytest

from infw import oracle, testing
from infw.compiler import LpmKey, compile_tables_from_content
from infw.kernels import jaxpath


def run_all_paths(tables, batch):
    dt = jaxpath.device_tables(tables)
    db = jaxpath.device_batch(batch)
    out = {}
    out["dense"] = jaxpath.jitted_classify(False)(dt, db)
    out["trie"] = jaxpath.jitted_classify(True)(dt, db)
    return out


def assert_matches_oracle(tables, batch):
    ref = oracle.classify(tables, batch)
    for name, (res, xdp, stats) in run_all_paths(tables, batch).items():
        np.testing.assert_array_equal(
            np.asarray(res), ref.results, err_msg=f"results mismatch ({name})"
        )
        np.testing.assert_array_equal(
            np.asarray(xdp), ref.xdp, err_msg=f"xdp mismatch ({name})"
        )
        got_stats = testing.stats_dict_from_array(
            jaxpath.merge_stats_host(np.asarray(stats))
        )
        assert got_stats == ref.stats, f"stats mismatch ({name})"


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_differential(seed):
    rng = np.random.default_rng(seed)
    tables = testing.random_tables(rng, n_entries=40, width=12)
    batch = testing.random_batch(rng, tables, n_packets=300)
    assert_matches_oracle(tables, batch)


def test_large_overlapping_differential():
    rng = np.random.default_rng(42)
    tables = testing.random_tables(
        rng, n_entries=200, width=8, overlap_fraction=0.6
    )
    batch = testing.random_batch(rng, tables, n_packets=500)
    assert_matches_oracle(tables, batch)


def test_empty_table():
    tables = compile_tables_from_content({}, rule_width=4)
    rng = np.random.default_rng(7)
    batch = testing.random_batch(rng, tables, n_packets=50)
    assert_matches_oracle(tables, batch)


@pytest.mark.parametrize("seed", [0, 5])
def test_hash_oracle_matches_scalar_oracle(seed):
    """The LPM-by-hash oracle (the big-tier spot-check ground truth) must
    agree bit-for-bit with the scalar transliteration — results, xdp AND
    stats — over adversarial nested/overlapping tables."""
    rng = np.random.default_rng(seed)
    tables = testing.random_tables_fast(
        rng, n_entries=3000, width=8, group_size=6, ifindexes=(2, 3, 9)
    )
    batch = testing.random_batch_fast(rng, tables, n_packets=4000)
    ref = oracle.classify(tables, batch)
    got = oracle.HashLpmOracle(tables).classify(batch)
    np.testing.assert_array_equal(got.results, ref.results)
    np.testing.assert_array_equal(got.xdp, ref.xdp)
    assert got.stats == ref.stats


def test_hash_oracle_empty_and_zero_mask():
    """mask_len 0 entries (match-everything-on-ifindex) take the shift-128
    path in both build and probe; empty tables must classify to UNDEF."""
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 0, 0, 0, 0, 0, 1]  # catch-all deny
    content = {LpmKey(32, 2, bytes(16)): rows}  # /0 on ifindex 2
    tables = compile_tables_from_content(content, rule_width=4)
    from infw.packets import make_batch

    batch = make_batch(
        src=["10.0.0.1", "2001:db8::1", "10.0.0.1"],
        proto=[6, 6, 6], dst_port=[80, 80, 80], ifindex=[2, 2, 3],
    )
    ref = oracle.classify(tables, batch)
    got = oracle.HashLpmOracle(tables).classify(batch)
    np.testing.assert_array_equal(got.results, ref.results)
    np.testing.assert_array_equal(got.xdp, ref.xdp)
    # the /0 catch-all denies both families on ifindex 2, misses ifindex 3
    assert got.xdp.tolist() == [1, 1, 2]

    empty = compile_tables_from_content({}, rule_width=4)
    got = oracle.HashLpmOracle(empty).classify(batch)
    assert got.xdp.tolist() == [2, 2, 2]


def test_nested_prefixes_longest_wins():
    # /8 allow, /16 deny, /24 allow, /32 deny nested — longest must win.
    rows_allow = np.zeros((4, 7), np.int32)
    rows_allow[1] = [1, 0, 0, 0, 0, 0, 2]  # catch-all allow
    rows_deny = np.zeros((4, 7), np.int32)
    rows_deny[1] = [1, 0, 0, 0, 0, 0, 1]  # catch-all deny

    def key(cidr_bytes, mask_len):
        return LpmKey(mask_len + 32, 2, bytes(cidr_bytes) + bytes(12))

    content = {
        key([10, 0, 0, 0], 8): rows_allow,
        key([10, 1, 0, 0], 16): rows_deny,
        key([10, 1, 2, 0], 24): rows_allow,
        key([10, 1, 2, 3], 32): rows_deny,
    }
    tables = compile_tables_from_content(content, rule_width=4)
    from infw.packets import make_batch

    batch = make_batch(
        src=["10.9.9.9", "10.1.9.9", "10.1.2.9", "10.1.2.3", "11.0.0.1"],
        proto=[6] * 5,
        dst_port=[80] * 5,
        ifindex=[2] * 5,
    )
    ref = oracle.classify(tables, batch)
    assert ref.xdp.tolist() == [2, 1, 2, 1, 2]
    assert_matches_oracle(tables, batch)


def test_v4_packet_cannot_match_long_v6_prefix():
    # A v6 entry with mask_len > 32 whose bytes coincide with a v4 key must
    # NOT match a v4 packet (packet key prefixLen cap = 64), but a v6 entry
    # with mask_len <= 32 CAN match a v4 packet (shared key space quirk).
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 0, 0, 0, 0, 0, 1]  # catch-all deny
    long_v6 = LpmKey(40 + 32, 2, bytes([10, 0, 0, 1, 0]) + bytes(11))  # /40 v6
    short_v6 = LpmKey(16 + 32, 2, bytes([10, 0]) + bytes(14))          # /16
    content_long = {long_v6: rows}
    content_short = {short_v6: rows}
    from infw.packets import make_batch

    batch = make_batch(src=["10.0.0.1"], proto=[6], dst_port=[80], ifindex=[2])
    t_long = compile_tables_from_content(content_long, rule_width=4)
    t_short = compile_tables_from_content(content_short, rule_width=4)
    assert oracle.classify(t_long, batch).xdp.tolist() == [2]   # no match
    assert oracle.classify(t_short, batch).xdp.tolist() == [1]  # match -> deny
    assert_matches_oracle(t_long, batch)
    assert_matches_oracle(t_short, batch)


def test_rule_scan_order_and_fallthrough():
    # Port-mismatch on an earlier rule must fall through to later rules;
    # first matching order wins even when a later rule also matches.
    rows = np.zeros((8, 7), np.int32)
    rows[1] = [1, 6, 100, 0, 0, 0, 1]    # TCP port 100 deny
    rows[2] = [2, 6, 80, 90, 0, 0, 1]    # TCP [80,90) deny
    rows[3] = [3, 6, 85, 0, 0, 0, 2]     # TCP port 85 allow (shadowed by 2)
    rows[5] = [5, 0, 0, 0, 0, 0, 2]      # catch-all allow
    content = {LpmKey(32, 2, bytes(16)): rows}  # 0.0.0.0/0 on ifindex 2
    tables = compile_tables_from_content(content, rule_width=8)
    from infw.packets import make_batch

    batch = make_batch(
        src=["1.1.1.1"] * 5,
        proto=[6, 6, 6, 17, 1],
        dst_port=[85, 100, 95, 85, 0],
        ifindex=[2] * 5,
    )
    ref = oracle.classify(tables, batch)
    # TCP 85 -> rule 2 (deny), TCP 100 -> rule 1 (deny),
    # TCP 95 -> no port match -> catch-all 5 allow,
    # UDP -> catch-all, ICMP -> catch-all
    assert [(r >> 8) for r in ref.results] == [2, 1, 5, 5, 5]
    assert ref.xdp.tolist() == [1, 1, 2, 2, 2]
    assert_matches_oracle(tables, batch)


def test_icmp_family_gating():
    # An ICMPv6 rule must not match a v4 packet with proto 58 and vice versa.
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 58, 0, 0, 128, 0, 1]  # ICMPv6 type 128 deny
    rows[2] = [2, 1, 0, 0, 8, 0, 1]     # ICMP type 8 deny
    content = {LpmKey(32, 2, bytes(16)): rows}
    tables = compile_tables_from_content(content, rule_width=4)
    from infw.packets import make_batch

    batch = make_batch(
        src=["1.1.1.1", "2002:db8::1", "1.1.1.1", "2002:db8::1"],
        proto=[58, 58, 1, 1],
        icmp_type=[128, 128, 8, 8],
        icmp_code=[0, 0, 0, 0],
        ifindex=[2] * 4,
    )
    ref = oracle.classify(tables, batch)
    # v4+proto58: rule1 proto matches but family-gated -> no match;
    # v6+proto58: deny; v4+proto1: deny; v6+proto1: family-gated -> pass.
    assert ref.xdp.tolist() == [2, 1, 1, 2]
    assert_matches_oracle(tables, batch)
