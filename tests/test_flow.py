"""Stateful flow tier (ISSUE-11): device-resident connection tracking
with an exact-match fast path.

Covers the kernel/model bit-identity (every probe/insert/age mutation
vs the numpy HostFlowModel), verdict bit-identity of the flow-enabled
classifier vs the stateless path and the CPU oracle (hits engaged, all
ladder rungs, single-chip + mesh + arena), the TCP state machine
(SYN-gated establishment, FIN half-close, RST teardown), epoch aging
and LRU eviction, generation-bump invalidation on incremental patches /
folded txn flushes / tenant swaps (no stale verdict ever served),
cross-tenant flow isolation (key-level, survives slab reuse), the
zero-recompile warm flow lifecycle, scheduler/daemon integration, and
the statecheck flow configs incl. the flowstale injected defect.
"""
import numpy as np
import pytest

from infw import oracle, testing
from infw.backend.tpu import ArenaClassifier, TpuClassifier
from infw.compiler import IncrementalTables
from infw.flow import FlowConfig
from infw.kernels import jaxpath


def _tables(seed=3, n=256, width=4, v6=0.4):
    return testing.random_tables_fast(
        np.random.default_rng(seed), n_entries=n, width=width,
        v6_fraction=v6, ifindexes=(2, 3),
    )


def _pair(tabs, entries=2048, track_model=False, **kw):
    clf = TpuClassifier(
        interpret=True, flow_table=FlowConfig.make(entries=entries),
        flow_track_model=track_model, **kw,
    )
    base = TpuClassifier(interpret=True, **kw)
    clf.load_tables(tabs)
    base.load_tables(tabs)
    return clf, base


def _assert_model_parity(tier):
    cols = tier.flow_columns()
    want = tier.model.columns()
    for k, dev in cols.items():
        assert np.array_equal(dev, want[k]), (
            f"device flow column {k!r} diverged from the host model "
            f"({int(np.sum(np.asarray(dev).reshape(dev.shape[0], -1) != want[k].reshape(dev.shape[0], -1)))} cells)"
        )


# --- config validation -------------------------------------------------------


def test_flow_config_validation():
    cfg = FlowConfig.make(entries=1000)
    assert cfg.entries == 1024  # pow2 bucketing
    assert cfg.capacity == 1024
    with pytest.raises(ValueError):
        FlowConfig.make(entries=0)
    with pytest.raises(ValueError):
        FlowConfig.make(ways=9)
    with pytest.raises(ValueError):
        FlowConfig.make(max_age=0)


# --- bit-identity: flow path vs stateless vs oracle --------------------------


def test_flow_hits_bit_identical_to_stateless():
    tabs = _tables()
    clf, base = _pair(tabs, track_model=True)
    batch = testing.random_batch_fast(np.random.default_rng(5), tabs, 384)
    ref = oracle.HashLpmOracle(tabs).classify(batch)
    for i in range(3):
        out = clf.classify(batch, apply_stats=False)
        want = base.classify(batch, apply_stats=False)
        assert np.array_equal(out.results, want.results), f"pass {i}"
        assert np.array_equal(out.results, ref.results), f"pass {i}"
        assert np.array_equal(out.xdp, ref.xdp)
        assert np.array_equal(out.stats_delta, want.stats_delta), (
            f"pass {i}: statistics diverge"
        )
    v = clf.flow.stats.values()
    assert v["hits"] > 300, "second/third passes must serve from the cache"
    assert v["inserts"] > 0
    _assert_model_parity(clf.flow)


def test_flow_ladder_bit_identity():
    tabs = _tables(n=512)
    for ef in (0.0, 0.5, 0.9):
        clf, base = _pair(tabs, entries=8192)
        batch, meta = testing.flow_trace_batch(
            np.random.default_rng(40 + int(ef * 10)), tabs, 4096, ef,
            chunk_packets=512,
        )
        for lo in range(0, len(batch), 512):
            out = clf.classify(batch.slice(lo, lo + 512),
                               apply_stats=False)
            want = base.classify(batch.slice(lo, lo + 512),
                                 apply_stats=False)
            assert np.array_equal(out.results, want.results), f"ef={ef}"
        hits = clf.flow.stats.values()["hits"]
        if ef >= 0.5:
            assert hits > 0.5 * ef * len(batch), (
                f"ef={ef}: hit rate collapsed ({hits}/{len(batch)})"
            )
        clf.close()
        base.close()


def test_flow_model_parity_under_churn():
    """Eviction pressure: a tiny table under a large flow population —
    every LRU displacement must mirror bit-exactly in the host model."""
    tabs = _tables()
    clf, base = _pair(tabs, entries=64, track_model=True)
    for seed in range(4):
        batch = testing.random_batch_fast(
            np.random.default_rng(100 + seed), tabs, 512
        )
        out = clf.classify(batch, apply_stats=False)
        want = base.classify(batch, apply_stats=False)
        assert np.array_equal(out.results, want.results)
    assert clf.flow.stats.values()["evictions"] > 0, (
        "a 64-slot table under 2K flows must evict"
    )
    _assert_model_parity(clf.flow)


# --- TCP state machine -------------------------------------------------------


def _one_flow_batch(tabs, flags):
    """len(flags) copies of one TCP packet, one flag word per copy."""
    batch = testing.random_batch_fast(np.random.default_rng(9), tabs, 1)
    batch.kind[:] = 1
    batch.l4_ok[:] = 1
    batch.proto[:] = 6
    batch.ip_words[:, 1:] = 0
    b = batch.take(np.zeros(len(flags), np.int64))
    b.tcp_flags = np.asarray(flags, np.int32)
    return b


def test_tcp_syn_not_established():
    """A pure-SYN stream never graduates into the fast path (SYN floods
    stay on the stateless tier); the first non-SYN packet promotes."""
    tabs = _tables()
    clf, base = _pair(tabs, track_model=True)
    syn = _one_flow_batch(tabs, [jaxpath.TCP_SYN] * 4)
    for _ in range(3):
        out = clf.classify(syn, apply_stats=False)
        want = base.classify(syn, apply_stats=False)
        assert np.array_equal(out.results, want.results)
    assert clf.flow.stats.values()["hits"] == 0, (
        "pure SYNs must never serve from the cache"
    )
    m = clf.flow.model
    assert (m.se[:, 0] == jaxpath.FLOW_NEW).sum() == 1
    # first ACK packet: still a miss (NEW is not serve-eligible), but
    # promotes the entry to EST...
    ack = _one_flow_batch(tabs, [jaxpath.TCP_ACK])
    clf.classify(ack, apply_stats=False)
    assert clf.flow.stats.values()["promotes"] == 1
    assert (m.se[:, 0] == jaxpath.FLOW_EST).sum() == 1
    # ...and the next packet serves
    clf.classify(ack, apply_stats=False)
    assert clf.flow.stats.values()["hits"] == 1
    _assert_model_parity(clf.flow)


def test_tcp_fin_and_rst_transitions():
    tabs = _tables()
    clf, base = _pair(tabs, track_model=True)
    m = clf.flow.model
    est = _one_flow_batch(tabs, [jaxpath.TCP_ACK] * 2)
    clf.classify(est, apply_stats=False)  # insert (EST via dedup winner)
    assert (m.se[:, 0] == jaxpath.FLOW_EST).sum() == 1
    fin = _one_flow_batch(tabs, [jaxpath.TCP_FIN | jaxpath.TCP_ACK])
    out = clf.classify(fin, apply_stats=False)
    want = base.classify(fin, apply_stats=False)
    assert np.array_equal(out.results, want.results)
    assert clf.flow.stats.values()["hits"] == 1, "FIN still serves"
    assert (m.se[:, 0] == jaxpath.FLOW_FIN).sum() == 1
    rst = _one_flow_batch(tabs, [jaxpath.TCP_RST])
    out = clf.classify(rst, apply_stats=False)
    want = base.classify(rst, apply_stats=False)
    assert np.array_equal(out.results, want.results)
    assert m.occupancy() == 0, "RST tears the entry down"
    _assert_model_parity(clf.flow)


# --- aging -------------------------------------------------------------------


def test_flow_aging_reclaims_and_max_age_gates():
    tabs = _tables()
    clf = TpuClassifier(
        interpret=True,
        flow_table=FlowConfig.make(entries=2048, max_age=2),
        flow_track_model=True,
    )
    base = TpuClassifier(interpret=True)
    clf.load_tables(tabs)
    base.load_tables(tabs)
    batch = testing.random_batch_fast(np.random.default_rng(5), tabs, 128)
    clf.classify(batch, apply_stats=False)
    h0 = clf.flow.stats.values()["hits"]
    # 3 probe epochs of unrelated traffic age the entries past max_age=2
    other = testing.random_batch_fast(np.random.default_rng(77), tabs, 64)
    for _ in range(3):
        clf.classify(other, apply_stats=False)
    h_before = clf.flow.stats.values()["hits"]
    assert h_before > h0, "the unrelated stream must hit its own repeats"
    # the original batch's entries are now 3 epochs old (> max_age=2):
    # the explicit sweep reclaims exactly them, and the re-classify
    # below serves nothing stale (it re-misses and re-inserts)
    aged = clf.flow_age_tick(horizon=2)
    assert aged > 0, "epoch-expired entries must be reclaimed"
    out = clf.classify(batch, apply_stats=False)
    want = base.classify(batch, apply_stats=False)
    assert np.array_equal(out.results, want.results)
    assert clf.flow.stats.values()["hits"] == h_before, (
        "expired entries must not serve"
    )
    _assert_model_parity(clf.flow)


# --- invalidation ------------------------------------------------------------


def test_invalidation_on_incremental_patch():
    """A rules-only edit through load_tables (the patch path) must bump
    the generation: the flow tier re-misses and serves the NEW verdict,
    bit-identical to the stateless path."""
    base_content = dict(_tables().content)
    upd = IncrementalTables.from_content(base_content, rule_width=4)
    clf, base = _pair(upd.snapshot(), track_model=True)
    batch = testing.random_batch_fast(
        np.random.default_rng(5), clf.tables, 256
    )
    for _ in range(2):
        clf.classify(batch, apply_stats=False)
    assert clf.flow.stats.values()["hits"] > 0
    # edit EVERY key's rules (order-preserving rid permutation keeps the
    # table patchable) so cached verdicts are broadly stale
    ups = {}
    for k, rules in list(base_content.items()):
        r = np.asarray(rules).copy()
        r[:, 6] = np.where(r[:, 0] != 0, 3 - r[:, 6], r[:, 6])  # flip act
        ups[k] = r
    upd.apply(ups, [])
    snap = upd.snapshot()
    hint = upd.peek_dirty()
    clf.load_tables(snap, dirty_hint=hint)
    base.load_tables(snap, dirty_hint=hint)
    inv0 = clf.flow.stats.values()["invalidations"]
    assert inv0 >= 2  # initial load + the patch
    out = clf.classify(batch, apply_stats=False)
    want = base.classify(batch, apply_stats=False)
    ref = oracle.classify(snap, batch)
    assert np.array_equal(out.results, want.results)
    assert np.array_equal(out.results, ref.results), (
        "stale flow verdict served after an incremental patch"
    )
    assert clf.flow.stats.values()["stale_rejects"] > 0, (
        "the probe must have rejected generation-stale entries"
    )
    _assert_model_parity(clf.flow)


def test_invalidation_on_txn_flush():
    """The folded patch-transaction path (syncer/txn integration): a
    flushed multi-edit transaction lands through load_tables and must
    invalidate affected flow verdicts."""
    from infw.txn import fold_ops, route_folded
    from infw.analysis.statecheck import EditOp

    base_content = dict(_tables().content)
    upd = IncrementalTables.from_content(base_content, rule_width=4)
    clf, base = _pair(upd.snapshot(), track_model=True)
    batch = testing.random_batch_fast(
        np.random.default_rng(5), clf.tables, 256
    )
    for _ in range(2):
        clf.classify(batch, apply_stats=False)
    ops = []
    for k, rules in list(base_content.items())[:8]:
        r = np.asarray(rules).copy()
        r[:, 6] = np.where(r[:, 0] != 0, 3 - r[:, 6], r[:, 6])
        ops.append(EditOp(kind="rules_edit", key=k, rules=r))
    folded = fold_ops(ops, set(upd._ident_to_t))
    ups, dels, _dirty = route_folded(folded, {}, False, 0)
    upd.apply(ups, dels)
    snap = upd.snapshot()
    hint = upd.peek_dirty()
    clf.load_tables(snap, dirty_hint=hint)
    base.load_tables(snap, dirty_hint=hint)
    out = clf.classify(batch, apply_stats=False)
    ref = oracle.classify(snap, batch)
    assert np.array_equal(out.results, ref.results), (
        "stale flow verdict served after a folded txn flush"
    )
    _assert_model_parity(clf.flow)


# --- multi-tenant (arena) ----------------------------------------------------


def _arena_pair(tabs_by_tenant, flow_entries=1024, spec_samples=()):
    spec = jaxpath.arena_spec_for(
        "ctrie", tuple(tabs_by_tenant.values()) + tuple(spec_samples),
        pages=6, max_tenants=8,
    )
    clf = ArenaClassifier(
        spec, interpret=True, fused_deep=False,
        flow_table=FlowConfig.make(entries=flow_entries),
        flow_track_model=True,
    )
    base = ArenaClassifier(spec, interpret=True, fused_deep=False)
    for t, tab in tabs_by_tenant.items():
        clf.load_tenant(t, tab)
        base.load_tenant(t, tab)
    return clf, base


def test_cross_tenant_flow_isolation():
    """Tenant A's cached verdict must NEVER serve tenant B's identical
    5-tuple: the same packets tagged per tenant classify against each
    tenant's own ruleset, bit-identical to per-tenant oracles, with
    flow hits engaged on both."""
    tabs = {
        0: testing.random_tables(np.random.default_rng(1), n_entries=24,
                                 width=4, v6_fraction=0.3),
        1: testing.random_tables(np.random.default_rng(2), n_entries=24,
                                 width=4, v6_fraction=0.3),
    }
    clf, base = _arena_pair(tabs)
    # the SAME packet columns for both tenants: only the tenant tag
    # (and therefore the ruleset) differs
    b = testing.random_batch(np.random.default_rng(7), tabs[0], 96)
    from infw import packets as packets_mod

    both = packets_mod.concat([b, b])
    tenant = np.concatenate(
        [np.zeros(96, np.int32), np.ones(96, np.int32)]
    )
    refs = [oracle.classify(tabs[0], b), oracle.classify(tabs[1], b)]
    want = np.concatenate([r.results for r in refs])
    for i in range(3):
        out = clf.classify_tenants(both, tenant, apply_stats=False)
        assert np.array_equal(out.results, want), (
            f"pass {i}: cross-tenant leak "
            f"({int(np.sum(out.results != want))} verdicts)"
        )
    assert clf.flow.stats.values()["hits"] > 150, "hits must engage"
    # the two tenants' rulesets differ, so at least some packet must
    # verdict differently — the isolation assertion has teeth
    assert not np.array_equal(refs[0].results, refs[1].results)
    _assert_model_parity(clf.flow)


def test_invalidation_on_tenant_swap():
    tabs = {
        0: testing.random_tables(np.random.default_rng(1), n_entries=24,
                                 width=4, v6_fraction=0.3),
        1: testing.random_tables(np.random.default_rng(2), n_entries=24,
                                 width=4, v6_fraction=0.3),
    }
    new_tab = testing.random_tables(np.random.default_rng(9),
                                    n_entries=24, width=4,
                                    v6_fraction=0.3)
    clf, _base = _arena_pair(tabs, spec_samples=(new_tab,))
    b = testing.random_batch(np.random.default_rng(7), tabs[0], 96)
    t0 = np.zeros(96, np.int32)
    for _ in range(2):
        clf.classify_tenants(b, t0, apply_stats=False)
    assert clf.flow.stats.values()["hits"] > 0
    # hot-swap tenant 0 to a different ruleset (page-table flip)
    clf.swap_tenant(0, new_tab)
    out = clf.classify_tenants(b, t0, apply_stats=False)
    ref = oracle.classify(new_tab, b)
    assert np.array_equal(out.results, ref.results), (
        "stale flow verdict served across a tenant swap"
    )
    # destroy: lanes go UNDEF, never a cached verdict
    clf.destroy_tenant(0)
    out = clf.classify_tenants(b, t0, apply_stats=False)
    assert int(out.results.max()) == 0
    _assert_model_parity(clf.flow)


# --- zero-recompile warm lifecycle -------------------------------------------


@pytest.mark.slow
def test_zero_recompile_warm_flow_lifecycle():
    """After the ladder warm, the whole flow lifecycle — probe across
    batch sizes and occupancies, insert, age, invalidation — compiles
    NOTHING (the _cache_size recompile lint)."""
    tabs = _tables()
    cfg = FlowConfig.make(entries=2048)
    clf = TpuClassifier(interpret=True, flow_table=cfg)
    base = TpuClassifier(interpret=True)
    clf.load_tables(tabs)
    base.load_tables(tabs)
    ladder = [64, 128, 256, 512]
    clf.warm_flow_ladder(ladder)
    # warm the stateless fall-through + merged path once per shape
    for b in ladder:
        batch = testing.random_batch_fast(np.random.default_rng(b), tabs, b)
        clf.classify(batch.pad_to(b), apply_stats=False)
        base.classify(batch.pad_to(b), apply_stats=False)
    clf.flow_age_tick()
    probe = jaxpath.jitted_flow_probe(cfg.entries, cfg.ways)
    ins = jaxpath.jitted_flow_insert(cfg.entries, cfg.ways)
    age = jaxpath.jitted_flow_age()
    size0 = (probe._cache_size() + ins._cache_size() + age._cache_size())
    # the measured lifecycle: mixed batch sizes, rising occupancy,
    # repeats (hits), an age sweep and a patch-free reload
    for seed, b in ((1, 512), (2, 256), (3, 512), (4, 64), (5, 128)):
        batch = testing.random_batch_fast(
            np.random.default_rng(seed), tabs, b
        )
        for _ in range(2):
            out = clf.classify(batch.pad_to(b), apply_stats=False)
            want = base.classify(batch.pad_to(b), apply_stats=False)
            assert np.array_equal(out.results, want.results)
    clf.flow_age_tick()
    grew = (probe._cache_size() + ins._cache_size() + age._cache_size()
            ) - size0
    assert grew == 0, (
        f"warm flow lifecycle recompiled: probe/insert/age cache grew "
        f"by {grew}"
    )


# --- scheduler / daemon integration ------------------------------------------


def test_scheduler_prewarm_covers_flow():
    from infw.scheduler import prewarm_ladder

    tabs = _tables()
    clf, _ = _pair(tabs, entries=1024)
    n = prewarm_ladder(clf, [32, 64], include_depth_classes=False)
    assert n > 0
    cfg = clf.flow.config
    probe = jaxpath.jitted_flow_probe(cfg.entries, cfg.ways)
    assert probe._cache_size() >= 2  # both wire widths warmed


def test_flow_counters_and_evict_events():
    tabs = _tables()
    clf, base = _pair(tabs, entries=64)  # tiny: force evictions
    events = []
    clf.flow.on_evict = lambda ev, ins, ep: events.append((ev, ins, ep))
    for seed in range(3):
        batch = testing.random_batch_fast(
            np.random.default_rng(200 + seed), tabs, 512
        )
        out = clf.classify(batch, apply_stats=False)
        want = base.classify(batch, apply_stats=False)
        assert np.array_equal(out.results, want.results)
    counters = clf.flow_counters()
    assert counters["flow_evictions_total"] > 0
    assert counters["flow_occupancy"] > 0
    assert counters["flow_capacity"] == 64
    assert events, "eviction events must fire under displacement"
    assert all(ev > 0 for ev, _i, _e in events)


def test_daemon_flow_flag_validation():
    from infw.daemon import main as daemon_main

    with pytest.raises(SystemExit) as e:
        daemon_main(["--state-dir", "/tmp/x", "--node-name", "n",
                     "--flow-table", "-5"])
    assert e.value.code == 2


def test_flow_evict_record_renders():
    from infw.obs.events import FlowEvictRecord

    rec = FlowEvictRecord(evicted=3, inserted=17, epoch=42)
    (line,) = rec.lines()
    assert "3 flow(s) displaced" in line and "epoch 42" in line


# --- statecheck configs ------------------------------------------------------


@pytest.mark.slow
def test_statecheck_flow_config_clean():
    from infw.analysis import statecheck

    rep = statecheck.run_config("flow", seed=1, n_ops=6,
                                shrink_on_failure=False)
    assert rep["ok"], rep.get("failure")


@pytest.mark.slow
def test_statecheck_flowstale_defect_caught():
    import infw.flow as flow_mod
    from infw.analysis import statecheck

    base, ops = statecheck.build_case("flow", 0, 12)
    flow_mod._INJECT_FLOW_STALE_BUG = True
    try:
        failure = statecheck.run_ops(base, ops, "flow", seed=0)
    finally:
        flow_mod._INJECT_FLOW_STALE_BUG = False
    assert failure is not None, (
        "dropped flow invalidation must be caught by the flow configs"
    )
    assert failure.phase in ("classify", "flow-classify", "flow-model")


# --- mesh --------------------------------------------------------------------


def test_mesh_flow_parity():
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs a multi-device pool")
    from infw.backend.mesh import MeshTpuClassifier

    tabs = _tables(n=128)
    clf = MeshTpuClassifier(
        data_shards=2, rules_shards=2, interpret=True,
        flow_table=FlowConfig.make(entries=512),
    )
    clf.load_tables(tabs)
    batch = testing.random_batch_fast(np.random.default_rng(5), tabs, 256)
    ref = oracle.HashLpmOracle(tabs).classify(batch)
    for i in range(2):
        out = clf.classify(batch, apply_stats=False)
        assert np.array_equal(out.results, ref.results), f"pass {i}"
    assert clf.flow.stats.values()["hits"] > 0
    clf.close()
