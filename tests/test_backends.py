"""T3: backend classifiers (TPU/JAX paths + native C++ reference) vs the
NumPy oracle, plus lifecycle semantics (table swap, stats accumulation,
close)."""
import time

import numpy as np
import pytest

from infw import oracle, testing
from infw.backend.cpu_ref import CpuRefClassifier
from infw.backend.tpu import TpuClassifier
from infw.compiler import LpmKey, compile_tables_from_content


def check_against_oracle(clf, tables, batch):
    ref = oracle.classify(tables, batch)
    out = clf.classify(batch)
    np.testing.assert_array_equal(out.results, ref.results)
    np.testing.assert_array_equal(out.xdp, ref.xdp)
    got = testing.stats_dict_from_array(out.stats_delta)
    assert got == ref.stats


@pytest.mark.parametrize("make", [CpuRefClassifier, TpuClassifier], ids=["cpp", "tpu"])
def test_backend_matches_oracle(make):
    rng = np.random.default_rng(21)
    tables = testing.random_tables(rng, n_entries=50, width=10)
    batch = testing.random_batch(rng, tables, n_packets=400)
    clf = make()
    clf.load_tables(tables)
    check_against_oracle(clf, tables, batch)
    clf.close()


def test_tpu_backend_trie_path():
    rng = np.random.default_rng(22)
    tables = testing.random_tables(rng, n_entries=50, width=10)
    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    assert clf.active_path == "trie"
    batch = testing.random_batch(rng, tables, n_packets=300)
    check_against_oracle(clf, tables, batch)
    clf.close()


@pytest.mark.parametrize("path", ["dense", "trie"])
def test_classify_async_packed_matches_unpacked(path):
    """The daemon's packed fast path (pack_wire_subset ->
    classify_async_packed) must be verdict/xdp/stats-identical to the
    composed take()+classify_async on every subset family shape,
    for both device paths."""
    rng = np.random.default_rng(27)
    tables = testing.random_tables(rng, n_entries=60, width=10)
    batch = testing.random_batch(rng, tables, n_packets=600)
    clf = TpuClassifier(force_path=path)
    clf.load_tables(tables)
    assert clf.supports_packed()
    kinds = np.asarray(batch.kind)
    subsets = [
        np.nonzero(kinds != 2)[0],           # daemon's non-v6 group
        np.nonzero(kinds == 2)[0],           # v6 group
        np.random.default_rng(1).permutation(len(batch)),
    ]
    for idx in subsets:
        if not len(idx):
            continue
        idx = np.ascontiguousarray(idx, np.int64)
        want = clf.classify_async(batch.take(idx), apply_stats=False).result()
        wire, v4_only = batch.pack_wire_subset(idx)
        got = clf.classify_async_packed(wire, v4_only, apply_stats=False).result()
        np.testing.assert_array_equal(got.results, want.results)
        np.testing.assert_array_equal(got.xdp, want.xdp)
        np.testing.assert_array_equal(got.stats_delta, want.stats_delta)
    clf.close()


def test_classify_async_packed_rejected_on_wide_rids():
    """Tables whose ruleIds exceed the wire format must refuse the packed
    entry point (supports_packed gates the daemon)."""
    rows = np.zeros((2, 7), np.int32)
    rows[0] = [3000, 6, 80, 0, 0, 0, 1]  # ruleId 3000 > 255 -> wide path
    content = {LpmKey(32, 2, bytes(16)): rows}
    tables = compile_tables_from_content(content, rule_width=2)
    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    assert not clf.supports_packed()
    with pytest.raises(RuntimeError):
        clf.classify_async_packed(np.zeros((1, 7), np.uint32), True)
    clf.close()


@pytest.mark.parametrize("make", [CpuRefClassifier, TpuClassifier], ids=["cpp", "tpu"])
def test_stats_accumulate_across_batches(make):
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 6, 80, 0, 0, 0, 1]  # TCP 80 deny
    content = {LpmKey(32, 2, bytes(16)): rows}
    tables = compile_tables_from_content(content, rule_width=4)
    from infw.packets import make_batch

    clf = make()
    clf.load_tables(tables)
    b = make_batch(src=["1.1.1.1"] * 3, proto=[6] * 3, dst_port=[80] * 3,
                   ifindex=[2] * 3, pkt_len=[100] * 3)
    clf.classify(b)
    clf.classify(b)
    snap = clf.stats.snapshot()
    assert snap[1, 2] == 6          # deny packets accumulate
    assert snap[1, 3] == 600        # deny bytes accumulate
    clf.stats.reset()
    assert clf.stats.snapshot().sum() == 0
    clf.close()


def test_table_swap_is_idempotent_and_atomic():
    rng = np.random.default_rng(23)
    t1 = testing.random_tables(rng, n_entries=20, width=8)
    t2 = testing.random_tables(rng, n_entries=25, width=8)
    clf = TpuClassifier()
    clf.load_tables(t1)
    batch = testing.random_batch(rng, t1, n_packets=100)
    check_against_oracle(clf, t1, batch)
    clf.load_tables(t2)  # swap
    batch2 = testing.random_batch(rng, t2, n_packets=100)
    check_against_oracle(clf, t2, batch2)
    clf.load_tables(t2)  # re-sync with identical tables: idempotent
    check_against_oracle(clf, t2, batch2)
    clf.close()


def test_auto_path_selection_flips_across_dense_limit():
    """Path choice is automatic by table size (dense up to the limit,
    trie beyond — the reference's analogue is MAX_TARGETS map sizing),
    and a reload across the boundary flips the path atomically in both
    directions with verdicts bit-exact throughout."""
    rng = np.random.default_rng(53)
    small = testing.random_tables(rng, n_entries=20, width=8)
    big = testing.random_tables(rng, n_entries=60, width=8)
    clf = TpuClassifier(dense_limit=30)
    clf.load_tables(small)
    assert clf.active_path == "dense"
    check_against_oracle(clf, small, testing.random_batch(rng, small, 200))
    clf.load_tables(big)  # grow past the limit: dense -> trie
    assert clf.active_path == "trie"
    check_against_oracle(clf, big, testing.random_batch(rng, big, 200))
    clf.load_tables(small)  # shrink back: trie -> dense
    assert clf.active_path == "dense"
    check_against_oracle(clf, small, testing.random_batch(rng, small, 200))
    clf.close()


def test_classify_after_close_raises():
    clf = TpuClassifier()
    clf.close()
    rng = np.random.default_rng(1)
    tables = testing.random_tables(rng, n_entries=3, width=4)
    with pytest.raises(RuntimeError):
        clf.load_tables(tables)


def test_cpp_large_random_differential():
    rng = np.random.default_rng(99)
    tables = testing.random_tables(rng, n_entries=150, width=16, overlap_fraction=0.5)
    batch = testing.random_batch(rng, tables, n_packets=2000)
    clf = CpuRefClassifier()
    clf.load_tables(tables)
    check_against_oracle(clf, tables, batch)


def test_classify_async_matches_sync_and_stats_once():
    """classify_async with several handles in flight returns identical
    results to the sync path, and each batch's stats apply exactly once,
    on materialization."""
    rng = np.random.default_rng(31)
    tables = testing.random_tables(rng, n_entries=40, width=8)
    batch = testing.random_batch(rng, tables, n_packets=256)

    sync_clf = TpuClassifier()
    sync_clf.load_tables(tables)
    want = sync_clf.classify(batch)
    sync_clf.close()

    clf = TpuClassifier()
    clf.load_tables(tables)
    pendings = [clf.classify_async(batch) for _ in range(3)]
    assert (clf.stats.snapshot() == 0).all()  # nothing applied yet
    outs = [p.result() for p in pendings]
    for out in outs:
        assert np.array_equal(np.asarray(out.results), np.asarray(want.results))
        assert np.array_equal(np.asarray(out.xdp), np.asarray(want.xdp))
    assert np.array_equal(clf.stats.snapshot(), 3 * want.stats_delta)
    # repeated result() must not re-apply stats
    assert pendings[0].result() is outs[0]
    assert np.array_equal(clf.stats.snapshot(), 3 * want.stats_delta)
    clf.close()


def test_cpu_ref_classify_async_parity():
    rng = np.random.default_rng(32)
    tables = testing.random_tables(rng, n_entries=40, width=8)
    batch = testing.random_batch(rng, tables, n_packets=256)
    clf = CpuRefClassifier()
    clf.load_tables(tables)
    want = clf.classify(batch)
    got = clf.classify_async(batch).result()
    assert np.array_equal(got.results, want.results)
    clf.close()


def test_wire_pack_unpack_roundtrip():
    """pack_wire ∘ unpack_wire is the identity on every classification
    field (pkt_len carries 21 bits — clamped at 2MiB-1, beyond any
    GRO/TSO aggregate)."""
    import jax.numpy as jnp
    from infw.kernels.jaxpath import unpack_wire

    rng = np.random.default_rng(41)
    tables = testing.random_tables(rng, n_entries=10, width=4)
    batch = testing.random_batch(rng, tables, n_packets=128)
    # include >u16 lengths (BIG-TCP scale) and one that clips at 21 bits
    pl = batch.pkt_len.copy()
    pl[:4] = [70000, 0x1FFFFF, 3_000_000, 524288]
    batch.pkt_len = pl
    db = unpack_wire(jnp.asarray(batch.pack_wire()))
    np.testing.assert_array_equal(np.asarray(db.kind), batch.kind)
    np.testing.assert_array_equal(np.asarray(db.l4_ok), batch.l4_ok)
    np.testing.assert_array_equal(np.asarray(db.ifindex), batch.ifindex)
    np.testing.assert_array_equal(np.asarray(db.ip_words), batch.ip_words)
    np.testing.assert_array_equal(np.asarray(db.proto), batch.proto)
    np.testing.assert_array_equal(np.asarray(db.dst_port), batch.dst_port)
    np.testing.assert_array_equal(np.asarray(db.icmp_type), batch.icmp_type)
    np.testing.assert_array_equal(np.asarray(db.icmp_code), batch.icmp_code)
    np.testing.assert_array_equal(
        np.asarray(db.pkt_len), np.clip(batch.pkt_len, 0, 0x1FFFFF)
    )


def test_wire_path_byte_stats_above_u16():
    """Byte statistics through the TPU wire path stay exact for frames
    larger than 64 KiB (the old u16 pkt_len silently undercounted them)."""
    from infw.packets import make_batch

    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 6, 80, 0, 0, 0, 1]  # TCP 80 deny
    content = {LpmKey(32, 2, bytes(16)): rows}
    tables = compile_tables_from_content(content, rule_width=4)
    b = make_batch(src=["1.1.1.1"] * 2, proto=[6] * 2, dst_port=[80] * 2,
                   ifindex=[2] * 2, pkt_len=[70000, 524288])
    ref = oracle.classify(tables, b)
    clf = TpuClassifier()
    clf.load_tables(tables)
    out = clf.classify(b)
    assert testing.stats_dict_from_array(out.stats_delta) == ref.stats
    assert int(out.stats_delta[1, 3]) == 70000 + 524288  # deny bytes exact
    clf.close()


def test_wire_ruleid_guard_trips_loudly():
    """Adversarial direct content with ruleId > 255 must be rejected at
    load time on the wire paths, never silently truncated in the uint16
    result (jaxpath guard; pallas analogue at ruleId > 127)."""
    from infw.kernels import jaxpath, pallas_dense

    rows = np.zeros((2, 7), np.int32)
    rows[1] = [300, 6, 80, 0, 0, 0, 1]
    tables = compile_tables_from_content(
        {LpmKey(32, 2, bytes(16)): rows}, rule_width=2
    )
    with pytest.raises(ValueError, match="ruleId"):
        jaxpath.check_wire_ruleids(tables)
    with pytest.raises(ValueError, match="ruleId"):
        pallas_dense.build_pallas_tables(tables)
    # the u32 (non-wire) jax path still classifies such tables correctly
    from infw import testing as _t
    batch = _t.random_batch(np.random.default_rng(7), tables, n_packets=64)
    ref = oracle.classify(tables, batch)
    got = np.asarray(
        jaxpath.jitted_classify(False)(
            jaxpath.device_tables(tables), jaxpath.device_batch(batch)
        )[0]
    )
    np.testing.assert_array_equal(got, ref.results)


def test_v4_depth_specialization_bit_exact():
    """A v4-only batch classified through the truncated trie walk must
    match the full-depth walk even when the table holds /128 entries."""
    import jax.numpy as jnp
    from infw.kernels import jaxpath
    from infw.compiler import LpmKey, RULE_COLS, compile_tables_from_content

    rng = np.random.default_rng(51)
    content = {}
    # v4 prefixes at /8../32 plus v6 entries to /128 (forcing 15 levels)
    while len(content) < 300:
        if rng.random() < 0.5:
            mask = int(rng.integers(8, 33))
            ip = bytes([10, rng.integers(0, 256), rng.integers(0, 256),
                        rng.integers(0, 256)]) + bytes(12)
        else:
            mask = int(rng.integers(33, 129))
            ip = bytes([0x20, 0x01]) + bytes(rng.integers(0, 256, 14).tolist())
        ipi = int.from_bytes(ip, "big") & ((1 << 128) - (1 << (128 - mask)))
        key = LpmKey(32 + mask, 2, ipi.to_bytes(16, "big"))
        rows = np.zeros((2, RULE_COLS), np.int32)
        rows[1] = [1, 6, int(rng.integers(1, 65000)), 0, 0, 0, int(rng.integers(1, 3))]
        content[key] = rows
    tables = compile_tables_from_content(content, rule_width=2)
    assert len(tables.trie_levels) == 15  # /128 table depth

    from infw import testing
    batch = testing.random_batch(rng, tables, n_packets=500)
    # make it v4-only: rewrite v6 packets as v4
    kinds = np.asarray(batch.kind).copy()
    kinds[kinds == 2] = 1
    batch.kind = kinds
    wire = jnp.asarray(batch.pack_wire())
    dev = jaxpath.device_tables(tables)
    full, _ = jaxpath.jitted_classify_wire(True, False)(dev, wire)
    fast, _ = jaxpath.jitted_classify_wire(True, True)(dev, wire)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(fast))

    # TpuClassifier auto-selects the fast path for v4-only batches and
    # stays bit-exact vs the oracle
    from infw import oracle
    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    out = clf.classify(batch)
    ref = oracle.classify(tables, batch)
    np.testing.assert_array_equal(out.results, ref.results)
    clf.close()


@pytest.mark.parametrize("path", ["trie", "dense"])
def test_double_buffer_swap_under_concurrency(tmp_path, path):
    """The double-buffer contract (infw/backend/tpu.py docstring; the TPU
    analogue of the mutex-serialized map rewrite,
    /root/reference/pkg/ebpfsyncer/ebpfsyncer.go:56-63,72-73): reader
    threads stream classify_async while a writer thread continuously swaps
    table generations and checkpoints them.  Every returned batch must
    match exactly one generation's oracle verdicts (never a torn mix), and
    the stats accumulator must equal the sum of the per-batch deltas
    (each batch applied exactly once)."""
    import threading

    from infw.packets import make_batch

    # G generations over the same key: order g rule, TCP port 80, action
    # alternating Deny/Allow -> verdict (g<<8)|action identifies the
    # generation a batch ran against.
    G = 4
    gens = []
    for g in range(1, G + 1):
        rows = np.zeros((8, 7), np.int32)
        rows[g] = [g, 6, 80, 0, 0, 0, 1 + (g % 2)]
        content = {LpmKey(40, 2, bytes([10]) + bytes(15)): rows}
        gens.append(compile_tables_from_content(content, rule_width=8))

    n = 64
    batch = make_batch(
        src=["10.0.0.9"] * n, proto=[6] * n, dst_port=[80] * n,
        ifindex=[2] * n, pkt_len=[100] * n,
    )
    expected = {}
    for g, t in enumerate(gens):
        ref = oracle.classify(t, batch)
        expected[tuple(ref.results.tolist())] = g

    clf = TpuClassifier(force_path=path)
    clf.load_tables(gens[0])

    stop = threading.Event()
    errors = []
    seen_gens = set()
    deltas_lock = threading.Lock()
    delta_total = [None]

    swaps = [0]

    def writer():
        i = 0
        while not stop.is_set():
            t = gens[i % G]
            try:
                clf.load_tables(t)
                t.save(str(tmp_path / f"ckpt-{i % G}.npz"))
            except Exception as e:  # pragma: no cover
                errors.append(f"writer: {e!r}")
                return
            i += 1
            swaps[0] = i

    def reader():
        while not stop.is_set():
            try:
                out = clf.classify_async(batch).result()
            except Exception as e:  # pragma: no cover
                errors.append(f"reader: {e!r}")
                return
            key = tuple(out.results.tolist())
            if key not in expected:
                errors.append(f"torn verdicts: {sorted(set(key))}")
                return
            seen_gens.add(expected[key])
            with deltas_lock:
                if delta_total[0] is None:
                    delta_total[0] = out.stats_delta.astype(np.int64)
                else:
                    delta_total[0] = delta_total[0] + out.stats_delta

    w = threading.Thread(target=writer)
    readers = [threading.Thread(target=reader) for _ in range(3)]
    w.start()
    [r.start() for r in readers]
    # run until the race is real: several completed swaps AND several
    # classified batches (interpret-mode readers are GIL-heavy, so a fixed
    # sleep can starve one side)
    deadline = time.time() + 60
    while time.time() < deadline and not errors and (
        swaps[0] < 8 or len(seen_gens) < 2
    ):
        time.sleep(0.05)
    stop.set()
    w.join(timeout=30)
    [r.join(timeout=30) for r in readers]
    clf.close()

    assert not errors, errors[:5]
    assert len(seen_gens) >= 2, f"swap never observed: {seen_gens}"
    # exactly-once stats: accumulator == sum of returned deltas
    np.testing.assert_array_equal(clf.stats.snapshot(), delta_total[0])


@pytest.mark.parametrize("path", ["dense", "trie"])
def test_v4_compact_wire_parity(path):
    """A v4-compactable batch auto-ships the 16B/packet (B,4) wire format
    on both device paths; verdicts/stats stay bit-exact vs the oracle."""
    rng = np.random.default_rng(29)
    tables = testing.random_tables(rng, n_entries=40, width=8)
    batch = testing.random_batch(rng, tables, n_packets=300)
    # make it v4-compactable: no IPv6 packets, high IP words zeroed
    batch.kind = np.where(batch.kind == 2, 1, batch.kind).astype(np.int32)
    batch.ip_words[:, 1:] = 0
    assert batch.is_v4_compactable()
    assert batch.pack_wire_v4().shape == (300, 4)
    clf = TpuClassifier(force_path=path)
    clf.load_tables(tables)
    check_against_oracle(clf, tables, batch)
    clf.close()


def test_is_v4_compactable_rejects_v6_and_high_words():
    rng = np.random.default_rng(30)
    tables = testing.random_tables(rng, n_entries=10, width=4)
    batch = testing.random_batch(rng, tables, n_packets=50)
    batch.kind[0] = 2  # one IPv6 packet
    assert not batch.is_v4_compactable()
    batch.kind[:] = 1
    batch.ip_words[:, 1:] = 0
    batch.ip_words[3, 2] = 7  # stray high word
    assert not batch.is_v4_compactable()


def test_wide_ruleid_tables_fall_back_to_u32_path():
    """Direct adversarial content with ruleIds > 255 loads on the TPU
    backend (u32 fallback) instead of refusing, and reports the full
    ruleId losslessly."""
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [70000, 6, 80, 0, 0, 0, 1]  # rid 70000 > u8/u16, TCP 80 deny
    content = {LpmKey(32, 2, bytes(16)): rows}
    tables = compile_tables_from_content(content, rule_width=4)
    from infw.packets import make_batch

    b = make_batch(src=["9.9.9.9"], proto=[6], dst_port=[80], ifindex=[2],
                   pkt_len=[100])
    ref = oracle.classify(tables, b)
    # both the forced-trie AND the default (auto -> dense -> fallback)
    # configurations must serve the table
    for kw in ({"force_path": "trie"}, {}):
        clf = TpuClassifier(**kw)
        clf.load_tables(tables)
        out = clf.classify(b)
        assert out.results[0] == ((70000 & 0xFFFFFF) << 8) | 1
        assert out.xdp[0] == 1  # XDP_DROP
        np.testing.assert_array_equal(out.results, ref.results)
        clf.close()


def test_device_patch_matches_full_upload_under_churn():
    """patch_device_tables must produce device arrays bit-identical to a
    fresh full upload after every incremental mutation round (the
    Map.Update-granularity device path)."""
    import jax

    from infw.compiler import IncrementalTables
    from infw.kernels import jaxpath
    from test_compiler import _random_content

    rng = np.random.default_rng(70)
    content = _random_content(rng, 60)
    it = IncrementalTables.from_content(content, rule_width=4)
    prev = it.snapshot()
    dev = jaxpath.device_tables(prev, pad=True)
    for round_ in range(6):
        keys = list(content)
        dels = [keys[int(i)] for i in rng.choice(len(keys), size=4, replace=False)]
        for k in dels:
            del content[k]
        adds = _random_content(rng, 5)
        content.update(adds)
        it.apply(adds, deletes=dels)
        new = it.snapshot()
        patched = jaxpath.patch_device_tables(dev, prev, new)
        fresh = jaxpath.device_tables(new, pad=True)
        if patched is None:
            dev = fresh  # structural change: full upload, keep iterating
        else:
            dev, n_rows = patched
            assert n_rows > 0
        for a, b in zip(jax.tree.leaves(dev), jax.tree.leaves(fresh)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        prev = new
    # classify on the final patched tables is bit-exact vs oracle
    batch = testing.random_batch(rng, prev, n_packets=400)
    ref = oracle.classify(prev, batch)
    from infw.kernels.jaxpath import device_batch, jitted_classify
    got = np.asarray(jitted_classify(True)(dev, device_batch(batch))[0])
    np.testing.assert_array_equal(got, ref.results)


def test_compact_transfer_upload_bit_identical():
    """device_tables ships a compacted transfer layout (sparse trie
    levels, u16-narrowed rules, mask_words reconstructed on device from
    mask_len) — the resident arrays must be bit-identical to a direct
    device_put of the host layout, with tombstoned rows, both pad modes,
    and the wide-ruleId (no-narrowing) fallback."""
    import jax
    import jax.numpy as jnp

    from infw.compiler import IncrementalTables, compile_tables_from_content
    from infw.kernels import jaxpath
    from test_compiler import _random_content

    rng = np.random.default_rng(81)
    content = _random_content(rng, 80)
    # every mask_words reconstruction regime: /0 (zero IP mask), exactly
    # one word (/32), multi-word v6 (/56, /96), full /128 — prefix_len is
    # mask_len + 32 ifindex bits
    rows = np.zeros((4, 7), np.int32)
    rows[0] = [1, 6, 80, 0, 0, 0, 1]
    for mask_len in (0, 32, 56, 96, 128):
        ip = bytes([mask_len + 1] * 16)
        content[LpmKey(mask_len + 32, 7, ip)] = rows
    it = IncrementalTables.from_content(content, rule_width=4)
    keys = list(content)
    it.apply({}, deletes=[keys[3], keys[11], keys[40]])  # tombstones
    variants = [it.snapshot()]
    # wide ruleIds: disables the u16 narrowing
    wide = _random_content(rng, 10)
    k0 = next(iter(wide))
    wide[k0] = wide[k0].copy()
    wide[k0][0] = [70000, 6, 80, 0, 0, 0, 1]
    variants.append(compile_tables_from_content(wide, rule_width=4))
    variants.append(compile_tables_from_content({}, rule_width=4))  # empty
    for tables in variants:
        for pad in (False, True):
            dev = jaxpath.device_tables(tables, pad=pad)
            host = jaxpath._host_device_layout(tables, pad)
            direct = jaxpath.DeviceTables(
                key_words=jnp.asarray(host[0]),
                mask_words=jnp.asarray(host[1]),
                mask_len=jnp.asarray(host[2]),
                rules=jnp.asarray(host[3]),
                trie_levels=tuple(jnp.asarray(l) for l in host[4]),
                trie_targets=jnp.asarray(host[5]),
                joined=jnp.asarray(host[7]),
                root_lut=jnp.asarray(host[6]),
                num_entries=jnp.asarray(np.int32(tables.num_entries)),
            )
            for a, b in zip(jax.tree.leaves(dev), jax.tree.leaves(direct)):
                assert a.dtype == b.dtype
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # in-range tables get the packed u16 rule rows; wide ruleIds keep i32
    assert jaxpath.device_tables(variants[0]).rules.dtype == jnp.uint16
    assert jaxpath.device_tables(variants[1]).rules.dtype == jnp.int32


def test_narrow_wire_classify_lossless():
    """The narrow wire transform overlays dst_port with the ICMP fields
    and folds the ifindex into w0; classification must stay bit-exact vs
    the oracle even for adversarial batches carrying garbage in the
    unused field (a synthetic ICMP packet with a nonzero dst_port, a TCP
    packet with nonzero icmp_type — the scan never reads the overlaid
    field for that protocol)."""
    from infw.packets import make_batch, narrow_wire

    rng = np.random.default_rng(91)
    tables = testing.random_tables(rng, n_entries=40, width=8,
                                   ifindexes=(2, 3))
    batch = testing.random_batch(rng, tables, n_packets=400)
    # poison the overlaid fields
    batch.dst_port = np.where(
        np.isin(batch.proto, (1, 58)), 4444, batch.dst_port
    ).astype(np.int32)
    batch.icmp_type = np.where(
        batch.proto == 6, 77, batch.icmp_type
    ).astype(np.int32)
    # the narrow form must engage for this batch
    assert narrow_wire(batch.pack_wire()) is not None
    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    check_against_oracle(clf, tables, batch)
    clf.close()


def test_narrow_wire_fallback_wide_values():
    """Wide ifindex (>= 2^16) or pkt_len (>= 2^16) rows must refuse the
    narrow form (return None) and classify correctly via the full wire."""
    from infw.packets import make_batch, narrow_wire

    rows = np.zeros((4, 7), np.int32)
    rows[1] = [1, 6, 80, 0, 0, 0, 1]
    content = {LpmKey(24 + 32, 70000, bytes([10, 0, 0, 0]) + bytes(12)): rows}
    tables = compile_tables_from_content(content, rule_width=4)
    batch = make_batch(src=["10.0.0.9", "10.0.0.9"], proto=[6, 6],
                       dst_port=[80, 81], ifindex=[70000, 70000])
    assert narrow_wire(batch.pack_wire()) is None
    batch2 = make_batch(src=["10.0.0.9"], proto=[6], dst_port=[80], ifindex=[2])
    batch2.pkt_len = np.asarray([1 << 17], np.int32)
    assert narrow_wire(batch2.pack_wire()) is None
    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    check_against_oracle(clf, tables, batch)
    out = clf.classify(batch)
    assert out.xdp.tolist() == [1, 2]
    clf.close()


def test_classifier_incremental_load_uses_patch():
    """A small rule edit on a loaded trie-path classifier must take the
    incremental device patch, and verdicts must match the oracle."""
    from infw.compiler import IncrementalTables
    from test_compiler import _random_content

    rng = np.random.default_rng(71)
    content = _random_content(rng, 40)
    it = IncrementalTables.from_content(content, rule_width=4)
    clf = TpuClassifier(force_path="trie")
    clf.load_tables(it.snapshot())
    assert clf._last_load[0] == "full"
    it.clear_dirty()  # device baseline established
    adds = _random_content(rng, 2)
    content.update(adds)
    it.apply(adds)
    snap = it.snapshot()
    clf.load_tables(snap, dirty_hint=it.peek_dirty())
    it.clear_dirty()
    mode, n_rows = clf._last_load
    # patched rows include leaf-push slot ranges, but must stay far below
    # a full upload (all padded array rows)
    full_rows = sum(
        a.shape[0]
        for a in (snap.key_words, snap.mask_words, snap.mask_len, snap.rules)
    ) + sum(l.shape[0] for l in snap.trie_levels)
    assert mode == "patch" and 0 < n_rows < full_rows // 2
    batch = testing.random_batch(rng, snap, n_packets=300)
    check_against_oracle(clf, snap, batch)
    clf.close()


def test_device_patch_with_hints_matches_full_upload_under_churn():
    """The hint-accelerated patch (no host diff) must stay bit-identical
    to a fresh padded upload across random churn, including the
    baseline-invalidation rules (fresh builds and compactions must NOT
    yield a usable hint until the device consumes a snapshot)."""
    import jax

    from infw.compiler import IncrementalTables
    from infw.kernels import jaxpath
    from test_compiler import _random_content

    rng = np.random.default_rng(72)
    content = _random_content(rng, 60)
    it = IncrementalTables.from_content(content, rule_width=4)
    assert it.peek_dirty() is None  # no device baseline yet
    prev = it.snapshot()
    dev = jaxpath.device_tables(prev, pad=True)
    it.clear_dirty()
    used_hint = 0
    for round_ in range(6):
        keys = list(content)
        dels = [keys[int(i)] for i in rng.choice(len(keys), size=4, replace=False)]
        for k in dels:
            del content[k]
        adds = _random_content(rng, 5)
        content.update(adds)
        it.apply(adds, deletes=dels)
        new = it.snapshot()
        hint = it.peek_dirty()
        patched = jaxpath.patch_device_tables(dev, prev, new, hint=hint)
        fresh = jaxpath.device_tables(new, pad=True)
        if patched is None:
            dev = fresh
        else:
            dev = patched[0]
            if hint is not None:
                used_hint += 1
        it.clear_dirty()
        for a, b in zip(jax.tree.leaves(dev), jax.tree.leaves(fresh)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        prev = new
    assert used_hint > 0  # the hint path must actually engage


def test_wire8_format_roundtrip_and_dispatch():
    """The 8B/packet wire format (packets.wire8): device decode must
    reconstruct every classification field, verdicts must match the
    oracle, and statistics (computed HOST-side for this format — pkt_len
    never crosses the link) must equal the device-stats path."""
    import jax

    from infw.backend.tpu import TpuClassifier
    from infw.kernels import jaxpath
    from infw.packets import wire8

    rng = np.random.default_rng(91)
    tables = testing.random_tables_fast(
        rng, n_entries=6000, width=4, v6_fraction=0.0, ifindexes=(2, 3, 9))
    batch = testing.random_batch_fast(rng, tables, n_packets=3000)
    kinds = np.asarray(batch.kind)
    v4 = batch.take(np.nonzero(kinds != 2)[0])  # no v6: v4-compactable
    # honor the pack_wire_v4 caller contract the dispatch gate enforces
    # (ip words 1..3 all zero): non-IP kinds may carry junk there that
    # classification never reads
    v4.ip_words[:, 1:] = 0

    w4 = v4.pack_wire_v4()
    w8 = wire8(w4)
    assert w8 is not None
    wire8_np, ifmap = w8
    assert wire8_np.shape[1] == 2
    db = jaxpath.unpack_wire8(
        jax.numpy.asarray(wire8_np), jax.numpy.asarray(ifmap))
    for field in ("kind", "l4_ok", "ifindex", "proto"):
        np.testing.assert_array_equal(
            np.asarray(getattr(db, field)), getattr(v4, field),
            err_msg=field)
    # the l4 word is an overlay (narrow_wire semantics): dst_port is
    # meaningful only for transport rows, icmp fields only for icmp rows
    # — exactly what the ordered scan reads (kernel.c:222-258)
    is_icmp = np.isin(v4.proto, (1, 58))
    np.testing.assert_array_equal(
        np.asarray(db.dst_port)[~is_icmp], v4.dst_port[~is_icmp])
    np.testing.assert_array_equal(
        np.asarray(db.icmp_type)[is_icmp], v4.icmp_type[is_icmp])
    np.testing.assert_array_equal(
        np.asarray(db.icmp_code)[is_icmp], v4.icmp_code[is_icmp])
    np.testing.assert_array_equal(
        np.asarray(db.ip_words), np.asarray(v4.ip_words).astype(np.uint32))

    # dispatch through the classifier: wire8 engages on the trie path
    # (pinned via the codec knob — the default "auto" codec prefers the
    # delta format when it compresses below 8 B/packet, which this
    # corpus does; the delta dispatch has its own tests)
    jaxpath.jitted_classify_wire8_fused.cache_clear()
    clf = TpuClassifier(force_path="trie", wire_codec="wire8")
    clf.load_tables(tables)
    out = clf.classify(v4)
    assert jaxpath.jitted_classify_wire8_fused.cache_info().currsize > 0, (
        "the 8B wire path must actually engage, not fall back to narrow")
    ref = oracle.classify(tables, v4)
    np.testing.assert_array_equal(out.results, ref.results)
    np.testing.assert_array_equal(out.xdp, ref.xdp)
    # host-derived stats must equal the oracle's per-rule aggregation
    for rid, vals in ref.stats.items():
        np.testing.assert_array_equal(out.stats_delta[rid], vals,
                                      err_msg=f"rule {rid}")
    nz = np.nonzero(out.stats_delta.any(axis=1))[0]
    assert set(nz) == set(ref.stats), "extra stats rows"
    clf.close()


def test_wire8_fallback_on_many_interfaces():
    from infw.packets import wire8

    rng = np.random.default_rng(92)
    tables = testing.random_tables_fast(
        rng, n_entries=200, width=4, v6_fraction=0.0,
        ifindexes=tuple(range(2, 30)))
    batch = testing.random_batch_fast(
        rng, tables, n_packets=2000)
    kinds = np.asarray(batch.kind)
    v4 = batch.take(np.nonzero(kinds != 2)[0])
    ifx = np.asarray(v4.ifindex)
    if len(np.unique(ifx)) <= 15:  # force >15 distinct ifindexes
        v4.ifindex = (np.arange(len(v4)) % 20 + 2).astype(np.int32)
    assert wire8(v4.pack_wire_v4()) is None


def test_depth_class_steering_bit_exact():
    """Depth-class steering (the v6 analogue of the family split): for
    every class group the truncated-walk verdicts must equal the
    full-depth walk — the LUT is a conservative per-root-slot bound."""
    import jax.numpy as jnp

    from infw.backend.tpu import TpuClassifier
    from infw.kernels import jaxpath

    rng = np.random.default_rng(77)
    tables = testing.random_tables_fast(
        rng, n_entries=8000, width=4, v6_fraction=0.6, ifindexes=(2, 3))
    batch = testing.random_batch_fast(rng, tables, n_packets=6000)
    kinds = np.asarray(batch.kind)
    idx6 = np.nonzero(kinds == 2)[0]

    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    groups = clf.v6_depth_groups(batch.ifindex, batch.ip_words, idx6)
    assert sum(len(g) for _d, g in groups) == len(idx6)
    assert len(groups) > 1, "bench-style tables must yield several classes"

    dt = jaxpath.device_tables(tables)
    full_fn = jaxpath.jitted_classify_wire(True)
    covered_classes = set()
    for (dclass, _gen), g in groups:
        sub = batch.take(g)
        wire = jnp.asarray(sub.pack_wire())
        ref16 = np.asarray(full_fn(dt, wire)[0])
        got16 = np.asarray(
            jaxpath.jitted_classify_wire(True, False, dclass)(dt, wire)[0])
        np.testing.assert_array_equal(got16, ref16)
        covered_classes.add(dclass)
    assert None in covered_classes, covered_classes
    clf.close()


def test_daemon_ingest_with_depth_steering_matches_oracle(tmp_path):
    """End-to-end: the daemon's depth-steered v6 jobs must produce
    oracle-exact verdicts (the steering only regroups, never changes
    results)."""
    import json
    import os

    from infw.backend.tpu import TpuClassifier
    from infw.daemon import Daemon, write_frames_file_v2
    from infw.obs.events import EventRing
    from infw.obs.pcap import build_frames_bulk

    rng = np.random.default_rng(78)
    tables = testing.random_tables_fast(
        rng, n_entries=6000, width=4, v6_fraction=0.6, ifindexes=(2, 3))
    batch = testing.random_batch_fast(rng, tables, n_packets=4000)
    fb = build_frames_bulk(batch.kind, batch.ip_words, batch.proto,
                           batch.dst_port, batch.icmp_type, batch.icmp_code,
                           l4_ok=batch.l4_ok)
    fb.ifindex = np.asarray(batch.ifindex, np.uint32)

    clf = TpuClassifier(force_path="trie")
    clf.load_tables(tables)
    d = Daemon.__new__(Daemon)
    d.ingest_dir = str(tmp_path / "in")
    d.out_dir = str(tmp_path / "out")
    os.makedirs(d.ingest_dir); os.makedirs(d.out_dir)
    d.ingest_chunk = 512   # force several jobs incl. depth classes
    d.pipeline_depth = 4
    d.max_tick_packets = 1 << 20
    d.debug_lookup = False
    d.ring = EventRing(capacity=1 << 16)

    class _S:
        classifier = clf
    d.syncer = _S()
    path = os.path.join(d.ingest_dir, "f.frames")
    write_frames_file_v2(path, fb)
    # the oracle input is the PARSED batch: frame synthesis canonicalizes
    # fields the wire cannot carry (l4_ok=0 rows etc.), exactly like real
    # capture would
    from infw.daemon import parse_frames_buf, read_frames_any
    parsed = parse_frames_buf(read_frames_any(path))
    assert d.process_ingest_once() == 1
    with open(os.path.join(d.out_dir, "f.frames.verdicts.json")) as f:
        summary = json.load(f)
    got = np.fromfile(
        os.path.join(d.out_dir, summary["results_file"]), "<u4")
    ref = oracle.classify(tables, parsed)
    np.testing.assert_array_equal(got, ref.results)
    clf.close()
